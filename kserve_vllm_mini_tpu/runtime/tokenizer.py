"""Tokenizers for the serving runtime.

Two implementations behind one protocol:

- ``ByteTokenizer`` — self-contained UTF-8 byte-level tokenizer (vocab 256 +
  specials). Zero external assets, so the runtime serves end-to-end in an
  air-gapped CI exactly like the reference's mock-cluster tiers (SURVEY.md
  §4.3). Token counts are real token counts for throughput metrics.
- ``HFTokenizer`` — wraps a local ``tokenizer.json``/sentencepiece checkpoint
  directory via ``transformers`` for real-model serving. Never touches the
  network.
"""

from __future__ import annotations

from pathlib import Path
from typing import Protocol, Sequence


class Tokenizer(Protocol):
    vocab_size: int
    bos_id: int
    eos_id: int
    pad_id: int

    def encode(self, text: str) -> list[int]: ...
    def decode(self, ids: Sequence[int]) -> str: ...


class ByteTokenizer:
    """UTF-8 bytes shifted by the special-token count."""

    SPECIALS = 3  # pad=0, bos=1, eos=2

    def __init__(self) -> None:
        self.pad_id = 0
        self.bos_id = 1
        self.eos_id = 2
        self.vocab_size = 256 + self.SPECIALS

    def encode(self, text: str) -> list[int]:
        return [b + self.SPECIALS for b in text.encode("utf-8")]

    def decode(self, ids: Sequence[int]) -> str:
        # ids outside the byte range (possible when the model's vocab exceeds
        # 259, e.g. random-weight smoke models) are dropped, not crashed on
        raw = bytes(
            i - self.SPECIALS for i in ids if self.SPECIALS <= i < 256 + self.SPECIALS
        )
        return raw.decode("utf-8", errors="replace")


class HFTokenizer:
    def __init__(self, path: str | Path) -> None:
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(str(path), local_files_only=True)
        self.vocab_size = int(self._tok.vocab_size)
        self.bos_id = int(self._tok.bos_token_id or 1)
        self.eos_id = int(self._tok.eos_token_id or 2)
        self.pad_id = int(
            self._tok.pad_token_id if self._tok.pad_token_id is not None else 0
        )

    def encode(self, text: str) -> list[int]:
        return list(self._tok.encode(text, add_special_tokens=False))

    def decode(self, ids: Sequence[int]) -> str:
        return self._tok.decode(list(ids), skip_special_tokens=True)


def load_tokenizer(path: str | Path | None) -> Tokenizer:
    """HF tokenizer when a local directory with tokenizer assets exists,
    byte-level fallback otherwise."""
    if path:
        p = Path(path)
        if (p / "tokenizer.json").exists() or (p / "tokenizer.model").exists() or (
            p / "tokenizer_config.json"
        ).exists():
            return HFTokenizer(p)
    return ByteTokenizer()
