"""Continuous-batching serving engine under XLA's static-shape constraint.

Core design (SURVEY.md §7.3.1 — this is the subsystem the reference
outsources to vLLM/TGI/Triton images):

- **Slots, not dynamic batches**: the KV cache is one static array
  [L, max_slots, KVH, max_seq, D]; a request occupies a slot from admission
  to completion, so the decode step is a single jitted call of fixed shape
  regardless of which requests are live (inactive slots compute padding).
- **Bucketed prefill**: prompts pad to power-of-two buckets; one compiled
  executable per bucket, cached. Prefill writes KV directly into the slot's
  cache region and returns the first sampled token.
- **Donated decode state**: cache arrays are donated through every jitted
  step, so XLA updates them in place in HBM — no cache copies per token.
- **Host scheduler thread**: admission (free slot + pending request ->
  prefill) interleaved with decode sweeps; tokens stream to per-request
  thread-safe queues; true server-side TTFT is recorded here and surfaced
  through the API (the reference can only approximate TTFT client-side,
  SURVEY.md §7.3.5).
"""

from __future__ import annotations

import queue
import threading
import time
import uuid
from dataclasses import dataclass, field
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from kserve_vllm_mini_tpu.models.config import ModelConfig
from kserve_vllm_mini_tpu.models.llama import forward
from kserve_vllm_mini_tpu.profiling.compile_stats import (
    CompileRecorder,
    InstrumentedJit,
)
from kserve_vllm_mini_tpu.runtime import tracing as rt_tracing
from kserve_vllm_mini_tpu.runtime.sampling import (
    apply_penalties,
    count_tokens,
    sample_tokens,
    token_logprobs,
)

# Constrained decoding speaks the TOKEN protocol (runtime/token_grammar.py):
# machines expose token_mask(budget) -> bool[V] / advance_token(id). Raw
# byte automata (runtime/constrain.py) passed as GenRequest.constraint are
# auto-wrapped for the ByteTokenizer id mapping in submit().


def _unpack_mask(packed, vocab_size: int):
    """Device-side inverse of np.packbits(..., bitorder='little'):
    [..., ceil(V/8)] uint8 -> [..., V] bool. Grammar masks travel
    host->device EVERY constrained step; packing cuts that transfer 8x
    (~1 MB instead of ~8 MB per token at 64 slots x 128k vocab)."""
    bits = (packed[..., :, None] >> jnp.arange(8, dtype=jnp.uint8)) & 1
    flat = bits.reshape(*packed.shape[:-1], -1)
    return flat[..., :vocab_size].astype(bool)


def build_spec_step(cfg_t: ModelConfig, cfg_d: ModelConfig, k: int):
    """Jitted fused speculative round, shared by the serving engine and
    bench.py's drafter measurement: drafter proposes k tokens (scan), the
    target verifies all of them in ONE T=k forward, and acceptance/bonus
    selection happens on-device. Greedy exact-match acceptance ⇒ emitted
    tokens are bit-identical to plain greedy decode of the target.

    Returns ``(new_cache_t, new_cache_d, emit)`` where ``emit[s, j]`` is
    draft j while accepted, the target's bonus token at the first mismatch,
    and -1 after (the host emits the >=0 prefix)."""

    @partial(jax.jit, donate_argnums=(1, 3))
    def spec_step(params_t, cache_t, params_d, cache_d, last, lengths):
        # drafter: k autoregressive proposals d1..dk
        def dbody(carry, _):
            c, tok, lens = carry
            logits, nc = forward(
                params_d, cfg_d, tok[:, None], lens[:, None], c, lens
            )
            nxt = jnp.argmax(logits[:, 0, :], axis=-1).astype(jnp.int32)
            return (nc, nxt, lens + 1), nxt

        (cache_d, _, _), drafts = jax.lax.scan(
            dbody, (cache_d, last, lengths), None, length=k
        )
        drafts = drafts.T                                   # [S, k]
        # target verifies [last, d1..d_{k-1}] in one forward
        fed = jnp.concatenate([last[:, None], drafts[:, :-1]], axis=1)
        pos = lengths[:, None] + jnp.arange(k, dtype=jnp.int32)[None, :]
        logits, nc_t = forward(
            params_t, cfg_t, fed, pos, cache_t, lengths
        )
        preds = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [S, k]
        # accepted draft count a in 0..k-1: longest prefix where the
        # target's argmax agrees with the draft
        matches = preds[:, : k - 1] == drafts[:, : k - 1]
        a = jnp.where(
            jnp.all(matches, axis=1),
            k - 1,
            jnp.argmin(matches.astype(jnp.int32), axis=1),
        ) if k > 1 else jnp.zeros(last.shape, jnp.int32)
        bonus = jnp.take_along_axis(preds, a[:, None], axis=1)[:, 0]
        # emit[s, j] = draft j while j < a, the bonus at j == a, -1 after
        j = jnp.arange(k, dtype=jnp.int32)[None, :]
        emit = jnp.where(
            j < a[:, None], drafts,
            jnp.where(j == a[:, None], bonus[:, None], -1),
        )
        return nc_t, cache_d, emit

    return spec_step


def build_spec_step_sampled(cfg_t: ModelConfig, cfg_d: ModelConfig, k: int):
    """Speculative round with REJECTION SAMPLING (Leviathan/Chen): sampled
    requests speculate too, and the emitted tokens are distributed exactly
    as plain sampling from the target's filtered distribution.

    Per slot (temperature/top-k/top-p as [S] vectors, the continuous-
    batching convention): the drafter SAMPLES k proposals from its own
    filtered distribution q; the target computes its filtered distribution
    p at every position in one T=k forward; draft i is accepted with
    probability min(1, p(d_i)/q(d_i)); at the first rejection the
    replacement is drawn from the residual ``normalize(max(p - q, 0))``,
    and when every draft survives a bonus token is drawn from the last p.

    Temperature-0 rows degenerate EXACTLY to the greedy accept rule (see
    sampling.filter_logits): their distributions are one-hots, so the
    ratio is 1 on an argmax match, 0 otherwise, and the residual is the
    target's argmax — greedy requests emit bit-identical tokens to plain
    greedy decode even through this sampled path, which is why the engine
    can run ONE spec executable for a mixed greedy/sampled batch.

    Reference analog: vLLM's rejection sampler is what lets its spec
    decode serve sampled traffic (the reference benchmarks it via the
    speculative-decoding profile, runners/profiles/speculative-decoding
    .yaml); greedy-only speculation was VERDICT round-4 item 3's gap."""
    from kserve_vllm_mini_tpu.runtime.sampling import filter_logits

    @partial(jax.jit, donate_argnums=(1, 3))
    def spec_step(params_t, cache_t, params_d, cache_d, last, lengths,
                  temps, topks, topps, rng):
        rng_d, rng_acc, rng_res = jax.random.split(rng, 3)

        # drafter: k SAMPLED proposals + the full proposal distribution per
        # step (the rejection test and the residual both need q)
        def dbody(carry, rng_step):
            c, tok, lens = carry
            logits, nc = forward(
                params_d, cfg_d, tok[:, None], lens[:, None], c, lens
            )
            q_lg = filter_logits(logits[:, 0, :], temps, topks, topps)
            nxt = jax.random.categorical(rng_step, q_lg).astype(jnp.int32)
            return (nc, nxt, lens + 1), (nxt, jax.nn.softmax(q_lg, axis=-1))

        (cache_d, _, _), (drafts, q_all) = jax.lax.scan(
            dbody, (cache_d, last, lengths), jax.random.split(rng_d, k)
        )
        drafts = drafts.T                                   # [S, k]
        q_all = q_all.transpose(1, 0, 2)                    # [S, k, V]

        fed = jnp.concatenate([last[:, None], drafts[:, :-1]], axis=1)
        pos = lengths[:, None] + jnp.arange(k, dtype=jnp.int32)[None, :]
        logits, nc_t = forward(params_t, cfg_t, fed, pos, cache_t, lengths)
        S, V = logits.shape[0], logits.shape[-1]
        p_all = jax.nn.softmax(
            filter_logits(
                logits.reshape(S * k, V),
                jnp.repeat(temps, k), jnp.repeat(topks, k),
                jnp.repeat(topps, k),
            ).reshape(S, k, V),
            axis=-1,
        )                                                   # [S, k, V]

        # rejection test on the k-1 verifiable drafts
        if k > 1:
            dcols = drafts[:, : k - 1, None]
            p_tok = jnp.take_along_axis(p_all[:, : k - 1], dcols, axis=2)[..., 0]
            q_tok = jnp.take_along_axis(q_all[:, : k - 1], dcols, axis=2)[..., 0]
            u = jax.random.uniform(rng_acc, p_tok.shape)
            # u in [0,1): ratio >= 1 always accepts, ratio 0 always rejects
            accept = u * q_tok < p_tok                      # [S, k-1]
            a = jnp.where(
                jnp.all(accept, axis=1),
                k - 1,
                jnp.argmin(accept.astype(jnp.int32), axis=1),
            ).astype(jnp.int32)
        else:
            a = jnp.zeros(last.shape, jnp.int32)

        # token at the stop position: residual max(p-q, 0) on a rejection,
        # plain p for the all-accepted bonus (position k-1 has no verified
        # draft). A numerically-empty residual (p == q) falls back to p —
        # the rejection probability there is 0 anyway.
        p_a = jnp.take_along_axis(
            p_all, a[:, None, None], axis=1
        )[:, 0]                                             # [S, V]
        q_a = jnp.take_along_axis(q_all, a[:, None, None], axis=1)[:, 0]
        residual = jnp.maximum(p_a - q_a, 0.0)
        res_sum = jnp.sum(residual, axis=-1, keepdims=True)
        use_res = (a[:, None] < k - 1) & (res_sum > 0)
        dist = jnp.where(use_res, residual / jnp.maximum(res_sum, 1e-20), p_a)
        stop_tok = jax.random.categorical(
            rng_res, jnp.log(jnp.maximum(dist, 1e-38))
        ).astype(jnp.int32)

        j = jnp.arange(k, dtype=jnp.int32)[None, :]
        emit = jnp.where(
            j < a[:, None], drafts,
            jnp.where(j == a[:, None], stop_tok[:, None], -1),
        )
        return nc_t, cache_d, emit

    return spec_step


@dataclass
class EngineConfig:
    max_slots: int = 8
    max_seq_len: int = 1024           # per-request cap (cache length)
    max_prefill_len: int = 512
    min_prefill_bucket: int = 16
    # Chunked prefill (ROADMAP item 3, the TTFT/ITL-tail fix): prompts
    # whose un-reused remainder exceeds this many tokens are split into
    # fixed-size chunks that the SCHEDULER advances one per iteration,
    # interleaved with decode sweeps — a long prompt no longer freezes
    # every streaming client behind one monolithic compile+execute. The
    # chunk writes KV at its running offset (the same continuation-chunk
    # executables the over-budget path already uses, so greedy streams
    # stay byte-identical to monolithic admission) and only the final
    # chunk's last-position logits feed sampling. None = monolithic
    # admission (the seed behavior). Clamped into
    # [min_prefill_bucket, max_prefill_len]; lockstep multihost engines
    # ignore it (chunk advancement is a host-local scheduling decision
    # the follower replay stream does not carry — same rule as deadline
    # sheds).
    prefill_chunk: Optional[int] = None
    # Disaggregated prefill/decode (docs/DISAGGREGATION.md, ROADMAP
    # item 1): admissions route to a dedicated prefill lane
    # (runtime/disagg.py PrefillLane — its own thread, optionally its own
    # mesh submesh) that stages the prompt's KV out-of-band and hands the
    # finished stripe back through the versioned KV-block handoff
    # protocol, so long prefills NEVER execute on the decode lane's
    # sweep loop. Greedy streams stay byte-identical to the colocated
    # engine (same forward/params/bucket schedule, stripe injected
    # verbatim). Every failure mode degrades to colocated prefill —
    # dropped handoffs tombstone, a dead lane flips routing off — never
    # a hung request. v1 composes with dense KV only and excludes
    # drafters, LoRA, and prefix_cache; lockstep multihost engines
    # reject it (the lane is host-local, same rule as prefill_chunk).
    disagg: bool = False
    # prompts whose length is below this many tokens prefill colocated
    # even with disagg on: a short prefill is cheaper than its handoff
    # round-trip. 0 = route everything (the measurement-friendly
    # default; bench/serving set a threshold per deployment).
    disagg_min_prompt: int = 0
    seed: int = 0
    kv_cache_dtype: Optional[str] = None  # None -> model dtype (e.g. "float32")
    # How quantized matmul leaves contract (ops/qmatmul.py QUANT_MODES):
    # "dequant" casts the int weight to the activation dtype before the
    # dot (W8A16/W4A16); "w8a8" quantizes activations per token and runs
    # the contraction int8 x int8 on the MXU, scales folded post-
    # accumulation. No-op on unquantized params. The engine folds it into
    # cfg.quant_mode so every compiled step sees it as a static config
    # attribute.
    quant_mode: str = "dequant"
    # decode steps fused into one dispatch. 1 = lowest per-token latency;
    # larger values amortize host dispatch + readback (the dominant cost
    # when the accelerator is remote) at the price of streaming granularity
    # and up to chunk-1 wasted steps when a request finishes mid-chunk.
    decode_chunk: int = 1
    # speculative decoding: draft tokens proposed per round by the drafter
    # model (requires a drafter; 0 disables). Verification is rejection
    # sampling (build_spec_step_sampled): sampled requests speculate with
    # their output distribution preserved exactly, and temperature-0 rows
    # degenerate to the exact argmax accept rule, so greedy output stays
    # bit-identical to plain greedy decode. Penalized/constrained/logprob
    # slots fall back to the normal sweep (_spec_partition).
    spec_tokens: int = 0
    # serving-PP microbatches: slot groups pipelined GPipe-style through the
    # stages (parallel/serving_pp.py); 1 = unpipelined. Only used on pp>1
    # meshes; Engine rejects values that do not divide max_slots (a
    # non-dividing M would silently decode unpipelined).
    pp_microbatches: int = 1
    # Automatic prefix caching: a finished request's slot RETAINS its KV,
    # and a new request whose prompt shares a token prefix with a retained
    # slot is admitted INTO that slot, prefilling only the suffix (vLLM's
    # APC, re-thought for slot-contiguous caches: reuse = slot affinity,
    # zero copies). Generated tokens are part of the reusable prefix
    # (multi-turn chat appends to its own transcript). Off by default:
    # reused rows were computed by whatever executable shape the ORIGINAL
    # request used, so outputs can differ from a cold run by bf16 rounding
    # — the oracle tests pin the cold paths bit-exactly and opt in where
    # reuse itself is under test. Disabled when a drafter is configured
    # (the drafter cache retains proposal garbage a new request's drafter
    # would attend).
    prefix_cache: bool = False
    # Paged KV ("paged") vs per-slot dense stripes ("dense"). Paged is the
    # TPU re-think of vLLM's PagedAttention (the reference stack's namesake
    # mechanism, reference README.md:26): the cache is a pool of
    # kv_block_size-position blocks (models/llama.init_paged_kv_cache) and
    # each request owns an ordered block list, so HBM is reserved per
    # TOKENS IN FLIGHT — admission takes ceil((prompt+max_new)/BLK) blocks
    # — instead of max_slots x max_seq_len up front. 64 slots x 4096
    # max_seq of 8B bf16 KV is 34 GB (unservable on one v5e); the same
    # load at 256-token requests pages in ~1 GB. Requests that don't fit
    # the free pool wait in the queue (admission backpressure, no
    # mid-flight preemption — reservations are worst-case).
    # v1 limits: incompatible with meshes (sharded pools), drafters
    # (spec decode), and prefix_cache (block-level sharing is the planned
    # merge of the two).
    kv_layout: str = "dense"
    kv_block_size: int = 64
    # pool size in blocks; None sizes it to max_slots x ceil(max_seq/BLK)
    # (memory-equal to dense — set it LOWER to realize the savings)
    kv_pool_blocks: Optional[int] = None
    # Host-RAM KV tier (docs/TROUBLESHOOTING.md "Host-RAM KV tier
    # thrash"): byte budget of host memory that catches _retained_lru
    # evictions instead of discarding them. Evicted registered blocks
    # DEMOTE to the tier (device -> host copy, content key kept) and
    # PROMOTE back on a prefix-key match at admission (fresh block +
    # host -> device upload). Priced by profiling/headroom.py as host
    # bytes only — never counted against the HBM estimate. 0/None = no
    # tier. The tier disables itself when eviction churn crosses the
    # kv_thrash monitor thresholds (demoting under thrash just moves
    # the churn to PCIe) — the kv_tier_disabled gauge records it.
    kv_host_tier_bytes: Optional[int] = None
    # Double-buffered decode (docs/DECODE_PIPELINE.md): in steady state the
    # scheduler dispatches sweep N+1 from the ON-DEVICE sampled-token carry
    # before retiring sweep N, so host-side token emission/admission work
    # overlaps device compute instead of serializing with it. Emitted
    # streams are identical to the synchronous loop's (the dispatch-ahead
    # guard keeps chunk sizes and the rng split sequence aligned); grammar-
    # constrained slots, speculative partitions, and iterations where the
    # active set changes fall back to the synchronous sweep. False forces
    # the seed's fully synchronous dispatch->readback->emit loop.
    decode_pipeline: bool = True
    # multi-LoRA bank capacity for adapters loaded AT RUNTIME into an
    # engine that started without a bank (load_adapter creates a zero bank
    # of this many adapter slots; the bank's array shapes are fixed once
    # created, so growing past it needs a restart). Engines built with a
    # preset bank keep that bank's capacity instead.
    lora_slots: int = 4
    # Request lifecycle tracing (docs/TRACING.md): per-request phase spans
    # (queue wait, prefill, decode, cancellation) plus engine-lane
    # dispatch->retire window spans, recorded into a bounded ring buffer
    # served at GET /traces. On by default — the recorder is post-hoc (at
    # most tracing.MAX_REQUEST_SPANS tuples per request, never per-token)
    # and the buffer evicts at trace_buffer spans. False disables span
    # recording entirely; the phase histograms (plain counters) stay on.
    request_tracing: bool = True
    trace_buffer: int = 4096
    # Compile-stats capture (docs/PROFILING.md): the engine's compiled
    # steps go through an explicit lower().compile() wrapper
    # (profiling.compile_stats.InstrumentedJit) so compile wall time, the
    # XLA cost model's FLOPs/bytes, and peak-buffer estimates accumulate
    # into snapshot_stats / /metrics. One compile total per executable
    # (the wrapper caches what it built); any AOT failure falls back to
    # the plain jit call. Disabled automatically on meshes (AOT calls
    # don't auto-reshard arguments the way jit does).
    compile_stats: bool = True
    # In-process fault injection (docs/RESILIENCE.md): a KVMINI_FAULTS-
    # syntax string ("sweep_stall:after=5,duration=2;device_error:...")
    # parsed into a runtime/faults.py registry at build. None/empty =
    # NO registry object at all, so every hot-path site pays exactly one
    # `is not None` check (off by default, zero overhead when disabled).
    # Points are also armable at runtime through the server's /faults
    # endpoint (gated behind --allow-fault-injection).
    faults: Optional[str] = None
    # seed for any probabilistic fault trigger: two runs of the same
    # scripted scenario observe the identical event sequence
    fault_seed: int = 0
    # Engine watchdog (docs/RESILIENCE.md): a side thread that declares
    # the scheduler WEDGED when no sweep retires within
    # max(watchdog_factor x rolling sweep EMA, watchdog_min_s) while
    # work is live, immediately fails the in-flight batch with
    # finish_reason="engine_fault" (clients unblock even while the
    # scheduler thread is still stuck), and — once the loop resumes —
    # drains the poisoned pipeline and DEGRADES one ladder level per
    # trip (sync pipeline -> decode_chunk 1 -> spec off) before giving
    # up. Off by default: a cold engine's first XLA compiles stall the
    # loop legitimately for tens of seconds, so arming the watchdog is
    # a warmed-serving deployment decision.
    watchdog: bool = False
    watchdog_factor: float = 10.0
    watchdog_min_s: float = 2.0
    # Server default for per-request deadlines (seconds, submit-to-done
    # budget) used by deadline-aware admission shedding
    # (docs/RESILIENCE.md): a request that cannot meet its deadline
    # given the current queue burn-rate is 429-shed at the door instead
    # of timing out after burning decode steps. None = no server
    # default; client-supplied deadlines still apply.
    default_deadline_s: Optional[float] = None
    # Live economics rail (docs/ECONOMICS.md): accelerator label used to
    # price the deployment against tpu-cost.yaml. None = auto-detect
    # (the device_kind of TPU backends; CPU backends get NO rail — the
    # absent-not-zero rule, a fabricated $0/1K-tok on a dev box would
    # poison fleet aggregation). Setting it explicitly turns the rail on
    # regardless of backend, which is how tests and mock fleets price a
    # CPU engine as if it were the named chip.
    econ_accelerator: Optional[str] = None


@dataclass
class GenRequest:
    prompt_tokens: list[int]
    max_new_tokens: int = 64
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    # OpenAI presence/frequency penalties over generated tokens (vLLM
    # semantics: output-only, prompt excluded). Applied device-side from a
    # per-slot token-count table before sampling; 0.0 = bit-exact identity.
    presence_penalty: float = 0.0
    frequency_penalty: float = 0.0
    eos_id: Optional[int] = None
    request_id: str = field(default_factory=lambda: uuid.uuid4().hex[:16])
    # set by Engine.submit when the prompt was cut to max_prefill_len: the
    # request served is not the request sent, and every downstream record
    # (stream events, requests.csv, results.json) must carry the flag — a
    # measurement framework must not silently measure a different workload
    truncated: bool = False
    truncated_tokens: int = 0
    # logprobs in the OpenAI sense: when True, each streamed token event
    # carries (logprob, top-k ids, top-k logprobs); top_logprobs <= 5
    logprobs: bool = False
    top_logprobs: int = 0
    # grammar-constrained decoding: json_object mode and tool calls. Either
    # a token-protocol machine (runtime/token_grammar.py — works for any
    # tokenizer/vocab) or a raw byte automaton (runtime/constrain.py),
    # which submit() wraps with the ByteTokenizer id mapping. The engine
    # masks device-side; the machine runs host-side.
    constraint: Optional[Any] = None
    # multi-LoRA: adapter name from the engine's bank registry (None = base
    # model). Resolved to a bank index at submit; each slot decodes with
    # its own adapter inside the same jitted step (ops/lora.py).
    adapter: Optional[str] = None
    # W3C trace context from the client's traceparent header
    # (runtime/server.py parses it): the engine's phase spans share
    # trace_id with the client's trace and parent under parent_span_id,
    # so /traces output joins the loadgen's traces.json by trace_id
    # (docs/TRACING.md). None = a fresh trace id is minted at submit when
    # tracing is enabled (the request still shows up in /traces).
    trace_id: Optional[str] = None
    parent_span_id: Optional[str] = None
    # per-request deadline (seconds, measured from submit): a queued
    # request whose deadline expires before the scheduler admits it is
    # finished with finish_reason="shed" WITHOUT spending a prefill
    # (docs/RESILIENCE.md). The server also sheds at the door (429 +
    # Retry-After) when the admission estimate says the deadline cannot
    # be met. None = no deadline.
    deadline_s: Optional[float] = None


@dataclass
class _AdminOp:
    """Engine-state mutation executed ON the scheduler thread between
    sweeps (single-writer discipline for bank/registry swaps). ``fn`` runs
    with no args; the result/error lands in the fields and ``done`` fires."""

    fn: Any
    done: threading.Event = field(default_factory=threading.Event)
    error: Optional[str] = None

    def run(self) -> None:
        try:
            self.fn()
        except Exception as e:  # noqa: BLE001 — error goes to the caller
            # Event-ordered handoff: written on the scheduler thread
            # BEFORE done.set(); callers read only after done.wait()
            # kvmini: thread-ok — see above
            self.error = f"{type(e).__name__}: {e}"
        finally:
            self.done.set()


def _require_tp_only_mesh(mesh) -> None:
    """Multi-LoRA's replicated-bank design assumes tp-only meshes — ONE
    check shared by Engine init (preset banks) and load_adapter (hot-swap)
    so the two paths can never drift on which mesh shapes they accept."""
    if mesh is not None and any(
        mesh.shape.get(ax, 1) > 1 for ax in ("dp", "sp", "pp", "ep")
    ):
        raise ValueError(
            "multi-LoRA composes with tp-only meshes (replicated "
            "banks); dp/sp/pp/ep need a LoRA-free engine"
        )


class RequestHandle:
    """Streamed results: ('token', id, ts) events then ('done', info)."""

    def __init__(self, req: GenRequest) -> None:
        self.request = req
        self.events: "queue.Queue[tuple]" = queue.Queue()
        self.t_submit = time.time()
        self.t_admit: float = 0.0   # queue wait ends / prefill begins
        self.t_first_token: float = 0.0
        self.t_done: float = 0.0
        self.tokens: list[int] = []
        self.logprobs: list[tuple] = []  # (logprob, [(id, lp) x K]) per token
        self.finish_reason: str = ""
        # set by Engine.cancel (any thread): the scheduler finishes the
        # slot with this reason at its next iteration. Server-side stop-
        # sequence detection and client disconnects use this — the slot's
        # remaining budget would otherwise keep decoding into the batch.
        self.cancelled: Optional[str] = None

    @property
    def server_ttft_ms(self) -> float:
        if self.t_first_token:
            return (self.t_first_token - self.t_submit) * 1000.0
        return 0.0


class Engine:
    """Slot-based continuous-batching engine over a (possibly sharded) model."""

    def __init__(
        self,
        params: dict[str, Any],
        cfg: ModelConfig,
        engine_cfg: Optional[EngineConfig] = None,
        mesh=None,
        pad_id: int = 0,
        drafter: Optional[tuple[dict[str, Any], ModelConfig]] = None,
        lora: Optional[dict[str, Any]] = None,  # ops/lora.py bank; its
                                 # "names" dict maps adapter name -> index
                                 # (index 0 = base, always available)
        prefill_mesh=None,       # disaggregated prefill lane's own submesh
                                 # (parallel/mesh.lane_meshes; needs
                                 # ecfg.disagg — docs/DISAGGREGATION.md)
    ) -> None:
        self.cfg = cfg
        self.ecfg = engine_cfg or EngineConfig()
        from kserve_vllm_mini_tpu.ops.qmatmul import validate_quant_mode

        validate_quant_mode(self.ecfg.quant_mode)
        if self.ecfg.quant_mode != cfg.quant_mode:
            # one source of truth at trace time: the config every compiled
            # step closes over (callers that pre-scaled cfg and left the
            # EngineConfig default keep their cfg — default never demotes)
            if self.ecfg.quant_mode != "dequant":
                self.cfg = cfg = cfg.scaled(quant_mode=self.ecfg.quant_mode)
            else:
                self.ecfg.quant_mode = cfg.quant_mode
        self.ecfg.max_seq_len = min(self.ecfg.max_seq_len, cfg.max_seq_len)
        # prefill bucket must fit inside the cache with at least one decode slot
        self.ecfg.max_prefill_len = min(
            self.ecfg.max_prefill_len, self.ecfg.max_seq_len - 1
        )
        if self.ecfg.prefill_chunk is not None:
            if self.ecfg.prefill_chunk < 1:
                raise ValueError(
                    f"prefill_chunk={self.ecfg.prefill_chunk} must be >= 1 "
                    "(or None to disable chunked prefill)"
                )
            # a chunk below one bucket would pad up to the bucket anyway;
            # above max_prefill_len it is the monolithic budget — and a
            # non-bucket value is rounded UP to the bucket its pieces
            # would compile at, so no piece carries permanent pad waste
            # (and the headroom estimate prices the real executable width)
            self.ecfg.prefill_chunk = self._bucket(min(
                max(self.ecfg.prefill_chunk, self.ecfg.min_prefill_bucket),
                self.ecfg.max_prefill_len,
            ))
        self.mesh = mesh
        self.pad_id = pad_id
        self.params = params

        # model executor: plain forward, or the pp-sharded drop-in when the
        # mesh pipelines layers (parallel/serving_pp.py — same signature, so
        # every compiled step below is executor-agnostic)
        self._fwd = forward
        if mesh is not None and mesh.shape.get("pp", 1) > 1:
            from kserve_vllm_mini_tpu.parallel.serving_pp import make_pp_forward

            mb = max(self.ecfg.pp_microbatches, 1)
            if mb > 1 and self.ecfg.max_slots % mb:
                raise ValueError(
                    f"pp_microbatches={mb} must divide max_slots="
                    f"{self.ecfg.max_slots}, or every decode sweep would "
                    "silently fall back to unpipelined"
                )
            self._fwd = make_pp_forward(cfg, mesh, microbatches=mb)
            if drafter is not None:
                raise ValueError(
                    "speculative decoding is not supported with serving "
                    "pipeline parallelism (pp > 1); drop the drafter or pp"
                )

        from kserve_vllm_mini_tpu.models.llama import init_kv_cache, init_paged_kv_cache

        S = self.ecfg.max_slots
        kv_quant = self.ecfg.kv_cache_dtype == "int8"
        kv_dt = (
            jnp.dtype(self.ecfg.kv_cache_dtype)
            if (self.ecfg.kv_cache_dtype and not kv_quant)
            else None
        )

        # Serializes every `self._cache = fn(self._cache, ...)` read-
        # dispatch-assign against the paged prefill lane (docs/
        # DISAGGREGATION.md v2): with HANDOFF_VERSION=2 the lane thread
        # dispatches paged prefills INTO the shared pool cache, and an
        # unserialized interleave could dispatch two donations of the
        # same buffer (the assign is not atomic with the read). JAX
        # async dispatch keeps the critical section microseconds —
        # device execution is ordered by buffer dependencies, not the
        # lock. Uncontended (colocated/dense engines never race it).
        self._cache_lock = threading.Lock()

        self.paged = self.ecfg.kv_layout == "paged"
        if self.ecfg.kv_layout not in ("dense", "paged"):
            raise ValueError(
                f"unknown kv_layout {self.ecfg.kv_layout!r}; known: dense, paged"
            )

        # Disaggregated prefill/decode (docs/DISAGGREGATION.md): validate
        # compositions up front — the lane is constructed further down,
        # once the compile recorder and fault registry it threads exist.
        if self.ecfg.disagg:
            if self.paged and prefill_mesh is not None:
                raise ValueError(
                    "paged disagg (HANDOFF_VERSION=2) shares ONE block "
                    "pool between the lanes, so the lane must run on the "
                    "engine's own mesh/devices; per-lane meshes compose "
                    "with kv_layout=dense only"
                )
            if drafter is not None:
                raise ValueError(
                    "disagg does not support speculative drafters yet "
                    "(the drafter's shadow prefill writes the decode "
                    "lane's drafter cache); drop the drafter or disagg"
                )
            if lora is not None:
                raise ValueError(
                    "disagg does not support multi-LoRA yet (the lane "
                    "would need the adapter bank); drop --lora or disagg"
                )
            if self.ecfg.prefix_cache and not self.paged:
                raise ValueError(
                    "disagg and the DENSE prefix_cache are mutually "
                    "exclusive: slot-level reuse matching happens at the "
                    "decode lane's slot index, which the prefill lane "
                    "cannot see. The paged layout composes — block "
                    "reuse is claimed at routing time on the scheduler "
                    "thread and the lane prefills only the suffix"
                )
            if mesh is not None and any(
                mesh.shape.get(ax, 1) > 1 for ax in ("dp", "sp", "pp")
            ):
                raise ValueError(
                    "disagg composes with tp-only decode meshes; "
                    "dp/sp/pp need a colocated engine"
                )
        elif prefill_mesh is not None:
            raise ValueError("prefill_mesh requires EngineConfig.disagg=True")
        if self.paged:
            if mesh is not None and any(
                mesh.shape.get(ax, 1) > 1 for ax in ("dp", "sp", "pp")
            ):
                raise ValueError(
                    "paged KV composes with tp-only meshes; dp/sp/pp need "
                    "kv_layout=dense (block gathers don't partition over "
                    "a sharded slot/seq/layer axis)"
                )
            if drafter is not None:
                raise ValueError("paged KV does not support speculative "
                                 "decoding yet; drop the drafter or use dense")
            # prefix_cache + paged = BLOCK-LEVEL prefix sharing (vLLM-style
            # hash-based APC): full prompt blocks are content-addressed and
            # shared across requests by table reference. Sharing full
            # blocks only means writes always land PAST the reused region
            # in private blocks — no copy-on-write needed. State below.
            blk = self.ecfg.kv_block_size
            if blk < 1:
                raise ValueError(f"kv_block_size={blk} must be >= 1")
            self._blk = blk
            self._maxb = -(-self.ecfg.max_seq_len // blk)
            # explicit None check: 0 must be rejected below, not silently
            # fall back to the memory-equal-to-dense default pool
            n_user = (
                self.ecfg.kv_pool_blocks
                if self.ecfg.kv_pool_blocks is not None
                else S * self._maxb
            )
            if n_user < 1:
                raise ValueError(f"kv_pool_blocks={n_user} must be >= 1")
            # a pool smaller than one max-length request is allowed: submit()
            # error-rejects any request whose worst case exceeds the pool,
            # so undersizing shrinks the admissible request size, not safety
            # +1: the last block is SCRATCH — freed slots' table rows point
            # at it so their harmless in-flight decode writes (the sweep
            # dispatches all S slots, active or not) can never land in a
            # block that was reassigned to another request
            self._scratch_block = n_user
            if mesh is not None:
                # allocate DIRECTLY into the tp layout (same rationale as
                # the dense mesh cache below: the pool may only fit HBM
                # sharded)
                from kserve_vllm_mini_tpu.parallel.sharding import (
                    paged_kv_cache_shardings,
                )

                self._cache = jax.jit(
                    partial(init_paged_kv_cache, cfg, n_user + 1, blk,
                            dtype=kv_dt, quantized=kv_quant),
                    out_shardings=paged_kv_cache_shardings(
                        cfg, mesh, quantized=kv_quant
                    ),
                )()
            else:
                self._cache = init_paged_kv_cache(
                    cfg, n_user + 1, blk, dtype=kv_dt, quantized=kv_quant
                )
            self._free_blocks: list[int] = list(range(n_user))
            self._slot_blocks: list[list[int]] = [[] for _ in range(S)]
            self._block_table = np.full((S, self._maxb), self._scratch_block,
                                        dtype=np.int32)
            self._table_dev: Optional[jnp.ndarray] = None  # lazy device mirror
            # head-of-line request that didn't fit the free pool; retried
            # first so admission stays FIFO
            self._deferred: Optional[RequestHandle] = None
            # block-level prefix sharing (prefix_cache=True): a FULL prompt
            # block's content key (sha256 of the token prefix up to its
            # end) maps to the pool block holding its KV. _block_rc counts
            # slot ownerships; rc==0 registered blocks park in the
            # _retained_lru (insertion order = recency) until the
            # allocator evicts them for fresh allocations.
            self._hash_block: dict[bytes, int] = {}
            self._block_hash: dict[int, bytes] = {}
            self._block_rc: dict[int, int] = {}
            # bumped whenever the content index CHANGES (registration /
            # eviction) — plans memoized on requests stay valid between
            # bumps, so a deferred head-of-line request's per-sweep fit
            # recheck is O(1) instead of re-hashing its whole prompt
            self._prefix_epoch = 0
            from collections import OrderedDict

            self._retained_lru: "OrderedDict[int, None]" = OrderedDict()
            # chain depth (1-based block index within the prompt chain
            # that registered it) per registered block: the migration
            # exporter orders blocks root-first by this so a bounded
            # byte budget truncates the LEAVES of a chain, never its
            # roots (plans match root-outward and stop at the first
            # miss — an orphaned leaf would be dead weight on the wire)
            self._block_depth: dict[int, int] = {}
            # Host-RAM KV tier (EngineConfig.kv_host_tier_bytes): content
            # key -> {"depth", "kv": host leaves}, insertion order =
            # recency (popitem(last=False) evicts the oldest). Scheduler-
            # thread-only, like every other pool structure. Tier
            # mutations bump _prefix_epoch: a memoized plan that counted
            # a tier hit must not survive the entry's eviction.
            self._tier: "OrderedDict[bytes, dict]" = OrderedDict()
            self._tier_bytes = 0
            self._tier_cap_bytes = int(self.ecfg.kv_host_tier_bytes or 0)
            self._tier_disabled = False
            # thrash guard window state (rate of kv_retained_evictions
            # over ~1 s windows, same thresholds as the monitor's
            # kv_thrash rule): (window start, eviction count at start),
            # consecutive over-threshold windows
            self._tier_thrash_win = (time.time(), 0)
            self._tier_thrash_hits = 0
            # paged-v2 handoff/abort bookkeeping: blocks owned by a slot
            # that was aborted while its prompt was still ON the lane.
            # They must not return to the free pool until the lane's
            # payload orphans at consume (the lane may still have
            # dispatches in flight writing them) — keyed by handle id.
            self._orphan_blocks: dict[int, list[int]] = {}

        def make_cache():
            return init_kv_cache(
                cfg, S, max_seq=self.ecfg.max_seq_len, dtype=kv_dt, quantized=kv_quant
            )

        if self.paged:
            pass  # pool allocated above
        elif mesh is not None:
            from kserve_vllm_mini_tpu.parallel.sharding import kv_cache_shardings

            # allocate DIRECTLY into the mesh layout: materializing the full
            # cache on one device first and device_put-ting after would OOM
            # exactly the deployments sharding exists for (a pp/tp mesh
            # because model+cache exceed one chip's HBM)
            sh = kv_cache_shardings(cfg, mesh, quantized=kv_quant)
            self._cache = jax.jit(make_cache, out_shardings=sh)()
        else:
            self._cache = make_cache()

        # speculative decoding: the drafter keeps its own KV cache with the
        # same slot/seq geometry so slot bookkeeping is shared
        self._drafter_params: Optional[dict[str, Any]] = None
        self._drafter_cfg: Optional[ModelConfig] = None
        if drafter is not None:
            self._drafter_params, self._drafter_cfg = drafter
            if (
                self.ecfg.quant_mode != "dequant"
                and self._drafter_cfg.quant_mode != self.ecfg.quant_mode
            ):
                # speculative decoding and quantization COMPOSE: the
                # drafter's projections ride the same quant_mode as the
                # target (w8a8 = int8 x int8 on the MXU when its leaves
                # are quantized; a documented no-op on plain weights —
                # ops/quant.linear), so spec rounds stream the drafter's
                # int8 bytes instead of silently excluding each other
                self._drafter_cfg = self._drafter_cfg.scaled(
                    quant_mode=self.ecfg.quant_mode
                )
            self._dcache = init_kv_cache(
                self._drafter_cfg, S, max_seq=self.ecfg.max_seq_len,
                dtype=kv_dt, quantized=kv_quant,
            )
        self._spec_fn = None

        # multi-LoRA bank: per-slot adapter index decoded inside the same
        # jitted step; index 0 is the base (zero) adapter
        if lora is not None:
            _require_tp_only_mesh(mesh)
            if mesh is not None:
                # replicate the bank over the mesh BEFORE it becomes engine
                # state: factor banks are MBs at serving ranks, and a
                # replicated delta lets GSPMD join it with the tp-sharded
                # base projections however each target is partitioned (no
                # per-target spec bookkeeping to get wrong). Hot-swap
                # (load_adapter) applies the same replication.
                from jax.sharding import NamedSharding, PartitionSpec

                rep = NamedSharding(mesh, PartitionSpec())
                lora = {
                    **lora,
                    "layers": jax.device_put(lora["layers"], rep),
                }
        self._lora = lora
        self._lora_names: dict[str, int] = dict(lora.get("names", {})) if lora else {}
        if lora is not None:
            if drafter is not None:
                # the drafter proposes from base weights; verification would
                # accept base-model continuations for adapted slots. The
                # per-slot gate below excludes adapted slots from spec, but
                # mixing the features is untested — reject loudly for now.
                raise ValueError("multi-LoRA with a speculative drafter is "
                                 "not supported yet")
            if self.ecfg.prefix_cache:
                # retained KV is matched by TOKENS only; K/V rows computed
                # under adapter a's wk/wv deltas must never be reused by a
                # base or adapter-b request sharing the same prompt prefix
                raise ValueError("multi-LoRA and prefix_cache are mutually "
                                 "exclusive: retained KV carries no record "
                                 "of the adapter that computed it")
        self._slot_adapter = [0] * S
        self._adapter_ids_dev: Optional[jnp.ndarray] = None
        # live adapter load/unload ops, drained by the scheduler between
        # sweeps; bank capacity for a runtime-created bank comes from
        # ecfg.lora_slots
        self._admin: "queue.Queue[_AdminOp]" = queue.Queue()

        # host-side slot state
        self._slot_req: list[Optional[RequestHandle]] = [None] * S
        self._slot_len = [0] * S
        self._slot_remaining = [0] * S
        self._last_tokens = [pad_id] * S
        self._slot_machine: list[Optional[Any]] = [None] * S  # constraints
        self._free = list(range(S))
        # prefix cache: tokens whose KV occupies the slot's rows 0..len-1
        # while live, and the retained (trimmed-to-written) prefix once the
        # slot is freed — matched against new prompts at admission
        self._slot_tokens: list[list[int]] = [[] for _ in range(S)]
        self._retained: list[list[int]] = [[] for _ in range(S)]

        # chunked-prefill state (EngineConfig.prefill_chunk): a slot whose
        # prompt is being chunk-prefilled is OCCUPIED (_slot_req set, so
        # cancellation / watchdog / drain all see its handle) but not
        # decode-ACTIVE — _decode_active() excludes it until the final
        # chunk's logits feed sampling. _slot_len doubles as the prefill
        # FRONTIER while the entry is live: concurrent sweeps' garbage
        # writes land at >= the frontier and the next chunk overwrites
        # them (dispatch order) before they can ever be attended.
        # _prefill_fifo orders advancement: the OLDEST admission advances
        # one chunk per scheduler iteration (completion order matches the
        # monolithic path's serial admissions). Scheduler-thread-only.
        self._slot_prefill: list[Optional[dict]] = [None] * S
        self._prefill_fifo: list[int] = []

        # disaggregated-prefill state (docs/DISAGGREGATION.md): a slot
        # whose prompt is prefilling ON THE LANE is OCCUPIED (_slot_req
        # set — cancellation/watchdog/drain all see the handle) but not
        # decode-ACTIVE until its handoff is consumed and _activate_slot
        # samples the first token. The dict holds {"handle", "t_route"}
        # (route time anchors the server.handoff span and the consume-
        # side never-hang timeout). Scheduler-thread-only.
        self._slot_handoff: list[Optional[dict]] = [None] * S

        self._pending: "queue.Queue[RequestHandle]" = queue.Queue()
        self._rng = jax.random.PRNGKey(self.ecfg.seed)
        # per-slot generated-token counts [S, V] int32, device-resident:
        # the presence/frequency-penalty state (sampling.apply_penalties).
        # int32 at the 8B headline geometry (80 x 128k) is 41 MB — 0.5% of
        # the per-step weight stream, cheap enough to keep unconditional so
        # the decode executable never re-traces when the first penalized
        # request arrives. Rows are reset at admission, not at finish.
        self._counts = jnp.zeros((S, self.cfg.vocab_size), jnp.int32)
        self._step_counter = 0
        self._prefill_fns: dict[tuple[int, bool], Any] = {}
        self._decode_fns: dict[int, Any] = {}
        self._running = False
        self._thread: Optional[threading.Thread] = None
        # sampling-parameter device arrays, rebuilt only on admit/finish —
        # never on the per-token hot path
        self._sampling_arrays: Optional[tuple] = None

        # double-buffered decode state (docs/DECODE_PIPELINE.md):
        # _tokens_dev mirrors _last_tokens on device — in steady state it is
        # the previous sweep's sampled-token carry, so the per-sweep
        # host->device token transfer disappears; None = rebuild from host.
        # _tokens_dev_slots is the set of slots whose carry rows are REAL
        # (they emitted through the sweep that produced the carry): a slot
        # outside it — e.g. a spec slot whose round was skipped — has a
        # garbage row and must be fed from _last_tokens instead.
        self._tokens_dev: Optional[jnp.ndarray] = None
        self._tokens_dev_slots: frozenset = frozenset()
        # dispatched-but-not-retired sweeps, oldest first; each record holds
        # the stacked per-step device outputs plus the host snapshot needed
        # to emit them (active slots, handle identities, chunk, rng rewind)
        self._inflight: list[dict] = []
        # decode positions the in-flight sweeps have already written past
        # the host-visible _slot_len (chunk per unretired sweep)
        self._pending_steps = 0
        self._t_last_ready = 0.0   # when the device last finished a sweep
        self._bubble_anchor = 0.0  # device went idle here with work queued
        # multihost lockstep mode (set by runtime/multihost.py drivers):
        # disables the retire-time cancelled-handle emission skip, whose
        # trigger is a host-local race the follower cannot observe —
        # lockstep cancellation latency is one published decision instead
        self._lockstep = False

        # compile-stats capture (docs/PROFILING.md): every compiled step
        # below registers its lower().compile() facts here; exported via
        # snapshot_stats -> /metrics (compile_* keys). Thread-safe — the
        # scheduler thread records, server threads snapshot.
        self._compile_recorder = CompileRecorder()

        # KV/HBM observability (docs/TROUBLESHOOTING.md "HBM pressure &
        # KV thrash"): prefix-hit depths (tokens reused per admission) in
        # a bounded ring, appended on the scheduler thread only; the
        # p50/p95 gauges are computed ON that thread too, inside the
        # _kv_admin_snapshot admin op, so no derived ratio is ever built
        # from torn cross-thread reads. _kv_gauges caches the last
        # consistent snapshot (served when the admin op can't run, e.g.
        # mid-shutdown) and _hbm_peak_seen tracks the high-water
        # bytes_in_use across scrapes for backends whose memory_stats
        # lacks a native peak counter; both move under _obs_lock because
        # any scraper thread may update them.
        from collections import deque

        self._hit_depths: "deque[int]" = deque(maxlen=4096)
        self._obs_lock = threading.Lock()
        self._kv_gauges: dict[str, Any] = {}
        self._kv_gauges_t = 0.0          # last refresh (scheduler clock)
        self._hbm_peak_seen = 0

        # Live economics rail (docs/ECONOMICS.md): rolling-window $/1K-tok,
        # Wh/1K-tok, and the $/hr accrual derived from the busy/token
        # counters this engine already keeps, priced by tpu-cost.yaml.
        # Auto-detected on TPU backends (device_kind names the chip the
        # pricing sheet matches fuzzily), forced on any backend by
        # ecfg.econ_accelerator, and absent — no object, no keys, no
        # fabricated $0 — everywhere else. Fed/read under _obs_lock only
        # (the PR 8 gauge-cache discipline: published under a lock, not
        # annotated away).
        self._econ = None
        accel = self.ecfg.econ_accelerator
        if not accel:
            try:
                dev = jax.devices()[0]
                if getattr(dev, "platform", "") == "tpu":
                    accel = getattr(dev, "device_kind", "") or "tpu"
            except Exception:
                accel = None
        if accel:
            from kserve_vllm_mini_tpu.costs.live import LiveEconomics

            self._econ = LiveEconomics(
                accelerator=accel,
                chips=self.mesh.size if self.mesh is not None else 1,
            )

        # Resilience state (docs/RESILIENCE.md). ONE lock guards every
        # cross-thread field: the scheduler beats/EMAs, the watchdog's
        # trip bookkeeping, the server's shed counter, the degrade
        # ladder, and the published live-handle snapshot — watchdog,
        # scheduler, and server threads all touch them (KVM05x
        # discipline: published under a lock, not annotated away). The
        # fault registry is created ONCE here (internally locked, never
        # reassigned); an un-armed registry costs one uncontended lock
        # acquire + dict miss per hot-path check.
        from kserve_vllm_mini_tpu.runtime.faults import FaultRegistry

        self._res_lock = threading.Lock()
        self._faults = FaultRegistry(seed=self.ecfg.fault_seed,
                                     config=self.ecfg.faults or "")
        self._watch_beat = time.time()   # last scheduler progress mark
        self._sweep_ema_s = 0.0          # rolling dispatch->retire wall
        self._service_ema_s = 0.0        # rolling admit->done wall
        self._watchdog_trips = 0
        self._engine_faults = 0          # recovered engine faults (all paths)
        self._degrade_level = 0          # 0 normal .. 3 spec off; 4 = dead
        self._requests_shed = 0          # deadline/admission sheds
        self._fault_pending: Optional[str] = None
        self._faulted_ids: set[str] = set()  # handles the watchdog already
        #                                      sent a terminal event to
        # the scheduler republishes its live handles here each iteration
        # so the watchdog/estimator never read the scheduler-owned slot
        # list directly
        self._live_handles: list[RequestHandle] = []
        self._watch_thread: Optional[threading.Thread] = None
        self._watch_stop = threading.Event()
        # scheduler-thread-only: paged admission backpressure window the
        # kv_alloc_fail injection opens (epoch seconds); expires by its
        # armed duration
        self._kv_fault_until = 0.0

        # disaggregated prefill lane (docs/DISAGGREGATION.md): built here
        # so it can thread the compile recorder (its executables land in
        # the compile-stats rail as disagg_prefill[...]) and the fault
        # registry (the kv_handoff_drop injection point). Degrade state
        # is scheduler-owned: consecutive tombstoned handoffs flip
        # _disagg_degraded and routing falls back to colocated prefill
        # for the rest of the run.
        self._disagg = None
        self._disagg_degraded = False
        self._disagg_drop_run = 0
        if self.ecfg.disagg:
            from kserve_vllm_mini_tpu.runtime.disagg import PrefillLane

            self._disagg = PrefillLane(
                self.params, cfg, self.ecfg, pad_id=pad_id,
                instrument=(
                    self._instrument if prefill_mesh is None else None
                ),
                faults=self._faults,
                prefill_mesh=prefill_mesh,
                # paged engines hand the lane the ENGINE's paged prefill
                # path (shared pool, zero-copy v2 block-table handoff)
                # instead of a staging cache + stripe
                paged_prefill=(
                    self._lane_paged_prefill if self.paged else None
                ),
            )

        # stats for /metrics and duty-cycle telemetry
        self.stats = {
            "prefill_tokens": 0,
            "decode_tokens": 0,
            "decode_steps": 0,
            "prefills": 0,
            # chunked-prefill telemetry (ROADMAP item 3): compiled prefill
            # piece dispatches (target + drafter shadow, monolithic and
            # chunked admissions alike), and the prefill wall that ran
            # while decode work was live — the direct measurement of the
            # stall chunking exists to break up (docs/TROUBLESHOOTING.md
            # "Long prompts stall streaming")
            "prefill_chunks": 0,
            "prefill_chunk_stall_s": 0.0,
            "requests_completed": 0,
            "busy_s": 0.0,        # exported: busy_seconds_total + duty_cycle
            "started_at": time.time(),  # kvmini: metrics-ok — raw input; exposed as duty_cycle
            "queue_depth": 0,
            "spec_rounds": 0,       # fused drafter-propose/target-verify rounds
            "spec_accepted": 0,     # draft tokens accepted across all rounds
            "spec_proposed": 0,     # draft tokens proposed (rounds x k-1)
            "prefix_hits": 0,       # admissions that reused a retained prefix
            "prefix_lookups": 0,    # admissions that ATTEMPTED prefix reuse
            "prefix_tokens_reused": 0,  # prompt tokens NOT re-prefilled
            # paged-block lifecycle (docs/TROUBLESHOOTING.md "HBM pressure
            # & KV thrash"): allocator churn the point-in-time pool gauges
            # cannot show — all three only move on the scheduler thread
            "kv_blocks_allocated": 0,    # fresh pool-block allocations
            "kv_retained_evictions": 0,  # retained-pool LRU evictions
            "kv_share_reclaims": 0,      # shared-block 0->1 rc claims
            # decode-pipeline telemetry (docs/DECODE_PIPELINE.md):
            "dispatch_depth": 0,    # high-water concurrently in-flight sweeps
            "pipelined_sweeps": 0,  # sweeps dispatched ahead of a retire
            "host_overlap_s": 0.0,  # host emit/bookkeeping under device compute
            "bubble_s": 0.0,        # device idle between sweeps with work live
            "pipeline_fallback_constrained": 0,  # grammar mask forced sync
            "pipeline_fallback_spec": 0,         # spec partition forced sync
            "pipeline_fallback_active_set": 0,   # admission/cancel forced retire
            "pipeline_fallback_headroom": 0,     # cache window forced sync
        }
        if self.paged:
            # KV-block economy rail (ISSUE 16), paged engines only (same
            # conditional contract as the pool gauges): host-tier
            # lifecycle and cross-replica migration accounting — all
            # scheduler-thread writes (demotion/promotion at alloc/admit,
            # import/export inside _run_admin ops), single-writer.
            self.stats.update({
                "kv_tier_demotions": 0,   # evictions caught by the tier
                "kv_tier_promotions": 0,  # tier blocks uploaded back
                "kv_tier_hits": 0,        # admissions that matched the tier
                "kv_migrated_blocks": 0,  # blocks installed via kv_import
                "kv_migrated_bytes": 0,   # wire bytes installed via kv_import
                "kv_export_blocks": 0,    # blocks shipped via kv_export
            })
        if self._disagg is not None:
            # disaggregated-serving rail (docs/DISAGGREGATION.md), present
            # only on disagg engines (same conditional contract as the
            # paged pool gauges): handoffs consumed, block/wait/lane-busy
            # accounting, tombstoned drops, and colocated fallbacks (the
            # degrade ladder's visible steps). All consumed into stats on
            # the scheduler thread (_consume_handoffs), single-writer.
            self.stats.update({
                "kv_handoffs": 0,            # handoffs consumed into slots
                "kv_handoff_blocks": 0,      # KV blocks handed across lanes
                "kv_handoff_wait_s": 0.0,    # lane-done -> consume wall
                "kv_handoff_drops": 0,       # tombstones (drop/error/timeout)
                # physical KV bytes the consume side copied per landed
                # handoff: the v1 dense stripe's nbytes, 0 on the v2
                # block-table path — the handoff-tax byte measurement
                "kv_handoff_bytes_copied": 0,
                "prefill_lane_busy_s": 0.0,  # lane compute wall
                "disagg_colocated_fallbacks": 0,  # prefills degraded back
            })

        # request lifecycle tracing (docs/TRACING.md): bounded ring of
        # completed phase spans served at GET /traces, plus per-phase
        # duration histograms for /metrics (kvmini_tpu_phase_seconds).
        # The histograms are plain counters and stay on even when span
        # recording is disabled (request_tracing=False).
        self.tracer: Optional[rt_tracing.SpanRecorder] = (
            rt_tracing.SpanRecorder(self.ecfg.trace_buffer)
            if self.ecfg.request_tracing else None
        )
        # engine-lane spans (decode dispatch->retire windows) accrue one
        # PER SWEEP — orders of magnitude faster than request spans. They
        # get their OWN ring so a long run's sweep spans can never evict
        # the per-request phase spans the analyzer joins; they share one
        # synthetic trace per engine lifetime and land in /traces beside
        # the request spans (traces_otlp merges the two rings).
        self._engine_tracer: Optional[rt_tracing.SpanRecorder] = (
            rt_tracing.SpanRecorder(min(1024, self.ecfg.trace_buffer))
            if self.ecfg.request_tracing else None
        )
        self._engine_trace_id = rt_tracing.new_trace_id()
        self._phase_hist = {
            p: rt_tracing.PhaseHistogram() for p in rt_tracing.PHASES
        }

        # Per-device analytic HBM footprint for headroom-model validation
        # (profiling/headroom.py; docs/TROUBLESHOOTING.md): the guard's
        # formula shape — weights + KV + workspace, x1.15 fusion margin —
        # but with the weights term taken from the ACTUAL loaded tree
        # (quant guessing validated separately by the guard's own tests)
        # and the KV term priced by kv_bytes_per_token, so what
        # headroom_error_pct measures is the analytic KV/workspace/margin
        # model — the part whose underestimate OOMed BENCH_r02.
        from kserve_vllm_mini_tpu.profiling.headroom import estimate_serving_bytes

        weight_bytes = sum(
            int(getattr(leaf, "nbytes", 0))
            for leaf in jax.tree_util.tree_leaves(self.params)
        )
        if drafter is not None:
            weight_bytes += sum(
                int(getattr(leaf, "nbytes", 0))
                for leaf in jax.tree_util.tree_leaves(self._drafter_params)
            )
        analytic = estimate_serving_bytes(
            cfg, S, self.ecfg.max_seq_len, kv_quant=kv_quant,
            quant_mode=cfg.quant_mode,
            prefill_chunk=self.ecfg.prefill_chunk,
        )
        kv_bytes = S * self.ecfg.max_seq_len * self.kv_bytes_per_token()
        n_dev = self.mesh.size if self.mesh is not None else 1
        self._headroom_estimate_bytes = int(
            (weight_bytes + kv_bytes + analytic["workspace_bytes"]) * 1.15
        ) // n_dev

        # seed the consistent-gauge cache with a build-time snapshot (the
        # scheduler isn't running yet, so _run_admin executes inline):
        # /metrics served before the first sweep must still carry the
        # paged pool gauges rather than an empty fallback dict
        self._kv_admin_snapshot()

    # -- paged-KV block accounting ----------------------------------------

    def _blocks_needed(self, req: GenRequest) -> int:
        """Worst-case pool blocks a request can touch: prompt + budgeted
        new tokens, plus up to decode_chunk-1 surplus writes from the fused
        sweep that logically finishes it, capped by the KV window."""
        worst = min(
            len(req.prompt_tokens) + req.max_new_tokens + self.ecfg.decode_chunk,
            self.ecfg.max_seq_len,
        )
        return -(-worst // self._blk)

    def _prefix_keys(self, prompt: list[int], n_blocks: int) -> list[bytes]:
        """Content keys of the prompt's first 1..n_blocks full blocks in
        ONE incremental pass: the KV of position p depends on ALL tokens
        <= p, so block i's key hashes the whole prefix up to its end — a
        running sha256 snapshot per block boundary keeps this O(len), not
        O(len^2/blk)."""
        import hashlib

        h = hashlib.sha256()
        keys: list[bytes] = []
        for i in range(n_blocks):
            for t in prompt[i * self._blk : (i + 1) * self._blk]:
                h.update(t.to_bytes(8, "little", signed=True))
            keys.append(h.copy().digest())
        return keys

    def _paged_plan(self, req: GenRequest) -> tuple[list[int], int]:
        """(reusable shared block ids for the longest cached prompt
        prefix, new blocks the request still needs). At least the final
        prompt token always prefills (its last-position logits feed the
        first sample), so reuse caps at (len-1)//BLK full blocks; and —
        same rule as the dense APC's slot matching — a match below
        max(min_prefill_bucket, len/4) doesn't count: it would move the
        big remainder off the flash fresh-prefill path onto the masked
        chunk path for a trivial saving.

        The plan (and the prompt's full key list, reused by registration)
        memoizes on the request, keyed by _prefix_epoch: stale plans must
        never survive an index change — an evicted block id in a cached
        plan would reuse a reallocated block's garbage KV."""
        cached = getattr(req, "_plan_cache", None)
        if cached is not None and cached[0] == self._prefix_epoch:
            return list(cached[2]), cached[3]
        prompt = req.prompt_tokens
        reuse: list[int] = []
        keys: list[bytes] = []
        tier_keys: list[bytes] = []
        if self.ecfg.prefix_cache:
            keys = self._prefix_keys(prompt, len(prompt) // self._blk)
            max_b = (len(prompt) - 1) // self._blk
            for key in keys[:max_b]:
                bid = self._hash_block.get(key)
                if bid is None:
                    break
                reuse.append(bid)
            if self._tier and not self._tier_disabled:
                # host-tier extension of the chain: demoted blocks whose
                # keys continue the match beyond the device-resident
                # prefix promote back at admission (read-only here — the
                # upload happens in _paged_admit_blocks). Contiguity
                # matters: a tier hit PAST a miss would leave a KV hole
                # the prefill would never fill.
                for key in keys[len(reuse):max_b]:
                    if key not in self._tier:
                        break
                    tier_keys.append(key)
            floor = max(self.ecfg.min_prefill_bucket, len(prompt) // 4)
            if (len(reuse) + len(tier_keys)) * self._blk < floor:
                reuse = []
                tier_keys = []
        # tier-promoted blocks still consume FRESH device blocks (the
        # upload targets a new allocation), so they count in need_new
        need_new = self._blocks_needed(req) - len(reuse)
        req._plan_cache = (
            self._prefix_epoch, keys, list(reuse), need_new, list(tier_keys),
        )
        return reuse, need_new

    def _paged_fits(self, req: GenRequest) -> bool:
        # kv_alloc_fail injection (docs/RESILIENCE.md): an armed fault
        # opens a backpressure window (expiring by its armed duration) —
        # admission behaves exactly as if the pool were exhausted
        # (head-of-line defer, queue growth, deadline sheds), which is
        # the graceful handling under test. Scheduler-thread-only state;
        # the registry check is internally locked.
        spec = self._faults.check("kv_alloc_fail")
        if spec is not None:
            self._kv_fault_until = time.time() + max(spec.duration, 0.0)
        if self._kv_fault_until and time.time() < self._kv_fault_until:
            return False
        reuse, need_new = self._paged_plan(req)
        reused_retained = sum(1 for b in reuse if self._block_rc.get(b, 0) == 0)
        available = (
            len(self._free_blocks) + len(self._retained_lru) - reused_retained
        )
        return need_new <= available

    def _paged_alloc(self) -> int:
        """One fresh block: free list first, then evict the least-recently
        retained shared block (dropping its content-key registration —
        demoted to the host-RAM tier first when one is configured)."""
        self.stats["kv_blocks_allocated"] += 1
        if self._free_blocks:
            return self._free_blocks.pop()
        bid, _ = self._retained_lru.popitem(last=False)  # oldest
        self.stats["kv_retained_evictions"] += 1  # LRU churn (kv_thrash)
        key = self._block_hash.pop(bid, None)
        depth = self._block_depth.pop(bid, 0)
        if key is not None:
            self._hash_block.pop(key, None)
            self._prefix_epoch += 1  # index changed: cached plans expire
            if self._tier_cap_bytes and not self._tier_disabled:
                self._tier_demote(bid, key, depth)
        self._block_rc.pop(bid, None)
        return bid

    def _tier_block_bytes(self) -> int:
        """Host bytes one demoted block occupies (the per-block slice of
        every cache leaf — int8 caches demote their scales alongside)."""
        return sum(
            int(leaf.nbytes) // leaf.shape[1]
            # aval metadata only (nbytes/shape are static across the
            # dispatch swaps the lock orders; the dict reference read is
            # atomic), never the buffer contents
            for leaf in self._cache.values()  # kvmini: lock-ok
        )

    def _tier_demote(self, bid: int, key: bytes, depth: int) -> None:
        """Catch an eviction in the host-RAM tier: copy the block's KV to
        host (bounded by kv_host_tier_bytes — oldest tier entries make
        room, a tier too small for even one block stays empty) and file
        it under its content key for promotion at a future admission.
        Scheduler-thread-only; the device fetch synchronizes, which is
        exactly the price the capacity knob exists to bound."""
        blob_bytes = self._tier_block_bytes()
        if blob_bytes > self._tier_cap_bytes:
            return
        while self._tier_bytes + blob_bytes > self._tier_cap_bytes:
            _, old = self._tier.popitem(last=False)  # oldest demotion
            self._tier_bytes -= old["bytes"]
            self._prefix_epoch += 1
        self._tier[key] = {
            "depth": depth,
            "bytes": blob_bytes,
            "kv": self._read_block_host(bid),
        }
        self._tier_bytes += blob_bytes
        self._prefix_epoch += 1  # tier keys now match: plans must replan
        self.stats["kv_tier_demotions"] += 1

    def _tier_thrash_tick(self) -> None:
        """Self-disabling thrash guard, run from the scheduler loop's
        gauge-republish cadence: when retained-eviction churn crosses the
        monitor's kv_thrash thresholds (>= 4.0 evictions/s over 3
        consecutive ~1 s windows — monitor/events.py's defaults, kept in
        lockstep so the chart marker and the tier agree on what churn
        means), demoting is just moving the thrash onto PCIe — the tier
        empties and disables for the rest of the run (sticky; the
        kv_tier_disabled gauge records it)."""
        if not self._tier_cap_bytes or self._tier_disabled:
            return
        now = time.time()
        t0, ev0 = self._tier_thrash_win
        if now - t0 < 1.0:
            return
        rate = (self.stats["kv_retained_evictions"] - ev0) / (now - t0)
        self._tier_thrash_win = (now, self.stats["kv_retained_evictions"])
        self._tier_thrash_hits = (
            self._tier_thrash_hits + 1 if rate >= 4.0 else 0
        )
        if self._tier_thrash_hits >= 3:
            self._tier_disabled = True
            if self._tier:
                self._tier.clear()
                self._tier_bytes = 0
                self._prefix_epoch += 1

    def _read_block_host(self, bid: int) -> dict[str, Any]:
        """One pool block's KV as host numpy leaves (block axis sliced
        out). Used by tier demotion and the migration exporter; stubbed
        by the JAX-free harness tests."""
        with self._cache_lock:
            out = {
                name: np.asarray(leaf[:, bid])
                for name, leaf in self._cache.items()
            }
        return out

    def _write_block_dev(self, bid: int, leaves: dict[str, Any]) -> None:
        """Install host KV leaves into pool block ``bid`` (the inverse of
        _read_block_host) with the cache donated — tier promotion and the
        migration importer both land through here."""
        fn = self._decode_fns.get("kv_block_write")
        if fn is None:
            from kserve_vllm_mini_tpu.models.llama import update_cache_slots

            @partial(jax.jit, donate_argnums=(0,))
            def kv_block_write(cache, sub, bid):
                return update_cache_slots(cache, sub, bid)

            fn = self._instrument(kv_block_write, "kv_block_write")
            self._decode_fns["kv_block_write"] = fn
        sub = {
            name: jnp.asarray(arr)[:, None] for name, arr in leaves.items()
        }
        with self._cache_lock:
            self._cache = fn(self._cache, sub, jnp.int32(bid))

    def _paged_register_keys(
        self, blks: list[int], keys: list[bytes]
    ) -> None:
        """Register content keys for ``blks`` (parallel lists; first
        registration wins — a key already mapped keeps its block) and
        record each block's chain depth for the migration exporter."""
        registered = False
        for i, key in enumerate(keys):
            if key not in self._hash_block and blks[i] not in self._block_hash:
                self._hash_block[key] = blks[i]
                self._block_hash[blks[i]] = key
                self._block_depth[blks[i]] = i + 1
                registered = True
        if registered:
            self._prefix_epoch += 1

    def _paged_admit_blocks(
        self, slot: int, req: GenRequest, register: bool = True
    ) -> int:
        """Reserve the request's blocks (caller checked fit): claim the
        cached prefix's shared blocks by reference, promote any host-tier
        continuation of the chain, allocate the rest, and point the
        slot's table row at them (scratch beyond). Registers the prompt's
        full blocks for future sharing — unless ``register=False`` (the
        disaggregated route: the lane fills the blocks ASYNCHRONOUSLY,
        so registering at admission would let a later admission reuse KV
        that does not exist yet; the consume side registers instead).
        Returns the reused token count (the prefill's start offset)."""
        prompt = req.prompt_tokens
        reuse, need_new = self._paged_plan(req)
        tier_keys: list[bytes] = list(req._plan_cache[4])
        # claim shared blocks FIRST: a 0->1 refcount leaves the retained
        # pool before eviction for the new allocations can touch it
        for bid in reuse:
            rc = self._block_rc.get(bid, 0)
            if rc == 0:
                self._retained_lru.pop(bid, None)
                self.stats["kv_share_reclaims"] += 1  # 0->1: left the pool
            self._block_rc[bid] = rc + 1
        new_blocks = [self._paged_alloc() for _ in range(need_new)]
        for bid in new_blocks:
            self._block_rc[bid] = 1
        # host-tier promotion: the plan's contiguous tier continuation
        # uploads into the first fresh blocks — positionally they ARE the
        # chain's next blocks, so the prefill can start past them. The
        # plan is epoch-memoized, but an eviction between plan and admit
        # (the _paged_alloc above can clear the tier under cap pressure)
        # must degrade to prefilling those positions, never to attending
        # a hole — re-check membership per key and stop at the first gap.
        promoted = 0
        for i, key in enumerate(tier_keys):
            entry = self._tier.pop(key, None) if not self._tier_disabled else None
            if entry is None:
                break
            self._tier_bytes -= entry["bytes"]
            self._prefix_epoch += 1
            self._write_block_dev(new_blocks[i], entry["kv"])
            promoted += 1
        if promoted:
            self.stats["kv_tier_promotions"] += promoted
            self.stats["kv_tier_hits"] += 1
        blks = reuse + new_blocks
        self._slot_blocks[slot] = blks
        row = np.full((self._maxb,), self._scratch_block, dtype=np.int32)
        row[: len(blks)] = blks
        self._block_table[slot] = row
        self._table_dev = None
        if self.ecfg.prefix_cache and register:
            # register this prompt's full blocks (content exists once the
            # synchronous prefill below runs; admissions are serialized on
            # the scheduler thread, so no reader can arrive earlier). The
            # key list comes from the memoized plan — no third hash pass.
            self._paged_register_keys(blks, req._plan_cache[1])
        reused_len = (len(reuse) + promoted) * self._blk
        if self.ecfg.prefix_cache:
            # a lookup only happened if block reuse was attempted at all —
            # counting otherwise would pin cache_hit_ratio to a hard 0
            # instead of letting the TTFT probe fall through
            self.stats["prefix_lookups"] += 1
        if reused_len:
            self.stats["prefix_hits"] += 1
            self.stats["prefix_tokens_reused"] += reused_len
            self._hit_depths.append(reused_len)
        return reused_len

    def _paged_release(self, slot: int) -> None:
        """Drop the slot's block ownerships and park its row on the scratch
        block, so the sweep's all-slots dispatch can never write a stale
        position into a block that was handed to another request. Shared
        blocks whose refcount reaches zero go to the retained pool (still
        content-addressed, evictable); unregistered blocks free outright.

        Before releasing, full blocks covering GENERATED tokens register
        too (prompt blocks registered at admission): KV at position p
        depends only on tokens <= p, so a multi-turn follow-up whose
        prompt replays the transcript (old prompt + emitted tokens + new
        turn) hits the whole previous conversation — the paged analog of
        the dense APC retaining generated tokens. Only blocks with
        (i+1)*BLK <= slot_len qualify: the fused sweep's surplus writes
        land at positions >= slot_len, which is always past the last full
        block's end."""
        if self.ecfg.prefix_cache and self._slot_blocks[slot]:
            tokens = self._slot_tokens[slot][: self._slot_len[slot]]
            n_full = len(tokens) // self._blk
            if n_full:
                self._paged_register_keys(
                    self._slot_blocks[slot][:n_full],
                    self._prefix_keys(tokens, n_full),
                )
        self._paged_release_blocks(self._slot_blocks[slot])
        self._slot_blocks[slot] = []
        self._block_table[slot] = self._scratch_block
        self._table_dev = None

    def _paged_release_blocks(self, blks: list[int]) -> None:
        """Drop one ownership reference per block in ``blks`` — the
        shared tail of _paged_release and the orphaned-handoff release
        (a paged-v2 slot aborted mid-lane frees its blocks only when the
        lane's payload lands, so in-flight lane writes can never hit a
        reallocated block). Reversed: the chain's LEAF blocks enter the
        LRU first (oldest end), so eviction takes leaves before roots —
        evicting a root first would orphan every still-retained
        descendant (plans match prefixes root-outward and stop at the
        first miss)."""
        for bid in reversed(blks):
            rc = self._block_rc.get(bid, 1) - 1
            if rc > 0:
                self._block_rc[bid] = rc
                continue
            if bid in self._block_hash:
                self._block_rc[bid] = 0
                self._retained_lru[bid] = None  # most-recent end
            else:
                self._block_rc.pop(bid, None)
                self._block_depth.pop(bid, None)
                self._free_blocks.append(bid)

    # -- cross-replica KV migration (docs/FLEET.md; POST /kv/export|import)

    def _wire_encode_block(self, leaves: dict[str, Any]) -> dict[str, Any]:
        """One block's host leaves -> the JSON wire format: int8 values
        with f32 per-row wire scales for unquantized k/v (int8-KV on the
        wire regardless of the resident dtype — migration is a warmup
        transfer, the same accuracy trade --kv-cache-dtype int8 makes),
        verbatim bytes for already-int8 leaves and scale leaves."""
        import base64

        wire: dict[str, Any] = {}
        for name, arr in leaves.items():
            a = np.asarray(arr)
            if name in ("k", "v") and a.dtype != np.int8:
                f = a.astype(np.float32)
                amax = np.max(np.abs(f), axis=-1)
                scale = np.where(amax > 0.0, amax / 127.0, 1.0).astype(
                    np.float32
                )
                q = np.clip(
                    np.round(f / scale[..., None]), -127, 127
                ).astype(np.int8)
                wire[name] = {
                    "b64": base64.b64encode(q.tobytes()).decode(),
                    "dtype": "int8",
                    "shape": list(q.shape),
                    "wire_scale_b64": base64.b64encode(
                        scale.tobytes()
                    ).decode(),
                }
            else:
                wire[name] = {
                    "b64": base64.b64encode(a.tobytes()).decode(),
                    "dtype": str(a.dtype),
                    "shape": list(a.shape),
                }
        return wire

    def _wire_decode_block(self, wire: dict[str, Any]) -> dict[str, Any]:
        """Inverse of _wire_encode_block, validated against THIS engine's
        cache geometry — a donor with different layer/head/block shapes
        must fail loudly, never scatter-write garbage."""
        import base64

        leaves: dict[str, Any] = {}
        for name, leaf in self._cache.items():
            spec = wire.get(name)
            if spec is None:
                raise ValueError(f"kv wire payload missing leaf {name!r}")
            want = (leaf.shape[0],) + tuple(leaf.shape[2:])
            if tuple(spec["shape"]) != want:
                raise ValueError(
                    f"kv wire leaf {name!r} shape {spec['shape']} does "
                    f"not match this engine's block shape {list(want)}"
                )
            raw = np.frombuffer(
                base64.b64decode(spec["b64"]), dtype=np.dtype(spec["dtype"])
            ).reshape(spec["shape"])
            if "wire_scale_b64" in spec:
                scale = np.frombuffer(
                    base64.b64decode(spec["wire_scale_b64"]), np.float32
                ).reshape(spec["shape"][:-1])
                raw = (raw.astype(np.float32) * scale[..., None]).astype(
                    np.asarray(leaf[:1, :1]).dtype
                )
            leaves[name] = raw
        return leaves

    def kv_export(self, budget_bytes: int) -> dict[str, Any]:
        """Bounded wire snapshot of this engine's registered (shareable)
        blocks, root-first by chain depth so budget truncation drops
        LEAVES (a shipped leaf without its roots could never match —
        plans walk root-outward and stop at the first miss). Thread-safe:
        the pool walk and device reads run on the scheduler thread via
        _run_admin. Raises on dense engines — the caller (POST
        /kv/export) turns that into a 400."""
        if not self.paged:
            raise ValueError("kv_export requires kv_layout=paged")
        out: dict[str, Any] = {
            "block_size": self._blk,
            "blocks": [],
            "bytes": 0,
            "truncated": False,
        }

        def _collect() -> None:
            budget = max(int(budget_bytes), 0)
            spent = 0
            cands = sorted(
                self._block_hash.items(),
                key=lambda item: self._block_depth.get(item[0], 0),
            )
            for bid, key in cands:
                wire = self._wire_encode_block(self._read_block_host(bid))
                nbytes = sum(
                    len(spec["b64"]) * 3 // 4
                    + len(spec.get("wire_scale_b64", "")) * 3 // 4
                    for spec in wire.values()
                )
                if spent + nbytes > budget:
                    out["truncated"] = True
                    break
                spent += nbytes
                out["blocks"].append({
                    "key": key.hex(),
                    "depth": self._block_depth.get(bid, 0),
                    "kv": wire,
                })
            out["bytes"] = spent
            self.stats["kv_export_blocks"] += len(out["blocks"])

        err = self._run_admin(_collect, timeout_s=30.0)
        if err is not None:
            raise RuntimeError(f"kv_export failed: {err}")
        return out

    def kv_import(self, payload: dict[str, Any]) -> dict[str, Any]:
        """Install a sibling's kv_export payload: each block takes a
        FREE pool block (never evicts — warming must not thrash the
        target's own cache), uploads through the block-write executable,
        and registers as a retained (rc=0, evictable) prefix block.
        Already-known keys skip; a dry free list stops the import early.
        Runs on the scheduler thread via _run_admin."""
        if not self.paged:
            raise ValueError("kv_import requires kv_layout=paged")
        if int(payload.get("block_size", -1)) != self._blk:
            raise ValueError(
                f"kv_import block_size {payload.get('block_size')} does "
                f"not match this engine's kv_block_size {self._blk}"
            )
        res = {"imported": 0, "skipped": 0, "bytes": 0, "exhausted": False}

        def _install() -> None:
            registered = False
            for entry in payload.get("blocks", []):
                key = bytes.fromhex(entry["key"])
                if key in self._hash_block:
                    res["skipped"] += 1
                    continue
                if not self._free_blocks:
                    res["exhausted"] = True
                    break
                leaves = self._wire_decode_block(entry["kv"])
                bid = self._free_blocks.pop()
                self.stats["kv_blocks_allocated"] += 1
                self._write_block_dev(bid, leaves)
                self._hash_block[key] = bid
                self._block_hash[bid] = key
                self._block_depth[bid] = int(entry.get("depth", 0))
                self._block_rc[bid] = 0
                self._retained_lru[bid] = None
                registered = True
                res["imported"] += 1
                res["bytes"] += sum(
                    len(spec["b64"]) * 3 // 4
                    + len(spec.get("wire_scale_b64", "")) * 3 // 4
                    for spec in entry["kv"].values()
                )
            if registered:
                self._prefix_epoch += 1
            self.stats["kv_migrated_blocks"] += res["imported"]
            self.stats["kv_migrated_bytes"] += res["bytes"]

        err = self._run_admin(_install, timeout_s=60.0)
        if err is not None:
            raise RuntimeError(f"kv_import failed: {err}")
        return res

    def _table(self) -> jnp.ndarray:
        """Device mirror of the block table, rebuilt only when allocation
        changed — never on the per-token hot path."""
        if self._table_dev is None:
            self._table_dev = jnp.asarray(self._block_table)
        return self._table_dev

    def _adapter_ids(self) -> jnp.ndarray:
        """Device mirror of per-slot adapter indices (multi-LoRA), rebuilt
        only when the slot population changes."""
        if self._adapter_ids_dev is None:
            self._adapter_ids_dev = jnp.asarray(self._slot_adapter, jnp.int32)
        return self._adapter_ids_dev

    # -- live adapter management (vLLM dynamic-LoRA analog) ----------------

    def _run_admin(self, fn, timeout_s: float = 60.0) -> Optional[str]:
        """Execute ``fn`` on the scheduler thread (between sweeps) and
        return its error string, or None on success. Direct call when the
        scheduler isn't running (build-time / tests) — or when the caller
        IS the scheduler thread (an op enqueued from the thread that
        drains the queue would deadlock waiting on itself)."""
        if not self._running or threading.current_thread() is self._thread:
            op = _AdminOp(fn)
            op.run()
            return op.error
        op = _AdminOp(fn)
        self._admin.put(op)
        # stop() can flip _running and drain the queue BETWEEN the check
        # above and the put — the op would then sit in a dead queue and
        # hang its caller for the full timeout. Re-check and self-drain:
        # with the scheduler gone, nothing else will. Only ops still IN
        # the queue are failed — an op absent from the queue was dequeued
        # by the scheduler (it either ran or is running right now), so its
        # own done/error must be awaited below, not overwritten with a
        # fabricated failure for work that actually applied.
        if not self._running and not op.done.is_set():
            while True:
                try:
                    q_op = self._admin.get_nowait()
                except queue.Empty:
                    break
                q_op.error = "engine stopped"
                q_op.done.set()
        if not op.done.wait(timeout=timeout_s):
            return f"admin op timed out after {timeout_s:.0f}s"
        return op.error

    def load_adapter(self, name: str, adapter: dict[str, Any]) -> Optional[str]:
        """Install a LoRA adapter under ``name`` without restarting the
        engine. ``adapter`` is the ops/lora.py install format (target ->
        (A [L, in, r], B [L, r, out]), B pre-scaled). On an engine started
        without a bank, the first load creates a zero bank with
        ``ecfg.lora_slots`` capacity and that adapter's rank/targets; the
        bank's shapes are then fixed (capacity/rank growth = restart).
        Returns an error string, or None on success."""

        def _apply():
            from kserve_vllm_mini_tpu.ops.lora import (
                grow_bank_rank,
                install_adapter,
                pad_adapter_rank,
                zero_lora_bank,
            )

            if self._drafter_params is not None or self.ecfg.prefix_cache:
                raise ValueError(
                    "multi-LoRA excludes drafters and prefix_cache"
                )
            _require_tp_only_mesh(self.mesh)
            # TRANSACTIONAL: every mutation lands on a local bank and
            # self._lora is only reassigned after install_adapter succeeds
            # — a rank/target mismatch raising mid-update must leave the
            # old adapter's weights serving, not a zeroed slot that is
            # still routable by name
            cur = self._lora
            if cur is None:
                rank = next(iter(adapter.values()))[0].shape[-1]
                cur = zero_lora_bank(
                    self.cfg, self.ecfg.lora_slots, rank,
                    targets=sorted(adapter), dtype=self.cfg.jnp_dtype,
                )
                cur["names"] = {}
            names = cur["names"]
            if name in names:
                idx = names[name]
                why = self._adapter_in_use(idx, name)
                if why:
                    raise ValueError(
                        f"{why}; updating its weights mid-stream would "
                        "corrupt them"
                    )
            else:
                capacity = next(iter(cur["layers"].values())).shape[1] - 1
                used = set(names.values())
                free = [i for i in range(1, capacity + 1) if i not in used]
                if not free:
                    raise ValueError(
                        f"adapter bank is full ({capacity} slots, "
                        f"{sorted(names)}); unload one or restart with a "
                        "larger bank (lora_slots / --lora-slots)"
                    )
                idx = free[0]
            # rank flexibility without a restart: a higher-rank adapter
            # grows the whole bank (zero-padding preserves installed
            # deltas exactly; the next decode dispatch retraces once), a
            # lower-rank adapter pads itself up to the bank
            in_rank = max(a.shape[-1] for a, _b in adapter.values())
            if in_rank > cur["rank"]:
                cur = grow_bank_rank(cur, in_rank)
            padded = pad_adapter_rank(adapter, cur["rank"])
            # zero the index first: the incoming adapter may cover FEWER
            # targets than the index's previous occupant, and install only
            # writes the targets it has — leftovers would silently blend
            # two fine-tunes
            bank = self._zero_bank_index(cur, idx)
            bank = install_adapter(bank, idx, padded)
            if self.mesh is not None:
                # same replication as the preset-bank init path: the delta
                # joins the tp-sharded base projections however each
                # target is partitioned. Eager .at[].set updates preserve
                # sharding, but the freshly-built bank (first load) and
                # the host-side adapter arrays do not — normalize here.
                from jax.sharding import NamedSharding, PartitionSpec

                rep = NamedSharding(self.mesh, PartitionSpec())
                bank = {
                    **bank,
                    "layers": jax.device_put(bank["layers"], rep),
                }
            bank["names"] = dict(names, **{name: idx})
            self._lora = bank
            self._lora_names = bank["names"]

        return self._run_admin(_apply)

    @staticmethod
    def _zero_bank_index(bank: dict[str, Any], idx: int) -> dict[str, Any]:
        layers = {
            k: v.at[:, idx].set(0) for k, v in bank["layers"].items()
        }
        return {**bank, "layers": layers}

    def _adapter_in_use(self, idx: int, name: str) -> Optional[str]:
        """Why adapter ``idx`` can't be replaced/removed right now, or
        None. Checks live slots AND queued work — a pending request whose
        adapter vanishes before admission would otherwise be silently
        served by the base model."""
        if any(
            self._slot_adapter[i] == idx
            for i in range(self.ecfg.max_slots)
            if self._slot_req[i] is not None
        ):
            return f"adapter {name!r} is serving active requests"
        with self._pending.mutex:
            queued = any(
                h.request.adapter == name for h in self._pending.queue
            )
        if queued or (
            self.paged
            and self._deferred is not None
            and self._deferred.request.adapter == name
        ):
            return f"adapter {name!r} has queued requests waiting for it"
        return None

    def unload_adapter(self, name: str) -> Optional[str]:
        """Remove ``name`` from the registry, freeing its bank slot for a
        future load. Refused while any active request uses it. Returns an
        error string, or None on success."""

        def _apply():
            if self._lora is None or name not in self._lora["names"]:
                raise ValueError(
                    f"unknown adapter {name!r}; loaded: "
                    f"{sorted(self._lora['names']) if self._lora else []}"
                )
            idx = self._lora["names"][name]
            why = self._adapter_in_use(idx, name)
            if why:
                raise ValueError(why)
            names = dict(self._lora["names"])
            del names[name]
            self._lora["names"] = names
            self._lora_names = names

        return self._run_admin(_apply)

    # -- compiled steps ----------------------------------------------------

    def _bucket(self, n: int) -> int:
        b = self.ecfg.min_prefill_bucket
        while b < n:
            b *= 2
        return min(b, self.ecfg.max_prefill_len)

    def _instrument(self, fn, label: str):
        """Route a compiled step through the compile-stats wrapper
        (docs/PROFILING.md). Meshes stay on the plain jit path: an AOT
        executable requires pre-placed arguments, while jit transparently
        reshards — the sharded engines keep that behavior."""
        if not self.ecfg.compile_stats or self.mesh is not None:
            return fn
        return InstrumentedJit(fn, self._compile_recorder, label=label)

    def _get_prefill_fn(self, bucket: int, draft: bool = False):
        key = (bucket, draft)
        if key in self._prefill_fns:
            return self._prefill_fns[key]
        cfg = self._drafter_cfg if draft else self.cfg
        fwd = forward if draft else self._fwd

        @partial(jax.jit, donate_argnums=(1,), static_argnums=())
        def prefill(params, cache, tokens, length, slot, lora=None, ids=None):
            # tokens: [1, bucket]; length: scalar; slot: scalar; lora/ids:
            # multi-LoRA bank + [1] adapter index (None = base path)
            from kserve_vllm_mini_tpu.models.llama import (
                slice_cache_slots,
                update_cache_slots,
            )

            pos = jnp.arange(tokens.shape[1], dtype=jnp.int32)[None]
            sub = slice_cache_slots(cache, slot)
            # logit_index: only the prompt's last position is sampled — a
            # full [1, bucket, V] f32 logits tensor is ~2 GB at 128k vocab
            # for the server-default 4096 bucket, on the per-request path
            kw = {"lora": lora, "lora_ids": ids} if lora is not None else {}
            logits, new_sub = fwd(
                params, cfg, tokens, pos,
                sub, jnp.zeros((1,), jnp.int32),
                fresh_prefill=True,
                logit_index=(length - 1)[None],
                **kw,
            )
            return update_cache_slots(cache, new_sub, slot), logits[0, 0]  # [V] f32

        prefill = self._instrument(prefill, f"prefill[{bucket}]"
                                   + (".draft" if draft else ""))
        self._prefill_fns[key] = prefill
        return prefill

    def _get_chunk_prefill_fn(self, bucket: int, draft: bool = False):
        """Continuation-chunk prefill: writes this chunk's KV at ``offset``
        inside the slot and attends the whole cache with positional masking
        (exact for chunked prefill — llama.py forward's cached path). The
        flash fresh-prefill fn handles chunk 0; this handles the rest, so
        prompts longer than max_prefill_len no longer truncate."""
        key = ("chunk", bucket, draft)
        if key in self._prefill_fns:
            return self._prefill_fns[key]
        cfg = self._drafter_cfg if draft else self.cfg
        fwd = forward if draft else self._fwd

        @partial(jax.jit, donate_argnums=(1,))
        def chunk_prefill(params, cache, tokens, length, slot, offset,
                          lora=None, ids=None):
            # tokens: [1, bucket]; length = valid tokens in this chunk;
            # offset = absolute position of the chunk's first token
            from kserve_vllm_mini_tpu.models.llama import (
                slice_cache_slots,
                update_cache_slots,
            )

            pos = offset + jnp.arange(tokens.shape[1], dtype=jnp.int32)[None]
            sub = slice_cache_slots(cache, slot)
            kw = {"lora": lora, "lora_ids": ids} if lora is not None else {}
            logits, new_sub = fwd(
                params, cfg, tokens, pos,
                sub, offset[None],
                logit_index=(length - 1)[None],
                **kw,
            )
            return update_cache_slots(cache, new_sub, slot), logits[0, 0]

        chunk_prefill = self._instrument(
            chunk_prefill, f"chunk_prefill[{bucket}]"
            + (".draft" if draft else ""))
        self._prefill_fns[key] = chunk_prefill
        return chunk_prefill

    def _get_paged_prefill_fn(self, bucket: int):
        """Paged fresh prefill: no slot slicing — the pool is global and the
        slot's table row [1, MAXB] routes the writes to its blocks."""
        key = ("paged", bucket)
        if key in self._prefill_fns:
            return self._prefill_fns[key]
        cfg = self.cfg
        fwd = self._fwd
        kernel_ok = self.mesh is None  # a 1-token chunk is a decode shape

        @partial(jax.jit, donate_argnums=(1,))
        def prefill(params, cache, tokens, length, trow, lora=None, ids=None):
            pos = jnp.arange(tokens.shape[1], dtype=jnp.int32)[None]
            kw = {"lora": lora, "lora_ids": ids} if lora is not None else {}
            logits, nc = fwd(
                params, cfg, tokens, pos,
                cache, jnp.zeros((1,), jnp.int32),
                fresh_prefill=True,
                logit_index=(length - 1)[None],
                block_table=trow,
                paged_kernel_ok=kernel_ok,
                **kw,
            )
            return nc, logits[0, 0]

        prefill = self._instrument(prefill, f"paged_prefill[{bucket}]")
        self._prefill_fns[key] = prefill
        return prefill

    def _get_paged_chunk_prefill_fn(self, bucket: int):
        key = ("paged-chunk", bucket)
        if key in self._prefill_fns:
            return self._prefill_fns[key]
        cfg = self.cfg
        fwd = self._fwd
        kernel_ok = self.mesh is None  # a 1-token chunk is a decode shape

        @partial(jax.jit, donate_argnums=(1,))
        def chunk_prefill(params, cache, tokens, length, offset, trow,
                          lora=None, ids=None):
            pos = offset + jnp.arange(tokens.shape[1], dtype=jnp.int32)[None]
            kw = {"lora": lora, "lora_ids": ids} if lora is not None else {}
            logits, nc = fwd(
                params, cfg, tokens, pos,
                cache, offset[None],
                logit_index=(length - 1)[None],
                block_table=trow,
                paged_kernel_ok=kernel_ok,
                **kw,
            )
            return nc, logits[0, 0]

        chunk_prefill = self._instrument(
            chunk_prefill, f"paged_chunk_prefill[{bucket}]")
        self._prefill_fns[key] = chunk_prefill
        return chunk_prefill

    def _get_decode_fn(self, n_steps: int = 1):
        """Compiled decode of ``n_steps`` sampling steps in ONE dispatch.

        Variants are cached per n_steps. The scan carries (cache, tokens,
        lengths, rng) and stacks the sampled tokens [n_steps, S]; host state
        is the source of truth between dispatches, so a request finishing
        mid-chunk just has its surplus tokens discarded on the host. Their
        KV writes stay inside the slot's own buffer at positions >= the
        retained/valid length, where the positional attention mask (key j
        attends iff j <= query position) makes them unreachable — with
        prefix caching a later admission may SKIP re-prefilling those rows,
        so the mask, not overwrite-on-admission, is the safety invariant."""
        key = ("paged", n_steps) if self.paged else n_steps
        fn = self._decode_fns.get(key)
        if fn is not None:
            return fn
        cfg = self.cfg
        fwd = self._fwd
        paged = self.paged
        kernel_ok = self.mesh is None  # GSPMD-sharded pools use the gather

        @partial(jax.jit, donate_argnums=(1, 8))
        def decode(params, cache, tokens, lengths, temps, topks, topps, rng,
                   counts, pres, freqs, table=None, lora=None, ids=None):
            def body(carry, _):
                c, toks, lens, r, cnt = carry
                r, sub = jax.random.split(r)
                kw = {}
                if paged:
                    kw["block_table"] = table
                    kw["paged_kernel_ok"] = kernel_ok
                if lora is not None:
                    kw["lora"], kw["lora_ids"] = lora, ids
                logits, nc = fwd(
                    params, cfg, toks[:, None], lens[:, None], c, lens, **kw,
                )
                lg = apply_penalties(logits[:, 0, :], cnt, pres, freqs)
                nxt = sample_tokens(lg, sub, temps, topks, topps)
                lp, tids, tlps = token_logprobs(lg, nxt)
                # counts update INSIDE the scan: the next fused step's
                # penalty must see this step's emission
                return (nc, nxt, lens + 1, r, count_tokens(cnt, nxt)), \
                    (nxt, lp, tids, tlps)

            (c, toks, _, _, cnt), ys = jax.lax.scan(
                body, (cache, tokens, lengths, rng, counts), None,
                length=n_steps,
            )
            # toks is the final carry: the last sampled token per slot,
            # returned ON DEVICE so the next dispatch can feed it without a
            # host round-trip (the double-buffered pipeline's token path)
            return c, cnt, toks, ys  # ys: ([n,S], [n,S], [n,S,K], [n,S,K])

        decode = self._instrument(decode, f"decode[chunk={n_steps}]"
                                  + (".paged" if paged else ""))
        self._decode_fns[key] = decode
        return decode

    def _get_masked_decode_fn(self):
        """Single-step decode with grammar masks: [S, V] bool token masks
        for constrained slots (True = allowed). Logprobs come from the
        MASKED logits — the true sampling distribution under the
        constraint. One step per dispatch because the next mask depends on
        the token just emitted (the automaton is host-side; only the mask
        application rides the device)."""
        key = ("paged", "masked") if self.paged else "masked"
        fn = self._decode_fns.get(key)
        if fn is not None:
            return fn
        cfg = self.cfg
        fwd = self._fwd
        paged = self.paged
        kernel_ok = self.mesh is None

        @partial(jax.jit, donate_argnums=(1, 8))
        def decode_masked(params, cache, tokens, lengths,
                          temps, topks, topps, rng, counts, pres, freqs,
                          packed_mask, use_mask,
                          table=None, lora=None, ids=None):
            kw = {}
            if paged:
                kw["block_table"] = table
                kw["paged_kernel_ok"] = kernel_ok
            if lora is not None:
                kw["lora"], kw["lora_ids"] = lora, ids
            logits, nc = fwd(
                params, cfg, tokens[:, None], lengths[:, None], cache, lengths,
                **kw,
            )
            lg = apply_penalties(logits[:, 0, :], counts, pres, freqs)
            mask = _unpack_mask(packed_mask, cfg.vocab_size)
            lg_masked = jnp.where(mask, lg, -jnp.inf)
            lg = jnp.where(use_mask[:, None], lg_masked, lg)
            nxt = sample_tokens(lg, rng, temps, topks, topps)
            lp, tids, tlps = token_logprobs(lg, nxt)
            # nxt doubles as the on-device token carry (same contract as
            # the plain decode fn), so a constrained sweep keeps the device
            # token buffer warm for the sweeps that follow it
            return nc, count_tokens(counts, nxt), nxt, \
                (nxt[None], lp[None], tids[None], tlps[None])

        decode_masked = self._instrument(
            decode_masked, "decode.masked" + (".paged" if paged else ""))
        self._decode_fns[key] = decode_masked
        return decode_masked

    def _get_spec_fn(self):
        # the rejection-sampling variant serves greedy AND sampled slots in
        # one executable: temperature-0 rows degenerate exactly to the
        # greedy accept rule (see build_spec_step_sampled), so greedy
        # output stays bit-identical to plain decode
        if self._spec_fn is None:
            self._spec_fn = self._instrument(
                build_spec_step_sampled(
                    self.cfg, self._drafter_cfg, self.ecfg.spec_tokens
                ),
                f"spec[k={self.ecfg.spec_tokens}]",
            )
        return self._spec_fn

    # -- public API --------------------------------------------------------

    def submit(self, req: GenRequest) -> RequestHandle:
        # prompts longer than one prefill bucket run as CHUNKED prefill
        # (_admit_one), so the only hard cap is the slot's KV window itself
        # (one position must remain for decode). Only past that does the
        # tail-keeping truncation — still flagged end-to-end — apply.
        prompt_cap = self.ecfg.max_seq_len - 1
        if len(req.prompt_tokens) > prompt_cap:
            req.truncated = True
            req.truncated_tokens = len(req.prompt_tokens) - prompt_cap
            req.prompt_tokens = req.prompt_tokens[-prompt_cap:]
        handle = RequestHandle(req)
        if req.constraint is not None and not hasattr(req.constraint, "token_mask"):
            # raw byte automaton -> ByteTokenizer token mapping
            from kserve_vllm_mini_tpu.runtime.token_grammar import ByteTokenMachine

            req.constraint = ByteTokenMachine(req.constraint, self.cfg.vocab_size)
        if req.constraint is not None:
            # the grammar must be closable inside BOTH the token budget and
            # the slot's remaining KV window — otherwise format compliance
            # is impossible and the request must fail up front, not emit
            # truncated pseudo-JSON
            budget = min(
                req.max_new_tokens,
                self.ecfg.max_seq_len - 1 - len(req.prompt_tokens),
            )
            need = req.constraint.min_close()
            if budget < need:
                handle.events.put(("done", {
                    "finish_reason": "error",
                    "error": (
                        f"constrained format needs >= {need} tokens but only "
                        f"{budget} fit (max_tokens / cache window)"
                    ),
                }))
                return handle
        # Registry lookup from the submitting thread; admin ops swap the
        # whole dict reference atomically, so the worst case is a
        # just-loaded adapter 404ing for one request.
        # kvmini: thread-ok — atomic reference swap, benign stale read
        if req.adapter is not None and req.adapter not in self._lora_names:
            handle.events.put(("done", {
                "finish_reason": "error",
                "error": (
                    f"unknown adapter {req.adapter!r}; available: "
                    f"{sorted(self._lora_names) or '(none loaded)'}"
                ),
            }))
            return handle
        if self.paged and self._blocks_needed(req) > self._scratch_block:
            # can NEVER fit the pool (scratch_block == total user blocks) —
            # failing now beats deadlocking the admission queue forever
            handle.events.put(("done", {
                "finish_reason": "error",
                "error": (
                    f"request needs {self._blocks_needed(req)} KV blocks "
                    f"but the pool has {self._scratch_block}; raise "
                    "kv_pool_blocks or lower max_tokens"
                ),
            }))
            return handle
        if self.tracer is not None and req.trace_id is None:
            # no client trace context: mint one so the request still shows
            # in /traces (it just won't join a client-side trace)
            req.trace_id = rt_tracing.new_trace_id()
        self._pending.put(handle)
        # Gauge write from the submitting thread while the scheduler owns
        # every other stats key; dict setitem is GIL-atomic and the
        # scheduler recomputes this key each iteration anyway.
        # kvmini: thread-ok — GIL-atomic gauge write, scheduler refreshes
        self.stats["queue_depth"] = self._queue_depth()
        return handle

    def _queue_depth(self) -> int:
        """Requests waiting for admission: the pending queue PLUS the
        paged-backpressure head-of-line handle (_deferred), which sits in
        neither _pending nor a slot — without it, reported depth is one
        low whenever paged backpressure is active."""
        n = self._pending.qsize()
        # Racy read of the scheduler-owned deferred handle from the stats
        # path; depth is a monitoring gauge and the `is not None` check is
        # atomic under the GIL.
        # kvmini: thread-ok — monitoring gauge, GIL-atomic None check
        if self.paged and self._deferred is not None:
            n += 1
        return n

    # -- request lifecycle tracing (docs/TRACING.md) -----------------------

    def _trace_span(
        self,
        handle: RequestHandle,
        name: str,
        t0: float,
        t1: float,
        ok: bool = True,
        attrs: Optional[dict[str, Any]] = None,
    ) -> None:
        """Record one completed per-request phase span. All phase spans
        parent DIRECTLY under the client's http.request span
        (parent_span_id from the traceparent header), so the joined trace
        reads client http.request -> server queue/prefill/decode. At most
        tracing.MAX_REQUEST_SPANS of these per request — the recorder-
        overhead contract."""
        req = handle.request
        # trace context is deliberately host-local telemetry: followers see
        # None here and return before recording (kvmini: protocol-ok)
        if self.tracer is None or req.trace_id is None:
            return
        a = {"request_id": req.request_id}
        if attrs:
            a.update(attrs)
        self.tracer.record(
            name, req.trace_id, int(t0 * 1e9), int(t1 * 1e9),
            # host-local span parentage, same None-gate (kvmini: protocol-ok)
            parent_span_id=req.parent_span_id, ok=ok, attrs=a,
        )

    def _trace_engine_span(
        self, name: str, t0: float, t1: float,
        attrs: Optional[dict[str, Any]] = None,
    ) -> None:
        """Engine-lane span (dispatch->retire windows): not tied to one
        request, recorded under the engine's own trace id into the
        engine-lane ring (never competes with request spans for slots)."""
        if self._engine_tracer is None:
            return
        self._engine_tracer.record(
            name, self._engine_trace_id, int(t0 * 1e9), int(t1 * 1e9),
            attrs=attrs,
        )

    def _observe_phase(self, phase: str, seconds: float) -> None:
        self._phase_hist[phase].observe(seconds)

    def snapshot_phase_hist(self) -> dict[str, Any]:
        """Per-phase histogram snapshots for /metrics
        (kvmini_tpu_phase_seconds) and tests."""
        return {p: h.snapshot() for p, h in self._phase_hist.items()}

    def traces_otlp(self) -> dict[str, Any]:
        """One OTLP doc for GET /traces: the request-span ring plus the
        engine-lane ring as a second scopeSpans entry (same resource).
        droppedSpans sums both rings' evictions."""
        doc = self.tracer.to_otlp()
        if self._engine_tracer is not None and len(self._engine_tracer):
            eng_doc = self._engine_tracer.to_otlp()
            eng_scope = eng_doc["resourceSpans"][0]["scopeSpans"][0]
            eng_scope["scope"] = {"name": rt_tracing.SERVER_SCOPE + ".engine"}
            doc["resourceSpans"][0]["scopeSpans"].append(eng_scope)
            doc["droppedSpans"] += eng_doc["droppedSpans"]
        return doc

    def start(self) -> None:
        if self._running:
            return
        self._running = True
        if self._disagg is not None:
            self._disagg.start()
        self._thread = threading.Thread(target=self._loop, daemon=True, name="engine-loop")
        self._thread.start()
        if self.ecfg.watchdog:
            self._watch_stop.clear()
            self._watch_thread = threading.Thread(
                target=self._watchdog_loop, daemon=True, name="engine-watchdog"
            )
            self._watch_thread.start()

    def stop(self) -> None:
        started = self._thread is not None
        self._running = False
        self._watch_stop.set()
        if self._thread:
            self._thread.join(timeout=10.0)
        if self._disagg is not None:
            # after the scheduler drained (mid-handoff slots got their
            # terminal events there); the lane flushes any leftover jobs
            # as tombstones on its own way out
            self._disagg.stop()
        if self._watch_thread is not None:
            self._watch_thread.join(timeout=2.0)
        # an admin op enqueued around shutdown would otherwise hang its
        # caller for the full wait timeout
        while True:
            try:
                op = self._admin.get_nowait()
            except queue.Empty:
                break
            op.error = "engine stopped"
            op.done.set()
        # Graceful drain (docs/RESILIENCE.md, the shutdown contract):
        # the scheduler thread itself drains slots/blocks as its LAST
        # act before exiting (_loop -> _drain_requests), so slot state
        # keeps its single-writer owner. Here we only cover the
        # never-started engine: requests queued against it must still
        # get their terminal event rather than hang a client forever
        # (the pending queue is thread-safe; no slot state exists yet).
        if not started:
            while True:
                try:
                    h = self._pending.get_nowait()
                except queue.Empty:
                    break
                h.events.put(("done", {
                    "finish_reason": "cancelled", "tokens_out": 0,
                }))

    # -- scheduler loop ----------------------------------------------------

    def _constraint_mask(self, machine, budget: int) -> np.ndarray:
        """Bit-packed [ceil(vocab/8)] uint8 mask (bit set = token allowed)
        from the token-protocol machine, padded/cut to the MODEL's logit
        width. Packed because it rides host->device every constrained
        step; the jitted steps unpack on device (_unpack_mask)."""
        m = machine.token_mask(budget)
        V = self.cfg.vocab_size
        if m.shape[0] != V:
            out = np.zeros((V,), dtype=bool)
            out[: min(m.shape[0], V)] = m[:V]
            m = out
        return np.packbits(m, bitorder="little")

    def _get_first_fn(self):
        """Jitted first-token sampler over the prefill's last-position
        logits: mask application + sampling + logprobs in one dispatch."""
        fn = self._decode_fns.get("first")
        if fn is not None:
            return fn

        cfg = self.cfg

        @jax.jit
        def first(last_logits, rng, temp, topk, topp, packed_mask, use_mask):
            lg = last_logits[None, :]
            mask = _unpack_mask(packed_mask, cfg.vocab_size)
            lg_masked = jnp.where(mask[None], lg, -jnp.inf)
            lg = jnp.where(use_mask, lg_masked, lg)
            tok = sample_tokens(lg, rng, temp[None], topk[None], topp[None])
            lp, tids, tlps = token_logprobs(lg, tok)
            return tok[0], lp[0], tids[0], tlps[0]

        self._decode_fns["first"] = first
        return first

    def _get_reset_counts_fn(self):
        """Jitted admission-time reset of one slot's penalty-count row:
        zero it, then record the first generated token."""
        fn = self._decode_fns.get("reset_counts")
        if fn is not None:
            return fn

        @partial(jax.jit, donate_argnums=(0,))
        def reset(counts, slot, tok):
            row = jnp.zeros((counts.shape[1],), counts.dtype).at[tok].add(1)
            return jax.lax.dynamic_update_index_in_dim(counts, row, slot, 0)

        self._decode_fns["reset_counts"] = reset
        return reset

    def _pop_slot_for(self, prompt: list[int]) -> tuple[int, int]:
        """(slot, reused_prefix_len): with prefix caching on, prefer the
        free slot whose retained tokens share the longest prefix with the
        new prompt (capped at len(prompt)-1 — at least one position must
        run so the last-token logits exist); otherwise plain pop().

        Matches below min_prefill_bucket don't count: reusing k tokens
        moves the remaining n-k off the flash fresh-prefill path onto the
        positional-masked chunk path, so a trivial match (a shared chat-
        template first byte) would make prefill SLOWER while reporting a
        cache hit. Comparison is slice-equality (C speed) with a bisect on
        mismatch, not a per-token Python loop — this runs on the scheduler
        thread."""
        if (
            not self.ecfg.prefix_cache
            or self.paged  # paged reuse is BLOCK-level (_paged_admit_blocks)
            or self._drafter_params is not None
            or not self._free
        ):
            return self._free.pop(), 0
        target = prompt[:-1]
        # default victim = the OLDEST-freed slot (front of the list): a
        # no-match admission must evict the least-recently-retained prefix,
        # not the newest one (pop() from the tail would wipe the freshest
        # cache entry on every miss)
        best_i, best_k = 0, 0
        for i, s in enumerate(self._free):
            retained = self._retained[s]
            limit = min(len(retained), len(target))
            if limit <= best_k:
                continue  # cannot beat the current best
            if retained[:limit] == target[:limit]:
                k = limit
            else:
                lo, hi = 0, limit - 1  # [:lo] matches; [:limit] doesn't
                while lo < hi:
                    mid = (lo + hi + 1) // 2
                    if retained[:mid] == target[:mid]:
                        lo = mid
                    else:
                        hi = mid - 1
                k = lo
            if k > best_k:
                best_i, best_k = i, k
                if best_k == len(target):
                    break  # perfect match
        # floor: absolute (one full bucket) AND relative (a quarter of the
        # prompt) — a shared 20-token chat header on a 500-token prompt
        # must not move the other 480 tokens off the flash prefill path
        floor = max(self.ecfg.min_prefill_bucket, len(target) // 4)
        if best_k < floor:
            best_k = 0
            best_i = 0  # LRU victim (see above)
        slot = self._free.pop(best_i)
        # accounting contract shared with the block-level path
        # (_paged_admit_blocks): exactly one lookup per admission, a hit
        # iff reused tokens > 0, and prefix_tokens_reused grows by the
        # EXACT reused token count — pinned by the cross-path regression
        # test (tests/test_kv_observability.py)
        self.stats["prefix_lookups"] += 1
        if best_k > 0:
            self.stats["prefix_hits"] += 1
            self.stats["prefix_tokens_reused"] += best_k
            self._hit_depths.append(best_k)
        return slot, best_k

    def _prefill_piece(self, piece: list[int], slot: int, off: int,
                       draft: bool = False, adapter_idx: int = 0):
        """ONE compiled prefill dispatch: ``piece`` written at absolute
        offset ``off`` of the slot's cache — offset 0 on the flash
        fresh-prefill path, continuation pieces on the positional-masked
        chunk path (int8-KV caches stream through the cached-prefill
        kernel on TPU, ops/flash_attention.py). Returns the piece's
        last-position logits [V] f32. ``adapter_idx`` picks the request's
        LoRA adapter (0 = base) when the engine carries a bank."""
        params = self._drafter_params if draft else self.params
        m = len(piece)
        bucket = self._bucket(m)
        toks = piece + [self.pad_id] * (bucket - m)
        tokens = jnp.asarray(toks, dtype=jnp.int32)[None]
        lkw = {}
        if self._lora is not None and not draft:
            lkw = {
                "lora": self._lora["layers"],
                "ids": jnp.asarray([adapter_idx], jnp.int32),
            }
        # the read-dispatch-assign below must be atomic against the v2
        # prefill lane's own cache mutations (_lane_paged_prefill) —
        # dispatch is async, so the critical section stays tiny
        with self._cache_lock:
            cache_in = self._dcache if draft else self._cache
            if self.paged:
                trow = jnp.asarray(self._block_table[slot : slot + 1])
                if off == 0:
                    fn = self._get_paged_prefill_fn(bucket)
                    cache, last_logits = fn(
                        params, cache_in, tokens, jnp.int32(m), trow, **lkw
                    )
                else:
                    fn = self._get_paged_chunk_prefill_fn(bucket)
                    cache, last_logits = fn(
                        params, cache_in, tokens,
                        jnp.int32(m), jnp.int32(off), trow, **lkw,
                    )
            elif off == 0:
                fn = self._get_prefill_fn(bucket, draft=draft)
                cache, last_logits = fn(
                    params, cache_in, tokens, jnp.int32(m), jnp.int32(slot),
                    **lkw,
                )
            else:
                fn = self._get_chunk_prefill_fn(bucket, draft=draft)
                cache, last_logits = fn(
                    params, cache_in, tokens,
                    jnp.int32(m), jnp.int32(slot), jnp.int32(off), **lkw,
                )
            if draft:
                self._dcache = cache
            else:
                self._cache = cache
        return last_logits

    def _lane_paged_prefill(self, handle: RequestHandle, meta: dict):
        """HANDOFF_VERSION=2 lane prefill — runs ON the prefill-lane
        thread. Writes the prompt suffix straight into the shared-pool
        blocks the scheduler reserved at routing (meta["row"]), through
        the SAME compiled paged executables and piece schedule the
        colocated path uses, so greedy streams stay byte-identical and
        the handoff carries a block table instead of KV bytes. Each
        read-dispatch-assign of self._cache serializes against the
        scheduler via _cache_lock; actual device execution orders by
        buffer dependencies on the single stream. Returns
        (last_logits, chunks) — the lane wraps them into the handoff."""
        prompt = handle.request.prompt_tokens
        row_dev = jnp.asarray(meta["row"][None])
        pos = int(meta["off"])
        budget = self.ecfg.max_prefill_len
        chunks = 0
        last_logits = None
        while pos < len(prompt):
            piece = prompt[pos : pos + budget]
            m = len(piece)
            bucket = self._bucket(m)
            toks = piece + [self.pad_id] * (bucket - m)
            tokens = jnp.asarray(toks, dtype=jnp.int32)[None]
            with self._cache_lock:
                if pos == 0:
                    fn = self._get_paged_prefill_fn(bucket)
                    self._cache, last_logits = fn(
                        self.params, self._cache, tokens, jnp.int32(m),
                        row_dev,
                    )
                else:
                    fn = self._get_paged_chunk_prefill_fn(bucket)
                    self._cache, last_logits = fn(
                        self.params, self._cache, tokens,
                        jnp.int32(m), jnp.int32(pos), row_dev,
                    )
            chunks += 1
            pos += m
        # the lane must not report the handoff complete until the block
        # writes landed: consume swaps the row in with zero copies, so
        # this sync IS the v2 payload barrier  # kvmini: sync-ok
        jax.block_until_ready(last_logits)
        return last_logits, chunks

    def _prefill_step(self, slot: int, st: dict, budget: int) -> bool:
        """Advance one prefill piece for ``st`` (the per-slot chunked-
        prefill state): target pieces first, then — once the target cache
        holds the whole prompt — the drafter's shadow pieces. Blocks until
        the dispatch completes so the stall accounting is honest wall
        time, and counts the piece into ``prefill_chunks`` (and into
        ``prefill_chunk_stall_s`` when decode work was live — the decode
        tail this piece's execution stood in front of). Returns True when
        every piece (target and draft) has run."""
        handle = st["handle"]
        prompt = handle.request.prompt_tokens
        n = len(prompt)
        draft = st["off"] >= n
        off = st["draft_off"] if draft else st["off"]
        piece = prompt[off : off + budget]
        t0 = time.time()
        last_logits = self._prefill_piece(
            piece, slot, off, draft=draft, adapter_idx=st["adapter_idx"]
        )
        jax.block_until_ready(last_logits)
        wall = time.time() - t0
        self.stats["busy_s"] += wall
        self.stats["prefill_chunks"] += 1
        if self._inflight or self._decode_active():
            self.stats["prefill_chunk_stall_s"] += wall
        if draft:
            st["draft_off"] = off + len(piece)
            st["draft_chunks"] += 1
        else:
            st["off"] = off + len(piece)
            st["chunks"] += 1
            st["logits"] = last_logits
            if self._slot_prefill[slot] is not None:
                # interleaved mode: advance the frontier so concurrent
                # sweeps' garbage writes stay >= it (see _slot_prefill)
                self._slot_len[slot] = st["off"]
        return st["off"] >= n and (
            st["draft_off"] is None or st["draft_off"] >= n
        )

    def _advance_prefills(self, on_decision=None) -> None:
        """Advance the OLDEST in-progress chunked prefill by ONE piece
        this scheduler iteration, so decode sweeps interleave with a long
        prompt instead of stalling behind it (EngineConfig.prefill_chunk).
        When the final piece lands, the slot is activated — sampled and
        joined to the decode set — via _activate_slot. Head-of-line only:
        FIFO completion order matches the monolithic path's serial
        admissions."""
        if not self._prefill_fifo:
            return
        slot = self._prefill_fifo[0]
        st = self._slot_prefill[slot]
        if st is None or st["handle"].cancelled is not None:
            # cancelled mid-prefill: the cancel pass in _schedule_once
            # aborts it (and pops the fifo) — nothing to advance here
            return
        if on_decision is not None:
            # never reached in lockstep (chunked admission is gated off
            # there), published for the decision-stream convention — no
            # follower replay arm needed (kvmini: protocol-ok)
            on_decision(("prefill_chunk", st["handle"].request.request_id))
        if self._prefill_step(slot, st, self.ecfg.prefill_chunk):
            self._prefill_fifo.pop(0)
            self._slot_prefill[slot] = None
            if self._inflight:
                # the slot joins the decode active set: in-flight sweeps
                # were dispatched under the old set, and the global
                # _pending_steps would misplace its first decode write —
                # retire against settled state (the admission invariant)
                self.stats["pipeline_fallback_active_set"] += 1
                self._retire_all(on_decision)
            self._activate_slot(slot, st)

    # -- disaggregated prefill: handoff consumption (docs/DISAGGREGATION.md)

    def _get_inject_fn(self):
        """Jitted KV-handoff injection: write the staged stripe back at
        the destination slot (``update_cache_slots``, the exact inverse
        of the lane's staging slice) with the decode cache donated so XLA
        updates it in place. One executable for every handoff."""
        fn = self._decode_fns.get("inject")
        if fn is not None:
            return fn
        from kserve_vllm_mini_tpu.models.llama import update_cache_slots

        @partial(jax.jit, donate_argnums=(0,))
        def inject(cache, sub, slot):
            return update_cache_slots(cache, sub, slot)

        inject = self._instrument(inject, "disagg_inject")
        self._decode_fns["inject"] = inject
        return inject

    def _consume_handoffs(self, on_decision=None) -> None:
        """Drain finished prefill-lane handoffs between sweeps: inject
        each staged stripe into its slot's cache region and activate the
        slot under the existing admission invariant (in-flight sweeps
        retire first — a newly active slot must never receive a stale
        token from a sweep dispatched before it joined). Tombstones and
        version mismatches degrade to colocated prefill; a routed slot
        whose handoff never arrives at all hits the HANDOFF_TIMEOUT_S
        last resort — no path leaves a client hanging."""
        if self._disagg is None:
            return
        from kserve_vllm_mini_tpu.runtime.disagg import (
            DENSE_HANDOFF_VERSION,
            DROPS_TO_DEGRADE,
            HANDOFF_TIMEOUT_S,
            HANDOFF_VERSION,
        )

        # version negotiation (docs/DISAGGREGATION.md): each layout
        # speaks exactly one payload format — paged consumes v2 block
        # tables, dense consumes v1 staged stripes. Anything else walks
        # the same degrade ladder as a drop.
        expected = HANDOFF_VERSION if self.paged else DENSE_HANDOFF_VERSION
        while True:
            ho = self._disagg.pop_ready()
            if ho is None:
                break
            slot = next(
                (i for i in range(self.ecfg.max_slots)
                 if self._slot_handoff[i] is not None
                 and self._slot_handoff[i]["handle"] is ho.handle),
                None,
            )
            if slot is None:
                # the slot was aborted (cancel/drain/fault recovery)
                # before the handoff landed: the payload is an orphan.
                # Paged orphans also return their quarantined blocks to
                # the pool — only now is it certain no lane write to
                # them is still in flight.
                self.stats["prefill_lane_busy_s"] += ho.busy_s
                self._reap_orphans(ho.handle)
                continue
            handle: RequestHandle = ho.handle
            if ho.dropped or ho.version != expected:
                # lost/injected-drop/stale-protocol handoff: count it,
                # climb the degrade ladder, and re-prefill colocated —
                # the request completes either way
                self.stats["kv_handoff_drops"] += 1
                self.stats["prefill_lane_busy_s"] += ho.busy_s
                self._disagg_drop_run += 1
                if self._disagg_drop_run >= DROPS_TO_DEGRADE:
                    self._disagg_degraded = True
                self._colocated_fallback(slot, on_decision)
                self._reap_orphans(handle)
                continue
            self._disagg_drop_run = 0
            if handle.cancelled is not None:
                # cancelled after the lane finished: the compute still
                # happened — account it before dropping the payload
                self.stats["prefill_lane_busy_s"] += ho.busy_s
                self._abort_handoff(slot, handle.cancelled)
                self._reap_orphans(handle)
                continue
            if self._inflight:
                # activation joins the decode set — retire against
                # settled state (the admission invariant)
                self.stats["pipeline_fallback_active_set"] += 1
                self._retire_all(on_decision)
            t_route = self._slot_handoff[slot]["t_route"]
            now = time.time()
            wait = max(now - ho.t_enqueued, 0.0)
            self.stats["kv_handoffs"] += 1
            self.stats["kv_handoff_blocks"] += ho.n_blocks
            self.stats["kv_handoff_wait_s"] += wait
            self.stats["prefill_lane_busy_s"] += ho.busy_s
            self.stats["prefill_chunks"] += ho.chunks
            hstate = self._slot_handoff[slot]
            if self.paged:
                # v2 block-table handoff: the lane already wrote the KV
                # into this slot's pool blocks — install the table row
                # the route parked on scratch and register the prompt's
                # content keys now that the blocks hold real KV. ZERO
                # bytes of cache move here.
                self._block_table[slot] = hstate["row"]
                self._table_dev = None
                if self.ecfg.prefix_cache and hstate["keys"]:
                    self._paged_register_keys(
                        self._slot_blocks[slot][: len(hstate["keys"])],
                        hstate["keys"],
                    )
            else:
                # v1 dense staged stripe: one device-side inject copy,
                # measured so the A/B against v2 is a stats read
                self.stats["kv_handoff_bytes_copied"] += sum(
                    int(leaf.nbytes) for leaf in ho.kv.values()
                )
                with self._cache_lock:
                    self._cache = self._get_inject_fn()(
                        self._cache, ho.kv, jnp.int32(slot)
                    )
            self._observe_phase("handoff", now - t_route)
            self._trace_span(
                handle, "server.handoff", t_route, now,
                attrs={"blocks": ho.n_blocks, "version": ho.version,
                       "wait_s": round(wait, 6),
                       "lane_busy_s": round(ho.busy_s, 6)},
            )
            self._slot_handoff[slot] = None
            st = {
                "handle": handle,
                "off": len(handle.request.prompt_tokens),
                "reused": ho.reused_prefix_tokens,
                "adapter_idx": 0,
                "chunks": ho.chunks,
                "draft_chunks": 0,
                "draft_off": None,
                "logits": jnp.asarray(ho.logits),
            }
            self._activate_slot(slot, st)
        # never-hang last resort: a routed slot whose handoff (payload OR
        # tombstone) never arrived — lane wedged past even its own flush
        # machinery — re-prefills colocated after the timeout. Never
        # reached in lockstep: multihost refuses disaggregated engines
        # outright (check_multihost_engine), so _disagg is None there
        # and this method early-returns before any clock read.
        now = time.time()
        for slot in range(self.ecfg.max_slots):
            hstate = self._slot_handoff[slot]
            # kvmini: lockstep-ok — see above (disagg is host-local only)
            if hstate is None or now - hstate["t_route"] <= HANDOFF_TIMEOUT_S:
                continue
            self.stats["kv_handoff_drops"] += 1
            self._disagg_drop_run += 1
            if self._disagg_drop_run >= DROPS_TO_DEGRADE:
                self._disagg_degraded = True
            self._colocated_fallback(slot, on_decision)

    def _reap_orphans(self, handle: RequestHandle) -> None:
        """Return a handle's quarantined v2 blocks to the pool once its
        lane work is provably finished — the payload (or tombstone) has
        arrived, so no lane write to them can still be in flight. No-op
        for dense engines and handles with nothing quarantined."""
        if not self.paged:
            return
        blks = self._orphan_blocks.pop(id(handle), None)
        if blks:
            self._paged_release_blocks(blks)

    def _colocated_fallback(self, slot: int, on_decision=None) -> None:
        """Degrade-to-colocated (the handoff ladder's recovery step): the
        routed prompt's handoff was lost, so its prefill runs right here
        on the scheduler thread — the monolithic piece loop the colocated
        engine would have used — and the slot activates normally. The
        request never observes the drop beyond added latency."""
        hstate = self._slot_handoff[slot]
        handle: RequestHandle = hstate["handle"]
        if handle.cancelled is not None:
            self._abort_handoff(slot, handle.cancelled)
            return
        self._slot_handoff[slot] = None
        self.stats["disagg_colocated_fallbacks"] += 1
        if self._inflight:
            self.stats["pipeline_fallback_active_set"] += 1
            self._retire_all(on_decision)
        off = 0
        if self.paged:
            # the v2 route already allocated this slot's blocks and
            # parked the table row on scratch: re-install the row and
            # re-prefill from the reused frontier right here. If a
            # wedged (not dead) lane is still writing the same blocks,
            # both writers produce identical bytes from identical
            # inputs through the same executables — benign overlap.
            self._block_table[slot] = hstate["row"]
            self._table_dev = None
            off = hstate.get("reused", 0)
        st = {
            "handle": handle,
            "off": off,
            "reused": off,
            "adapter_idx": 0,
            "chunks": 0,
            "draft_chunks": 0,
            "draft_off": None,
            "logits": None,
        }
        while not self._prefill_step(slot, st, self.ecfg.max_prefill_len):
            pass
        if self.paged and self.ecfg.prefix_cache and hstate.get("keys"):
            # the route deferred key registration to consume; the
            # fallback prefill just wrote the real KV, so register here
            self._paged_register_keys(
                self._slot_blocks[slot][: len(hstate["keys"])],
                hstate["keys"],
            )
        self._activate_slot(slot, st)

    def _abort_handoff(self, slot: int, reason: str) -> None:
        """Finish a slot cancelled (or drained) while its prompt was on
        the prefill lane: no token was ever sampled, the stream ends with
        zero tokens, and the slot frees. The lane's payload, when it
        lands, is dropped as an orphan by the consume identity check."""
        handle = self._slot_req[slot]
        if self.paged and self._slot_blocks[slot]:
            # v2: the lane may still have writes in flight against this
            # slot's blocks — quarantine them out of the free pool until
            # the lane's payload (or tombstone) orphans at consume, else
            # a reallocation could race the lane's stores
            self._orphan_blocks[id(handle)] = self._slot_blocks[slot]
            self._slot_blocks[slot] = []
        handle.t_done = time.time()
        handle.finish_reason = reason
        self._observe_phase("prefill", handle.t_done - handle.t_admit)
        self._trace_span(
            handle, "server.prefill", handle.t_admit, handle.t_done,
            ok=False, attrs={"cancelled": reason, "disagg": True},
        )
        handle.events.put(("done", {
            "finish_reason": reason,
            "tokens_out": 0,
            "truncated": handle.request.truncated,
            "truncated_tokens": handle.request.truncated_tokens,
        }))
        self.stats["requests_completed"] += 1
        self._release_slot(slot)

    def cancel(self, handle: RequestHandle, reason: str = "stop") -> None:
        """Finish ``handle``'s generation early (thread-safe; effective at
        the scheduler's next iteration). Tokens already emitted stand; the
        'done' event carries ``reason``. A still-queued handle is finished
        at admission instead of prefilling; a handle mid-chunked-prefill
        is aborted at the scheduler's next iteration (_abort_prefill)."""
        handle.cancelled = reason

    def _admit_one(self, handle: RequestHandle) -> None:
        req = handle.request
        if handle.cancelled is not None:
            # cancelled while queued: report done without spending a
            # prefill (no tokens were produced)
            handle.t_done = time.time()
            handle.finish_reason = handle.cancelled
            self._observe_phase("queue", handle.t_done - handle.t_submit)
            self._trace_span(
                handle, "server.queue", handle.t_submit, handle.t_done,
                ok=False, attrs={"cancelled": handle.cancelled},
            )
            handle.events.put(("done", {
                "finish_reason": handle.cancelled,
                "tokens_out": 0,
            }))
            return
        # Deadline shed (docs/RESILIENCE.md). Lockstep-DISABLED: followers
        # replay this method and a wall-clock branch would diverge their
        # slot state from the primary's; multihost deadline sheds need a
        # published decision (v2).
        deadline_expired = (
            req.deadline_s is not None
            and not self._lockstep
            and time.time() - handle.t_submit > req.deadline_s
        )
        if deadline_expired:
            # deadline expired while queued: shed WITHOUT spending a
            # prefill (docs/RESILIENCE.md) — the client's budget is gone
            # either way, and burning decode steps on it would push
            # every queued neighbor past its own deadline too
            handle.t_done = time.time()
            handle.finish_reason = "shed"
            self._observe_phase("queue", handle.t_done - handle.t_submit)
            self._trace_span(
                handle, "server.queue", handle.t_submit, handle.t_done,
                ok=False, attrs={"shed": "deadline expired in queue"},
            )
            with self._res_lock:
                self._requests_shed += 1
            handle.events.put(("done", {
                "finish_reason": "shed",
                "tokens_out": 0,
                "error": (
                    f"deadline {req.deadline_s:.3f}s expired after "
                    f"{handle.t_done - handle.t_submit:.3f}s in queue"
                ),
            }))
            return
        handle.t_admit = time.time()
        # queue phase: submit -> the scheduler picking the request up
        self._observe_phase("queue", handle.t_admit - handle.t_submit)
        self._trace_span(handle, "server.queue", handle.t_submit, handle.t_admit)
        if (
            self._disagg is not None
            and not self._disagg_degraded
            and not self._lockstep
            and len(req.prompt_tokens) >= self.ecfg.disagg_min_prompt
            and self._disagg.accepts()
        ):
            # disaggregated route (docs/DISAGGREGATION.md): occupy a slot
            # now (cancel/watchdog/drain all see the handle) and hand the
            # prompt to the prefill lane; _consume_handoffs injects the
            # staged KV and activates the slot when the handoff lands.
            # Backpressure/degrade fall through to the colocated path
            # below — a saturated or dead lane sheds work back to the
            # decode lane, it never queues requests unboundedly.
            slot = self._free.pop()
            self._slot_req[slot] = handle
            self._slot_len[slot] = 0
            meta = None
            if self.paged:
                # HANDOFF_VERSION=2 (docs/DISAGGREGATION.md): allocate
                # the slot's blocks from the SHARED pool right here on
                # the scheduler thread (prefix reuse + tier promotion
                # both settle now), but park the slot's table row on
                # scratch while the lane writes — decode sweeps dispatch
                # all S slots, and this len-0 slot's garbage writes must
                # land in scratch, not in blocks the lane is filling.
                # register=False: content keys are registered at consume,
                # after the KV actually exists — registering now would
                # let a later admission reuse blocks not yet written.
                reused = self._paged_admit_blocks(slot, req, register=False)
                blks = list(self._slot_blocks[slot])
                row = np.full((self._maxb,), self._scratch_block, np.int32)
                row[: len(blks)] = blks
                self._block_table[slot] = self._scratch_block
                self._table_dev = None
                meta = {
                    "row": row,
                    "off": reused,
                    "keys": list(req._plan_cache[1]),
                }
            self._slot_handoff[slot] = {
                "handle": handle, "t_route": handle.t_admit,
                "reused": (meta or {}).get("off", 0),
                "row": None if meta is None else meta["row"],
                "keys": None if meta is None else meta["keys"],
            }
            self._disagg.submit(handle, meta)
            return
        slot, reused = self._pop_slot_for(req.prompt_tokens)
        if self.paged:
            # fit is the caller's job: _schedule_once defers a non-fitting
            # head-of-line request before calling here, and the idle path
            # only runs with zero active slots, where the whole pool is
            # free and submit()'s never-fit rejection guarantees the fit.
            # _paged_alloc pops _free_blocks / evicts retained and would
            # fail loudly on a (multihost-divergence) violation.
            # Block-level prefix sharing may cover a prompt prefix; the
            # prefill below starts after it, exactly like the dense APC.
            reused = self._paged_admit_blocks(slot, req)
        adapter_idx = 0
        # multihost submit refuses adapter requests outright, so in lockstep
        # this branch is dead on both sides (kvmini: protocol-ok)
        if req.adapter is not None:
            if req.adapter not in self._lora_names:
                # the registry is also checked at submit and unload refuses
                # while requests are queued — but if the name still vanished
                # (defensive), failing beats silently serving the base model
                if self.paged:
                    self._paged_release(slot)
                self._free.append(slot)
                handle.events.put(("done", {
                    "finish_reason": "error",
                    "error": f"adapter {req.adapter!r} was unloaded before "
                             "this request could be admitted",
                }))
                return
            adapter_idx = self._lora_names[req.adapter]
        n = len(req.prompt_tokens)
        st = {
            "handle": handle,
            "off": reused,          # target-prefill frontier (next position)
            "reused": reused,
            "adapter_idx": adapter_idx,
            "chunks": 0,            # target pieces dispatched
            "draft_chunks": 0,      # drafter shadow pieces dispatched
            # None = no drafter shadow prefill; 0 = pending from offset 0
            # (the drafter cache never carries a reused prefix —
            # prefix_cache and drafters are mutually exclusive)
            "draft_off": (
                0 if self._drafter_params is not None
                and self.ecfg.spec_tokens > 0 else None
            ),
            "logits": None,         # last target piece's [V] f32 logits
        }
        chunk = self.ecfg.prefill_chunk
        if chunk is not None and not self._lockstep and n - reused > chunk:
            # interleaved chunked prefill: occupy the slot now, advance
            # one piece per scheduler iteration (_advance_prefills) so
            # decode sweeps ride between pieces; sampling happens when
            # the final piece lands (_activate_slot)
            self._slot_req[slot] = handle
            self._slot_len[slot] = reused  # prefill frontier (see init)
            if self.ecfg.prefix_cache and not self.paged:
                # rows past the reused prefix are being overwritten with
                # THIS prompt's KV: the old occupant's retained match
                # must not outlive its rows (an abort mid-prefill re-
                # retains the new prompt up to the frontier instead)
                self._retained[slot] = list(req.prompt_tokens[:reused])
            self._slot_prefill[slot] = st
            self._prefill_fifo.append(slot)
            return
        # monolithic admission: every piece back-to-back (budget =
        # max_prefill_len), then the drafter's shadow pieces, then sample
        while not self._prefill_step(slot, st, self.ecfg.max_prefill_len):
            pass
        self._activate_slot(slot, st)

    def _activate_slot(self, slot: int, st: dict) -> None:
        """Prefill is complete: sample the first token from the final
        piece's last-position logits and join the slot to the decode set.
        The shared tail of monolithic admission (_admit_one) and chunked-
        prefill completion (_advance_prefills). Callers settle the
        in-flight pipeline first: _schedule_once retires before admitting
        and _advance_prefills retires before activating."""
        handle: RequestHandle = st["handle"]
        req = handle.request
        n = len(req.prompt_tokens)
        reused = st["reused"]
        last_logits = st["logits"]
        t0 = time.time()
        # first token: sampled from the prompt's last-position logits,
        # grammar-masked when the request is constrained
        # multihost submit refuses constrained requests (req_payload has no
        # constraint field), dead on both sides there (kvmini: protocol-ok)
        machine = req.constraint
        if machine is not None:
            # budget = tokens the slot can actually emit: the grammar must
            # close before max_new_tokens AND before the KV window fills,
            # else out_of_space cuts the structure mid-emission
            budget = min(req.max_new_tokens, self.ecfg.max_seq_len - 1 - n)
            mask = self._constraint_mask(machine, budget)
        else:
            mask = np.zeros(((self.cfg.vocab_size + 7) // 8,), dtype=np.uint8)
        self._rng, sub = jax.random.split(self._rng)
        first_tok, first_lp, first_tids, first_tlps = self._get_first_fn()(
            last_logits, sub,
            jnp.float32(req.temperature),
            jnp.int32(req.top_k),
            jnp.float32(req.top_p),
            jnp.asarray(mask),
            jnp.bool_(machine is not None),
        )
        first_id = int(first_tok)
        self.stats["busy_s"] += time.time() - t0
        self.stats["prefills"] += 1
        # only tokens actually prefilled: reused prefix tokens are counted
        # in prefix_tokens_reused, not here (throughput math stays honest)
        self.stats["prefill_tokens"] += n - reused

        handle.t_first_token = time.time()
        # prefill phase: admission -> first sampled token (chunked prefill
        # and the drafter's shadow prefill included; for interleaved
        # chunking this span also contains the decode sweeps that rode
        # between pieces — the request's real TTFT anatomy)
        self._observe_phase("prefill", handle.t_first_token - handle.t_admit)
        self._trace_span(
            handle, "server.prefill", handle.t_admit, handle.t_first_token,
            attrs={"prompt_tokens": n, "reused_prefix_tokens": reused,
                   "slot": slot, "prefill_chunks": st["chunks"]},
        )
        handle.tokens.append(first_id)
        lp_info = None
        if req.logprobs:
            lp_info = (
                float(first_lp),
                list(zip(np.asarray(first_tids).tolist(),
                         np.asarray(first_tlps).tolist())),
            )
            handle.logprobs.append(lp_info)
        handle.events.put(("token", first_id, handle.t_first_token, lp_info))

        self._slot_req[slot] = handle
        self._slot_len[slot] = n
        self._slot_remaining[slot] = req.max_new_tokens - 1
        self._last_tokens[slot] = first_id
        self._tokens_dev = None  # host mutation: device token carry is stale
        self._slot_machine[slot] = machine
        self._slot_adapter[slot] = st["adapter_idx"]
        self._adapter_ids_dev = None
        # rows 0..n-1 now hold the prompt's KV; emitted tokens append as
        # their KV lands (fed on the next step)
        self._slot_tokens[slot] = list(req.prompt_tokens) + [first_id]
        self._retained[slot] = []
        self._sampling_arrays = None  # slot population changed
        # penalty state: clear the previous occupant's generated-token
        # counts and record the first token (it IS a generated token — the
        # next step's penalty must already see it)
        self._counts = self._get_reset_counts_fn()(
            self._counts, jnp.int32(slot), first_tok
        )
        if machine is not None:
            machine.advance_token(first_id)
            if machine.done:
                self._finish_slot(slot, "stop")
                return
        hit_eos = req.eos_id is not None and first_id == req.eos_id
        if self._slot_remaining[slot] <= 0 or hit_eos:
            self._finish_slot(slot, "stop" if hit_eos else "length")

    def _abort_prefill(self, slot: int, reason: str) -> None:
        """Finish a slot that was cancelled (or drained) MID-chunked-
        prefill: no token was ever sampled, so the whole occupancy is the
        prefill phase and the stream ends with zero tokens. With the
        dense APC on, the rows already written hold THIS prompt's KV up
        to the frontier — retain that (exact) prefix rather than the old
        occupant's overwritten one."""
        handle = self._slot_req[slot]
        st = self._slot_prefill[slot]
        handle.t_done = time.time()
        handle.finish_reason = reason
        self._observe_phase("prefill", handle.t_done - handle.t_admit)
        self._trace_span(
            handle, "server.prefill", handle.t_admit, handle.t_done,
            ok=False,
            attrs={"cancelled": reason,
                   "prefill_chunks": st["chunks"] if st else 0},
        )
        handle.events.put(("done", {
            "finish_reason": reason,
            "tokens_out": 0,
            "truncated": handle.request.truncated,
            "truncated_tokens": handle.request.truncated_tokens,
        }))
        self.stats["requests_completed"] += 1
        if self.ecfg.prefix_cache and not self.paged:
            self._retained[slot] = list(
                handle.request.prompt_tokens[: self._slot_len[slot]]
            )
        self._release_slot(slot)

    def _decode_active(self) -> list[int]:
        """Slots with a live request that is PAST prefill — the set decode
        sweeps cover. A slot mid-chunked-prefill (or awaiting its prefill
        lane handoff) is occupied but excluded until _activate_slot
        samples its first token."""
        return [
            i for i in range(self.ecfg.max_slots)
            if self._slot_req[i] is not None
            and self._slot_prefill[i] is None
            and self._slot_handoff[i] is None
        ]

    def _get_sampling_arrays(self) -> tuple:
        if self._sampling_arrays is None:
            S = self.ecfg.max_slots
            self._sampling_arrays = (
                jnp.asarray(
                    [self._slot_req[i].request.temperature if self._slot_req[i] else 0.0
                     for i in range(S)], jnp.float32),
                jnp.asarray(
                    [self._slot_req[i].request.top_k if self._slot_req[i] else 0
                     for i in range(S)], jnp.int32),
                jnp.asarray(
                    [self._slot_req[i].request.top_p if self._slot_req[i] else 1.0
                     for i in range(S)], jnp.float32),
                jnp.asarray(
                    [self._slot_req[i].request.presence_penalty
                     if self._slot_req[i] else 0.0
                     for i in range(S)], jnp.float32),
                jnp.asarray(
                    [self._slot_req[i].request.frequency_penalty
                     if self._slot_req[i] else 0.0
                     for i in range(S)], jnp.float32),
            )
        return self._sampling_arrays

    def _finish_slot(self, slot: int, reason: str) -> None:
        handle = self._slot_req[slot]
        if handle is not None:
            handle.t_done = time.time()
            handle.finish_reason = reason
            # decode phase: first token -> done (a first-token-only request
            # records a zero-length decode span — the phase still existed)
            self._observe_phase("decode", handle.t_done - handle.t_first_token)
            self._trace_span(
                handle, "server.decode", handle.t_first_token, handle.t_done,
                ok=reason in ("stop", "length"),
                attrs={"tokens_out": len(handle.tokens),
                       "finish_reason": reason},
            )
            if handle.cancelled is not None:
                # cancellation as its own zero-length marker span: the
                # joined trace shows WHEN the cancel landed, not just that
                # the decode span ended early
                self._trace_span(
                    handle, "server.cancel", handle.t_done, handle.t_done,
                    ok=False, attrs={"reason": handle.cancelled},
                )
            handle.events.put(("done", {
                "finish_reason": reason,
                "tokens_out": len(handle.tokens),
                "server_ttft_ms": handle.server_ttft_ms,
                "truncated": handle.request.truncated,
                "truncated_tokens": handle.request.truncated_tokens,
            }))
            self.stats["requests_completed"] += 1
            # admit->done service EMA: the admission estimate's denominator
            # (estimate_wait_s; docs/RESILIENCE.md deadline-aware shedding)
            if handle.t_admit:
                span = max(handle.t_done - handle.t_admit, 0.0)
                with self._res_lock:
                    self._service_ema_s = (
                        span if self._service_ema_s == 0.0
                        else 0.8 * self._service_ema_s + 0.2 * span
                    )
        if self.ecfg.prefix_cache and not self.paged:
            # dense slot-affinity APC: retain exactly the tokens whose KV
            # is WRITTEN (the last emitted token was never fed, so trim to
            # slot_len rows). Paged retention is block-level, inside
            # _paged_release.
            self._retained[slot] = self._slot_tokens[slot][: self._slot_len[slot]]
        self._release_slot(slot)

    def _release_slot(self, slot: int) -> None:
        """Slot-release bookkeeping shared by _finish_slot, engine-fault
        recovery, and the shutdown drain — ONE copy of the invariants so
        the rarely-exercised fault/drain paths can never drift from the
        normal finish path and leak a slot or block. Resets to the base
        adapter because the all-slots sweep still computes this slot's
        row, and a stale adapter id would gather a real adapter's factors
        for discarded garbage."""
        self._slot_req[slot] = None
        self._slot_machine[slot] = None
        if self._slot_prefill[slot] is not None:
            # releasing a slot mid-chunked-prefill (abort, fault recovery,
            # drain): drop the advancement state with it
            self._slot_prefill[slot] = None
            if slot in self._prefill_fifo:
                self._prefill_fifo.remove(slot)
        # releasing a slot mid-lane-handoff: the payload, when it lands,
        # is dropped by the consume identity check (orphan)
        self._slot_handoff[slot] = None
        if self.paged:
            self._paged_release(slot)
        self._slot_adapter[slot] = 0
        self._adapter_ids_dev = None
        self._free.append(slot)
        self._sampling_arrays = None  # slot population changed

    def _emit_token(self, slot: int, tok: int, now: float, lp_info=None) -> bool:
        """Record one generated token for a live slot: cache-length/stat
        bookkeeping, stream event, constraint-automaton advance, and finish
        handling (EOS / budget / cache space / grammar completion). Returns
        True if the slot finished. The single state machine both the plain
        and speculative sweeps share."""
        handle = self._slot_req[slot]
        req = handle.request
        self._slot_len[slot] += 1      # the fed token is now in cache
        self._last_tokens[slot] = tok
        self._slot_tokens[slot].append(tok)
        handle.tokens.append(tok)
        if lp_info is not None and req.logprobs:
            handle.logprobs.append(lp_info)
        handle.events.put(
            ("token", tok, now, lp_info if req.logprobs else None)
        )
        self.stats["decode_tokens"] += 1
        self._slot_remaining[slot] -= 1
        machine = self._slot_machine[slot]
        if machine is not None:
            machine.advance_token(tok)
            if machine.done:
                self._finish_slot(slot, "stop")
                return True
        hit_eos = req.eos_id is not None and tok == req.eos_id
        out_of_space = self._slot_len[slot] + 1 >= self.ecfg.max_seq_len
        if self._slot_remaining[slot] <= 0 or hit_eos or out_of_space:
            self._finish_slot(slot, "stop" if hit_eos else "length")
            return True
        return False

    def _spec_partition(self, active: list[int]) -> tuple[list[int], list[int]]:
        """Per-slot speculative gating: split the active slots into
        (spec, plain). Spec slots run the fused rejection-sampling round
        (build_spec_step_sampled) — greedy AND sampled requests both
        qualify: rejection sampling preserves sampled output
        distributions exactly, and temperature-0 rows degenerate to the
        exact argmax accept rule (bit-identical to plain greedy decode).
        Penalized slots (the fused round carries no count table),
        constrained slots (fresh mask per token), and logprob slots
        (per-token distributions the verify doesn't produce) go to the
        plain sweep. One ineligible request no longer silently degrades
        every speculating neighbor (VERDICT round-3 weak #2).

        Cache-room caveat: the fused spec kernels write k positions into
        EVERY slot's cache region — including plain and free slots, whose
        results are discarded. Writes at >= slot_len are overwritten before
        they can be attended (the padding invariant), but a slot within k
        of its cache end would have the write CLAMPED backwards onto real
        KV. So if ANY active slot lacks k of headroom, speculation skips
        this sweep entirely (transient — such a slot is about to finish)."""
        k = self.ecfg.spec_tokens
        if k <= 0 or self._drafter_params is None:
            return [], active
        if any(self._slot_len[i] + k >= self.ecfg.max_seq_len for i in active):
            return [], active
        spec = [i for i in active if self._spec_capable(i)]
        if not spec:
            return [], active
        rest = [i for i in active if i not in spec]
        return spec, rest

    def _spec_capable(self, i: int) -> bool:
        """STATIC per-request spec eligibility (fixed for a slot's whole
        occupancy — unlike _spec_partition's transient cache-headroom
        gate). Sampled requests speculate too (rejection sampling keeps
        their output distribution exact; greedy rows degenerate to the
        exact-match rule). Penalties need the per-step count table the
        fused round doesn't carry; constrained slots need a fresh mask
        per token; logprob slots need per-token distributions the verify
        doesn't produce; adapted slots can't speculate — the drafter
        proposes from base weights (defensive: lora+drafter is rejected
        at init)."""
        req = self._slot_req[i].request
        return (
            req.presence_penalty == 0.0
            and req.frequency_penalty == 0.0
            and self._slot_machine[i] is None
            and not req.logprobs
            and self._slot_adapter[i] == 0
        )

    def _spec_sweep(self, active: list[int]) -> None:
        """One fused speculative round: drafter proposes k-1 tokens, target
        verifies in a single T=k forward, host emits the accepted prefix plus
        the target's bonus token. Rejected positions leave garbage KV beyond
        the new length in both caches; it is overwritten before it can ever
        be attended (the same overwrite-before-attend invariant that covers
        prompt padding)."""
        k = self.ecfg.spec_tokens
        spec = self._get_spec_fn()
        tokens = jnp.asarray(self._last_tokens, dtype=jnp.int32)
        lengths = jnp.asarray(self._slot_len, dtype=jnp.int32)
        temps, topks, topps, _pres, _freqs = self._get_sampling_arrays()
        self._rng, sub = jax.random.split(self._rng)
        t0 = time.time()
        with self._cache_lock:
            self._cache, self._dcache, emit = spec(
                self.params, self._cache,
                self._drafter_params, self._dcache,
                tokens, lengths, temps, topks, topps, sub,
            )
        # one transfer for the whole [S, k] block (same rationale as decode)
        emit_host = np.asarray(jax.device_get(emit))
        now = time.time()
        self.stats["busy_s"] += now - t0
        self.stats["spec_rounds"] += 1
        self.stats["spec_proposed"] += (k - 1) * len(active)

        for i in active:
            n_emitted = 0
            for j in range(k):
                tok = int(emit_host[i, j])
                if tok < 0:
                    break
                n_emitted += 1
                if self._emit_token(i, tok, now):
                    break
            # accepted drafts = emitted minus the bonus token
            self.stats["spec_accepted"] += max(n_emitted - 1, 0)
        self._trace_engine_span(
            "engine.decode.window", t0, now,
            attrs={"chunk": k, "slots": len(active), "mode": "spec"},
        )
        self._observe_phase("emit", time.time() - now)
        # spec emission advanced _last_tokens host-side; the device carry
        # (if any) predates it, so the next plain dispatch must rebuild
        self._tokens_dev = None

    def _decode_sweep(self) -> None:
        """One SYNCHRONOUS dispatch->readback->emit sweep (the seed loop's
        shape). The pipelined steady state goes through _sweep_phase
        instead; this remains the fallback for spec partitions and
        grammar-constrained slots, and the follower replay target for the
        ('sweep',) decision."""
        active = self._decode_active()
        if not active:
            return
        spec_slots, plain_slots = self._spec_partition(active)
        if spec_slots:
            self._spec_sweep(spec_slots)
        if plain_slots:
            constrained = [
                i for i in plain_slots if self._slot_machine[i] is not None
            ]
            if constrained:
                self._masked_sweep(plain_slots, constrained)
            else:
                self._dispatch_plain(plain_slots)
                self._retire_one()

    # -- double-buffered decode pipeline (docs/DECODE_PIPELINE.md) ---------

    def _feed_tokens(self, active: list[int]) -> jnp.ndarray:
        """Last-sampled tokens for the next decode dispatch over ``active``.
        In steady state this is the previous sweep's ON-DEVICE carry, so no
        host->device transfer happens per sweep. The carry is only usable
        when every slot being fed emitted through the sweep that produced
        it (_tokens_dev_slots): a slot outside that set — a spec slot whose
        fused round was skipped this iteration, say — holds a discarded
        garbage row, and feeding it would corrupt that slot's context. Any
        other case (host mutation invalidated the carry, new slot in the
        mix) rebuilds from _last_tokens, which the emit path keeps
        authoritative for all S slots."""
        if (
            self._tokens_dev is not None
            and self._tokens_dev_slots.issuperset(active)
        ):
            return self._tokens_dev
        self._tokens_dev = jnp.asarray(self._last_tokens, dtype=jnp.int32)
        self._tokens_dev_slots = frozenset(range(self.ecfg.max_slots))
        return self._tokens_dev

    def _chunk_for(self, active: list[int]) -> int:
        """Fused-step count for the next plain dispatch: decode_chunk
        clamped into every active slot's REMAINING cache window (minus
        positions in-flight sweeps have already claimed), rounded down to
        a power of two so at most log2(decode_chunk)+1 scan variants ever
        compile. Requests finishing mid-chunk surplus-discard on the host
        — shrinking instead would recompile per remaining-budget value."""
        window = min(
            self.ecfg.max_seq_len - 1 - self._slot_len[i] for i in active
        ) - self._pending_steps
        chunk = max(1, min(self.ecfg.decode_chunk, window))
        return 1 << (chunk.bit_length() - 1)

    def _pipeline_eligible(self, active: list[int]) -> tuple[bool, Optional[str]]:
        """Whether the next sweep may be dispatched ahead (before the
        previous one retires). The fallback-to-synchronous conditions,
        each pinned by a test (tests/test_decode_pipeline.py):

        - ``constrained``: a grammar-masked slot's next mask depends on the
          byte just emitted — the host must see sweep N before building
          sweep N+1's operands.
        - ``spec``: speculative rounds interleave drafter/target dispatches
          and emit a data-dependent number of tokens per round; the plain
          dispatch-ahead carry doesn't model them.
        - ``headroom``: the dispatched-ahead sweep must stay inside every
          active slot's cache window AND use the same chunk size the
          synchronous loop would pick — otherwise sampled streams diverge
          (different scan length => different per-step rng folds) and
          clamped writes could back onto real KV. Requiring a full
          decode_chunk of window past the in-flight positions guarantees
          both.

        The fourth condition — ``active_set`` (admission/cancellation
        landing mid-flight) — is enforced by _schedule_once retiring all
        in-flight sweeps before mutating the slot population."""
        if not self.ecfg.decode_pipeline or not active:
            return False, None
        if any(self._slot_machine[i] is not None for i in active):
            return False, "constrained"
        # STATIC spec capability, deliberately NOT _spec_partition: the
        # partition's transient cache-headroom gate can flip spec back ON
        # when a near-window-end slot finishes, and a plain sweep already
        # dispatched ahead would then replace the spec round the
        # synchronous loop runs at that index (rejection sampling consumes
        # rng differently — sampled streams would diverge). A statically
        # capable slot therefore pins the engine synchronous for its
        # whole residency.
        if (
            self.ecfg.spec_tokens > 0
            and self._drafter_params is not None
            and any(self._spec_capable(i) for i in active)
        ):
            return False, "spec"
        full = 1 << (max(1, self.ecfg.decode_chunk).bit_length() - 1)
        window = min(
            self.ecfg.max_seq_len - 1 - self._slot_len[i] for i in active
        ) - self._pending_steps
        if window < full:
            return False, "headroom"
        return True, None

    def _dispatch_plain(self, active: list[int]) -> None:
        """Dispatch one plain decode sweep WITHOUT waiting for results.
        The dispatch covers all S slots (static shapes); slots outside
        ``active`` get harmless overwritten-before-attend KV writes and
        their sampled tokens are discarded at retire. The sampled-token
        carry stays on device as the next dispatch's feed; the stacked
        per-step outputs ride in _inflight until _retire_one() reads them
        back and emits."""
        if self._faults.check("device_error"):
            # dispatch-time device error (docs/RESILIENCE.md): raised as
            # DeviceFault so the loop runs the engine-fault RECOVERY
            # path (batch fails "engine_fault", engine degrades and
            # keeps serving) instead of the generic fail-everything
            # crash handler
            from kserve_vllm_mini_tpu.runtime.faults import DeviceFault

            raise DeviceFault("injected dispatch-time device error")
        chunk = self._chunk_for(active)
        tokens = self._feed_tokens(active)
        # The fed token occupies absolute position slot_len + already-in-
        # flight steps; forward writes its KV there and attends <=. The cap
        # only ever binds on inactive rows (eligibility guarantees active
        # windows), whose writes are masked-garbage either way.
        lengths = np.minimum(
            np.asarray(self._slot_len, dtype=np.int32) + self._pending_steps,
            self.ecfg.max_seq_len - 1,
        )
        temps, topks, topps, pres, freqs = self._get_sampling_arrays()
        rng_prev = self._rng
        self._rng, sub = jax.random.split(self._rng)
        lkw = {}
        if self.paged:
            lkw["table"] = self._table()
        if self._lora is not None:
            lkw["lora"] = self._lora["layers"]
            lkw["ids"] = self._adapter_ids()
        t0 = time.time()
        if self._bubble_anchor:
            self.stats["bubble_s"] += max(t0 - self._bubble_anchor, 0.0)
            self._bubble_anchor = 0.0
        decode = self._get_decode_fn(chunk)
        with jax.profiler.TraceAnnotation("kvmini.decode_dispatch"):
            with self._cache_lock:
                self._cache, self._counts, next_toks, ys = decode(
                    self.params, self._cache,
                    tokens, jnp.asarray(lengths, dtype=jnp.int32),
                    temps, topks, topps, sub,
                    self._counts, pres, freqs, **lkw,
                )
        self._tokens_dev = next_toks
        self._tokens_dev_slots = frozenset(active)
        self._inflight.append({
            "ys": ys,
            "active": list(active),
            # handle identity per slot: retire must never emit into a
            # handle that replaced the one this sweep was dispatched for
            "handles": {i: self._slot_req[i] for i in active},
            "chunk": chunk,
            "t_dispatch": t0,
            # rng state BEFORE this dispatch's split: if every slot
            # finishes before this sweep is retired, the sweep is dropped
            # and the split rewound, keeping the dispatch/rng sequence
            # identical to the synchronous loop's
            "rng_prev": rng_prev,
        })
        self._pending_steps += chunk
        depth = len(self._inflight)
        if depth > 1:
            self.stats["pipelined_sweeps"] += 1
        if depth > self.stats["dispatch_depth"]:
            self.stats["dispatch_depth"] = depth

    def _retire_one(self) -> None:
        """Read back and emit the OLDEST in-flight sweep. Emission skips a
        slot when its handle was cancelled or replaced after the dispatch —
        in-flight results of a cancelled request must never reach its
        stream. When every slot finished, any younger in-flight sweep is
        pure garbage: drop it and rewind the rng split it consumed."""
        rec = self._inflight.pop(0)
        with jax.profiler.TraceAnnotation("kvmini.decode_retire"):
            # ONE host transfer for the whole chunk block — per-element
            # int(row[i]) costs a separate device readback each (chunk x
            # slots round-trips per sweep; this line was the serving
            # bottleneck, not the decode math)
            toks_h, lps_h, tids_h, tlps_h = (
                np.asarray(a) for a in jax.device_get(rec["ys"])
            )
        t_ready = time.time()
        self.stats["busy_s"] += t_ready - max(rec["t_dispatch"], self._t_last_ready)
        self._t_last_ready = t_ready
        # watchdog food (docs/RESILIENCE.md): a retire IS scheduler
        # progress, and its wall time feeds the rolling sweep EMA the
        # wedge threshold scales from
        span = t_ready - rec["t_dispatch"]
        with self._res_lock:
            self._watch_beat = t_ready
            self._sweep_ema_s = (
                span if self._sweep_ema_s == 0.0
                else 0.8 * self._sweep_ema_s + 0.2 * span
            )
        self._pending_steps -= rec["chunk"]
        self.stats["decode_steps"] += rec["chunk"]
        overlapped = bool(self._inflight)  # device still computing N+1
        now = time.time()
        for step in range(toks_h.shape[0]):
            for i in rec["active"]:
                h = self._slot_req[i]
                if h is None or h is not rec["handles"][i]:
                    continue  # finished earlier in this chunk, or freed
                if h.cancelled is not None and not self._lockstep:
                    # cancelled between dispatch and retire: drop its
                    # tokens. In lockstep the race is host-local (the
                    # follower can't see it) — there the cancel DECISION,
                    # which precedes the retire in the stream, is what
                    # stops emission on both sides.
                    continue
                lp_info = None
                if h.request.logprobs:
                    lp_info = (
                        float(lps_h[step, i]),
                        list(zip(tids_h[step, i].tolist(),
                                 tlps_h[step, i].tolist())),
                    )
                self._emit_token(i, int(toks_h[step, i]), now, lp_info)
        t_emitted = time.time()
        # emit phase: readback -> host emission done for this window; the
        # engine-lane span records the dispatch->retire window itself
        self._observe_phase("emit", t_emitted - t_ready)
        self._trace_engine_span(
            "engine.decode.window", rec["t_dispatch"], t_ready,
            attrs={"chunk": rec["chunk"], "slots": len(rec["active"]),
                   "pipelined": overlapped},
        )
        if overlapped:
            # emission ran while the device computed the next sweep — the
            # host time the synchronous loop would have serialized
            self.stats["host_overlap_s"] += t_emitted - t_ready
        any_active = bool(self._decode_active())
        if not any_active and self._inflight:
            # every decode slot finished: younger sweeps computed only garbage.
            # Rewind to the oldest dropped sweep's pre-dispatch rng (their
            # counts/KV pollution sits in freed rows, reset at admission).
            self._rng = self._inflight[0]["rng_prev"]
            self._inflight.clear()
            self._pending_steps = 0
            self._tokens_dev = None
        self._bubble_anchor = (
            t_ready if (any_active and not self._inflight) else 0.0
        )

    def _retire_all(self, on_decision=None) -> None:
        while self._inflight:
            if on_decision is not None:
                on_decision(("retire",))
            self._retire_one()

    def _sweep_phase(self, on_decision=None) -> None:
        """Dispatch/retire policy for one iteration with live slots. The
        double-buffered steady state dispatches sweep N+1 from the
        on-device carry BEFORE retiring sweep N, so emission (and the
        next iteration's admin/cancel/admission work) runs while the
        device computes. Ineligible mixes retire what's in flight and run
        the synchronous sweep, preserving the seed scheduler exactly."""
        # sweep_stall injection (docs/RESILIENCE.md): sleep on the
        # scheduler thread with work live — a wedged/slow device sweep,
        # exactly what the watchdog watches for. The sleep runs outside
        # the registry lock.
        self._faults.stall("sweep_stall")
        active = self._decode_active()
        ok, reason = self._pipeline_eligible(active)
        if not ok and reason is not None:
            # counted per sweep iteration on pipeline-enabled engines: how
            # often the steady state COULD NOT engage, and why
            self.stats[f"pipeline_fallback_{reason}"] += 1
        if self._inflight:
            if ok:
                if on_decision is not None:
                    on_decision(("dispatch",))
                self._dispatch_plain(active)
            if on_decision is not None:
                on_decision(("retire",))
            self._retire_one()
            return
        if ok:
            if on_decision is not None:
                on_decision(("dispatch",))
            self._dispatch_plain(active)
            return  # overlap begins: host work rides the device compute
        if on_decision is not None:
            on_decision(("sweep",))
        self._decode_sweep()

    def _replay_dispatch(self) -> None:
        """Multihost follower side of a published ('dispatch',): the
        active set is deterministic from the replayed decision stream, so
        operands and jitted-call order match the primary's."""
        self._dispatch_plain(self._decode_active())

    def _masked_sweep(self, active: list[int], constrained: list[int]) -> None:
        """Grammar-constrained decode sweep: single step, synchronous —
        the next mask depends on the byte just emitted, so there is
        nothing to dispatch ahead."""
        S = self.ecfg.max_slots
        tokens = self._feed_tokens(active)
        lengths = jnp.asarray(self._slot_len, dtype=jnp.int32)
        temps, topks, topps, pres, freqs = self._get_sampling_arrays()
        self._rng, sub = jax.random.split(self._rng)
        t0 = time.time()
        if self._bubble_anchor:
            self.stats["bubble_s"] += max(t0 - self._bubble_anchor, 0.0)
            self._bubble_anchor = 0.0
        mask = np.zeros((S, (self.cfg.vocab_size + 7) // 8), dtype=np.uint8)
        for i in constrained:
            budget = min(
                self._slot_remaining[i],
                self.ecfg.max_seq_len - 1 - self._slot_len[i],
            )
            mask[i] = self._constraint_mask(self._slot_machine[i], budget)
        use_mask = np.zeros((S,), dtype=bool)
        use_mask[constrained] = True
        lkw = {}
        if self.paged:
            lkw["table"] = self._table()
        if self._lora is not None:
            lkw["lora"] = self._lora["layers"]
            lkw["ids"] = self._adapter_ids()
        decode = self._get_masked_decode_fn()
        with self._cache_lock:
            self._cache, self._counts, next_toks, ys = decode(
                self.params, self._cache,
                tokens, lengths, temps, topks, topps, sub,
                self._counts, pres, freqs,
                jnp.asarray(mask), jnp.asarray(use_mask), **lkw,
            )
        self._tokens_dev = next_toks
        self._tokens_dev_slots = frozenset(active)
        toks_h, lps_h, tids_h, tlps_h = (
            # the constrained path is synchronous by design: the next
            # mask depends on the byte just emitted  # kvmini: sync-ok
            np.asarray(a) for a in jax.device_get(ys)
        )
        now = time.time()
        self.stats["busy_s"] += now - t0
        self._t_last_ready = now
        with self._res_lock:  # watchdog beat + sweep EMA (masked path)
            self._watch_beat = now
            span = now - t0
            self._sweep_ema_s = (
                span if self._sweep_ema_s == 0.0
                else 0.8 * self._sweep_ema_s + 0.2 * span
            )
        self.stats["decode_steps"] += 1
        for step in range(toks_h.shape[0]):
            for i in active:
                if self._slot_req[i] is None:
                    continue  # finished earlier in this chunk
                lp_info = None
                if self._slot_req[i].request.logprobs:
                    lp_info = (
                        float(lps_h[step, i]),
                        # kvmini: sync-ok — lps/tids are host numpy already
                        list(zip(tids_h[step, i].tolist(), tlps_h[step, i].tolist())),
                    )
                self._emit_token(i, int(toks_h[step, i]), now, lp_info)
        self._trace_engine_span(
            "engine.decode.window", t0, now,
            attrs={"chunk": 1, "slots": len(active), "mode": "masked"},
        )
        self._observe_phase("emit", time.time() - now)
        if self._decode_active():
            self._bubble_anchor = now

    def _fail_all(self, exc: BaseException) -> None:
        """Push an error 'done' to every live/pending handle so no client
        blocks forever on a dead scheduler."""
        info = {"finish_reason": "error", "error": f"{type(exc).__name__}: {exc}"}
        # in-flight sweeps die with the scheduler; drop their bookkeeping so
        # a post-mortem snapshot_stats doesn't report phantom depth
        self._inflight.clear()
        self._pending_steps = 0
        self._tokens_dev = None
        # half-prefilled slots die with it too (their handles error below
        # through the same _slot_req sweep), and so do slots awaiting a
        # prefill-lane handoff (their payloads orphan at consume)
        self._slot_prefill = [None] * self.ecfg.max_slots
        self._prefill_fifo.clear()
        self._slot_handoff = [None] * self.ecfg.max_slots
        for slot in range(self.ecfg.max_slots):
            h = self._slot_req[slot]
            if h is not None:
                h.events.put(("done", dict(info)))
                self._slot_req[slot] = None
        if self.paged and self._deferred is not None:
            # the backpressure-held head-of-line request is in neither a
            # slot nor _pending — it must fail too or its client hangs
            self._deferred.events.put(("done", dict(info)))
            self._deferred = None
        while True:  # pending adapter ops must error out, not time out
            try:
                op = self._admin.get_nowait()
            except queue.Empty:
                break
            op.error = f"engine failed: {info['error']}"
            op.done.set()
        while True:
            try:
                h = self._pending.get_nowait()
            except queue.Empty:
                break
            h.events.put(("done", dict(info)))

    def _schedule_once(self, on_decision=None) -> None:
        """One scheduler iteration: drain admissions into free slots, then
        advance decode — pipelined (dispatch sweep N+1, retire sweep N) in
        steady state, one synchronous sweep otherwise, or a short blocking
        wait when idle. The SINGLE source of scheduling policy —
        Engine._loop runs it directly and the multi-host primary
        (runtime/multihost.py) runs it with ``on_decision``, which receives
        every state-advancing decision (("admit", request) / ("sweep",) /
        ("dispatch",) / ("retire",) / ("cancel", ...)) BEFORE it executes,
        so followers can replay the identical stream."""
        # adapter load/unload ops run here — between DISPATCHES, on this
        # thread. An in-flight sweep holds references to the (immutable)
        # arrays it was dispatched with, so a bank/registry swap here only
        # affects future dispatches.
        while True:
            try:
                op = self._admin.get_nowait()
            except queue.Empty:
                break
            op.run()

        # cancellations first: a cancelled slot must not burn a sweep (and
        # its freed slot can admit in the same iteration below). Published
        # as a decision — a follower that kept the slot live would diverge
        # its free-list from the primary's at the next admission. Finishing
        # the slot is safe even with a sweep in flight: the retire path
        # checks handle identity and drops the freed slot's in-flight
        # tokens — deterministically on primary and follower alike (the
        # cancel decision precedes the retire decision in the stream), so
        # a cancelled request never receives a token sampled after its
        # cancellation landed.
        for slot in range(self.ecfg.max_slots):
            h = self._slot_req[slot]
            if h is not None and h.cancelled is not None:
                if on_decision is not None:
                    on_decision(("cancel", h.request.request_id, h.cancelled))
                if self._slot_prefill[slot] is not None:
                    # cancelled mid-chunked-prefill: no token was ever
                    # sampled — abort without a decode span or a sweep
                    self._abort_prefill(slot, h.cancelled)
                elif self._slot_handoff[slot] is not None:
                    # cancelled while its prompt was on the prefill lane:
                    # same zero-token abort; the lane's eventual payload
                    # orphans at the consume identity check
                    self._abort_handoff(slot, h.cancelled)
                else:
                    # the ("cancel") decision published above covers this
                    # branch too — it only selects the finish shape
                    # kvmini: lockstep-ok — see above
                    self._finish_slot(slot, h.cancelled)

        admitted = False
        while self._free:
            if self.paged and self._deferred is not None:
                handle, self._deferred = self._deferred, None
            else:
                try:
                    handle = self._pending.get_nowait()
                except queue.Empty:
                    break
            if handle.cancelled is not None:
                # cancelled while queued: finish locally WITHOUT publishing
                # an admit (followers would otherwise admit a request the
                # primary never did and their free-lists would diverge)
                self._admit_one(handle)  # kvmini: lockstep-ok — early-
                continue                 # returns with the done event
            if self.paged and not self._paged_fits(handle.request):
                # hold at the head of the line until decode frees blocks
                self._deferred = handle
                break
            if self._inflight:
                # admission mutates the active set and cache bookkeeping
                # the in-flight sweep was dispatched under — retire first,
                # admit against settled state (a newly admitted slot must
                # never receive a stale token from a sweep dispatched
                # before its admission)
                self.stats["pipeline_fallback_active_set"] += 1
                self._retire_all(on_decision)
            if on_decision is not None:
                on_decision(("admit", handle.request))
            self._admit_one(handle)
            admitted = True
        self.stats["queue_depth"] = self._queue_depth()
        # republish the live-handle snapshot (docs/RESILIENCE.md): the
        # watchdog reads THIS under the lock to unblock clients on a
        # wedge, and the admission estimator counts occupancy from it —
        # neither ever touches the scheduler-owned slot list directly
        # (built OUTSIDE the lock: the slot list stays scheduler-owned)
        live_now = [h for h in self._slot_req if h is not None]
        with self._res_lock:
            self._live_handles = live_now
        # finished prefill-lane handoffs inject BETWEEN decode sweeps —
        # the decode lane's only disagg cost is one cache write per
        # admission (docs/DISAGGREGATION.md)
        self._consume_handoffs(on_decision)
        # chunked prefill rides BETWEEN decode sweeps: one piece of the
        # oldest in-progress prompt per iteration (docs/TROUBLESHOOTING.md
        # "Long prompts stall streaming")
        self._advance_prefills(on_decision)
        if self._decode_active():
            self._sweep_phase(on_decision)
        elif not admitted:
            if self._inflight:
                # every live slot was cancelled this iteration: whatever is
                # still in flight is garbage — retire (emissions all skip
                # on the freed slots) so the drop/rewind logic settles the
                # pipeline before the engine idles
                self._retire_all(on_decision)
            if self._prefill_fifo:
                # chunks still pending with no decode work: loop again
                # immediately — the next iteration advances the next piece
                return
            if not self._free:
                # every slot occupied but none decode-active: ONLY
                # possible with all slots awaiting a prefill-lane
                # handoff (docs/DISAGGREGATION.md) — popping a pending
                # request here would have no slot to hold it. Wait a
                # beat for a handoff to land instead (pre-disagg this
                # state was unreachable: occupied slots were always
                # decode-active or in the prefill fifo).
                time.sleep(0.02)
                return
            try:
                handle = self._pending.get(timeout=0.02)
            except queue.Empty:
                return
            if handle.cancelled is not None:
                # finish-without-admit, deliberately unpublished (see the
                # cancelled-while-queued note above)  # kvmini: lockstep-ok
                self._admit_one(handle)
                return
            if on_decision is not None:
                on_decision(("admit", handle.request))
            self._admit_one(handle)

    def _loop(self) -> None:
        from kserve_vllm_mini_tpu.runtime.faults import DeviceFault

        while self._running:
            try:
                with self._res_lock:
                    pending = self._fault_pending
                if pending is not None:
                    # the watchdog declared a wedge while this thread was
                    # stuck — drain the poisoned pipeline and degrade
                    # BEFORE touching new work
                    self._recover_engine_fault(pending)
                    continue
                self._schedule_once()
                with self._res_lock:
                    # watchdog beat: one full iteration IS progress (an
                    # idle engine must never look wedged)
                    self._watch_beat = time.time()
                # republish the derived KV gauges from THIS thread so
                # /metrics & /healthz (event-loop handlers) can read a
                # consistent snapshot without ever blocking on a sweep;
                # ~4 Hz is plenty for the monitor's 1 Hz scrape
                with self._obs_lock:
                    stale = time.time() - self._kv_gauges_t >= 0.25
                if stale:
                    self._kv_admin_snapshot()
                    if self.paged:
                        # host-RAM tier thrash guard rides the same
                        # cadence as the gauge republish (~4 Hz)
                        self._tier_thrash_tick()
            except DeviceFault as exc:
                # injected (or classified) dispatch-time device error:
                # recoverable by design — fail the batch, degrade, keep
                # serving (docs/RESILIENCE.md)
                self._recover_engine_fault(f"device_error: {exc}")
            except Exception as exc:  # scheduler must never die silently
                import traceback

                traceback.print_exc()
                self._fail_all(exc)
                # start()/stop() write this flag from the control thread;
                # the loop only ever clears it on crash, and every reader
                # tolerates staleness.
                # kvmini: thread-ok — GIL-atomic bool flag
                self._running = False
        # graceful drain (docs/RESILIENCE.md): the loop's LAST act, on
        # THIS thread, so slot/block state never changes owner — every
        # in-flight and queued handle gets its terminal event exactly
        # once and every slot/block is released. After a crash the
        # _fail_all above already emptied everything; the drain then
        # finds nothing.
        self._drain_requests()

    def _drain_requests(self) -> None:
        """Shutdown drain (scheduler thread): finish live slots with
        their cancel reason (default "cancelled"), release blocks, and
        fail queued/deferred handles — exactly one terminal event per
        handle, no slot or block leak."""
        self._inflight.clear()
        self._pending_steps = 0
        self._tokens_dev = None
        self._tokens_dev_slots = frozenset()
        with self._res_lock:
            faulted = set(self._faulted_ids)
        for slot in range(self.ecfg.max_slots):
            h = self._slot_req[slot]
            if h is None:
                continue
            if h.request.request_id in faulted:
                # the watchdog already sent this handle its terminal
                # event — release the slot without a second 'done'
                # (_release_slot also drops any chunked-prefill state)
                self._release_slot(slot)
                continue
            h.cancelled = h.cancelled or "cancelled"
            if self._slot_prefill[slot] is not None:
                self._abort_prefill(slot, h.cancelled)
            elif self._slot_handoff[slot] is not None:
                # drained mid-handoff: zero-token terminal event exactly
                # once; the lane's payload orphans at consume (or the
                # lane flushes it as a tombstone on its own stop)
                self._abort_handoff(slot, h.cancelled)
            else:
                self._finish_slot(slot, h.cancelled)
        if self.paged and self._deferred is not None:
            # the backpressure-held head-of-line handle sits in neither
            # a slot nor _pending — it must drain too
            self._deferred.events.put(("done", {
                "finish_reason": "cancelled", "tokens_out": 0,
            }))
            self._deferred = None
        while True:
            try:
                h = self._pending.get_nowait()
            except queue.Empty:
                break
            h.events.put(("done", {
                "finish_reason": "cancelled", "tokens_out": 0,
            }))

    # -- resilience: watchdog, engine-fault recovery, admission estimate ---
    # (docs/RESILIENCE.md)

    def _watchdog_loop(self) -> None:
        """Side thread: declare the scheduler WEDGED when no progress
        beat lands within max(factor x sweep EMA, min_s) while work is
        live. On a trip it sends every in-flight handle its terminal
        ``engine_fault`` event IMMEDIATELY (clients unblock even though
        the scheduler thread is still stuck) and parks the recovery
        reason for the loop to act on when it resumes. One trip per
        wedge: the same stuck beat never trips twice."""
        interval = max(min(self.ecfg.watchdog_min_s / 4.0, 0.25), 0.02)
        tripped_beat: Optional[float] = None
        while not self._watch_stop.wait(interval):
            with self._res_lock:
                beat = self._watch_beat
                ema = self._sweep_ema_s
                pending = self._fault_pending
                live = list(self._live_handles)
            if pending is not None:
                continue  # a trip is already waiting for recovery
            if not live:
                tripped_beat = None
                continue
            if ema <= 0.0:
                # not armed until the FIRST sweep retires: a cold engine's
                # first decode dispatch blocks in XLA compile for seconds,
                # and with no EMA the floor alone would trip on it. The
                # first retire seeds the EMA compile-inflated, so the
                # threshold self-decays toward warm sweep times.
                continue
            threshold = max(
                self.ecfg.watchdog_factor * ema, self.ecfg.watchdog_min_s
            )
            stalled = time.time() - beat
            if stalled < threshold or beat == tripped_beat:
                continue
            tripped_beat = beat
            reason = (
                f"watchdog: no sweep retired for {stalled:.2f}s "
                f"(threshold {threshold:.2f}s, sweep EMA {ema:.3f}s)"
            )
            now = time.time()
            faulted: list[str] = []
            for h in live:
                # cancel first: the wedged sweep's retire (when the
                # thread resumes) checks `cancelled` and drops this
                # handle's tokens — no token event can follow the
                # terminal event below
                h.cancelled = h.cancelled or "engine_fault"
                h.finish_reason = "engine_fault"
                h.t_done = now
                h.events.put(("done", {
                    "finish_reason": "engine_fault",
                    "tokens_out": len(h.tokens),
                    "error": reason,
                }))
                faulted.append(h.request.request_id)
            with self._res_lock:
                self._watchdog_trips += 1
                self._faulted_ids.update(faulted)
                self._fault_pending = reason

    def _recover_engine_fault(self, reason: str) -> None:
        """Scheduler-thread recovery from a wedge/device fault: drop the
        poisoned in-flight pipeline, finish every live slot with
        ``finish_reason="engine_fault"`` (exactly once — handles the
        watchdog already unblocked are only released), free slots and
        blocks, climb one degrade-ladder level, and keep serving. Past
        the ladder the engine gives up via the generic crash path."""
        import sys

        print(f"engine: recovering from fault: {reason}", file=sys.stderr)
        self._inflight.clear()
        self._pending_steps = 0
        self._tokens_dev = None
        self._tokens_dev_slots = frozenset()
        now = time.time()
        with self._res_lock:
            faulted = set(self._faulted_ids)
            self._faulted_ids.clear()
            self._fault_pending = None
        for slot in range(self.ecfg.max_slots):
            h = self._slot_req[slot]
            if h is None:
                continue
            if h.request.request_id not in faulted:
                h.t_done = now
                h.finish_reason = "engine_fault"
                self._observe_phase(
                    "decode", max(now - (h.t_first_token or now), 0.0)
                )
                self._trace_span(
                    h, "server.decode", h.t_first_token or now, now,
                    ok=False, attrs={"finish_reason": "engine_fault"},
                )
                h.events.put(("done", {
                    "finish_reason": "engine_fault",
                    "tokens_out": len(h.tokens),
                    "error": reason,
                }))
            self.stats["requests_completed"] += 1
            # never retain this slot's KV: the wedged/errored sweep may
            # have written garbage into it
            self._retained[slot] = []
            self._release_slot(slot)
        with self._res_lock:
            self._engine_faults += 1
            self._degrade_level = min(self._degrade_level + 1, 4)
            level = self._degrade_level
        # degrade ladder: each trip gives up one optimization the fault
        # may have been hiding in; the queue keeps serving throughout
        if level == 1:
            self.ecfg.decode_pipeline = False
        elif level == 2:
            self.ecfg.decode_chunk = 1
        elif level == 3:
            self.ecfg.spec_tokens = 0
        elif level >= 4:
            # past the ladder: give up loudly — queued clients error out
            # through the crash path, never hang
            exc = RuntimeError(
                f"engine fault past the degrade ladder (trip {level}): {reason}"
            )
            print(f"engine: {exc}", file=sys.stderr)
            self._fail_all(exc)
            # scheduler-thread write, same as the _loop crash path
            self._running = False

    def estimate_wait_s(self) -> float:
        """Admission burn-rate estimate: seconds a request submitted NOW
        would take to COMPLETE, from queue depth and the rolling
        admit->done service EMA (waves of max_slots requests). 0.0 with
        no service history — the engine admits until it has data. The
        server's deadline-aware shed gate compares this against the
        request's deadline (docs/RESILIENCE.md)."""
        with self._res_lock:
            service = self._service_ema_s
            occupied = len(self._live_handles)
        if service <= 0.0:
            return 0.0
        depth = self._queue_depth()
        slots = max(self.ecfg.max_slots, 1)
        if depth == 0 and occupied < slots:
            # a free slot RIGHT NOW: admission is immediate. The queue
            # burn-rate model only gates work that must WAIT — an idle
            # engine must never shed on a stale (e.g. cold-compile-
            # inflated) service EMA.
            return 0.0
        # full waves ahead of it, plus its own
        waves = depth // slots + 1
        return (waves + 1) * service

    def count_shed(self) -> None:
        """Server-side admission shed accounting (the 429 path lives in
        runtime/server.py; the counter lives here so ONE stats key covers
        both shed sites)."""
        with self._res_lock:
            self._requests_shed += 1

    def arm_fault(self, name: str, **params: Any) -> dict[str, Any]:
        """Arm a named injection point at runtime (the /faults endpoint,
        docs/RESILIENCE.md). The registry is built once at construction
        and internally locked, so this is callable from any thread."""
        if name == "kv_alloc_fail" and not self.paged:
            # the point lives in the paged admission path: arming it on a
            # dense engine would let a chaos run stamp a green recovered
            # row for a fault that can never execute
            raise ValueError(
                "kv_alloc_fail needs kv_layout=paged; this engine is dense"
            )
        if name == "kv_handoff_drop" and self._disagg is None:
            # same honesty rule: the point lives on the prefill lane —
            # arming it on a colocated engine can never fire
            raise ValueError(
                "kv_handoff_drop needs a disaggregated engine (disagg "
                "/ --disagg); this engine prefills colocated"
            )
        return self._faults.arm(name, **params).to_dict()

    def clear_fault(self, name: Optional[str] = None) -> None:
        """Clear one armed point (None = all). An open kv_alloc_fail
        backpressure window expires by its armed duration (that state is
        scheduler-owned)."""
        self._faults.disarm(name)

    def active_faults(self) -> dict[str, Any]:
        return self._faults.active()

    def check_fault(self, name: str):
        """Hot-path fault check for NON-engine threads (the server's
        sse_disconnect point lives on the event loop): returns the fired
        FaultSpec or None. The registry is internally locked."""
        return self._faults.check(name)

    # -- introspection -----------------------------------------------------

    def snapshot_stats(self) -> dict[str, Any]:
        # Deliberately lock-free monitoring snapshot (single-writer engine:
        # only the scheduler thread mutates this state; list len/iteration
        # and dict copy are GIL-atomic). A snapshot taken mid-sweep is at
        # worst one sweep stale — adding a stats lock to the decode hot
        # path to fix that is the wrong trade. Each read below carries its
        # own thread-ok so a NEW cross-thread surface still gets flagged.
        s = dict(self.stats)
        wall = max(time.time() - s["started_at"], 1e-9)
        s["duty_cycle"] = min(s["busy_s"] / wall, 1.0)
        # kvmini: thread-ok — benign racy snapshot (see above)
        s["active_slots"] = sum(1 for h in self._slot_req if h is not None)
        # kvmini: thread-ok — benign racy snapshot (see above)
        s["free_slots"] = len(self._free)
        # live recompute: the cached value goes stale between scheduler
        # iterations, and the deferred head-of-line handle must count
        s["queue_depth"] = self._queue_depth()
        # kvmini: thread-ok — benign racy snapshot (see above)
        s["inflight_sweeps"] = len(self._inflight)
        # Derived KV gauges (occupancy, fragmentation, retained fraction,
        # hit-depth percentiles) come from ONE consistent scheduler-thread
        # pass (_kv_admin_snapshot): a ratio built from independent
        # lock-free len() reads could tear between them, which the
        # single-writer annotations above never had to worry about.
        kv = self._kv_admin_snapshot()
        s["kv_prefix_hit_depth_p50"] = kv.get("kv_prefix_hit_depth_p50", 0)
        s["kv_prefix_hit_depth_p95"] = kv.get("kv_prefix_hit_depth_p95", 0)
        s["kv_bytes_per_token"] = self.kv_bytes_per_token()
        # physical bytes the reused prompt tokens did NOT re-write — the
        # byte-denominated view of prefix_tokens_reused_total
        s["kv_reused_bytes"] = s["prefix_tokens_reused"] * s["kv_bytes_per_token"]
        # per-device analytic footprint (computed once at build; see
        # __init__) — exported so headroom_error_pct can be derived from a
        # plain /metrics scrape next to the observed watermark
        s["hbm_headroom_estimate_bytes"] = self._headroom_estimate_bytes
        if self.paged:
            for key in ("kv_pool_blocks", "kv_free_blocks",
                        "kv_retained_blocks", "kv_used_blocks",
                        "kv_block_size", "kv_occupancy",
                        "kv_retained_fraction", "kv_fragmentation",
                        "kv_logical_bytes", "kv_physical_bytes",
                        "kv_tier_blocks", "kv_tier_bytes",
                        "kv_tier_capacity_bytes", "kv_tier_disabled"):
                if key in kv:
                    s[key] = kv[key]
        # HBM watermarks (docs/TROUBLESHOOTING.md): device memory_stats
        # when the backend reports them — gracefully absent (no keys, no
        # fabricated zeros) on CPU backends that don't
        from kserve_vllm_mini_tpu.profiling.headroom import hbm_watermarks

        hbm = hbm_watermarks()
        if hbm:
            s["hbm_bytes_in_use"] = hbm["bytes_in_use"]
            if "bytes_limit" in hbm:
                s["hbm_bytes_limit"] = hbm["bytes_limit"]
            with self._obs_lock:
                self._hbm_peak_seen = max(
                    self._hbm_peak_seen,
                    hbm.get("peak_bytes_in_use", 0),
                    hbm["bytes_in_use"],
                )
                s["hbm_peak_bytes"] = self._hbm_peak_seen
        if self._disagg is not None:
            # disaggregated-serving gauges (docs/DISAGGREGATION.md): lane
            # backlog (the handoff_stall monitor rule's input) and the
            # degrade-ladder position. queue_depth() is internally
            # locked; the degrade flag is a scheduler-owned bool.
            s["kv_handoff_queue_depth"] = self._disagg.queue_depth()
            # kvmini: thread-ok — GIL-atomic bool gauge, scheduler-owned
            s["disagg_degraded"] = 1 if self._disagg_degraded else 0
        s["spec_accept_ratio"] = (
            s["spec_accepted"] / s["spec_proposed"] if s["spec_proposed"] else 0.0
        )
        # resilience rail (docs/RESILIENCE.md): sheds, watchdog trips,
        # recovered engine faults, the degrade ladder position, and how
        # many injection points are currently armed — read in one pass
        # under the lock their writers hold
        with self._res_lock:
            s["requests_shed"] = self._requests_shed
            s["watchdog_trips"] = self._watchdog_trips
            s["engine_faults"] = self._engine_faults
            s["degrade_level"] = self._degrade_level
        s["faults_armed"] = self._faults.armed_count()
        # fleet-router placement input (docs/FLEET.md): the same
        # admission burn-rate estimate the deadline shed gate compares
        # deadlines against, exported so a router can score replicas
        # from one /metrics scrape (estimate_wait_s locks internally)
        s["estimated_wait_s"] = self.estimate_wait_s()
        # compile-stats totals (docs/PROFILING.md): the recorder is
        # internally locked, so this read is consistent by construction
        cs = self._compile_recorder.snapshot()
        s["compiles"] = cs["compiles"]
        s["compile_s"] = cs["compile_s"]
        s["compiled_flops"] = cs["compiled_flops"]
        s["compiled_bytes"] = cs["compiled_bytes"]
        s["compile_peak_bytes"] = cs["compile_peak_bytes"]
        # live economics rail (docs/ECONOMICS.md): one rolling-window
        # observation per snapshot, fed the busy/token values THIS
        # snapshot already read, under _obs_lock (scrapers from any
        # thread drive it). The $/hr accrual is a level gauge known from
        # construction; the per-token rates appear once the window holds
        # token progress — absent while warming up, never $0. No rail
        # object (CPU backend, no econ_accelerator) -> no keys at all.
        if self._econ is not None:
            with self._obs_lock:
                econ = self._econ.observe(
                    time.time(), s["busy_s"], s["decode_tokens"]
                )
            s["econ_usd_per_hour"] = self._econ.usd_per_hour
            if econ:
                s["econ_usd_per_1k_tokens"] = econ["usd_per_1k_tokens"]
                s["econ_wh_per_1k_tokens"] = econ["wh_per_1k_tokens"]
                s["econ_tokens_per_sec"] = econ["tokens_per_sec"]
        return s

    def kv_bytes_per_token(self) -> int:
        """Physical KV bytes one cached position costs, parameterized by
        the KV dtype — priced by the SAME kv_elem_bytes formula the
        admission estimate uses (profiling/headroom.py), so
        headroom_error_pct never compares two different models and the
        logical/physical byte gauges keep reading true when quantized
        KV lands on the paged path (ROADMAP item 3)."""
        from kserve_vllm_mini_tpu.profiling.headroom import kv_elem_bytes

        cfg = self.cfg
        if self.ecfg.kv_cache_dtype == "int8":
            elem = kv_elem_bytes(cfg.head_dim, 0.0, quantized=True)
        elif self.ecfg.kv_cache_dtype:
            elem = kv_elem_bytes(
                cfg.head_dim, jnp.dtype(self.ecfg.kv_cache_dtype).itemsize
            )
        else:
            elem = kv_elem_bytes(cfg.head_dim, cfg.jnp_dtype.itemsize)
        return int(2 * cfg.n_layers * cfg.n_kv_heads * cfg.head_dim * elem)

    def _kv_admin_snapshot(self, force: bool = False) -> dict[str, Any]:
        """Every DERIVED KV gauge — pool occupancy, fragmentation,
        retained fraction, logical/physical bytes, prefix-hit-depth
        percentiles — computed in ONE pass ON the scheduler thread.
        Ratios over ``_free_blocks``/``_retained_lru`` built from
        independent lock-free ``len()`` reads could tear between the
        reads: the single-writer discipline those attributes live under
        makes a lone stale length benign, but not a ratio of two lengths
        from different sweeps.

        While the scheduler runs, cross-thread callers (the aiohttp
        /metrics and /healthz handlers — which live ON the event loop, so
        they must never block on a sweep) read the cache the scheduler
        republishes every ~250 ms from its own loop; ``force=True``
        (the once-per-run results snapshot, called off the event loop)
        rendezvouses via ``_run_admin`` for a fully fresh pass, falling
        back to the cache on timeout/shutdown."""
        if (
            self._running
            and threading.current_thread() is not self._thread
            and not force
        ):
            with self._obs_lock:
                return dict(self._kv_gauges)
        fresh: dict[str, Any] = {}

        def _collect() -> None:
            depths = sorted(self._hit_depths)

            def pct(p: float) -> int:
                if not depths:
                    return 0
                k = max(int(round(p / 100.0 * len(depths) + 0.5)) - 1, 0)
                return depths[min(k, len(depths) - 1)]

            fresh["kv_prefix_hit_depth_p50"] = pct(50.0)
            fresh["kv_prefix_hit_depth_p95"] = pct(95.0)
            if not self.paged:
                return
            pool = self._scratch_block
            free = len(self._free_blocks)
            retained = len(self._retained_lru)
            used = pool - free - retained
            bpt = self.kv_bytes_per_token()
            live = sum(
                self._slot_len[i]
                for i in range(self.ecfg.max_slots)
                if self._slot_blocks[i]
            )
            # blocks allocated to a routed slot whose handoff/migration
            # is still in flight (_slot_len is 0 until activation): they
            # are BEING written, not fragmented — counting them would
            # false-fire the gauge on every disagg/migration run
            in_transit = sum(
                len(self._slot_blocks[i])
                for i in range(self.ecfg.max_slots)
                if self._slot_handoff[i] is not None
            )
            settled = used - in_transit
            fresh.update({
                "kv_pool_blocks": pool,
                "kv_free_blocks": free,
                "kv_retained_blocks": retained,
                "kv_used_blocks": used,
                "kv_block_size": self._blk,
                "kv_occupancy": used / pool,
                "kv_retained_fraction": retained / pool,
                # allocated-but-unwritten positions inside slot-owned
                # blocks (reservations are worst-case); shared prefixes
                # can push live-token totals past used*blk, so clamp
                "kv_fragmentation": (
                    min(max(1.0 - live / (settled * self._blk), 0.0), 1.0)
                    if settled > 0 else 0.0
                ),
                "kv_logical_bytes": live * bpt,
                "kv_physical_bytes": pool * self._blk * bpt,
                # host-RAM tier gauges (priced as HOST bytes — never in
                # the HBM headroom estimate)
                "kv_tier_blocks": len(self._tier),
                "kv_tier_bytes": self._tier_bytes,
                "kv_tier_capacity_bytes": self._tier_cap_bytes,
                "kv_tier_disabled": 1 if self._tier_disabled else 0,
            })

        err = self._run_admin(_collect, timeout_s=2.0)
        with self._obs_lock:
            if err is None and fresh:
                self._kv_gauges = dict(fresh)
                self._kv_gauges_t = time.time()
            return dict(self._kv_gauges)

    def kv_cache_snapshot(self) -> dict[str, Any]:
        """The results.json ``kv_cache`` block (core/schema.py
        validate_kv_cache): lifecycle counters plus the derived gauges,
        keyed the way the analyzer's /metrics scrape maps them
        (analysis/telemetry.py KV_METRIC_KEYS) — snapshotted directly in
        self-serve runs, where it is authoritative (it cannot race the
        server teardown the way a post-run scrape can). Called off the
        event loop once per run, so it can afford the forced scheduler
        rendezvous for a fully fresh gauge pass."""
        self._kv_admin_snapshot(force=True)
        s = self.snapshot_stats()
        block: dict[str, Any] = {
            "source": "engine:snapshot",
            "hit_depth_p50": s["kv_prefix_hit_depth_p50"],
            "hit_depth_p95": s["kv_prefix_hit_depth_p95"],
            "bytes_per_token": s["kv_bytes_per_token"],
            "reused_bytes": s["kv_reused_bytes"],
            "blocks_allocated": s["kv_blocks_allocated"],
            "retained_evictions": s["kv_retained_evictions"],
            "share_reclaims": s["kv_share_reclaims"],
            "prefix_hits": s["prefix_hits"],
            "prefix_lookups": s["prefix_lookups"],
            "headroom_estimate_bytes": s["hbm_headroom_estimate_bytes"],
        }
        for stats_key, sub in (
            ("kv_pool_blocks", "pool_blocks"),
            ("kv_free_blocks", "free_blocks"),
            ("kv_retained_blocks", "retained_blocks"),
            ("kv_used_blocks", "used_blocks"),
            ("kv_block_size", "block_size"),
            ("kv_occupancy", "occupancy"),
            ("kv_retained_fraction", "retained_fraction"),
            ("kv_fragmentation", "fragmentation"),
            ("kv_logical_bytes", "logical_bytes"),
            ("kv_physical_bytes", "physical_bytes"),
            ("kv_tier_demotions", "tier_demotions"),
            ("kv_tier_promotions", "tier_promotions"),
            ("kv_tier_hits", "tier_hits"),
            ("kv_tier_blocks", "tier_blocks"),
            ("kv_tier_bytes", "tier_bytes"),
            ("kv_tier_capacity_bytes", "tier_capacity_bytes"),
            ("kv_tier_disabled", "tier_disabled"),
            ("kv_migrated_blocks", "migrated_blocks"),
            ("kv_migrated_bytes", "migrated_bytes"),
            ("kv_export_blocks", "export_blocks"),
            ("hbm_bytes_in_use", "hbm_bytes_in_use"),
            ("hbm_peak_bytes", "hbm_peak_bytes"),
            ("hbm_bytes_limit", "hbm_bytes_limit"),
        ):
            if stats_key in s:
                block[sub] = s[stats_key]
        return block

    def disagg_snapshot(self) -> dict[str, Any]:
        """The results.json ``disagg`` block (docs/DISAGGREGATION.md):
        handoff counters keyed the way the analyzer's /metrics scrape
        maps them (analysis/telemetry.py DISAGG_METRIC_KEYS) —
        snapshotted directly in self-serve runs, where it is
        authoritative. Empty on colocated engines (no block, never
        fabricated zeros — the same absence contract as kv_cache)."""
        if self._disagg is None:
            return {}
        s = self.snapshot_stats()
        return {
            "source": "engine:snapshot",
            "handoffs": s["kv_handoffs"],
            "handoff_blocks": s["kv_handoff_blocks"],
            "handoff_wait_s": round(s["kv_handoff_wait_s"], 6),
            "handoff_drops": s["kv_handoff_drops"],
            "handoff_bytes_copied": s["kv_handoff_bytes_copied"],
            "lane_busy_s": round(s["prefill_lane_busy_s"], 6),
            "colocated_fallbacks": s["disagg_colocated_fallbacks"],
            "queue_depth": s["kv_handoff_queue_depth"],
            "degraded": bool(s["disagg_degraded"]),
        }

    def economics_snapshot(self) -> dict[str, Any]:
        """The results.json ``economics`` block (docs/ECONOMICS.md):
        live-rail gauges keyed the way the analyzer's /metrics scrape
        maps them (analysis/telemetry.py ECON_METRIC_KEYS) — snapshotted
        directly in self-serve runs, where it is authoritative. Empty on
        engines without the rail (CPU backends with no econ_accelerator:
        no block, never fabricated $0 — the same absence contract as
        kv_cache/disagg). The marginal-replica gauge never appears here:
        it is a fleet-router aggregate, not a single-engine fact."""
        if self._econ is None:
            return {}
        s = self.snapshot_stats()
        block: dict[str, Any] = {
            "source": "engine:snapshot",
            "usd_per_hour": s["econ_usd_per_hour"],
        }
        for stats_key, sub in (
            ("econ_usd_per_1k_tokens", "usd_per_1k_tokens"),
            ("econ_wh_per_1k_tokens", "wh_per_1k_tokens"),
            ("econ_tokens_per_sec", "tokens_per_sec"),
        ):
            if stats_key in s:
                block[sub] = s[stats_key]
        return block

    def compile_stats_snapshot(self) -> dict[str, Any]:
        """The results.json ``compile_stats`` block (docs/PROFILING.md):
        recorder totals keyed the way the analyzer's /metrics scrape maps
        them, plus the per-executable entries for run artifacts."""
        cs = self._compile_recorder.snapshot()
        return {
            "compiles": cs["compiles"],
            "compile_wall_s": round(cs["compile_s"], 4),
            "flops": cs["compiled_flops"],
            "bytes_accessed": cs["compiled_bytes"],
            "peak_bytes": cs["compile_peak_bytes"],
            "executables": [
                e.to_dict() for e in self._compile_recorder.entries()
            ],
        }
