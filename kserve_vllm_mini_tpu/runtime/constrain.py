"""Grammar-constrained decoding: byte-level masks for JSON mode and tool calls.

Reference surface: ``response_format: {"type": "json_object"}`` and
``tools``/``tool_choice`` in the OpenAI dialect, exercised by
/root/reference/scripts/openai_parity_probe.py:104-186 and the
structured-output / tool-calling load profiles (which claim "100% format
compliance", runners/profiles/structured-output.yaml:41). The engines the
reference benchmarks implement this with token-grammar libraries; here the
runtime is in-repo, so the mechanism is explicit:

- a host-side **pushdown automaton over bytes** tracks the JSON parse state
  and yields the set of bytes allowed next;
- the engine turns that set into an additive logit mask over the byte span
  of the vocab (ByteTokenizer: one token == one byte, so the automaton and
  the sampler agree by construction) and applies it **on device** — the
  hot loop stays jitted; the host only flips mask bits between steps;
- a **budget guard** forces the shortest legal close when the remaining
  token budget gets tight, so output is valid JSON even at max_tokens.

The grammar is deliberately a clean JSON subset (objects/arrays/strings
without escapes/integers/true/false/null, bounded depth and item counts):
every emission is valid JSON, not every valid JSON is emittable. That is
the right trade for *format* guarantees — and it makes constrained decoding
work even on random-weight smoke models, which is exactly what CI needs.
"""

from __future__ import annotations

from typing import Optional, Sequence

# printable ASCII minus '"' and '\\' — the characters allowed inside
# generated strings (no escape sequences => no escape-state machinery)
_STR_BYTES = bytes(b for b in range(0x20, 0x7F) if b not in (0x22, 0x5C))
_DIGITS = b"0123456789"
_SCALAR_STARTS = b'"' + _DIGITS + b"tfn"
_VALUE_STARTS = b"{[" + _SCALAR_STARTS

_LITERALS = {ord("t"): b"rue", ord("f"): b"alse", ord("n"): b"ull"}

# frame kinds:
#   value      — want any value start byte
#   value_obj  — want '{' specifically (root of json_object mode)
#   obj n      — inside '{', no key yet: '"' opens first key, '}' closes
#   obj_next n — after a member: ',' continues, '}' closes
#   key_open   — after ',': '"' must open the next key
#   key        — inside a key string
#   colon      — want ':'
#   arr n      — inside '[', no item yet
#   arr_next n — after an item: ',' continues, ']' closes
#   str        — inside a value string
#   num        — inside an integer (complete at every digit)
#   lit rest   — finishing true/false/null


class JsonMachine:
    """Incremental generator state for one JSON value.

    ``allowed(budget)`` -> bytes legal next, shrinking to the forced-close
    set as ``budget`` approaches ``min_close()``; ``advance(b)`` consumes
    one emitted byte; ``done`` flips when the root value completes.
    """

    def __init__(
        self,
        root: str = "object",
        max_depth: int = 4,
        max_str: int = 32,
        max_items: int = 8,
    ) -> None:
        self.max_depth = max_depth
        self.max_str = max_str
        self.max_items = max_items
        self.done = False
        self.stack: list[list] = [["value_obj" if root == "object" else "value"]]
        self._str_len = 0

    # -- sizing -------------------------------------------------------------

    def _depth(self) -> int:
        return sum(1 for f in self.stack if f[0] in ("obj", "obj_next", "arr", "arr_next"))

    def min_close(self) -> int:
        """Minimal bytes from here to a complete root value."""
        n = 0
        for f in reversed(self.stack):
            kind = f[0]
            if kind == "value":
                n += 1            # one digit
            elif kind == "value_obj":
                n += 2            # "{}"
            elif kind in ("obj", "obj_next", "arr", "arr_next"):
                n += 1            # the close byte
            elif kind == "key_open":
                n += 4            # '"' + '"' + ':' + digit
            elif kind == "key":
                n += 3            # closing '"' + ':' + digit
            elif kind == "colon":
                n += 2            # ':' + digit
            elif kind == "str":
                n += 1            # closing '"'
            elif kind == "num":
                n += 0            # already complete
            elif kind == "lit":
                n += len(f[1])
        return n

    # -- allowed sets -------------------------------------------------------

    def clone(self) -> "JsonMachine":
        m = JsonMachine.__new__(JsonMachine)
        m.max_depth, m.max_str, m.max_items = self.max_depth, self.max_str, self.max_items
        m.done = self.done
        m._str_len = self._str_len
        m.stack = [
            [f[0], bytearray(f[1])] if f[0] == "lit" else list(f) for f in self.stack
        ]
        return m

    def _raw_allowed(self) -> bytes:
        """Grammar-legal next bytes, honoring size caps but not the budget."""
        f = self.stack[-1]
        kind = f[0]
        if kind == "value_obj":
            return b"{"
        if kind == "value":
            return _VALUE_STARTS if self._depth() < self.max_depth else _SCALAR_STARTS
        if kind == "obj":
            return b'"}'
        if kind == "obj_next":
            return b"}" if f[1] >= self.max_items else b",}"
        if kind == "arr":
            starts = _VALUE_STARTS if self._depth() < self.max_depth else _SCALAR_STARTS
            return b"]" + starts
        if kind == "arr_next":
            return b"]" if f[1] >= self.max_items else b",]"
        if kind == "key_open":
            return b'"'
        if kind in ("key", "str"):
            return b'"' if self._str_len >= self.max_str else b'"' + _STR_BYTES
        if kind == "colon":
            return b":"
        if kind == "num":
            parent = self.stack[-2]
            close = b"}" if parent[0] == "obj" else b"]"
            cont = close if parent[1] + 1 >= self.max_items else b"," + close
            # JSON forbids leading zeros: a number that began with '0'
            # cannot take further digits
            return cont if f[1] else _DIGITS + cont
        if kind == "lit":
            return bytes(f[1][:1])
        raise AssertionError(f"unknown frame {kind!r}")

    def allowed(self, budget: int) -> bytes:
        """Bytes legal next AND completable within ``budget`` total bytes.

        Correctness by construction: a byte survives iff one simulated
        advance leaves ``min_close() <= budget - 1``. The forced-close byte
        always survives when ``budget >= min_close()``, so the set is never
        empty while closing remains possible. The simulation is skipped on
        the fast path (comfortable budget — one byte commits at most ~8
        more, literals being the worst case)."""
        if self.done:
            return b""
        cands = self._raw_allowed()
        if budget >= self.min_close() + 16:
            return cands
        out = bytearray()
        for b in cands:
            m = self.clone()
            m.advance(b)
            if m.done or m.min_close() <= budget - 1:
                out.append(b)
        return bytes(out)

    def str_room(self) -> Optional[int]:
        """Remaining capacity of the string being generated, or None when
        not inside a string/key. Token-level masking (token_grammar.py)
        uses this to admit multi-byte string tokens: string interiors are
        the one place a token's bytes can advance several automaton steps
        without ever completing the machine mid-token."""
        f = self.stack[-1]
        if f[0] in ("key", "str"):
            return self.max_str - self._str_len
        return None

    # -- transitions --------------------------------------------------------

    def _value_done(self) -> None:
        """The value on top just completed; fold into the enclosing frame.
        An empty stack means the root value itself completed."""
        if not self.stack:
            self.done = True
            return
        parent = self.stack[-1]
        assert parent[0] in ("obj", "arr"), parent
        parent[1] += 1
        parent[0] = "obj_next" if parent[0] == "obj" else "arr_next"

    def advance(self, b: int) -> None:
        assert not self.done, "advance after completion"
        f = self.stack[-1]
        kind = f[0]

        if kind in ("value", "value_obj"):
            self.stack.pop()
            if b == ord("{"):
                self.stack.append(["obj", 0])
            elif b == ord("["):
                self.stack.append(["arr", 0])
            elif b == ord('"'):
                self._str_len = 0
                self.stack.append(["str"])
            elif b in _DIGITS:
                self.stack.append(["num", b == ord("0")])
            elif b in _LITERALS:
                self.stack.append(["lit", bytearray(_LITERALS[b])])
            else:
                raise ValueError(f"byte {b!r} is not a value start")
            return
        if kind == "obj":
            if b == ord("}"):
                self.stack.pop()
                self._value_done()
            else:
                assert b == ord('"'), b
                self._str_len = 0
                self.stack.append(["key"])
            return
        if kind == "obj_next":
            if b == ord("}"):
                self.stack.pop()
                self._value_done()
            else:
                assert b == ord(","), b
                f[0] = "obj"  # reuse the frame; count kept
                self.stack.append(["key_open"])
            return
        if kind == "key_open":
            assert b == ord('"'), b
            self.stack.pop()
            self._str_len = 0
            self.stack.append(["key"])
            return
        if kind == "arr":
            if b == ord("]"):
                self.stack.pop()
                self._value_done()
            else:
                self.stack.append(["value"])
                self.advance(b)  # re-dispatch the value-start byte
            return
        if kind == "arr_next":
            if b == ord("]"):
                self.stack.pop()
                self._value_done()
            else:
                assert b == ord(","), b
                f[0] = "arr"
                self.stack.append(["value"])
            return
        if kind == "key":
            if b == ord('"'):
                self.stack.pop()
                self.stack.append(["colon"])
            else:
                self._str_len += 1
            return
        if kind == "colon":
            assert b == ord(":"), b
            self.stack.pop()
            self.stack.append(["value"])
            return
        if kind == "str":
            if b == ord('"'):
                self.stack.pop()
                self._value_done()
            else:
                self._str_len += 1
            return
        if kind == "num":
            if b in _DIGITS:
                return
            # implicit end: the byte belongs to the enclosing container
            self.stack.pop()
            self._value_done()
            self.advance(b)
            return
        if kind == "lit":
            assert b == f[1][0], (bytes(f[1]), b)
            del f[1][:1]
            if not f[1]:
                self.stack.pop()
                self._value_done()
            return
        raise AssertionError(f"unknown frame {kind!r}")


class TemplateMachine:
    """Fixed byte template with free JSON holes — the tool-call grammar.

    Parts: ``bytes`` literals, ``("choice", [bytes, ...])`` one-of branches
    (the tool name under ``tool_choice: auto``), and ``("json",)`` holes
    filled by a fresh JsonMachine (the tool's free-form arguments).
    Exposes the same allowed/advance/done/min_close protocol as JsonMachine
    so the engine treats both uniformly.
    """

    def __init__(self, parts: Sequence) -> None:
        self.parts = list(parts)
        self.idx = 0
        self.pos = 0
        self.cands: Optional[list[bytes]] = None  # live choice candidates
        self.sub: Optional[JsonMachine] = None
        self.done = not self.parts

    def _next_literal_byte(self) -> Optional[int]:
        """First byte of the part after the current one (None at the end).
        Parts following a choice are literals in every grammar we build, so
        this is the disambiguator for prefix-overlapping tool names."""
        if self.idx + 1 >= len(self.parts):
            return None
        nxt = self.parts[self.idx + 1]
        if isinstance(nxt, (bytes, bytearray)) and nxt:
            return nxt[0]
        return None

    def _part_min(self, i: int) -> int:
        p = self.parts[i]
        if isinstance(p, (bytes, bytearray)):
            return len(p) - (self.pos if i == self.idx else 0)
        if p[0] == "choice":
            cands = self.cands if (i == self.idx and self.cands is not None) else p[1]
            return min(len(c) for c in cands) - (self.pos if i == self.idx else 0)
        if i == self.idx and self.sub is not None:
            return self.sub.min_close()
        return 2  # "{}"

    def min_close(self) -> int:
        return sum(self._part_min(i) for i in range(self.idx, len(self.parts)))

    def allowed(self, budget: int) -> bytes:
        if self.done:
            return b""
        p = self.parts[self.idx]
        tail = sum(self._part_min(i) for i in range(self.idx + 1, len(self.parts)))
        if isinstance(p, (bytes, bytearray)):
            return bytes(p[self.pos:self.pos + 1])
        if p[0] == "choice":
            cands = self.cands if self.cands is not None else list(p[1])
            out = set()
            for c in cands:
                if len(c) > self.pos:
                    # picking this byte commits to the cheapest candidate
                    # still compatible with it — must fit the budget
                    cost = min(
                        len(c2) for c2 in cands
                        if len(c2) > self.pos and c2[self.pos] == c[self.pos]
                    ) - self.pos + tail
                    if cost <= budget:
                        out.add(c[self.pos])
            if any(len(c) == self.pos for c in cands):
                nb = self._next_literal_byte()
                if nb is not None:
                    out.add(nb)
            return bytes(sorted(out))
        if self.sub is None:
            self.sub = JsonMachine(root="object")
        return self.sub.allowed(budget - tail)

    def str_room(self) -> Optional[int]:
        """String capacity inside the live JSON hole (see JsonMachine);
        literal and choice parts are never string interiors."""
        if self.done:
            return None
        p = self.parts[self.idx]
        if (not isinstance(p, (bytes, bytearray)) and p[0] == "json"
                and self.sub is not None):
            return self.sub.str_room()
        return None

    def advance(self, b: int) -> None:
        assert not self.done, "advance after completion"
        p = self.parts[self.idx]
        if isinstance(p, (bytes, bytearray)):
            assert p[self.pos] == b, (bytes(p), self.pos, b)
            self.pos += 1
            if self.pos == len(p):
                self._next_part()
            return
        if p[0] == "choice":
            cands = self.cands if self.cands is not None else list(p[1])
            cont = [c for c in cands if len(c) > self.pos and c[self.pos] == b]
            if not cont and any(len(c) == self.pos for c in cands):
                # the byte belongs to the next literal: a candidate just
                # completed — close the choice and re-dispatch
                self._next_part()
                self.advance(b)
                return
            assert cont, f"byte {b!r} fits no choice candidate"
            self.cands = cont
            self.pos += 1
            if len(cont) == 1 and self.pos == len(cont[0]):
                # unambiguous full match with no longer sibling: finish now
                self._next_part()
            return
        if self.sub is None:
            self.sub = JsonMachine(root="object")
        self.sub.advance(b)
        if self.sub.done:
            self._next_part()

    def _next_part(self) -> None:
        self.idx += 1
        self.pos = 0
        self.cands = None
        self.sub = None
        if self.idx >= len(self.parts):
            self.done = True


def json_constraint() -> JsonMachine:
    """response_format json_object: any object from the emittable subset."""
    return JsonMachine(root="object")


def tool_call_constraint(
    tool_names: Sequence[str], parallel: bool = False
) -> TemplateMachine:
    """Constrain output to our canonical tool-call transcript:

    ``[{"name": "<choice>", "arguments": {...}}, ...]``

    ``parallel=True`` requires one call per provided tool, in order (the
    deterministic reading of ``parallel_tool_calls`` — the probe asks for
    "use both tools"); otherwise exactly one call with a model-chosen name.
    The server parses this JSON back into OpenAI ``tool_calls`` entries.
    """
    parts: list = []
    if parallel:
        parts.append(b"[")
        for i, name in enumerate(tool_names):
            if i:
                parts.append(b", ")
            parts.append(b'{"name": "' + name.encode() + b'", "arguments": ')
            parts.append(("json",))
            parts.append(b"}")
        parts.append(b"]")
    else:
        parts.append(b'[{"name": "')
        parts.append(("choice", [n.encode() for n in tool_names]))
        parts.append(b'", "arguments": ')
        parts.append(("json",))
        parts.append(b"}]")
    return TemplateMachine(parts)
