"""OpenAI-compatible HTTP server over the in-repo engine.

Endpoints:

- ``POST /v1/chat/completions`` — streaming (SSE) and non-streaming, with a
  ``metrics.server_ttft_ms`` extension carrying the engine's true first-token
  latency (the loadgen records it next to the client-side TTFT; the reference
  can only approximate TTFT client-side, SURVEY.md §7.3.5)
- ``GET /v1/models`` — model listing
- ``GET /healthz`` — readiness (KServe-style probe target)
- ``GET /metrics`` — Prometheus text format: token counters, duty cycle,
  queue depth, slot occupancy. This is the runtime leg of the telemetry
  fallback chain (analysis/telemetry.py) replacing DCGM.

Run: ``kvmini-tpu serve --model llama-tiny --port 8000`` (random weights) or
``--checkpoint /path/to/hf_dir`` for real ones.
"""

from __future__ import annotations

import argparse
import asyncio
import dataclasses
import json
import threading
import time
import uuid
from pathlib import Path
from typing import Any, Optional

from kserve_vllm_mini_tpu.runtime.engine import Engine, EngineConfig, GenRequest
from kserve_vllm_mini_tpu.runtime.tokenizer import Tokenizer, load_tokenizer


def build_engine(
    model: str = "llama-tiny",
    checkpoint: Optional[str] = None,
    tokenizer_path: Optional[str] = None,
    max_slots: int = 8,
    max_seq_len: int = 1024,
    topology: Optional[str] = None,
    seed: int = 0,
    quantization: str = "none",
    quant_mode: str = "dequant",   # how quantized matmuls contract
                                   # (ops/qmatmul.py QUANT_MODES):
                                   # "dequant" = cast-to-bf16 epilogue,
                                   # "w8a8" = int8 MXU contraction
    kv_cache_dtype: Optional[str] = None,
    decode_chunk: int = 1,
    prefill_chunk: Optional[int] = None,  # tokens per interleaved prefill
                                   # chunk (EngineConfig.prefill_chunk);
                                   # None = monolithic admission
    disagg: bool = False,          # disaggregated prefill/decode lanes
                                   # (EngineConfig.disagg; docs/
                                   # DISAGGREGATION.md)
    disagg_min_prompt: int = 0,    # prompts below this prefill colocated
    prefill_lane_devices: int = 0, # >0: split the device set into a
                                   # prefill submesh of this many devices
                                   # + a decode mesh over the rest
                                   # (parallel/mesh.lane_meshes); needs
                                   # disagg and no other mesh source
    drafter: Optional[str] = None,
    spec_tokens: int = 0,
    pp: int = 0,
    pp_microbatches: int = 1,
    scan_unroll: int = 1,
    mesh=None,
    prefix_cache: bool = False,
    kv_layout: str = "dense",
    kv_block_size: int = 64,
    kv_pool_blocks: Optional[int] = None,
    kv_host_tier_bytes: Optional[int] = None,
    lora_adapters: Optional[dict[str, str]] = None,  # name -> PEFT dir
    lora_demo: int = 0,       # N random adapters "demo-1..N" (bench/testing)
    lora_rank: int = 8,       # rank for the demo bank (PEFT dirs carry theirs)
    lora_slots: int = 4,      # runtime-load bank capacity (load_adapter)
    request_tracing: bool = True,  # phase-span recorder (docs/TRACING.md)
    trace_buffer: int = 4096,      # span ring-buffer capacity
    faults: Optional[str] = None,  # KVMINI_FAULTS-syntax injection config
    fault_seed: int = 0,           # deterministic fault triggers
    watchdog: bool = False,        # wedged-sweep watchdog (docs/RESILIENCE.md)
    default_deadline_s: Optional[float] = None,  # deadline-aware shedding
    econ_accelerator: Optional[str] = None,  # price the live economics
                                   # rail as this chip (docs/ECONOMICS.md);
                                   # None = TPU auto-detect, no rail on CPU
) -> tuple[Engine, Tokenizer, str]:
    """Construct (engine, tokenizer, model_name) from a preset or checkpoint.

    ``drafter`` is a preset name or checkpoint dir for the speculative-decode
    draft model (reference knob: runners/profiles/speculative-decoding.yaml);
    ``spec_tokens`` is the fused propose/verify depth per round (0 disables).
    ``mesh`` overrides topology/pp mesh construction — the multi-host path
    passes the process-spanning global mesh (parallel/distributed.py).
    """
    import os as _os

    import jax

    # honor JAX_PLATFORMS even when a site hook pre-imported jax pinned to
    # another platform (works pre-device-touch; same recipe as
    # tests/conftest.py — without this, `JAX_PLATFORMS=cpu kvmini-tpu serve`
    # still dials the TPU plugin)
    _plat = _os.environ.get("JAX_PLATFORMS")
    if _plat:
        jax.config.update("jax_platforms", _plat)

    from kserve_vllm_mini_tpu.models.config import get_config
    from kserve_vllm_mini_tpu.models.llama import init_params, init_params_quantized

    if quantization not in ("none", "int8", "int4", "int4-awq"):
        raise ValueError(
            f"unknown quantization {quantization!r}; known: none, int8, "
            "int4, int4-awq"
        )
    from kserve_vllm_mini_tpu.ops.qmatmul import validate_quant_mode

    quant_mode = validate_quant_mode(quant_mode or "dequant")
    if quantization == "none":
        # documented no-op: without quantized leaves there is nothing to
        # contract in int8, and folding w8a8 into cfg anyway would make
        # the headroom guard price a phantom activation-quant workspace
        quant_mode = "dequant"
    if kv_cache_dtype == "auto":
        # profile sentinel for "model default" (profiles/quantization/*.yaml
        # mirror the reference's 'auto'); the deploy layer drops it too
        kv_cache_dtype = None
    if kv_cache_dtype not in (None, "bfloat16", "float32", "float16", "int8"):
        raise ValueError(
            f"unsupported kv_cache_dtype {kv_cache_dtype!r}; "
            "known: auto, bfloat16, float32, float16, int8 (scaled)"
        )

    prefill_mesh = None
    if prefill_lane_devices:
        # disaggregated per-lane meshes (docs/DISAGGREGATION.md): a
        # disjoint prefill submesh + decode mesh over one device set —
        # mutually exclusive with every other mesh source, which would
        # otherwise claim the same devices twice
        if not disagg:
            raise ValueError("prefill_lane_devices requires disagg=True")
        if mesh is not None or (pp and pp > 1) or topology:
            raise ValueError(
                "prefill_lane_devices is its own mesh source; drop "
                "mesh/pp/topology (the lanes split the device set "
                "themselves via parallel/mesh.lane_meshes)"
            )
        from kserve_vllm_mini_tpu.parallel.mesh import lane_meshes

        prefill_mesh, mesh = lane_meshes(prefill_lane_devices)
    if mesh is not None and prefill_mesh is None:
        pass  # caller-provided (multi-host global mesh)
    elif prefill_mesh is not None:
        pass  # lane split above
    elif pp and pp > 1:
        # serving pipeline parallelism: layer-range stages over a pure-pp
        # mesh (parallel/serving_pp.py); needs exactly pp devices
        from kserve_vllm_mini_tpu.parallel.mesh import MeshSpec, make_mesh

        mesh = make_mesh(MeshSpec(pp=pp))
    elif topology:
        from kserve_vllm_mini_tpu.parallel.mesh import mesh_for_topology

        mesh = mesh_for_topology(topology)

    tok = load_tokenizer(tokenizer_path or checkpoint)
    if checkpoint:
        from kserve_vllm_mini_tpu.models.loader import load_hf_checkpoint

        # quantize-as-you-load: the bf16 8B tree must never fully exist on
        # device (VERDICT.md Weak #1 applies to real checkpoints too).
        # int4-awq is the exception: calibration needs the fp tree + one
        # eager forward (ops/awq.py memory note) — calibrate 8B off-chip.
        params, cfg = load_hf_checkpoint(
            checkpoint,
            quantize="none" if quantization == "int4-awq" else quantization,
        )
        if scan_unroll > 1:
            cfg = cfg.scaled(scan_unroll=scan_unroll)
        name = cfg.name
    else:
        cfg = get_config(model)
        if tok.vocab_size > cfg.vocab_size:
            cfg = cfg.scaled(vocab_size=tok.vocab_size)
        if scan_unroll > 1:
            cfg = cfg.scaled(scan_unroll=scan_unroll)
        # int8 presets init straight into int8 leaves: materializing the bf16
        # 8B tree first is itself an OOM on a 16 GB v5e (VERDICT.md Weak #1)
        if quantization in ("int8", "int4"):
            from functools import partial as _p

            init_fn = _p(init_params_quantized,
                         bits=4 if quantization == "int4" else 8)
        else:
            init_fn = init_params
        if mesh is not None:
            # init DIRECTLY into the mesh layout (out_shardings on the jitted
            # init) — a full single-device tree + device_put would OOM the
            # very deployments the mesh exists for
            from functools import partial as _partial

            from kserve_vllm_mini_tpu.parallel.sharding import param_shardings

            tree = jax.eval_shape(_partial(init_fn, cfg=cfg), jax.random.PRNGKey(seed))
            shardings = param_shardings(cfg, mesh, params=tree)
            params = jax.jit(
                _partial(init_fn, cfg=cfg), out_shardings=shardings
            )(jax.random.PRNGKey(seed))
        else:
            params = init_fn(jax.random.PRNGKey(seed), cfg)
        name = cfg.name
    if quant_mode != "dequant":
        # static trace-time knob: every execution path threads cfg, so the
        # config is where the mode rides (models/config.py quant_mode)
        cfg = cfg.scaled(quant_mode=quant_mode)
    if quantization == "int4-awq":
        # activation-aware calibration (ops/awq.py): stats from one eager
        # forward of the embedded corpus through the live tokenizer, then
        # per-layer alpha-searched scales; the fp tree is dropped after
        from kserve_vllm_mini_tpu.ops.awq import (
            calibration_tokens,
            quantize_params_awq,
        )

        cal = calibration_tokens(cfg.vocab_size, tok)
        params = quantize_params_awq(params, cfg, tokens=cal, bits=4)

    if mesh is not None and (checkpoint or quantization == "int4-awq"):
        from kserve_vllm_mini_tpu.parallel.sharding import shard_params

        params = shard_params(params, cfg, mesh)

    drafter_pair = None
    if drafter and spec_tokens > 0:
        import os

        if os.path.isdir(drafter):
            from kserve_vllm_mini_tpu.models.loader import load_hf_checkpoint

            # the drafter rides the target's quantization: spec decode and
            # quantization compose (the engine folds quant_mode into the
            # drafter cfg too, so w8a8 rounds contract the drafter int8)
            dparams, dcfg = load_hf_checkpoint(
                drafter,
                quantize="none" if quantization == "int4-awq" else quantization,
            )
        else:
            dcfg = get_config(drafter)
            if tok.vocab_size > dcfg.vocab_size:
                dcfg = dcfg.scaled(vocab_size=tok.vocab_size)
            if quantization in ("int8", "int4"):
                dparams = init_params_quantized(
                    jax.random.PRNGKey(seed + 1), dcfg,
                    bits=4 if quantization == "int4" else 8,
                )
            else:
                dparams = init_params(jax.random.PRNGKey(seed + 1), dcfg)
        if dcfg.vocab_size != cfg.vocab_size:
            raise ValueError(
                f"drafter vocab {dcfg.vocab_size} != target vocab "
                f"{cfg.vocab_size}; speculative verify compares token ids"
            )
        drafter_pair = (dparams, dcfg)

    # multi-LoRA bank: PEFT checkpoint adapters, or a random demo bank so
    # multi-adapter serving can be benchmarked without fine-tuned weights
    lora_bank = None
    if lora_adapters:
        from kserve_vllm_mini_tpu.ops.lora import (
            LORA_TARGETS_ALL,
            install_adapter,
            load_peft_adapter,
            zero_lora_bank,
        )

        loaded = {
            nm: load_peft_adapter(path, cfg, targets=LORA_TARGETS_ALL)
            for nm, path in lora_adapters.items()
        }
        ranks = {
            # max over EVERY target: PEFT rank_pattern adapters carry
            # per-target ranks, and the bank must fit the largest (the
            # engine hot-swap path computes in_rank the same way)
            nm: max(a.shape[-1] for a, _b in ad.values())
            for nm, ad in loaded.items()
        }
        # mixed ranks share one bank at the MAX rank: zero-padding a
        # lower-rank adapter's factors is exact (the padding contributes
        # nothing to A @ B), same mechanism hot-swap growth uses
        from kserve_vllm_mini_tpu.ops.lora import pad_adapter_rank

        rank = max(ranks.values())
        targets = sorted({t for ad in loaded.values() for t in ad})
        bank = zero_lora_bank(cfg, len(loaded), rank, targets=targets,
                              dtype=cfg.jnp_dtype)
        names: dict[str, int] = {}
        for i, (nm, ad) in enumerate(sorted(loaded.items()), start=1):
            bank = install_adapter(bank, i, pad_adapter_rank(ad, rank))
            names[nm] = i
        bank["names"] = names
        lora_bank = bank
    elif lora_demo:
        from kserve_vllm_mini_tpu.ops.lora import init_lora_bank

        lora_bank = init_lora_bank(
            jax.random.PRNGKey(seed + 1), cfg, lora_demo, rank=lora_rank,
            dtype=cfg.jnp_dtype,
        )
        lora_bank["names"] = {f"demo-{i}": i for i in range(1, lora_demo + 1)}

    ecfg = EngineConfig(
        max_slots=max_slots,
        max_seq_len=min(max_seq_len, cfg.max_seq_len),
        max_prefill_len=min(max_seq_len, cfg.max_seq_len) // 2,
        seed=seed,
        kv_cache_dtype=kv_cache_dtype,
        quant_mode=quant_mode,
        decode_chunk=decode_chunk,
        prefill_chunk=prefill_chunk,
        disagg=disagg,
        disagg_min_prompt=disagg_min_prompt,
        spec_tokens=spec_tokens if drafter_pair is not None else 0,
        pp_microbatches=pp_microbatches,
        prefix_cache=prefix_cache,
        kv_layout=kv_layout,
        kv_block_size=kv_block_size,
        kv_pool_blocks=kv_pool_blocks,
        kv_host_tier_bytes=kv_host_tier_bytes,
        lora_slots=lora_slots,
        request_tracing=request_tracing,
        trace_buffer=trace_buffer,
        faults=faults,
        fault_seed=fault_seed,
        watchdog=watchdog,
        default_deadline_s=default_deadline_s,
        econ_accelerator=econ_accelerator,
    )
    engine = Engine(
        params, cfg, ecfg, mesh=mesh, pad_id=tok.pad_id, drafter=drafter_pair,
        lora=lora_bank, prefill_mesh=prefill_mesh,
    )
    return engine, tok, name


def make_app(engine: Engine, tok: Tokenizer, model_name: str,
             multihost: bool = False, alive_check=None,
             allow_fault_injection: bool = False):
    # default health gate: the engine's own scheduler liveness — a crashed
    # _loop drops _running and the frontend must refuse, not enqueue
    # forever. The multihost primary overrides with its driver thread's
    # liveness (the engine thread never starts in that mode).
    if alive_check is None:
        alive_check = lambda: engine._running  # noqa: E731
    from aiohttp import web

    started = time.time()

    def _shed_response(message: str) -> "web.Response":
        """ONE wire shape for every shed site (docs/RESILIENCE.md): the
        at-the-door 429, the non-streaming queue-expiry conversion, and
        the streaming first-event peek all speak this, so the loadgen's
        retry contract can never fork between them."""
        return web.json_response(
            {"error": {
                "message": message,
                "type": "overloaded_error",
                "code": "request_shed",
            }},
            status=429,
            headers={"Retry-After": str(max(
                1, int(engine.estimate_wait_s() + 0.999)
            ))},
        )

    def _messages_to_prompt(messages: list[dict[str, Any]]) -> str:
        parts = []
        for m in messages:
            parts.append(f"{m.get('role', 'user')}: {m.get('content', '')}")
        parts.append("assistant:")
        return "\n".join(parts)

    def _stable_len(text: str) -> int:
        """Chars of ``text`` that no future token can revise: a TRAILING
        run of U+FFFD is an incomplete multibyte sequence still being
        assembled (byte-level tokens split UTF-8 chars across tokens) and
        must not be emitted — the next token may resolve it to the real
        char. Interior replacements are final (later bytes cannot rewrite
        already-decoded output) and flush normally; a genuinely invalid
        trailing sequence flushes in the done-event tail."""
        n = len(text)
        while n > 0 and text[n - 1] == "�":
            n -= 1
        return n

    def _first_stop_hit(text: str, stops: list[str]) -> Optional[int]:
        """Character index of the earliest stop-sequence occurrence."""
        best: Optional[int] = None
        for s in stops:
            i = text.find(s)
            if i >= 0 and (best is None or i < best):
                best = i
        return best

    def _lp_entry(token_id: int, lp_info, top_n: int) -> dict[str, Any]:
        """OpenAI logprobs.content entry for one emitted token. -inf
        alternatives (grammar-masked bytes) are dropped: json.dumps would
        render them as '-Infinity', which is not RFC-valid JSON."""
        import math

        text = tok.decode([token_id])
        lp, top = lp_info
        return {
            "token": text,
            "logprob": lp,
            "bytes": list(text.encode()),
            "top_logprobs": [
                {"token": tok.decode([tid]), "logprob": tlp,
                 "bytes": list(tok.decode([tid]).encode())}
                for tid, tlp in top[:top_n]
                if math.isfinite(tlp)
            ],
        }

    # HF-vocab grammar table: one precomputation per server (token id ->
    # byte expansion + single-byte/string-safe indexes), built EAGERLY at
    # app construction — on the request path it would block the event loop
    # for the full ~vocab-size expansion. False = tokenizer can't support
    # grammar masking (the reason is appended); None = ByteTokenizer server
    # (no table needed).
    from kserve_vllm_mini_tpu.runtime.tokenizer import ByteTokenizer

    _hf_vocab_cache: list[Any] = [None]
    if not isinstance(tok, ByteTokenizer):
        from kserve_vllm_mini_tpu.runtime.token_grammar import (
            HFVocabTable,
            token_bytes_table,
        )

        try:
            _hf_vocab_cache[0] = HFVocabTable(token_bytes_table(tok))
        except Exception as e:  # noqa: BLE001 — degrade to honest reject
            _hf_vocab_cache[0] = False
            _hf_vocab_cache.append(str(e))

    def _wrap_machine(machine, tool_names=()):
        """Lift a byte automaton to the engine's token protocol for this
        server's tokenizer (runtime/token_grammar.py): identity byte
        mapping for the ByteTokenizer, byte-expansion table for real HF
        vocabularies. Returns (wrapped, err)."""
        from kserve_vllm_mini_tpu.runtime.token_grammar import (
            ByteTokenMachine,
            HFTokenMachine,
        )

        if isinstance(tok, ByteTokenizer):
            return ByteTokenMachine(machine, engine.cfg.vocab_size), None
        if _hf_vocab_cache[0] is False:
            return None, (
                "tools/json_mode unavailable for this tokenizer: "
                f"{_hf_vocab_cache[-1]}"
            )
        # a tool-name byte with no single-token representation would leave
        # the template grammar's forced path unmaskable (deadlock) — reject
        # the request up front instead
        missing = sorted({
            c for n in tool_names for c in n.encode()
            if c not in _hf_vocab_cache[0].single
        })
        if missing:
            return None, (
                "tool name characters lack single-token representations in "
                f"this tokenizer: {[chr(c) for c in missing]!r}"
            )
        try:
            return HFTokenMachine(
                machine, _hf_vocab_cache[0], engine.cfg.vocab_size
            ), None
        except ValueError as e:
            return None, str(e)

    def _build_constraint(body: dict[str, Any], max_tokens: int):
        """Constraint machine + tool flag from the request, or an error str.

        The byte automata (runtime/constrain.py) define the grammar; the
        token_grammar adapter maps it onto this server's vocabulary, so
        json_mode/tools work for the ByteTokenizer AND real HF checkpoints
        (VERDICT round-3 weak #3)."""
        from kserve_vllm_mini_tpu.runtime.constrain import (
            json_constraint,
            tool_call_constraint,
        )

        import re

        tools = body.get("tools") or []
        tool_choice = body.get("tool_choice", "auto" if tools else "none")
        wants_tools = bool(tools) and tool_choice != "none"
        rf = (body.get("response_format") or {}).get("type")
        if rf not in (None, "text", "json_object"):
            # e.g. json_schema: unsupported — reject rather than return
            # unconstrained output under a structured-output contract
            return None, False, f"response_format type {rf!r} is not supported"
        wants_json = rf == "json_object"
        if not (wants_tools or wants_json):
            return None, False, None
        if multihost:
            # constraint masks are host-built per token; the lockstep
            # channel does not carry them yet (runtime/multihost.py v1)
            return None, False, (
                "tools/json_mode are not yet supported in multi-host serving"
            )
        if wants_tools:
            names = [
                t.get("function", {}).get("name", "")
                for t in tools if t.get("type") == "function"
            ]
            names = [n for n in names if n]
            bad = [n for n in names if not re.fullmatch(r"[a-zA-Z0-9_-]{1,64}", n)]
            if bad:
                # names are interpolated into the byte-template grammar; a
                # quote or backslash would break the emitted JSON (OpenAI
                # enforces this same charset)
                return None, False, f"invalid tool name(s): {bad!r}"
            if isinstance(tool_choice, dict):  # {"type":"function","function":{"name":...}}
                forced = tool_choice.get("function", {}).get("name")
                if forced not in names:
                    return None, False, (
                        f"tool_choice names {forced!r} which is not in tools"
                    )
                names = [forced]
            if not names:
                return None, False, "tools given but no function names"
            machine = tool_call_constraint(
                names, parallel=bool(body.get("parallel_tool_calls")) and len(names) > 1
            )
        else:
            machine = json_constraint()
        if max_tokens < machine.min_close():
            return None, False, (
                f"max_tokens={max_tokens} cannot fit the constrained format "
                f"(needs >= {machine.min_close()})"
            )
        wrapped, werr = _wrap_machine(
            machine, tool_names=names if wants_tools else ()
        )
        if werr:
            return None, False, werr
        return wrapped, wants_tools, None

    def _constrained_text(ids: list[int]) -> str:
        """Constrained output must be reconstructed from the SAME byte
        expansions the automaton validated: ``tok.decode`` may join tokens
        with separators (WordLevel) or apply cleanup that desyncs the text
        from the grammar-approved byte string. ByteTokenizer servers have
        no table — their decode IS the byte expansion."""
        if _hf_vocab_cache[0]:
            table = _hf_vocab_cache[0].table
            raw = b"".join(
                (table[t] or b"") if t < len(table) else b"" for t in ids
            )
            return raw.decode("utf-8", errors="replace")
        return tok.decode(ids)

    def _tool_calls_from_text(text: str) -> Optional[list[dict[str, Any]]]:
        """Parse our canonical constrained transcript back into OpenAI
        tool_calls entries."""
        try:
            calls = json.loads(text)
        except json.JSONDecodeError:  # kvmini: workload-ok — unconstrained
            # runs may emit free text; the response then carries `content`
            # instead of tool_calls, which IS the surfaced outcome
            return None
        if not isinstance(calls, list):
            return None
        out = []
        for i, c in enumerate(calls):
            if not isinstance(c, dict) or "name" not in c:
                return None
            out.append({
                "id": f"call_{uuid.uuid4().hex[:8]}_{i}",
                "type": "function",
                "function": {
                    "name": c["name"],
                    "arguments": json.dumps(c.get("arguments", {})),
                },
            })
        return out

    async def chat(request: "web.Request"):
        try:
            body = await request.json()
        except json.JSONDecodeError:
            return web.json_response({"error": {"message": "invalid JSON body"}}, status=400)
        messages = body.get("messages")
        if not isinstance(messages, list) or not messages:
            return web.json_response(
                {"error": {"message": "'messages' must be a non-empty list"}}, status=400
            )
        if not alive_check():
            # a dead scheduler must refuse, not enqueue forever — the load
            # balancer sees 503 here and on /healthz and rotates the replica
            return web.json_response(
                {"error": {"message": "scheduler is not running"}}, status=503
            )
        # Deadline-aware admission (docs/RESILIENCE.md): the client's
        # deadline_ms (body field or x-request-deadline-ms header) or the
        # server default. A request whose estimated COMPLETION time —
        # queue depth x rolling service time — already exceeds its
        # deadline is shed HERE with 429 + Retry-After instead of timing
        # out after burning decode steps on work nobody can use.
        raw_deadline = body.get("deadline_ms")
        if raw_deadline is None:
            raw_deadline = request.headers.get("x-request-deadline-ms")
        deadline_s: Optional[float] = None
        if raw_deadline is not None:
            try:
                deadline_s = float(raw_deadline) / 1000.0
            except (TypeError, ValueError):
                return web.json_response(
                    {"error": {"message": "deadline_ms must be a number"}},
                    status=400,
                )
            if deadline_s <= 0:
                return web.json_response(
                    {"error": {"message": "deadline_ms must be > 0"}},
                    status=400,
                )
        if deadline_s is None:
            deadline_s = engine.ecfg.default_deadline_s
        if deadline_s is not None:
            est = engine.estimate_wait_s()
            if est > deadline_s:
                engine.count_shed()
                return _shed_response(
                    f"shed: estimated completion {est:.2f}s exceeds "
                    f"the {deadline_s:.2f}s deadline at current load"
                )
        max_tokens = int(body.get("max_tokens", 64))
        machine, wants_tools, err = _build_constraint(body, max_tokens)
        if err:
            return web.json_response({"error": {"message": err}}, status=400)
        want_logprobs = bool(body.get("logprobs", False))
        top_lp = int(body.get("top_logprobs", 0) or 0)
        if top_lp < 0:
            return web.json_response(
                {"error": {"message": "top_logprobs must be >= 0"}}, status=400
            )
        top_lp = min(top_lp, 5)
        # OpenAI sampling knobs the reference's loadgen sends to vLLM
        # (reference scripts/loadtest.py:260-342): presence/frequency
        # penalties and n/best_of fan-out. The in-repo engine must honor
        # what the load generator exercises — silently dropping them would
        # measure a different workload than the one requested.
        try:
            pres = float(body.get("presence_penalty", 0.0) or 0.0)
            freq = float(body.get("frequency_penalty", 0.0) or 0.0)
        except (TypeError, ValueError):
            return web.json_response(
                {"error": {"message": "penalties must be numbers"}}, status=400
            )
        if not (-2.0 <= pres <= 2.0 and -2.0 <= freq <= 2.0):
            return web.json_response(
                {"error": {"message":
                           "presence_penalty/frequency_penalty must be in "
                           "[-2, 2]"}}, status=400
            )
        try:
            _n_raw = body.get("n")
            n_choices = 1 if _n_raw is None else int(_n_raw)
            _bo_raw = body.get("best_of")
            fanout = n_choices if _bo_raw is None else int(_bo_raw)
        except (TypeError, ValueError):
            return web.json_response(
                {"error": {"message": "n/best_of must be integers"}}, status=400
            )
        if n_choices < 1 or fanout < n_choices:
            return web.json_response(
                {"error": {"message": "need 1 <= n <= best_of"}}, status=400
            )
        if fanout > engine.ecfg.max_slots:
            return web.json_response(
                {"error": {"message":
                           f"best_of={fanout} exceeds the engine's "
                           f"{engine.ecfg.max_slots} slots"}}, status=400
            )
        if body.get("stream", False) and fanout > n_choices:
            # OpenAI semantics: best_of ranking needs every candidate
            # complete before any can stream
            return web.json_response(
                {"error": {"message": "best_of > n cannot stream"}}, status=400
            )
        prompt = _messages_to_prompt(messages)
        prompt_ids = tok.encode(prompt)
        # multi-LoRA routing (vLLM convention): "model" names either the
        # base model or a loaded adapter. The loadgen's placeholder
        # "default" always means the base, and with NO adapters loaded
        # unknown names keep the legacy ignore-the-field behavior (every
        # pre-LoRA profile sends "default"); once adapters exist, a name
        # that matches nothing 404s — silently serving the base where a
        # fine-tune was requested would be a measurement lie
        req_model = body.get("model")
        adapter = None
        adapter_names = getattr(engine, "_lora_names", {})
        if (
            adapter_names
            and req_model
            and req_model not in (model_name, "default")
        ):
            if req_model in adapter_names:
                adapter = req_model
            else:
                return web.json_response(
                    {"error": {
                        "message": (
                            f"model {req_model!r} not found; available: "
                            f"{[model_name, *sorted(adapter_names)]}"
                        ),
                        "type": "invalid_request_error",
                        "code": "model_not_found",
                    }},
                    status=404,
                )
        # best_of ranking needs per-token logprobs even when the client did
        # not ask for them (they are stripped from the response)
        # OpenAI stop sequences (vLLM honors them; the loadgen sends them
        # when a profile sets params.stop — a dropped knob measures a
        # different workload). Detection is server-side over decoded text;
        # a hit cancels the engine slot (Engine.cancel) so the remaining
        # budget isn't decoded into the batch. Grammar-constrained and
        # tool requests ignore stop: the grammar defines completion.
        stop_raw = body.get("stop")
        stops: list[str] = []
        if stop_raw is not None and machine is None and not wants_tools:
            if isinstance(stop_raw, str):
                stops = [stop_raw] if stop_raw else []
            elif isinstance(stop_raw, list) and all(
                isinstance(s, str) for s in stop_raw
            ):
                stops = [s for s in stop_raw if s]
            else:
                return web.json_response(
                    {"error": {"message":
                               "'stop' must be a string or list of strings"}},
                    status=400,
                )
            if len(stops) > 4:
                return web.json_response(
                    {"error": {"message": "'stop' supports at most 4 sequences"}},
                    status=400,
                )
        max_stop_len = max((len(s) for s in stops), default=0)

        # W3C trace context: parent the engine's phase spans under the
        # client's http.request span so /traces joins the loadgen's trace
        # by trace_id (docs/TRACING.md). Malformed headers are ignored —
        # the engine mints a local trace id instead.
        from kserve_vllm_mini_tpu.runtime.tracing import parse_traceparent

        trace_ctx = parse_traceparent(request.headers.get("traceparent"))
        rank_lp = fanout > n_choices
        req = GenRequest(
            prompt_tokens=prompt_ids or [tok.bos_id],
            max_new_tokens=max_tokens,
            temperature=float(body.get("temperature", 0.0)),
            top_k=int(body.get("top_k", 0)),
            top_p=float(body.get("top_p", 1.0)),
            presence_penalty=pres,
            frequency_penalty=freq,
            eos_id=None if machine is not None else tok.eos_id,
            logprobs=want_logprobs or rank_lp,
            top_logprobs=top_lp,
            constraint=machine,
            adapter=adapter,
            trace_id=trace_ctx[0] if trace_ctx else None,
            parent_span_id=trace_ctx[1] if trace_ctx else None,
            deadline_s=deadline_s,
        )
        all_reqs = [req]
        for _ in range(fanout - 1):
            # each candidate needs its OWN grammar machine (stateful) and
            # its own prompt list (submit rebinds it on truncation)
            m_i = None
            if machine is not None:
                m_i, _, err_i = _build_constraint(body, max_tokens)
                if err_i:  # cannot happen if the first build succeeded
                    return web.json_response(
                        {"error": {"message": err_i}}, status=400
                    )
            all_reqs.append(dataclasses.replace(
                req,
                prompt_tokens=list(req.prompt_tokens),
                request_id=uuid.uuid4().hex[:16],
                constraint=m_i,
            ))
        handles = [engine.submit(r) for r in all_reqs]
        handle = handles[0]
        rid = f"chatcmpl-{uuid.uuid4().hex[:20]}"
        created = int(time.time())
        # OpenAI semantics: echo the served model — the adapter name when
        # the request was routed to one, else the base
        resp_model = adapter or model_name
        loop = asyncio.get_running_loop()

        async def next_event():
            return await loop.run_in_executor(None, handle.events.get)

        if not body.get("stream", False):
            def collect_sync(h: Any) -> tuple:
                """Drain one candidate: (token ids, logprob entries,
                cumulative chosen-token logprob, done info, stop-cut char
                index or None). On a stop-sequence hit the engine slot is
                cancelled — the drain continues (events already queued
                still arrive) but the budget stops burning device steps.
                Runs in a DEDICATED thread per candidate so every
                candidate's stop detection is live concurrently — a
                sequential drain would not cancel candidate k's hit until
                candidates 0..k-1 finished their whole budgets."""
                ids: list[int] = []
                entries: list[dict[str, Any]] = []
                lp_sum = 0.0
                stop_cut: Optional[int] = None
                while True:
                    kind, *rest = h.events.get()
                    if kind == "token":
                        if stop_cut is not None:
                            # surplus between the stop hit and the
                            # scheduler processing the cancel: dropped
                            # everywhere (ids/lp_sum/usage), or best_of
                            # ranking would depend on scheduler timing
                            continue
                        ids.append(rest[0])
                        if len(rest) > 2 and rest[2] is not None:
                            lp_sum += rest[2][0]
                            if want_logprobs:
                                entries.append(
                                    _lp_entry(rest[0], rest[2], top_lp)
                                )
                        if stops:
                            hit = _first_stop_hit(tok.decode(ids), stops)
                            if hit is not None:
                                stop_cut = hit
                                engine.cancel(h)
                    else:
                        return ids, entries, lp_sum, rest[0], stop_cut

            async def collect_all() -> list:
                futs = [loop.create_future() for _ in handles]

                def worker(h: Any, fut: Any) -> None:
                    try:
                        res = collect_sync(h)
                    except BaseException as e:  # noqa: BLE001 — must reach
                        # the awaiting coroutine, not die in the thread
                        loop.call_soon_threadsafe(fut.set_exception, e)
                        return
                    loop.call_soon_threadsafe(fut.set_result, res)

                for h, f in zip(handles, futs):
                    threading.Thread(
                        target=worker, args=(h, f), daemon=True
                    ).start()
                return list(await asyncio.gather(*futs))

            collected = await collect_all()
            for _ids, _e, _lp, info, _cut in collected:
                if info.get("finish_reason") == "error":
                    # e.g. the constrained grammar cannot close inside the
                    # KV window — surface the engine's message, don't 200 it
                    return web.json_response(
                        {"error": {"message": info.get("error", "engine error")}},
                        status=400,
                    )
                if info.get("finish_reason") == "shed":
                    # deadline expired while queued (docs/RESILIENCE.md):
                    # same wire contract as the at-the-door shed — a 200
                    # with zero tokens would count as a healthy request
                    return _shed_response(info.get("error", "request shed"))
            # usage counts EVERY candidate actually generated (OpenAI/vLLM
            # accounting): best_of work that ranking discards was still
            # decoded, and a benchmark computing tokens/sec from usage must
            # see the served work, not the kept subset
            completion_tokens = sum(len(c[0]) for c in collected)
            if fanout > n_choices:
                # best_of: keep the n candidates with the highest log
                # probability PER TOKEN (OpenAI's documented ranking —
                # length-normalized, so a short early-EOS candidate cannot
                # beat a longer, better-average one on raw sum; stable sort
                # keeps submission order on ties)
                collected = sorted(
                    collected, key=lambda c: -c[2] / max(len(c[0]), 1)
                )[:n_choices]
            choices: list[dict[str, Any]] = []
            for idx, (out_ids, lp_entries, _lp_sum, info, stop_cut) in \
                    enumerate(collected):
                text = (
                    _constrained_text(out_ids) if machine is not None
                    else tok.decode(out_ids)
                )
                finish = info.get("finish_reason", "stop")
                if stop_cut is not None:
                    # OpenAI semantics: output ends BEFORE the matched stop
                    # sequence (the match itself is not returned); surfaced
                    # to the client via finish_reason
                    text = text[:stop_cut]
                    finish = "stop"
                message: dict[str, Any] = {"role": "assistant", "content": text}
                if wants_tools:
                    calls = _tool_calls_from_text(text)
                    if calls is not None:
                        message = {"role": "assistant", "content": None,
                                   "tool_calls": calls}
                        finish = "tool_calls"
                choice: dict[str, Any] = {
                    "index": idx,
                    "message": message,
                    "finish_reason": finish,
                }
                if want_logprobs:
                    choice["logprobs"] = {"content": lp_entries}
                choices.append(choice)
            info0 = collected[0][3]  # noqa: E501 — done info of choice 0
            return web.json_response(
                {
                    "id": rid,
                    "object": "chat.completion",
                    "created": created,
                    "model": resp_model,
                    "choices": choices,
                    "usage": {
                        "prompt_tokens": len(prompt_ids),
                        "completion_tokens": completion_tokens,
                        "total_tokens": len(prompt_ids) + completion_tokens,
                    },
                    "metrics": {
                        "server_ttft_ms": handle.server_ttft_ms,
                        "truncated": bool(info0.get("truncated", False)),
                        "truncated_tokens": int(info0.get("truncated_tokens", 0)),
                    },
                }
            )

        # Streaming (n==1 included — ONE emitter for every n, so chunk
        # shape can never drift between a single- and a multi-choice
        # path): merge the candidates' event queues and tag every chunk
        # with its choice index — the OpenAI interleaved-stream shape.
        # Identical submit-time parameters mean a submit rejection hits
        # every candidate, so peeking choice 0 covers the
        # 400-before-SSE case (a 400 is impossible once stream headers
        # have gone out).
        first_event = await next_event()
        if (
            first_event[0] == "done"
            and first_event[1].get("finish_reason") == "error"
        ):
            return web.json_response(
                {"error": {"message":
                           first_event[1].get("error", "engine error")}},
                status=400,
            )
        if (
            first_event[0] == "done"
            and first_event[1].get("finish_reason") == "shed"
        ):
            # engine-side deadline shed lands BEFORE any token, so the
            # peek catches it while a 429 can still go out (same
            # contract as the non-streaming path)
            return _shed_response(first_event[1].get("error", "request shed"))
        merged: asyncio.Queue = asyncio.Queue()

        # DEDICATED daemon threads, not the shared default executor: a
        # pump blocks on events.get for its candidate's whole lifetime,
        # and a few concurrent n=8 streams would otherwise pin every
        # worker of the shared pool and stall unrelated handlers. Each
        # thread exits at its candidate's 'done'; on client disconnect
        # the engine still finishes the slot, so the thread is bounded.
        def pump(idx: int, h: Any) -> None:
            while True:
                evt = h.events.get()
                loop.call_soon_threadsafe(merged.put_nowait, (idx, evt))
                if evt[0] == "done":
                    return

        # choice 0's first event was consumed by the peek — replay it,
        # then pump every queue (pump 0 resumes from its second event;
        # if the peeked event already WAS its 'done', there is nothing
        # left to pump for it)
        await merged.put((0, tuple(first_event)))
        for _i, _h in enumerate(handles):
            if _i > 0 or first_event[0] != "done":
                threading.Thread(
                    target=pump, args=(_i, _h), daemon=True
                ).start()

        resp = web.StreamResponse(
            status=200,
            headers={"Content-Type": "text/event-stream",
                     "Cache-Control": "no-cache"},
        )
        await resp.prepare(request)
        # sse_disconnect injection (docs/RESILIENCE.md): when the point
        # fires for this stream, drop the transport after after_tokens
        # streamed chunks — a mid-stream network fault, exercised by the
        # local chaos harness. None on every un-armed server.
        sse_cut: Optional[int] = None
        cut_spec = engine.check_fault("sse_disconnect")
        if cut_spec is not None:
            sse_cut = max(int(cut_spec.after_tokens), 1)
        sse_streamed = 0
        per_out = [0] * len(handles)
        per_first = [False] * len(handles)
        per_tools: list[list[int]] = [[] for _ in handles]
        # Incremental detokenization state: the authoritative text is the
        # FULL re-decode of the ids so far (per-token decode([id]) loses
        # HF-tokenizer spacing — 'Ġn' decodes alone as 'n' but in context
        # as ' n' — so piece concatenation would drift from the
        # non-streaming text). per_sent tracks chars already emitted; with
        # stop sequences a tail of (max stop length - 1) chars is held
        # back so a stop split across tokens is never partially emitted.
        # Full re-decode is O(n²) tokens per request — bounded by
        # max_seq_len (tens of ms of host work at 2k tokens, in the event
        # loop, far under the device step time it overlaps); a trailing-
        # window decode would need per-tokenizer prefix-artifact handling
        # for chars the window boundary perturbs.
        per_ids: list[list[int]] = [[] for _ in handles]
        per_full = [""] * len(handles)
        per_sent = [0] * len(handles)
        per_stopped = [False] * len(handles)
        # logprob entries of tokens whose text is currently held back:
        # carried to the next chunk that actually emits for the choice, so
        # the stream's entry count matches the non-streaming response
        per_lp_pending: list[list[dict[str, Any]]] = [[] for _ in handles]
        done_count = 0
        try:
            while done_count < len(handles):
                idx, (kind, *rest) = await merged.get()
                if kind == "token":
                    if per_stopped[idx]:
                        # surplus decoded between the stop hit and the
                        # scheduler processing the cancel: swallowed AND
                        # uncounted, so streamed usage matches the
                        # non-streaming accounting deterministically
                        continue
                    per_out[idx] += 1
                    if wants_tools:
                        per_tools[idx].append(rest[0])
                        if not per_first[idx]:
                            await resp.write((
                                "data: " + json.dumps({
                                    "id": rid,
                                    "object": "chat.completion.chunk",
                                    "created": created,
                                    "model": resp_model,
                                    "choices": [{"index": idx, "delta": {},
                                                 "finish_reason": None}],
                                    "metrics": {"server_ttft_ms":
                                                handles[idx].server_ttft_ms},
                                }) + "\n\n").encode())
                            per_first[idx] = True
                        continue
                    if want_logprobs and len(rest) > 2 and rest[2] is not None:
                        # recorded BEFORE any hold-back: a held token's
                        # entry rides the next emitted chunk
                        per_lp_pending[idx].append(
                            _lp_entry(rest[0], rest[2], top_lp)
                        )
                    if machine is not None:
                        # the byte machine's transcript is byte-exact; stop
                        # is disabled for constrained requests at parse time
                        piece = _constrained_text([rest[0]])
                    else:
                        per_ids[idx].append(rest[0])
                        per_full[idx] = tok.decode(per_ids[idx])
                        hit = (_first_stop_hit(per_full[idx], stops)
                               if stops else None)
                        if hit is not None:
                            per_stopped[idx] = True
                            engine.cancel(handles[idx])
                            cut = max(hit, per_sent[idx])
                            piece = per_full[idx][per_sent[idx]:cut]
                            per_sent[idx] = cut
                        else:
                            holdback = max_stop_len - 1 if stops else 0
                            safe = min(
                                len(per_full[idx]) - holdback,
                                _stable_len(per_full[idx]),
                            )
                            if safe > per_sent[idx]:
                                piece = per_full[idx][per_sent[idx]:safe]
                                per_sent[idx] = safe
                            else:
                                piece = ""
                        if not piece and per_first[idx]:
                            continue  # held back; metrics already sent
                    chunk_choice = {
                        "index": idx, "delta": {"content": piece},
                        "finish_reason": None,
                    }
                    if want_logprobs and per_lp_pending[idx]:
                        chunk_choice["logprobs"] = {
                            "content": per_lp_pending[idx]
                        }
                        per_lp_pending[idx] = []
                    evt = {
                        "id": rid, "object": "chat.completion.chunk",
                        "created": created, "model": resp_model,
                        "choices": [chunk_choice],
                    }
                    if not per_first[idx]:
                        evt["metrics"] = {
                            "server_ttft_ms": handles[idx].server_ttft_ms
                        }
                        per_first[idx] = True
                    await resp.write(f"data: {json.dumps(evt)}\n\n".encode())
                    sse_streamed += 1
                    if sse_cut is not None and sse_streamed >= sse_cut:
                        # injected mid-stream disconnect: drop the
                        # transport the way a network fault would, then
                        # run the normal client-gone cleanup below
                        if request.transport is not None:
                            request.transport.close()
                        raise ConnectionResetError("injected sse_disconnect")
                else:
                    done_count += 1
                    info = rest[0]
                    final_delta: dict[str, Any] = {}
                    finish = info.get("finish_reason", "stop")
                    if per_stopped[idx]:
                        finish = "stop"
                    elif machine is None:
                        # flush the held-back tail (stop never matched) /
                        # any decode-revision residue
                        tail = per_full[idx][per_sent[idx]:]
                        if tail:
                            final_delta = {"content": tail}
                            per_sent[idx] = len(per_full[idx])
                    if wants_tools:
                        calls = _tool_calls_from_text(
                            _constrained_text(per_tools[idx])
                        )
                        if calls is not None:
                            final_delta = {"tool_calls": calls}
                            finish = "tool_calls"
                    final_choice: dict[str, Any] = {
                        "index": idx, "delta": final_delta,
                        "finish_reason": finish,
                    }
                    if want_logprobs and per_lp_pending[idx]:
                        # entries for tokens whose text only flushes here
                        final_choice["logprobs"] = {
                            "content": per_lp_pending[idx]
                        }
                        per_lp_pending[idx] = []
                    final = {
                        "id": rid, "object": "chat.completion.chunk",
                        "created": created, "model": resp_model,
                        "choices": [final_choice],
                        # same metrics block as the single-stream final
                        # chunk: the loadgen must not lose truncation /
                        # server-TTFT telemetry just because n>1
                        "metrics": {
                            "server_ttft_ms": handles[idx].server_ttft_ms,
                            "truncated": bool(info.get("truncated", False)),
                            "truncated_tokens": int(
                                info.get("truncated_tokens", 0)
                            ),
                        },
                    }
                    if done_count == len(handles):
                        total_out = sum(per_out)
                        final["usage"] = {
                            "prompt_tokens": len(prompt_ids),
                            "completion_tokens": total_out,
                            "total_tokens": len(prompt_ids) + total_out,
                        }
                    await resp.write(f"data: {json.dumps(final)}\n\n".encode())
            await resp.write(b"data: [DONE]\n\n")
        except (ConnectionResetError, asyncio.CancelledError):
            # client went away mid-stream: cancel every still-running
            # candidate — nobody is reading, and n big-budget slots would
            # otherwise burn decode steps and block admissions until their
            # budgets ran out
            for h in handles:
                engine.cancel(h, reason="cancelled")
        try:
            await resp.write_eof()
        except ConnectionResetError:  # kvmini: workload-ok — client already
            pass                      # gone; the cancel above surfaced it
        return resp

    async def models(_request):
        data = [
            {"id": model_name, "object": "model", "created": int(started),
             "owned_by": "kvmini-tpu"}
        ]
        for name in sorted(getattr(engine, "_lora_names", {})):
            data.append(
                {"id": name, "object": "model", "created": int(started),
                 "owned_by": "kvmini-tpu", "parent": model_name,
                 "root": model_name}
            )
        return web.json_response({"object": "list", "data": data})

    async def healthz(_request):
        if not alive_check():
            return web.json_response(
                {"status": "unhealthy", "reason": "scheduler not running"},
                status=503,
            )
        s = engine.snapshot_stats()
        return web.json_response({
            "status": "ok",
            "uptime_s": time.time() - started,
            # probe-visible pipeline state (docs/DECODE_PIPELINE.md): lets a
            # readiness/debug probe distinguish "idle" from "pipelining"
            # without parsing the Prometheus exposition
            "decode_pipeline": {
                "dispatch_depth": s["dispatch_depth"],
                "inflight_sweeps": s["inflight_sweeps"],
            },
        })

    profile_lock = threading.Lock()
    profile_root = Path("runs").resolve()

    async def profile(request: "web.Request"):
        """Capture a jax.profiler (TensorBoard) trace of the live engine —
        the runtime-side profiling leg SURVEY.md §5.1 calls for; the
        client-side OTLP tracer covers the other leg. POST {"seconds": N,
        "out_dir": runs-relative path}; returns the trace directory. Point
        TensorBoard's profile plugin at it to see the decode timeline."""
        try:
            body = await request.json()
        except Exception:
            body = {}
        if not isinstance(body, dict):
            body = {}
        try:
            seconds = float(body.get("seconds", 3.0))
        except (TypeError, ValueError):
            return web.json_response(
                {"error": {"message": "'seconds' must be a number"}}, status=400
            )
        if not 0.1 <= seconds <= 60.0:
            return web.json_response(
                {"error": {"message": "'seconds' must be in [0.1, 60]"}}, status=400
            )
        # traces land under runs/ only: the write path must not be client-
        # controlled (SECURITY.md input-handling stance)
        sub = str(body.get("out_dir") or f"profile-{int(time.time())}")
        out_path = (profile_root / sub).resolve()
        if not out_path.is_relative_to(profile_root):
            return web.json_response(
                {"error": {"message": "'out_dir' must stay under runs/"}}, status=400
            )
        if not profile_lock.acquire(blocking=False):
            return web.json_response(
                {"error": {"message": "a profile capture is already running"}},
                status=409,
            )

        def capture() -> None:
            import jax

            try:
                jax.profiler.start_trace(str(out_path))
                try:
                    time.sleep(seconds)
                finally:
                    jax.profiler.stop_trace()
            finally:
                profile_lock.release()

        loop = asyncio.get_running_loop()
        try:
            await loop.run_in_executor(None, capture)
        except Exception as e:  # start_trace can fail on unwritable dirs
            return web.json_response(
                {"error": {"message": f"profile capture failed: {e}"}}, status=500
            )
        return web.json_response(
            {"trace_dir": str(out_path), "seconds": seconds, "format": "tensorboard"}
        )

    async def metrics(_request):
        s = engine.snapshot_stats()
        lines = [
            "# TYPE kvmini_tpu_decode_tokens_total counter",
            f"kvmini_tpu_decode_tokens_total {s['decode_tokens']}",
            "# TYPE kvmini_tpu_prefill_tokens_total counter",
            f"kvmini_tpu_prefill_tokens_total {s['prefill_tokens']}",
            "# TYPE kvmini_tpu_requests_completed_total counter",
            f"kvmini_tpu_requests_completed_total {s['requests_completed']}",
            "# TYPE kvmini_tpu_duty_cycle gauge",
            f"kvmini_tpu_duty_cycle {s['duty_cycle']:.6f}",
            # raw busy-time as a counter so consumers can compute WINDOWED
            # duty (delta busy / delta wall) — the gauge above is
            # cumulative-since-start and flattens mid-run stalls; the live
            # monitor (docs/MONITORING.md) and Prometheus rate() need this
            "# TYPE kvmini_tpu_busy_seconds_total counter",
            f"kvmini_tpu_busy_seconds_total {s['busy_s']:.6f}",
            "# TYPE kvmini_tpu_queue_depth gauge",
            f"kvmini_tpu_queue_depth {s['queue_depth']}",
            "# TYPE kvmini_tpu_active_slots gauge",
            f"kvmini_tpu_active_slots {s['active_slots']}",
            "# TYPE kvmini_tpu_free_slots gauge",
            f"kvmini_tpu_free_slots {s['free_slots']}",
            "# TYPE kvmini_tpu_decode_steps_total counter",
            f"kvmini_tpu_decode_steps_total {s['decode_steps']}",
            "# TYPE kvmini_tpu_prefills_total counter",
            f"kvmini_tpu_prefills_total {s['prefills']}",
            # chunked-prefill rail (docs/TROUBLESHOOTING.md "Long prompts
            # stall streaming"): compiled prefill piece dispatches, and
            # the prefill wall that ran while decode work was live
            "# TYPE kvmini_tpu_prefill_chunks_total counter",
            f"kvmini_tpu_prefill_chunks_total {s['prefill_chunks']}",
            "# TYPE kvmini_tpu_prefill_chunk_stall_seconds_total counter",
            "kvmini_tpu_prefill_chunk_stall_seconds_total "
            f"{s['prefill_chunk_stall_s']:.6f}",
            # decode-pipeline telemetry (docs/DECODE_PIPELINE.md): depth >= 2
            # + low bubble = the double-buffered steady state is engaged
            "# TYPE kvmini_tpu_dispatch_depth gauge",
            f"kvmini_tpu_dispatch_depth {s['dispatch_depth']}",
            "# TYPE kvmini_tpu_inflight_sweeps gauge",
            f"kvmini_tpu_inflight_sweeps {s['inflight_sweeps']}",
            "# TYPE kvmini_tpu_pipelined_sweeps_total counter",
            f"kvmini_tpu_pipelined_sweeps_total {s['pipelined_sweeps']}",
            "# TYPE kvmini_tpu_host_overlap_seconds_total counter",
            f"kvmini_tpu_host_overlap_seconds_total {s['host_overlap_s']:.6f}",
            "# TYPE kvmini_tpu_bubble_seconds_total counter",
            f"kvmini_tpu_bubble_seconds_total {s['bubble_s']:.6f}",
            # sync-fallback attribution (docs/DECODE_PIPELINE.md): which
            # constraint broke the double-buffered steady state, labeled so
            # PromQL can aggregate (the scrape parser sums label series)
            "# TYPE kvmini_tpu_pipeline_fallback_total counter",
            "kvmini_tpu_pipeline_fallback_total"
            f"{{reason=\"constrained\"}} {s['pipeline_fallback_constrained']}",
            "kvmini_tpu_pipeline_fallback_total"
            f"{{reason=\"spec\"}} {s['pipeline_fallback_spec']}",
            "kvmini_tpu_pipeline_fallback_total"
            f"{{reason=\"active_set\"}} {s['pipeline_fallback_active_set']}",
            "kvmini_tpu_pipeline_fallback_total"
            f"{{reason=\"headroom\"}} {s['pipeline_fallback_headroom']}",
            "# TYPE kvmini_tpu_spec_rounds_total counter",
            f"kvmini_tpu_spec_rounds_total {s['spec_rounds']}",
            "# TYPE kvmini_tpu_spec_accepted_total counter",
            f"kvmini_tpu_spec_accepted_total {s['spec_accepted']}",
            "# TYPE kvmini_tpu_spec_proposed_total counter",
            f"kvmini_tpu_spec_proposed_total {s['spec_proposed']}",
            "# TYPE kvmini_tpu_spec_accept_ratio gauge",
            f"kvmini_tpu_spec_accept_ratio {s['spec_accept_ratio']:.6f}",
            "# TYPE kvmini_tpu_prefix_hits_total counter",
            f"kvmini_tpu_prefix_hits_total {s['prefix_hits']}",
            "# TYPE kvmini_tpu_prefix_tokens_reused_total counter",
            f"kvmini_tpu_prefix_tokens_reused_total {s['prefix_tokens_reused']}",
            # prefix-reuse counters under the generic cache names the
            # analysis fallback chain scrapes (analysis/telemetry.py
            # cache_hit_ratio) — before these lines the runtime branch of
            # that chain silently yielded nothing
            "# TYPE kvmini_tpu_cache_hits_total counter",
            f"kvmini_tpu_cache_hits_total {s['prefix_hits']}",
            "# TYPE kvmini_tpu_cache_lookups_total counter",
            f"kvmini_tpu_cache_lookups_total {s['prefix_lookups']}",
            # compile-stats capture (docs/PROFILING.md): explicit
            # lower().compile() facts for every executable the engine
            # built — wall time plus the XLA cost model's per-invocation
            # FLOPs/bytes and the buffer-assignment peak estimate
            "# TYPE kvmini_tpu_compiles_total counter",
            f"kvmini_tpu_compiles_total {s['compiles']}",
            "# TYPE kvmini_tpu_compile_seconds_total counter",
            f"kvmini_tpu_compile_seconds_total {s['compile_s']:.6f}",
            "# TYPE kvmini_tpu_compiled_flops_total counter",
            f"kvmini_tpu_compiled_flops_total {s['compiled_flops']:.6g}",
            "# TYPE kvmini_tpu_compiled_bytes_total counter",
            f"kvmini_tpu_compiled_bytes_total {s['compiled_bytes']:.6g}",
            "# TYPE kvmini_tpu_compile_peak_bytes gauge",
            f"kvmini_tpu_compile_peak_bytes {s['compile_peak_bytes']}",
            # resilience rail (docs/RESILIENCE.md): admission sheds,
            # watchdog trips, recovered engine faults, the degrade-ladder
            # position, and the armed-injection-point gauge — the monitor
            # timeline's overload_shedding / engine_fault event inputs
            "# TYPE kvmini_tpu_requests_shed_total counter",
            f"kvmini_tpu_requests_shed_total {s['requests_shed']}",
            "# TYPE kvmini_tpu_watchdog_trips_total counter",
            f"kvmini_tpu_watchdog_trips_total {s['watchdog_trips']}",
            "# TYPE kvmini_tpu_engine_faults_total counter",
            f"kvmini_tpu_engine_faults_total {s['engine_faults']}",
            "# TYPE kvmini_tpu_degrade_level gauge",
            f"kvmini_tpu_degrade_level {s['degrade_level']}",
            "# TYPE kvmini_tpu_faults_armed gauge",
            f"kvmini_tpu_faults_armed {s['faults_armed']}",
            # fleet-router placement input (docs/FLEET.md): seconds a
            # request submitted NOW would take to complete at this
            # replica — the deadline-shed estimate promoted to a scraped
            # signal so a fleet router can score replicas by load
            "# TYPE kvmini_tpu_estimated_wait_seconds gauge",
            f"kvmini_tpu_estimated_wait_seconds {s['estimated_wait_s']:.6f}",
            # KV-cache lifecycle + prefix-cache attribution (docs/
            # TROUBLESHOOTING.md "HBM pressure & KV thrash"): allocator
            # churn counters the point-in-time pool gauges cannot show,
            # hit-depth percentiles from one consistent scheduler-thread
            # snapshot, and the byte-denominated reuse view
            "# TYPE kvmini_tpu_kv_blocks_allocated_total counter",
            f"kvmini_tpu_kv_blocks_allocated_total {s['kv_blocks_allocated']}",
            "# TYPE kvmini_tpu_kv_retained_evictions_total counter",
            f"kvmini_tpu_kv_retained_evictions_total {s['kv_retained_evictions']}",
            "# TYPE kvmini_tpu_kv_share_reclaims_total counter",
            f"kvmini_tpu_kv_share_reclaims_total {s['kv_share_reclaims']}",
            "# TYPE kvmini_tpu_kv_prefix_hit_depth_p50 gauge",
            f"kvmini_tpu_kv_prefix_hit_depth_p50 {s['kv_prefix_hit_depth_p50']}",
            "# TYPE kvmini_tpu_kv_prefix_hit_depth_p95 gauge",
            f"kvmini_tpu_kv_prefix_hit_depth_p95 {s['kv_prefix_hit_depth_p95']}",
            "# TYPE kvmini_tpu_kv_bytes_per_token gauge",
            f"kvmini_tpu_kv_bytes_per_token {s['kv_bytes_per_token']}",
            "# TYPE kvmini_tpu_kv_reused_bytes_total counter",
            f"kvmini_tpu_kv_reused_bytes_total {s['kv_reused_bytes']}",
            # per-device analytic footprint (profiling/headroom.py): the
            # admission model's estimate, exported so headroom_error_pct
            # is derivable from a scrape next to the observed watermark
            "# TYPE kvmini_tpu_hbm_headroom_estimate_bytes gauge",
            f"kvmini_tpu_hbm_headroom_estimate_bytes {s['hbm_headroom_estimate_bytes']}",
        ]
        if "kv_handoffs" in s:  # disaggregated engines only (docs/
            # DISAGGREGATION.md): the prefill-lane handoff rail — volume,
            # block/wait accounting, tombstoned drops, lane busy wall,
            # the lane backlog gauge the handoff_stall monitor rule
            # watches, and the degrade-ladder position
            lines += [
                "# TYPE kvmini_tpu_kv_handoffs_total counter",
                f"kvmini_tpu_kv_handoffs_total {s['kv_handoffs']}",
                "# TYPE kvmini_tpu_kv_handoff_blocks_total counter",
                f"kvmini_tpu_kv_handoff_blocks_total {s['kv_handoff_blocks']}",
                "# TYPE kvmini_tpu_kv_handoff_wait_seconds_total counter",
                "kvmini_tpu_kv_handoff_wait_seconds_total "
                f"{s['kv_handoff_wait_s']:.6f}",
                "# TYPE kvmini_tpu_kv_handoff_drops_total counter",
                f"kvmini_tpu_kv_handoff_drops_total {s['kv_handoff_drops']}",
                "# TYPE kvmini_tpu_prefill_lane_busy_seconds_total counter",
                "kvmini_tpu_prefill_lane_busy_seconds_total "
                f"{s['prefill_lane_busy_s']:.6f}",
                "# TYPE kvmini_tpu_disagg_colocated_fallbacks_total counter",
                "kvmini_tpu_disagg_colocated_fallbacks_total "
                f"{s['disagg_colocated_fallbacks']}",
                "# TYPE kvmini_tpu_kv_handoff_queue_depth gauge",
                f"kvmini_tpu_kv_handoff_queue_depth {s['kv_handoff_queue_depth']}",
                "# TYPE kvmini_tpu_disagg_degraded gauge",
                f"kvmini_tpu_disagg_degraded {s['disagg_degraded']}",
                # KV bytes the handoff physically copied: the v1 dense
                # stripe's nbytes per inject; 0 forever on the v2
                # block-table path — the A/B the ISSUE 16 acceptance
                # criterion reads straight off this counter
                "# TYPE kvmini_tpu_kv_handoff_bytes_copied_total counter",
                "kvmini_tpu_kv_handoff_bytes_copied_total "
                f"{s['kv_handoff_bytes_copied']}",
            ]
        if "kv_pool_blocks" in s:  # paged layout only
            lines += [
                "# TYPE kvmini_tpu_kv_pool_blocks gauge",
                f"kvmini_tpu_kv_pool_blocks {s['kv_pool_blocks']}",
                "# TYPE kvmini_tpu_kv_free_blocks gauge",
                f"kvmini_tpu_kv_free_blocks {s['kv_free_blocks']}",
                "# TYPE kvmini_tpu_kv_retained_blocks gauge",
                f"kvmini_tpu_kv_retained_blocks {s['kv_retained_blocks']}",
                "# TYPE kvmini_tpu_kv_used_blocks gauge",
                f"kvmini_tpu_kv_used_blocks {s['kv_used_blocks']}",
                "# TYPE kvmini_tpu_kv_block_size gauge",
                f"kvmini_tpu_kv_block_size {s['kv_block_size']}",
                "# TYPE kvmini_tpu_kv_occupancy gauge",
                f"kvmini_tpu_kv_occupancy {s['kv_occupancy']:.6f}",
                "# TYPE kvmini_tpu_kv_retained_fraction gauge",
                f"kvmini_tpu_kv_retained_fraction {s['kv_retained_fraction']:.6f}",
                "# TYPE kvmini_tpu_kv_fragmentation gauge",
                f"kvmini_tpu_kv_fragmentation {s['kv_fragmentation']:.6f}",
                "# TYPE kvmini_tpu_kv_logical_bytes gauge",
                f"kvmini_tpu_kv_logical_bytes {s['kv_logical_bytes']}",
                "# TYPE kvmini_tpu_kv_physical_bytes gauge",
                f"kvmini_tpu_kv_physical_bytes {s['kv_physical_bytes']}",
                # host-RAM KV tier (docs/TROUBLESHOOTING.md "Host-RAM KV
                # tier thrash"): demote/promote/hit counters plus the
                # pool/capacity gauges and the thrash-guard disable flag
                "# TYPE kvmini_tpu_kv_tier_demotions_total counter",
                f"kvmini_tpu_kv_tier_demotions_total {s['kv_tier_demotions']}",
                "# TYPE kvmini_tpu_kv_tier_promotions_total counter",
                f"kvmini_tpu_kv_tier_promotions_total {s['kv_tier_promotions']}",
                "# TYPE kvmini_tpu_kv_tier_hits_total counter",
                f"kvmini_tpu_kv_tier_hits_total {s['kv_tier_hits']}",
                "# TYPE kvmini_tpu_kv_tier_blocks gauge",
                f"kvmini_tpu_kv_tier_blocks {s['kv_tier_blocks']}",
                "# TYPE kvmini_tpu_kv_tier_bytes gauge",
                f"kvmini_tpu_kv_tier_bytes {s['kv_tier_bytes']}",
                "# TYPE kvmini_tpu_kv_tier_capacity_bytes gauge",
                f"kvmini_tpu_kv_tier_capacity_bytes {s['kv_tier_capacity_bytes']}",
                "# TYPE kvmini_tpu_kv_tier_disabled gauge",
                f"kvmini_tpu_kv_tier_disabled {s['kv_tier_disabled']}",
                # cross-replica prefix migration (docs/FLEET.md): what
                # this replica shipped (/kv/export) and installed
                # (/kv/import)
                "# TYPE kvmini_tpu_kv_migrated_blocks_total counter",
                f"kvmini_tpu_kv_migrated_blocks_total {s['kv_migrated_blocks']}",
                "# TYPE kvmini_tpu_kv_migrated_bytes_total counter",
                f"kvmini_tpu_kv_migrated_bytes_total {s['kv_migrated_bytes']}",
                "# TYPE kvmini_tpu_kv_export_blocks_total counter",
                f"kvmini_tpu_kv_export_blocks_total {s['kv_export_blocks']}",
            ]
        if "hbm_bytes_in_use" in s:  # device reports memory_stats only
            lines += [
                "# TYPE kvmini_tpu_hbm_bytes_in_use gauge",
                f"kvmini_tpu_hbm_bytes_in_use {s['hbm_bytes_in_use']}",
                "# TYPE kvmini_tpu_hbm_peak_bytes gauge",
                f"kvmini_tpu_hbm_peak_bytes {s['hbm_peak_bytes']}",
            ]
        if "hbm_bytes_limit" in s:
            lines += [
                "# TYPE kvmini_tpu_hbm_bytes_limit gauge",
                f"kvmini_tpu_hbm_bytes_limit {s['hbm_bytes_limit']}",
            ]
        if "econ_usd_per_hour" in s:  # live economics rail (docs/
            # ECONOMICS.md): priced engines only (TPU backend or an
            # explicit econ_accelerator). The $/hr accrual is always
            # present once the rail exists; the rolling-window rates
            # appear only after the window sees token progress — a CPU
            # dev box or an idle engine never exports a fabricated $0
            lines += [
                "# TYPE kvmini_tpu_econ_usd_per_hour gauge",
                f"kvmini_tpu_econ_usd_per_hour {s['econ_usd_per_hour']:.6f}",
            ]
            if "econ_usd_per_1k_tokens" in s:
                lines += [
                    "# TYPE kvmini_tpu_econ_usd_per_1k_tokens gauge",
                    "kvmini_tpu_econ_usd_per_1k_tokens "
                    f"{s['econ_usd_per_1k_tokens']:.6f}",
                    "# TYPE kvmini_tpu_econ_wh_per_1k_tokens gauge",
                    "kvmini_tpu_econ_wh_per_1k_tokens "
                    f"{s['econ_wh_per_1k_tokens']:.6f}",
                    "# TYPE kvmini_tpu_econ_tokens_per_sec gauge",
                    "kvmini_tpu_econ_tokens_per_sec "
                    f"{s['econ_tokens_per_sec']:.6f}",
                ]
        # per-phase latency histograms (docs/TRACING.md): queue / prefill /
        # decode / emit durations the engine observes at phase transitions
        from kserve_vllm_mini_tpu.runtime.tracing import render_phase_histograms

        lines += render_phase_histograms(engine._phase_hist)
        return web.Response(text="\n".join(lines) + "\n", content_type="text/plain")

    async def traces(_request):
        """Runtime-side span buffer, OTLP-shaped JSON (the same schema the
        loadgen's traces.json uses — analysis/traces.py joins the two by
        trace_id). The buffer is a bounded ring: spans past the capacity
        evict oldest-first, and 'droppedSpans' reports how many did. An
        engine with tracing disabled serves an empty document, not a 404,
        so scrapers need no capability probe."""
        if engine.tracer is None:
            return web.json_response({"resourceSpans": [], "droppedSpans": 0,
                                      "tracing": "disabled"})
        return web.json_response(engine.traces_otlp())

    def _reject_multihost_admin() -> "Optional[web.Response]":
        """Multi-host serving rejects LoRA entirely at startup
        (runtime/multihost.check_multihost_engine): admin ops run only on
        the primary and are NOT replayed over the command channel, so a
        load would leave followers serving base weights (silent lockstep
        divergence). These endpoints reject up front — BEFORE body
        parsing, so multihost callers get the real reason rather than an
        incidental JSON error."""
        if multihost:
            return web.json_response(
                {"error": {"message":
                           "adapter hot-swap is not supported under "
                           "multi-host serving (v1)"}}, status=400,
            )
        return None

    async def load_lora(request: "web.Request"):
        # vLLM dynamic-LoRA surface: {"lora_name": ..., "lora_path": <PEFT dir>}
        rej = _reject_multihost_admin()
        if rej is not None:
            return rej
        try:
            body = await request.json()
        except Exception:
            return web.json_response({"error": {"message": "invalid JSON"}},
                                     status=400)
        name = body.get("lora_name")
        path = body.get("lora_path")
        if not name or not path:
            return web.json_response(
                {"error": {"message": "lora_name and lora_path are required"}},
                status=400,
            )
        from kserve_vllm_mini_tpu.ops.lora import LORA_TARGETS_ALL, load_peft_adapter

        loop = asyncio.get_running_loop()
        try:
            # file IO + host->device transfer + the blocking scheduler-op
            # wait all leave the event loop (like the chat path) — a slow
            # load must not freeze in-flight streams or /healthz
            adapter = await loop.run_in_executor(
                None,
                lambda: load_peft_adapter(path, engine.cfg,
                                          targets=LORA_TARGETS_ALL),
            )
        except Exception as e:  # noqa: BLE001 — surfaced to the caller
            return web.json_response(
                {"error": {"message": f"loading {path!r}: {e}"}}, status=400
            )
        err = await loop.run_in_executor(
            None, lambda: engine.load_adapter(name, adapter)
        )
        if err:
            return web.json_response({"error": {"message": err}}, status=409)
        return web.json_response({"status": "ok", "loaded": name})

    async def unload_lora(request: "web.Request"):
        rej = _reject_multihost_admin()
        if rej is not None:
            return rej
        try:
            body = await request.json()
        except Exception:
            return web.json_response({"error": {"message": "invalid JSON"}},
                                     status=400)
        name = body.get("lora_name")
        if not name:
            return web.json_response(
                {"error": {"message": "lora_name is required"}}, status=400
            )
        err = await asyncio.get_running_loop().run_in_executor(
            None, lambda: engine.unload_adapter(name)
        )
        if err:
            status = 404 if "unknown adapter" in err else 409
            return web.json_response({"error": {"message": err}}, status=status)
        return web.json_response({"status": "ok", "unloaded": name})

    async def faults_get(_request: "web.Request"):
        """Armed injection points (docs/RESILIENCE.md). Always readable —
        an operator must be able to SEE armed faults even on a server
        that refuses to arm new ones."""
        return web.json_response({
            "enabled": allow_fault_injection,
            "active": engine.active_faults(),
        })

    async def faults_post(request: "web.Request"):
        """Arm/clear a named injection point: {"name": ..., "action":
        "arm"|"clear", <params>}. Gated behind --allow-fault-injection —
        a production server must not expose a kill switch."""
        if not allow_fault_injection:
            return web.json_response(
                {"error": {"message":
                           "fault injection is disabled; start the server "
                           "with --allow-fault-injection"}}, status=403,
            )
        try:
            body = await request.json()
        except Exception:
            return web.json_response({"error": {"message": "invalid JSON"}},
                                     status=400)
        if not isinstance(body, dict):
            return web.json_response({"error": {"message": "body must be an "
                                                "object"}}, status=400)
        action = body.get("action", "arm")
        name = body.get("name")
        if action == "clear":
            engine.clear_fault(name)
            return web.json_response({"status": "ok",
                                      "cleared": name or "all"})
        if action != "arm" or not name:
            return web.json_response(
                {"error": {"message": "need action 'arm'|'clear' and, for "
                           "arm, a fault 'name'"}}, status=400,
            )
        params = {k: v for k, v in body.items() if k not in ("action", "name")}
        try:
            spec = engine.arm_fault(name, **params)
        except (ValueError, TypeError) as e:
            return web.json_response({"error": {"message": str(e)}},
                                     status=400)
        return web.json_response({"status": "ok", "armed": spec})

    async def kv_export(request: "web.Request"):
        """Cross-replica prefix migration, donor side (docs/FLEET.md):
        {"budget_bytes": N} -> a bounded, root-first wire snapshot of
        this replica's registered prefix blocks (int8-KV on the wire).
        The engine walk runs on its scheduler thread; the (possibly
        slow) rendezvous runs in an executor so the event loop never
        blocks on a sweep. 400 on dense engines — migration is a paged
        block-pool operation."""
        try:
            body = await request.json()
        except Exception:
            body = {}
        budget = int((body or {}).get("budget_bytes", 16 * 1024 * 1024))
        loop = asyncio.get_running_loop()
        try:
            payload = await loop.run_in_executor(
                None, engine.kv_export, budget
            )
        except ValueError as e:
            return web.json_response({"error": {"message": str(e)}},
                                     status=400)
        except RuntimeError as e:
            return web.json_response({"error": {"message": str(e)}},
                                     status=503)
        return web.json_response(payload)

    async def kv_import(request: "web.Request"):
        """Cross-replica prefix migration, target side: install a
        sibling's /kv/export payload into FREE pool blocks (never
        evicts) and register the keys as retained prefix blocks. 400 on
        dense engines or geometry mismatches (block_size/leaf shapes)."""
        try:
            body = await request.json()
        except Exception:
            return web.json_response({"error": {"message": "invalid JSON"}},
                                     status=400)
        if not isinstance(body, dict):
            return web.json_response(
                {"error": {"message": "body must be an object"}}, status=400
            )
        loop = asyncio.get_running_loop()
        try:
            res = await loop.run_in_executor(None, engine.kv_import, body)
        except (ValueError, KeyError) as e:
            return web.json_response({"error": {"message": str(e)}},
                                     status=400)
        except RuntimeError as e:
            return web.json_response({"error": {"message": str(e)}},
                                     status=503)
        return web.json_response(res)

    app = web.Application()
    app.router.add_post("/v1/chat/completions", chat)
    app.router.add_get("/v1/models", models)
    app.router.add_post("/v1/load_lora_adapter", load_lora)
    app.router.add_post("/v1/unload_lora_adapter", unload_lora)
    app.router.add_get("/healthz", healthz)
    app.router.add_get("/metrics", metrics)
    app.router.add_get("/traces", traces)
    app.router.add_post("/profile", profile)
    app.router.add_get("/faults", faults_get)
    app.router.add_post("/faults", faults_post)
    app.router.add_post("/kv/export", kv_export)
    app.router.add_post("/kv/import", kv_import)
    return app


# -- CLI ---------------------------------------------------------------------

def register(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("--model", default="llama-tiny", help="Model preset name")
    parser.add_argument("--checkpoint", default=None, help="Local HF checkpoint dir")
    parser.add_argument("--tokenizer", default=None, help="Local tokenizer dir")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8000)
    parser.add_argument("--max-slots", type=int, default=None,
                        help="Decode slots (default: $KVMINI_MAX_BATCH or 8)")
    parser.add_argument("--max-seq-len", type=int, default=None,
                        help="Per-slot KV window (default: $KVMINI_MAX_MODEL_LEN "
                             "or 1024)")
    parser.add_argument("--topology", default=None,
                        help="Mesh topology preset (e.g. v5e-8); default single-device")
    parser.add_argument("--pp", type=int, default=0,
                        help="Serving pipeline-parallel stages (layer-range "
                             "sharding over a pure-pp mesh; overrides --topology)")
    parser.add_argument("--pp-microbatches", type=int, default=None,
                        help="Slot groups pipelined per step with --pp "
                             "(GPipe-style; shrinks the stage bubble). "
                             "Default: $KVMINI_PP_MICROBATCHES or 1")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--quantization", default="none",
                        choices=["none", "int8", "int4", "int4-awq"],
                        help="Weight quantization (int8 = W8A16, int4 = W4A16 "
                             "per-channel; XLA packs int4 two-per-byte in HBM; "
                             "int4-awq = activation-aware calibrated scales)")
    parser.add_argument("--kv-cache-dtype", default=None,
                        help="KV cache dtype: bfloat16/float32/float16/int8 "
                             "(int8 = scaled per-position) or 'auto'")
    parser.add_argument("--quant-mode", default=None,
                        choices=["dequant", "w8a8"],
                        help="How quantized matmuls contract: 'dequant' "
                             "casts the int weight to the activation dtype "
                             "before the dot (W8A16/W4A16); 'w8a8' "
                             "quantizes activations per token and runs the "
                             "contraction int8 x int8 on the MXU "
                             "(ops/qmatmul.py). Default: $KVMINI_QUANT_MODE "
                             "or dequant. No-op with --quantization none")
    parser.add_argument("--scan-unroll", type=int, default=1,
                        help="lax.scan unroll over the layer stack (XLA "
                             "schedule knob; results equivalent)")
    parser.add_argument("--decode-chunk", type=int, default=1,
                        help="Decode steps fused per dispatch (throughput vs "
                             "streaming granularity)")
    parser.add_argument("--prefill-chunk", type=int, default=None,
                        help="Tokens per interleaved prefill chunk: prompts "
                             "above this threshold are chunk-prefilled "
                             "BETWEEN decode sweeps instead of stalling "
                             "them behind one monolithic call (TTFT/ITL "
                             "tail; docs/TROUBLESHOOTING.md). Default: "
                             "$KVMINI_PREFILL_CHUNK or monolithic")
    parser.add_argument("--disagg", action="store_true",
                        help="Disaggregated prefill/decode serving "
                             "(docs/DISAGGREGATION.md): prompt prefills "
                             "run on a dedicated prefill lane and hand "
                             "finished KV blocks to the decode engine, so "
                             "long prefills never stall the decode sweep "
                             "loop. Dense KV only (v1); excludes drafter/"
                             "LoRA/prefix-cache. Also $KVMINI_DISAGG=1")
    parser.add_argument("--disagg-min-prompt", type=int, default=None,
                        help="Prompts shorter than this many tokens "
                             "prefill colocated even with --disagg (a "
                             "short prefill is cheaper than its handoff "
                             "round-trip). Default: "
                             "$KVMINI_DISAGG_MIN_PROMPT or 0 = route all")
    parser.add_argument("--prefill-lane-devices", type=int, default=None,
                        help="With --disagg: split the device set into a "
                             "prefill submesh of this many devices plus a "
                             "decode mesh over the rest (parallel/mesh."
                             "lane_meshes; e.g. 2 on an 8-device slice = "
                             "a 2+6 split). Default: "
                             "$KVMINI_PREFILL_LANE_DEVICES or 0 = the "
                             "lane shares the engine's devices on its "
                             "own thread")
    parser.add_argument("--drafter", default=None,
                        help="Drafter model preset/checkpoint for speculative "
                             "decoding (default: $KVMINI_DRAFTER)")
    parser.add_argument("--spec-tokens", type=int, default=None,
                        help="Speculative propose/verify depth per round "
                             "(default: $KVMINI_SPEC_TOKENS or 4 when a "
                             "drafter is set)")
    parser.add_argument("--kv-layout", default="dense",
                        choices=["dense", "paged"],
                        help="KV cache layout: dense per-slot stripes, or a "
                             "paged block pool (PagedAttention-style) where "
                             "admission reserves ceil((prompt+max_tokens)/"
                             "block) blocks — long --max-seq-len stops "
                             "multiplying across slots")
    parser.add_argument("--kv-block-size", type=int, default=64,
                        help="Positions per paged-KV block")
    parser.add_argument("--kv-pool-blocks", type=int, default=None,
                        help="Paged-KV pool size in blocks (default "
                             "slots x ceil(max_seq/block), memory-equal to "
                             "dense; set lower to cap KV HBM)")
    parser.add_argument("--kv-host-tier-bytes", type=int, default=None,
                        help="Host-RAM KV tier capacity in bytes (paged "
                             "layout only): retained-LRU evictions demote "
                             "to host memory and promote back on prefix "
                             "match; 0/absent disables the tier "
                             "(docs/TROUBLESHOOTING.md)")
    parser.add_argument("--lora", action="append", default=None,
                        metavar="NAME=PEFT_DIR",
                        help="Load a LoRA adapter (PEFT safetensors dir) "
                             "servable via the request's 'model' field; "
                             "repeatable — one jitted step serves mixed "
                             "adapters (ops/lora.py)")
    parser.add_argument("--lora-demo", type=int, default=0,
                        help="Create N random adapters 'demo-1..N' for "
                             "multi-LoRA benchmarking without fine-tuned "
                             "weights")
    parser.add_argument("--lora-rank", type=int, default=8,
                        help="Rank of the --lora-demo bank (PEFT adapters "
                             "carry their own rank)")
    parser.add_argument("--lora-slots", type=int, default=4,
                        help="Adapter-bank capacity for adapters loaded at "
                             "RUNTIME (/v1/load_lora_adapter) on an engine "
                             "that started without any --lora")
    parser.add_argument("--no-request-tracing", action="store_true",
                        help="Disable the request-lifecycle span recorder "
                             "(GET /traces; docs/TRACING.md). Also "
                             "KVMINI_REQUEST_TRACING=0. Phase histograms "
                             "on /metrics stay on either way")
    parser.add_argument("--trace-buffer", type=int, default=4096,
                        help="Span ring-buffer capacity for /traces "
                             "(bounded memory; oldest spans evict)")
    parser.add_argument("--prefix-cache", action="store_true",
                        help="Automatic prefix caching: finished requests "
                             "retain their KV and new prompts sharing a "
                             "token prefix reuse it (slot-affinity APC; "
                             "repeat-heavy traffic skips most prefill)")
    parser.add_argument("--distributed", action="store_true",
                        help="Join a multi-host jax.distributed runtime "
                             "(KVMINI_COORDINATOR / KVMINI_NUM_PROCESSES / "
                             "KVMINI_PROCESS_ID or TPU-pod autodiscovery); "
                             "process 0 serves HTTP, others follow in "
                             "lockstep (runtime/multihost.py)")
    parser.add_argument("--tp", type=int, default=None,
                        help="Tensor-parallel width for --distributed "
                             "(default: all global devices; dp must stay 1)")
    parser.add_argument("--command-port", type=int, default=None,
                        help="Multi-host scheduler-command channel port "
                             "(default: $KVMINI_COMMAND_PORT or 8470)")
    parser.add_argument("--faults", default=None,
                        help="Arm in-process fault injection points at "
                             "startup (docs/RESILIENCE.md), e.g. "
                             "'sweep_stall:after=50,duration=3;"
                             "device_error:after=200'. Also $KVMINI_FAULTS. "
                             "Default: none (zero overhead)")
    parser.add_argument("--fault-seed", type=int, default=None,
                        help="Seed for probabilistic fault triggers — a "
                             "fixed seed makes a scripted chaos scenario "
                             "deterministic (default: $KVMINI_FAULT_SEED "
                             "or 0)")
    parser.add_argument("--watchdog", action="store_true",
                        help="Arm the wedged-sweep watchdog: no retire "
                             "within watchdog-factor x the rolling sweep "
                             "time fails the in-flight batch with "
                             "finish_reason=engine_fault and degrades "
                             "(sync pipeline -> chunk 1 -> no spec) "
                             "instead of hanging clients. Also "
                             "$KVMINI_WATCHDOG=1 (docs/RESILIENCE.md)")
    parser.add_argument("--watchdog-min-s", type=float, default=2.0,
                        help="Watchdog floor: a wedge shorter than this "
                             "never trips (first compiles excepted — arm "
                             "the watchdog on warmed servers)")
    parser.add_argument("--default-deadline-ms", type=float, default=None,
                        help="Server default per-request deadline for "
                             "deadline-aware admission: requests that "
                             "cannot meet it at current load are shed "
                             "with 429 + Retry-After. Clients override "
                             "per request via deadline_ms / the "
                             "x-request-deadline-ms header. Also "
                             "$KVMINI_DEFAULT_DEADLINE_MS. Default: no "
                             "shedding")
    parser.add_argument("--allow-fault-injection", action="store_true",
                        help="Enable POST /faults (arm/clear injection "
                             "points at runtime — what `kvmini-tpu chaos "
                             "--target local` drives). Also "
                             "$KVMINI_ALLOW_FAULT_INJECTION=1. Never "
                             "enable on a production server")
    parser.add_argument("--econ-accelerator", default=None,
                        help="Price the live economics rail "
                             "($/1K-tok, Wh/1K-tok on /metrics) as this "
                             "chip from tpu-cost.yaml (e.g. 'v5e'). "
                             "Default: auto-detect on TPU backends; CPU "
                             "backends export NO economics. Also "
                             "$KVMINI_ECON_ACCELERATOR "
                             "(docs/ECONOMICS.md)")


def _parse_lora_args(items: Optional[list]) -> Optional[dict[str, str]]:
    """--lora NAME=PEFT_DIR (repeatable) -> {name: dir}."""
    if not items:
        return None
    out: dict[str, str] = {}
    for it in items:
        if "=" not in it:
            raise SystemExit(f"--lora expects NAME=PEFT_DIR, got {it!r}")
        name, path = it.split("=", 1)
        out[name] = path
    return out


def run(args: argparse.Namespace) -> int:
    import os

    from aiohttp import web

    drafter = args.drafter or os.environ.get("KVMINI_DRAFTER")
    # container contract: the deploy layer (deploy/backends.py _jax_native_env)
    # configures the runtime through KVMINI_* env; explicit CLI flags win
    # (including --pp-microbatches 1 to force unpipelined decode)
    pp = args.pp or int(os.environ.get("KVMINI_PP", "0") or 0)
    pp_mb = (
        args.pp_microbatches
        if args.pp_microbatches is not None
        else int(os.environ.get("KVMINI_PP_MICROBATCHES", "1") or 1)
    )
    max_slots = args.max_slots or int(os.environ.get("KVMINI_MAX_BATCH", "8") or 8)
    max_seq = args.max_seq_len or int(
        # kvmini: config-ok — deploy manifests default 4096 by design
        os.environ.get("KVMINI_MAX_MODEL_LEN", "1024") or 1024
    )
    quantization = (
        args.quantization
        if args.quantization != "none"
        else os.environ.get("KVMINI_QUANTIZATION", "none")
    )
    kv_dtype = args.kv_cache_dtype or os.environ.get("KVMINI_KV_CACHE_DTYPE")
    quant_mode = (
        args.quant_mode or os.environ.get("KVMINI_QUANT_MODE") or "dequant"
    )
    spec_tokens = args.spec_tokens
    if spec_tokens is None:
        spec_tokens = int(os.environ.get("KVMINI_SPEC_TOKENS", "4" if drafter else "0"))
    prefill_chunk = args.prefill_chunk
    if prefill_chunk is None:
        env_pc = os.environ.get("KVMINI_PREFILL_CHUNK")
        prefill_chunk = int(env_pc) if env_pc else None
    disagg = bool(
        args.disagg or os.environ.get("KVMINI_DISAGG", "") in ("1", "true")
    )
    disagg_min_prompt = args.disagg_min_prompt
    if disagg_min_prompt is None:
        disagg_min_prompt = int(
            os.environ.get("KVMINI_DISAGG_MIN_PROMPT", "0") or 0
        )
    prefill_lane_devices = args.prefill_lane_devices
    if prefill_lane_devices is None:
        prefill_lane_devices = int(
            os.environ.get("KVMINI_PREFILL_LANE_DEVICES", "0") or 0
        )
    faults = args.faults or os.environ.get("KVMINI_FAULTS") or None
    fault_seed = (
        args.fault_seed
        if args.fault_seed is not None
        else int(os.environ.get("KVMINI_FAULT_SEED", "0") or 0)
    )
    watchdog = bool(
        args.watchdog
        or os.environ.get("KVMINI_WATCHDOG", "") in ("1", "true")
    )
    default_deadline_ms = args.default_deadline_ms
    if default_deadline_ms is None:
        env_dl = os.environ.get("KVMINI_DEFAULT_DEADLINE_MS")
        default_deadline_ms = float(env_dl) if env_dl else None
    allow_faults = bool(
        args.allow_fault_injection
        or os.environ.get("KVMINI_ALLOW_FAULT_INJECTION", "") in ("1", "true")
    )

    # multi-host: join the process group BEFORE any device is touched, then
    # shard the engine over the global mesh (runtime/multihost.py lockstep)
    multihost = False
    mesh_override = None
    if args.distributed:
        import jax as _jax

        from kserve_vllm_mini_tpu.parallel import distributed as dist

        # the site-hook platform fix must land BEFORE the process group
        # forms (build_engine applies it too, but that is post-initialize)
        if os.environ.get("JAX_PLATFORMS"):
            _jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
        joined = dist.initialize()
        multihost = joined and dist.process_count() > 1
        if multihost:
            import jax

            from kserve_vllm_mini_tpu.parallel.mesh import MeshSpec

            n_global = len(jax.devices())
            topo_name = args.topology or os.environ.get("KVMINI_TOPOLOGY")
            if pp and pp > 1:
                spec = MeshSpec(pp=pp)
            elif topo_name:
                # a layout preset (e.g. v5p-16-longctx: tp4 x sp4) names the
                # GLOBAL mesh across hosts — without this, multi-host serving
                # would silently fall back to plain tp and drop the layout
                from kserve_vllm_mini_tpu.parallel.mesh import TOPOLOGY_PRESETS

                if topo_name not in TOPOLOGY_PRESETS:
                    raise SystemExit(f"unknown topology preset {topo_name!r}")
                pr = TOPOLOGY_PRESETS[topo_name]
                if pr["chips"] != n_global:
                    raise SystemExit(
                        f"topology {topo_name} is {pr['chips']} chips but the "
                        f"process group has {n_global} devices"
                    )
                spec = MeshSpec.fill(n_global, tp=pr.get("tp"),
                                     sp=pr.get("sp", 1))
            else:
                spec = MeshSpec.fill(n_global, tp=args.tp or n_global)
            if spec.dp > 1:
                raise SystemExit(
                    f"--distributed needs dp == 1 (got tp={spec.tp} over "
                    f"{n_global} devices -> dp={spec.dp}); raise --tp or use --pp"
                )
            if drafter:
                raise SystemExit("--distributed does not support --drafter (v1)")
            if disagg:
                # the prefill lane is host-local; a lockstep follower
                # cannot replay its handoff timing (same rule as
                # prefill_chunk / deadline sheds — but loud, because
                # silently colocating would bench the wrong architecture)
                raise SystemExit("--distributed does not support --disagg (v1)")
            mesh_override = dist.global_mesh(spec)

    engine, tok, name = build_engine(
        model=args.model,
        checkpoint=args.checkpoint,
        tokenizer_path=args.tokenizer,
        max_slots=max_slots,
        decode_chunk=args.decode_chunk,
        prefill_chunk=prefill_chunk,
        disagg=disagg,
        disagg_min_prompt=disagg_min_prompt,
        prefill_lane_devices=prefill_lane_devices,
        max_seq_len=max_seq,
        topology=args.topology or os.environ.get("KVMINI_TOPOLOGY") or None,
        pp=pp,
        pp_microbatches=pp_mb,
        scan_unroll=args.scan_unroll,
        seed=args.seed,
        quantization=quantization,
        quant_mode=quant_mode,
        kv_cache_dtype=kv_dtype,
        drafter=drafter,
        spec_tokens=spec_tokens,
        mesh=mesh_override,
        prefix_cache=bool(
            args.prefix_cache
            or os.environ.get("KVMINI_PREFIX_CACHE", "") in ("1", "true")
        ),
        kv_layout=args.kv_layout,
        kv_block_size=args.kv_block_size,
        kv_pool_blocks=args.kv_pool_blocks,
        kv_host_tier_bytes=args.kv_host_tier_bytes,
        lora_adapters=_parse_lora_args(args.lora),
        lora_demo=args.lora_demo,
        lora_rank=args.lora_rank,
        lora_slots=args.lora_slots,
        request_tracing=not (
            args.no_request_tracing
            or os.environ.get("KVMINI_REQUEST_TRACING", "").lower()
            in ("0", "false", "off")
        ),
        trace_buffer=args.trace_buffer,
        faults=faults,
        fault_seed=fault_seed,
        watchdog=watchdog,
        default_deadline_s=(
            default_deadline_ms / 1000.0 if default_deadline_ms else None
        ),
        econ_accelerator=(
            args.econ_accelerator
            or os.environ.get("KVMINI_ECON_ACCELERATOR") or None
        ),
    )
    if watchdog and args.watchdog_min_s is not None:
        engine.ecfg.watchdog_min_s = float(args.watchdog_min_s)

    if multihost:
        from kserve_vllm_mini_tpu.parallel import distributed as dist
        from kserve_vllm_mini_tpu.runtime import multihost as mh

        cmd_port = args.command_port or int(
            os.environ.get("KVMINI_COMMAND_PORT", "8470")
        )
        # process-0's reachable host, NOT loopback: on a TPU pod the
        # coordinator comes from autodiscovery (TPU_WORKER_HOSTNAMES), and
        # followers on other hosts must dial that machine
        coord_host = dist.coordinator_host()
        if dist.is_primary():
            handle = mh.serve_multihost(
                engine, primary=True, coordinator_host=coord_host,
                command_port=cmd_port, n_followers=dist.process_count() - 1,
            )
            app = make_app(engine, tok, name, multihost=True,
                           alive_check=handle.is_alive,
                           allow_fault_injection=allow_faults)
            print(f"kvmini-tpu serve: {name} on http://{args.host}:{args.port} "
                  f"(slots={max_slots}, max_seq={max_seq}, "
                  f"multihost primary, {dist.process_count()} processes, "
                  f"mesh={dict(engine.mesh.shape)})", flush=True)
            try:
                web.run_app(app, host=args.host, port=args.port, print=None)
            finally:
                # synchronous: followers must get the stop command even as
                # the interpreter tears down this daemon thread's world
                handle.shutdown()
            return 0
        print(f"kvmini-tpu serve: follower {dist.process_index()}/"
              f"{dist.process_count()} (mesh={dict(engine.mesh.shape)})",
              flush=True)
        mh.serve_multihost(
            engine, primary=False, coordinator_host=coord_host,
            command_port=cmd_port, n_followers=0,
        )
        return 0

    engine.start()
    app = make_app(engine, tok, name, allow_fault_injection=allow_faults)
    print(f"kvmini-tpu serve: {name} on http://{args.host}:{args.port} "
          f"(slots={max_slots}, max_seq={max_seq})")
    try:
        web.run_app(app, host=args.host, port=args.port, print=None)
    finally:
        engine.stop()
    return 0
