"""Deterministic in-process fault injection (docs/RESILIENCE.md).

The chaos harness (`chaos/harness.py`) injects faults at the Kubernetes
layer; the owned runtime needs the SAME failure classes injectable
in-process, deterministically, so the recovery machinery (watchdog,
degrade ladder, shedding, client retry) is exercisable in a unit test
and by `kvmini-tpu chaos --target local` with no cluster.

Design contract:

- **Named injection points**, each armed independently. The registry is
  ``None`` on an engine/server that never armed a fault — hot paths pay
  one attribute check and nothing else (zero overhead when disabled,
  off by default).
- **Deterministic**: triggers are count-based (``after`` = skip the
  first N checks, ``times`` = fire at most N times) and any
  probabilistic trigger (``p``) draws from a ``random.Random`` seeded
  per point from the registry seed — two runs of the same scripted
  scenario observe the identical event sequence.
- **Config-driven**: ``KVMINI_FAULTS="sweep_stall:after=5,duration=2;
  device_error:after=20"`` or ``EngineConfig.faults`` with the same
  syntax; the server's ``POST /faults`` (gated by
  ``--allow-fault-injection``) arms/clears points at runtime for the
  local chaos harness.

Injection points the runtime threads through its hot paths:

| point            | where                         | effect                |
|------------------|-------------------------------|-----------------------|
| ``sweep_stall``  | scheduler, before a sweep     | sleep ``duration`` (wedged device sweep — the watchdog's prey) |
| ``device_error`` | decode dispatch               | raises ``DeviceFault`` (recovered: batch fails ``engine_fault``, engine degrades + keeps serving) |
| ``kv_alloc_fail``| paged-KV admission fit check  | admission backpressure for ``duration`` (queue grows, sheds kick in) |
| ``sse_disconnect``| server streaming loop        | stream transport drops mid-response |
| ``publish_drop`` | multihost decision publish    | one published decision is silently dropped |
| ``kv_handoff_drop`` | prefill-lane handoff (runtime/disagg.py) | a finished KV handoff is lost in transit; the engine must degrade to colocated prefill, never hang the request |
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Optional

FAULT_POINTS = (
    "sweep_stall",
    "device_error",
    "kv_alloc_fail",
    "sse_disconnect",
    "publish_drop",
    "kv_handoff_drop",
)

_FLOAT_PARAMS = ("duration", "p")
_INT_PARAMS = ("after", "times", "after_tokens")


class DeviceFault(RuntimeError):
    """An injected (or classified-as-injectable) device dispatch error.

    The scheduler catches THIS type specifically and runs the
    engine-fault recovery path (fail the in-flight batch with
    ``finish_reason="engine_fault"``, drain, degrade) instead of the
    generic fail-everything crash handler."""


@dataclass
class FaultSpec:
    """One armed injection point."""

    name: str
    after: int = 0          # checks to pass through before firing
    times: int = 1          # fires remaining (<=0 means unlimited)
    duration: float = 0.0   # seconds (stalls / backpressure windows)
    p: float = 1.0          # fire probability once past `after`
    after_tokens: int = 1   # sse_disconnect: tokens to stream first
    extra: dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> dict[str, Any]:
        return {
            "name": self.name, "after": self.after, "times": self.times,
            "duration": self.duration, "p": self.p,
            "after_tokens": self.after_tokens, **self.extra,
        }


class FaultRegistry:
    """Thread-safe registry of armed injection points.

    ``check(name)`` is the hot-path call: returns the ``FaultSpec`` when
    the point is armed AND its trigger condition fires this call, else
    ``None``. Every mutation and every trigger decision happens under
    one lock — the scheduler, the watchdog, and the server's ``/faults``
    handler all touch it (KVM05x discipline)."""

    def __init__(self, seed: int = 0, config: str = "") -> None:
        self._lock = threading.Lock()
        self._seed = seed
        self._specs: dict[str, FaultSpec] = {}
        self._counts: dict[str, int] = {}
        self._fired: dict[str, int] = {}
        self._rngs: dict[str, random.Random] = {}
        if config:
            arm_from_config(self, config)

    # -- arming ------------------------------------------------------------

    def arm(self, name: str, **params: Any) -> FaultSpec:
        if name not in FAULT_POINTS:
            raise ValueError(
                f"unknown fault point {name!r}; known: {list(FAULT_POINTS)}"
            )
        known = {k: v for k, v in params.items()
                 if k in _FLOAT_PARAMS + _INT_PARAMS}
        extra = {k: v for k, v in params.items() if k not in known}
        spec = FaultSpec(name=name, extra=extra)
        for k in _FLOAT_PARAMS:
            if k in known:
                setattr(spec, k, float(known[k]))
        for k in _INT_PARAMS:
            if k in known:
                setattr(spec, k, int(known[k]))
        with self._lock:
            self._specs[name] = spec
            self._counts[name] = 0
            self._fired[name] = 0
            # per-point rng seeded from (registry seed, point name): the
            # trigger sequence of one point is independent of how often
            # OTHER points are checked
            self._rngs[name] = random.Random(f"{self._seed}:{name}")
        return spec

    def disarm(self, name: Optional[str] = None) -> None:
        """Disarm one point (None = all). Named ``disarm`` rather than
        a container verb: the registry is internally locked, and the
        package linter's container-mutation heuristics are tuned to
        mutating-verb method names."""
        with self._lock:
            if name is None:
                self._specs.clear()
            else:
                self._specs.pop(name, None)

    def active(self) -> dict[str, dict[str, Any]]:
        with self._lock:
            return {n: s.to_dict() for n, s in self._specs.items()}

    def armed_count(self) -> int:
        with self._lock:
            return len(self._specs)

    # -- hot path ----------------------------------------------------------

    def check(self, name: str) -> Optional[FaultSpec]:
        with self._lock:
            spec = self._specs.get(name)
            if spec is None:
                return None
            self._counts[name] += 1
            if self._counts[name] <= spec.after:
                return None
            if spec.times > 0 and self._fired[name] >= spec.times:
                return None
            if spec.p < 1.0 and self._rngs[name].random() >= spec.p:
                return None
            self._fired[name] += 1
            return spec

    def stall(self, name: str, sleep=time.sleep) -> bool:
        """check() + sleep the spec's duration when it fires. The sleep
        happens OUTSIDE the lock so a wedged point never blocks /faults
        or other points' checks."""
        spec = self.check(name)
        if spec is None:
            return False
        if spec.duration > 0:
            sleep(spec.duration)
        return True


def arm_from_config(reg: FaultRegistry, config: str) -> FaultRegistry:
    """Arm ``reg`` from a ``"name:key=val,key=val;name2:..."`` string
    (the KVMINI_FAULTS / EngineConfig.faults syntax). Blank = no-op."""
    config = (config or "").strip()
    if not config:
        return reg
    for part in config.split(";"):
        part = part.strip()
        if not part:
            continue
        name, _, rest = part.partition(":")
        params: dict[str, Any] = {}
        for kv in rest.split(","):
            kv = kv.strip()
            if not kv:
                continue
            k, _, v = kv.partition("=")
            try:
                params[k.strip()] = float(v) if "." in v else int(v)
            except ValueError:
                params[k.strip()] = v.strip()
        reg.arm(name.strip(), **params)
    return reg


def parse_faults(config: str, seed: int = 0) -> Optional[FaultRegistry]:
    """``"name:..."`` -> armed registry, or None for an empty/blank
    config (callers that want an always-present registry construct one
    and use arm_from_config)."""
    if not (config or "").strip():
        return None
    return arm_from_config(FaultRegistry(seed=seed), config)
