"""Back-compat shim: the profiler CLI grew into the profiling subsystem
(kserve_vllm_mini_tpu/profiling/ — docs/PROFILING.md). The ``kvmini-tpu
profile`` subcommand now dispatches to ``profiling.capture``; this module
stays importable for anything that referenced the old path."""

from kserve_vllm_mini_tpu.profiling.capture import register, run  # noqa: F401
