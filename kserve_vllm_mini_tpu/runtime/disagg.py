"""Disaggregated prefill/decode serving: the prefill lane and the
KV-block handoff protocol (docs/DISAGGREGATION.md).

The engine's scheduler thread is the DECODE lane: it retires decode
sweeps, and every millisecond it spends executing a prompt prefill is a
millisecond every streaming client's next token waits (chunked prefill —
PR 11 — bounds that stall; it does not remove it). This module moves
prompt prefills off that thread entirely:

- **PrefillLane** — a dedicated worker (its own thread, optionally its
  own mesh submesh via ``parallel/mesh.lane_meshes``) that owns a
  single-slot STAGING KV cache and its own compiled prefill executables
  (``disagg_prefill[bucket]`` / ``disagg_chunk_prefill[bucket]`` in the
  compile-stats rail). It consumes routed admissions from a bounded job
  queue, runs the prompt's prefill pieces against the staging cache, and
  emits one finished :class:`KVHandoff` per request.

- **KVHandoff** — the explicit, versioned handoff protocol: the staged
  KV payload (the slot stripe as the model's cache tree — int8 values +
  per-position f32 scales when the cache is quantized, bf16 otherwise),
  the last-position logits the first sampled token needs, block-count
  accounting (``n_blocks`` at the engine's ``kv_block_size``
  granularity), and prefix-attribution metadata
  (``reused_prefix_tokens``; always 0 in v1 — the lane has no prefix
  index). A payload computed under a different protocol version is
  REFUSED at consume (tombstoned, degrade-to-colocated) rather than
  injected: silently consuming a mismatched layout would corrupt the
  slot's cache.

- **Degrade ladder** — every failure mode ends in COLOCATED prefill,
  never a hung request: a dropped handoff (the ``kv_handoff_drop``
  injection point, a lane-side exception, a version mismatch) arrives
  as a TOMBSTONE and the engine re-prefills that prompt on the
  scheduler thread; ``DROPS_TO_DEGRADE`` consecutive tombstones (or a
  dead lane thread) flips the engine to colocated routing for the rest
  of the run (``disagg_degraded`` gauge). A handoff that never arrives
  at all (lane wedged without even a tombstone) hits the consume-side
  ``HANDOFF_TIMEOUT_S`` and takes the same colocated path.

Two payload formats, negotiated by KV layout (docs/DISAGGREGATION.md):

- **v1 (dense)** — the handoff unit is the SLOT STRIPE: the lane owns a
  1-slot staging cache, and consume injects the staged stripe verbatim
  (``update_cache_slots``) — one device-side copy per handoff.
- **v2 (paged, ``HANDOFF_VERSION``)** — the handoff unit is a BLOCK
  TABLE: the scheduler reserves blocks from the engine's shared pool at
  routing time and the lane prefills straight into them through the
  engine's own compiled paged executables (``Engine._lane_paged_prefill``
  — per-dispatch ``_cache_lock`` serializes the cache swap against the
  scheduler; device execution orders by buffer dependencies). The
  ``KVHandoff`` then carries NO KV bytes at all (``kv=None``): consume
  installs the slot's table row and the handoff tax is a host-side
  pointer write.

Byte-identity either way: both paths run the SAME forward, params,
bucket shapes, and piece schedule as colocated monolithic admission, so
greedy streams are byte-identical to the colocated engine's — pinned by
tests/test_disagg.py.
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

# Protocol versions stamped on every payload; bump whenever a payload's
# layout/semantics change. Consume accepts exactly the version its KV
# layout speaks (v2 block tables on paged engines, v1 dense stripes on
# dense ones) and refuses the rest (tombstone -> colocated re-prefill),
# so a rolling upgrade can never install a stale-layout payload.
HANDOFF_VERSION = 2        # paged block-table handoff (zero KV bytes)
DENSE_HANDOFF_VERSION = 1  # dense staged-stripe handoff

# consecutive tombstoned handoffs before the engine stops routing to the
# lane entirely (degrade-to-colocated for the rest of the run); one
# successful handoff resets the run
DROPS_TO_DEGRADE = 3

# consume-side last resort: a routed slot whose handoff has not arrived
# (payload OR tombstone) within this many seconds is re-prefilled
# colocated — a lane that dies without flushing can never hang a client.
# Generous on purpose: the lane tombstones every per-job failure and
# flushes its queue on crash, so this only fires when even that machinery
# is gone.
HANDOFF_TIMEOUT_S = 60.0


@dataclass
class KVHandoff:
    """One finished prefill crossing lanes (the wire unit of the
    protocol). ``kv`` is the staged slot stripe in the model's cache-tree
    layout — ``{"k","v"}`` leaves ``[L, 1, KVH, T, D]``, plus
    ``{"k_s","v_s"}`` ``[L, 1, KVH, T]`` f32 scales when the KV cache is
    int8-quantized — exactly what ``update_cache_slots`` writes back at
    the destination slot. ``dropped=True`` marks a tombstone: the
    payload was lost (injected drop, lane error, version mismatch) and
    the consumer must degrade to colocated prefill."""

    version: int
    request_id: str
    handle: Any                      # the engine RequestHandle (identity key)
    n_tokens: int = 0                # prompt tokens whose KV is staged
    n_blocks: int = 0                # ceil(n_tokens / kv_block_size)
    reused_prefix_tokens: int = 0    # prefix attribution (v1: lane has no index)
    chunks: int = 0                  # lane prefill pieces dispatched
    busy_s: float = 0.0              # lane compute wall for this prefill
    kv: Optional[dict[str, Any]] = None      # staged stripe (None on tombstone)
    logits: Optional[Any] = None     # [V] f32 last-position logits
    t_enqueued: float = 0.0          # handoff-queue entry (wait accounting)
    dropped: bool = False            # tombstone: degrade to colocated
    error: str = ""                  # why (tombstones only)


@dataclass
class _LaneStats:
    """Lane-internal counters, published under one lock (KVM05x: the
    lane thread writes, snapshot readers are server/scheduler threads)."""

    prefills: int = 0
    busy_s: float = 0.0
    drops: int = 0
    errors: int = 0
    lock: threading.Lock = field(default_factory=threading.Lock)


class PrefillLane:
    """The dedicated prefill worker of a disaggregated engine.

    Owns a 1-slot staging KV cache plus its own compiled prefill
    executables, consumes routed admissions from a bounded job queue,
    and pushes finished :class:`KVHandoff` payloads (or tombstones —
    NEVER nothing) onto the ready queue the engine's scheduler drains
    between sweeps. All cross-thread state is internally locked or
    thread-safe queues; the staging cache and compiled-fn dict are
    lane-thread-only.
    """

    def __init__(
        self,
        params: dict[str, Any],
        cfg: Any,                    # models/config.py ModelConfig
        ecfg: Any,                   # runtime/engine.py EngineConfig
        pad_id: int = 0,
        instrument: Optional[Callable[[Any, str], Any]] = None,
        faults: Optional[Any] = None,         # runtime/faults.py FaultRegistry
        prefill_mesh: Optional[Any] = None,   # parallel/mesh.lane_meshes submesh
        max_inflight: Optional[int] = None,
        paged_prefill: Optional[Callable[[Any, dict], Any]] = None,
    ) -> None:
        self.cfg = cfg
        self.ecfg = ecfg
        self.pad_id = pad_id
        self._instrument = instrument or (lambda fn, label: fn)
        self._faults = faults
        self.prefill_mesh = prefill_mesh
        # HANDOFF_VERSION=2 hook (paged engines): the engine's
        # _lane_paged_prefill bound method — (handle, meta) -> (logits,
        # chunks) — which writes the prompt's KV straight into the
        # shared-pool blocks meta["row"] names. None = v1 dense lane
        # with its own staging cache.
        self._paged_prefill = paged_prefill
        # backpressure bound: jobs routed but not yet handed off. Past it
        # the engine admits colocated (accepts() goes False) — the lane
        # sheds load back to the decode lane instead of queueing unbounded
        self.max_inflight = max_inflight or max(ecfg.max_slots, 1)
        if prefill_mesh is not None:
            # per-lane mesh (parallel/mesh.lane_meshes): the lane computes
            # on its own device subset with tp-sharded params; the staged
            # stripe crosses lanes through host memory (_to_host below)
            from kserve_vllm_mini_tpu.parallel.sharding import shard_params

            self.params = shard_params(params, cfg, prefill_mesh)
        else:
            # thread-only lanes share the engine's params by reference —
            # zero weight duplication, the handoff stays on-device
            self.params = params
        self._staging: Optional[dict[str, Any]] = None  # lazy (lane thread)
        self._prefill_fns: dict[Any, Any] = {}
        self._jobs: "queue.Queue[Any]" = queue.Queue()
        self._ready: "queue.Queue[KVHandoff]" = queue.Queue()
        self._inflight = 0               # routed-not-yet-ready, under _lock
        self._lock = threading.Lock()
        self.stats = _LaneStats()
        self._stop = threading.Event()
        self._dead = False               # lane loop crashed (under _lock)
        self._thread: Optional[threading.Thread] = None

    # -- engine-facing API (any thread) ------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="prefill-lane"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)

    def accepts(self) -> bool:
        """Whether the engine should route the next admission here:
        lane alive and under the backpressure bound. False = admit
        colocated (the degrade ladder's zeroth step)."""
        with self._lock:
            return (
                not self._dead
                and not self._stop.is_set()
                and self._inflight < self.max_inflight
            )

    def queue_depth(self) -> int:
        """Routed prefills not yet consumed (jobs pending or computing
        plus finished handoffs awaiting the scheduler) — the
        ``kv_handoff_queue_depth`` gauge and the ``handoff_stall``
        monitor rule's input."""
        with self._lock:
            return self._inflight

    def submit(self, handle: Any, meta: Optional[dict] = None) -> None:
        """Route one admission to the lane (scheduler thread; the caller
        checked ``accepts()``). ``meta`` is the v2 block reservation —
        ``{"row", "off", "keys"}`` — for paged lanes; None on v1."""
        with self._lock:
            self._inflight += 1
        self._jobs.put((handle, meta))

    def pop_ready(self) -> Optional[KVHandoff]:
        """Next finished handoff (payload or tombstone), or None. The
        scheduler drains these between sweeps (_consume_handoffs)."""
        try:
            ho = self._ready.get_nowait()
        except queue.Empty:
            return None
        with self._lock:
            self._inflight -= 1
        return ho

    def snapshot(self) -> dict[str, Any]:
        with self.stats.lock:
            return {
                "lane_prefills": self.stats.prefills,
                "lane_busy_s": self.stats.busy_s,
                "lane_drops": self.stats.drops,
                "lane_errors": self.stats.errors,
            }

    # -- lane thread -------------------------------------------------------

    def _run(self) -> None:
        try:
            while not self._stop.is_set():
                try:
                    handle, meta = self._jobs.get(timeout=0.05)
                except queue.Empty:
                    continue
                ho = self._one_job(handle, meta)
                ho.t_enqueued = time.time()
                self._ready.put(ho)
        finally:
            # the lane must NEVER exit with jobs unanswered: whatever is
            # still queued tombstones out so the consume path degrades
            # those requests to colocated prefill instead of hanging them
            with self._lock:
                self._dead = True
            while True:
                try:
                    h, _ = self._jobs.get_nowait()
                except queue.Empty:
                    break
                ho = self._tombstone(h, "prefill lane stopped")
                ho.t_enqueued = time.time()
                self._ready.put(ho)

    def _one_job(self, handle: Any, meta: Optional[dict] = None) -> KVHandoff:
        """One routed prefill -> exactly one KVHandoff (payload or
        tombstone — every exit path answers, the never-hang contract)."""
        if handle.cancelled is not None:
            # cancelled while queued in the lane: skip the compute; the
            # consume/cancel path already finishes the handle
            return self._tombstone(handle, "cancelled before lane prefill")
        try:
            if meta is not None and self._paged_prefill is not None:
                ho = self._paged_job(handle, meta)
            else:
                ho = self._prefill(handle)
        except Exception as e:  # noqa: BLE001 — a lane fault must become
            # a tombstone (degrade-to-colocated), never an unanswered job
            with self.stats.lock:
                self.stats.errors += 1
            return self._tombstone(handle, f"{type(e).__name__}: {e}")
        if self._faults is not None and self._faults.check("kv_handoff_drop"):
            # injected handoff loss (docs/RESILIENCE.md): the compute is
            # spent — exactly like a payload lost on a real transport —
            # and the tombstone makes the engine re-prefill colocated
            with self.stats.lock:
                self.stats.drops += 1
            return self._tombstone(
                handle, "injected kv_handoff_drop", busy_s=ho.busy_s,
            )
        return ho

    def _tombstone(self, handle: Any, error: str,
                   busy_s: float = 0.0) -> KVHandoff:
        return KVHandoff(
            version=(
                HANDOFF_VERSION if self._paged_prefill is not None
                else DENSE_HANDOFF_VERSION
            ),
            request_id=handle.request.request_id,
            handle=handle, busy_s=busy_s, dropped=True, error=error,
        )

    def _paged_job(self, handle: Any, meta: dict) -> KVHandoff:
        """HANDOFF_VERSION=2: delegate the compute to the engine's
        _lane_paged_prefill (same executables as colocated — the KV
        lands directly in the reserved shared-pool blocks) and hand back
        a table-only payload: zero KV bytes cross the lanes."""
        t0 = time.time()
        logits, chunks = self._paged_prefill(handle, meta)
        wall = time.time() - t0
        n = len(handle.request.prompt_tokens)
        blk = max(getattr(self.ecfg, "kv_block_size", 64), 1)
        with self.stats.lock:
            self.stats.prefills += 1
            self.stats.busy_s += wall
        return KVHandoff(
            version=HANDOFF_VERSION,
            request_id=handle.request.request_id,
            handle=handle,
            n_tokens=n,
            n_blocks=-(-n // blk),
            reused_prefix_tokens=int(meta.get("off", 0)),
            chunks=chunks,
            busy_s=wall,
            kv=None,
            logits=logits,
        )

    # -- compiled staging prefill (lane thread only) ------------------------

    def _bucket(self, n: int) -> int:
        b = self.ecfg.min_prefill_bucket
        while b < n:
            b *= 2
        return min(b, self.ecfg.max_prefill_len)

    def _make_staging(self) -> dict[str, Any]:
        import jax.numpy as jnp

        from kserve_vllm_mini_tpu.models.llama import init_kv_cache

        kv_quant = self.ecfg.kv_cache_dtype == "int8"
        kv_dt = (
            jnp.dtype(self.ecfg.kv_cache_dtype)
            if (self.ecfg.kv_cache_dtype and not kv_quant)
            else None
        )
        return init_kv_cache(
            self.cfg, 1, max_seq=self.ecfg.max_seq_len,
            dtype=kv_dt, quantized=kv_quant,
        )

    def _get_fresh_fn(self, bucket: int):
        key = ("fresh", bucket)
        fn = self._prefill_fns.get(key)
        if fn is not None:
            return fn
        import jax
        import jax.numpy as jnp
        from functools import partial

        from kserve_vllm_mini_tpu.models.llama import forward

        cfg = self.cfg

        @partial(jax.jit, donate_argnums=(1,))
        def fresh(params, cache, tokens, length):
            # tokens [1, bucket]; the staging cache IS the slot (B=1), so
            # no slice/update pair — forward writes rows 0..bucket-1 and
            # only the prompt's last position is sampled (logit_index)
            pos = jnp.arange(tokens.shape[1], dtype=jnp.int32)[None]
            logits, nc = forward(
                params, cfg, tokens, pos,
                cache, jnp.zeros((1,), jnp.int32),
                fresh_prefill=True,
                logit_index=(length - 1)[None],
            )
            return nc, logits[0, 0]

        fresh = self._instrument(fresh, f"disagg_prefill[{bucket}]")
        self._prefill_fns[key] = fresh
        return fresh

    def _get_chunk_fn(self, bucket: int):
        key = ("chunk", bucket)
        fn = self._prefill_fns.get(key)
        if fn is not None:
            return fn
        import jax
        import jax.numpy as jnp
        from functools import partial

        from kserve_vllm_mini_tpu.models.llama import forward

        cfg = self.cfg

        @partial(jax.jit, donate_argnums=(1,))
        def chunk(params, cache, tokens, length, offset):
            pos = offset + jnp.arange(tokens.shape[1], dtype=jnp.int32)[None]
            logits, nc = forward(
                params, cfg, tokens, pos,
                cache, offset[None],
                logit_index=(length - 1)[None],
            )
            return nc, logits[0, 0]

        chunk = self._instrument(chunk, f"disagg_chunk_prefill[{bucket}]")
        self._prefill_fns[key] = chunk
        return chunk

    def _get_slice_fn(self):
        """Jitted UNDONATED copy of the staging stripe: the payload must
        survive the next job's donated prefill over the same staging
        buffers."""
        fn = self._prefill_fns.get("slice")
        if fn is not None:
            return fn
        import jax

        from kserve_vllm_mini_tpu.models.llama import slice_cache_slots

        fn = jax.jit(lambda cache: slice_cache_slots(cache, 0))
        self._prefill_fns["slice"] = fn
        return fn

    def _prefill(self, handle: Any) -> KVHandoff:
        """Run one prompt's prefill against the staging cache: the same
        piece schedule as colocated monolithic admission (fresh piece at
        the prompt's bucket, continuation pieces at max_prefill_len), so
        the staged KV and last-position logits are byte-identical to
        what the engine would have computed in place."""
        import jax
        import jax.numpy as jnp

        req = handle.request
        prompt = req.prompt_tokens
        n = len(prompt)
        if self._staging is None:
            self._staging = self._make_staging()
        t0 = time.time()
        off, chunks = 0, 0
        last_logits = None
        budget = self.ecfg.max_prefill_len
        while off < n:
            piece = prompt[off : off + budget]
            m = len(piece)
            bucket = self._bucket(m)
            toks = piece + [self.pad_id] * (bucket - m)
            tokens = jnp.asarray(toks, dtype=jnp.int32)[None]
            if off == 0:
                self._staging, last_logits = self._get_fresh_fn(bucket)(
                    self.params, self._staging, tokens, jnp.int32(m)
                )
            else:
                self._staging, last_logits = self._get_chunk_fn(bucket)(
                    self.params, self._staging, tokens,
                    jnp.int32(m), jnp.int32(off),
                )
            off += m
            chunks += 1
        payload = self._get_slice_fn()(self._staging)
        logits = last_logits
        if self.prefill_mesh is not None:
            # cross-mesh handoff travels through host memory: the decode
            # lane's inject re-uploads into its own layout. Same-device
            # lanes skip this (the payload stays on device, zero copies).
            payload = jax.device_get(payload)
            logits = jax.device_get(logits)
        else:
            jax.block_until_ready(logits)
        wall = time.time() - t0
        blk = max(getattr(self.ecfg, "kv_block_size", 64), 1)
        with self.stats.lock:
            self.stats.prefills += 1
            self.stats.busy_s += wall
        return KVHandoff(
            version=DENSE_HANDOFF_VERSION,
            request_id=req.request_id,
            handle=handle,
            n_tokens=n,
            n_blocks=-(-n // blk),
            reused_prefix_tokens=0,
            chunks=chunks,
            busy_s=wall,
            kv=payload,
            logits=logits,
        )
