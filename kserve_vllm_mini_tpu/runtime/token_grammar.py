"""Token-level grammar constraints over arbitrary vocabularies.

The byte automata in ``runtime/constrain.py`` (JsonMachine / TemplateMachine)
define WHAT byte strings are legal; this module maps that onto WHICH TOKENS a
given tokenizer may emit next — the piece the reference's engines get from
token-grammar libraries (surface exercised by
/root/reference/scripts/openai_parity_probe.py:104-186). Two adapters share
the engine-facing protocol (``token_mask(budget) -> bool[V]``,
``advance_token(id)``, ``min_close()``, ``done``):

- ``ByteTokenMachine`` — the ByteTokenizer identity case: token id == byte+3.
- ``HFTokenMachine`` — real HF vocabularies (BPE / sentencepiece / wordlevel).
  Each token id is pre-expanded to its byte sequence once per tokenizer
  (``HFVocabTable``); per step the mask enables
    (a) every single-byte token whose byte the automaton allows, and
    (b) when the automaton is inside a string, every multi-byte token made
        purely of string-safe bytes that fits the string's remaining room
        and leaves the close affordable.
  (b) is what makes real-model JSON fluent (whole words per step) while (a)
  alone already guarantees progress and closure: the table is validated at
  build time to contain a single-byte token for every structural byte the
  grammar can force, so the masked set can never go empty while closing
  remains possible.

Budget semantics: the engine's budget is in TOKENS; the automata count
BYTES. Every token advances the automaton by >= 1 byte, so passing the token
budget as the byte budget is conservative — closure within N bytes implies
closure within N single-byte tokens.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from kserve_vllm_mini_tpu.runtime.constrain import _STR_BYTES

# bytes the grammars can force as the ONLY legal continuation: structure,
# the forced-close digit (min_close counts one digit per open value), the
# literal bodies of true/false/null, and the tool-call template's fixed
# literals ('[{"name": "', '", "arguments": ' — constrain.py
# tool_call_constraint), whose every byte is forced once a call starts
_REQUIRED_SINGLE_BYTES = bytes(set(
    b'{}[],:" 0123456789'
    + b"true" + b"false" + b"null"
    + b'[{"name": "' + b'", "arguments": ' + b"}]" + b", "
))


class ByteTokenMachine:
    """Token adapter for the ByteTokenizer (token id == byte + SPECIALS)."""

    SPECIALS = 3

    def __init__(self, machine, vocab_size: int) -> None:
        self.machine = machine
        self.vocab_size = vocab_size

    @property
    def done(self) -> bool:
        return self.machine.done

    def min_close(self) -> int:
        return self.machine.min_close()

    def token_mask(self, budget: int) -> np.ndarray:
        mask = np.zeros((self.vocab_size,), dtype=bool)
        for b in self.machine.allowed(budget):
            tid = b + self.SPECIALS
            if tid < self.vocab_size:
                mask[tid] = True
        return mask

    def advance_token(self, tid: int) -> None:
        self.machine.advance(tid - self.SPECIALS)


class HFVocabTable:
    """Per-tokenizer precomputation: token id -> byte expansion, plus the
    indexes the per-step mask needs (single-byte map; string-safe
    multi-byte tokens grouped by length)."""

    def __init__(self, table: Sequence[Optional[bytes]]) -> None:
        self.table = list(table)
        self.n_tokens = len(self.table)
        self.single: dict[int, int] = {}
        str_ids: list[int] = []
        str_lens: list[int] = []
        strset = frozenset(_STR_BYTES)
        for tid, bs in enumerate(self.table):
            if not bs:
                continue
            if len(bs) == 1:
                self.single.setdefault(bs[0], tid)
            elif all(c in strset for c in bs):
                str_ids.append(tid)
                str_lens.append(len(bs))
        self.str_ids = np.asarray(str_ids, dtype=np.int64)
        self.str_lens = np.asarray(str_lens, dtype=np.int64)
        missing = [
            chr(b) for b in sorted(set(_REQUIRED_SINGLE_BYTES))
            if b not in self.single
        ]
        if missing:
            raise ValueError(
                "tokenizer lacks single-byte tokens the grammar can force: "
                f"{missing!r} — constrained decoding could deadlock, refusing"
            )


class HFTokenMachine:
    """Drives a byte automaton with real-vocabulary tokens.

    ``model_vocab_size`` sizes the mask to the MODEL's logits (may exceed
    the tokenizer's id space; the excess stays disallowed)."""

    def __init__(self, machine, vocab: HFVocabTable, model_vocab_size: int) -> None:
        if vocab.n_tokens > model_vocab_size:
            raise ValueError(
                f"tokenizer has {vocab.n_tokens} ids but the model only "
                f"{model_vocab_size} logits"
            )
        self.machine = machine
        self.vocab = vocab
        self.vocab_size = model_vocab_size

    @property
    def done(self) -> bool:
        return self.machine.done

    def min_close(self) -> int:
        return self.machine.min_close()

    def token_mask(self, budget: int) -> np.ndarray:
        mask = np.zeros((self.vocab_size,), dtype=bool)
        for b in self.machine.allowed(budget):
            tid = self.vocab.single.get(b)
            if tid is not None:
                mask[tid] = True
        # multi-byte tokens: string interiors only — they never complete the
        # machine mid-token, every byte is string-legal, and one token spends
        # one unit of the token budget, so the close must fit in budget-1
        room = self.machine.str_room()
        if room is not None and budget - 1 >= self.machine.min_close():
            sel = self.vocab.str_ids[self.vocab.str_lens <= room]
            mask[sel] = True
        return mask

    def advance_token(self, tid: int) -> None:
        bs = self.vocab.table[tid] if tid < self.vocab.n_tokens else None
        if not bs:
            raise ValueError(f"token {tid} has no byte expansion (special?)")
        for b in bs:
            self.machine.advance(b)


# -- token id -> bytes extraction -------------------------------------------

def _bytelevel_decoder() -> dict[str, int]:
    """The GPT-2 byte-level BPE printable-unicode <-> byte bijection
    (public algorithm used by every byte-level BPE tokenizer)."""
    bs = (
        list(range(ord("!"), ord("~") + 1))
        + list(range(0xA1, 0xAD))
        + list(range(0xAE, 0x100))
    )
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return {chr(c): b for b, c in zip(bs, cs)}


def token_bytes_table(hf_tokenizer) -> list[Optional[bytes]]:
    """Byte expansion for every id of a transformers tokenizer, handling the
    three encodings in the wild: byte-level BPE (Ġ-style), sentencepiece
    (▁-style with <0xNN> byte fallbacks), and plain word/char vocabularies.
    Specials map to None (never maskable)."""
    t = getattr(hf_tokenizer, "_tok", hf_tokenizer)
    n = len(t)
    tokens = t.convert_ids_to_tokens(list(range(n)))
    special_ids = set(getattr(t, "all_special_ids", []) or [])
    sample = [s for s in tokens if s][:2000]
    bytelevel = any("Ġ" in s or "Ċ" in s for s in sample)
    spiece = any("▁" in s for s in sample)
    bl = _bytelevel_decoder() if bytelevel else None

    out: list[Optional[bytes]] = []
    for tid, s in enumerate(tokens):
        if s is None or tid in special_ids:
            out.append(None)
            continue
        if bytelevel:
            try:
                out.append(bytes(bl[c] for c in s))
                continue
            except KeyError:  # kvmini: workload-ok — added tokens are stored
                # verbatim (not byte-encoded); utf-8 IS their byte form
                out.append(s.encode("utf-8"))
                continue
        if spiece and len(s) == 6 and s.startswith("<0x") and s.endswith(">"):
            try:
                out.append(bytes([int(s[3:5], 16)]))
                continue
            except ValueError:  # kvmini: workload-ok — not a <0xNN> byte
                pass            # token after all; falls through to text path
        if spiece:
            out.append(s.replace("▁", " ").encode("utf-8"))
        else:
            out.append(s.encode("utf-8"))
    return out
