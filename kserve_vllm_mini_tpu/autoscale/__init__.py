from kserve_vllm_mini_tpu.autoscale.controller import (  # noqa: F401
    Controller,
    PolicyConfig,
    Signals,
    desired_replicas,
)
