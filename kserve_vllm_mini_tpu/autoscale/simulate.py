"""Autoscale policy simulation: replay a load timeline against the
controller's policy without a cluster.

The reference can only tune Knative knobs by deploying and measuring
(sweeps/autoscale-sweep.sh — hours per point); the in-repo controller's
policy core is a pure function (`controller.desired_replicas`), so the
whole what-if space replays in milliseconds: recorded run-dir traffic (or
a synthetic arrival pattern) drives a fluid queue model, the controller
polls simulated fleet signals on its real cadence, and scale-ups apply
after a provisioning delay — minutes on TPU pools (docs/TOPOLOGY.md), the
thing that actually decides whether a policy survives a burst.

Model (deliberately simple, stated so the numbers are interpretable):
- each request arrives at its timestamp carrying `tokens_out` tokens of
  decode work (or 1 unit when the timeline has no token counts);
- the fleet serves FIFO at ``replicas x rate`` work-units/s; a request
  completes when its work is drained;
- duty cycle = capacity utilization of the step, queue depth = requests
  waiting or in service beyond instantaneous capacity — the same two
  signals the live /metrics endpoint feeds the controller;
- scale-up decisions become capacity only after ``provision_delay_s``
  (pending replicas are tracked with ready times); scale-down is
  immediate (killing a pod is fast, providing one is not).

Outputs: the controller's own decision log plus a per-step series CSV and
a summary (peak/mean queue, request p50/p95 wait, replica-seconds = the
cost proxy, unserved backlog at end) -> ``autoscale_sim.json`` in the run
dir, which the report layer's decision-timeline section can plot against
the load.
"""

from __future__ import annotations

import argparse
import csv
import json
import math
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Optional

from kserve_vllm_mini_tpu.autoscale.controller import (
    Controller,
    PolicyConfig,
    Signals,
)


@dataclass
class SimConfig:
    rate_per_replica: float = 2000.0   # work-units/s (tokens/s/chip scale)
    poll_interval_s: float = 15.0
    provision_delay_s: float = 180.0   # TPU pool cold start: minutes
    initial_replicas: int = 1
    drain_s: float = 120.0             # sim tail after the last arrival


@dataclass
class SimResult:
    steps: list[dict[str, Any]] = field(default_factory=list)
    decisions: list[dict[str, Any]] = field(default_factory=list)
    summary: dict[str, Any] = field(default_factory=dict)


def load_timeline_from_rundir(run_dir: str) -> list[tuple[float, float]]:
    """(arrival_ts, work_units) per request from a recorded requests.csv,
    times rebased to 0. Work = tokens_out when recorded (>0), else 1."""
    path = Path(run_dir) / "requests.csv"
    rows: list[tuple[float, float]] = []
    with path.open() as f:
        for rec in csv.DictReader(f):
            try:
                ts = float(rec.get("scheduled_ts") or rec.get("start_ts") or 0)
            except ValueError:
                continue
            if ts <= 0:
                continue
            try:
                work = float(rec.get("tokens_out") or 0)
            except ValueError:
                work = 0.0
            rows.append((ts, work if work > 0 else 1.0))
    if not rows:
        raise ValueError(f"no usable rows in {path}")
    rows.sort()
    t0 = rows[0][0]
    return [(ts - t0, w) for ts, w in rows]


def synthetic_timeline(
    pattern: str, requests: int, duration_s: float,
    work_per_request: float = 64.0, seed: int = 42,
) -> list[tuple[float, float]]:
    """Synthetic arrivals through the load generator's own pattern engine
    (loadgen/arrivals.py) so the sim and a real run share traffic shapes."""
    from kserve_vllm_mini_tpu.loadgen.arrivals import generate_arrival_times

    times = generate_arrival_times(pattern, requests, duration_s, seed=seed)
    return [(t, work_per_request) for t in times]


def simulate(
    timeline: list[tuple[float, float]],
    sim: Optional[SimConfig] = None,
    policy: Optional[PolicyConfig] = None,
) -> SimResult:
    sim = sim or SimConfig()
    policy = policy or PolicyConfig()
    res = SimResult()

    from collections import deque

    # fluid queue: FIFO of [remaining_work, arrival_ts]; completed requests
    # record their wait (arrival -> fully served). deque: a deep
    # underprovisioned backlog would make list.pop(0) O(n²)
    queue: "deque[list[float]]" = deque()
    waits: list[float] = []
    clock = {"t": 0.0}

    # fleet state: active replicas + pending scale-ups with ready times
    state = {"active": sim.initial_replicas}
    pending: list[tuple[float, int]] = []   # (ready_ts, target_count)
    # signals computed by the previous sim step, handed to the controller
    last_sig = {"duty": 0.0, "queue": 0}

    def now_fn() -> float:
        return clock["t"]

    def scaler(n: int) -> None:
        # ANY new target invalidates in-flight scale-ups above it — also
        # an intermediate shrink issued while capacity is still
        # provisioning (active < n < old pending), or the stale pendings
        # would land later and pin the fleet above desired
        pending[:] = [(ts, t) for ts, t in pending if t <= n]
        if n <= state["active"]:
            state["active"] = n          # shrink: immediate
        else:
            pending.append((clock["t"] + sim.provision_delay_s, n))

    def signal_fn() -> Signals:
        return Signals(
            duty_cycle=last_sig["duty"],
            queue_depth=float(last_sig["queue"]),
            ts=clock["t"],
            valid=True,
        )

    ctl = Controller(
        signal_fn, scaler, policy,
        initial_replicas=sim.initial_replicas, now_fn=now_fn,
    )

    horizon = (timeline[-1][0] if timeline else 0.0) + sim.drain_s
    dt = sim.poll_interval_s
    n_steps = max(int(math.ceil(horizon / dt)), 1)
    arr_idx = 0
    replica_seconds = 0.0

    for step in range(n_steps):
        t_end = (step + 1) * dt
        # provisioned capacity lands when ready
        for ready_ts, target in sorted(pending):
            if ready_ts <= t_end:
                state["active"] = max(state["active"], target)
        pending[:] = [(ts, t) for ts, t in pending if ts > t_end]

        # arrivals within the step
        while arr_idx < len(timeline) and timeline[arr_idx][0] < t_end:
            ts, work = timeline[arr_idx]
            queue.append([work, ts])
            arr_idx += 1

        # serve FIFO with this step's capacity
        capacity = state["active"] * sim.rate_per_replica * dt
        served = 0.0
        while queue and capacity > 0:
            need = queue[0][0]
            take = min(need, capacity)
            queue[0][0] -= take
            capacity -= take
            served += take
            if queue[0][0] <= 1e-9:
                _, arrived = queue.popleft()
                waits.append(t_end - arrived)
        total_capacity = state["active"] * sim.rate_per_replica * dt
        last_sig["duty"] = min(served / total_capacity, 1.0) if total_capacity else 0.0
        last_sig["queue"] = len(queue)
        replica_seconds += state["active"] * dt

        clock["t"] = t_end
        ctl.step()
        res.steps.append({
            "t": t_end,
            "replicas_active": state["active"],
            "replicas_desired": ctl.replicas,
            "pending_ups": len(pending),
            "queue": len(queue),
            "duty": round(last_sig["duty"], 4),
        })

    waits_sorted = sorted(waits)

    def pct(p: float) -> float:
        if not waits_sorted:
            return 0.0
        i = min(int(p * (len(waits_sorted) - 1)), len(waits_sorted) - 1)
        return waits_sorted[i]

    res.decisions = ctl.decisions
    res.summary = {
        "requests": len(timeline),
        "completed": len(waits),
        "unserved_at_end": len(queue),
        "peak_queue": max((s["queue"] for s in res.steps), default=0),
        "wait_p50_s": round(pct(0.50), 2),
        "wait_p95_s": round(pct(0.95), 2),
        "replica_seconds": round(replica_seconds, 1),
        "peak_replicas": max((s["replicas_active"] for s in res.steps), default=0),
        "final_replicas": state["active"],
        "provision_delay_s": sim.provision_delay_s,
    }
    return res


# -- CLI ---------------------------------------------------------------------

def register(parser: argparse.ArgumentParser) -> None:
    src = parser.add_mutually_exclusive_group(required=True)
    src.add_argument("--run-dir", help="Replay a recorded requests.csv timeline")
    from kserve_vllm_mini_tpu.loadgen.arrivals import PATTERNS

    src.add_argument("--pattern", choices=sorted(PATTERNS),
                     help="Synthesize arrivals with the loadgen's pattern engine")
    parser.add_argument("--requests", type=int, default=200,
                        help="Synthetic request count (--pattern)")
    parser.add_argument("--duration", type=float, default=300.0,
                        help="Synthetic timeline seconds (--pattern)")
    parser.add_argument("--work-per-request", type=float, default=64.0,
                        help="Work units (output tokens) per synthetic request")
    parser.add_argument("--rate-per-replica", type=float, default=2000.0,
                        help="Serving rate per replica, work-units/s "
                             "(tokens/s/chip; see docs/PERFORMANCE.md)")
    parser.add_argument("--provision-delay", type=float, default=180.0,
                        help="Seconds before a scale-up becomes capacity "
                             "(TPU pools provision in minutes)")
    parser.add_argument("--interval", type=float, default=15.0)
    parser.add_argument("--drain", type=float, default=None,
                        help="Sim tail seconds after the last arrival "
                             "(default: max(120, 2x provisioning delay) so "
                             "late-landing capacity and its drain are "
                             "always observed)")
    parser.add_argument("--min", type=int, default=1)
    parser.add_argument("--max", type=int, default=8)
    parser.add_argument("--target-duty", type=float, default=0.75)
    parser.add_argument("--target-queue", type=float, default=4.0)
    parser.add_argument("--initial-replicas", type=int, default=1)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--output", default=None,
                        help="Write autoscale_sim.json here (default: "
                             "<run-dir>/autoscale_sim.json or stdout only)")


def run(args: argparse.Namespace) -> int:
    if args.run_dir:
        timeline = load_timeline_from_rundir(args.run_dir)
    else:
        timeline = synthetic_timeline(
            args.pattern, args.requests, args.duration,
            work_per_request=args.work_per_request, seed=args.seed,
        )
    res = simulate(
        timeline,
        SimConfig(
            rate_per_replica=args.rate_per_replica,
            poll_interval_s=args.interval,
            provision_delay_s=args.provision_delay,
            initial_replicas=args.initial_replicas,
            drain_s=(args.drain if args.drain is not None
                     else max(120.0, 2.0 * args.provision_delay)),
        ),
        PolicyConfig(
            min_replicas=args.min, max_replicas=args.max,
            target_duty=args.target_duty,
            target_queue_per_replica=args.target_queue,
        ),
    )
    print(json.dumps(res.summary, indent=2))
    out = args.output
    if out is None and args.run_dir:
        out = str(Path(args.run_dir) / "autoscale_sim.json")
    if out:
        Path(out).write_text(json.dumps({
            "summary": res.summary,
            "steps": res.steps,
            "decisions": res.decisions,
        }, indent=2))
        print(f"wrote {out}")
    return 0
