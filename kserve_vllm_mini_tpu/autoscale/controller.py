"""SLO-signal-driven autoscaling controller.

The reference MEASURES Knative's autoscaler from outside (its autoscale
sweep tunes minScale/maxScale/containerConcurrency knobs and records cold
starts — sweeps/autoscale-sweep.sh:25-163); this module closes the loop
the harness already instruments: the runtime's own /metrics signals
(duty cycle, queue depth — runtime/server.py) plus the SLO gate's verdict
(gates/slo.py) drive replica counts directly.

Design (HPA-style target tracking, simplified to what the signals
support):

- **scale up** when duty cycle exceeds its target (the engine is
  saturated) or queued requests per replica exceed their target (work is
  waiting) — desired = ceil(current x signal / target), the standard
  proportional rule; an SLO breach (p95 / TTFT / error-rate over budget)
  forces at least one step up immediately.
- **scale down** only when duty sits under a low watermark AND every
  desired value across the stabilization window agrees — the max of the
  window wins (Kubernetes HPA's downscale stabilization), so one quiet
  poll can't shed replicas a burst will need back (cold starts on TPU
  pools are minutes, docs/TOPOLOGY.md; flapping is far more expensive
  than holding a replica).
- **actuation** is pluggable: a KServe patch through deploy.Kubectl
  (min/max replica fields + Knative min-scale annotation), or dry-run
  recording. Every decision lands in a JSONL log the report layer can
  plot against the load timeline.

The policy core is a pure function (``desired_replicas``) so the whole
behavior matrix is unit-testable without a cluster or clock.
"""

from __future__ import annotations

import argparse
import json
import math
import sys
import time
import urllib.request
from dataclasses import dataclass
from pathlib import Path
from typing import Any, Callable, Optional


@dataclass
class PolicyConfig:
    min_replicas: int = 1
    max_replicas: int = 8
    # duty cycle the fleet should sit at; above it the engines are compute-
    # saturated and latency grows with queue depth
    target_duty: float = 0.75
    # queued requests per replica the fleet may carry before adding one
    target_queue_per_replica: float = 4.0
    # below this duty the fleet is idle enough to consider shrinking
    scale_down_duty: float = 0.30
    # downscale stabilization: shrink only to the MAX desired seen over
    # this window (HPA semantics)
    stabilization_s: float = 120.0
    # never add more than this many replicas in one step (TPU pools
    # provision slowly; a huge jump mostly buys pending pods)
    max_step_up: int = 4
    # cost-aware mode (docs/ECONOMICS.md): when the fleet's MARGINAL
    # replica prices its own tokens above the $/1K-tok budget, shed it —
    # one replica per step, vetoed by an SLO breach and by queue
    # pressure (a queue means the "unprofitable" replica is about to be
    # needed; cost never outranks the latency SLO). Inert without a
    # budget so the default policy is unchanged.
    cost_aware: bool = False
    cost_budget_usd_per_1k_tok: Optional[float] = None


@dataclass
class Signals:
    """One poll of the fleet's state, already aggregated across replicas."""

    duty_cycle: float = 0.0        # mean across replicas, 0..1
    queue_depth: float = 0.0       # total queued requests
    slo_breached: bool = False     # gate verdict on the latest results
    # live-economics gauges from the SAME scrape that produced duty/queue
    # (docs/ECONOMICS.md); None when the runtime exports no rail — the
    # cost-aware rule is inert on missing data, never reads it as free
    usd_per_1k_tok: Optional[float] = None
    marginal_usd_per_1k_tok: Optional[float] = None
    ts: float = 0.0
    # False when the poll produced no data (endpoint down / pod churn):
    # the controller HOLDS the current count — zero-signals must not be
    # read as "idle" and shed the capacity a restarting fleet needs
    valid: bool = True


def desired_replicas(current: int, sig: Signals, cfg: PolicyConfig) -> int:
    """Pure target-tracking policy: what the fleet should run RIGHT NOW
    given one signal sample (stabilization is the controller's job)."""
    want = current
    if sig.duty_cycle > cfg.target_duty:
        want = max(want, math.ceil(current * sig.duty_cycle / cfg.target_duty))
    queue_per = sig.queue_depth / max(current, 1)
    if queue_per > cfg.target_queue_per_replica:
        want = max(
            want,
            math.ceil(current * queue_per / cfg.target_queue_per_replica),
        )
    if sig.slo_breached:
        want = max(want, current + 1)
    if (
        want <= current
        and sig.duty_cycle < cfg.scale_down_duty
        and sig.queue_depth == 0
        and not sig.slo_breached
    ):
        # idle: propose proportional shrink, floored so one replica of
        # headroom always remains ahead of the next request
        want = min(
            want,
            max(math.ceil(current * sig.duty_cycle / cfg.target_duty), 1),
        )
    if (
        cfg.cost_aware
        and cfg.cost_budget_usd_per_1k_tok is not None
        and sig.marginal_usd_per_1k_tok is not None
        and sig.marginal_usd_per_1k_tok > cfg.cost_budget_usd_per_1k_tok
        and want <= current
        and current > 1
        and not sig.slo_breached
        and queue_per <= cfg.target_queue_per_replica
    ):
        # the marginal replica prices its tokens over budget: shed ONE
        # replica (never a proportional collapse — each shed re-prices
        # the survivors, so re-evaluate from the new count next poll).
        # SLO breach and queue pressure veto: a replica that keeps the
        # fleet inside its latency budget is worth running at a loss.
        want = min(want, current - 1)
    want = max(cfg.min_replicas, min(cfg.max_replicas, want))
    if want > current:
        want = min(want, current + cfg.max_step_up)
    return want


def metrics_signals(url: str, timeout_s: float = 5.0, replicas: int = 1) -> Signals:
    """Read one replica's /metrics into Signals via the telemetry layer's
    exposition parser (labels/timestamps handled; fetch errors yield an
    empty dict, i.e. a zero-signal sample the policy treats as idle). For
    a multi-replica fleet behind one Service this samples whichever
    replica answers — duty is representative under round-robin; queue
    depth is that replica's SHARE, so it is scaled by ``replicas`` to the
    fleet total ``Signals.queue_depth`` promises. Without the scaling the
    policy (which divides by the count again) would see 1/N² of the real
    queue and the queue trigger would effectively never fire at fleet
    size (round-4 advisor finding)."""
    from kserve_vllm_mini_tpu.analysis.telemetry import scrape_runtime_metrics

    vals = scrape_runtime_metrics(url, timeout_s=timeout_s)
    return Signals(
        duty_cycle=vals.get("kvmini_tpu_duty_cycle", 0.0),
        queue_depth=vals.get("kvmini_tpu_queue_depth", 0.0) * max(replicas, 1),
        # economics rail from the SAME scrape (docs/ECONOMICS.md): the
        # fleet router exports the marginal-replica gauge; a bare engine
        # exports neither and the cost-aware rule stays inert
        usd_per_1k_tok=vals.get("kvmini_tpu_econ_usd_per_1k_tokens"),
        marginal_usd_per_1k_tok=vals.get(
            "kvmini_tpu_econ_marginal_replica_usd_per_1k_tokens"
        ),
        ts=time.time(),
        valid=bool(vals),
    )


def fleet_signals(urls: list[str], timeout_s: float = 5.0) -> Signals:
    """Aggregate /metrics across EVERY replica endpoint: duty is the mean
    over replicas that answered, queue depth the true sum (no per-share
    estimate needed — the exact aggregation the single-URL path can only
    approximate by scaling). A replica that fails to answer is excluded;
    the sample is valid while at least one answers. Use when the fleet's
    pods are individually addressable (headless Service / port-forward
    list); fall back to ``metrics_signals(url, replicas=N)`` behind a
    single load-balanced URL."""
    from kserve_vllm_mini_tpu.analysis.telemetry import scrape_runtime_metrics

    from kserve_vllm_mini_tpu.costs.live import usd_per_1k_tokens

    duties: list[float] = []
    queue_total = 0.0
    per_1ks: list[float] = []
    marginal: Optional[float] = None
    for url in urls:
        vals = scrape_runtime_metrics(url, timeout_s=timeout_s)
        if not vals:
            continue
        duties.append(vals.get("kvmini_tpu_duty_cycle", 0.0))
        queue_total += vals.get("kvmini_tpu_queue_depth", 0.0)
        if "kvmini_tpu_econ_usd_per_1k_tokens" in vals:
            per_1ks.append(vals["kvmini_tpu_econ_usd_per_1k_tokens"])
        # marginal replica = the priciest tokens any single replica is
        # producing right now, from each replica's own price/rate pair —
        # the same derivation the fleet router aggregates
        price = vals.get("kvmini_tpu_econ_usd_per_hour")
        rate = vals.get("kvmini_tpu_econ_tokens_per_sec")
        if price and rate and rate > 0.0:
            cand = usd_per_1k_tokens(price, rate)
            marginal = cand if marginal is None else max(marginal, cand)
    return Signals(
        duty_cycle=sum(duties) / len(duties) if duties else 0.0,
        queue_depth=queue_total,
        usd_per_1k_tok=(
            sum(per_1ks) / len(per_1ks) if per_1ks else None
        ),
        marginal_usd_per_1k_tok=marginal,
        ts=time.time(),
        valid=bool(duties),
    )


def slo_breach(results: dict[str, Any], slo_path: Optional[str] = None) -> bool:
    """True when the SLO gate fails a MEASURED budget. Metrics missing from
    the snapshot fail the CI gate (gates/slo.py — absence of evidence is a
    red build) but must not drive scaling: a partial results.json would
    otherwise force a step up on every poll."""
    from kserve_vllm_mini_tpu.gates.slo import gate_results, load_slo

    budgets = load_slo(slo_path)
    return any(
        not v.ok and v.value is not None for v in gate_results(results, budgets)
    )


class Controller:
    """Polls signals, applies the policy with downscale stabilization, and
    actuates through a pluggable scaler.

    ``scaler(replicas) -> None`` applies the count (KServe patch, or a
    recorder in dry runs); ``signal_fn() -> Signals`` supplies each poll.
    """

    def __init__(
        self,
        signal_fn: Callable[[], Signals],
        scaler: Callable[[int], None],
        cfg: Optional[PolicyConfig] = None,
        initial_replicas: int = 1,
        decision_log: Optional[Path] = None,
        now_fn: Callable[[], float] = time.time,
    ) -> None:
        self.cfg = cfg or PolicyConfig()
        self.signal_fn = signal_fn
        self.scaler = scaler
        self.replicas = initial_replicas
        self.decision_log = Path(decision_log) if decision_log else None
        self.now_fn = now_fn
        # (ts, desired) samples inside the stabilization window — seeded
        # with the initial count so the first quiet poll can't shed
        # capacity the controller has no history about
        self._window: list[tuple[float, int]] = [(self.now_fn(), initial_replicas)]
        self.decisions: list[dict[str, Any]] = []

    def step(self) -> int:
        """One control iteration; returns the (possibly new) replica count."""
        try:
            sig = self.signal_fn()
        except Exception as e:  # noqa: BLE001 — the loop must outlive blips
            sig = Signals(ts=self.now_fn(), valid=False)
            sig_err = f"{type(e).__name__}: {e}"
        else:
            sig_err = None
        now = self.now_fn()
        if not sig.valid:
            decision = {
                "ts": now, "current": self.replicas,
                "applied": self.replicas,
                "note": f"no signal ({sig_err or 'empty scrape'}); holding",
            }
            # single-writer: only the thread driving step() appends;
            # cross-thread readers (FleetAutoscaler.decisions) snapshot
            self.decisions.append(decision)  # kvmini: thread-ok — above
            if self.decision_log:
                with self.decision_log.open("a") as f:
                    f.write(json.dumps(decision) + "\n")
            return self.replicas
        raw = desired_replicas(self.replicas, sig, self.cfg)
        # single-writer: _window lives entirely inside step(), which
        # exactly one thread drives
        # kvmini: thread-ok — single-writer window (see above)
        self._window.append((now, raw))
        cutoff = now - self.cfg.stabilization_s
        self._window = [(t, d) for t, d in self._window if t >= cutoff]
        if raw < self.replicas:
            # downscale stabilization: the max desired over the window wins
            target = max(d for _, d in self._window)
            target = min(target, self.replicas)  # never scale UP from here
        else:
            target = raw
        decision = {
            "ts": now,
            "duty": round(sig.duty_cycle, 4),
            "queue": sig.queue_depth,
            "slo_breached": sig.slo_breached,
            "current": self.replicas,
            "raw_desired": raw,
            "applied": target,
        }
        # economics fields ride into the decision log only when the
        # scrape carried the rail — absent, never a fabricated $0
        if sig.usd_per_1k_tok is not None:
            decision["usd_per_1k_tok"] = round(sig.usd_per_1k_tok, 6)
        if sig.marginal_usd_per_1k_tok is not None:
            decision["marginal_usd_per_1k_tok"] = round(
                sig.marginal_usd_per_1k_tok, 6
            )
        self.decisions.append(decision)
        if self.decision_log:
            with self.decision_log.open("a") as f:
                f.write(json.dumps(decision) + "\n")
        if target != self.replicas:
            self.scaler(target)
            # single-writer int assignment (GIL-atomic); cross-thread
            # readers observe the current-or-previous count
            # kvmini: thread-ok — single-writer count (see above)
            self.replicas = target
        return self.replicas

    def run(self, interval_s: float = 15.0, max_iterations: int = 0) -> None:
        i = 0
        while True:
            try:
                self.step()
            except Exception as e:  # noqa: BLE001 — an autoscaler that dies
                # on one bad poll/patch stops scaling exactly when pod churn
                # makes polls flaky; log and keep the loop alive
                print(f"autoscale: step failed ({type(e).__name__}: {e}); "
                      "continuing")
            i += 1
            if max_iterations and i >= max_iterations:
                return
            time.sleep(interval_s)


def kserve_scaler(
    name: str,
    namespace: str,
    kubectl=None,
    max_replicas: int = 8,
) -> Callable[[int], None]:
    """Scaler that patches a KServe InferenceService's replica window and
    Knative min-scale annotation (the knobs the autoscale sweep tunes;
    deploy/manifests.py writes the same fields). ``maxReplicas`` is pinned
    to the POLICY ceiling, not the step's desired count — Knative keeps
    burst headroom above the controller's floor even if the controller
    later dies."""
    from kserve_vllm_mini_tpu.deploy.kubectl import Kubectl

    kc = kubectl or Kubectl()

    def scale(replicas: int) -> None:
        patch = {
            "metadata": {"annotations": {
                "autoscaling.knative.dev/min-scale": str(replicas),
            }},
            "spec": {"predictor": {
                "minReplicas": replicas,
                "maxReplicas": max(max_replicas, replicas, 1),
            }},
        }
        res = kc.run([
            "patch", "inferenceservice", name,
            "-n", namespace, "--type=merge",
            "-p", json.dumps(patch),
        ])
        if not res.ok:
            raise RuntimeError(
                f"kubectl patch failed rc={res.returncode}: {res.stderr[-500:]}"
            )

    return scale


# -- CLI ---------------------------------------------------------------------

def register(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("--url", required=True,
                        help="Runtime base URL whose /metrics drives the "
                             "loop. Comma-separate several replica URLs to "
                             "aggregate fleet-wide (duty = mean, queue = "
                             "true sum) instead of estimating from one "
                             "load-balanced sample; an '{i}' placeholder "
                             "(e.g. http://pod-{i}.svc:8000) expands to the "
                             "current replica count every poll, tracking "
                             "the controller's own scaling")
    parser.add_argument("--service", default=None,
                        help="InferenceService to scale (omit with --dry-run)")
    parser.add_argument("--namespace", default="default")
    parser.add_argument("--min", type=int, default=1)
    parser.add_argument("--max", type=int, default=8)
    parser.add_argument("--target-duty", type=float, default=0.75)
    parser.add_argument("--target-queue", type=float, default=4.0)
    parser.add_argument("--scale-down-duty", type=float, default=0.30)
    parser.add_argument("--stabilization", type=float, default=120.0)
    parser.add_argument("--cost-aware", action="store_true",
                        help="Shed the marginal replica when it prices its "
                             "tokens over --cost-budget-usd-per-1k-tok "
                             "(docs/ECONOMICS.md; SLO breach and queue "
                             "pressure veto the shed)")
    parser.add_argument("--cost-budget-usd-per-1k-tok", type=float,
                        default=None,
                        help="$/1K-token budget for --cost-aware")
    parser.add_argument("--interval", type=float, default=15.0)
    parser.add_argument("--iterations", type=int, default=0,
                        help="Stop after N control steps (0 = run forever)")
    parser.add_argument("--initial-replicas", type=int, default=1)
    parser.add_argument("--results", default=None,
                        help="results.json to gate each step (SLO breach "
                             "forces a step up)")
    parser.add_argument("--results-max-age", type=float, default=600.0,
                        help="Ignore --results older than this many seconds "
                             "(a stale breached snapshot would ratchet the "
                             "fleet to max and pin it there)")
    parser.add_argument("--slo", default=None, help="SLO budgets JSON")
    parser.add_argument("--decision-log", default=None,
                        help="JSONL decision log (default: stdout only)")
    parser.add_argument("--dry-run", action="store_true",
                        help="Record decisions without patching anything")


def run(args: argparse.Namespace) -> int:
    cfg = PolicyConfig(
        min_replicas=args.min,
        max_replicas=args.max,
        target_duty=args.target_duty,
        target_queue_per_replica=args.target_queue,
        scale_down_duty=args.scale_down_duty,
        stabilization_s=args.stabilization,
        cost_aware=args.cost_aware,
        cost_budget_usd_per_1k_tok=args.cost_budget_usd_per_1k_tok,
    )
    if cfg.cost_aware and cfg.cost_budget_usd_per_1k_tok is None:
        print("autoscale-controller: --cost-aware requires "
              "--cost-budget-usd-per-1k-tok", file=sys.stderr)
        return 2

    # breach latch: one breached snapshot steps up ONCE; re-stepping needs
    # a NEW snapshot that still breaches. Without the latch a single stale
    # breached results.json inside results_max_age would force +1 on every
    # 15 s poll and ratchet the fleet to max in ~2 minutes (round-4
    # advisor finding).
    _breach_acted = {"mtime": None}

    urls = [u.strip() for u in args.url.split(",") if u.strip()]

    def signal_fn() -> Signals:
        current = ctl.replicas if ctl is not None else args.initial_replicas
        if len(urls) == 1 and "{i}" in urls[0]:
            # ordinal template (StatefulSet / headless-Service DNS):
            # expanded by the CURRENT replica count each poll, so pods the
            # controller itself added are polled too — a static list would
            # undercount the fleet after its own scale-up
            sig = fleet_signals([urls[0].format(i=i) for i in range(current)])
        elif len(urls) > 1:
            # explicit per-replica endpoints: exact aggregation over the
            # LISTED pods only (fine for fixed fleets; use the {i}
            # template when the controller changes the count)
            sig = fleet_signals(urls)
        else:
            # one load-balanced URL: the sampled per-replica queue share
            # is scaled to the fleet total (late-bound: ctl exists by the
            # time the controller polls)
            sig = metrics_signals(urls[0], replicas=current)
        # latch only on samples the controller will ACT on: an invalid
        # scrape (pod churn — exactly when breaches happen) is discarded
        # by step(), and consuming the latch there would swallow the
        # breach for good. A scaler-patch failure after a valid sample
        # can still consume it; the next results.json rewrite re-arms.
        if args.results and sig.valid:
            try:
                p = Path(args.results)
                mtime = p.stat().st_mtime
                fresh = (time.time() - mtime) <= args.results_max_age
                if fresh and slo_breach(json.loads(p.read_text()), args.slo):
                    if _breach_acted["mtime"] != mtime:
                        sig.slo_breached = True
                        _breach_acted["mtime"] = mtime
            except Exception:  # noqa: BLE001 — a torn mid-rewrite snapshot
                # or missing file must not kill (or drive) the loop
                pass
        return sig

    ctl = None

    if args.dry_run or not args.service:
        def scaler(n: int) -> None:
            print(f"autoscale: would scale to {n} replicas (dry run)")
    else:
        scaler = kserve_scaler(args.service, args.namespace,
                               max_replicas=cfg.max_replicas)

    ctl = Controller(
        signal_fn, scaler, cfg,
        initial_replicas=args.initial_replicas,
        decision_log=args.decision_log,
    )
    print(
        f"autoscale-controller: url={args.url} "
        f"replicas {cfg.min_replicas}..{cfg.max_replicas} "
        f"duty<={cfg.target_duty} queue/replica<={cfg.target_queue_per_replica}"
    )
    try:
        ctl.run(interval_s=args.interval, max_iterations=args.iterations)
    except KeyboardInterrupt:
        pass
    last = ctl.decisions[-1] if ctl.decisions else {}
    print(f"autoscale-controller: final replicas={ctl.replicas} "
          f"(last decision: {json.dumps(last)})")
    return 0
