"""Open-loop arrival-time schedules for the four traffic patterns.

Behavioral spec is the reference's generator (/root/reference/scripts/
loadtest.py:178-237): given a request count and target duration, produce a
sorted list of relative arrival offsets (seconds) per pattern:

- ``steady``  — uniform spacing
- ``poisson`` — exponential inter-arrivals at the mean rate
- ``bursty``  — alternating high-rate bursts and idle gaps
- ``heavy``   — heavy-tailed (Pareto) inter-arrivals: long quiet stretches
                punctuated by clumps

All randomness is seeded for reproducible runs (the reference's repro-smoke
CI depends on seeded load, SURVEY.md §4.3).
"""

from __future__ import annotations

import random
from typing import Optional

PATTERNS = ("steady", "poisson", "bursty", "heavy")


def duration_and_rps(
    num_requests: int,
    concurrency: int,
    target_rps: Optional[float] = None,
    duration_s: Optional[float] = None,
) -> tuple[float, float]:
    """Resolve (duration_s, rps) from whichever the caller pinned.

    Mirrors the reference's heuristic (loadtest.py:240-257): if neither is
    given, assume each in-flight slot sustains ~2 rps.
    """
    if target_rps and target_rps > 0:
        return (num_requests / target_rps, target_rps)
    if duration_s and duration_s > 0:
        return (duration_s, num_requests / duration_s)
    est_rps = max(concurrency * 2.0, 1.0)
    return (num_requests / est_rps, est_rps)


def generate_arrival_times(
    pattern: str,
    num_requests: int,
    duration_s: float,
    seed: int = 42,
    burst_factor: float = 5.0,
    pareto_alpha: float = 1.5,
) -> list[float]:
    """Sorted relative arrival offsets in [0, ~duration_s]."""
    if num_requests <= 0:
        return []
    if pattern not in PATTERNS:
        raise ValueError(f"unknown pattern {pattern!r}; expected one of {PATTERNS}")
    rng = random.Random(seed)
    rate = num_requests / max(duration_s, 1e-9)

    if pattern == "steady":
        step = duration_s / num_requests
        return [i * step for i in range(num_requests)]

    if pattern == "poisson":
        t = 0.0
        out = []
        for _ in range(num_requests):
            t += rng.expovariate(rate)
            out.append(t)
        return out

    if pattern == "bursty":
        # bursts at `burst_factor`x the mean rate, separated by idle gaps so
        # the overall duration still averages out to `duration_s`.
        out = []
        t = 0.0
        burst_len = max(num_requests // 10, 1)
        burst_rate = rate * burst_factor
        idle_gap = (duration_s - num_requests / burst_rate) / max(num_requests // burst_len, 1)
        i = 0
        while i < num_requests:
            for _ in range(min(burst_len, num_requests - i)):
                t += rng.expovariate(burst_rate)
                out.append(t)
                i += 1
            t += max(idle_gap, 0.0)
        return out

    # heavy: Pareto inter-arrivals scaled so the mean inter-arrival matches
    # 1/rate. Pareto(alpha) has mean alpha/(alpha-1) for alpha>1.
    mean_pareto = pareto_alpha / (pareto_alpha - 1.0)
    scale = (1.0 / rate) / mean_pareto
    t = 0.0
    out = []
    for _ in range(num_requests):
        t += rng.paretovariate(pareto_alpha) * scale
        out.append(t)
    return out
