"""Open-loop async load generator: the framework's L2.

One worker pool for every backend protocol (SURVEY.md §7.1). Behavior spec is
/root/reference/scripts/loadtest.py:345-623: workers sleep until their
scheduled arrival, a semaphore caps in-flight concurrency (open-loop: late
arrivals are NOT rescheduled, queueing shows up as latency), TTFT/TLLT come
from streamed chunk marks, and everything lands in requests.csv + meta.json +
traces.json. Fixes over the reference: one shared AsyncClient
(loadtest.py:407-409 built one per request), first-class prompt sets, and a
normalized adapter layer.
"""

from __future__ import annotations

import argparse
import asyncio
import sys
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Optional

import httpx

from kserve_vllm_mini_tpu.core.rundir import RequestRecord, RunDir
from kserve_vllm_mini_tpu.loadgen.adapters.base import GenParams, ProtocolAdapter, get_adapter
from kserve_vllm_mini_tpu.loadgen.arrivals import duration_and_rps, generate_arrival_times
from kserve_vllm_mini_tpu.loadgen.prompts import make_prompt_fn
from kserve_vllm_mini_tpu.loadgen.tracing import TraceCollector, new_trace_id, traceparent


class LiveStats:
    """Thread-safe live view of an in-progress load run.

    Workers (asyncio, one thread) update it; the run monitor
    (monitor/sampler.py, its own thread) polls ``snapshot()`` and
    ``completions()`` at ~1 Hz for the timeline and rolling burn-rate
    windows — hence the lock and the bounded completion deque (the
    monitor only ever looks back one window, not the whole run). This is
    the locking pattern kvmini-lint's KVM051/052/055 rules enforce
    package-wide (docs/LINTING.md): every access under ONE lock, and
    readers get snapshots (``list(self._events)``), never the live
    container."""

    def __init__(self, max_events: int = 8192) -> None:
        self._lock = threading.Lock()
        self.started = 0
        self.inflight = 0
        self.completed = 0
        self.errors = 0
        self.tokens_out = 0
        self.skipped = 0  # scheduled requests dropped by an early abort
        self.shed = 0     # 429-shed past the retry budget (NOT errors:
        #                   docs/RESILIENCE.md — sheds count separately)
        self.retries = 0  # total 429 resends absorbed across requests
        # (end_ts, ok, latency_ms, ttft_ms, tokens_out) per completion
        self._events: deque[tuple[float, bool, float, float, int]] = deque(
            maxlen=max_events
        )
        # trace ids currently in flight (docs/MONITORING.md): the monitor
        # stamps these into detected events so an alert is clickable into
        # the merged traces.json. Bounded by concurrency (a worker adds
        # exactly one id per started request and discards it on done).
        self._inflight_ids: set[str] = set()

    def record_start(self, trace_id: str = "") -> None:
        with self._lock:
            self.started += 1
            self.inflight += 1
            if trace_id:
                self._inflight_ids.add(trace_id)

    def record_done(self, rec: RequestRecord) -> None:
        with self._lock:
            self.inflight -= 1
            self.completed += 1
            self._inflight_ids.discard(rec.trace_id)
            if rec.shed:
                self.shed += 1
            elif not rec.ok:
                self.errors += 1
            self.retries += rec.retries
            self.tokens_out += rec.tokens_out
            self._events.append(
                (rec.end_ts, rec.ok, rec.latency_ms, rec.ttft_ms,
                 rec.tokens_out)
            )

    def record_skipped(self) -> None:
        with self._lock:
            self.skipped += 1

    def snapshot(self) -> dict[str, int]:
        with self._lock:
            return {
                "started": self.started,
                "inflight": self.inflight,
                "completed": self.completed,
                "errors": self.errors,
                "tokens_out": self.tokens_out,
                "skipped": self.skipped,
                "shed": self.shed,
                "retries": self.retries,
            }

    def completions(self) -> list[tuple[float, bool, float, float, int]]:
        with self._lock:
            return list(self._events)

    def inflight_trace_ids(self, limit: int = 8) -> list[str]:
        """A bounded, sorted sample of the trace ids in flight right now
        — what the monitor stamps into event payloads. Sorted so the
        sample is deterministic for a given in-flight set."""
        with self._lock:
            return sorted(self._inflight_ids)[:limit]


@dataclass
class LoadConfig:
    url: str
    model: str = "default"
    # multi-LoRA runs: rotate the request's "model" over these names
    # (round-robin by request index); empty/None = every request uses
    # ``model``. Per-request routing lands in requests.csv's model column.
    models: Optional[list[str]] = None
    backend: str = "openai"
    num_requests: int = 100
    concurrency: int = 10
    pattern: str = "steady"
    target_rps: Optional[float] = None
    duration_s: Optional[float] = None
    streaming: bool = True
    max_tokens: int = 64
    temperature: float = 0.0
    top_p: float = 1.0
    top_k: int = 0
    # the rest of the OpenAI payload the reference's loadtest forwards
    # (scripts/loadtest.py:260-342) — first-class, not extra_body-only, so
    # profiles and the CLI exercise the knobs the server now honors
    n: int = 1
    presence_penalty: float = 0.0
    frequency_penalty: float = 0.0
    stop: Optional[list[str]] = None
    prompt_set: str = "default"
    base_prompt: Optional[str] = None
    input_tokens: int = 0
    seed: int = 42                          # traffic seed: arrivals + prompts
    sampling_seed: Optional[int] = None     # server-side sampler seed (off by default)
    tenant: str = ""
    # Split HTTP timeouts (docs/RESILIENCE.md): `timeout_s` bounds the
    # write/pool phases (and is the legacy whole-request budget);
    # `connect_timeout_s` bounds dialing and `read_timeout_s` bounds the
    # gap BETWEEN stream chunks — a stalled SSE stream fails fast as a
    # `timeout` row instead of hanging a worker for the full budget.
    timeout_s: float = 120.0
    connect_timeout_s: float = 10.0
    read_timeout_s: float = 30.0
    # 429-shed retry policy (docs/RESILIENCE.md): capped exponential
    # backoff with deterministic per-request jitter, honoring the
    # server's Retry-After when it is larger. Every resend lands in the
    # record's `retries` column; a request still shed past the budget
    # lands as `shed` (separate from errors). 0 disables retries.
    max_retries: int = 3
    retry_backoff_s: float = 0.25
    retry_backoff_max_s: float = 5.0
    # per-request deadline forwarded as deadline_ms so the server's
    # deadline-aware admission can shed at the door; None sends nothing
    deadline_ms: Optional[float] = None
    headers: dict[str, str] = field(default_factory=dict)
    extra_body: dict[str, Any] = field(default_factory=dict)

    def gen_params(self) -> GenParams:
        # a bare-string stop (natural YAML spelling: `stop: "END"`) must
        # become ONE sequence — list("END") would explode it into
        # per-character stops and silently measure a ~1-token workload
        stop = self.stop
        if isinstance(stop, str):
            stop = [stop]
        elif stop:
            stop = [str(s) for s in stop]
        else:
            stop = None
        extra = dict(self.extra_body)
        if self.deadline_ms is not None:
            # rides the raw body so the server's deadline-aware admission
            # (docs/RESILIENCE.md) sees it
            extra.setdefault("deadline_ms", float(self.deadline_ms))
        return GenParams(
            max_tokens=self.max_tokens,
            temperature=self.temperature,
            top_p=self.top_p,
            top_k=self.top_k,
            n=self.n,
            presence_penalty=self.presence_penalty,
            frequency_penalty=self.frequency_penalty,
            stop=stop,
            seed=self.sampling_seed,
            extra=extra,
        )


async def _worker(
    idx: int,
    arrival_offset: float,
    t_start: float,
    cfg: LoadConfig,
    adapter: ProtocolAdapter,
    client: httpx.AsyncClient,
    sem: asyncio.Semaphore,
    prompt_fn,
    tracer: TraceCollector,
    live: Optional[LiveStats] = None,
    abort_evt: Optional[asyncio.Event] = None,
) -> Optional[RequestRecord]:
    trace_id = new_trace_id()
    rec = RequestRecord(
        request_id=f"req-{idx:06d}",
        scheduled_ts=t_start + arrival_offset,
        trace_id=trace_id,
        prompt_set=cfg.prompt_set,
        tenant=cfg.tenant,
    )
    root = tracer.span("client.request", trace_id, request_id=rec.request_id, index=idx)

    wait_span = tracer.span("client.wait_scheduled", trace_id, parent=root)
    delay = rec.scheduled_ts - time.time()
    if delay > 0:
        if abort_evt is not None:
            # an abort wakes every waiting worker immediately instead of
            # letting the remaining schedule play out
            try:
                await asyncio.wait_for(abort_evt.wait(), timeout=delay)
            except asyncio.TimeoutError:  # kvmini: workload-ok — the timeout
                pass  # IS the scheduled arrival (no abort happened); the
                      # abort path below stamps meta aborted_early/skipped
        else:
            await asyncio.sleep(delay)
    wait_span.end()
    if abort_evt is not None and abort_evt.is_set():
        # not-yet-sent request dropped by an early abort: no record at all
        # (a fabricated error row would poison error_rate); the drop is
        # surfaced via meta.json requests_skipped + results aborted_early
        root.end(ok=False)
        if live is not None:
            live.record_skipped()
        return None

    async with sem:
        if abort_evt is not None and abort_evt.is_set():
            # aborted while queued on the concurrency cap: same drop as
            # above — the semaphore wait is queueing, not service
            root.end(ok=False)
            if live is not None:
                live.record_skipped()
            return None
        prompt = prompt_fn(idx)
        model = cfg.models[idx % len(cfg.models)] if cfg.models else cfg.model
        rec.model = model
        http_span = tracer.span(
            "http.request", trace_id, parent=root, backend=cfg.backend, stream=cfg.streaming
        )
        headers = dict(cfg.headers)
        headers["traceparent"] = traceparent(trace_id, http_span.span_id)
        if live is not None:
            live.record_start(trace_id)
        rec.start_ts = time.time()
        # 429-shed retry loop (docs/RESILIENCE.md): capped exponential
        # backoff with DETERMINISTIC per-request jitter (seeded from the
        # traffic seed + index, so two runs of the same scenario resend
        # at the same offsets), honoring the server's Retry-After when
        # larger. All resends stay inside this ONE record — retries are
        # never fabricated as fresh requests (KVM041 contract).
        import random as _random

        backoff_rng = _random.Random((cfg.seed << 20) ^ idx)
        attempt = 0
        while True:
            try:
                result = await adapter.generate(
                    client, cfg.url, model, prompt, cfg.gen_params(),
                    cfg.streaming, headers,
                )
            except Exception as e:
                # Adapters record their own errors; this guard ensures even an
                # adapter bug costs one row, never the whole run's artifacts.
                from kserve_vllm_mini_tpu.loadgen.adapters.base import CallResult

                result = CallResult(error=f"adapter-{type(e).__name__}")
            if result.status_code != 429 or attempt >= cfg.max_retries:
                break
            if abort_evt is not None and abort_evt.is_set():
                break  # aborted mid-backoff: the shed row stands as-is
            rec.retries += 1
            backoff = min(
                cfg.retry_backoff_s * (2 ** attempt), cfg.retry_backoff_max_s
            ) * (0.5 + backoff_rng.random())
            await asyncio.sleep(max(result.retry_after_s, backoff))
            attempt += 1
        rec.end_ts = time.time()
        http_span.set("http.status_code", result.status_code)
        http_span.set("retries", rec.retries)
        http_span.end(ok=result.ok)

    rec.status_code = result.status_code
    rec.ok = result.ok
    rec.error = result.error
    if result.status_code == 429:
        # shed past the retry budget: its own outcome class — the
        # analyzer counts sheds separately from errors (an overload run
        # shedding by design is not a broken run)
        rec.shed = True
        rec.error = "shed"
    rec.tokens_in = result.tokens_in
    rec.tokens_out = result.tokens_out
    rec.first_token_ts = result.first_token_ts
    rec.last_token_ts = result.last_token_ts
    rec.server_ttft_ms = result.server_ttft_ms
    rec.truncated = result.truncated
    rec.truncated_tokens = result.truncated_tokens
    rec.latency_ms = (rec.end_ts - rec.start_ts) * 1000.0
    if result.first_token_ts > 0:
        rec.ttft_ms = (result.first_token_ts - rec.start_ts) * 1000.0
        ttft_span = tracer.span("server.ttft", trace_id, parent=root)
        ttft_span.start_ns = int(rec.start_ts * 1e9)
        ttft_span.end_ns = int(result.first_token_ts * 1e9)
        if result.last_token_ts > result.first_token_ts:
            tllt = tracer.span("server.tllt", trace_id, parent=root)
            tllt.start_ns = int(result.first_token_ts * 1e9)
            tllt.end_ns = int(result.last_token_ts * 1e9)
    elif rec.ok:
        rec.ttft_ms = rec.latency_ms  # non-streaming: whole response is "first token"
    root.set("tokens_out", rec.tokens_out)
    root.end(ok=rec.ok)
    if live is not None:
        live.record_done(rec)
    return rec


async def run_load_async(
    cfg: LoadConfig,
    run_dir: RunDir,
    live: Optional[LiveStats] = None,
    abort: Optional[Any] = None,
) -> list[RequestRecord]:
    """``live``: a LiveStats the run monitor polls; ``abort``: a
    monitor AbortSignal (monitor/events.py) — when set mid-run, waiting
    workers wake and drop their un-sent requests (in-flight requests
    drain normally) so a hopeless sweep cell stops burning wall-clock."""
    dur, rps = duration_and_rps(cfg.num_requests, cfg.concurrency, cfg.target_rps, cfg.duration_s)
    arrivals = generate_arrival_times(cfg.pattern, cfg.num_requests, dur, seed=cfg.seed)
    adapter = get_adapter(cfg.backend)
    if cfg.backend != "openai":
        # the jetstream / kserve_v2 wire formats carry only the basic
        # knobs; a run that configures OpenAI-only ones would measure a
        # different workload than asked for — say so LOUDLY up front (the
        # repo's own server comments call this the silent-drop hazard)
        dropped = [
            k for k, v in (
                ("n", cfg.n != 1),
                ("presence_penalty", cfg.presence_penalty != 0.0),
                ("frequency_penalty", cfg.frequency_penalty != 0.0),
                ("stop", bool(cfg.stop)),
            ) if v
        ]
        if dropped:
            print(
                f"loadgen WARNING: backend {cfg.backend!r} cannot express "
                f"{dropped}; these knobs will NOT reach the server and the "
                "run measures a different workload than configured",
                file=sys.stderr,
            )
    prompt_fn = make_prompt_fn(
        cfg.prompt_set, cfg.base_prompt, seed=cfg.seed, input_tokens=cfg.input_tokens
    )
    tracer = TraceCollector()
    sem = asyncio.Semaphore(cfg.concurrency)
    abort_evt: Optional[asyncio.Event] = None
    if abort is not None:
        abort_evt = asyncio.Event()
        loop = asyncio.get_running_loop()
        evt = abort_evt

        def _wake_loop() -> None:
            # the monitor thread sets the signal; hop back onto this
            # loop. The signal can also fire AFTER this load completed
            # and asyncio.run closed the loop — then there is nothing
            # left to wake and the closed-loop error must not propagate
            # into the monitor thread mid-sample.
            try:
                loop.call_soon_threadsafe(evt.set)
            except RuntimeError:  # kvmini: workload-ok — loop already
                pass              # closed: the run is over, nothing to
                                  # abort; the signal flag itself is set

        abort.on_set(_wake_loop)
    t_start = time.time()
    limits = httpx.Limits(
        max_connections=cfg.concurrency + 4, max_keepalive_connections=cfg.concurrency
    )
    # split timeouts (docs/RESILIENCE.md): read bounds the gap BETWEEN
    # stream chunks, so a stalled SSE stream fails fast as a `timeout`
    # row; the legacy whole-budget value keeps bounding write/pool
    timeout = httpx.Timeout(
        cfg.timeout_s, connect=cfg.connect_timeout_s, read=cfg.read_timeout_s
    )
    async with httpx.AsyncClient(timeout=timeout, limits=limits) as client:
        records = await asyncio.gather(
            *(
                _worker(i, off, t_start, cfg, adapter, client, sem, prompt_fn,
                        tracer, live=live, abort_evt=abort_evt)
                for i, off in enumerate(arrivals)
            )
        )
    skipped = sum(1 for r in records if r is None)
    records = sorted((r for r in records if r is not None),
                     key=lambda r: r.start_ts)
    aborted_reason = getattr(abort, "reason", None) if abort is not None else None
    if skipped:
        # the run measured FEWER requests than configured — say so loudly
        # (same surfacing contract as the truncation warnings)
        print(
            f"loadgen WARNING: aborted early ({aborted_reason}); "
            f"{skipped}/{cfg.num_requests} scheduled requests were never sent",
            file=sys.stderr,
        )
    meta = {
        "url": cfg.url,
        "model": cfg.model,
        "models": cfg.models,
        "backend": cfg.backend,
        "pattern": cfg.pattern,
        "requests": cfg.num_requests,
        "concurrency": cfg.concurrency,
        "streaming": cfg.streaming,
        "max_tokens": cfg.max_tokens,
        "prompt_set": cfg.prompt_set,
        "seed": cfg.seed,
        "sampling_seed": cfg.sampling_seed,
        "target_rps": rps,
        "planned_duration_s": dur,
        "started_at": t_start,
        "finished_at": time.time(),
    }
    if skipped:
        meta["requests_skipped"] = skipped
        meta["aborted_early"] = aborted_reason or "aborted"
    run_dir.write_meta(meta)
    run_dir.write_requests(records)
    tracer.export(run_dir.traces_json)
    return list(records)


def run_load(
    cfg: LoadConfig,
    run_dir: RunDir,
    live: Optional[LiveStats] = None,
    abort: Optional[Any] = None,
) -> list[RequestRecord]:
    return asyncio.run(run_load_async(cfg, run_dir, live=live, abort=abort))


# -- CLI ---------------------------------------------------------------------

def register(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("--url", required=True, help="Base URL of the serving endpoint")
    parser.add_argument("--model", default="default")
    parser.add_argument("--models", default=None,
                        help="Comma-separated model/adapter names rotated "
                             "round-robin across requests (multi-LoRA runs)")
    parser.add_argument("--backend", default="openai", help="Protocol adapter name")
    parser.add_argument("--requests", type=int, default=100)
    parser.add_argument("--concurrency", type=int, default=10)
    parser.add_argument("--pattern", default="steady",
                        choices=["steady", "poisson", "bursty", "heavy"])
    parser.add_argument("--rps", type=float, default=None, help="Target requests/sec")
    parser.add_argument("--duration", type=float, default=None, help="Target duration (s)")
    parser.add_argument("--max-tokens", type=int, default=64)
    parser.add_argument("--temperature", type=float, default=0.0)
    parser.add_argument("--n", type=int, default=1,
                        help="Choices per request (OpenAI n)")
    parser.add_argument("--presence-penalty", type=float, default=0.0)
    parser.add_argument("--frequency-penalty", type=float, default=0.0)
    parser.add_argument("--stop", action="append", default=None,
                        help="Stop sequence (repeatable, up to 4)")
    parser.add_argument("--no-stream", action="store_true")
    parser.add_argument("--prompt-set", default="default",
                        choices=["default", "repeat", "unique", "mixed",
                                 "sessions"],
                        help="Prompt shape (loadgen/prompts.py); "
                             "'sessions' = prefix-heavy multi-session "
                             "traffic, the cache-aware fleet-routing "
                             "workload (docs/FLEET.md)")
    parser.add_argument("--input-tokens", type=int, default=0)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--sampling-seed", type=int, default=None,
                        help="Server-side sampler seed (omitted from requests by default)")
    parser.add_argument("--run-dir", default=None, help="Existing run dir (default: new under runs/)")
    parser.add_argument("--tenant", default="")
    parser.add_argument("--connect-timeout", type=float, default=10.0,
                        help="HTTP connect timeout (s)")
    parser.add_argument("--read-timeout", type=float, default=30.0,
                        help="Max gap between stream chunks (s) — a stalled "
                             "SSE stream fails fast as a `timeout` row")
    parser.add_argument("--max-retries", type=int, default=3,
                        help="Resends per request on a 429 shed (capped "
                             "exponential backoff honoring Retry-After; "
                             "0 disables)")
    parser.add_argument("--deadline-ms", type=float, default=None,
                        help="Per-request deadline forwarded as deadline_ms "
                             "for the server's deadline-aware admission "
                             "(docs/RESILIENCE.md)")


def run(args: argparse.Namespace) -> int:
    cfg = LoadConfig(
        url=args.url,
        model=args.model,
        models=(
            [m.strip() for m in args.models.split(",") if m.strip()]
            if args.models else None
        ),
        backend=args.backend,
        num_requests=args.requests,
        concurrency=args.concurrency,
        pattern=args.pattern,
        target_rps=args.rps,
        duration_s=args.duration,
        streaming=not args.no_stream,
        max_tokens=args.max_tokens,
        temperature=args.temperature,
        n=args.n,
        presence_penalty=args.presence_penalty,
        frequency_penalty=args.frequency_penalty,
        stop=args.stop,
        prompt_set=args.prompt_set,
        input_tokens=args.input_tokens,
        seed=args.seed,
        sampling_seed=args.sampling_seed,
        tenant=args.tenant,
        connect_timeout_s=args.connect_timeout,
        read_timeout_s=args.read_timeout,
        max_retries=args.max_retries,
        deadline_ms=args.deadline_ms,
    )
    run_dir = RunDir(args.run_dir) if args.run_dir else RunDir.create()
    run_dir.path.mkdir(parents=True, exist_ok=True)
    records = run_load(cfg, run_dir)
    ok = sum(1 for r in records if r.ok)
    print(f"load complete: {ok}/{len(records)} ok -> {run_dir.path}")
    return 0 if ok > 0 else 1
