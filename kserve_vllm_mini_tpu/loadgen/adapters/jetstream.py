"""JetStream HTTP adapter.

JetStream (google/JetStream) exposes a gRPC Decode API; its HTTP front-end
(jetstream http server) accepts ``POST /generate`` with
``{"prompt": ..., "max_tokens": ...}`` and streams newline-delimited JSON
events ``{"text": ...}``. This adapter speaks that shape and normalizes to
the same CallResult as every other backend — the reference's equivalent is
the per-backend invoke.sh embedded clients (SURVEY.md §2.4 backend adapters).
"""

from __future__ import annotations

import json
from typing import Optional

import httpx

from kserve_vllm_mini_tpu.loadgen.adapters.base import CallResult, GenParams, ProtocolAdapter
from kserve_vllm_mini_tpu.loadgen.prompts import approx_token_count


class JetStreamAdapter(ProtocolAdapter):
    name = "jetstream"

    async def generate(
        self,
        client: httpx.AsyncClient,
        base_url: str,
        model: str,
        prompt: str,
        params: GenParams,
        stream: bool,
        headers: Optional[dict[str, str]] = None,
    ) -> CallResult:
        url = base_url.rstrip("/") + "/generate"
        body = {
            "prompt": prompt,
            "max_tokens": params.max_tokens,
            "temperature": params.temperature,
        }
        if params.top_k:
            body["top_k"] = params.top_k
        res = CallResult(tokens_in=approx_token_count(prompt))
        try:
            if not stream:
                resp = await client.post(url, json=body, headers=headers)
                res.status_code = resp.status_code
                if resp.status_code != 200:
                    res.error = f"http-{resp.status_code}"
                    return res
                data = resp.json()
                res.text = data.get("response", data.get("text", "")) or ""
                res.tokens_out = int(data.get("output_tokens", 0)) or approx_token_count(
                    res.text
                )
                res.ok = True
                return res

            def parse_event(evt: dict, r: CallResult) -> str:
                return evt.get("text", evt.get("response", "")) or ""

            async with client.stream(
                "POST", url, json={**body, "stream": True}, headers=headers
            ) as resp:
                res.status_code = resp.status_code
                if resp.status_code != 200:
                    res.error = f"http-{resp.status_code}"
                    await resp.aread()
                    return res
                await self._consume_sse(resp, res, parse_event)
            res.tokens_out = approx_token_count(res.text)
            res.ok = True
            return res
        except httpx.TimeoutException:
            # split connect/read timeouts (docs/RESILIENCE.md): a stalled
            # stream fails fast as an honest `timeout` row
            res.error = "timeout"
            return res
        except Exception as e:  # record, never abort the whole run
            res.error = type(e).__name__
            return res


ADAPTER = JetStreamAdapter()
