"""OpenAI chat-completions adapter (vLLM-TPU, JetStream HTTP proxies, and the
in-repo jax-native runtime all speak this).

Behavioral spec: /root/reference/scripts/loadtest.py:260-342 — streaming SSE
with client-side first/last chunk marks, usage-based token counts with len/4
fallback, json_mode via response_format, and raw extra-JSON passthrough.
"""

from __future__ import annotations

import json
from typing import Any, Optional

import httpx

from kserve_vllm_mini_tpu.loadgen.adapters.base import (
    CallResult,
    GenParams,
    ProtocolAdapter,
    parse_retry_after,
)
from kserve_vllm_mini_tpu.loadgen.prompts import approx_token_count


def _payload(model: str, prompt: str, params: GenParams, stream: bool) -> dict[str, Any]:
    body: dict[str, Any] = {
        "model": model,
        "messages": [{"role": "user", "content": prompt}],
        "max_tokens": params.max_tokens,
        "temperature": params.temperature,
        "stream": stream,
    }
    if stream:
        body["stream_options"] = {"include_usage": True}
    if params.top_p != 1.0:
        body["top_p"] = params.top_p
    if params.top_k:
        body["top_k"] = params.top_k
    if params.n != 1:
        body["n"] = params.n
    if params.presence_penalty:
        body["presence_penalty"] = params.presence_penalty
    if params.frequency_penalty:
        body["frequency_penalty"] = params.frequency_penalty
    if params.stop:
        body["stop"] = params.stop
    if params.seed is not None:
        body["seed"] = params.seed
    if params.json_mode:
        body["response_format"] = {"type": "json_object"}
    body.update(params.extra)
    return body


class OpenAIChatAdapter(ProtocolAdapter):
    name = "openai"

    async def generate(
        self,
        client: httpx.AsyncClient,
        base_url: str,
        model: str,
        prompt: str,
        params: GenParams,
        stream: bool,
        headers: Optional[dict[str, str]] = None,
    ) -> CallResult:
        url = base_url.rstrip("/") + "/v1/chat/completions"
        body = _payload(model, prompt, params, stream)
        res = CallResult(tokens_in=approx_token_count(prompt))
        try:
            if not stream:
                resp = await client.post(url, json=body, headers=headers)
                res.status_code = resp.status_code
                if resp.status_code != 200:
                    res.error = f"http-{resp.status_code}"
                    res.retry_after_s = parse_retry_after(
                        resp.headers.get("Retry-After")
                    )
                    return res
                data = resp.json()
                choice = (data.get("choices") or [{}])[0]
                res.text = (choice.get("message") or {}).get("content", "") or ""
                usage = data.get("usage") or {}
                res.tokens_in = usage.get("prompt_tokens", res.tokens_in)
                res.tokens_out = usage.get(
                    "completion_tokens", approx_token_count(res.text)
                )
                metrics = data.get("metrics") or {}
                res.server_ttft_ms = float(metrics.get("server_ttft_ms", 0.0))
                res.truncated = bool(metrics.get("truncated", False))
                res.truncated_tokens = int(metrics.get("truncated_tokens", 0))
                res.ok = True
                return res

            # streaming SSE: data: {...}\n\n frames, terminated by [DONE]
            usage: dict[str, Any] = {}

            def parse_event(evt: dict, r: CallResult) -> str:
                if evt.get("usage"):
                    usage.update(evt["usage"])
                metrics = evt.get("metrics") or {}
                srv = metrics.get("server_ttft_ms")
                if srv:
                    r.server_ttft_ms = float(srv)
                if metrics.get("truncated"):
                    r.truncated = True
                    r.truncated_tokens = int(metrics.get("truncated_tokens", 0))
                delta = ""
                for ch in evt.get("choices") or []:
                    # choice 0 only, matching the non-streaming path: with
                    # n>1 the server interleaves per-choice chunks, and a
                    # concatenated mix would feed garbled text to the
                    # quality checks and double-count fallback tokens
                    if ch.get("index", 0) == 0:
                        delta += (ch.get("delta") or {}).get("content", "") or ""
                return delta

            async with client.stream("POST", url, json=body, headers=headers) as resp:
                res.status_code = resp.status_code
                if resp.status_code != 200:
                    res.error = f"http-{resp.status_code}"
                    res.retry_after_s = parse_retry_after(
                        resp.headers.get("Retry-After")
                    )
                    await resp.aread()
                    return res
                await self._consume_sse(resp, res, parse_event)
            res.tokens_in = usage.get("prompt_tokens", res.tokens_in)
            res.tokens_out = usage.get("completion_tokens", approx_token_count(res.text))
            res.ok = True
            return res
        except httpx.TimeoutException:
            # connect/read timeout (split timeouts, docs/RESILIENCE.md): a
            # stalled SSE stream lands here fast as an honest `timeout`
            # row instead of hanging the worker for the whole budget
            res.error = "timeout"
            return res
        except Exception as e:  # record, never abort the whole run
            res.error = type(e).__name__
            return res


ADAPTER = OpenAIChatAdapter()
