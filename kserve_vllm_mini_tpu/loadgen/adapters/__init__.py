from kserve_vllm_mini_tpu.loadgen.adapters.base import (
    CallResult,
    GenParams,
    ProtocolAdapter,
    get_adapter,
)

__all__ = ["CallResult", "GenParams", "ProtocolAdapter", "get_adapter"]
