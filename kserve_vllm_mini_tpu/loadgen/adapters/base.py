"""Protocol-adapter interface: one load-generator core, pluggable wire formats.

The reference grew three divergent embedded clients (OpenAI in loadtest.py,
HF-generate in tgi/invoke.sh:68-227, KServe-v2 in triton/invoke.sh:68-259)
with drifting metrics — SURVEY.md §7.1 calls this out as the thing NOT to
replicate. Here every backend implements one async interface and the worker
pool is shared.
"""

from __future__ import annotations

import time
from abc import ABC, abstractmethod
from dataclasses import dataclass, field
from typing import Any, Optional

import httpx


@dataclass
class GenParams:
    """Generation parameters, superset of the OpenAI knobs the reference
    forwards (loadtest.py:260-342)."""

    max_tokens: int = 64
    temperature: float = 0.0
    top_p: float = 1.0
    top_k: int = 0
    n: int = 1
    presence_penalty: float = 0.0
    frequency_penalty: float = 0.0
    stop: Optional[list[str]] = None
    json_mode: bool = False
    seed: Optional[int] = None
    extra: dict[str, Any] = field(default_factory=dict)  # raw passthrough


@dataclass
class CallResult:
    """Normalized observation of one generate call."""

    status_code: int = 0
    ok: bool = False
    error: str = ""
    tokens_in: int = 0
    tokens_out: int = 0
    first_token_ts: float = 0.0   # epoch s of first streamed chunk
    last_token_ts: float = 0.0    # epoch s of last streamed chunk
    server_ttft_ms: float = 0.0   # server-reported true TTFT when available
    truncated: bool = False       # server reported the prompt was cut to its
                                  # prefill budget (workload differs from sent)
    truncated_tokens: int = 0     # how many prompt tokens were dropped
    text: str = ""
    # 429 shed responses (docs/RESILIENCE.md): the server's Retry-After
    # hint in seconds (0 = none); the runner's backoff honors it
    retry_after_s: float = 0.0


def parse_retry_after(value: Optional[str]) -> float:
    """Seconds from a Retry-After header (delta-seconds form only; the
    HTTP-date form degrades to 0 and the caller's backoff applies)."""
    try:
        return max(float(value), 0.0) if value else 0.0
    except (TypeError, ValueError):  # kvmini: workload-ok — an unparsable
        return 0.0  # hint only loses the HINT; the caller's capped
        #             backoff still runs and the retry is still counted


class ProtocolAdapter(ABC):
    """One wire protocol. Instances are stateless; the shared AsyncClient is
    passed in (fixing the reference's per-request client construction,
    loadtest.py:407-409)."""

    name: str = "base"

    @abstractmethod
    async def generate(
        self,
        client: httpx.AsyncClient,
        base_url: str,
        model: str,
        prompt: str,
        params: GenParams,
        stream: bool,
        headers: Optional[dict[str, str]] = None,
    ) -> CallResult:
        ...

    @staticmethod
    def _now() -> float:
        return time.time()

    async def _consume_sse(
        self,
        resp: "httpx.Response",
        res: CallResult,
        parse_event,
    ) -> None:
        """Shared streaming loop: all token-timing semantics live here, once.

        ``parse_event(evt, res) -> str`` extracts the text piece from one
        decoded event and may set usage/server-timing fields on ``res``.
        Handles SSE ``data:`` frames and bare NDJSON lines; ``aiter_lines``
        flushes an unterminated final frame on close, so trailing usage
        records are never lost.
        """
        import json

        chunks: list[str] = []
        async for line in resp.aiter_lines():
            now = self._now()
            line = line.strip()
            if line.startswith("data:"):
                line = line[len("data:"):].strip()
            if not line or line == "[DONE]":
                continue
            try:
                evt = json.loads(line)
            except json.JSONDecodeError:  # kvmini: workload-ok — SSE
                # comments/keepalives; token-carrying events that fail to
                # parse would also fail the analyzer's token reconciliation
                continue
            piece = parse_event(evt, res) or ""
            if piece:
                if res.first_token_ts == 0.0:
                    res.first_token_ts = now
                res.last_token_ts = now
                chunks.append(piece)
        res.text = "".join(chunks)


_REGISTRY: dict[str, str] = {
    "openai": "kserve_vllm_mini_tpu.loadgen.adapters.openai_chat",
    "jax-native": "kserve_vllm_mini_tpu.loadgen.adapters.openai_chat",
    "vllm-tpu": "kserve_vllm_mini_tpu.loadgen.adapters.openai_chat",
    "jetstream": "kserve_vllm_mini_tpu.loadgen.adapters.jetstream",
    "kserve-v2": "kserve_vllm_mini_tpu.loadgen.adapters.kserve_v2",
    "triton": "kserve_vllm_mini_tpu.loadgen.adapters.kserve_v2",
}


def get_adapter(name: str) -> ProtocolAdapter:
    import importlib

    key = name.lower()
    if key not in _REGISTRY:
        raise ValueError(f"unknown protocol adapter {name!r}; known: {sorted(_REGISTRY)}")
    mod = importlib.import_module(_REGISTRY[key])
    return mod.ADAPTER
