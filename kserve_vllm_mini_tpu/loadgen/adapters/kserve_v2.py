"""KServe Open Inference Protocol (v2) generate adapter.

Speaks ``POST /v2/models/<name>/generate`` and ``/generate_stream`` (SSE),
the protocol the reference's Triton adapter uses
(/root/reference/runners/backends/triton/invoke.sh:68-259), with token
counting normalized like scripts/triton_token_utils.py (explicit token
fields first, len/4 heuristic fallback).
"""

from __future__ import annotations

import json
from typing import Optional

import httpx

from kserve_vllm_mini_tpu.loadgen.adapters.base import CallResult, GenParams, ProtocolAdapter
from kserve_vllm_mini_tpu.loadgen.prompts import approx_token_count


class KServeV2Adapter(ProtocolAdapter):
    name = "kserve-v2"

    async def generate(
        self,
        client: httpx.AsyncClient,
        base_url: str,
        model: str,
        prompt: str,
        params: GenParams,
        stream: bool,
        headers: Optional[dict[str, str]] = None,
    ) -> CallResult:
        suffix = "generate_stream" if stream else "generate"
        url = f"{base_url.rstrip('/')}/v2/models/{model}/{suffix}"
        body = {
            "text_input": prompt,
            "parameters": {
                "max_tokens": params.max_tokens,
                "temperature": params.temperature,
                **({"top_k": params.top_k} if params.top_k else {}),
                **({"top_p": params.top_p} if params.top_p != 1.0 else {}),
            },
        }
        res = CallResult(tokens_in=approx_token_count(prompt))
        try:
            if not stream:
                resp = await client.post(url, json=body, headers=headers)
                res.status_code = resp.status_code
                if resp.status_code != 200:
                    res.error = f"http-{resp.status_code}"
                    return res
                data = resp.json()
                res.text = data.get("text_output", "") or ""
                res.tokens_out = self._count_tokens(data, res.text)
                res.ok = True
                return res

            def parse_event(evt: dict, r: CallResult) -> str:
                piece = evt.get("text_output", "") or ""
                # per-chunk counts accumulate (a chunk reports its own tokens,
                # not a running total — reference triton_token_utils.py:24-52)
                r.tokens_out += self._count_tokens(evt, "")
                return piece

            async with client.stream("POST", url, json=body, headers=headers) as resp:
                res.status_code = resp.status_code
                if resp.status_code != 200:
                    res.error = f"http-{resp.status_code}"
                    await resp.aread()
                    return res
                await self._consume_sse(resp, res, parse_event)
            if not res.tokens_out:
                res.tokens_out = approx_token_count(res.text)
            res.ok = True
            return res
        except httpx.TimeoutException:
            # split connect/read timeouts (docs/RESILIENCE.md): a stalled
            # stream fails fast as an honest `timeout` row
            res.error = "timeout"
            return res
        except Exception as e:  # record, never abort the whole run
            res.error = type(e).__name__
            return res

    @staticmethod
    def _count_tokens(data: dict, text: str) -> int:
        """Explicit token-count fields first, heuristic fallback
        (reference scripts/triton_token_utils.py:4-21)."""
        for key in ("output_token_count", "completion_tokens", "generated_tokens"):
            v = data.get(key)
            if isinstance(v, (int, float)) and v > 0:
                return int(v)
        out = data.get("outputs")
        if isinstance(out, list):
            for o in out:
                if isinstance(o, dict) and o.get("name") in ("output_token_count", "sequence_length"):
                    arr = o.get("data")
                    if isinstance(arr, list) and arr:
                        return int(arr[0])
        return approx_token_count(text) if text else 0


ADAPTER = KServeV2Adapter()
