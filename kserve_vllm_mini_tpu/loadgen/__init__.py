from kserve_vllm_mini_tpu.loadgen.arrivals import generate_arrival_times, duration_and_rps

__all__ = ["generate_arrival_times", "duration_and_rps"]
