"""Prompt-set construction — first-class, replacing the reference's
cache-probe monkeypatching (/root/reference/cache-probe.sh:163-210, noted as a
defect in SURVEY.md §7.4).

A prompt set is a named, seeded sequence of prompts assigned per request:

- ``default``  — one templated prompt with a varying integer filler
- ``repeat``   — a small pool of identical prompts (high cache-hit potential)
- ``unique``   — every prompt distinct (zero cache-hit potential)
- ``mixed``    — repeat/unique interleaved at a given ratio
- ``sessions`` — ``pool_size`` concurrent sessions, each with its own LONG
  shared prefix and a short per-request tail (the prefix-heavy
  multi-session shape cache-aware fleet routing exists for, docs/FLEET.md)

The cache probe benches ``repeat`` vs ``unique`` and infers hit ratio from
the TTFT delta (reference cache-probe.sh:229-364).
"""

from __future__ import annotations

import random
from typing import Callable

_LOREM = (
    "Explain the trade-offs between tensor parallelism and pipeline "
    "parallelism for transformer inference on accelerator meshes"
)


def make_prompt_fn(
    prompt_set: str,
    base_prompt: str | None = None,
    seed: int = 42,
    pool_size: int = 8,
    mixed_repeat_ratio: float = 0.8,
    input_tokens: int = 0,
) -> Callable[[int], str]:
    """Return idx -> prompt for the named set.

    ``input_tokens`` pads prompts with filler words to approximate a target
    prompt length (4 chars/token heuristic shared with token counting).
    """
    base = base_prompt or _LOREM

    pad = ""
    if input_tokens > 0:
        words = max(input_tokens - len(base) // 4, 0)
        pad = " " + " ".join(f"w{i % 97}" for i in range(words))

    if prompt_set == "default":
        return lambda i: f"{base}{pad} (case {i % 100})"
    if prompt_set == "repeat":
        pool = [f"{base}{pad} [variant {j}]" for j in range(pool_size)]
        return lambda i: pool[i % pool_size]
    # "unique" and "mixed" derive per-index randomness from (seed, i) so the
    # idx->prompt mapping is independent of the async order in which workers
    # first call the function — seeded runs must be byte-reproducible.
    if prompt_set == "unique":
        # nonce FIRST: "unique" is the zero-cache-hit control set, and
        # prefix caches (including this repo's own engine APC) match from
        # the front — a trailing nonce would leave the whole base+pad
        # prefix reusable and quietly turn the miss baseline into hits
        def unique(i: int) -> str:
            salt = random.Random(f"{seed}:{i}").getrandbits(64)
            return f"[nonce {salt:016x} #{i}] {base}{pad}"

        return unique
    if prompt_set == "mixed":
        pool = [f"{base}{pad} [variant {j}]" for j in range(pool_size)]

        def mixed(i: int) -> str:
            r = random.Random(f"{seed}:{i}")
            if r.random() < mixed_repeat_ratio:
                return pool[i % pool_size]
            return f"[nonce {i}-{r.getrandbits(32):08x}] {base}{pad}"

        return mixed
    if prompt_set == "sessions":
        # multi-session prefix-heavy workload (docs/FLEET.md): request i
        # belongs to session i % pool_size; every session carries its own
        # LONG shared prefix (a system-prompt/history surrogate, salted
        # FIRST so sessions diverge from token 0 — prefix caches match
        # from the front) and a short per-turn tail. The shape
        # cache-aware routing exists for: a session's later turns reuse
        # deep prefix KV on the replica that served its earlier ones.
        def sessions(i: int) -> str:
            s = i % pool_size
            salt = random.Random(f"{seed}:session:{s}").getrandbits(64)
            ctx = " ".join(f"ctx{s}-{k % 89}" for k in range(160))
            return (
                f"[session {s:03d} {salt:016x}] {base}{pad} {ctx} "
                f"### turn {i // pool_size}: question {i}"
            )

        return sessions
    raise ValueError(f"unknown prompt set {prompt_set!r}")


def approx_token_count(text: str) -> int:
    """len/4 heuristic used when the server reports no usage
    (reference scripts/triton_token_utils.py:4-21)."""
    return max(len(text) // 4, 1)
