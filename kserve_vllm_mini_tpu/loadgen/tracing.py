"""Self-contained client-side tracer with OTLP-shaped JSON export.

Reimplements the behavior of the reference's embedded tracer
(/root/reference/scripts/loadtest.py:35-175): spans named
``client.request`` -> ``client.wait_scheduled`` / ``http.request`` ->
``server.ttft`` / ``server.tllt``, W3C ``traceparent`` propagation to the
server, and an OTLP/JSON resource-spans document written to
``runs/<id>/traces/traces.json``. No OpenTelemetry SDK dependency.
"""

from __future__ import annotations

import json
import secrets
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Optional


def new_trace_id() -> str:
    return secrets.token_hex(16)


def new_span_id() -> str:
    return secrets.token_hex(8)


def traceparent(trace_id: str, span_id: str, sampled: bool = True) -> str:
    """W3C trace-context header value (reference loadtest.py:64-67)."""
    return f"00-{trace_id}-{span_id}-{'01' if sampled else '00'}"


@dataclass
class TraceSpan:
    name: str
    trace_id: str
    span_id: str = field(default_factory=new_span_id)
    parent_span_id: Optional[str] = None
    start_ns: int = 0
    end_ns: int = 0
    attributes: dict[str, Any] = field(default_factory=dict)
    status_ok: bool = True

    def start(self) -> "TraceSpan":
        self.start_ns = time.time_ns()
        return self

    def end(self, ok: bool = True) -> "TraceSpan":
        self.end_ns = time.time_ns()
        self.status_ok = ok
        return self

    def set(self, key: str, value: Any) -> None:
        self.attributes[key] = value

    def to_otlp(self) -> dict[str, Any]:
        def _attr(k: str, v: Any) -> dict[str, Any]:
            if isinstance(v, bool):
                val = {"boolValue": v}
            elif isinstance(v, int):
                val = {"intValue": str(v)}
            elif isinstance(v, float):
                val = {"doubleValue": v}
            else:
                val = {"stringValue": str(v)}
            return {"key": k, "value": val}

        # a span abandoned on an error path (end() never ran) would export
        # end_ns=0 — a negative duration every viewer renders as garbage.
        # Clamp to the start instant and mark the status as error; the
        # exported doc stays valid and the abandonment is visible.
        end_ns, ok = self.end_ns, self.status_ok
        if end_ns < self.start_ns:
            end_ns, ok = self.start_ns, False
        return {
            "traceId": self.trace_id,
            "spanId": self.span_id,
            **({"parentSpanId": self.parent_span_id} if self.parent_span_id else {}),
            "name": self.name,
            "kind": 3,  # SPAN_KIND_CLIENT
            "startTimeUnixNano": str(self.start_ns),
            "endTimeUnixNano": str(end_ns),
            "attributes": [_attr(k, v) for k, v in self.attributes.items()],
            "status": {"code": 1 if ok else 2},
        }


class TraceCollector:
    """Accumulates spans across workers; exports one OTLP/JSON document."""

    def __init__(self, service_name: str = "kvmini-tpu-loadgen") -> None:
        self.service_name = service_name
        self.spans: list[TraceSpan] = []

    def span(
        self,
        name: str,
        trace_id: str,
        parent: Optional[TraceSpan] = None,
        **attributes: Any,
    ) -> TraceSpan:
        s = TraceSpan(
            name=name,
            trace_id=trace_id,
            parent_span_id=parent.span_id if parent else None,
            attributes=dict(attributes),
        ).start()
        self.spans.append(s)
        return s

    def to_otlp(self) -> dict[str, Any]:
        return {
            "resourceSpans": [
                {
                    "resource": {
                        "attributes": [
                            {
                                "key": "service.name",
                                "value": {"stringValue": self.service_name},
                            }
                        ]
                    },
                    "scopeSpans": [
                        {
                            "scope": {"name": "kserve_vllm_mini_tpu.loadgen"},
                            "spans": [s.to_otlp() for s in self.spans],
                        }
                    ],
                }
            ]
        }

    def export(self, path: str | Path) -> None:
        p = Path(path)
        p.parent.mkdir(parents=True, exist_ok=True)
        with p.open("w") as f:
            json.dump(self.to_otlp(), f, indent=2)
            f.write("\n")
