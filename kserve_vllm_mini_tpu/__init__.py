"""kserve-vllm-mini-tpu: a TPU-native LLM serving benchmark + runtime framework.

A ground-up rebuild of the capability surface of `kserve-vllm-mini`
(deploy -> load-test -> analyze -> cost -> energy -> report pipelines for LLM
inference services) designed TPU-first:

- the serving runtime is in-repo (JAX/XLA/Pallas continuous-batching engine,
  ``kserve_vllm_mini_tpu.runtime``) rather than an external container image;
- parallelism is real (``jax.sharding.Mesh`` over ICI/DCN with tp/dp/sp/ep
  axes, ``kserve_vllm_mini_tpu.parallel``) instead of passthrough env knobs;
- telemetry uses TPU device-plugin / libtpu style metrics with modeled power
  fallback instead of DCGM/NVML;
- cost accounting is TPU chip-hour based.

The universal contract mirrors the reference's run-directory pipeline
(reference: SURVEY.md L1; /root/reference/bench.sh:201-289): every stage
read-modify-writes ``results.json`` inside ``runs/<id>/``.
"""

__version__ = "0.1.0"
