"""Unified ``kvmini-tpu`` CLI.

The reference wraps its stages behind a single console script that dispatches
to per-stage scripts via subprocess (/root/reference/kvmini/cli.py:30-150).
Here every stage is an importable module with a ``register(subparsers)`` /
``run(args)`` pair, dispatched in-process — no shelling out, no flag
reconstruction.

Subcommands are registered lazily so that e.g. ``kvmini-tpu analyze`` works in
an environment without JAX while ``kvmini-tpu serve`` needs it.
"""

from __future__ import annotations

import argparse
import importlib
import sys
from typing import Callable, Optional, Sequence

# subcommand -> (module, help). Each module exposes
#   register(parser: argparse.ArgumentParser) -> None
#   run(args: argparse.Namespace) -> int
_SUBCOMMANDS: dict[str, tuple[str, str]] = {
    "loadtest": ("kserve_vllm_mini_tpu.loadgen.runner", "Generate load against an endpoint"),
    "analyze": ("kserve_vllm_mini_tpu.analysis.analyzer", "requests.csv -> results.json metrics"),
    "cost": ("kserve_vllm_mini_tpu.costs.estimator", "Attribute cost from resource-seconds x pricing"),
    "energy": ("kserve_vllm_mini_tpu.energy.collector", "Collect/integrate chip power into Wh metrics"),
    "report": ("kserve_vllm_mini_tpu.report.html", "Render HTML report from results.json / sweep CSVs"),
    "plan": ("kserve_vllm_mini_tpu.costs.planner", "Capacity planning: chips for target RPS at SLO"),
    "gate": ("kserve_vllm_mini_tpu.gates.slo", "Pass/fail results against SLO budgets"),
    "canary": ("kserve_vllm_mini_tpu.gates.canary", "Compare candidate vs baseline run"),
    "serve": ("kserve_vllm_mini_tpu.runtime.server", "Start the in-repo JAX serving runtime"),
    "bench": ("kserve_vllm_mini_tpu.bench_pipeline", "Full pipeline: validate -> load -> analyze -> cost"),
    "validate": ("kserve_vllm_mini_tpu.core.validate", "Pre-flight config validation"),
    "quality": ("kserve_vllm_mini_tpu.quality.evaluator", "Run the mini quality-eval suite"),
    "sweep": ("kserve_vllm_mini_tpu.sweeps.grid", "Run a parameter sweep"),
    "compare": ("kserve_vllm_mini_tpu.compare.backends", "A/B/C compare serving backends"),
    "parity": ("kserve_vllm_mini_tpu.compare.parity", "OpenAI API conformance probe"),
    "fairness": ("kserve_vllm_mini_tpu.compare.fairness", "Dual-tenant fairness/backpressure run"),
    "bundle": ("kserve_vllm_mini_tpu.provenance.bundle", "Create a signed reproducible artifact bundle"),
    "deploy": ("kserve_vllm_mini_tpu.deploy.manifests", "Render/apply KServe TPU manifests"),
    "probe": ("kserve_vllm_mini_tpu.probes.net_storage", "Network/storage IO probe"),
    "chaos": ("kserve_vllm_mini_tpu.chaos.harness", "Fault injection + MTTR measurement"),
}


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="kvmini-tpu",
        description="TPU-native LLM serving benchmark + runtime framework",
    )
    sub = parser.add_subparsers(dest="command", metavar="COMMAND")
    for name, (module_name, help_text) in sorted(_SUBCOMMANDS.items()):
        p = sub.add_parser(name, help=help_text)
        p.set_defaults(_module=module_name)
        try:
            mod = importlib.import_module(module_name)
        except ImportError:
            # Stage not built / optional deps missing: the subcommand still
            # lists in --help but errors with a clear message when invoked.
            p.set_defaults(_unavailable=module_name)
            continue
        register = getattr(mod, "register", None)
        if register is not None:
            register(p)
        p.set_defaults(_run=getattr(mod, "run", None))
    return parser


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    if not getattr(args, "command", None):
        parser.print_help()
        return 2
    if getattr(args, "_unavailable", None):
        print(
            f"kvmini-tpu: subcommand '{args.command}' is unavailable "
            f"(module {args._unavailable} failed to import)",
            file=sys.stderr,
        )
        return 2
    run: Optional[Callable[[argparse.Namespace], int]] = getattr(args, "_run", None)
    if run is None:
        print(f"kvmini-tpu: subcommand '{args.command}' has no runner yet", file=sys.stderr)
        return 2
    return int(run(args) or 0)


if __name__ == "__main__":
    raise SystemExit(main())
