"""Unified ``kvmini-tpu`` CLI.

The reference wraps its stages behind a single console script that dispatches
to per-stage scripts via subprocess (/root/reference/kvmini/cli.py:30-150).
Here every stage is an importable module with a ``register(parser)`` /
``run(args)`` pair, dispatched in-process — no shelling out, no flag
reconstruction.

Dispatch is genuinely lazy: only the chosen subcommand's module is imported,
so ``kvmini-tpu analyze`` never pays the JAX/libtpu import that
``kvmini-tpu serve`` needs, and a broken stage module breaks only its own
subcommand.
"""

from __future__ import annotations

import argparse
import importlib
import sys
import traceback
from typing import Optional, Sequence

# subcommand -> (module, help). Each module exposes
#   register(parser: argparse.ArgumentParser) -> None   (optional)
#   run(args: argparse.Namespace) -> int
_SUBCOMMANDS: dict[str, tuple[str, str]] = {
    "loadtest": ("kserve_vllm_mini_tpu.loadgen.runner", "Generate load against an endpoint"),
    "analyze": ("kserve_vllm_mini_tpu.analysis.analyzer", "requests.csv -> results.json metrics"),
    "cost": ("kserve_vllm_mini_tpu.costs.estimator", "Attribute cost from resource-seconds x pricing"),
    "cost-simple": ("kserve_vllm_mini_tpu.costs.simple", "Back-of-envelope $/1K tokens from latency x chip price"),
    "energy": ("kserve_vllm_mini_tpu.energy.collector", "Collect/integrate chip power into Wh metrics"),
    "report": ("kserve_vllm_mini_tpu.report.html", "Render HTML report from results.json / sweep CSVs"),
    "plan": ("kserve_vllm_mini_tpu.costs.planner", "Capacity planning: chips for target RPS at SLO"),
    "gate": ("kserve_vllm_mini_tpu.gates.slo", "Pass/fail results against SLO budgets"),
    "canary": ("kserve_vllm_mini_tpu.gates.canary", "Compare candidate vs baseline run"),
    "serve": ("kserve_vllm_mini_tpu.runtime.server", "Start the in-repo JAX serving runtime"),
    "bench": ("kserve_vllm_mini_tpu.bench_pipeline", "Full pipeline: validate -> load -> analyze -> cost"),
    "validate": ("kserve_vllm_mini_tpu.core.validate", "Pre-flight config validation"),
    "quality": ("kserve_vllm_mini_tpu.quality.evaluator", "Run the mini quality-eval suite"),
    "sweep": ("kserve_vllm_mini_tpu.sweeps.runner", "Run a parameter sweep"),
    "compare": ("kserve_vllm_mini_tpu.compare.backends", "A/B/C compare serving backends"),
    "parity": ("kserve_vllm_mini_tpu.compare.parity", "OpenAI API conformance probe"),
    "fairness": ("kserve_vllm_mini_tpu.compare.fairness", "Dual-tenant fairness/backpressure run"),
    "bundle": ("kserve_vllm_mini_tpu.provenance.bundle", "Create a signed reproducible artifact bundle"),
    "deploy": ("kserve_vllm_mini_tpu.deploy.manifests", "Render/apply KServe TPU manifests"),
    "probe": ("kserve_vllm_mini_tpu.probes.net_storage", "Network/storage IO probe"),
    "cache-probe": ("kserve_vllm_mini_tpu.probes.cache", "Infer prompt-cache hit ratio from TTFT deltas"),
    "preflight": ("kserve_vllm_mini_tpu.deploy.preflight", "Cluster/local environment checks"),
    "facts": ("kserve_vllm_mini_tpu.provenance.facts", "Collect cluster/local provenance facts"),
    "matrix": ("kserve_vllm_mini_tpu.matrix.runner", "GA-hardening reference matrix run"),
    "compile-sweep": ("kserve_vllm_mini_tpu.sweeps.compile_perf", "AOT compile-time vs serving-perf tradeoff"),
    "chaos": ("kserve_vllm_mini_tpu.chaos.harness", "Fault injection + MTTR measurement"),
    "profile": ("kserve_vllm_mini_tpu.profiling.capture", "Capture a TensorBoard trace of a live runtime"),
    "trajectory": ("kserve_vllm_mini_tpu.analysis.trajectory",
                   "Perf trend over BENCH_*.json rounds (real + proxy series)"),
    "autoscale-controller": ("kserve_vllm_mini_tpu.autoscale.controller",
                             "SLO/duty-signal-driven replica controller"),
    "fleet": ("kserve_vllm_mini_tpu.fleet.service",
              "N serving replicas behind the cache-aware router "
              "(+ optional live local autoscaler)"),
    "autoscale-sim": ("kserve_vllm_mini_tpu.autoscale.simulate",
                      "Replay a load timeline against the autoscale policy"),
}


def _help_text() -> str:
    lines = [
        "usage: kvmini-tpu COMMAND [options]",
        "",
        "TPU-native LLM serving benchmark + runtime framework",
        "",
        "commands:",
    ]
    for name, (_, help_text) in sorted(_SUBCOMMANDS.items()):
        lines.append(f"  {name:<10} {help_text}")
    lines.append("")
    lines.append("run 'kvmini-tpu COMMAND --help' for command options")
    return "\n".join(lines)


def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        print(_help_text())
        return 0 if argv else 2
    command, rest = argv[0], argv[1:]
    if command not in _SUBCOMMANDS:
        print(f"kvmini-tpu: unknown command {command!r}\n\n{_help_text()}", file=sys.stderr)
        return 2
    module_name, help_text = _SUBCOMMANDS[command]
    try:
        mod = importlib.import_module(module_name)
    except Exception:
        print(
            f"kvmini-tpu: subcommand '{command}' is unavailable "
            f"({module_name} failed to import):\n{traceback.format_exc(limit=1)}",
            file=sys.stderr,
        )
        return 2
    parser = argparse.ArgumentParser(prog=f"kvmini-tpu {command}", description=help_text)
    register = getattr(mod, "register", None)
    if register is not None:
        register(parser)
    run = getattr(mod, "run", None)
    if run is None:
        print(f"kvmini-tpu: subcommand '{command}' has no runner yet", file=sys.stderr)
        return 2
    args = parser.parse_args(rest)
    try:
        return int(run(args) or 0)
    except FileNotFoundError as e:
        print(f"kvmini-tpu {command}: file not found: {e}", file=sys.stderr)
        return 1
    except BrokenPipeError:
        # Output pipe (head/less) closed early. Exit 141 (128+SIGPIPE), never
        # 0 — a truncated gate/canary verdict must not read as a pass.
        try:
            sys.stdout.close()
        except Exception:
            pass
        return 141


if __name__ == "__main__":
    raise SystemExit(main())
