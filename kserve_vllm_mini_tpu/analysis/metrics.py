"""Latency/throughput statistics over per-request records.

Behavior parity with the reference analyzer's math
(/root/reference/analyze.py:59-180): linear-interpolated percentiles,
fixed-bucket histograms, and token-timing analysis (TTFT vs per-token
time), reimplemented as typed pure functions.
"""

from __future__ import annotations

import math
from typing import Any, Optional, Sequence

from kserve_vllm_mini_tpu.core.rundir import RequestRecord, window_bounds


def percentile(values: Sequence[float], pct: float) -> float:
    """Linear interpolation between closest ranks (reference analyze.py:59-81).

    pct is clamped to [0, 100]. Returns NaN for empty input so that absence of
    data is never mistaken for a 0 ms latency by downstream gates.
    """
    if not values:
        return math.nan
    s = sorted(values)
    if len(s) == 1:
        return float(s[0])
    pct = min(max(pct, 0.0), 100.0)
    rank = (pct / 100.0) * (len(s) - 1)
    lo = int(math.floor(rank))
    hi = int(math.ceil(rank))
    if lo == hi:
        return float(s[lo])
    frac = rank - lo
    return float(s[lo] * (1.0 - frac) + s[hi] * frac)


def compute_histogram(values: Sequence[float], num_buckets: int = 20) -> dict[str, Any]:
    """Fixed-width histogram (reference analyze.py:84-122)."""
    if not values:
        return {"buckets": [], "counts": [], "min": 0.0, "max": 0.0}
    vmin, vmax = min(values), max(values)
    if vmax <= vmin:
        return {"buckets": [vmin], "counts": [len(values)], "min": vmin, "max": vmax}
    width = (vmax - vmin) / num_buckets
    counts = [0] * num_buckets
    for v in values:
        idx = min(int((v - vmin) / width), num_buckets - 1)
        counts[idx] += 1
    edges = [vmin + i * width for i in range(num_buckets)]
    return {"buckets": edges, "counts": counts, "min": vmin, "max": vmax}


def compute_latency_stats(records: list[RequestRecord]) -> dict[str, Any]:
    """Core latency/throughput block of results.json.

    Error rate is over all requests; latency percentiles over successful ones
    (matching the reference's handling in analyze.py:484-520).
    """
    total = len(records)
    ok = [r for r in records if r.ok]
    # shed requests (429 past the retry budget, docs/RESILIENCE.md) are
    # their OWN outcome class: an overload run shedding by design is not
    # a broken run, so they never inflate error_rate — and they are never
    # hidden either (shed_requests/shed_rate count them separately).
    # Latency percentiles stay over admitted (ok) requests only.
    shed = sum(1 for r in records if r.shed)
    retries = sum(r.retries for r in records)
    lat = [r.latency_ms for r in ok if r.latency_ms > 0]
    ttft = [r.ttft_ms for r in ok if r.ttft_ms > 0]
    t0, t1 = window_bounds(records)
    duration = max(t1 - t0, 1e-9)
    tokens_out = sum(r.tokens_out for r in ok)
    tokens_in = sum(r.tokens_in for r in ok)

    out: dict[str, Any] = {
        "requests": total,
        "error_rate": (total - len(ok) - shed) / total if total else 0.0,
        "throughput_rps": len(ok) / duration if t1 > t0 else 0.0,
        "tokens_per_sec": tokens_out / duration if t1 > t0 else 0.0,
        "window": {"start": t0, "end": t1, "duration_s": t1 - t0},
        "total_tokens_in": tokens_in,
        "total_tokens_out": tokens_out,
    }
    if shed:
        out["shed_requests"] = shed
        out["shed_rate"] = shed / total
    if retries:
        out["retries_total"] = retries
    # Latency keys are emitted only when data exists: an all-error run must
    # not write p95_ms=0.0 that a downstream SLO gate would happily pass.
    if lat:
        out.update(
            {
                "p50_ms": percentile(lat, 50),
                "p95_ms": percentile(lat, 95),
                "p99_ms": percentile(lat, 99),
                "mean_ms": sum(lat) / len(lat),
                "latency_histogram": compute_histogram(lat),
            }
        )
    if ttft:
        out.update(
            {
                "ttft_p50_ms": percentile(ttft, 50),
                "ttft_p95_ms": percentile(ttft, 95),
                "ttft_avg_ms": sum(ttft) / len(ttft),
                "ttft_histogram": compute_histogram(ttft),
            }
        )
    return out


def compute_token_timing(records: list[RequestRecord]) -> dict[str, Any]:
    """Streaming token-timing analysis (reference analyze.py:125-180).

    TPOT (time per output token) is measured between client first-token and
    last-token marks; requests with <2 output tokens or no streaming marks are
    skipped. When the runtime reported true server-side TTFT we also surface
    the client-vs-server delta, which the reference cannot (its TTFB-as-TTFT
    is client-approximate, SURVEY.md §7.3.5).
    """
    tpots: list[float] = []
    stream_ttfts: list[float] = []
    server_deltas: list[float] = []
    for r in records:
        if not r.ok:
            continue
        if r.first_token_ts > 0 and r.last_token_ts > r.first_token_ts and r.tokens_out > 1:
            per_tok = (r.last_token_ts - r.first_token_ts) * 1000.0 / (r.tokens_out - 1)
            tpots.append(per_tok)
        if r.ttft_ms > 0 and r.first_token_ts > 0:
            stream_ttfts.append(r.ttft_ms)
        if r.server_ttft_ms > 0 and r.ttft_ms > 0:
            server_deltas.append(r.ttft_ms - r.server_ttft_ms)
    out: dict[str, Any] = {"streaming_requests": len(stream_ttfts)}
    if tpots:
        out.update(
            {
                "tpot_p50_ms": percentile(tpots, 50),
                "tpot_p95_ms": percentile(tpots, 95),
                "tpot_mean_ms": sum(tpots) / len(tpots),
            }
        )
    if server_deltas:
        out["client_server_ttft_delta_ms_p50"] = percentile(server_deltas, 50)
    return out
