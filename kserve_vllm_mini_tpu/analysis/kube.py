"""kubectl introspection with graceful degradation.

Every cluster call is a subprocess (the reference's pattern, analyze.py:29-31)
that returns empty results rather than raising when no cluster is reachable —
the analyzer must work on a laptop against a bare run dir, exactly like the
reference CI running with KUBECONFIG=/dev/null (SURVEY.md §4.2).
"""

from __future__ import annotations

import json
import shutil
import subprocess
from datetime import datetime, timezone
from typing import Any, Optional

KUBECTL_TIMEOUT_S = 15


def kubectl_available() -> bool:
    return shutil.which("kubectl") is not None


def _run_kubectl(args: list[str]) -> Optional[dict[str, Any]]:
    if not kubectl_available():
        return None
    try:
        proc = subprocess.run(
            ["kubectl", *args, "-o", "json"],
            capture_output=True,
            timeout=KUBECTL_TIMEOUT_S,
            text=True,
        )
    except (subprocess.TimeoutExpired, OSError):
        return None
    if proc.returncode != 0:
        return None
    try:
        return json.loads(proc.stdout)
    except json.JSONDecodeError:
        return None


def get_service_pods(namespace: str, service: str) -> list[dict[str, Any]]:
    """Pods belonging to an InferenceService (KServe label convention)."""
    for selector in (
        f"serving.kserve.io/inferenceservice={service}",
        f"app={service}",
    ):
        data = _run_kubectl(["get", "pods", "-n", namespace, "-l", selector])
        if data and data.get("items"):
            return data["items"]
    return []


def parse_k8s_time(ts: str) -> Optional[float]:
    try:
        return datetime.fromisoformat(ts.replace("Z", "+00:00")).timestamp()
    except (ValueError, AttributeError):
        return None


def pod_started_times(pods: list[dict[str, Any]]) -> list[float]:
    """container startedAt epochs — the cold-start instants
    (reference analyze.py:358-395)."""
    out: list[float] = []
    for pod in pods:
        statuses = (pod.get("status") or {}).get("containerStatuses") or []
        for cs in statuses:
            started = ((cs.get("state") or {}).get("running") or {}).get("startedAt")
            t = parse_k8s_time(started) if started else None
            if t is not None:
                out.append(t)
    return out


def pod_lifetimes(pods: list[dict[str, Any]]) -> list[tuple[float, Optional[float]]]:
    """(start, end|None) epochs per pod for resource-second accounting."""
    out = []
    for pod in pods:
        meta = pod.get("metadata") or {}
        start = parse_k8s_time((pod.get("status") or {}).get("startTime", ""))
        end = parse_k8s_time(meta.get("deletionTimestamp", "")) if meta.get(
            "deletionTimestamp"
        ) else None
        if start is not None:
            out.append((start, end))
    return out


def parse_k8s_quantity(q: str) -> float:
    """K8s resource quantity -> float (cores or bytes). Mirrors the behavior
    of reference cost_estimator.py:48-83."""
    if not q:
        return 0.0
    q = str(q)
    suffixes = {
        "Ki": 1024.0, "Mi": 1024.0**2, "Gi": 1024.0**3, "Ti": 1024.0**4,
        "k": 1e3, "M": 1e6, "G": 1e9, "T": 1e12,
    }
    for suf, mult in suffixes.items():
        if q.endswith(suf):
            return float(q[: -len(suf)]) * mult
    if q.endswith("m"):
        return float(q[:-1]) / 1000.0
    try:
        return float(q)
    except ValueError:
        return 0.0


def pod_resources(pod: dict[str, Any]) -> dict[str, float]:
    """Summed container requests/limits: tpu chips, cpu cores, memory bytes.

    ``google.com/tpu`` replaces the reference's ``nvidia.com/gpu`` resource
    key (SURVEY.md §7.2.5)."""
    chips = cpu = mem = 0.0
    for c in (pod.get("spec") or {}).get("containers", []):
        res = c.get("resources") or {}
        merged = {**(res.get("requests") or {}), **(res.get("limits") or {})}
        chips += parse_k8s_quantity(merged.get("google.com/tpu", "0"))
        cpu += parse_k8s_quantity(merged.get("cpu", "0"))
        mem += parse_k8s_quantity(merged.get("memory", "0"))
    return {"tpu_chips": chips, "cpu_cores": cpu, "memory_bytes": mem}


def node_accelerator_of_pod(pod: dict[str, Any]) -> Optional[str]:
    """gke-tpu-accelerator label of the pod's node (pricing key)."""
    node_name = (pod.get("spec") or {}).get("nodeName")
    if not node_name:
        return None
    data = _run_kubectl(["get", "node", node_name])
    if not data:
        return None
    labels = (data.get("metadata") or {}).get("labels") or {}
    return labels.get("cloud.google.com/gke-tpu-accelerator") or labels.get(
        "cloud.google.com/gke-accelerator"
    )
