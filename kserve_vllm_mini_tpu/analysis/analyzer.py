"""Stage 2: requests.csv -> results.json.

Behavioral parity with the reference analyzer (/root/reference/analyze.py:
463-618): latency/TTFT percentiles + histograms, throughput, token timing,
cold/warm attribution from pod startedAt (or explicit instants, or the
runtime's start time), TPU utilization via the telemetry fallback chain,
cache-hit ratio, io-probe merge — all merged key-granular into results.json.

Degrades gracefully: with no cluster, no Prometheus, and no endpoint it still
produces the full latency/throughput block from the CSV alone.
"""

from __future__ import annotations

import argparse
from typing import Any, Optional

from kserve_vllm_mini_tpu.analysis.coldwarm import (
    classify_requests_cold_warm,
    compute_cold_warm_metrics,
)
from kserve_vllm_mini_tpu.analysis.metrics import (
    compute_latency_stats,
    compute_token_timing,
)
from kserve_vllm_mini_tpu.analysis import telemetry
from kserve_vllm_mini_tpu.core.rundir import RunDir, window_bounds


def analyze_run(
    run_dir: RunDir,
    prom_url: Optional[str] = None,
    endpoint: Optional[str] = None,
    namespace: Optional[str] = None,
    service: Optional[str] = None,
    cold_start_times: Optional[list[float]] = None,
    cold_window_s: float = 30.0,
) -> dict[str, Any]:
    records = run_dir.read_requests()
    meta = run_dir.read_meta()

    update: dict[str, Any] = {}
    for key in ("model", "runtime", "pattern", "concurrency", "streaming",
                "accelerator", "aborted_early"):
        if key in meta:
            update[key] = meta[key]
    update["run_id"] = run_dir.path.name

    update.update(compute_latency_stats(records))
    n_truncated = sum(1 for r in records if r.truncated)
    if n_truncated:
        # the engine cut these prompts to its prefill budget: the measured
        # workload differs from the requested one — surface, never hide,
        # and report severity (5 tokens lost ≠ 5000 tokens lost)
        update["truncated_requests"] = n_truncated
        update["truncated_prompt_tokens"] = sum(r.truncated_tokens for r in records)
    update["token_timing"] = compute_token_timing(records)
    for k in ("tpot_p50_ms", "tpot_p95_ms"):
        if k in update["token_timing"]:
            update[k] = update["token_timing"][k]

    # per-model breakdown: a multi-LoRA run rotates requests across
    # adapters (loadgen `models:` list; requests.csv model column) — the
    # aggregate alone would hide a slow adapter behind a fast base
    by_model: dict[str, list] = {}
    for r in records:
        if r.model:
            by_model.setdefault(r.model, []).append(r)
    if len(by_model) > 1:
        update["per_model"] = {
            name: {
                k: v
                for k, v in compute_latency_stats(rs).items()
                if k in ("requests", "p50_ms", "p95_ms", "ttft_p50_ms",
                         "ttft_p95_ms", "tokens_per_sec", "error_rate")
            }
            for name, rs in sorted(by_model.items())
        }

    # cold/warm: explicit instants > cluster pod introspection > none
    instants = list(cold_start_times or [])
    if not instants and namespace and service:
        from kserve_vllm_mini_tpu.analysis import kube

        pods = kube.get_service_pods(namespace, service)
        instants = kube.pod_started_times(pods)
    if instants:
        flags = classify_requests_cold_warm(records, instants, cold_window_s)
        run_dir.write_classified(records, flags)
        update.update(compute_cold_warm_metrics(records, flags))

    t0, t1 = window_bounds(records)
    # ONE /metrics scrape shared by the three telemetry consumers below —
    # a slow endpoint must cost one 5 s timeout, not three
    runtime_metrics = (
        telemetry.scrape_runtime_metrics(endpoint) if endpoint else {}
    )
    update.update(
        telemetry.collect_utilization(
            prom_url, endpoint, window_s=max(t1 - t0, 1.0),
            accelerator=meta.get("accelerator"),
            runtime_metrics=runtime_metrics,
        )
    )
    # monitor timeline (docs/MONITORING.md): when the run carried the 1 Hz
    # sampler, derive the TRUE windowed duty cycle and queue-depth
    # percentiles from it — a lone /metrics snapshot only ever fills the
    # instant keys above. A measured Prometheus window still outranks the
    # timeline's modeled power; the queue distribution is timeline-only
    # either way.
    timeline = run_dir.read_timeline()
    if timeline:
        tl_util = telemetry.timeline_utilization(
            timeline, accelerator=meta.get("accelerator")
        )
        if "tpu_duty_cycle_avg" in update:
            for k in ("tpu_duty_cycle_avg", "tpu_metrics_source",
                      "tpu_power_watts_avg", "power_provenance"):
                tl_util.pop(k, None)
        update.update(tl_util)
    update.update(
        telemetry.cache_hit_ratio(prom_url, endpoint,
                                  runtime_metrics=runtime_metrics)
    )
    # decode-pipeline counters (docs/DECODE_PIPELINE.md): only the in-repo
    # runtime exports these; absent for external engines
    update.update(
        telemetry.pipeline_counters(endpoint, runtime_metrics=runtime_metrics)
    )
    # chunked-prefill counters (docs/TROUBLESHOOTING.md "Long prompts
    # stall streaming"): same in-repo-only, absent-not-zero rule
    update.update(
        telemetry.prefill_counters(endpoint, runtime_metrics=runtime_metrics)
    )
    # compile-stats block (docs/PROFILING.md): same in-repo-only rule
    update.update(
        telemetry.compile_stats_block(endpoint, runtime_metrics=runtime_metrics)
    )
    # KV-cache & HBM block (docs/TROUBLESHOOTING.md "HBM pressure & KV
    # thrash") + headroom-model validation when the scrape carried both
    # the analytic estimate and an observed peak: same in-repo-only rule
    update.update(
        telemetry.kv_cache_block(endpoint, runtime_metrics=runtime_metrics)
    )
    # resilience block (docs/RESILIENCE.md): sheds/watchdog/degrade from
    # the runtime rail; the CSV-side shed accounting (shed_requests,
    # shed_rate, retries_total) already landed via compute_latency_stats
    update.update(
        telemetry.resilience_block(endpoint, runtime_metrics=runtime_metrics)
    )
    # disaggregated-serving block (docs/DISAGGREGATION.md): prefill-lane
    # handoff counters; only disaggregated in-repo runtimes export the
    # rail, so the same absent-not-zero rule applies
    update.update(
        telemetry.disagg_block(endpoint, runtime_metrics=runtime_metrics)
    )
    # fleet block (docs/FLEET.md): replica counts, placement/reroute/
    # shed accounting and scale-step cold starts — present only when the
    # endpoint was the fleet router's aggregated /metrics
    update.update(
        telemetry.fleet_block(endpoint, runtime_metrics=runtime_metrics)
    )
    # live-economics block (docs/ECONOMICS.md): the rolling-window cost/
    # energy rail from a priced engine or the fleet router's aggregate;
    # CPU backends without an econ_accelerator export nothing and get no
    # block — absent, never a fabricated $0
    update.update(
        telemetry.economics_block(endpoint, runtime_metrics=runtime_metrics)
    )

    # server-side request traces (docs/TRACING.md): fetch /traces, merge
    # the server leg into runs/<id>/traces/traces.json joined by trace_id,
    # and summarize the queue/prefill/decode phases into phase_breakdown.
    # A fleet-router endpoint stitches THREE lanes — client, router
    # (fleet.route/fleet.proxy), and one lane per replica with its own
    # clock offset — and joins the p99 outlier to its routing decision.
    # External engines without /traces degrade to the client-only doc.
    if endpoint:
        from kserve_vllm_mini_tpu.analysis import traces as traces_mod

        fleet_replicas = traces_mod.fetch_fleet_replicas(endpoint)
        if fleet_replicas:
            router_doc = traces_mod.fetch_server_traces(endpoint)
            replica_docs = {
                rid: traces_mod.fetch_server_traces(url)
                for rid, url in fleet_replicas
            }
            client_doc = run_dir.read_traces()
            merged, matched = traces_mod.merge_fleet_traces(
                client_doc, router_doc, replica_docs
            )
            if matched:
                run_dir.write_traces(merged)
                pb = traces_mod.phase_breakdown(
                    matched, merged.get("clockOffsetNanosEstimate"),
                    source="fleet:/traces",
                )
                if pb:
                    update["phase_breakdown"] = pb
            outlier = traces_mod.outlier_attribution(
                records, traces_mod.fetch_fleet_decisions(endpoint)
            )
            if outlier:
                update["routing_outlier"] = outlier
        else:
            server_doc = traces_mod.fetch_server_traces(endpoint)
            if server_doc.get("resourceSpans"):
                client_doc = run_dir.read_traces()
                merged, matched = traces_mod.merge_server_traces(
                    client_doc, server_doc
                )
                if matched:
                    run_dir.write_traces(merged)
                    pb = traces_mod.phase_breakdown(
                        matched, merged.get("clockOffsetNanosEstimate")
                    )
                    if pb:
                        update["phase_breakdown"] = pb

    io_probe = run_dir.read_io_probe()
    for key in ("network_rtt_p50_ms", "network_rtt_p95_ms", "storage_fetch_mbps"):
        if key in io_probe:
            update[key] = io_probe[key]

    chips = meta.get("chips") or meta.get("tpu_chips")
    if chips and update.get("tokens_per_sec"):
        update["tokens_per_sec_per_chip"] = update["tokens_per_sec"] / chips

    return run_dir.merge_into_results(update)


# -- CLI ---------------------------------------------------------------------

def register(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("--run-dir", required=True)
    parser.add_argument("--prom-url", default=None, help="Prometheus base URL")
    parser.add_argument("--endpoint", default=None,
                        help="Runtime base URL for /metrics scrape fallback")
    parser.add_argument("--namespace", default=None)
    parser.add_argument("--service", default=None)
    parser.add_argument("--cold-start-times", default=None,
                        help="Comma-separated epoch seconds (overrides cluster lookup)")
    parser.add_argument("--cold-window", type=float, default=30.0)


def run(args: argparse.Namespace) -> int:
    instants = None
    if args.cold_start_times:
        instants = [float(x) for x in args.cold_start_times.split(",") if x]
    results = analyze_run(
        RunDir(args.run_dir),
        prom_url=args.prom_url,
        endpoint=args.endpoint,
        namespace=args.namespace,
        service=args.service,
        cold_start_times=instants,
        cold_window_s=args.cold_window,
    )
    p95 = results.get("p95_ms")
    ttft = results.get("ttft_p50_ms")
    print(
        f"analyze: {results.get('requests', 0)} requests, "
        f"p95={p95:.1f}ms " if p95 is not None else "analyze: no successful requests ",
        end="",
    )
    if ttft is not None:
        print(f"ttft_p50={ttft:.1f}ms ", end="")
    print(
        f"rps={results.get('throughput_rps', 0):.2f} "
        f"tok/s={results.get('tokens_per_sec', 0):.1f} "
        f"err={results.get('error_rate', 0):.1%} -> {RunDir(args.run_dir).results_json}"
    )
    return 0
