from kserve_vllm_mini_tpu.analysis.metrics import (
    percentile,
    compute_histogram,
    compute_latency_stats,
    compute_token_timing,
)
from kserve_vllm_mini_tpu.analysis.coldwarm import (
    classify_requests_cold_warm,
    compute_cold_warm_metrics,
)

__all__ = [
    "percentile",
    "compute_histogram",
    "compute_latency_stats",
    "compute_token_timing",
    "classify_requests_cold_warm",
    "compute_cold_warm_metrics",
]
