"""Cold-vs-warm request attribution.

The reference detects cold starts from pod ``startedAt`` timestamps and tags
requests that begin within a window after a cold start as "cold"
(/root/reference/analyze.py:358-460). The mechanism is runtime-agnostic, so we
keep it: cold-start instants come from the cluster (pod introspection), from
the in-repo runtime's self-reported engine-ready timestamp, or from synthetic
fixtures in tests.
"""

from __future__ import annotations

from typing import Any, Sequence

from kserve_vllm_mini_tpu.analysis.metrics import percentile
from kserve_vllm_mini_tpu.core.rundir import RequestRecord

# Requests starting within this many seconds after a cold-start instant are
# classified cold (reference analyze.py:402-419 uses 30 s).
DEFAULT_COLD_WINDOW_S = 30.0


def classify_requests_cold_warm(
    records: Sequence[RequestRecord],
    cold_start_times: Sequence[float],
    window_s: float = DEFAULT_COLD_WINDOW_S,
) -> list[bool]:
    """Return per-request cold flags, aligned with ``records``."""
    flags: list[bool] = []
    for r in records:
        cold = any(0.0 <= r.start_ts - t <= window_s for t in cold_start_times)
        flags.append(cold)
    return flags


def compute_cold_warm_metrics(
    records: Sequence[RequestRecord], cold_flags: Sequence[bool]
) -> dict[str, Any]:
    """Cold/warm latency split + cold multiplier (reference analyze.py:422-460)."""
    cold_lat = [
        r.latency_ms for r, c in zip(records, cold_flags) if c and r.ok and r.latency_ms > 0
    ]
    warm_lat = [
        r.latency_ms for r, c in zip(records, cold_flags) if not c and r.ok and r.latency_ms > 0
    ]
    out: dict[str, Any] = {
        "cold_requests": sum(1 for c in cold_flags if c),
        "warm_requests": sum(1 for c in cold_flags if not c),
    }
    if cold_lat:
        out["cold_p50_ms"] = percentile(cold_lat, 50)
        out["cold_p95_ms"] = percentile(cold_lat, 95)
        out["cold_mean_ms"] = sum(cold_lat) / len(cold_lat)
    if warm_lat:
        out["warm_p50_ms"] = percentile(warm_lat, 50)
        out["warm_p95_ms"] = percentile(warm_lat, 95)
        out["warm_mean_ms"] = sum(warm_lat) / len(warm_lat)
    if cold_lat and warm_lat and out["warm_p95_ms"] > 0:
        out["cold_multiplier"] = out["cold_p95_ms"] / out["warm_p95_ms"]
    return out
