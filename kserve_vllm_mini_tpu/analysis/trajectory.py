"""Perf trajectory: every BENCH round in one trend table, never dark.

The driver benches land one artifact per round (``BENCH_r01.json`` ...):
a wrapper ``{n, cmd, rc, tail, parsed}`` whose ``parsed`` is bench.py's
one JSON line (or null when the round crashed — the pre-proxy era). This
module ingests all of them into a trajectory:

- **real** rounds (device throughput measured) and **proxy** rounds (the
  CPU-mesh fallback tier's compile/cost-model metrics, docs/PROFILING.md)
  are kept as SEPARATE series — a proxy FLOPs number must never be
  plotted against a device tokens/s number;
- **dark** rounds (no payload at all) stay visible as gaps, because a
  trajectory that hides its holes overstates its coverage;
- every round carries a **regression delta vs the anchor** — the last
  round of its own series that produced the metric — so a speed PR reads
  its effect straight off the table.

CLI: ``kvmini-tpu trajectory [--glob 'BENCH_*.json'] [--html out.html]
[--json out.json]`` — the HTML is report/html.py's "Perf trajectory"
section (chart + table), the same rendering the run report embeds.
"""

from __future__ import annotations

import argparse
import glob as glob_mod
import json
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Optional

_ROUND_NUM = re.compile(r"r?(\d+)$")

# proxy metrics tracked round-over-round, with direction of "worse"
# (+1 = an increase is a regression, -1 = a decrease is)
PROXY_TREND_KEYS = {
    "compile_wall_s": 1,
    "step_count_ratio": 1,
    "flops": 1,
    "bytes_accessed": 1,
    "peak_bytes": 1,
}


@dataclass
class Round:
    """One BENCH artifact, classified into a trajectory series."""

    name: str                      # "r01" / file stem
    index: int                     # ordering key (round number when parseable)
    status: str                    # ok | tpu_unavailable | oom | error | dark
    series: str                    # "real" | "proxy" | "dark"
    tokens_per_sec_per_chip: Optional[float] = None
    # real-round $/1K-tok from the chip-hour sheet (docs/ECONOMICS.md);
    # None for CPU rows, which report a 0.0 "n/a" that must never track
    # as a real price
    cost_per_1k_tokens_usd: Optional[float] = None
    vs_baseline: Optional[float] = None
    label: Optional[str] = None    # bench config label from the metric name
    downshifted: Optional[str] = None
    proxy: dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> dict[str, Any]:
        out: dict[str, Any] = {
            "name": self.name, "index": self.index, "status": self.status,
            "series": self.series,
        }
        for key in ("tokens_per_sec_per_chip", "cost_per_1k_tokens_usd",
                    "vs_baseline", "label", "downshifted"):
            v = getattr(self, key)
            if v is not None:
                out[key] = v
        if self.proxy:
            out["proxy"] = self.proxy
        return out


def _round_name(path: Path) -> tuple[str, int]:
    stem = path.stem
    name = stem[6:] if stem.startswith("BENCH_") else stem
    m = _ROUND_NUM.search(name)
    return name, int(m.group(1)) if m else 0


def _classify_dark(wrapper: dict[str, Any]) -> str:
    tail = str(wrapper.get("tail", ""))
    if "RESOURCE_EXHAUSTED" in tail:
        return "oom"
    if "UNAVAILABLE" in tail or "Unable to initialize backend" in tail:
        return "tpu_unavailable"
    return "error"


def load_round(path: Path) -> Round:
    """Parse one BENCH artifact — the driver wrapper or a bare bench.py
    line — into a Round. Unreadable files become dark rounds (the
    trajectory must survive a corrupt artifact)."""
    name, index = _round_name(path)
    try:
        doc = json.loads(path.read_text())
    except (OSError, ValueError):
        return Round(name=name, index=index, status="error", series="dark")
    parsed = doc.get("parsed", doc) if isinstance(doc, dict) else None
    if not isinstance(parsed, dict) or "metric" not in parsed:
        return Round(name=name, index=index,
                     status=_classify_dark(doc if isinstance(doc, dict) else {}),
                     series="dark")
    detail = parsed.get("detail") or {}
    status = str(parsed.get("status", "ok"))
    value = parsed.get("value")
    tok_s = float(value) if isinstance(value, (int, float)) and value > 0 \
        else None
    proxy = detail.get("proxy") or {}
    if proxy.get("status") == "ok" or proxy.get("series") == "proxy":
        proxy = {k: proxy[k] for k in PROXY_TREND_KEYS if k in proxy}
    else:
        proxy = {}
    if tok_s is not None:
        series = "real"
    elif proxy:
        series = "proxy"
    else:
        series = "dark"
    label = None
    metric = str(parsed.get("metric", ""))
    if "(" in metric:
        label = metric.split("(", 1)[1].split(")", 1)[0]
    cost = detail.get("cost_per_1k_tokens_usd")
    cost = float(cost) if isinstance(cost, (int, float)) and cost > 0 \
        else None
    return Round(
        name=name, index=index, status=status, series=series,
        tokens_per_sec_per_chip=tok_s,
        cost_per_1k_tokens_usd=cost,
        vs_baseline=parsed.get("vs_baseline"),
        label=label,
        downshifted=detail.get("downshifted"),
        proxy=proxy,
    )


def load_rounds(paths: list[Path]) -> list[Round]:
    return sorted((load_round(Path(p)) for p in paths),
                  key=lambda r: (r.index, r.name))


def _delta_pct(value: float, anchor: float) -> Optional[float]:
    if not anchor:
        return None
    return round((value - anchor) / anchor * 100.0, 2)


def build_trajectory(rounds: list[Round]) -> dict[str, Any]:
    """The trend document: per-round rows with same-series regression
    deltas, the last-real anchor, and coverage accounting."""
    rows: list[dict[str, Any]] = []
    last_real: Optional[Round] = None
    last_cost: Optional[Round] = None
    last_proxy: dict[str, float] = {}
    regressions: list[dict[str, Any]] = []
    for r in rounds:
        row = r.to_dict()
        if r.series == "real" and r.tokens_per_sec_per_chip:
            if last_real is not None and last_real.tokens_per_sec_per_chip:
                d = _delta_pct(r.tokens_per_sec_per_chip,
                               last_real.tokens_per_sec_per_chip)
                row["delta_vs_last_real_pct"] = d
                if d is not None and d < 0:
                    regressions.append({
                        "round": r.name, "metric": "tokens_per_sec_per_chip",
                        "value": r.tokens_per_sec_per_chip,
                        "anchor": last_real.tokens_per_sec_per_chip,
                        "anchor_round": last_real.name,
                        "delta_pct": d,
                    })
            last_real = r
        # $/1K-tok trend (docs/ECONOMICS.md): its own anchor, because a
        # priced round can follow an unpriced real one (CPU smoke) —
        # anchoring on last_real would lose the trend across the gap.
        # A cost INCREASE is the regression (worse direction +1).
        if r.cost_per_1k_tokens_usd:
            if last_cost is not None and last_cost.cost_per_1k_tokens_usd:
                d = _delta_pct(r.cost_per_1k_tokens_usd,
                               last_cost.cost_per_1k_tokens_usd)
                if d is not None:
                    row["cost_delta_pct"] = d
                    if d > 10.0:
                        regressions.append({
                            "round": r.name,
                            "metric": "cost_per_1k_tokens_usd",
                            "value": r.cost_per_1k_tokens_usd,
                            "anchor": last_cost.cost_per_1k_tokens_usd,
                            "anchor_round": last_cost.name,
                            "delta_pct": d,
                        })
            last_cost = r
        # any round CARRYING proxy data advances the proxy trend — a
        # healthy round run with KVMINI_BENCH_PROXY=always tracks
        # compile-time drift exactly like a dark round's fallback does
        if r.proxy:
            deltas = {}
            for key, worse_dir in PROXY_TREND_KEYS.items():
                v = r.proxy.get(key)
                a = last_proxy.get(key)
                if isinstance(v, (int, float)) and a:
                    d = _delta_pct(float(v), a)
                    if d is not None:
                        deltas[key] = d
                        if d * worse_dir > 10.0:  # >10% in the bad direction
                            regressions.append({
                                "round": r.name, "metric": f"proxy:{key}",
                                "value": v, "anchor": a, "delta_pct": d,
                            })
            if deltas:
                row["proxy_delta_pct"] = deltas
            for key in PROXY_TREND_KEYS:
                if isinstance(r.proxy.get(key), (int, float)):
                    last_proxy[key] = float(r.proxy[key])
        rows.append(row)
    n_real = sum(1 for r in rounds if r.series == "real")
    n_proxy = sum(1 for r in rounds if r.series == "proxy")
    return {
        "rounds": rows,
        "last_real": last_real.to_dict() if last_real else None,
        "regressions": regressions,
        "coverage": {
            "total": len(rounds),
            "real": n_real,
            "proxy": n_proxy,
            "dark": len(rounds) - n_real - n_proxy,
        },
    }


def render_table(traj: dict[str, Any]) -> str:
    """Plain-text trend table (the CLI's stdout; markdown-compatible)."""
    cov = traj["coverage"]
    lines = [
        f"Perf trajectory — {cov['total']} rounds: {cov['real']} real, "
        f"{cov['proxy']} proxy, {cov['dark']} dark",
        "",
        "| round | series | status | tok/s/chip | Δ vs last real |"
        " $/1K tok | Δ cost | compile s | step ratio | note |",
        "|---|---|---|---|---|---|---|---|---|---|",
    ]
    for row in traj["rounds"]:
        tok = row.get("tokens_per_sec_per_chip")
        delta = row.get("delta_vs_last_real_pct")
        cost = row.get("cost_per_1k_tokens_usd")
        cost_d = row.get("cost_delta_pct")
        px = row.get("proxy", {})
        note = row.get("downshifted") or ""
        lines.append(
            f"| {row['name']} | {row['series']} | {row['status']} "
            f"| {tok if tok is not None else '—'} "
            f"| {f'{delta:+.1f}%' if delta is not None else '—'} "
            f"| {f'{cost:.4f}' if cost is not None else '—'} "
            f"| {f'{cost_d:+.1f}%' if cost_d is not None else '—'} "
            f"| {px.get('compile_wall_s', '—')} "
            f"| {px.get('step_count_ratio', '—')} | {note} |"
        )
    if traj["regressions"]:
        lines.append("")
        lines.append("Regressions (vs same-series anchor):")
        for reg in traj["regressions"]:
            lines.append(
                f"  {reg['round']}: {reg['metric']} {reg['value']} "
                f"vs {reg['anchor']} ({reg['delta_pct']:+.1f}%)"
            )
    return "\n".join(lines)


# -- CLI ---------------------------------------------------------------------

def register(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("--glob", default="BENCH_*.json",
                        help="BENCH artifact glob (driver wrapper or bare "
                             "bench.py line)")
    parser.add_argument("--files", nargs="*", default=None,
                        help="Explicit artifact paths (overrides --glob)")
    parser.add_argument("--json", default=None,
                        help="Write the trajectory document here")
    parser.add_argument("--html", default=None,
                        help="Write the 'Perf trajectory' HTML page here")


def run(args: argparse.Namespace) -> int:
    paths = [Path(p) for p in (args.files or sorted(glob_mod.glob(args.glob)))]
    if not paths:
        print(f"trajectory: no artifacts matched {args.glob!r}")
        return 1
    traj = build_trajectory(load_rounds(paths))
    print(render_table(traj))
    if args.json:
        Path(args.json).write_text(json.dumps(traj, indent=2))
        print(f"trajectory: wrote {args.json}")
    if args.html:
        from kserve_vllm_mini_tpu.report.html import generate_trajectory_html

        Path(args.html).write_text(generate_trajectory_html(traj))
        print(f"trajectory: wrote {args.html}")
    return 0
