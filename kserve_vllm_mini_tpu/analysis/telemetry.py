"""TPU telemetry with fallback chains — the DCGM replacement.

The reference queries Prometheus for DCGM GPU metrics with metric-name
fallbacks (/root/reference/analyze.py:250-309, energy/collector.py:44-48)
because metric names vary by stack. TPU stacks vary even more, so the same
pattern applies over three sources, tried in order:

1. **Prometheus** with GKE / tpu-device-plugin metric-name candidates
   (``kubernetes_io:node_accelerator_tpu_duty_cycle`` et al)
2. **The runtime's own /metrics endpoint** (kvmini_tpu_* gauges served by
   runtime/server.py) — works with no cluster at all
3. **Modeled values** (duty-cycle x TDP) — always available, marked
   ``provenance: modeled`` per SURVEY.md §7.3.3

All HTTP via urllib (no client dependency for the harness layers).
"""

from __future__ import annotations

import json
import urllib.parse
import urllib.request
from typing import Any, Optional

# metric-name fallback chains (query templates get .format(window_s=...))
TPU_DUTY_CYCLE_QUERIES = [
    "avg(kubernetes_io:node_accelerator_tpu_duty_cycle)",
    "avg(tpu_duty_cycle)",
    "avg(duty_cycle)",
    "avg(kvmini_tpu_duty_cycle)",
]
TPU_HBM_QUERIES = [
    "avg(kubernetes_io:node_accelerator_tpu_memory_used)",
    "avg(tpu_memory_used_bytes)",
    "avg(memory_used)",
]
TPU_POWER_QUERIES = [
    "sum(kubernetes_io:node_accelerator_tpu_power_usage)",
    "sum(tpu_power_usage_watts)",
    "sum(tpu_power_watts)",
]
CPU_UTIL_QUERIES = [
    'avg(rate(container_cpu_usage_seconds_total{{container!=""}}[{window_s}s]))',
]
CACHE_HIT_QUERIES = [
    "sum(kvmini_tpu_cache_hits_total) / clamp_min(sum(kvmini_tpu_cache_lookups_total), 1)",
    "sum(vllm:cache_query_hit) / clamp_min(sum(vllm:cache_query_total), 1)",
]

# Thermal design power per chip (watts) for modeled energy. Public figures:
# v4 ~170W, v5e ~can be taken ~170W max / typical serving ~120W, v5p ~350W.
TPU_TDP_WATTS = {
    "v4": 170.0,
    "v5e": 170.0,
    "v5p": 350.0,
    "v6e": 170.0,
    "default": 170.0,
}


def tdp_for_accelerator(accelerator: Optional[str]) -> float:
    if accelerator:
        for key, w in TPU_TDP_WATTS.items():
            if key != "default" and key in accelerator.lower():
                return w
    return TPU_TDP_WATTS["default"]


# idle power floor as a fraction of TDP for the modeled-power formula
MODELED_IDLE_FRACTION = 0.15


def modeled_power(duty_cycle: float, accelerator: Optional[str]) -> float:
    """The single source of truth for duty-cycle -> watts modeling; used by
    both live sampling (energy/collector.py) and post-hoc utilization."""
    tdp = tdp_for_accelerator(accelerator)
    return tdp * (MODELED_IDLE_FRACTION + (1.0 - MODELED_IDLE_FRACTION) * duty_cycle)


def prom_instant_query(prom_url: str, query: str, timeout_s: float = 5.0) -> Optional[float]:
    """Single instant query -> first scalar value, or None."""
    url = prom_url.rstrip("/") + "/api/v1/query?" + urllib.parse.urlencode({"query": query})
    try:
        with urllib.request.urlopen(url, timeout=timeout_s) as resp:
            data = json.loads(resp.read())
    except Exception:
        return None
    if data.get("status") != "success":
        return None
    results = data.get("data", {}).get("result", [])
    if not results:
        return None
    try:
        return float(results[0]["value"][1])
    except (KeyError, IndexError, TypeError, ValueError):
        return None


def query_with_fallbacks(
    prom_url: str, queries: list[str], window_s: float = 60.0
) -> tuple[Optional[float], Optional[str]]:
    """Try each query until one answers; returns (value, winning_query)."""
    for q in queries:
        v = prom_instant_query(prom_url, q.format(window_s=int(window_s)))
        if v is not None:
            return v, q
    return None, None


def parse_prometheus_text(text: str) -> dict[str, float]:
    """Prometheus text exposition -> flat {metric_name: value} dict.

    Labeled series sharing one metric name are SUMMED, not last-wins: a
    runtime exporting ``kvmini_tpu_foo_total{tenant="a"} 3`` and
    ``{tenant="b"} 4`` must aggregate to 7 — the old overwrite silently
    reported whichever series the exporter emitted last. Summing is the
    Prometheus aggregation for counters; consumers needing per-label
    series should query Prometheus, not this flat scrape."""
    out: dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        # `name{labels} value [timestamp]` — labels may contain spaces, and a
        # trailing timestamp must not be mistaken for the value
        if "}" in line:
            name = line.split("{", 1)[0]
            rest = line[line.rindex("}") + 1:].split()
        else:
            parts = line.split()
            name, rest = parts[0], parts[1:]
        if rest:
            try:
                out[name] = out.get(name, 0.0) + float(rest[0])
            except ValueError:
                continue
    return out


def scrape_runtime_metrics(endpoint: str, timeout_s: float = 5.0) -> dict[str, float]:
    """Parse the runtime's Prometheus text exposition into a flat dict."""
    url = endpoint.rstrip("/") + "/metrics"
    try:
        with urllib.request.urlopen(url, timeout=timeout_s) as resp:
            text = resp.read().decode()
    except Exception:
        return {}
    return parse_prometheus_text(text)


def collect_utilization(
    prom_url: Optional[str],
    endpoint: Optional[str],
    window_s: float,
    accelerator: Optional[str] = None,
    runtime_metrics: Optional[dict[str, float]] = None,
) -> dict[str, Any]:
    """The full fallback chain -> utilization block for results.json.

    ``runtime_metrics``: a pre-scraped /metrics dict, so a caller hitting
    several telemetry consumers (analyzer) pays ONE scrape, not one per
    consumer; None = scrape here."""
    out: dict[str, Any] = {}
    if prom_url:
        duty, q = query_with_fallbacks(prom_url, TPU_DUTY_CYCLE_QUERIES, window_s)
        if duty is not None:
            out["tpu_duty_cycle_avg"] = duty if duty <= 1.0 else duty / 100.0
            out["tpu_metrics_source"] = f"prometheus:{q}"
        hbm, _ = query_with_fallbacks(prom_url, TPU_HBM_QUERIES, window_s)
        if hbm is not None:
            out["tpu_hbm_used_avg_gib"] = hbm / (1024**3) if hbm > 1e6 else hbm
        power, _ = query_with_fallbacks(prom_url, TPU_POWER_QUERIES, window_s)
        if power is not None:
            out["tpu_power_watts_avg"] = power
            out["power_provenance"] = "measured"
        cpu, _ = query_with_fallbacks(prom_url, CPU_UTIL_QUERIES, window_s)
        if cpu is not None:
            out["cpu_util_avg"] = cpu
    if "tpu_duty_cycle_avg" not in out and endpoint:
        m = (runtime_metrics if runtime_metrics is not None
             else scrape_runtime_metrics(endpoint))
        if "kvmini_tpu_duty_cycle" in m:
            # ONE instantaneous scrape is not a window average: it lands
            # in the instant key with an honest source tag, and the *_avg
            # key stays absent unless a real window (Prometheus range or
            # the monitor timeline — timeline_utilization) backs it
            out["tpu_duty_cycle"] = m["kvmini_tpu_duty_cycle"]
            out["tpu_metrics_source"] = "runtime:/metrics:instant"
    if "tpu_power_watts_avg" not in out and "tpu_duty_cycle_avg" in out:
        out["tpu_power_watts_avg"] = modeled_power(out["tpu_duty_cycle_avg"], accelerator)
        out["power_provenance"] = "modeled"
    return out


def nearest_rank_percentile(vals: list[float], pct: float) -> float:
    """Nearest-rank percentile — the ONE implementation shared by the
    live monitor's burn-rate windows (monitor/burnrate.py) and the
    timeline summaries below. (analysis/metrics.py keeps its
    deliberately different interpolated percentile for the post-hoc
    latency stats.) Empty input yields 0.0."""
    vals = sorted(vals)
    if not vals:
        return 0.0
    k = max(int(round(pct / 100.0 * len(vals) + 0.5)) - 1, 0)
    return vals[min(k, len(vals) - 1)]


def windowed_duty_series(
    pts: list[tuple[float, dict[str, Any]]],
) -> list[tuple[float, float]]:
    """Per-sample windowed duty cycle from timeline runtime blocks: the
    delta of the busy-seconds counter over each sample gap (clamped to
    [0, 1]) assigned to the gap's end; samples without a usable delta
    fall back to the cumulative duty-cycle gauge. The ONE implementation
    behind energy integration (power_from_timeline) and the report's
    timeline lane — counter-reset/gap-handling fixes land once."""
    out: list[tuple[float, float]] = []
    prev_t: Optional[float] = None
    prev_busy: Optional[float] = None
    for t, rt in pts:
        duty: Optional[float] = None
        busy = rt.get("busy_seconds_total")
        if (
            busy is not None and prev_busy is not None
            and prev_t is not None and t > prev_t
        ):
            duty = max(min((busy - prev_busy) / (t - prev_t), 1.0), 0.0)
        elif "duty_cycle" in rt:
            duty = float(rt["duty_cycle"])
        if duty is not None:
            out.append((t, duty))
        if busy is not None:
            prev_t, prev_busy = t, float(busy)
    return out


def timeline_utilization(
    timeline: list[dict[str, Any]],
    accelerator: Optional[str] = None,
) -> dict[str, Any]:
    """True windowed utilization from the monitor's 1 Hz timeline
    (monitor/sampler.py; docs/MONITORING.md) — the fix for the
    snapshot-as-average lie: ``tpu_duty_cycle_avg`` here is the delta of
    the runtime's busy-seconds counter over the sampled span (falling
    back to time-weighting the instantaneous gauge), and queue-depth
    percentiles summarize every sample, not one scrape."""
    pts = [
        (float(s["t"]), s["runtime"])
        for s in timeline
        if isinstance(s.get("t"), (int, float))
        and isinstance(s.get("runtime"), dict)
    ]
    if len(pts) < 2:
        return {}
    out: dict[str, Any] = {}
    t0, t1 = pts[0][0], pts[-1][0]
    busy0 = pts[0][1].get("busy_seconds_total")
    busy1 = pts[-1][1].get("busy_seconds_total")
    duty: Optional[float] = None
    if busy0 is not None and busy1 is not None and t1 > t0:
        # full-span counter delta == the gap-length-weighted mean of
        # windowed_duty_series — one subtraction instead of a fold
        duty = max(min((busy1 - busy0) / (t1 - t0), 1.0), 0.0)
    else:
        gauges = [
            (t, rt["duty_cycle"]) for t, rt in pts if "duty_cycle" in rt
        ]
        if len(gauges) >= 2:
            # time-weighted mean of the gauge — weaker (the gauge is
            # cumulative-since-start) but still a span, not a snapshot
            num = sum(
                0.5 * (va + vb) * (tb - ta)
                for (ta, va), (tb, vb) in zip(gauges, gauges[1:])
            )
            den = gauges[-1][0] - gauges[0][0]
            if den > 0:
                duty = max(min(num / den, 1.0), 0.0)
    if duty is not None:
        out["tpu_duty_cycle_avg"] = duty
        out["tpu_metrics_source"] = (
            f"timeline:runtime:/metrics ({len(pts)} samples)"
        )
        out["tpu_power_watts_avg"] = modeled_power(duty, accelerator)
        out["power_provenance"] = "modeled"
    depths = [rt["queue_depth"] for _t, rt in pts if "queue_depth" in rt]
    if depths:
        out["queue_depth_p50"] = nearest_rank_percentile(depths, 50.0)
        out["queue_depth_p95"] = nearest_rank_percentile(depths, 95.0)
        out["queue_depth_max"] = max(depths)
    return out


# runtime gauge/counter -> results.json key for the decode-pipeline block
# (docs/DECODE_PIPELINE.md). Exported by runtime/server.py /metrics and,
# for parity testing, by tests/mock_server.py.
PIPELINE_METRIC_KEYS = {
    "kvmini_tpu_dispatch_depth": "pipeline_dispatch_depth",
    "kvmini_tpu_pipelined_sweeps_total": "pipeline_pipelined_sweeps",
    "kvmini_tpu_host_overlap_seconds_total": "pipeline_host_overlap_s",
    "kvmini_tpu_bubble_seconds_total": "pipeline_bubble_s",
}


def _mapped_counters(
    endpoint: Optional[str],
    key_map: dict[str, str],
    runtime_metrics: Optional[dict[str, float]] = None,
) -> dict[str, Any]:
    """Scrape-and-remap shared by the flat counter rails (decode
    pipeline, chunked prefill): runtime metric -> results.json key, with
    the absent-not-zero contract — an endpoint that doesn't expose a
    metric (external engines) yields NO key, never a fabricated zero.
    ``runtime_metrics``: pre-scraped dict (see collect_utilization)."""
    if not endpoint:
        return {}
    m = (runtime_metrics if runtime_metrics is not None
         else scrape_runtime_metrics(endpoint))
    return {
        out_key: m[metric]
        for metric, out_key in key_map.items()
        if metric in m
    }


def pipeline_counters(
    endpoint: Optional[str],
    runtime_metrics: Optional[dict[str, float]] = None,
) -> dict[str, Any]:
    """Decode-pipeline counters from the runtime's /metrics, keyed for
    results.json. Absence tells 'no pipeline' from 'pipeline never
    engaged' (_mapped_counters)."""
    return _mapped_counters(endpoint, PIPELINE_METRIC_KEYS,
                            runtime_metrics=runtime_metrics)


# runtime counter -> results.json key for the chunked-prefill rail
# (docs/TROUBLESHOOTING.md "Long prompts stall streaming"). Exported by
# runtime/server.py /metrics and, for parity testing, tests/mock_server.py.
PREFILL_METRIC_KEYS = {
    "kvmini_tpu_prefill_chunks_total": "prefill_chunks",
    "kvmini_tpu_prefill_chunk_stall_seconds_total": "prefill_chunk_stall_s",
}


def prefill_counters(
    endpoint: Optional[str],
    runtime_metrics: Optional[dict[str, float]] = None,
) -> dict[str, Any]:
    """Chunked-prefill counters from the runtime's /metrics, keyed for
    results.json (_mapped_counters: same absent-not-zero contract as
    pipeline_counters)."""
    return _mapped_counters(endpoint, PREFILL_METRIC_KEYS,
                            runtime_metrics=runtime_metrics)


# results.json `compile_stats` sub-key -> runtime metric (docs/
# PROFILING.md). Keyed by SUB-KEY (the inverse of PIPELINE_METRIC_KEYS'
# orientation) because the whole map lands under the one typed
# `compile_stats` results field rather than as flat schema fields.
COMPILE_METRIC_KEYS = {
    "compiles": "kvmini_tpu_compiles_total",
    "compile_wall_s": "kvmini_tpu_compile_seconds_total",
    "flops": "kvmini_tpu_compiled_flops_total",
    "bytes_accessed": "kvmini_tpu_compiled_bytes_total",
    "peak_bytes": "kvmini_tpu_compile_peak_bytes",
}


def compile_stats_block(
    endpoint: Optional[str],
    runtime_metrics: Optional[dict[str, float]] = None,
) -> dict[str, Any]:
    """Compile-stats counters from the runtime's /metrics, nested under
    the `compile_stats` results key (core/schema.py). Same degradation
    rule as pipeline_counters: an endpoint that doesn't export them (any
    external engine) yields NO block, never fabricated zeros. A runtime
    that exported them but compiled nothing (0 compiles) also yields no
    block — an all-zero compile report carries no information."""
    if not endpoint:
        return {}
    m = (runtime_metrics if runtime_metrics is not None
         else scrape_runtime_metrics(endpoint))
    block = {
        out_key: m[metric]
        for out_key, metric in COMPILE_METRIC_KEYS.items()
        if metric in m
    }
    if not block or not block.get("compiles"):
        return {}
    return {"compile_stats": block}


# results.json `kv_cache` sub-key -> runtime metric (docs/
# TROUBLESHOOTING.md "HBM pressure & KV thrash"). Keyed by SUB-KEY, the
# COMPILE_METRIC_KEYS orientation, because the whole map lands under the
# one typed `kv_cache` results field. The hbm_* entries are absent on
# backends whose devices report no memory_stats (CPU) — absence, not
# zeros, survives the mapping.
KV_METRIC_KEYS = {
    "hit_depth_p50": "kvmini_tpu_kv_prefix_hit_depth_p50",
    "hit_depth_p95": "kvmini_tpu_kv_prefix_hit_depth_p95",
    "bytes_per_token": "kvmini_tpu_kv_bytes_per_token",
    "reused_bytes": "kvmini_tpu_kv_reused_bytes_total",
    "blocks_allocated": "kvmini_tpu_kv_blocks_allocated_total",
    "retained_evictions": "kvmini_tpu_kv_retained_evictions_total",
    "share_reclaims": "kvmini_tpu_kv_share_reclaims_total",
    "prefix_hits": "kvmini_tpu_prefix_hits_total",
    "prefix_lookups": "kvmini_tpu_cache_lookups_total",
    "pool_blocks": "kvmini_tpu_kv_pool_blocks",
    "free_blocks": "kvmini_tpu_kv_free_blocks",
    "retained_blocks": "kvmini_tpu_kv_retained_blocks",
    "used_blocks": "kvmini_tpu_kv_used_blocks",
    "block_size": "kvmini_tpu_kv_block_size",
    "occupancy": "kvmini_tpu_kv_occupancy",
    "retained_fraction": "kvmini_tpu_kv_retained_fraction",
    "fragmentation": "kvmini_tpu_kv_fragmentation",
    "logical_bytes": "kvmini_tpu_kv_logical_bytes",
    "physical_bytes": "kvmini_tpu_kv_physical_bytes",
    "tier_demotions": "kvmini_tpu_kv_tier_demotions_total",
    "tier_promotions": "kvmini_tpu_kv_tier_promotions_total",
    "tier_hits": "kvmini_tpu_kv_tier_hits_total",
    "tier_blocks": "kvmini_tpu_kv_tier_blocks",
    "tier_bytes": "kvmini_tpu_kv_tier_bytes",
    "tier_capacity_bytes": "kvmini_tpu_kv_tier_capacity_bytes",
    "tier_disabled": "kvmini_tpu_kv_tier_disabled",
    "migrated_blocks": "kvmini_tpu_kv_migrated_blocks_total",
    "migrated_bytes": "kvmini_tpu_kv_migrated_bytes_total",
    "export_blocks": "kvmini_tpu_kv_export_blocks_total",
    "hbm_bytes_in_use": "kvmini_tpu_hbm_bytes_in_use",
    "hbm_peak_bytes": "kvmini_tpu_hbm_peak_bytes",
    "hbm_bytes_limit": "kvmini_tpu_hbm_bytes_limit",
    "headroom_estimate_bytes": "kvmini_tpu_hbm_headroom_estimate_bytes",
}


def kv_cache_block(
    endpoint: Optional[str],
    runtime_metrics: Optional[dict[str, float]] = None,
) -> dict[str, Any]:
    """KV-cache & HBM telemetry from the runtime's /metrics, nested under
    the `kv_cache` results key plus a top-level `headroom_error_pct` when
    both sides of the headroom-model validation are present. Degradation
    rules as ever: an endpoint that doesn't export the kv_* names (any
    external engine) yields NO block; a runtime that exported them but
    saw no cache activity, holds no paged pool, and reports no HBM also
    yields no block — an all-zero cache report carries no information."""
    if not endpoint:
        return {}
    m = (runtime_metrics if runtime_metrics is not None
         else scrape_runtime_metrics(endpoint))
    block: dict[str, Any] = {
        out_key: m[metric]
        for out_key, metric in KV_METRIC_KEYS.items()
        if metric in m
    }
    if "hit_depth_p50" not in block:
        return {}  # the runtime doesn't export the KV observability rail
    if (
        not block.get("prefix_lookups")
        and "pool_blocks" not in block
        and "hbm_bytes_in_use" not in block
    ):
        return {}
    block["source"] = "metrics:scrape"
    out: dict[str, Any] = {"kv_cache": block}
    from kserve_vllm_mini_tpu.profiling.headroom import headroom_error_pct

    err = headroom_error_pct(
        block.get("headroom_estimate_bytes"), block.get("hbm_peak_bytes")
    )
    if err is not None:
        out["headroom_error_pct"] = err
    return out


# results.json `resilience` sub-key -> runtime metric (docs/
# RESILIENCE.md). Keyed by SUB-KEY (the COMPILE/KV orientation) because
# the whole map lands under the one typed `resilience` results field.
RESILIENCE_METRIC_KEYS = {
    "requests_shed": "kvmini_tpu_requests_shed_total",
    "watchdog_trips": "kvmini_tpu_watchdog_trips_total",
    "engine_faults": "kvmini_tpu_engine_faults_total",
    "degrade_level": "kvmini_tpu_degrade_level",
    "faults_armed": "kvmini_tpu_faults_armed",
}


def resilience_block(
    endpoint: Optional[str],
    runtime_metrics: Optional[dict[str, float]] = None,
) -> dict[str, Any]:
    """Resilience counters (sheds, watchdog trips, recovered engine
    faults, degrade level, armed injection points) from the runtime's
    /metrics, nested under the `resilience` results key
    (docs/RESILIENCE.md). Degradation rules as ever: an endpoint that
    doesn't export the rail (any external engine) yields NO block, and a
    runtime with zero resilience activity yields no block either — an
    all-zero resilience report carries no information."""
    if not endpoint:
        return {}
    m = (runtime_metrics if runtime_metrics is not None
         else scrape_runtime_metrics(endpoint))
    block = {
        out_key: m[metric]
        for out_key, metric in RESILIENCE_METRIC_KEYS.items()
        if metric in m
    }
    if "requests_shed" not in block or not any(block.values()):
        return {}
    block["source"] = "metrics:scrape"
    return {"resilience": block}


# results.json `disagg` sub-key -> runtime metric (docs/
# DISAGGREGATION.md). Keyed by SUB-KEY (the COMPILE/KV/RESILIENCE
# orientation) because the whole map lands under the one typed `disagg`
# results field. Only disaggregated engines export the series at all.
DISAGG_METRIC_KEYS = {
    "handoffs": "kvmini_tpu_kv_handoffs_total",
    "handoff_blocks": "kvmini_tpu_kv_handoff_blocks_total",
    "handoff_wait_s": "kvmini_tpu_kv_handoff_wait_seconds_total",
    "handoff_drops": "kvmini_tpu_kv_handoff_drops_total",
    "handoff_bytes_copied": "kvmini_tpu_kv_handoff_bytes_copied_total",
    "lane_busy_s": "kvmini_tpu_prefill_lane_busy_seconds_total",
    "colocated_fallbacks": "kvmini_tpu_disagg_colocated_fallbacks_total",
    "queue_depth": "kvmini_tpu_kv_handoff_queue_depth",
    "degraded": "kvmini_tpu_disagg_degraded",
}


def disagg_block(
    endpoint: Optional[str],
    runtime_metrics: Optional[dict[str, float]] = None,
) -> dict[str, Any]:
    """Disaggregated-serving counters (prefill-lane handoffs, drops,
    lane busy wall, degrade state) from the runtime's /metrics, nested
    under the `disagg` results key (docs/DISAGGREGATION.md). Degradation
    rules as ever: a colocated engine (or any external one) doesn't
    export the rail and yields NO block, and a disaggregated engine with
    zero handoff activity yields no block either — an all-zero handoff
    report carries no information."""
    if not endpoint:
        return {}
    m = (runtime_metrics if runtime_metrics is not None
         else scrape_runtime_metrics(endpoint))
    block = {
        out_key: m[metric]
        for out_key, metric in DISAGG_METRIC_KEYS.items()
        if metric in m
    }
    if "handoffs" not in block or not any(block.values()):
        return {}
    block["source"] = "metrics:scrape"
    return {"disagg": block}


# results.json `fleet` sub-key -> router metric (docs/FLEET.md). Keyed
# by SUB-KEY (the COMPILE/KV/RESILIENCE/DISAGG orientation) because the
# whole map lands under the one typed `fleet` results field. Only the
# fleet router (fleet/router.py) exports the series.
FLEET_METRIC_KEYS = {
    "replicas_desired": "kvmini_tpu_fleet_replicas_desired",
    "replicas_live": "kvmini_tpu_fleet_replicas_live",
    "placements": "kvmini_tpu_fleet_placements_total",
    "reroutes": "kvmini_tpu_fleet_reroutes_total",
    "sheds": "kvmini_tpu_fleet_sheds_total",
    "stream_errors": "kvmini_tpu_fleet_stream_errors_total",
    "replica_restarts": "kvmini_tpu_fleet_replica_restarts_total",
    "scale_ups": "kvmini_tpu_fleet_scale_ups_total",
    "scale_downs": "kvmini_tpu_fleet_scale_downs_total",
    "last_cold_start_s": "kvmini_tpu_fleet_last_cold_start_seconds",
    # routing-latency rail (docs/TRACING.md "Fleet tracing"): cumulative
    # fleet.route span wall + audit-ring eviction count
    "route_seconds_total": "kvmini_tpu_fleet_route_seconds_total",
    "decisions_dropped": "kvmini_tpu_fleet_decisions_dropped_total",
}


def fleet_block(
    endpoint: Optional[str],
    runtime_metrics: Optional[dict[str, float]] = None,
) -> dict[str, Any]:
    """Fleet-router counters (replica counts, placements — the labeled
    reasons arrive summed — reroutes, fleet sheds, restarts, scale
    steps, last cold start) from the router's aggregated /metrics,
    nested under the `fleet` results key (docs/FLEET.md). Degradation
    rules as ever: a single-server endpoint (or any external engine)
    doesn't export the rail and yields NO block, and a router that never
    placed anything and holds no replicas yields no block either."""
    if not endpoint:
        return {}
    m = (runtime_metrics if runtime_metrics is not None
         else scrape_runtime_metrics(endpoint))
    block = {
        out_key: m[metric]
        for out_key, metric in FLEET_METRIC_KEYS.items()
        if metric in m
    }
    if "replicas_live" not in block:
        return {}
    if not block.get("replicas_live") and not block.get("placements"):
        return {}
    block["source"] = "metrics:scrape"
    return {"fleet": block}


# results.json `economics` sub-key -> runtime/router metric (docs/
# ECONOMICS.md). Keyed by SUB-KEY (the COMPILE/KV/RESILIENCE/DISAGG/
# FLEET orientation) because the whole map lands under the one typed
# `economics` results field. Single engines export the first four;
# `marginal_replica_usd_per_1k_tokens` only exists on a fleet router's
# aggregated /metrics (fleet/router.py).
ECON_METRIC_KEYS = {
    "usd_per_1k_tokens": "kvmini_tpu_econ_usd_per_1k_tokens",
    "wh_per_1k_tokens": "kvmini_tpu_econ_wh_per_1k_tokens",
    "usd_per_hour": "kvmini_tpu_econ_usd_per_hour",
    "tokens_per_sec": "kvmini_tpu_econ_tokens_per_sec",
    "marginal_replica_usd_per_1k_tokens":
        "kvmini_tpu_econ_marginal_replica_usd_per_1k_tokens",
}


def economics_block(
    endpoint: Optional[str],
    runtime_metrics: Optional[dict[str, float]] = None,
) -> dict[str, Any]:
    """Live-economics gauges ($/1K-tok, Wh/1K-tok, $/hr accrual, window
    token rate, fleet marginal-replica attribution) from the runtime's or
    router's /metrics, nested under the `economics` results key (docs/
    ECONOMICS.md). Degradation rules as ever: a CPU backend (or any
    external engine) doesn't export the rail and yields NO block —
    absent, never a fabricated $0 — and the gate is the $/hr accrual
    gauge because it is the one rail member that is non-zero whenever
    the rail exists at all (rates can legitimately be missing while the
    window warms up)."""
    if not endpoint:
        return {}
    m = (runtime_metrics if runtime_metrics is not None
         else scrape_runtime_metrics(endpoint))
    block = {
        out_key: m[metric]
        for out_key, metric in ECON_METRIC_KEYS.items()
        if metric in m
    }
    if "usd_per_hour" not in block or not block.get("usd_per_hour"):
        return {}
    block["source"] = "metrics:scrape"
    return {"economics": block}


def cache_hit_ratio(
    prom_url: Optional[str],
    endpoint: Optional[str],
    runtime_metrics: Optional[dict[str, float]] = None,
) -> dict[str, Any]:
    """Cache-hit chain: Prometheus counters -> runtime metrics -> absent
    (the TTFT-inference probe fills this when nothing else can,
    probes/cache_probe.py). ``runtime_metrics``: pre-scraped dict (see
    collect_utilization)."""
    if prom_url:
        v, _ = query_with_fallbacks(prom_url, CACHE_HIT_QUERIES)
        if v is not None:
            return {"cache_hit_ratio": v, "cache_hit_source": "metrics"}
    if endpoint:
        m = (runtime_metrics if runtime_metrics is not None
             else scrape_runtime_metrics(endpoint))
        hits, total = m.get("kvmini_tpu_cache_hits_total"), m.get("kvmini_tpu_cache_lookups_total")
        if hits is not None and total:
            return {"cache_hit_ratio": hits / total, "cache_hit_source": "metrics"}
    return {}
