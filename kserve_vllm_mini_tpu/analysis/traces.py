"""Join the server's /traces spans with the loadgen's client traces.

The loadgen exports client-leg spans (``client.request`` ->
``http.request``) to ``runs/<id>/traces/traces.json`` and propagates W3C
``traceparent`` headers; the runtime records the server leg
(``server.queue`` / ``server.prefill`` / ``server.decode``, runtime/
tracing.py) and serves it at ``GET /traces`` in the same OTLP/JSON shape.
This module fetches the server document, estimates the client<->server
clock offset, merges the two legs into one traces.json joined by
trace_id, and summarizes the server phases into the ``phase_breakdown``
results.json block (docs/TRACING.md).

Clock-offset method: for every trace present in both legs, the client's
``http.request`` span necessarily STARTS BEFORE the server's
``server.queue`` span on a common clock (the request must travel before
the server can queue it). ``delta = server.queue.start -
http.request.start`` therefore equals the clock offset plus one-way
network+parse delay; the MINIMUM delta across requests is the tightest
upper bound on the offset (the request with the fastest delivery). We
report that minimum as the estimate — biased high by the fastest one-way
delay, which on the deployments this targets (same host or same rack) is
microseconds against millisecond-scale phases.
"""

from __future__ import annotations

import json
import urllib.request
from typing import Any, Optional

from kserve_vllm_mini_tpu.runtime.tracing import (
    ROUTER_SCOPE,
    SERVER_SCOPE,
    spans_from_otlp,
)

SERVER_PHASE_SPANS = ("server.queue", "server.handoff", "server.prefill",
                      "server.decode")

# router-lane phase spans (fleet/router.py): the placement+proxy window
# and each per-attempt upstream call — phase keys "route" and "proxy"
FLEET_PHASE_SPANS = ("fleet.route", "fleet.proxy")

PHASE_SPANS = SERVER_PHASE_SPANS + FLEET_PHASE_SPANS

# scopes the analyzer merges in (and must strip back out on re-analyze):
# the server leg and the fleet-router leg each export under their own
# scope so each lane replaces independently
_MERGED_SCOPES = frozenset({SERVER_SCOPE, ROUTER_SCOPE})


def _is_server_leg(rs: dict[str, Any]) -> bool:
    """A resourceSpans entry previously merged from a /traces export —
    identified by the scope names the server and router legs stamp."""
    return any(
        (ss.get("scope") or {}).get("name") in _MERGED_SCOPES
        for ss in rs.get("scopeSpans", []) or []
    )


def strip_server_leg(doc: dict[str, Any]) -> dict[str, Any]:
    """The client-only view of a (possibly already merged) traces doc.
    Re-running analyze on an existing run dir reads back the MERGED doc;
    without this strip each re-run would append duplicate server/router
    blocks (and the offset estimates would key off stale spans)."""
    return {
        **doc,
        "resourceSpans": [
            rs for rs in doc.get("resourceSpans", []) or []
            if not _is_server_leg(rs)
        ],
    }


def fetch_server_traces(endpoint: str, timeout_s: float = 5.0) -> dict[str, Any]:
    """GET <endpoint>/traces -> OTLP doc, or {} when the endpoint doesn't
    serve it (external engines) / is unreachable — absence degrades the
    merge, never fails the analyze stage."""
    url = endpoint.rstrip("/") + "/traces"
    try:
        with urllib.request.urlopen(url, timeout=timeout_s) as resp:
            doc = json.loads(resp.read())
    except Exception:
        return {}
    return doc if isinstance(doc, dict) else {}


def _span_ns(span: dict[str, Any]) -> tuple[int, int]:
    try:
        return (int(span.get("startTimeUnixNano", 0)),
                int(span.get("endTimeUnixNano", 0)))
    except (TypeError, ValueError):
        return (0, 0)


def estimate_clock_offset_ns(
    client_doc: dict[str, Any], server_doc: dict[str, Any],
    span_name: str = "server.queue",
) -> Optional[int]:
    """min over joined traces of (<span_name>.start - http.request.start);
    None when no trace appears in both legs. See the module docstring for
    why min is the right statistic. ``span_name`` is the other leg's
    first-touch span: ``server.queue`` for a replica, ``fleet.route``
    for the router lane."""
    client_http: dict[str, int] = {}
    for _svc, s in spans_from_otlp(client_doc):
        if s.get("name") == "http.request":
            client_http[s.get("traceId", "")] = _span_ns(s)[0]
    deltas = [
        _span_ns(s)[0] - client_http[s["traceId"]]
        for _svc, s in spans_from_otlp(server_doc)
        if s.get("name") == span_name and s.get("traceId") in client_http
    ]
    return min(deltas) if deltas else None


def merge_server_traces(
    client_doc: dict[str, Any], server_doc: dict[str, Any]
) -> tuple[dict[str, Any], list[dict[str, Any]]]:
    """(merged OTLP doc, matched server spans).

    Server spans joining a client trace merge as an extra resourceSpans
    entry; engine-lane spans (``engine.*`` — dispatch->retire windows)
    ride along when they overlap the run's time window, so the report can
    show device-occupancy context beside the per-request lanes. Spans of
    OTHER runs still sitting in the server's ring buffer are dropped.
    The clock-offset estimate lands doc-level as
    ``clockOffsetNanosEstimate`` (server clock minus client clock).

    IDEMPOTENT: a previously merged server leg in ``client_doc`` (analyze
    re-run on the same run dir) is stripped and replaced, never
    duplicated."""
    client_doc = strip_server_leg(client_doc)
    client_ids = {
        s.get("traceId") for _svc, s in spans_from_otlp(client_doc)
    }
    run_bounds = [
        ns
        for _svc, s in spans_from_otlp(client_doc)
        for ns in _span_ns(s)
        if ns > 0
    ]
    offset = estimate_clock_offset_ns(client_doc, server_doc)
    t0 = min(run_bounds) + (offset or 0) if run_bounds else 0
    t1 = max(run_bounds) + (offset or 0) if run_bounds else 0

    matched: list[dict[str, Any]] = []
    server_resource: Optional[dict[str, Any]] = None
    for rs in server_doc.get("resourceSpans", []) or []:
        server_resource = rs.get("resource")
        break
    for _svc, s in spans_from_otlp(server_doc):
        if s.get("traceId") in client_ids:
            matched.append(s)
        elif str(s.get("name", "")).startswith("engine.") and run_bounds:
            start, end = _span_ns(s)
            if end >= t0 and start <= t1:  # overlaps the run window
                matched.append(s)

    merged = dict(client_doc)
    merged["resourceSpans"] = list(client_doc.get("resourceSpans", []) or [])
    if matched:
        merged["resourceSpans"].append(
            {
                "resource": server_resource
                or {
                    "attributes": [
                        {"key": "service.name",
                         "value": {"stringValue": "kvmini-tpu-runtime"}}
                    ]
                },
                "scopeSpans": [
                    {
                        "scope": {"name": SERVER_SCOPE},
                        "spans": matched,
                    }
                ],
            }
        )
    if offset is not None:
        merged["clockOffsetNanosEstimate"] = offset
    return merged, matched


def fetch_fleet_replicas(
    endpoint: str, timeout_s: float = 5.0
) -> list[tuple[str, str]]:
    """GET <endpoint>/fleet -> [(rid, url), ...], or [] when the endpoint
    is not a fleet router (single engines, external stacks) — absence
    degrades the stitch to the single-server merge, never fails it."""
    url = endpoint.rstrip("/") + "/fleet"
    try:
        with urllib.request.urlopen(url, timeout=timeout_s) as resp:
            doc = json.loads(resp.read())
    except Exception:
        return []
    if not isinstance(doc, dict):
        return []
    out: list[tuple[str, str]] = []
    for r in doc.get("replicas") or []:
        if isinstance(r, dict) and r.get("rid") and r.get("url"):
            out.append((str(r["rid"]), str(r["url"])))
    return out


def fetch_fleet_decisions(
    endpoint: str, timeout_s: float = 5.0
) -> list[dict[str, Any]]:
    """GET <endpoint>/fleet/decisions -> the routing audit entries, or []
    off a non-router endpoint — same degrade rule as every fetch here."""
    url = endpoint.rstrip("/") + "/fleet/decisions"
    try:
        with urllib.request.urlopen(url, timeout=timeout_s) as resp:
            doc = json.loads(resp.read())
    except Exception:
        return []
    if not isinstance(doc, dict):
        return []
    return [d for d in doc.get("decisions") or [] if isinstance(d, dict)]


def outlier_attribution(
    records: list[Any], decisions: list[dict[str, Any]]
) -> dict[str, Any]:
    """Join the p99-latency request to its routing decision(s) by
    trace_id: "why was the worst request slow" answered from the audit
    ring — which replica won, what every candidate scored, and how many
    times the request was re-placed. {} when the join is empty (no
    trace ids, no matching audit entries, ring already evicted them)."""
    ok = [r for r in records if r.ok and r.trace_id]
    if not ok or not decisions:
        return {}
    by_latency = sorted(ok, key=lambda r: r.latency_ms)
    outlier = by_latency[min(int(0.99 * len(by_latency)),
                             len(by_latency) - 1)]
    mine = [d for d in decisions
            if d.get("type") == "placement"
            and d.get("trace_id") == outlier.trace_id]
    if not mine:
        return {}
    return {
        "trace_id": outlier.trace_id,
        "latency_ms": outlier.latency_ms,
        "placements": len(mine),
        "decisions": mine,
    }


def _lane_entry(service: str, scope: str,
                spans: list[dict[str, Any]]) -> dict[str, Any]:
    return {
        "resource": {
            "attributes": [
                {"key": "service.name", "value": {"stringValue": service}}
            ]
        },
        "scopeSpans": [{"scope": {"name": scope}, "spans": spans}],
    }


def merge_fleet_traces(
    client_doc: dict[str, Any],
    router_doc: dict[str, Any],
    replica_docs: dict[str, dict[str, Any]],
) -> tuple[dict[str, Any], list[dict[str, Any]]]:
    """Three-lane stitch: (merged OTLP doc, matched router+server spans).

    Lanes: client (loadgen), router (``fleet.route``/``fleet.proxy``
    under ``ROUTER_SCOPE``), and one server lane PER replica — each
    replica gets its OWN clock-offset estimate against the client clock
    (``clockOffsetsNanosByReplica``), because the single min-offset
    assumption of ``merge_server_traces`` is wrong the moment two
    replicas' clocks disagree. Every merged server span is stamped with
    a ``replica`` attribute so the report can shift each span by its own
    replica's offset. The router's own offset lands as
    ``clockOffsetNanosRouter``; the legacy ``clockOffsetNanosEstimate``
    is kept as the min over replicas so single-lane consumers keep
    working. IDEMPOTENT like the single-server merge: previously merged
    server AND router legs are stripped and replaced."""
    client_doc = strip_server_leg(client_doc)
    client_ids = {
        s.get("traceId") for _svc, s in spans_from_otlp(client_doc)
    }
    matched: list[dict[str, Any]] = []
    entries: list[dict[str, Any]] = []

    router_spans = [
        s for _svc, s in spans_from_otlp(router_doc)
        if s.get("traceId") in client_ids
    ]
    router_offset = estimate_clock_offset_ns(
        client_doc, router_doc, span_name="fleet.route"
    )
    if router_spans:
        entries.append(
            _lane_entry("kvmini-tpu-router", ROUTER_SCOPE, router_spans)
        )
        matched += router_spans

    offsets: dict[str, int] = {}
    for rid in sorted(replica_docs):
        doc = replica_docs[rid] or {}
        spans: list[dict[str, Any]] = []
        for _svc, s in spans_from_otlp(doc):
            if s.get("traceId") in client_ids:
                spans.append({
                    **s,
                    "attributes": list(s.get("attributes") or []) + [
                        {"key": "replica",
                         "value": {"stringValue": rid}}
                    ],
                })
        if not spans:
            continue
        off = estimate_clock_offset_ns(client_doc, doc)
        if off is not None:
            offsets[rid] = off
        entries.append(
            _lane_entry(f"kvmini-tpu-runtime/{rid}", SERVER_SCOPE, spans)
        )
        matched += spans

    merged = dict(client_doc)
    merged["resourceSpans"] = (
        list(client_doc.get("resourceSpans", []) or []) + entries
    )
    if offsets:
        merged["clockOffsetsNanosByReplica"] = offsets
        merged["clockOffsetNanosEstimate"] = min(offsets.values())
    if router_offset is not None:
        merged["clockOffsetNanosRouter"] = router_offset
    return merged, matched


def phase_breakdown(
    server_spans: list[dict[str, Any]],
    clock_offset_ns: Optional[int] = None,
    source: str = "server:/traces",
) -> dict[str, Any]:
    """Phase spans -> the results.json ``phase_breakdown`` block:
    per-phase duration percentiles so the next perf PR knows whether
    latency is queueing, prefill, decode — or, through a fleet router,
    routing (``route``) and per-attempt proxying (``proxy``). {} when no
    phase spans. Durations are same-clock intra-span deltas, so no
    clock-offset correction applies to them; the offset rides along as
    ``clock_offset_ms_est`` context only."""
    by_phase: dict[str, list[float]] = {}
    for s in server_spans:
        name = s.get("name", "")
        if name not in PHASE_SPANS:
            continue
        start, end = _span_ns(s)
        if end < start:
            continue
        by_phase.setdefault(name.split(".", 1)[1], []).append(
            (end - start) / 1e6
        )
    if not by_phase:
        return {}

    def _pct(vals: list[float], q: float) -> float:
        vs = sorted(vals)
        return vs[min(int(q * len(vs)), len(vs) - 1)]

    out: dict[str, Any] = {
        phase: {
            "count": len(vals),
            "mean_ms": sum(vals) / len(vals),
            "p50_ms": _pct(vals, 0.50),
            "p95_ms": _pct(vals, 0.95),
            "max_ms": max(vals),
        }
        for phase, vals in sorted(by_phase.items())
    }
    if clock_offset_ns is not None:
        out["clock_offset_ms_est"] = clock_offset_ns / 1e6
    out["source"] = source
    return out
