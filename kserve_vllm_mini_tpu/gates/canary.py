"""Canary comparison: candidate run vs baseline run, regression-flagged.

Reference behavior (/root/reference/tools/canary_compare.py:19-134): a
metric/direction/threshold table drives relative-delta checks; improvements
always pass; regressions beyond threshold fail; exit 2 on any regression.
Inputs are run dirs (or bare results.json files); JSON + HTML outputs.
"""

from __future__ import annotations

import argparse
import json
from dataclasses import dataclass
from pathlib import Path
from typing import Any, Optional

# metric -> (direction, relative threshold). "lower": candidate should not be
# more than threshold above baseline; "higher": not more than threshold below.
CANARY_METRICS: dict[str, tuple[str, float]] = {
    "p95_ms": ("lower", 0.10),
    "p99_ms": ("lower", 0.10),
    "ttft_p95_ms": ("lower", 0.10),
    "error_rate": ("lower", 0.01),          # absolute for rates near zero
    "throughput_rps": ("higher", 0.10),
    "tokens_per_sec": ("higher", 0.10),
    "cost_per_1k_tokens": ("lower", 0.10),
    "energy_wh_per_1k_tokens": ("lower", 0.10),
    "cache_hit_ratio": ("higher", 0.10),
    "quality_score": ("higher", 0.02),
}


@dataclass
class Delta:
    metric: str
    baseline: Optional[float]
    candidate: Optional[float]
    rel_delta: Optional[float]
    verdict: str  # "pass" | "regression" | "skipped"
    note: str = ""


def _load_results(path: str | Path) -> dict[str, Any]:
    p = Path(path)
    if p.is_dir():
        p = p / "results.json"
    with p.open() as f:
        return json.load(f)


def compare(
    baseline: dict[str, Any],
    candidate: dict[str, Any],
    metrics: Optional[dict[str, tuple[str, float]]] = None,
) -> list[Delta]:
    metrics = metrics or CANARY_METRICS
    out: list[Delta] = []
    for metric, (direction, threshold) in metrics.items():
        b, c = baseline.get(metric), candidate.get(metric)
        if b is None or c is None:
            out.append(Delta(metric, b, c, None, "skipped", "missing in one side"))
            continue
        b, c = float(b), float(c)
        if metric == "error_rate":
            # near-zero rates: absolute delta, not relative
            delta = c - b
            bad = delta > threshold
            rel = delta
        else:
            if b == 0.0:
                out.append(Delta(metric, b, c, None, "skipped", "baseline is zero"))
                continue
            rel = (c - b) / abs(b)
            bad = rel > threshold if direction == "lower" else rel < -threshold
        out.append(
            Delta(metric, b, c, rel, "regression" if bad else "pass")
        )
    return out


def summarize(deltas: list[Delta]) -> dict[str, Any]:
    return {
        "regressions": [d.metric for d in deltas if d.verdict == "regression"],
        "passes": [d.metric for d in deltas if d.verdict == "pass"],
        "skipped": [d.metric for d in deltas if d.verdict == "skipped"],
        "deltas": [d.__dict__ for d in deltas],
    }


def html_report(deltas: list[Delta]) -> str:
    rows = []
    for d in deltas:
        color = {"pass": "#0a7f3f", "regression": "#c22", "skipped": "#888"}[d.verdict]
        rel = f"{d.rel_delta:+.1%}" if d.rel_delta is not None else "—"
        rows.append(
            f"<tr><td>{d.metric}</td><td>{d.baseline}</td><td>{d.candidate}</td>"
            f"<td>{rel}</td><td style='color:{color};font-weight:bold'>"
            f"{d.verdict}{(' (' + d.note + ')') if d.note else ''}</td></tr>"
        )
    return (
        "<html><head><title>Canary comparison</title></head><body>"
        "<h1>Canary: candidate vs baseline</h1>"
        "<table border=1 cellpadding=6 style='border-collapse:collapse'>"
        "<tr><th>metric</th><th>baseline</th><th>candidate</th>"
        "<th>delta</th><th>verdict</th></tr>"
        + "".join(rows)
        + "</table></body></html>"
    )


# -- CLI ---------------------------------------------------------------------

def register(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("--baseline", required=True, help="Baseline run dir or results.json")
    parser.add_argument("--candidate", required=True, help="Candidate run dir or results.json")
    parser.add_argument("--json-out", default=None)
    parser.add_argument("--html-out", default=None)


def run(args: argparse.Namespace) -> int:
    deltas = compare(_load_results(args.baseline), _load_results(args.candidate))
    summary = summarize(deltas)
    for d in deltas:
        rel = f"{d.rel_delta:+.1%}" if d.rel_delta is not None else "    —"
        print(f"{d.metric:<28} {rel:>8}  {d.verdict}")
    if args.json_out:
        Path(args.json_out).write_text(json.dumps(summary, indent=2))
    if args.html_out:
        Path(args.html_out).write_text(html_report(deltas))
    if summary["regressions"]:
        print(f"canary: REGRESSION in {', '.join(summary['regressions'])}")
        return 2
    print(f"canary: no regressions ({len(summary['passes'])} metrics compared)")
    return 0
