"""SLO gate: CI-friendly pass/fail of results.json against budgets.

Reference behavior (/root/reference/tools/gate.py:26-153): each budget key
checks one results key against a threshold; missing metrics FAIL (absence of
data must not pass a gate — see analysis/metrics.py on NaN); prints a table;
exit 3 on any violation. Budget file is slo.json.
"""

from __future__ import annotations

import argparse
import json
from dataclasses import dataclass
from pathlib import Path
from typing import Any, Optional

DEFAULT_SLO_PATH = Path(__file__).resolve().parents[2] / "slo.json"

# budget key -> (results key, direction). "max": value must be <= budget.
BUDGET_RULES: dict[str, tuple[str, str]] = {
    "p95_ms_max": ("p95_ms", "max"),
    "p99_ms_max": ("p99_ms", "max"),
    "ttft_p95_ms_max": ("ttft_p95_ms", "max"),
    "error_rate_max": ("error_rate", "max"),
    "cost_per_1k_tokens_max": ("cost_per_1k_tokens", "max"),
    "cold_multiplier_max": ("cold_multiplier", "max"),
    "energy_wh_per_1k_tokens_max": ("energy_wh_per_1k_tokens", "max"),
    "throughput_rps_min": ("throughput_rps", "min"),
    "tokens_per_sec_min": ("tokens_per_sec", "min"),
    "cache_hit_ratio_min": ("cache_hit_ratio", "min"),
    # fairness budgets (reference gate.py:97-128), fed by compare/fairness.py
    "fairness_p95_ratio_max": ("fairness_p95_ratio", "max"),
    "fairness_throughput_share_min": ("fairness_throughput_share_min_tenant", "min"),
}


@dataclass
class Verdict:
    budget_key: str
    metric: str
    budget: float
    value: Optional[float]
    ok: bool
    note: str = ""


def load_slo(path: str | Path | None = None) -> dict[str, float]:
    p = Path(path) if path else DEFAULT_SLO_PATH
    with p.open() as f:
        return {k: float(v) for k, v in json.load(f).items()}


def gate_results(results: dict[str, Any], budgets: dict[str, float]) -> list[Verdict]:
    verdicts: list[Verdict] = []
    for key, budget in budgets.items():
        rule = BUDGET_RULES.get(key)
        if rule is None:
            verdicts.append(
                Verdict(key, "?", budget, None, False, "unknown budget key")
            )
            continue
        metric, direction = rule
        value = results.get(metric)
        if value is None:
            verdicts.append(
                Verdict(key, metric, budget, None, False, "metric missing from results")
            )
            continue
        value = float(value)
        ok = value <= budget if direction == "max" else value >= budget
        verdicts.append(Verdict(key, metric, budget, value, ok))
    return verdicts


def print_table(verdicts: list[Verdict]) -> None:
    print(f"{'budget':<32} {'metric':<28} {'limit':>12} {'value':>12}  verdict")
    for v in verdicts:
        val = f"{v.value:.4f}" if v.value is not None else "—"
        status = "PASS" if v.ok else f"FAIL{' (' + v.note + ')' if v.note else ''}"
        print(f"{v.budget_key:<32} {v.metric:<28} {v.budget:>12.4f} {val:>12}  {status}")


# -- CLI ---------------------------------------------------------------------

def register(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("--results", required=True, help="results.json path")
    parser.add_argument("--slo", default=None, help="Budgets JSON (default: repo slo.json)")
    parser.add_argument("--energy", default=None, help="Optional energy.json to fold in")
    parser.add_argument("--fairness", default=None,
                        help="Optional fairness_summary.json to fold in")


def run(args: argparse.Namespace) -> int:
    with open(args.results) as f:
        results = json.load(f)
    for extra in (args.energy, args.fairness):
        if extra:
            with open(extra) as f:
                results.update(json.load(f))
    verdicts = gate_results(results, load_slo(args.slo))
    print_table(verdicts)
    failed = [v for v in verdicts if not v.ok]
    if failed:
        print(f"gate: FAILED {len(failed)}/{len(verdicts)} budget(s)")
        return 3
    print(f"gate: PASSED all {len(verdicts)} budget(s)")
    return 0
