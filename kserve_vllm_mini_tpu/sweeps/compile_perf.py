"""AOT compile-time vs serving-performance tradeoff sweep.

The reference's analog is scripts/trtllm_build_vs_perf.py: time the TRT-LLM
engine *build*, then benchmark the built engine, and emit a CSV of
build-time vs p95/RPS tradeoffs (:124-308). On TPU the "engine build" is
XLA compilation — the cost moves from an offline builder container to
`jax.jit` tracing + compilation, paid per (shape-bucket, config). This sweep
makes that cost visible: for each config it AOT-compiles the runtime's
prefill and decode steps (`.lower().compile()`), records wall-clock compile
time, then measures steady-state decode throughput of the compiled step —
so operators can weigh e.g. more prefill buckets (lower padding waste,
more compiles) against fewer (slower prefill, faster boot), or int8 vs
bf16 (compile cost vs tokens/sec).
"""

from __future__ import annotations

import argparse
import time
from dataclasses import dataclass
from functools import partial
from pathlib import Path
from typing import Any

CSV_COLUMNS = [
    "model",
    "slots",
    "max_seq",
    "prefill_bucket",
    "quantization",
    "compile_prefill_s",
    "compile_decode_s",
    "compile_total_s",
    "decode_tokens_per_sec",
    "params_mib",
    "status",
    "error",
]


@dataclass
class CompileConfig:
    model: str = "llama-tiny"
    slots: int = 8
    max_seq: int = 512
    prefill_bucket: int = 128
    quantization: str = "none"   # none | int8


def measure_config(cc: CompileConfig, decode_steps: int = 32) -> dict[str, Any]:
    """AOT-compile prefill + decode for one config; measure compile seconds
    and post-compile decode throughput."""
    import jax
    import jax.numpy as jnp

    from kserve_vllm_mini_tpu.models.config import get_config
    from kserve_vllm_mini_tpu.models.llama import forward, init_kv_cache, init_params

    cfg = get_config(cc.model, max_seq_len=cc.max_seq)
    params = init_params(jax.random.PRNGKey(0), cfg)
    if cc.quantization == "int8":
        from kserve_vllm_mini_tpu.ops.quant import quantize_params

        params = quantize_params(params)
    params_bytes = sum(
        x.size * x.dtype.itemsize for x in jax.tree_util.tree_leaves(params)
        if hasattr(x, "dtype")
    )

    S, B = cc.slots, cc.prefill_bucket
    cache = init_kv_cache(cfg, S, max_seq=cc.max_seq)
    toks = jnp.zeros((S, B), dtype=jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(B, dtype=jnp.int32), (S, B))
    lengths = jnp.full((S,), B, dtype=jnp.int32)

    @partial(jax.jit, donate_argnums=(1,))
    def prefill(params, cache, toks, pos):
        logits, cache = forward(params, cfg, toks, pos, cache,
                                jnp.zeros((S,), jnp.int32))
        return cache, jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)

    def make_decode_n(n_steps: int):
        """N greedy decode steps fused into ONE dispatch via lax.fori_loop —
        the timing unit. Per-dispatch timing is hopeless under the remote-TPU
        relay (RTT ≫ step time for small models); a fused loop puts all the
        work behind a single dispatch + readback."""

        @partial(jax.jit, donate_argnums=(1,))
        def decode_n(params, cache, tokens, lengths):
            def body(_, carry):
                cache, tokens, lengths = carry
                lengths = lengths + 1
                logits, cache = forward(params, cfg, tokens[:, None],
                                        lengths[:, None], cache, lengths)
                nxt = jnp.argmax(logits[:, 0, :], axis=-1).astype(jnp.int32)
                return cache, nxt, lengths

            return jax.lax.fori_loop(0, n_steps, body, (cache, tokens, lengths))

        return decode_n

    t0 = time.time()
    prefill_exe = prefill.lower(params, cache, toks, pos).compile()
    compile_prefill_s = time.time() - t0

    tokens0 = jnp.zeros((S,), dtype=jnp.int32)
    t0 = time.time()
    decode_n1 = make_decode_n(decode_steps).lower(
        params, cache, tokens0, lengths).compile()
    decode_n2 = make_decode_n(2 * decode_steps).lower(
        params, cache, tokens0, lengths).compile()
    compile_decode_s = time.time() - t0

    # Timing (same rationale as bench.py): each fused run ends in a host
    # readback — the only reliable completion barrier over the relay — and
    # differencing the N-step and 2N-step runs cancels RTT + dispatch cost.
    import numpy as np

    cache, tokens = prefill_exe(params, cache, toks, pos)
    _ = np.asarray(tokens)  # warm the readback path
    t0 = time.time()
    cache, tokens, lengths = decode_n1(params, cache, tokens, lengths)
    _ = np.asarray(tokens)
    d1 = time.time() - t0
    t0 = time.time()
    cache, tokens, lengths = decode_n2(params, cache, tokens, lengths)
    _ = np.asarray(tokens)
    d2 = time.time() - t0
    if d2 > d1:
        step_s = (d2 - d1) / decode_steps
    else:
        # RTT jitter swamped the difference; fall back to the 2N run as an
        # upper bound on per-step time (reported tok/s is then a lower bound)
        step_s = d2 / (2 * decode_steps)
    tok_per_s = S / step_s

    return {
        "model": cc.model,
        "slots": S,
        "max_seq": cc.max_seq,
        "prefill_bucket": B,
        "quantization": cc.quantization,
        "compile_prefill_s": round(compile_prefill_s, 3),
        "compile_decode_s": round(compile_decode_s, 3),
        "compile_total_s": round(compile_prefill_s + compile_decode_s, 3),
        "decode_tokens_per_sec": round(tok_per_s, 1),
        "params_mib": round(params_bytes / 2**20, 1),
    }


def run_compile_sweep(
    configs: list[CompileConfig], csv_path: Path, decode_steps: int = 32
) -> list[dict[str, Any]]:
    from kserve_vllm_mini_tpu.sweeps.base import write_row

    csv_path.unlink(missing_ok=True)
    rows = []
    for cc in configs:
        row: dict[str, Any]
        try:
            row = measure_config(cc, decode_steps=decode_steps)
            row["status"], row["error"] = "ok", ""
        except Exception as e:  # noqa: BLE001 — record-and-continue
            row = {
                "model": cc.model, "slots": cc.slots, "max_seq": cc.max_seq,
                "prefill_bucket": cc.prefill_bucket, "quantization": cc.quantization,
                "status": "failed", "error": f"{type(e).__name__}: {e}",
            }
        rows.append(row)
        write_row(csv_path, row, CSV_COLUMNS)
    return rows


# -- CLI ---------------------------------------------------------------------

def register(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("--model", default="llama-tiny")
    parser.add_argument("--slots", default="4,8", help="Comma list")
    parser.add_argument("--buckets", default="64,128", help="Prefill buckets, comma list")
    parser.add_argument("--max-seq", type=int, default=512)
    parser.add_argument("--quantization", default="none,int8", help="Comma list")
    parser.add_argument("--decode-steps", type=int, default=32)
    parser.add_argument("--output", default="compile_sweep.csv")


def run(args: argparse.Namespace) -> int:
    configs = [
        CompileConfig(model=args.model, slots=int(s), max_seq=args.max_seq,
                      prefill_bucket=int(b), quantization=q)
        for s in args.slots.split(",")
        for b in args.buckets.split(",")
        for q in args.quantization.split(",")
    ]
    rows = run_compile_sweep(configs, Path(args.output), decode_steps=args.decode_steps)
    ok = [r for r in rows if r["status"] == "ok"]
    for r in ok:
        print(
            f"{r['model']} slots={r['slots']} bucket={r['prefill_bucket']} "
            f"quant={r['quantization']}: compile {r['compile_total_s']:.1f}s, "
            f"decode {r['decode_tokens_per_sec']:.0f} tok/s"
        )
    print(f"compile-sweep: {len(ok)}/{len(rows)} ok -> {args.output}")
    return 0 if ok else 1
