"""Experiment sweeps (reference L6: grid-sweep.sh, sweeps/*).

All four sweeps share one loop shape — ``for cfg in space: bench -> append
CSV row -> continue on failure`` (reference grid-sweep.sh:103-174,
autoscale-sweep.sh:196-333, mig-sweep.sh:163-193,
quantization_sweep.py:321-341) — factored into sweeps.base here instead of
four copies. The CSV is flushed after every configuration so an interrupted
sweep is resumable (reference quantization_sweep.py:343-349 pattern).
"""
