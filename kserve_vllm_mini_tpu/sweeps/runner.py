"""``kvmini-tpu sweep {grid,autoscale,topology,quantization}`` CLI."""

from __future__ import annotations

import argparse
from pathlib import Path
from typing import Any

import yaml


def register(parser: argparse.ArgumentParser) -> None:
    sub = parser.add_subparsers(dest="kind", required=True)

    common = argparse.ArgumentParser(add_help=False)
    common.add_argument("--profile", default=None, help="Base profile YAML")
    common.add_argument("--out-dir", default="runs/sweep", help="CSV/summary output dir")
    common.add_argument("--model", default=None)
    common.add_argument("--requests", type=int, default=None)
    common.add_argument("--concurrency", type=int, default=None)
    common.add_argument("--abort-slo", default=None,
                        help="Budgets JSON for the live monitor; cells "
                             "whose rolling burn-rate stays over budget "
                             "abort early and record aborted_early "
                             "(docs/MONITORING.md)")
    common.add_argument("--no-monitor", action="store_true",
                        help="Disable the per-cell live monitor/timeline")

    g = sub.add_parser("grid", parents=[common],
                       help="concurrency x max_tokens x pattern")
    # only the grid sweep varies pure load knobs, so only it can target an
    # existing endpoint; the other sweeps change server-side configuration
    # per point and must boot their own runtime
    g.add_argument("--url", default=None,
                   help="Benchmark an existing endpoint instead of self-serving")
    g.add_argument("--concurrencies", default="5,10,20")
    g.add_argument("--max-tokens-list", default="32,64,128")
    g.add_argument("--patterns", default="steady,poisson,bursty")

    a = sub.add_parser("autoscale", parents=[common],
                       help="capacity knobs: slots x initial-scale x grace")
    a.add_argument("--container-concurrencies", default="4,8")
    a.add_argument("--initial-scales", default="0,1")
    a.add_argument("--grace-periods", default="30,300")

    t = sub.add_parser("topology", parents=[common],
                       help="TPU slice matrix (v5e-1/-4/-8), the MIG analog")
    t.add_argument("--topologies", default="v5e-1,v5e-4,v5e-8")

    q = sub.add_parser("quantization", parents=[common],
                       help="quantization x kv-dtype x decoding, Pareto analysis")
    q.add_argument("--quantizations", default="none,int8,int4")
    q.add_argument("--kv-dtypes", default="model,float32")
    q.add_argument("--decodings", default="greedy,sampled")
    q.add_argument("--kv-layouts", default="dense",
                   help="Comma list of cache layouts to sweep (dense,paged) "
                        "— 'dense,paged' measures the block-pool cache and "
                        "its Pallas kernel against dense stripes per config")
    q.add_argument("--no-quality", action="store_true",
                   help="Skip the quality-eval pass per config")


def _base_profile(args: argparse.Namespace) -> dict[str, Any]:
    profile: dict[str, Any] = {}
    if args.profile:
        with open(args.profile) as f:
            profile = yaml.safe_load(f) or {}
    for key in ("model", "requests", "concurrency"):
        v = getattr(args, key, None)
        if v is not None:
            profile[key] = v
    profile.setdefault("model", "llama-tiny")
    profile.setdefault("requests", 30)
    profile.setdefault("concurrency", 8)
    # monitor knobs ride the profile: run_bench honors monitor/
    # monitor_slo/monitor_abort profile keys, so every sweep kind gets
    # early-abort without threading new parameters through each module
    if getattr(args, "no_monitor", False):
        profile["monitor"] = False
    if getattr(args, "abort_slo", None):
        profile["monitor_slo"] = args.abort_slo
        profile["monitor_abort"] = True
    return profile


def _csv_list(s: str, cast=str) -> list:
    return [cast(x.strip()) for x in s.split(",") if x.strip()]


def run(args: argparse.Namespace) -> int:
    base_profile = _base_profile(args)
    out_dir = Path(args.out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)

    if args.kind == "grid":
        from kserve_vllm_mini_tpu.sweeps.grid import run_grid

        rows = run_grid(
            base_profile,
            out_dir,
            grid={
                "concurrency": _csv_list(args.concurrencies, int),
                "max_tokens": _csv_list(args.max_tokens_list, int),
                "pattern": _csv_list(args.patterns),
            },
            url=args.url,
        )
    elif args.kind == "autoscale":
        from kserve_vllm_mini_tpu.sweeps.autoscale import run_autoscale

        rows = run_autoscale(
            base_profile,
            out_dir,
            space={
                "container_concurrency": _csv_list(args.container_concurrencies, int),
                "initial_scale": _csv_list(args.initial_scales, int),
                "scale_to_zero_grace_s": _csv_list(args.grace_periods, int),
            },
        )
    elif args.kind == "topology":
        from kserve_vllm_mini_tpu.sweeps.topology import run_topology

        rows = run_topology(base_profile, out_dir, topologies=_csv_list(args.topologies))
    elif args.kind == "quantization":
        from kserve_vllm_mini_tpu.sweeps.quantization import run_quantization

        rows = run_quantization(
            base_profile,
            out_dir,
            space={
                "quantization": _csv_list(args.quantizations),
                "kv_cache_dtype": _csv_list(args.kv_dtypes),
                "decoding": _csv_list(args.decodings),
                "kv_layout": _csv_list(args.kv_layouts),
            },
            with_quality=not args.no_quality,
        )
    else:  # pragma: no cover — argparse enforces choices
        return 2

    failed = sum(1 for r in rows if r.get("status") != "ok")
    print(f"sweep: {len(rows) - failed}/{len(rows)} configs succeeded -> {out_dir}")
    return 0 if failed == 0 else 1
