"""Autoscaling-knob sweep (reference sweeps/autoscale-sweep.sh).

The reference sweeps Knative autoscaler annotations (containerConcurrency x
initialScale x scaleToZeroGrace x windows, autoscale-sweep.sh:25-29) and
records deploy time, cold multiplier, and cost per combination. The TPU
build keeps that matrix for cluster mode (the annotations render via
deploy/manifests.py) and gives the knobs real local meaning against the
in-repo runtime:

- ``container_concurrency`` -> engine decode slots (admission width),
- ``initial_scale`` 0 -> runtime boots inside the measured window (a true
  cold start: weights + XLA compile); >=1 -> pre-warmed before load,
- ``scale_to_zero_grace_s`` -> recorded for the k8s annotation; locally a
  runtime is torn down after each config regardless.
"""

from __future__ import annotations

from pathlib import Path
from typing import Any, Optional

from kserve_vllm_mini_tpu.sweeps import base

DEFAULT_SPACE: dict[str, list[Any]] = {
    "container_concurrency": [4, 8],
    "initial_scale": [0, 1],
    "scale_to_zero_grace_s": [30, 300],
}

CONFIG_KEYS = ["container_concurrency", "initial_scale", "scale_to_zero_grace_s"]


def knative_annotations(cfg: dict[str, Any]) -> dict[str, str]:
    """The K8s-mode rendering of one sweep point (reference
    autoscale-sweep.sh:120-179 deploy_with_config)."""
    return {
        "autoscaling.knative.dev/initial-scale": str(cfg.get("initial_scale", 0)),
        "autoscaling.knative.dev/scale-to-zero-pod-retention-period": (
            f"{cfg.get('scale_to_zero_grace_s', 30)}s"
        ),
        "autoscaling.knative.dev/target": str(cfg.get("container_concurrency", 8)),
    }


def make_local_bench(base_profile: dict[str, Any]) -> base.BenchFn:
    def bench(cfg: dict[str, Any]) -> dict[str, Any]:
        from kserve_vllm_mini_tpu.bench_pipeline import run_bench

        profile = {**base_profile}
        profile["max_slots"] = int(cfg.get("container_concurrency", 8))
        warm = int(cfg.get("initial_scale", 0)) >= 1
        if warm:
            from kserve_vllm_mini_tpu.runtime.local import local_server

            with local_server(profile) as srv:
                results, code = run_bench(url=srv.url, profile=profile)
                results.setdefault("deploy_time_s", round(srv.boot_seconds, 2))
        else:
            results, code = run_bench(url=None, profile=profile, self_serve=True)
            results.setdefault("deploy_time_s", results.get("cold_start_seconds"))
        if not results:
            raise RuntimeError(f"bench failed with exit code {code}")
        return results

    return bench


def _extra(cfg: dict[str, Any], results: dict[str, Any]) -> dict[str, Any]:
    return {"deploy_time_s": results.get("deploy_time_s")}


def run_autoscale(
    base_profile: dict[str, Any],
    out_dir: Path,
    space: Optional[dict[str, list[Any]]] = None,
    bench_fn: Optional[base.BenchFn] = None,
) -> list[dict[str, Any]]:
    space = space or DEFAULT_SPACE
    configs = base.grid_product(space)
    bench = bench_fn or make_local_bench(base_profile)
    csv_path = Path(out_dir) / "autoscale_results.csv"
    rows = base.run_sweep(
        configs, bench, csv_path, CONFIG_KEYS, extra_row_fn=_extra, label="autoscale-sweep"
    )
    _print_tradeoff(rows)
    return rows


def _print_tradeoff(rows: list[dict[str, Any]]) -> None:
    """Scale-to-zero vs pre-warmed tradeoff summary (reference
    autoscale-sweep.sh:345-415)."""
    import sys

    cold = [r for r in rows if r.get("status") == "ok" and not int(r.get("initial_scale") or 0)]
    warm = [r for r in rows if r.get("status") == "ok" and int(r.get("initial_scale") or 0)]

    def avg(rs: list[dict[str, Any]], key: str) -> Optional[float]:
        vals = [float(r[key]) for r in rs if r.get(key) not in (None, "")]
        return sum(vals) / len(vals) if vals else None

    for name, rs in (("scale-to-zero", cold), ("pre-warmed", warm)):
        if not rs:
            continue
        p95, mult, cost = avg(rs, "p95_ms"), avg(rs, "cold_multiplier"), avg(rs, "cost_per_1k_tokens")
        print(
            f"autoscale-sweep: {name}: avg p95 {p95 and round(p95)} ms,"
            f" cold multiplier {mult and round(mult, 2)},"
            f" $/1K tok {cost and round(cost, 6)}",
            file=sys.stderr,
        )
