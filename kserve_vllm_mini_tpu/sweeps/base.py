"""Shared sweep machinery: the bench-per-config loop and CSV accumulation."""

from __future__ import annotations

import csv
import itertools
import sys
import time
from pathlib import Path
from typing import Any, Callable, Iterable, Optional

# A bench function takes a merged profile dict and returns a results dict
# (the flat results.json schema). Injectable so sweep logic is unit-testable
# without booting the runtime.
BenchFn = Callable[[dict[str, Any]], dict[str, Any]]

# Metrics every sweep row carries, pulled from results.json when present.
RESULT_KEYS = (
    "p50_ms",
    "p95_ms",
    "p99_ms",
    "ttft_p50_ms",
    "ttft_p95_ms",
    "throughput_rps",
    "tokens_per_sec",
    "tokens_per_sec_per_chip",
    "error_rate",
    "cost_per_request",
    "cost_per_1k_tokens",
    "energy_wh_per_1k_tokens",
    "cold_multiplier",
    # monitor early-abort reason (docs/MONITORING.md): a cell the live
    # monitor terminated records why; blank for cells that ran out
    "aborted_early",
)


def sweep_fieldnames(
    config_keys: list[str], extra_keys: Iterable[str] = ()
) -> list[str]:
    """Canonical CSV column layout for every sweep (and for post-hoc
    rewrites of a sweep CSV — single source of truth, nothing reconstructs
    this by hand)."""
    return (
        list(config_keys)
        + list(RESULT_KEYS)
        + sorted(extra_keys)
        + ["status", "error", "elapsed_s"]
    )


def default_bench_fn(
    base: dict[str, Any],
    self_serve: bool = True,
    url: Optional[str] = None,
    **bench_kwargs: Any,
) -> BenchFn:
    """Bench via the in-process pipeline (bench_pipeline.run_bench)."""

    def bench(profile: dict[str, Any]) -> dict[str, Any]:
        from kserve_vllm_mini_tpu.bench_pipeline import run_bench

        merged = {**base, **profile}
        results, code = run_bench(
            url=url, profile=merged, self_serve=self_serve, **bench_kwargs
        )
        if not results:
            raise RuntimeError(f"bench failed with exit code {code}")
        return results

    return bench


def grid_product(grid: dict[str, Iterable[Any]]) -> list[dict[str, Any]]:
    """{'a': [1,2], 'b': [x]} -> [{'a':1,'b':x}, {'a':2,'b':x}] (sorted keys
    for deterministic order)."""
    keys = sorted(grid)
    return [dict(zip(keys, combo)) for combo in itertools.product(*(grid[k] for k in keys))]


def write_row(csv_path: Path, row: dict[str, Any], fieldnames: list[str]) -> None:
    """Append one row, writing the header iff the file is new. Flushed per
    row so a killed sweep keeps everything it measured."""
    csv_path.parent.mkdir(parents=True, exist_ok=True)
    new = not csv_path.exists()
    with csv_path.open("a", newline="") as f:
        w = csv.DictWriter(f, fieldnames=fieldnames, extrasaction="ignore")
        if new:
            w.writeheader()
        w.writerow({k: ("" if row.get(k) is None else row.get(k)) for k in fieldnames})


def run_sweep(
    configs: list[dict[str, Any]],
    bench_fn: BenchFn,
    csv_path: Path,
    config_keys: list[str],
    extra_row_fn: Optional[Callable[[dict[str, Any], dict[str, Any]], dict[str, Any]]] = None,
    label: str = "sweep",
) -> list[dict[str, Any]]:
    """The one loop all sweeps share. Failure rows record the error and the
    sweep continues (reference autoscale-sweep.sh:215-224)."""
    extra_keys: list[str] = []
    if extra_row_fn is not None:
        # extra columns appear between metrics and status
        extra_keys = list(extra_row_fn({}, {}))
    fieldnames = sweep_fieldnames(config_keys, extra_keys)
    rows: list[dict[str, Any]] = []
    for i, cfg in enumerate(configs):
        desc = ", ".join(f"{k}={cfg[k]}" for k in sorted(cfg) if k in config_keys)
        print(f"{label}: [{i + 1}/{len(configs)}] {desc}", file=sys.stderr)
        t0 = time.time()
        row: dict[str, Any] = {k: cfg.get(k) for k in config_keys}
        try:
            results = bench_fn(cfg)
            for k in RESULT_KEYS:
                row[k] = results.get(k)
            if extra_row_fn is not None:
                row.update(extra_row_fn(cfg, results))
            row["status"] = "ok"
            row["error"] = ""
            if row.get("aborted_early"):
                # the cell's partial metrics are still recorded, but the
                # operator must see WHY the cell stopped early
                print(f"{label}: aborted early: {row['aborted_early']}",
                      file=sys.stderr)
        except Exception as e:  # noqa: BLE001 — record-and-continue is the contract
            if extra_row_fn is not None:
                row.update(extra_row_fn(cfg, {}))
            row["status"] = "failed"
            row["error"] = f"{type(e).__name__}: {e}"[:200]
            print(f"{label}: config failed: {row['error']}", file=sys.stderr)
        row["elapsed_s"] = round(time.time() - t0, 2)
        write_row(csv_path, row, fieldnames)
        rows.append(row)
    return rows


def summarize_top(
    rows: list[dict[str, Any]],
    by: str,
    minimize: bool,
    n: int = 3,
) -> list[dict[str, Any]]:
    ok = [r for r in rows if r.get("status") == "ok" and r.get(by) is not None]
    return sorted(ok, key=lambda r: float(r[by]), reverse=not minimize)[:n]
