"""Grid sweep: concurrency x max_tokens x pattern (reference grid-sweep.sh).

Same matrix as the reference's default grid (grid-sweep.sh:23-25:
concurrency {5,10,20} x max_tokens {32,64,128} x pattern
{steady,poisson,bursty}) and the same output contract — one CSV row per
cell, top-performers summary (grid-sweep.sh:181-198) — but run in-process
against the self-served TPU runtime or any URL.
"""

from __future__ import annotations

import sys
from pathlib import Path
from typing import Any, Optional

from kserve_vllm_mini_tpu.sweeps import base

DEFAULT_GRID: dict[str, list[Any]] = {
    "concurrency": [5, 10, 20],
    "max_tokens": [32, 64, 128],
    "pattern": ["steady", "poisson", "bursty"],
}

CONFIG_KEYS = ["pattern", "concurrency", "max_tokens"]


def run_grid(
    base_profile: dict[str, Any],
    out_dir: Path,
    grid: Optional[dict[str, list[Any]]] = None,
    bench_fn: Optional[base.BenchFn] = None,
    url: Optional[str] = None,
) -> list[dict[str, Any]]:
    grid = grid or DEFAULT_GRID
    configs = base.grid_product(grid)
    bench = bench_fn or base.default_bench_fn(base_profile, self_serve=url is None, url=url)
    csv_path = Path(out_dir) / "sweep_results.csv"
    rows = base.run_sweep(configs, bench, csv_path, CONFIG_KEYS, label="grid-sweep")

    print("\ntop throughput:", file=sys.stderr)
    for r in base.summarize_top(rows, "throughput_rps", minimize=False):
        print(
            f"  {r['pattern']} conc={r['concurrency']} tok={r['max_tokens']}"
            f" -> {float(r['throughput_rps']):.2f} rps, p95 {float(r['p95_ms'] or 0):.0f} ms",
            file=sys.stderr,
        )
    print("lowest p95:", file=sys.stderr)
    for r in base.summarize_top(rows, "p95_ms", minimize=True):
        print(
            f"  {r['pattern']} conc={r['concurrency']} tok={r['max_tokens']}"
            f" -> p95 {float(r['p95_ms']):.0f} ms",
            file=sys.stderr,
        )
    return rows
