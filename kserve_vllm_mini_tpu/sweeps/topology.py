"""Topology-slice sweep — the TPU analog of the reference MIG sweep.

The reference benchmarks each NVIDIA MIG slice against the full GPU
(sweeps/mig-sweep.sh:90-193, profiles/mig/*) to answer "how small a slice
still meets the SLO". On TPU the partitioning axis is the pod slice: v5e-1
vs v5e-4 vs v5e-8 (SURVEY.md §7.2 step 7). Each point re-serves the model
over the corresponding ``jax.sharding.Mesh`` and the output matrix keeps the
mig_matrix.csv shape the report's topology-matrix HTML consumes
(report/html.py generate_topology_matrix_html).
"""

from __future__ import annotations

from pathlib import Path
from typing import Any, Optional

from kserve_vllm_mini_tpu.sweeps import base

DEFAULT_TOPOLOGIES = ["v5e-1", "v5e-4", "v5e-8"]

CONFIG_KEYS = ["topology", "chips"]


def make_local_bench(base_profile: dict[str, Any]) -> base.BenchFn:
    def bench(cfg: dict[str, Any]) -> dict[str, Any]:
        from kserve_vllm_mini_tpu.bench_pipeline import run_bench

        profile = {**base_profile}
        profile["jax_topology"] = cfg["topology"]
        profile["chips"] = cfg["chips"]
        profile["accelerator"] = f"tpu-{cfg['topology']}"
        results, code = run_bench(url=None, profile=profile, self_serve=True)
        if not results:
            raise RuntimeError(f"bench failed with exit code {code}")
        return results

    return bench


def run_topology(
    base_profile: dict[str, Any],
    out_dir: Path,
    topologies: Optional[list[str]] = None,
    bench_fn: Optional[base.BenchFn] = None,
) -> list[dict[str, Any]]:
    from kserve_vllm_mini_tpu.parallel.mesh import TOPOLOGY_PRESETS

    names = topologies or DEFAULT_TOPOLOGIES
    configs = []
    for name in names:
        if name not in TOPOLOGY_PRESETS:
            raise ValueError(f"unknown topology {name!r}; known: {sorted(TOPOLOGY_PRESETS)}")
        configs.append({"topology": name, "chips": TOPOLOGY_PRESETS[name]["chips"]})
    bench = bench_fn or make_local_bench(base_profile)
    csv_path = Path(out_dir) / "topology_matrix.csv"
    rows = base.run_sweep(configs, bench, csv_path, CONFIG_KEYS, label="topology-sweep")

    import sys

    best = base.summarize_top(rows, "tokens_per_sec_per_chip", minimize=False, n=1)
    if best:
        b = best[0]
        print(
            f"topology-sweep: most chip-efficient: {b['topology']}"
            f" ({float(b['tokens_per_sec_per_chip']):.1f} tok/s/chip)",
            file=sys.stderr,
        )
    return rows
