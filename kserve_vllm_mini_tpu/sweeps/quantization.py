"""Quantization x kv-dtype x decoding sweep with Pareto analysis
(reference sweeps/quantization_sweep.py).

The reference sweeps vLLM quantization modes (none/fp8/awq/gptq) by
redeploying container images with env knobs (quantization_sweep.py:40-234).
Here the quantization is done by our own runtime (ops/quant.py int8
weight-only; kv-cache dtype is an engine knob), each configuration serves
once and is measured for latency/cost AND quality on the same server — then
the multi-objective Pareto frontier (p95, $/1K tok vs quality, tokens/s)
and 3-axis bucket classification mirror quantization_sweep.py:510-549 via
quality.evaluator.
"""

from __future__ import annotations

import json
import sys
from pathlib import Path
from typing import Any, Optional

from kserve_vllm_mini_tpu.sweeps import base

DEFAULT_SPACE: dict[str, list[Any]] = {
    "quantization": ["none", "int8", "int4", "int4-awq"],
    "kv_cache_dtype": ["model", "int8"],   # int8 = scaled int8-KV cache
    "decoding": ["greedy", "sampled"],
    # how quantized matmuls contract (ops/qmatmul.py): "dequant" casts to
    # bf16 before the dot, "w8a8" runs the int8 MXU contraction with
    # per-token activation quant. A no-op axis for quantization=none rows
    # (dropped from the grid below to avoid benching duplicates).
    "quant_mode": ["dequant", "w8a8"],
}

DECODING_PRESETS: dict[str, dict[str, Any]] = {
    "greedy": {"temperature": 0.0},
    "sampled": {"temperature": 0.7, "extra_body": {"top_p": 0.95}},
}

CONFIG_KEYS = ["quantization", "kv_cache_dtype", "decoding", "kv_layout",
               "quant_mode"]

# perplexity gate (docs/FEATURES.md): a quantized cell whose NLL/token
# exceeds the unquantized greedy baseline's by more than this is a
# NUMERICS BREAK (e.g. a dropped activation scale), not a quality
# trade-off — the cell FAILS so the speedup can't ship on broken math.
# Legit int4 damage on real checkpoints measures well under 0.5 nats;
# dropping a scale factor blows NLL up by several nats.
PERPLEXITY_GATE_MAX_NLL_DELTA = 1.0


def is_baseline_config(cfg: dict[str, Any]) -> bool:
    """The fidelity reference config — unquantized, model-dtype dense KV,
    greedy. ONE predicate shared by capture (make_local_bench) and ordering
    (run_quantization): if they diverge, the baseline can bench after a row
    that wanted a fidelity score against it, silently flipping the Pareto
    quality axis to the ~chance task score."""
    # `or default`, not a .get default: sweep ROWS carry every CONFIG_KEY
    # with None for axes the grid didn't sweep, and the gate post-pass
    # matches the baseline against rows, not just grid configs
    return (
        cfg.get("quantization") == "none"
        and (cfg.get("kv_cache_dtype") or "model") == "model"
        and (cfg.get("decoding") or "greedy") == "greedy"
        and (cfg.get("kv_layout") or "dense") == "dense"
        and (cfg.get("quant_mode") or "dequant") == "dequant"
    )


def make_local_bench(
    base_profile: dict[str, Any], with_quality: bool = True
) -> base.BenchFn:
    # greedy fidelity reference: the UNQUANTIZED greedy config's captured
    # outputs (quantization=none, kv=model), captured once and compared
    # against by every other greedy config — a quantization-quality ordering
    # that discriminates even on random-weight CI models, where the task
    # suite scores ~chance for every config (round-2 VERDICT Weak #8). The
    # reference identity is explicit: if the baseline config is absent from
    # the grid or failed, fidelity is skipped rather than silently measured
    # against a quantized "reference" (which would invert the ordering).
    ref_capture: dict[str, Any] = {}
    nll_cache: dict[str, Any] = {}  # quantization -> eval_text_nll result

    _is_baseline = is_baseline_config

    def bench(cfg: dict[str, Any]) -> dict[str, Any]:
        from kserve_vllm_mini_tpu.bench_pipeline import run_bench
        from kserve_vllm_mini_tpu.runtime.local import local_server

        profile = {**base_profile}
        profile["quantization"] = cfg["quantization"]
        if cfg.get("quant_mode"):
            profile["quant_mode"] = cfg["quant_mode"]
        if cfg.get("kv_cache_dtype") and cfg["kv_cache_dtype"] != "model":
            profile["kv_cache_dtype"] = cfg["kv_cache_dtype"]
        if cfg.get("kv_layout"):
            # paged rows measure the block-pool cache (+ Pallas kernel on
            # TPU) against dense at the same quant/decoding point
            profile["kv_layout"] = cfg["kv_layout"]
        profile.update(DECODING_PRESETS.get(cfg.get("decoding", "greedy"), {}))

        # one server boot serves both the load test and the quality eval —
        # the reference pays a full redeploy per config (quantization_sweep
        # .py:226-234); in-process we pay one XLA compile
        with local_server(profile) as srv:
            results, code = run_bench(url=srv.url, profile=profile)
            if not results:
                raise RuntimeError(f"bench failed with exit code {code}")
            if with_quality:
                from kserve_vllm_mini_tpu.quality.evaluator import (
                    capture_outputs,
                    evaluate,
                    fidelity_metrics,
                )

                model = profile.get("model", "default")
                results.update(evaluate(srv.url, model=model))
                # the capture sends temperature=0 per request, so it is
                # greedy regardless of the config's load-test decoding —
                # every row gets a fidelity score for its quantization
                # (run_quantization orders the baseline config first)
                cap = capture_outputs(srv.url, model=model)
                if _is_baseline(cfg):
                    ref_capture["outputs"] = cap
                if "outputs" in ref_capture:
                    results.update(fidelity_metrics(ref_capture["outputs"], cap))
                    results["fidelity_reference"] = "none/model/greedy"
                # likelihood axis: teacher-forced NLL on curated real text,
                # computed in-process against the SAME params this config
                # serves — the metric that separates int8 from int4 even
                # when the task suite scores ~chance (quality/perplexity.py).
                # Cached per (quantization, quant_mode): kv dtype and
                # decoding cannot change it, and each call pays a fresh
                # jit trace. quant_mode IS in the key — the w8a8
                # activation rounding is exactly what the NLL gate exists
                # to measure.
                q = (cfg["quantization"], cfg.get("quant_mode") or "dequant")
                if q not in nll_cache:
                    from kserve_vllm_mini_tpu.quality.perplexity import (
                        eval_text_nll,
                    )

                    nll_cache[q] = eval_text_nll(
                        srv.engine.params, srv.engine.cfg, srv.tokenizer
                    )
                results["quality_nll_per_token"] = round(
                    nll_cache[q]["nll_per_token"], 5
                )
                results["quality_perplexity"] = round(
                    nll_cache[q]["perplexity"], 3
                )
        return results

    return bench


def _extra(cfg: dict[str, Any], results: dict[str, Any]) -> dict[str, Any]:
    return {
        "quality_score": results.get("quality_score"),
        "quality_fidelity": results.get("quality_fidelity"),
        "quality_nll_per_token": results.get("quality_nll_per_token"),
        "quality_perplexity": results.get("quality_perplexity"),
        # NLL/token delta vs the unquantized greedy baseline (nats = the
        # log-perplexity delta); gated post-sweep — past
        # PERPLEXITY_GATE_MAX_NLL_DELTA the cell FAILS (numerics break)
        "quality_perplexity_delta_vs_baseline": None,
        "fidelity_exact_match": results.get("fidelity_exact_match"),
        "fidelity_reference": results.get("fidelity_reference"),
        "pareto": "",     # filled after the full sweep
        "bucket": "",
    }


def run_quantization(
    base_profile: dict[str, Any],
    out_dir: Path,
    space: Optional[dict[str, list[Any]]] = None,
    bench_fn: Optional[base.BenchFn] = None,
    with_quality: bool = True,
) -> list[dict[str, Any]]:
    from kserve_vllm_mini_tpu.quality.evaluator import (
        classify_pareto_bucket,
        pareto_frontier,
    )

    space = space or DEFAULT_SPACE
    configs = base.grid_product(space)
    # quant_mode is a no-op for unquantized rows: rewrite them to the
    # canonical "dequant" label and dedup, so the grid never benches the
    # same program twice — and a w8a8-only grid still gets its
    # unquantized BASELINE row (the fidelity/perplexity reference)
    seen: set[tuple] = set()
    deduped = []
    for c in configs:
        if c.get("quantization") == "none" and c.get("quant_mode"):
            c = {**c, "quant_mode": "dequant"}
        key = tuple(sorted((k, str(v)) for k, v in c.items()))
        if key not in seen:
            seen.add(key)
            deduped.append(c)
    configs = deduped
    # the unquantized greedy baseline must bench before any row that wants a
    # fidelity score against it; stable sort keeps the rest in grid order
    configs = sorted(configs, key=lambda c: 0 if is_baseline_config(c) else 1)
    bench = bench_fn or make_local_bench(base_profile, with_quality=with_quality)
    out_dir = Path(out_dir)
    csv_path = out_dir / "quant_sweep.csv"
    rows = base.run_sweep(
        configs, bench, csv_path, CONFIG_KEYS, extra_row_fn=_extra, label="quant-sweep"
    )

    # perplexity gate (PERPLEXITY_GATE_MAX_NLL_DELTA): every quantized
    # cell's NLL/token is compared against the unquantized greedy
    # baseline's. A delta past the threshold is a numerics BREAK (dropped
    # activation scale, wrapped accumulator, ...) masquerading as a config
    # — the cell is FAILED before the Pareto pass so broken math can never
    # land on the frontier. Skipped when the baseline has no NLL
    # (--no-quality runs measure nothing to gate against).
    base_row = next(
        (r for r in rows
         if is_baseline_config(r) and r.get("status") == "ok"
         and r.get("quality_nll_per_token") is not None),
        None,
    )
    if base_row is not None:
        base_nll = float(base_row["quality_nll_per_token"])
        for r in rows:
            if r.get("status") != "ok" or r.get("quality_nll_per_token") is None:
                continue
            delta = round(float(r["quality_nll_per_token"]) - base_nll, 5)
            r["quality_perplexity_delta_vs_baseline"] = delta
            if delta > PERPLEXITY_GATE_MAX_NLL_DELTA:
                r["status"] = "failed"
                r["error"] = (
                    f"perplexity gate: nll_per_token delta {delta} vs "
                    f"baseline {base_nll} exceeds "
                    f"{PERPLEXITY_GATE_MAX_NLL_DELTA} (numerics break, "
                    "not a quality trade-off)"
                )
                print(f"quant-sweep: {r['error']}", file=sys.stderr)

    # post-pass: Pareto frontier + buckets over the successful rows. Quality
    # participates only when it was actually measured — with --no-quality the
    # score is absent and must not enter the frontier as 0.0 or drive bucket
    # labels ("cheap-fast-degraded" for a quality that was never evaluated).
    ok_rows = [r for r in rows if r.get("status") == "ok"]
    have_quality = with_quality and any(
        r.get("quality_score") is not None for r in ok_rows
    )
    # quality axis for the frontier: baseline-fidelity, but ONLY when every
    # row has it (greedy-only grids) — mixing fidelity rows with task-score
    # rows would rank configs by which metric they carry, not by quality
    all_fidelity = bool(ok_rows) and all(
        r.get("quality_fidelity") is not None for r in ok_rows
    )
    points = [
        {
            "p95_ms": float(r.get("p95_ms") or 0),
            "cost_per_1k_tokens": float(r.get("cost_per_1k_tokens") or 0),
            "quality_score": float(
                r.get("quality_fidelity") if all_fidelity
                else (r.get("quality_score") or 0)
            ),
            "tokens_per_sec": float(r.get("tokens_per_sec") or 0),
        }
        for r in ok_rows
    ]
    maximize = ("quality_score", "tokens_per_sec") if have_quality else ("tokens_per_sec",)
    frontier = set(
        pareto_frontier(
            points,
            minimize=("p95_ms", "cost_per_1k_tokens"),
            maximize=maximize,
        )
    )
    for i, r in enumerate(ok_rows):
        r["pareto"] = "yes" if i in frontier else ""
        if have_quality:
            r["bucket"] = classify_pareto_bucket(
                points[i]["quality_score"], points[i]["p95_ms"], points[i]["cost_per_1k_tokens"]
            )

    # rewrite the CSV with pareto/bucket populated (flush-per-row kept the
    # partial data safe; this final write is the enriched version)
    if csv_path.exists():
        csv_path.unlink()
    fieldnames = base.sweep_fieldnames(CONFIG_KEYS, _extra({}, {}))
    for r in rows:
        base.write_row(csv_path, r, fieldnames)

    summary = {
        "configs": len(rows),
        "succeeded": len(ok_rows),
        "pareto_optimal": [
            {k: ok_rows[i].get(k) for k in CONFIG_KEYS + ["p95_ms", "cost_per_1k_tokens", "quality_score"]}
            for i in sorted(frontier)
        ],
    }
    (out_dir / "quant_sweep_summary.json").write_text(json.dumps(summary, indent=2))
    for p in summary["pareto_optimal"]:
        print(f"quant-sweep: pareto-optimal: {p}", file=sys.stderr)
    return rows
