"""The 1 Hz unified sampler: runtime /metrics + loadgen live stats ->
``runs/<id>/timeline.jsonl`` + burn-rates + events + abort.

One background thread per run. Every tick it scrapes the runtime's
``/metrics`` (reusing analysis/telemetry.scrape_runtime_metrics — the
same parser the post-hoc analyzer uses, so names can't drift between
live and post-hoc views), snapshots the load generator's LiveStats,
appends one JSON line to the timeline, recomputes rolling-window SLO
burn-rates (monitor/burnrate.py) and runs event detection
(monitor/events.py). Overhead contract (docs/MONITORING.md): the scrape
timeout is strictly below the sample interval, a tick that overruns its
slot is SKIPPED (counted, never queued), and the thread never blocks the
benchmark — stopping joins with a bounded timeout and the thread is a
daemon.
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import TYPE_CHECKING, Any, Callable, Optional

from kserve_vllm_mini_tpu.analysis import telemetry
from kserve_vllm_mini_tpu.monitor import burnrate
from kserve_vllm_mini_tpu.monitor.events import AbortSignal, Event, EventDetector

if TYPE_CHECKING:  # type-only: the monitor must not import httpx at runtime
    from kserve_vllm_mini_tpu.loadgen.runner import LiveStats

# runtime /metrics series carried into each timeline sample, stored under
# sample["runtime"] with the kvmini_tpu_ prefix stripped. Counters keep
# their _total suffix so consumers can tell rates from gauges.
TIMELINE_RUNTIME_METRICS = (
    "kvmini_tpu_duty_cycle",
    "kvmini_tpu_busy_seconds_total",
    "kvmini_tpu_queue_depth",
    "kvmini_tpu_active_slots",
    "kvmini_tpu_inflight_sweeps",
    "kvmini_tpu_decode_tokens_total",
    "kvmini_tpu_decode_steps_total",
    "kvmini_tpu_requests_completed_total",
    "kvmini_tpu_pipelined_sweeps_total",
    # chunked-prefill rail (docs/TROUBLESHOOTING.md "Long prompts stall
    # streaming"): prefill progress feeds the prefill_stall rule — decode
    # frozen WHILE prefill advances is the attribution decode_stall alone
    # cannot make
    "kvmini_tpu_prefills_total",
    "kvmini_tpu_prefill_chunks_total",
    "kvmini_tpu_prefill_chunk_stall_seconds_total",
    # disaggregated-serving rail (docs/DISAGGREGATION.md): the lane
    # backlog gauge feeds the handoff_stall rule (decode live while the
    # handoff queue grows = prefill lane saturated), and the handoff/
    # drop/lane-busy counters ride into the report's disagg facts
    "kvmini_tpu_kv_handoffs_total",
    "kvmini_tpu_kv_handoff_queue_depth",
    "kvmini_tpu_kv_handoff_drops_total",
    "kvmini_tpu_prefill_lane_busy_seconds_total",
    "kvmini_tpu_kv_free_blocks",
    # KV-cache & HBM deep observability (docs/TROUBLESHOOTING.md "HBM
    # pressure & KV thrash"): pool occupancy + eviction churn feed the
    # kv_thrash rule, the watermark pair feeds hbm_watermark_high, and
    # all of them ride into the report's KV/memory timeline lanes
    "kvmini_tpu_kv_occupancy",
    "kvmini_tpu_kv_retained_evictions_total",
    # host-RAM tier demotions ride beside eviction churn so the report's
    # churn lane can split recoverable demotions from true discards
    "kvmini_tpu_kv_tier_demotions_total",
    "kvmini_tpu_hbm_bytes_in_use",
    "kvmini_tpu_hbm_bytes_limit",
    # resilience rail (docs/RESILIENCE.md): admission sheds feed the
    # overload_shedding rule, recovered faults feed engine_fault, and
    # the degrade-ladder position rides into the event detail/report
    "kvmini_tpu_requests_shed_total",
    "kvmini_tpu_engine_faults_total",
    "kvmini_tpu_degrade_level",
    # fleet rail (docs/FLEET.md): live-vs-desired replica counts feed
    # the replica_down rule, and the reroute/shed counters attribute a
    # latency cliff to failover churn vs plain overload
    "kvmini_tpu_fleet_replicas_desired",
    "kvmini_tpu_fleet_replicas_live",
    "kvmini_tpu_fleet_reroutes_total",
    "kvmini_tpu_fleet_sheds_total",
    # live-economics rail (docs/ECONOMICS.md): the $/1K-tok gauge feeds
    # the cost_burn_exceeded rule and the sampler's live cost budget,
    # the router-only marginal gauge feeds replica_unprofitable, and all
    # five ride into the report's cost/energy timeline lanes. Engines
    # without a priced accelerator export none of them — the timeline
    # stays absent, never a fabricated $0.
    "kvmini_tpu_econ_usd_per_1k_tokens",
    "kvmini_tpu_econ_wh_per_1k_tokens",
    "kvmini_tpu_econ_usd_per_hour",
    "kvmini_tpu_econ_tokens_per_sec",
    "kvmini_tpu_econ_marginal_replica_usd_per_1k_tokens",
)

_PREFIX = "kvmini_tpu_"

# event types that trigger the abort hook when abort is enabled: sustained
# budget burn and a wedged decode loop are unrecoverable for the cell;
# the other events are diagnostic (a bursty pattern legitimately collapses
# throughput between bursts)
DEFAULT_ABORT_ON = frozenset({"burn_rate_exceeded", "decode_stall"})


@dataclass
class MonitorConfig:
    interval_s: float = 1.0
    # strictly below interval_s: a slow endpoint costs one skipped tick,
    # never a backlog
    scrape_timeout_s: float = 0.8
    window_s: float = 10.0
    warmup_s: float = 5.0
    burn_threshold: float = 2.0
    burn_samples: int = 3
    stall_samples: int = 5
    prefill_stall_samples: int = 3    # prefill_stall rule (docs/MONITORING.md)
    handoff_stall_samples: int = 3    # handoff_stall rule (docs/MONITORING.md)
    queue_depth_limit: float = 32.0
    kv_thrash_rate: float = 4.0       # retained evictions/s (docs/MONITORING.md)
    kv_thrash_samples: int = 3
    hbm_high_fraction: float = 0.92   # of kvmini_tpu_hbm_bytes_limit
    replica_down_samples: int = 3     # replica_down rule (docs/FLEET.md)
    # economics rules (docs/ECONOMICS.md): both inert without a budget
    cost_budget_usd_per_1k_tok: Optional[float] = None
    cost_burn_samples: int = 3
    unprofitable_samples: int = 3
    abort_enabled: bool = False
    abort_on: frozenset[str] = DEFAULT_ABORT_ON
    budgets: dict[str, float] = field(default_factory=dict)

    def __post_init__(self) -> None:
        self.scrape_timeout_s = min(
            self.scrape_timeout_s, max(self.interval_s * 0.8, 0.01)
        )


class RunMonitor:
    """Background sampler for one benchmark run.

    ``live`` is the loadgen's LiveStats (None for endpoint-only
    monitoring); ``scrape_fn(endpoint, timeout_s)`` is injectable for
    tests and defaults to the real /metrics scrape.
    """

    def __init__(
        self,
        timeline_path: Path,
        endpoint: Optional[str],
        live: Optional["LiveStats"] = None,
        cfg: Optional[MonitorConfig] = None,
        abort: Optional[AbortSignal] = None,
        scrape_fn: Optional[Callable[..., dict[str, float]]] = None,
    ) -> None:
        self.timeline_path = Path(timeline_path)
        self.endpoint = endpoint
        self.live = live
        self.cfg = cfg or MonitorConfig()
        self.abort = abort
        self._scrape = scrape_fn or telemetry.scrape_runtime_metrics
        # guards the cross-thread view (samples/events/skipped/burn_*):
        # the sampler thread mutates while stop()/summary()/timeline()
        # read — stop()'s join is BOUNDED, so the thread may still be
        # mid-tick when the summary is taken (KVM051)
        self._state_lock = threading.Lock()
        self.samples: list[dict[str, Any]] = []
        self.events: list[Event] = []
        self.skipped = 0
        self.burn_latest: dict[str, float] = {}
        self.burn_peak: dict[str, float] = {}
        self._detector = EventDetector(
            stall_samples=self.cfg.stall_samples,
            prefill_stall_samples=self.cfg.prefill_stall_samples,
            handoff_stall_samples=self.cfg.handoff_stall_samples,
            queue_depth_limit=self.cfg.queue_depth_limit,
            burn_threshold=self.cfg.burn_threshold,
            burn_samples=self.cfg.burn_samples,
            warmup_s=self.cfg.warmup_s,
            kv_thrash_rate=self.cfg.kv_thrash_rate,
            kv_thrash_samples=self.cfg.kv_thrash_samples,
            hbm_high_fraction=self.cfg.hbm_high_fraction,
            replica_down_samples=self.cfg.replica_down_samples,
            cost_budget_usd_per_1k_tok=self.cfg.cost_budget_usd_per_1k_tok,
            cost_burn_samples=self.cfg.cost_burn_samples,
            unprofitable_samples=self.cfg.unprofitable_samples,
        )
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._t_started: Optional[float] = None  # first tick (burn windows)

    # -- one tick ----------------------------------------------------------

    def _runtime_block(self) -> Optional[dict[str, float]]:
        if not self.endpoint:
            return None
        m = self._scrape(self.endpoint, timeout_s=self.cfg.scrape_timeout_s)
        if not m:
            return None
        return {
            name[len(_PREFIX):]: m[name]
            for name in TIMELINE_RUNTIME_METRICS
            if name in m
        }

    def sample_once(self, fh=None) -> dict[str, Any]:
        t_tick = time.time()
        runtime = self._runtime_block()
        scrape_ms = (time.time() - t_tick) * 1000.0
        sample: dict[str, Any] = {"t": t_tick, "scrape_ms": round(scrape_ms, 3)}
        if runtime is not None:
            sample["runtime"] = runtime
        burn: dict[str, float] = {}
        if self.live is not None:
            lg = self.live.snapshot()
            if self._t_started is None:
                self._t_started = t_tick
            win = burnrate.window_stats(
                self.live.completions(), t_tick, self.cfg.window_s,
                t_start=self._t_started,
            )
            if not win and lg.get("completed"):
                # completions happened EARLIER but none inside the window:
                # the current throughput is genuinely zero, not unknown —
                # without this, a server that wedges mid-run empties the
                # window and the monitor goes blind exactly when it
                # matters (no burn, no collapse event, no abort)
                win = {"throughput_rps": 0.0, "tokens_per_sec": 0.0}
            if "throughput_rps" in win:
                lg["window_throughput_rps"] = round(win["throughput_rps"], 4)
            sample["loadgen"] = lg
            # trace ids in flight at sample time (docs/MONITORING.md):
            # TOP-level, not inside `loadgen` — that block's schema is a
            # flat name->number map. The detector stamps these into any
            # event fired off this tick, making alerts clickable into
            # the merged traces.json.
            ids_fn = getattr(self.live, "inflight_trace_ids", None)
            ids = ids_fn() if callable(ids_fn) else []
            if ids:
                sample["inflight_trace_ids"] = ids
            # the live $/1K-tok comes from the runtime's economics gauge,
            # not from completions — inject it so a slo.json
            # cost_per_1k_tokens_max budget produces a LIVE burn rate
            # (docs/ECONOMICS.md) instead of waiting for the post-hoc gate
            if runtime is not None and "econ_usd_per_1k_tokens" in runtime:
                win["cost_per_1k_tokens"] = runtime["econ_usd_per_1k_tokens"]
            burn = burnrate.burn_rates(win, self.cfg.budgets)
            if burn:
                sample["burn_rates"] = {
                    k: round(v, 4) for k, v in burn.items()
                }
        fired = self._detector.observe(sample, burn)
        if fired:
            sample["events"] = [e.to_dict() for e in fired]
        # publish the tick atomically: stop()/summary()/timeline() read
        # from other threads, and the bounded stop-join means they can
        # overlap a tick still in flight
        with self._state_lock:
            if self.live is not None:
                self.burn_latest = burn
                for k, v in burn.items():
                    self.burn_peak[k] = max(self.burn_peak.get(k, 0.0), v)
            self.events.extend(fired)
            self.samples.append(sample)
        for e in fired:
            if (
                self.abort is not None
                and self.cfg.abort_enabled
                and e.type in self.cfg.abort_on
            ):
                # outside _state_lock: AbortSignal.set takes its own lock
                # and fires registered callbacks — keep the lock graph flat
                self.abort.set(f"{e.type}: {e.detail}")
        if fh is not None:
            fh.write(json.dumps(sample, sort_keys=True) + "\n")
            fh.flush()
        return sample

    # -- thread ------------------------------------------------------------

    def _loop(self) -> None:
        self.timeline_path.parent.mkdir(parents=True, exist_ok=True)
        with self.timeline_path.open("a") as fh:
            next_tick = time.time()
            while True:
                self.sample_once(fh)
                next_tick += self.cfg.interval_s
                now = time.time()
                if now > next_tick:
                    # the tick overran its slot (slow scrape / loaded
                    # host): skip the missed slots rather than queue them
                    # — a backlog of catch-up scrapes would hammer the
                    # very endpoint the run is measuring
                    missed = int((now - next_tick) / self.cfg.interval_s) + 1
                    with self._state_lock:
                        self.skipped += missed
                    next_tick = now + self.cfg.interval_s
                if self._stop.wait(timeout=max(next_tick - time.time(), 0.0)):
                    return

    def start(self) -> "RunMonitor":
        if self._thread is not None:
            raise RuntimeError("monitor already started")
        self._thread = threading.Thread(
            target=self._loop, name="run-monitor", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, join_timeout_s: float = 5.0) -> dict[str, Any]:
        """Signal the thread, join (bounded — a scrape stuck in its
        timeout must not stall the pipeline), and return the summary
        block for results.json."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=join_timeout_s)
        return self.summary()

    def timeline(self) -> list[dict[str, Any]]:
        """Snapshot of the samples recorded so far — the safe way to hand
        the timeline across threads (the raw ``samples`` list is live
        while the sampler runs; iterating it races ``append``)."""
        with self._state_lock:
            return list(self.samples)

    def summary(self) -> dict[str, Any]:
        """The ``monitor`` block (core/schema.py validate_monitor)."""
        with self._state_lock:
            out: dict[str, Any] = {
                "interval_s": self.cfg.interval_s,
                "window_s": self.cfg.window_s,
                "samples": len(self.samples),
                "skipped_samples": self.skipped,
                "events": [e.to_dict() for e in self.events],
                "burn_rates": {
                    k: round(v, 4) for k, v in self.burn_latest.items()
                },
                "burn_rates_peak": {
                    k: round(v, 4) for k, v in self.burn_peak.items()
                },
            }
        if self.abort is not None and self.abort.is_set():
            out["aborted"] = self.abort.reason
        return out
