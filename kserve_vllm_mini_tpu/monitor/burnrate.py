"""Rolling-window SLO burn-rates over live load-generator completions.

The budgets are the SAME file gates/slo.py gates on post-hoc (slo.json
keys like ``p95_ms_max`` / ``throughput_rps_min``); the monitor evaluates
the subset computable from a sliding window of completed requests while
the run is still going. Burn rate is normalized budget consumption:

- ``max`` budgets (latency, error rate): ``value / budget``
- ``min`` budgets (throughput): ``budget / value``

so 1.0 means exactly on budget and anything above 1.0 means the current
window is out of budget — a sustained burn > threshold is grounds to
abort a sweep cell early (docs/MONITORING.md).
"""

from __future__ import annotations

from typing import Any, Optional

from kserve_vllm_mini_tpu.gates.slo import BUDGET_RULES

# budget keys whose results-metric the live window can produce: most come
# from the rolling window of request completions; cost_per_1k_tokens is
# injected by the sampler from the runtime's live-economics gauge
# (kvmini_tpu_econ_usd_per_1k_tokens, docs/ECONOMICS.md) when the engine
# exports the rail. The rest — energy, cold multiplier, fairness — still
# need post-hoc stages and are gated only at the end.
LIVE_BUDGET_KEYS = (
    "p95_ms_max",
    "p99_ms_max",
    "ttft_p95_ms_max",
    "error_rate_max",
    "cost_per_1k_tokens_max",
    "throughput_rps_min",
    "tokens_per_sec_min",
)

# ceiling for a burn rate (division by ~zero): keeps the serialized
# monitor block strict JSON — float('inf') would render as Infinity
BURN_CAP = 1e9


def window_stats(
    events: list[tuple[float, bool, float, float, int]],
    t_now: float,
    window_s: float,
    t_start: Optional[float] = None,
) -> dict[str, float]:
    """Live metrics over completions inside ``[t_now - window_s, t_now]``.

    ``events`` rows are ``(end_ts, ok, latency_ms, ttft_ms, tokens_out)``
    (loadgen LiveStats.completions). Returns only keys the window can
    honestly back: an empty window yields an empty dict, never zeros
    that would read as "infinitely fast".

    ``t_start`` (when the run began) shrinks the rate divisor for a
    window that is only partially populated yet: dividing 2 completions
    at t=2s of a run by the full 10 s window would read 0.2 rps where the
    true early throughput is 1 rps — min-direction burn rates would spike
    and abort perfectly healthy runs at startup.
    """
    from kserve_vllm_mini_tpu.analysis.telemetry import nearest_rank_percentile

    cut = t_now - window_s
    span = window_s
    if t_start is not None:
        span = max(min(window_s, t_now - t_start), 1e-9)
    win = [e for e in events if e[0] >= cut and e[0] <= t_now]
    if not win:
        return {}
    ok = [e for e in win if e[1]]
    out: dict[str, float] = {
        "window_s": span,
        "completed": float(len(win)),
        "error_rate": (len(win) - len(ok)) / len(win),
        "throughput_rps": len(win) / span,
    }
    if ok:
        lats = [e[2] for e in ok]
        out["p95_ms"] = nearest_rank_percentile(lats, 95.0)
        out["p99_ms"] = nearest_rank_percentile(lats, 99.0)
        ttfts = [e[3] for e in ok if e[3] > 0]
        if ttfts:
            out["ttft_p95_ms"] = nearest_rank_percentile(ttfts, 95.0)
        out["tokens_per_sec"] = sum(e[4] for e in ok) / span
    return out


def burn_rates(
    stats: dict[str, float], budgets: dict[str, float]
) -> dict[str, float]:
    """Normalized budget consumption per live budget key; keys whose
    metric the window could not produce are omitted (absence of data is
    not a pass — but it is not a live abort signal either; the post-hoc
    gate still fails on missing metrics). Rates are capped at BURN_CAP so
    a zero-throughput window stays strict JSON (Infinity is not)."""
    out: dict[str, float] = {}
    for key in LIVE_BUDGET_KEYS:
        budget = budgets.get(key)
        if budget is None:
            continue
        metric, direction = BUDGET_RULES[key]
        value: Optional[Any] = stats.get(metric)
        if value is None:
            continue
        value = float(value)
        if direction == "max":
            rate = value / budget if budget > 0 else BURN_CAP
        else:
            rate = budget / value if value > 0 else BURN_CAP
        out[key] = min(rate, BURN_CAP)
    return out
