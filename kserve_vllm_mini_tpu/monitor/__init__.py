"""Live run monitoring (docs/MONITORING.md).

One background sampler thread per benchmark run unifies the two views the
post-hoc pipeline previously kept separate — the runtime's ``/metrics``
exposition and the load generator's in-flight/completed/latency state —
into ``runs/<id>/timeline.jsonl`` at ~1 Hz, computes rolling-window SLO
burn-rates from the same budgets ``gates/slo.py`` gates on after the
fact, detects degradation events (stalls, queue runaway, throughput
collapse, duty-cycle drop, budget burn) and can raise an
:class:`AbortSignal` that the load generator and sweeps consume to
early-terminate hopeless configurations.
"""

from kserve_vllm_mini_tpu.monitor.burnrate import burn_rates, window_stats
from kserve_vllm_mini_tpu.monitor.events import AbortSignal, Event, EventDetector
from kserve_vllm_mini_tpu.monitor.sampler import MonitorConfig, RunMonitor

__all__ = [
    "AbortSignal",
    "Event",
    "EventDetector",
    "MonitorConfig",
    "RunMonitor",
    "burn_rates",
    "window_stats",
]
