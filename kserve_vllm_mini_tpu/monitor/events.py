"""Typed degradation events over the timeline stream + the abort hook.

The detector is pure host-side state over the sample dicts the sampler
produces (monitor/sampler.py) — no IO, no clock reads of its own — so
every rule is unit-testable from synthetic sample lists. Event taxonomy
and default thresholds are documented in docs/MONITORING.md; a rule only
ever fires once per run (a stalled run would otherwise emit one event
per sample and drown the log).
"""

from __future__ import annotations

import sys
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

EVENT_TYPES = (
    "throughput_collapse",
    "decode_stall",
    "prefill_stall",
    "handoff_stall",
    "queue_depth_runaway",
    "duty_cycle_drop",
    "burn_rate_exceeded",
    "kv_thrash",
    "hbm_watermark_high",
    "overload_shedding",
    "engine_fault",
    "replica_down",
    "cost_burn_exceeded",
    "replica_unprofitable",
)


class AbortSignal:
    """Thread-safe one-shot abort flag with a reason and callbacks.

    The monitor thread sets it; the load generator registers a callback
    that wakes its asyncio loop (loadgen/runner.py), and sweeps read the
    reason into the cell's results as ``aborted_early``. ``set`` is
    idempotent — the first reason wins, later calls are ignored.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._event = threading.Event()
        self._reason: Optional[str] = None
        self._callbacks: list[Callable[[], None]] = []

    def set(self, reason: str) -> None:
        with self._lock:
            if self._event.is_set():
                return
            self._reason = reason
            self._event.set()
            callbacks = list(self._callbacks)
        for cb in callbacks:
            try:
                cb()
            except Exception as e:  # noqa: BLE001 — notification is
                # best-effort: a dead listener (e.g. a load loop that
                # already finished) must not crash the monitor thread
                # mid-sample; the flag itself IS set either way
                print(f"monitor: abort callback failed: {e}", file=sys.stderr)

    def is_set(self) -> bool:
        return self._event.is_set()

    @property
    def reason(self) -> Optional[str]:
        # same lock the writer holds (KVM052): the monitor thread sets the
        # reason while sweeps/loadgen read it — without the lock a reader
        # could observe `_event` set but `_reason` still None
        with self._lock:
            return self._reason

    def on_set(self, callback: Callable[[], None]) -> None:
        """Register a callback fired when the signal is set. Fires
        immediately (in the caller's thread) if already set."""
        with self._lock:
            if not self._event.is_set():
                self._callbacks.append(callback)
                return
        callback()


@dataclass
class Event:
    """One detected degradation; serialized into timeline.jsonl and the
    results.json ``monitor`` block (core/schema.py validate_monitor)."""

    t: float
    type: str
    detail: str
    data: dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> dict[str, Any]:
        return {"t": self.t, "type": self.type, "detail": self.detail,
                "data": self.data}


def _runtime(sample: dict[str, Any], key: str) -> Optional[float]:
    v = (sample.get("runtime") or {}).get(key)
    return float(v) if v is not None else None


def _loadgen(sample: dict[str, Any], key: str) -> Optional[float]:
    v = (sample.get("loadgen") or {}).get(key)
    return float(v) if v is not None else None


class EventDetector:
    """Stateful rule evaluation over successive samples.

    ``observe(sample, burn)`` returns newly-fired events (each type at
    most once per run). Thresholds are constructor args so tests can
    hand-compute fixtures; defaults documented in docs/MONITORING.md.
    """

    def __init__(
        self,
        stall_samples: int = 5,
        prefill_stall_samples: int = 3,
        handoff_stall_samples: int = 3,
        queue_samples: int = 5,
        queue_depth_limit: float = 32.0,
        collapse_fraction: float = 0.3,
        duty_drop_fraction: float = 0.25,
        burn_threshold: float = 2.0,
        burn_samples: int = 3,
        warmup_s: float = 5.0,
        kv_thrash_rate: float = 4.0,
        kv_thrash_samples: int = 3,
        hbm_high_fraction: float = 0.92,
        replica_down_samples: int = 3,
        cost_budget_usd_per_1k_tok: Optional[float] = None,
        cost_burn_samples: int = 3,
        unprofitable_samples: int = 3,
    ) -> None:
        self.stall_samples = stall_samples
        self.prefill_stall_samples = prefill_stall_samples
        self.handoff_stall_samples = handoff_stall_samples
        self.queue_samples = queue_samples
        self.queue_depth_limit = queue_depth_limit
        self.collapse_fraction = collapse_fraction
        self.duty_drop_fraction = duty_drop_fraction
        self.burn_threshold = burn_threshold
        self.burn_samples = burn_samples
        self.warmup_s = warmup_s
        self.kv_thrash_rate = kv_thrash_rate
        self.kv_thrash_samples = kv_thrash_samples
        self.hbm_high_fraction = hbm_high_fraction
        self.replica_down_samples = replica_down_samples
        self.cost_budget_usd_per_1k_tok = cost_budget_usd_per_1k_tok
        self.cost_burn_samples = cost_burn_samples
        self.unprofitable_samples = unprofitable_samples
        self._fired: set[str] = set()
        self._t0: Optional[float] = None
        self._prev: Optional[dict[str, Any]] = None
        self._decode_progressed = False
        self._stall_run = 0
        self._prefill_stall_run = 0
        self._handoff_stall_run = 0
        self._queue_run = 0
        self._burn_run = 0
        self._thrash_run = 0
        self._replica_down_run = 0
        self._cost_burn_run = 0
        self._unprofitable_run = 0
        self._peak_throughput = 0.0
        self._peak_duty = 0.0

    # -- individual rules --------------------------------------------------

    def _check_decode_stall(self, sample: dict[str, Any]) -> Optional[Event]:
        """Engine counters frozen across N samples while requests are in
        flight: the decode loop STOPPED making progress (e.g. a wedged
        sweep) — wall-clock keeps burning with nothing to show. Armed
        only after progress has been observed at least once: a cold
        engine spends its first requests in XLA compile with the
        counters legitimately frozen at zero (and a server that never
        progresses at all shows up in the burn rates instead)."""
        inflight = _loadgen(sample, "inflight")
        steps = _runtime(sample, "decode_steps_total")
        prev = self._prev
        prev_steps = _runtime(prev, "decode_steps_total") if prev else None
        if steps is not None and prev_steps is not None and steps != prev_steps:
            self._decode_progressed = True
        sweeps = _runtime(sample, "pipelined_sweeps_total")
        if (
            prev is not None
            and self._decode_progressed
            and inflight
            and steps is not None
            and steps == prev_steps
            and (sweeps is None
                 or sweeps == _runtime(prev, "pipelined_sweeps_total"))
        ):
            self._stall_run += 1
        else:
            self._stall_run = 0
        if self._stall_run >= self.stall_samples:
            return Event(
                sample["t"], "decode_stall",
                f"no decode progress for {self._stall_run} consecutive "
                f"samples with {int(inflight)} request(s) in flight",
                {"samples": self._stall_run, "inflight": inflight},
            )
        return None

    def _check_prefill_stall(self, sample: dict[str, Any]) -> Optional[Event]:
        """Decode retire rate COLLAPSED while prefill work ADVANCED with
        decode requests in flight: the attribution decode_stall alone
        cannot make — the engine is not wedged, it is running a long
        monolithic prefill in front of every streaming client (docs/
        TROUBLESHOOTING.md "Long prompts stall streaming"; the
        prefill_chunk knob is the fix). Windowed: decode_steps_total
        frozen across N consecutive samples while prefills_total or
        prefill_chunks_total moved and >= 2 requests are in flight (the
        prefilling one plus at least one stalled decode). Armed only
        after decode progress has been observed once — the same cold-
        compile immunity rule as decode_stall (a cold engine's first
        prefill legitimately freezes the counters)."""
        prev = self._prev
        steps = _runtime(sample, "decode_steps_total")
        inflight = _loadgen(sample, "inflight")
        if prev is None or steps is None:
            return None
        prev_steps = _runtime(prev, "decode_steps_total")
        prefill_moved = False
        for key in ("prefills_total", "prefill_chunks_total"):
            cur, old = _runtime(sample, key), _runtime(prev, key)
            if cur is not None and old is not None and cur > old:
                prefill_moved = True
        if (
            self._decode_progressed
            and prefill_moved
            and inflight is not None
            and inflight >= 2
            and steps == prev_steps
        ):
            self._prefill_stall_run += 1
        else:
            self._prefill_stall_run = 0
        if self._prefill_stall_run >= self.prefill_stall_samples:
            return Event(
                sample["t"], "prefill_stall",
                f"decode retire rate collapsed for {self._prefill_stall_run} "
                f"consecutive samples while prefill advanced with "
                f"{int(inflight)} request(s) in flight — long prompts are "
                "stalling streaming (consider the prefill_chunk knob)",
                {"samples": self._prefill_stall_run, "inflight": inflight},
            )
        return None

    def _check_handoff_stall(self, sample: dict[str, Any]) -> Optional[Event]:
        """The prefill lane is FALLING BEHIND a healthy decode lane
        (docs/DISAGGREGATION.md): the handoff queue depth GREW across N
        consecutive samples while decode retires stayed live
        (decode_steps_total advancing). That attribution matters — a
        frozen decode counter is decode_stall's event; a growing handoff
        backlog with decode humming means prefill capacity, not the
        engine, is the bottleneck (more lane devices, or raise
        disagg_min_prompt so short prompts stop queueing behind long
        ones). Only disaggregated runtimes export the depth gauge, so
        the rule is inert everywhere else."""
        prev = self._prev
        depth = _runtime(sample, "kv_handoff_queue_depth")
        steps = _runtime(sample, "decode_steps_total")
        if prev is None or depth is None or steps is None:
            return None
        prev_depth = _runtime(prev, "kv_handoff_queue_depth")
        prev_steps = _runtime(prev, "decode_steps_total")
        if (
            prev_depth is not None
            and depth > prev_depth
            and prev_steps is not None
            and steps > prev_steps
        ):
            self._handoff_stall_run += 1
        else:
            self._handoff_stall_run = 0
        if self._handoff_stall_run >= self.handoff_stall_samples:
            return Event(
                sample["t"], "handoff_stall",
                f"prefill-lane handoff queue grew {self._handoff_stall_run} "
                f"consecutive samples to depth {depth:g} while decode "
                "stayed live — the prefill lane is saturated (add lane "
                "devices or raise disagg_min_prompt)",
                {"queue_depth": depth, "samples": self._handoff_stall_run},
            )
        return None

    def _check_queue_runaway(self, sample: dict[str, Any]) -> Optional[Event]:
        depth = _runtime(sample, "queue_depth")
        prev_depth = (
            _runtime(self._prev, "queue_depth") if self._prev else None
        )
        if (
            depth is not None
            and prev_depth is not None
            and depth > prev_depth
        ):
            self._queue_run += 1
        else:
            self._queue_run = 0
        if (
            depth is not None
            and depth >= self.queue_depth_limit
            and self._queue_run >= self.queue_samples
        ):
            return Event(
                sample["t"], "queue_depth_runaway",
                f"queue depth grew {self._queue_run} samples in a row to "
                f"{depth:g} (limit {self.queue_depth_limit:g})",
                {"queue_depth": depth, "samples": self._queue_run},
            )
        return None

    def _check_throughput_collapse(self, sample: dict[str, Any]) -> Optional[Event]:
        rps = _loadgen(sample, "window_throughput_rps")
        if rps is None or self._t0 is None:
            return None
        if sample["t"] - self._t0 < self.warmup_s:
            self._peak_throughput = max(self._peak_throughput, rps)
            return None
        inflight = _loadgen(sample, "inflight")
        if (
            self._peak_throughput > 0
            and inflight
            and rps < self.collapse_fraction * self._peak_throughput
        ):
            return Event(
                sample["t"], "throughput_collapse",
                f"window throughput {rps:.2f} rps fell below "
                f"{self.collapse_fraction:.0%} of peak "
                f"{self._peak_throughput:.2f} rps",
                {"window_throughput_rps": rps,
                 "peak_throughput_rps": self._peak_throughput},
            )
        self._peak_throughput = max(self._peak_throughput, rps)
        return None

    def _check_duty_drop(self, sample: dict[str, Any]) -> Optional[Event]:
        """Windowed duty cycle (delta busy-seconds / delta wall) collapsed
        while work was in flight. Needs the kvmini_tpu_busy_seconds_total
        counter — the cumulative duty gauge flattens mid-run dips."""
        prev = self._prev
        busy = _runtime(sample, "busy_seconds_total")
        if prev is None or busy is None:
            return None
        prev_busy = _runtime(prev, "busy_seconds_total")
        dt = sample["t"] - prev["t"]
        if prev_busy is None or dt <= 0:
            return None
        duty = max(min((busy - prev_busy) / dt, 1.0), 0.0)
        inflight = _loadgen(sample, "inflight")
        in_warmup = (
            self._t0 is not None and sample["t"] - self._t0 < self.warmup_s
        )
        if (
            not in_warmup
            and self._peak_duty > 0.05
            and inflight
            and duty < self.duty_drop_fraction * self._peak_duty
        ):
            return Event(
                sample["t"], "duty_cycle_drop",
                f"windowed duty cycle {duty:.3f} fell below "
                f"{self.duty_drop_fraction:.0%} of peak {self._peak_duty:.3f}",
                {"windowed_duty_cycle": duty, "peak_duty_cycle": self._peak_duty},
            )
        self._peak_duty = max(self._peak_duty, duty)
        return None

    def _check_kv_thrash(self, sample: dict[str, Any]) -> Optional[Event]:
        """Retained-pool eviction churn (docs/TROUBLESHOOTING.md "HBM
        pressure & KV thrash"): the windowed rate of the retained-LRU
        eviction counter stayed above threshold for N consecutive
        samples — the prefix cache is being torn down as fast as it is
        built, so every "hit" is paid for with a re-prefill elsewhere.
        Rate-based (delta/dt), not level-based: a large total after a
        long run is history, a sustained rate is live thrash."""
        prev = self._prev
        evictions = _runtime(sample, "kv_retained_evictions_total")
        if prev is None or evictions is None:
            return None
        prev_ev = _runtime(prev, "kv_retained_evictions_total")
        dt = sample["t"] - prev["t"]
        if prev_ev is None or dt <= 0:
            return None
        rate = max(evictions - prev_ev, 0.0) / dt
        if rate >= self.kv_thrash_rate:
            self._thrash_run += 1
        else:
            self._thrash_run = 0
        if self._thrash_run >= self.kv_thrash_samples:
            return Event(
                sample["t"], "kv_thrash",
                f"retained-block eviction churn {rate:.1f}/s >= "
                f"{self.kv_thrash_rate:g}/s for {self._thrash_run} "
                "consecutive samples",
                {"evictions_per_s": rate, "samples": self._thrash_run},
            )
        return None

    def _check_hbm_watermark(self, sample: dict[str, Any]) -> Optional[Event]:
        """HBM watermark crossed the high-water fraction of the device
        limit. Level-based and immediate — unlike churn, a watermark is
        not noisy, and by the time it is this close to the limit the
        next big prefill can RESOURCE_EXHAUST the run. The headroom
        guard admits at 90% of capacity, so the default 92% trigger
        means the plan's margin is already gone."""
        in_use = _runtime(sample, "hbm_bytes_in_use")
        limit = _runtime(sample, "hbm_bytes_limit")
        if in_use is None or not limit:
            return None
        frac = in_use / limit
        if frac >= self.hbm_high_fraction:
            return Event(
                sample["t"], "hbm_watermark_high",
                f"HBM in use {in_use / 1e9:.2f} GB is {frac:.0%} of the "
                f"{limit / 1e9:.2f} GB limit "
                f"(threshold {self.hbm_high_fraction:.0%})",
                {"hbm_bytes_in_use": in_use, "hbm_bytes_limit": limit,
                 "fraction": frac},
            )
        return None

    def _check_overload_shedding(self, sample: dict[str, Any]) -> Optional[Event]:
        """Live shedding observed (docs/RESILIENCE.md): the shed counter
        — the loadgen's (429s past the retry budget) or the runtime's
        (admission sheds) — INCREASED across a sample. Delta-based, not
        level-based: a historical total from an earlier burst is not
        live shedding. One-shot like every rule; the per-sample shed
        numbers stay on the timeline for the report."""
        prev = self._prev
        if prev is None:
            return None
        for src, key in (
            (_loadgen, "shed"),
            (_runtime, "requests_shed_total"),
        ):
            cur, old = src(sample, key), src(prev, key)
            if cur is not None and old is not None and cur > old:
                return Event(
                    sample["t"], "overload_shedding",
                    f"{cur - old:g} request(s) shed in the last sample "
                    f"window ({cur:g} total)",
                    {"shed_total": cur, "shed_delta": cur - old},
                )
        return None

    def _check_engine_fault(self, sample: dict[str, Any]) -> Optional[Event]:
        """The runtime recovered from an engine fault (watchdog trip or
        injected/classified device error, docs/RESILIENCE.md): the
        engine_faults counter moved. Immediate and delta-based — one
        fault is one event, there is no 'noise floor' for a failed
        batch."""
        prev = self._prev
        faults = _runtime(sample, "engine_faults_total")
        if prev is None or faults is None:
            return None
        prev_faults = _runtime(prev, "engine_faults_total")
        if prev_faults is None or faults <= prev_faults:
            return None
        level = _runtime(sample, "degrade_level")
        return Event(
            sample["t"], "engine_fault",
            f"engine fault recovered ({faults:g} total"
            + (f", degrade level {level:g})" if level is not None else ")"),
            {"engine_faults_total": faults,
             **({"degrade_level": level} if level is not None else {})},
        )

    def _check_replica_down(self, sample: dict[str, Any]) -> Optional[Event]:
        """The fleet is running BELOW its desired replica count for N
        consecutive samples (docs/FLEET.md): a replica died (or never
        came up) and the supervisor hasn't healed it yet. Level-based
        against the router's own desired gauge — unlike overload, a
        missing replica is a fact, not a rate. Only the fleet router
        exports the pair, so the rule is inert everywhere else."""
        live = _runtime(sample, "fleet_replicas_live")
        desired = _runtime(sample, "fleet_replicas_desired")
        if live is None or desired is None:
            return None
        if live < desired:
            self._replica_down_run += 1
        else:
            self._replica_down_run = 0
        if self._replica_down_run >= self.replica_down_samples:
            return Event(
                sample["t"], "replica_down",
                f"fleet at {live:g}/{desired:g} replicas for "
                f"{self._replica_down_run} consecutive samples — a "
                "replica is down and not yet healed",
                {"replicas_live": live, "replicas_desired": desired,
                 "samples": self._replica_down_run},
            )
        return None

    def _check_cost_burn(self, sample: dict[str, Any]) -> Optional[Event]:
        """The live $/1K-tok gauge (kvmini_tpu_econ_usd_per_1k_tokens,
        docs/ECONOMICS.md) stayed over the --cost-budget-usd-per-1k-tok
        budget for N consecutive samples. Rides the burn-rate machinery
        (monitor/burnrate.burn_rates with the cost_per_1k_tokens_max
        rule — including its capped-at-BURN_CAP zero-budget contract)
        rather than re-deriving the normalization; burn > 1.0 is the
        out-of-budget line. Inert without a budget, inert on engines
        that don't export the rail (no gauge -> no fabricated verdict),
        and warmup-immune like burn_rate_exceeded — cold-start windows
        price the first tokens absurdly high by construction."""
        if self.cost_budget_usd_per_1k_tok is None:
            return None
        if (
            self._t0 is not None
            and sample["t"] - self._t0 < self.warmup_s
        ):
            self._cost_burn_run = 0
            return None
        cost = _runtime(sample, "econ_usd_per_1k_tokens")
        if cost is None:
            self._cost_burn_run = 0
            return None
        from kserve_vllm_mini_tpu.monitor.burnrate import burn_rates

        rate = burn_rates(
            {"cost_per_1k_tokens": cost},
            {"cost_per_1k_tokens_max": self.cost_budget_usd_per_1k_tok},
        ).get("cost_per_1k_tokens_max", 0.0)
        if rate > 1.0:
            self._cost_burn_run += 1
        else:
            self._cost_burn_run = 0
        if self._cost_burn_run >= self.cost_burn_samples:
            return Event(
                sample["t"], "cost_burn_exceeded",
                f"windowed cost ${cost:.6f}/1K-tok is {rate:.2f}x the "
                f"${self.cost_budget_usd_per_1k_tok:g}/1K-tok budget for "
                f"{self._cost_burn_run} consecutive samples",
                {"usd_per_1k_tokens": cost, "burn_rate": rate,
                 "budget_usd_per_1k_tok": self.cost_budget_usd_per_1k_tok,
                 "samples": self._cost_burn_run},
            )
        return None

    def _check_replica_unprofitable(
        self, sample: dict[str, Any]
    ) -> Optional[Event]:
        """The fleet's MARGINAL replica stopped paying for itself for N
        consecutive windows (docs/ECONOMICS.md): the router's marginal-
        replica gauge — the least-productive healthy replica's hourly
        price spread over its own token output — stayed above the
        $/1K-tok budget while the fleet held >= 2 live replicas. At the
        budget price, that replica's token contribution is worth less
        than its hour costs, so the fleet is over-provisioned; the
        cost-aware autoscaler (autoscale/controller.py) acts on the same
        comparison. Gated on >= 2 live replicas — the LAST replica is
        never 'unprofitable', scaling to zero is an availability
        decision this monitor must not suggest. Only the fleet router
        exports the gauge, so the rule is inert everywhere else."""
        if self.cost_budget_usd_per_1k_tok is None:
            return None
        marginal = _runtime(
            sample, "econ_marginal_replica_usd_per_1k_tokens"
        )
        live = _runtime(sample, "fleet_replicas_live")
        if marginal is None or live is None or live < 2:
            self._unprofitable_run = 0
            return None
        if marginal > self.cost_budget_usd_per_1k_tok:
            self._unprofitable_run += 1
        else:
            self._unprofitable_run = 0
        if self._unprofitable_run >= self.unprofitable_samples:
            return Event(
                sample["t"], "replica_unprofitable",
                f"marginal replica at ${marginal:.6f}/1K-tok > the "
                f"${self.cost_budget_usd_per_1k_tok:g}/1K-tok budget for "
                f"{self._unprofitable_run} consecutive samples with "
                f"{live:g} replicas live — the fleet is over-provisioned",
                {"marginal_replica_usd_per_1k_tokens": marginal,
                 "budget_usd_per_1k_tok": self.cost_budget_usd_per_1k_tok,
                 "replicas_live": live,
                 "samples": self._unprofitable_run},
            )
        return None

    def _check_burn_rate(
        self, sample: dict[str, Any], burn: dict[str, float]
    ) -> Optional[Event]:
        if (
            self._t0 is not None
            and sample["t"] - self._t0 < self.warmup_s
        ):
            # startup transients (partially-filled windows, first cold
            # requests) must not abort a run in its first seconds
            self._burn_run = 0
            return None
        over = {k: v for k, v in burn.items() if v > self.burn_threshold}
        if over:
            self._burn_run += 1
        else:
            self._burn_run = 0
        if self._burn_run >= self.burn_samples:
            worst = max(over, key=lambda k: over[k])
            return Event(
                sample["t"], "burn_rate_exceeded",
                f"{worst} burn rate {over[worst]:.2f} > "
                f"{self.burn_threshold:g} for {self._burn_run} consecutive "
                "samples",
                {"burn_rates": over, "samples": self._burn_run},
            )
        return None

    # -- driver ------------------------------------------------------------

    def observe(
        self, sample: dict[str, Any], burn: Optional[dict[str, float]] = None
    ) -> list[Event]:
        if self._t0 is None:
            self._t0 = float(sample["t"])
        checks: list[tuple[str, Optional[Event]]] = [
            ("decode_stall", self._check_decode_stall(sample)),
            ("prefill_stall", self._check_prefill_stall(sample)),
            ("handoff_stall", self._check_handoff_stall(sample)),
            ("queue_depth_runaway", self._check_queue_runaway(sample)),
            ("throughput_collapse", self._check_throughput_collapse(sample)),
            ("duty_cycle_drop", self._check_duty_drop(sample)),
            ("burn_rate_exceeded", self._check_burn_rate(sample, burn or {})),
            ("kv_thrash", self._check_kv_thrash(sample)),
            ("hbm_watermark_high", self._check_hbm_watermark(sample)),
            ("overload_shedding", self._check_overload_shedding(sample)),
            ("engine_fault", self._check_engine_fault(sample)),
            ("replica_down", self._check_replica_down(sample)),
            ("cost_burn_exceeded", self._check_cost_burn(sample)),
            ("replica_unprofitable",
             self._check_replica_unprofitable(sample)),
        ]
        self._prev = sample
        fired: list[Event] = []
        for etype, evt in checks:
            if evt is not None and etype not in self._fired:
                self._fired.add(etype)
                # stamp the trace ids in flight at detection time into
                # the payload (docs/MONITORING.md `inflight_trace_ids`
                # data field): the event becomes clickable into the
                # merged traces.json — which requests a replica_down or
                # handoff_stall actually caught mid-flight
                ids = sample.get("inflight_trace_ids")
                if ids:
                    evt.data["inflight_trace_ids"] = list(ids)
                fired.append(evt)
        return fired
