"""GA-hardening reference matrix (reference scripts/reference_runner.py)."""
