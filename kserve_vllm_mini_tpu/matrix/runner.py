"""Reference matrix runner: the GA-hardening acceptance grid.

Reference behavior (scripts/reference_runner.py): run a hardware × model ×
traffic matrix (:321-349), validate each cell against acceptance thresholds
(:281-312), generate a BOM.md of everything in play (:65-110, k8s/KServe
versions :114-137), and write matrix_summary.json (:351-390) plus optionally
signed bundles. Configured by a YAML sheet (reference-matrix.yaml analog:
``tpu-matrix.yaml``).

TPU translation: the hardware axis is topology slices (v5e-1/-4/-8, v5p-…)
instead of GPU SKUs; expected-throughput baselines are tokens/sec/chip; and
the BOM captures JAX/libtpu versions, which determine XLA codegen, where
the reference captured driver/CUDA versions.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path
from typing import Any, Callable, Optional

import yaml

DEFAULT_MATRIX: dict[str, Any] = {
    # acceptance thresholds (reference-matrix.yaml:52-57)
    "thresholds": {
        "p95_variance_pct": 10.0,       # p95 within ±10% of expectation
        "error_rate_max": 0.01,
        "cold_multiplier_max": 3.0,
        "throughput_min_rps": 5.0,
    },
    "topologies": [
        {"name": "v5e-8", "expected_tokens_per_sec_per_chip": 2000.0},
    ],
    "models": [
        {"name": "llama-tiny", "expected_p95_ms": 2000.0},
    ],
    "traffic": [
        {"pattern": "steady", "requests": 100, "concurrency": 10, "p95_budget_ms": 2000.0},
        {"pattern": "bursty", "requests": 100, "concurrency": 20, "p95_budget_ms": 3000.0},
    ],
}

# cell bench function: merged cell config -> flat results dict
CellBenchFn = Callable[[dict[str, Any]], dict[str, Any]]


def validate_cell(
    results: dict[str, Any], cell: dict[str, Any], thresholds: dict[str, Any]
) -> list[str]:
    """Threshold validation (reference_runner.py:281-312). Returns failure
    strings; empty means the cell is accepted."""
    failures: list[str] = []

    p95 = results.get("p95_ms")
    budget = cell.get("p95_budget_ms") or cell.get("expected_p95_ms")
    if p95 is None:
        failures.append("p95_ms missing from results")
    elif budget:
        limit = budget * (1 + thresholds.get("p95_variance_pct", 10.0) / 100.0)
        if p95 > limit:
            failures.append(f"p95 {p95:.0f}ms > {limit:.0f}ms (budget {budget:.0f} ±var)")

    err = results.get("error_rate")
    if err is None:
        failures.append("error_rate missing from results")
    elif err > thresholds.get("error_rate_max", 0.01):
        failures.append(f"error_rate {err:.3f} > {thresholds['error_rate_max']}")

    cold = results.get("cold_multiplier")
    if cold is not None and cold > thresholds.get("cold_multiplier_max", 3.0):
        failures.append(f"cold_multiplier {cold:.1f} > {thresholds['cold_multiplier_max']}")

    rps = results.get("throughput_rps")
    if rps is not None and rps < thresholds.get("throughput_min_rps", 0.0):
        failures.append(f"throughput {rps:.1f} rps < {thresholds['throughput_min_rps']}")

    expected_tps = cell.get("expected_tokens_per_sec_per_chip")
    tps = results.get("tokens_per_sec_per_chip")
    if expected_tps and tps is not None and tps < 0.9 * expected_tps:
        failures.append(
            f"tokens/sec/chip {tps:.0f} < 90% of expected {expected_tps:.0f}"
        )
    return failures


def render_bom(facts: dict[str, Any], matrix: dict[str, Any]) -> str:
    """BOM.md: everything that defines the run (reference_runner.py:65-110)."""
    git = facts.get("git", {})
    local = facts.get("local", {})
    cluster = facts.get("cluster", {})
    lines = [
        "# Bill of Materials — reference matrix run",
        "",
        "## Harness",
        f"- commit: {git.get('commit', 'unknown')}{' (dirty)' if git.get('dirty') else ''}",
        f"- python: {local.get('python')}  platform: {local.get('platform')}",
        "",
        "## Runtime stack",
        f"- jax: {local.get('jax_version')}  jaxlib: {local.get('jaxlib_version')}",
        f"- devices: {json.dumps(local.get('devices', []))}",
        "",
        "## Cluster",
        f"- reachable: {cluster.get('reachable', False)}",
        f"- kserve: {cluster.get('kserve_image')}",
        f"- knative: {cluster.get('knative_image')}",
        f"- tpu nodes: {len(cluster.get('tpu_nodes', []))}",
        "",
        "## Matrix",
        f"- topologies: {[t['name'] for t in matrix['topologies']]}",
        f"- models: {[m['name'] for m in matrix['models']]}",
        f"- traffic: {[t['pattern'] for t in matrix['traffic']]}",
        f"- thresholds: {json.dumps(matrix['thresholds'])}",
    ]
    return "\n".join(lines) + "\n"


def run_matrix(
    matrix: dict[str, Any],
    bench_fn: CellBenchFn,
    out_dir: Path,
    facts: Optional[dict[str, Any]] = None,
) -> dict[str, Any]:
    """Execute every (topology, model, traffic) cell; write BOM.md +
    matrix_summary.json; return the summary."""
    out_dir.mkdir(parents=True, exist_ok=True)
    thresholds = matrix.get("thresholds", DEFAULT_MATRIX["thresholds"])
    if facts is None:
        from kserve_vllm_mini_tpu.provenance.facts import collect_facts

        facts = collect_facts(include_cluster=False)
    (out_dir / "BOM.md").write_text(render_bom(facts, matrix))

    cells = []
    for topo in matrix["topologies"]:
        for model in matrix["models"]:
            for traffic in matrix["traffic"]:
                cell = {**topo, **model, **traffic}
                cell_id = f"{topo['name']}/{model['name']}/{traffic['pattern']}"
                print(f"matrix: {cell_id}", file=sys.stderr)
                t0 = time.time()
                entry: dict[str, Any] = {
                    "cell": cell_id,
                    "topology": topo["name"],
                    "model": model["name"],
                    "pattern": traffic["pattern"],
                }
                try:
                    results = bench_fn(dict(cell))
                    failures = validate_cell(results, cell, thresholds)
                    entry["results"] = {
                        k: results.get(k)
                        for k in ("p95_ms", "ttft_p95_ms", "throughput_rps",
                                  "tokens_per_sec", "tokens_per_sec_per_chip",
                                  "error_rate", "cold_multiplier")
                    }
                    entry["failures"] = failures
                    entry["accepted"] = not failures
                except Exception as e:  # noqa: BLE001 — record-and-continue
                    entry["failures"] = [f"bench error: {type(e).__name__}: {e}"]
                    entry["accepted"] = False
                entry["elapsed_s"] = round(time.time() - t0, 1)
                cells.append(entry)

    summary = {
        "schema": "kvmini-tpu/matrix/v1",
        "cells": cells,
        "accepted": sum(1 for c in cells if c["accepted"]),
        "total": len(cells),
        "all_accepted": all(c["accepted"] for c in cells),
        "thresholds": thresholds,
    }
    with (out_dir / "matrix_summary.json").open("w") as f:
        json.dump(summary, f, indent=2)
    return summary


def default_cell_bench(url: Optional[str]) -> CellBenchFn:
    """Bench a cell via the standard pipeline (self-serve when no URL)."""

    def bench(cell: dict[str, Any]) -> dict[str, Any]:
        from kserve_vllm_mini_tpu.bench_pipeline import run_bench

        profile = {
            "model": cell["name"] if "llama" in str(cell.get("name")) else "llama-tiny",
            "requests": cell.get("requests", 100),
            "concurrency": cell.get("concurrency", 10),
            "pattern": cell.get("pattern", "steady"),
        }
        results, code = run_bench(url=url, profile=profile, self_serve=not url)
        if not results:
            raise RuntimeError(f"bench exit {code}")
        return results

    return bench


# -- CLI ---------------------------------------------------------------------

def register(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("--config", default=None, help="tpu-matrix.yaml (defaults inline)")
    parser.add_argument("--url", default=None, help="Endpoint (self-serve if unset)")
    parser.add_argument("--output-dir", default="matrix_results")


def run(args: argparse.Namespace) -> int:
    matrix = DEFAULT_MATRIX
    if args.config:
        with open(args.config) as f:
            matrix = yaml.safe_load(f)
    summary = run_matrix(
        matrix, default_cell_bench(args.url), Path(args.output_dir)
    )
    for c in summary["cells"]:
        mark = "PASS" if c["accepted"] else "FAIL"
        detail = "" if c["accepted"] else " — " + "; ".join(c["failures"])
        print(f"[{mark}] {c['cell']}{detail}")
    print(f"matrix: {summary['accepted']}/{summary['total']} cells accepted")
    return 0 if summary["all_accepted"] else 1
