"""Llama-family decoder in pure functional JAX.

Design (TPU-first, not a port — the reference has no model code at all):

- parameters are a pytree of stacked per-layer arrays with a leading
  ``n_layers`` axis, walked with ``lax.scan`` so an 80-layer 70B compiles to
  one rolled loop instead of 80 unrolled blocks;
- one ``forward`` covers prefill and decode: the KV cache is a static-shape
  [L, B, KVH, S, D] pair written at per-slot offsets (decode-state slots are
  pre-allocated; XLA never sees a dynamic shape);
- attention masking is positional: query at absolute position p attends cache
  slot j iff j <= p, which subsumes causal prefill, chunked prefill, and
  decode against ragged slot fills in one formulation;
- bf16 params/activations feed the MXU; softmax/norm accumulate f32.

Weight layout matches HF Llama naming via models/loader.py so real
checkpoints (Llama-3.1-8B etc., BASELINE.json configs[1-4]) drop in.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from kserve_vllm_mini_tpu.models.config import ModelConfig
from kserve_vllm_mini_tpu.ops.attention import attention
from kserve_vllm_mini_tpu.ops.quant import linear
from kserve_vllm_mini_tpu.ops.rmsnorm import layer_norm, rms_norm
from kserve_vllm_mini_tpu.ops.rope import apply_rope, rope_frequencies

Params = dict[str, Any]
# {"k": [L,B,KVH,S,D], "v": [L,B,KVH,S,D]} — plus, when int8-quantized,
# per-position scales {"k_s": [L,B,KVH,S], "v_s": [L,B,KVH,S]} (presence of
# "k_s" is the static flag that selects the quantized cache path)
KVCache = dict[str, jnp.ndarray]

# Test hook: True/False forces the Pallas paged-decode kernel on/off
# regardless of backend (None = auto: kernel on TPU, gather oracle
# elsewhere). See run_cached_layers' use_paged_kernel.
_FORCE_PAGED_KERNEL: Optional[bool] = None

# Same hook for the DENSE int8-KV decode kernel (ops/paged_attention.py
# dense_decode_attention): None = auto (kernel on TPU, the eager
# dequantize-on-read oracle elsewhere). See use_dense_kernel.
_FORCE_DENSE_KERNEL: Optional[bool] = None

# Same hook for the int8-KV CACHED-PREFILL kernel (ops/flash_attention.py
# cached_prefill_attention — continuation chunks attending the cache):
# None = auto (kernel on TPU, the eager dequantize-on-read oracle
# elsewhere). See use_chunk_kernel.
_FORCE_CHUNK_KERNEL: Optional[bool] = None


def _stacked_weight_shapes(cfg: ModelConfig) -> dict[str, tuple[int, ...]]:
    """Per-layer shape of every stacked transformer matmul weight (last two
    axes are [in, out]; MoE expert weights carry a leading expert axis), in a
    fixed order shared by the bf16 and quantized initializers (the order
    defines which RNG key each weight gets, so the two inits draw identical
    values)."""
    hd, kvd = cfg.head_dim, cfg.n_kv_heads * cfg.head_dim
    shapes: dict[str, tuple[int, ...]] = {
        "wq": (cfg.d_model, cfg.n_heads * hd),
        "wk": (cfg.d_model, kvd),
        "wv": (cfg.d_model, kvd),
        "wo": (cfg.n_heads * hd, cfg.d_model),
    }
    if cfg.block == "phi":
        # phi MLP is fc1/fc2 (up/down) with GELU — no gate projection
        shapes.update({
            "w_up": (cfg.d_model, cfg.d_ff),
            "w_down": (cfg.d_ff, cfg.d_model),
        })
        return shapes
    if cfg.is_moe:
        shapes.update({
            "w_gate": (cfg.n_experts, cfg.d_model, cfg.d_ff),
            "w_up": (cfg.n_experts, cfg.d_model, cfg.d_ff),
            "w_down": (cfg.n_experts, cfg.d_ff, cfg.d_model),
            "router": (cfg.d_model, cfg.n_experts),
        })
    else:
        shapes.update({
            "w_gate": (cfg.d_model, cfg.d_ff),
            "w_up": (cfg.d_model, cfg.d_ff),
            "w_down": (cfg.d_ff, cfg.d_model),
        })
    return shapes


def _init_keys(rng: jax.Array, cfg: ModelConfig) -> dict[str, jax.Array]:
    keys = jax.random.split(rng, 11)
    named = {"embed": keys[0], "lm_head": keys[8], "router": keys[9]}
    for i, name in enumerate(n for n in _stacked_weight_shapes(cfg) if n != "router"):
        named[name] = keys[1 + i]
    return named


def _nrm(key: jax.Array, shape: tuple, dt) -> jnp.ndarray:
    return (jax.random.normal(key, shape, dtype=jnp.float32) * 0.02).astype(dt)


def _init_impl(rng: jax.Array, cfg: ModelConfig, leaf_fn) -> Params:
    """Shared init skeleton. ``leaf_fn(w)`` maps each per-layer bf16 matmul
    weight to its stored leaf inside the per-layer scan — identity for the
    bf16 tree, quantize for the int8 tree. One implementation keeps the
    key-for-key RNG order identical between the two inits (the invariant
    the equivalence oracle in tests/test_quant.py rests on)."""
    dt = cfg.jnp_dtype
    keys = _init_keys(rng, cfg)
    L = cfg.n_layers

    layers: Params = {"attn_norm": jnp.ones((L, cfg.d_model), dtype=dt)}
    if cfg.block == "phi":
        # one LayerNorm (weight + bias) feeds both branches; biased o/fc
        layers["attn_norm_b"] = jnp.zeros((L, cfg.d_model), dtype=dt)
        layers["bo"] = jnp.zeros((L, cfg.d_model), dtype=dt)
        layers["b_up"] = jnp.zeros((L, cfg.d_ff), dtype=dt)
        layers["b_down"] = jnp.zeros((L, cfg.d_model), dtype=dt)
    elif cfg.block == "gemma2":
        # gemma norm weights are OFFSETS (applied as 1+w, the HF storage
        # convention), so identity init is zeros; four norms per layer
        # (sandwich: post-norms on both branches before their residuals)
        layers["attn_norm"] = jnp.zeros((L, cfg.d_model), dtype=dt)
        layers["post_attn_norm"] = jnp.zeros((L, cfg.d_model), dtype=dt)
        layers["mlp_norm"] = jnp.zeros((L, cfg.d_model), dtype=dt)
        layers["post_mlp_norm"] = jnp.zeros((L, cfg.d_model), dtype=dt)
    else:
        layers["mlp_norm"] = jnp.ones((L, cfg.d_model), dtype=dt)
    for name, shape in _stacked_weight_shapes(cfg).items():
        lkeys = jax.random.split(keys[name], L)
        # the router is accuracy-critical and noise-level bytes — it stays
        # full precision even in the int8 tree (models/moe.py contract)
        fn = leaf_fn if name != "router" else (lambda w: w)

        def body(_, k, s=shape, f=fn):
            return None, f(_nrm(k, s, dt))

        _, layers[name] = jax.lax.scan(body, None, lkeys)
    if cfg.attn_bias:
        hd, kvd = cfg.head_dim, cfg.n_kv_heads * cfg.head_dim
        layers["bq"] = jnp.zeros((L, cfg.n_heads * hd), dtype=dt)
        layers["bk"] = jnp.zeros((L, kvd), dtype=dt)
        layers["bv"] = jnp.zeros((L, kvd), dtype=dt)

    params: Params = {
        "embed": _nrm(keys["embed"], (cfg.vocab_size, cfg.d_model), dt),
        "layers": layers,
        "final_norm": (
            jnp.zeros((cfg.d_model,), dtype=dt) if cfg.block == "gemma2"
            else jnp.ones((cfg.d_model,), dtype=dt)
        ),
    }
    if cfg.block == "phi":
        params["final_norm_b"] = jnp.zeros((cfg.d_model,), dtype=dt)
        params["lm_head_b"] = jnp.zeros((cfg.vocab_size,), dtype=dt)
    if not cfg.tie_embeddings:
        params["lm_head"] = _nrm(keys["lm_head"], (cfg.vocab_size, cfg.d_model), dt)
    return params


def init_params(rng: jax.Array, cfg: ModelConfig) -> Params:
    """Random-normal init (0.02 std), bf16 — for tests, benches, and as the
    target pytree structure for checkpoint loading.

    Stacked weights are drawn layer-by-layer from per-layer keys (a
    ``lax.scan`` over ``jax.random.split(key, L)``) so
    ``init_params_quantized`` can draw the exact same values one layer at a
    time without ever materializing the full-precision stack."""
    return _init_impl(rng, cfg, lambda w: w)


def init_params_quantized(rng: jax.Array, cfg: ModelConfig, bits: int = 8) -> Params:
    """Random init straight into int8 (or int4) leaves, one layer at a time.

    Fixes the round-2 flagship failure (VERDICT.md Weak #1): materializing
    the 8B bf16 tree first needs ~16 GB — the whole v5e HBM — before
    quantization can even start. Here each stacked matmul weight is drawn
    per layer inside a ``lax.scan`` and quantized immediately, so the peak
    transient is ONE layer's f32 weight (~1 GB for 8B) on top of the int8
    output. Equal to ``quantize_params(init_params(rng, cfg))`` to within
    one quantization LSB (same per-layer keys, same per-output-channel
    scale math — oracle-tested on llama-tiny in tests/test_quant.py)."""
    from kserve_vllm_mini_tpu.ops.quant import quantize_weight

    def leaf_fn(w):
        # the barrier materializes the layer's true bf16 values before
        # quantize_weight reads them back in f32 — without it XLA fuses
        # the bf16 cast into the quantize math and rounds at a different
        # boundary than quantize-after-init (±1 LSB drift)
        return quantize_weight(jax.lax.optimization_barrier(w), bits=bits)

    return _init_impl(rng, cfg, leaf_fn)


def init_kv_cache(
    cfg: ModelConfig,
    batch: int,
    max_seq: Optional[int] = None,
    dtype: Optional[Any] = None,
    quantized: bool = False,
) -> KVCache:
    """``quantized=True`` -> int8 cache with per-(position, head) f32
    scales: (D+4)/(2D) of the bf16 cache's HBM footprint (~52% at D=128)
    and the same factor off the bytes streamed per decode step (the KV
    read is the second-largest stream after the weights). Values are quantized on write with
    a per-position amax scale — reference analog: the kv-cache-dtype knob
    the quantization sweep measures (sweeps/quantization_sweep.py:40-234)."""
    s = max_seq or cfg.max_seq_len
    shape = (cfg.n_layers, batch, cfg.n_kv_heads, s, cfg.head_dim)
    if quantized:
        return {
            "k": jnp.zeros(shape, dtype=jnp.int8),
            "v": jnp.zeros(shape, dtype=jnp.int8),
            "k_s": jnp.zeros(shape[:-1], dtype=jnp.float32),
            "v_s": jnp.zeros(shape[:-1], dtype=jnp.float32),
        }
    return {
        "k": jnp.zeros(shape, dtype=dtype or cfg.jnp_dtype),
        "v": jnp.zeros(shape, dtype=dtype or cfg.jnp_dtype),
    }


def init_paged_kv_cache(
    cfg: ModelConfig,
    n_blocks: int,
    block_size: int = 64,
    dtype: Optional[Any] = None,
    quantized: bool = False,
) -> KVCache:
    """Block-pool KV cache for paged attention (the TPU answer to vLLM's
    PagedAttention, the reference stack's namesake mechanism).

    Layout: ``k``/``v`` are [L, P, KVH, BLK, D] pools of P blocks of BLK
    token positions each; a request owns an ordered list of block ids (its
    block table) instead of a private [max_seq] stripe. Dense serving must
    reserve slots x max_seq positions up front — 64 slots x 4096 max_seq
    of 8B bf16 KV is 34 GB, unservable on a 16 GB v5e — while the pool is
    sized by TOKENS IN FLIGHT (admission reserves worst-case
    ceil((prompt+max_new)/BLK) blocks per request), so long max_model_len
    stops multiplying across slots.

    Same dict contract as ``init_kv_cache`` (`k`/`v` [+ `k_s`/`v_s` int8
    scales]); the rank-5 value layout moves the slot axis to a block axis.
    Consumed by ``forward(..., block_table=...)``.
    """
    shape = (cfg.n_layers, n_blocks, cfg.n_kv_heads, block_size, cfg.head_dim)
    if quantized:
        return {
            "k": jnp.zeros(shape, dtype=jnp.int8),
            "v": jnp.zeros(shape, dtype=jnp.int8),
            "k_s": jnp.zeros(shape[:-1], dtype=jnp.float32),
            "v_s": jnp.zeros(shape[:-1], dtype=jnp.float32),
        }
    return {
        "k": jnp.zeros(shape, dtype=dtype or cfg.jnp_dtype),
        "v": jnp.zeros(shape, dtype=dtype or cfg.jnp_dtype),
    }


def slice_cache_slots(cache: KVCache, slot, n: int = 1) -> KVCache:
    """Sub-cache for slots [slot, slot+n) — slot axis is dim 1 on every
    leaf (value tensors are rank-5, scale tensors rank-4)."""
    out = {}
    for key, arr in cache.items():
        starts = (0, slot) + (0,) * (arr.ndim - 2)
        sizes = (arr.shape[0], n) + arr.shape[2:]
        out[key] = jax.lax.dynamic_slice(arr, starts, sizes)
    return out


def update_cache_slots(cache: KVCache, sub: KVCache, slot) -> KVCache:
    """Write a sub-cache back at ``slot`` (inverse of slice_cache_slots)."""
    return {
        key: jax.lax.dynamic_update_slice(
            arr, sub[key], (0, slot) + (0,) * (arr.ndim - 2)
        )
        for key, arr in cache.items()
    }


def _quantize_kv_block(x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """[B,KVH,T,D] -> (int8 values, f32 per-position scales [B,KVH,T])."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(
        jnp.round(x.astype(jnp.float32) / scale[..., None]), -127, 127
    ).astype(jnp.int8)
    return q, scale




def qkv_proj(
    p: Params,
    cfg: ModelConfig,
    h: jnp.ndarray,              # [B, T, D] (already attn-normed)
    positions: jnp.ndarray,      # [B, T]
    cos: jnp.ndarray,
    sin: jnp.ndarray,
    lora: Optional[Params] = None,       # one layer's adapter bank slices
    lora_ids: Optional[jnp.ndarray] = None,  # [B] adapter index per row
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """QKV projections + RoPE -> (q [B,H,T,hd], k [B,KVH,T,hd], v). The one
    implementation every execution path (scan-rolled, cached, pipelined)
    shares. With ``lora``/``lora_ids`` each row adds its adapter's low-rank
    delta (ops/lora.py; index 0 = base)."""
    from kserve_vllm_mini_tpu.ops.lora import adapted_linear

    B, T, _ = h.shape
    qm = cfg.quant_mode
    q = adapted_linear(h, p["wq"], lora, "wq", lora_ids, mode=qm)
    k = adapted_linear(h, p["wk"], lora, "wk", lora_ids, mode=qm)
    v = adapted_linear(h, p["wv"], lora, "wv", lora_ids, mode=qm)
    if cfg.attn_bias:
        q, k, v = q + p["bq"], k + p["bk"], v + p["bv"]
    q = q.reshape(B, T, cfg.n_heads, cfg.head_dim).transpose(0, 2, 1, 3)
    k = k.reshape(B, T, cfg.n_kv_heads, cfg.head_dim).transpose(0, 2, 1, 3)
    v = v.reshape(B, T, cfg.n_kv_heads, cfg.head_dim).transpose(0, 2, 1, 3)
    rd = cfg.rotary_dim
    if rd < cfg.head_dim:
        # phi-style partial rotary: RoPE on the first rotary_dim dims, the
        # rest pass through (cos/sin tables are built at rotary_dim width)
        q = jnp.concatenate(
            [apply_rope(q[..., :rd], positions, cos, sin), q[..., rd:]], axis=-1
        )
        k = jnp.concatenate(
            [apply_rope(k[..., :rd], positions, cos, sin), k[..., rd:]], axis=-1
        )
        return q, k, v
    return apply_rope(q, positions, cos, sin), apply_rope(k, positions, cos, sin), v


def embed_tokens(params: Params, cfg: ModelConfig, tokens: jnp.ndarray) -> jnp.ndarray:
    """Embedding lookup + family-specific input transform. EVERY execution
    path (forward, pipeline trainer, serving-pp executor) must enter the
    layer stack through this helper — gemma scales embeddings by
    sqrt(d_model), and an executor that skips it produces silently-wrong
    activations ~sqrt(d_model)x too small."""
    x = params["embed"][tokens]
    if cfg.block == "gemma2":
        # computed in the model dtype, matching the published
        # implementation's bf16 rounding
        x = x * jnp.asarray(cfg.d_model ** 0.5, dtype=cfg.jnp_dtype)
    return x


def final_logits(params: Params, cfg: ModelConfig, x: jnp.ndarray) -> jnp.ndarray:
    """Final norm + LM head + family epilogues (phi bias, gemma (1+w) norm
    and logit soft-capping), shared by every execution path — the exit
    twin of ``embed_tokens``. Returns f32 logits."""
    if cfg.block == "phi":
        x = layer_norm(x, params["final_norm"], params["final_norm_b"], cfg.rms_eps)
    elif cfg.block == "gemma2":
        x = rms_norm(x, 1.0 + params["final_norm"].astype(jnp.float32), cfg.rms_eps)
    else:
        x = rms_norm(x, params["final_norm"], cfg.rms_eps)
    head = params["embed"] if cfg.tie_embeddings else params["lm_head"]
    logits = (x @ head.T).astype(jnp.float32)
    if cfg.block == "phi":
        logits = logits + params["lm_head_b"].astype(jnp.float32)
    if cfg.final_softcap is not None:
        logits = jnp.tanh(logits / cfg.final_softcap) * cfg.final_softcap
    return logits


def block_norm(p: Params, cfg: ModelConfig, x: jnp.ndarray, name: str) -> jnp.ndarray:
    """The block's norm: RMSNorm (llama family), biased LayerNorm (phi),
    or (1+w)-weighted RMSNorm (gemma — weights stored as offsets)."""
    if cfg.block == "phi":
        return layer_norm(x, p[name], p[name + "_b"], cfg.rms_eps)
    if cfg.block == "gemma2":
        return rms_norm(x, 1.0 + p[name].astype(jnp.float32), cfg.rms_eps)
    return rms_norm(x, p[name], cfg.rms_eps)


def attn_scale_softcap(cfg: ModelConfig) -> tuple[float, Optional[float]]:
    """(attention scale, attention-logit softcap) for every attention call
    site — gemma scales by query_pre_attn_scalar and tanh-caps the scores;
    everyone else uses the standard 1/sqrt(head_dim) with no cap."""
    denom = cfg.query_pre_attn_scalar or float(cfg.head_dim)
    return denom ** -0.5, cfg.attn_softcap


def attn_out_and_mlp(
    p: Params,
    cfg: ModelConfig,
    x: jnp.ndarray,
    o: jnp.ndarray,
    h: Optional[jnp.ndarray] = None,
    lora: Optional[Params] = None,
    lora_ids: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Layer tail shared by every execution path.

    llama block: attention output projection + residual, then a fresh
    mlp_norm feeds the SwiGLU (or MoE) MLP + residual.
    phi block: ``h`` is the single LayerNorm output that already fed
    attention; the GELU MLP reads the same ``h``, and both branch outputs
    add to the residual in parallel.
    With ``lora``/``lora_ids``, every projection the bank covers adds its
    per-row adapter delta (ops/lora.py).
    """
    from functools import partial

    from kserve_vllm_mini_tpu.ops.lora import adapted_linear

    _al = partial(adapted_linear, mode=cfg.quant_mode)
    B, T, _ = x.shape
    dt = cfg.jnp_dtype
    o = o.transpose(0, 2, 1, 3).reshape(B, T, cfg.n_heads * cfg.head_dim)
    if cfg.block == "phi":
        attn_out = _al(o, p["wo"], lora, "wo", lora_ids) + p["bo"]
        up = _al(h, p["w_up"], lora, "w_up", lora_ids) + p["b_up"]
        act = jax.nn.gelu(up.astype(jnp.float32), approximate=True).astype(dt)
        mlp_out = _al(act, p["w_down"], lora, "w_down", lora_ids) + p["b_down"]
        return x + attn_out + mlp_out
    if cfg.block == "gemma2":
        # sandwich norms: each branch output is normed BEFORE its residual
        attn_out = _al(o, p["wo"], lora, "wo", lora_ids)
        x = x + block_norm(p, cfg, attn_out, "post_attn_norm")
        h2 = block_norm(p, cfg, x, "mlp_norm")
        gate = jax.nn.gelu(
            _al(h2, p["w_gate"], lora, "w_gate", lora_ids).astype(jnp.float32),
            approximate=True,
        ).astype(dt)
        mlp_out = _al(gate * _al(h2, p["w_up"], lora, "w_up", lora_ids),
                      p["w_down"], lora, "w_down", lora_ids)
        return x + block_norm(p, cfg, mlp_out, "post_mlp_norm")
    x = x + _al(o, p["wo"], lora, "wo", lora_ids)
    h = rms_norm(x, p["mlp_norm"], cfg.rms_eps)
    if cfg.is_moe:
        from kserve_vllm_mini_tpu.models.moe import moe_mlp

        return x + moe_mlp(p, cfg, h)
    gated = jax.nn.silu(
        _al(h, p["w_gate"], lora, "w_gate", lora_ids).astype(jnp.float32)
    ).astype(dt) * _al(h, p["w_up"], lora, "w_up", lora_ids)
    return x + _al(gated, p["w_down"], lora, "w_down", lora_ids)


def layer_forward(
    p: Params,
    cfg: ModelConfig,
    x: jnp.ndarray,              # [B, T, D]
    positions: jnp.ndarray,      # [B, T] int32 absolute positions
    cos: jnp.ndarray,
    sin: jnp.ndarray,
    attention_fn=None,
    layer_idx: Optional[jnp.ndarray] = None,  # global layer index (scalar) —
                                 # only alt_sliding_window models need it
) -> jnp.ndarray:
    """One cache-free decoder layer (pre-norm attn + SwiGLU MLP, residuals).

    Shared by the scan-rolled forward below and the pipeline-parallel stage
    executor (parallel/pipeline.py), so every execution strategy runs the
    same layer math."""
    T = x.shape[1]
    h = block_norm(p, cfg, x, "attn_norm")
    q, k, v = qkv_proj(p, cfg, h, positions, cos, sin)
    if attention_fn is not None:
        o = attention_fn(q, k, v, positions)
    else:
        kj = jnp.arange(T)[None, None, :]
        qi = positions[:, :, None]
        mask = kj <= qi
        if cfg.sliding_window is not None:
            wmask = mask & (kj > qi - cfg.sliding_window)
            if cfg.alt_sliding_window:
                if layer_idx is None:
                    raise ValueError(
                        "alt_sliding_window models need layer_idx to pick "
                        "the local/global mask phase"
                    )
                mask = jnp.where(layer_idx % 2 == 0, wmask, mask)
            else:
                mask = wmask
        scale, softcap = attn_scale_softcap(cfg)
        o = attention(q, k, v, mask[:, None, :, :], scale=scale, softcap=softcap)
    return attn_out_and_mlp(p, cfg, x, o, h)


def run_cached_layers(
    layers: Params,              # stacked per-layer tree, leading axis = L (or a
                                 # local L/pp range under the pipeline executor)
    cfg: ModelConfig,
    x: jnp.ndarray,              # [B, T, D] embedded input
    positions: jnp.ndarray,      # [B, T] int32 absolute positions
    cos: jnp.ndarray,
    sin: jnp.ndarray,
    kv_cache: KVCache,           # leading axis matches ``layers``
    cache_offsets: jnp.ndarray,  # [B]
    fresh_prefill: bool = False,
    write_gate: Optional[jnp.ndarray] = None,  # scalar bool: when False, the
                                 # cache write is a no-op (old values are
                                 # gathered and written back) — lets the
                                 # SPMD pipeline executor run every stage
                                 # every tick without corrupting inactive
                                 # stages' caches (parallel/serving_pp.py)
    slot_base: Optional[jnp.ndarray] = None,  # scalar int32: this block is
                                 # slots [slot_base, slot_base+B) of the
                                 # cache — the microbatched pipeline
                                 # executor walks slot groups while the
                                 # cache keeps the full slot axis
    layer_offset: int = 0,       # global index of this stack's first layer
                                 # (pipeline stages pass their range start;
                                 # alt_sliding_window's local/global phase
                                 # follows GLOBAL layer parity)
    block_table: Optional[jnp.ndarray] = None,  # [B, MAXB] int32 block ids:
                                 # paged-KV mode — ``kv_cache`` holds
                                 # [L, P, KVH, BLK, D] pools
                                 # (init_paged_kv_cache) and row b's K/V
                                 # live in blocks table[b, 0..], in order,
                                 # so the flattened MAXB*BLK axis is still
                                 # absolute-position order and every
                                 # positional mask below applies unchanged
    lora: Optional[Params] = None,  # ops/lora.py bank LAYER TREE (the
                                 # bank's "layers" entry — pure arrays, so
                                 # it can cross jit): {t_A: [L, N, in, r],
                                 # t_B: [L, N, r, out]}; leading axis L
                                 # rides the layer scan like the base
                                 # weights
    lora_ids: Optional[jnp.ndarray] = None,  # [B] adapter index per row
    paged_kernel_ok: bool = True,  # False under GSPMD-sharded pools: a
                                 # pallas_call inside an auto-partitioned
                                 # jit would see global shapes; the gather
                                 # path partitions per kv head instead
) -> tuple[jnp.ndarray, KVCache]:
    """The cached transformer stack: scan over stacked layers, writing this
    block's K/V at ``cache_offsets`` and attending with positional masking
    (or block-causal flash when ``fresh_prefill``). Shared by ``forward``
    and the serving pipeline executor so both run identical layer math.

    Cache-performance invariants (measured on llama-1b @ v5e; breaking
    either regresses decode by the full cache size in HBM traffic):
    1. The cache rides the scan CARRY — XLA aliases loop-carried buffers in
       place. Routing it through scan xs/ys stacks fresh outputs, i.e.
       copies the ENTIRE cache every forward call.
    2. New keys/values land via an indexed scatter (.at[...].set) that
       touches only [B, KVH, T, D] elements — extracting a layer, patching
       it, and writing the whole layer back rewrites the full layer per
       step instead.
    """
    B, T = positions.shape
    dt = cfg.jnp_dtype
    n_local = kv_cache["k"].shape[0]
    quantized_kv = "k_s" in kv_cache  # static: selects the int8 path
    paged = block_table is not None
    if paged and (write_gate is not None or slot_base is not None):
        raise ValueError(
            "paged KV is not supported under the serving pipeline executor "
            "(write_gate/slot_base); use the dense cache with pp"
        )
    if paged:
        blk = kv_cache["k"].shape[3]          # positions per block
        s = block_table.shape[1] * blk        # flattened key axis (abs order)
    else:
        s = kv_cache["k"].shape[3]
    # Pallas paged decode kernel: table-driven block DMA instead of the
    # gather copy. TPU-only (the gather path stays the CPU oracle every
    # bit-parity test pins against); plain-causal decode steps only —
    # int8-KV pools dequantize in-kernel. _FORCE_PAGED_KERNEL overrides
    # for interpret-mode tests.
    use_paged_kernel = (
        paged
        and paged_kernel_ok
        and positions.shape[1] == 1
        and cfg.attn_softcap is None
        and cfg.sliding_window is None
        and (
            _FORCE_PAGED_KERNEL
            if _FORCE_PAGED_KERNEL is not None
            else jax.default_backend() == "tpu"
        )
    )
    # Dense int8-KV decode kernel: the dense twin — each BLK stripe of the
    # per-slot cache is DMA'd int8 and dequantized in-kernel, so the
    # materialized bf16 [B,KVH,S,D] tensor the eager _read_layer builds
    # never exists. Plain-causal single-token decode on the full-slot-axis
    # cache only (the pp executor's write_gate/slot_base sub-views keep
    # the eager oracle); _read_layer stays the non-kernel fallback.
    from kserve_vllm_mini_tpu.ops.paged_attention import dense_decode_block

    use_dense_kernel = (
        (not paged)
        and quantized_kv
        and paged_kernel_ok
        and write_gate is None
        and slot_base is None
        and positions.shape[1] == 1
        and cfg.attn_softcap is None
        and cfg.sliding_window is None
        and dense_decode_block(s) is not None
        and (
            _FORCE_DENSE_KERNEL
            if _FORCE_DENSE_KERNEL is not None
            else jax.default_backend() == "tpu"
        )
    )
    # Int8-KV cached-prefill kernel (ops/flash_attention.py
    # cached_prefill_attention): continuation chunks (T > 1 against the
    # cache, NOT fresh_prefill) stream the int8 stripes with in-kernel
    # dequant instead of materializing the eager read's bf16 KV tensor.
    # Plain-causal, full-slot-axis dense caches only — same exclusions as
    # the dense decode kernel, plus the tiling contract on (T, S).
    from kserve_vllm_mini_tpu.ops.flash_attention import cached_prefill_blocks

    use_chunk_kernel = (
        (not paged)
        and (not fresh_prefill)
        and quantized_kv
        and paged_kernel_ok
        and write_gate is None
        and slot_base is None
        and positions.shape[1] > 1
        and cfg.attn_softcap is None
        and cfg.sliding_window is None
        and cached_prefill_blocks(positions.shape[1], s) is not None
        and (
            _FORCE_CHUNK_KERNEL
            if _FORCE_CHUNK_KERNEL is not None
            else jax.default_backend() == "tpu"
        )
    )
    kj = jnp.arange(s)[None, None, :]
    qi = positions[:, :, None]
    causal = kj <= qi
    if cfg.sliding_window is not None:
        # Mistral-style window: key j valid iff p - W < j <= p. Cache
        # slots are absolute positions, so the window is a second bound
        # on the same positional mask. Gemma-style alternation keeps BOTH
        # masks and selects per layer inside the scan.
        windowed = causal & (kj > qi - cfg.sliding_window)
        mask_global = causal[:, None, :, :] if cfg.alt_sliding_window else None
        mask = windowed[:, None, :, :]
    else:
        mask_global = None
        mask = causal[:, None, :, :]                         # [B, 1, T, S]
    attn_scale, attn_cap = attn_scale_softcap(cfg)
    base = slot_base if slot_base is not None else jnp.int32(0)
    h_idx = jnp.arange(cfg.n_kv_heads)[None, :, None]        # [1, KVH, 1]
    t_idx = cache_offsets[:, None, None] + jnp.arange(T)[None, None, :]  # [B, 1, T]
    if paged:
        # position p of row b lives at pool block table[b, p // blk],
        # offset p % blk — the scatter's slot axis becomes the block axis
        blk_of_t = jnp.take_along_axis(
            block_table, t_idx[:, 0, :] // blk, axis=1
        )                                                    # [B, T]
        b_idx = blk_of_t[:, None, :]                         # [B, 1, T]
        w_idx = t_idx % blk                                  # [B, 1, T]
    else:
        b_idx = base + jnp.arange(B)[:, None, None]          # [B, 1, 1]
        w_idx = t_idx

    def _gate(cache, name, lidx, new):
        """Value actually scattered: ``new``, or — when write_gate is False —
        the existing values at the same indices (a same-size gather, so the
        no-op write stays O(B*KVH*T*D), never a full-cache select)."""
        if write_gate is None:
            return new
        # broadcasting yields [B,KVH,T,D] for values, [B,KVH,T] for scales
        old = cache[name][lidx, b_idx, h_idx, w_idx]
        return jnp.where(write_gate, new, old.astype(new.dtype))

    def _gather_blocks(arr):
        """Pool leaf -> this batch's blocks in table order, flattened to
        absolute-position order: [P, KVH, BLK, D] values -> [B, KVH, s, D],
        [P, KVH, BLK] scales -> [B, KVH, s]. ONE transpose/reshape for both
        layouts so the value and scale gathers can never drift apart."""
        g = arr[block_table]                     # [B, MAXB, KVH, BLK(, D)]
        g = g.transpose((0, 2, 1, 3) + ((4,) if g.ndim == 5 else ()))
        return g.reshape((B, cfg.n_kv_heads, s) + g.shape[4:])

    def _read_layer(cache, name, lidx):
        """Eager (non-kernel) cache read: gather/slice this layer's live
        view and dequantize on read. The fallback path wherever the Pallas
        decode kernels don't apply (prefill-against-cache, pp sub-views,
        windowed/softcap models, CPU oracle)."""
        vals = jax.lax.dynamic_index_in_dim(cache[name], lidx, axis=0, keepdims=False)
        if paged:
            # the flattened axis is absolute position order, so downstream
            # masking is identical to dense
            vals = _gather_blocks(vals)
        elif slot_base is not None:
            # attention only needs this slot group's rows
            vals = jax.lax.dynamic_slice_in_dim(vals, base, B, axis=0)
        if quantized_kv:
            sc = jax.lax.dynamic_index_in_dim(
                cache[name + "_s"], lidx, axis=0, keepdims=False
            )
            if paged:
                sc = _gather_blocks(sc)
            elif slot_base is not None:
                sc = jax.lax.dynamic_slice_in_dim(sc, base, B, axis=0)
            # dequantize on read: halves the HBM stream vs bf16 and the
            # multiply fuses into the attention matmul's prologue
            return vals.astype(dt) * sc.astype(dt)[..., None]
        return vals.astype(dt)

    def scan_body(carry, layer_xs):
        y0, cache = carry
        if lora is not None:
            p, lora_p, lidx = layer_xs
        else:
            (p, lidx), lora_p = layer_xs, None
        h = block_norm(p, cfg, y0, "attn_norm")
        q, k, v = qkv_proj(p, cfg, h, positions, cos, sin,
                           lora=lora_p, lora_ids=lora_ids)
        cache = dict(cache)
        if quantized_kv:
            kq, ks = _quantize_kv_block(k)
            vq, vs = _quantize_kv_block(v)
            idx_s = (lidx, b_idx, h_idx, w_idx)
            cache["k"] = cache["k"].at[lidx, b_idx, h_idx, w_idx].set(
                _gate(cache, "k", lidx, kq)
            )
            cache["v"] = cache["v"].at[lidx, b_idx, h_idx, w_idx].set(
                _gate(cache, "v", lidx, vq)
            )
            cache["k_s"] = cache["k_s"].at[idx_s].set(_gate(cache, "k_s", lidx, ks))
            cache["v_s"] = cache["v_s"].at[idx_s].set(_gate(cache, "v_s", lidx, vs))
        else:
            cache["k"] = cache["k"].at[lidx, b_idx, h_idx, w_idx].set(
                _gate(cache, "k", lidx, k.astype(cache["k"].dtype))
            )
            cache["v"] = cache["v"].at[lidx, b_idx, h_idx, w_idx].set(
                _gate(cache, "v", lidx, v.astype(cache["v"].dtype))
            )
        glidx = layer_offset + lidx  # global layer index (mask phase)
        if fresh_prefill:
            # block-causal flash over the fresh block is exact for a
            # windowed model too as long as T <= window (every causal
            # key is inside the window); longer prefills take the masked
            # jnp path. T is static, so this is a trace-time branch. The
            # flash kernel has no softcap, so gemma's capped attention
            # always takes the masked path.
            needs_mask_path = (
                attn_cap is not None
                or attn_scale != float(cfg.head_dim) ** -0.5
                or (cfg.sliding_window is not None and T > cfg.sliding_window)
            )
            if needs_mask_path:
                fj = jnp.arange(T)[None, None, :]
                fcausal = fj <= qi
                if cfg.sliding_window is not None:
                    fwin = fcausal & (fj > qi - cfg.sliding_window)
                    if cfg.alt_sliding_window:
                        fmask = jnp.where(glidx % 2 == 0, fwin, fcausal)
                    else:
                        fmask = fwin
                else:
                    fmask = fcausal
                o = attention(q, k, v, fmask[:, None, :, :],
                              scale=attn_scale, softcap=attn_cap)
            else:
                from kserve_vllm_mini_tpu.ops.flash_attention import prefill_attention

                o = prefill_attention(q, k, v)
        elif use_paged_kernel:
            # Pallas paged decode: the block table drives per-block DMA
            # straight from the LAYER-STACKED pool — no gathered KV copy,
            # and no per-layer pool slice either (a dynamic-slice operand
            # to the custom call would materialize the whole layer pool;
            # lidx rides the kernel's index map instead)
            from kserve_vllm_mini_tpu.ops.paged_attention import (
                paged_decode_attention,
            )

            G = cfg.n_heads // cfg.n_kv_heads
            qg = q[:, :, 0, :].reshape(B, cfg.n_kv_heads, G, cfg.head_dim)
            og = paged_decode_attention(
                qg, cache["k"], cache["v"], block_table,
                cache_offsets, layer=lidx, scale=attn_scale,
                k_scale=cache.get("k_s"), v_scale=cache.get("v_s"),
            )
            o = og.reshape(B, cfg.n_heads, 1, cfg.head_dim)
        elif use_dense_kernel:
            # dense int8-KV decode: BLK stripes of the LAYER-STACKED cache
            # are DMA'd int8 and dequantized in-kernel — no materialized
            # bf16 KV tensor, and no per-layer cache slice either (lidx
            # rides the kernel's index map, same contract as paged)
            from kserve_vllm_mini_tpu.ops.paged_attention import (
                dense_decode_attention,
            )

            G = cfg.n_heads // cfg.n_kv_heads
            qg = q[:, :, 0, :].reshape(B, cfg.n_kv_heads, G, cfg.head_dim)
            og = dense_decode_attention(
                qg, cache["k"], cache["v"], cache_offsets,
                layer=lidx, scale=attn_scale,
                k_scale=cache.get("k_s"), v_scale=cache.get("v_s"),
            )
            o = og.reshape(B, cfg.n_heads, 1, cfg.head_dim)
        elif use_chunk_kernel:
            # int8-KV cached prefill: the chunk's queries attend the whole
            # cache stripe — earlier chunks' KV plus the rows this scan
            # step just wrote — with the stripes DMA'd int8 and dequantized
            # in-kernel (lidx rides the index map, same contract as the
            # decode kernels)
            from kserve_vllm_mini_tpu.ops.flash_attention import (
                cached_prefill_attention,
            )

            o = cached_prefill_attention(
                q, cache["k"], cache["v"], cache_offsets,
                layer=lidx, scale=attn_scale,
                k_scale=cache.get("k_s"), v_scale=cache.get("v_s"),
            )
        else:
            k_layer = _read_layer(cache, "k", lidx)
            v_layer = _read_layer(cache, "v", lidx)
            m = mask
            if mask_global is not None:
                # gemma alternation: even global layers local, odd global
                m = jnp.where(glidx % 2 == 0, mask, mask_global)
            o = attention(q, k_layer, v_layer, m,
                          scale=attn_scale, softcap=attn_cap)
        return (
            attn_out_and_mlp(p, cfg, y0, o, h, lora=lora_p, lora_ids=lora_ids),
            cache,
        ), None

    xs = (
        (layers, lora, jnp.arange(n_local))
        if lora is not None
        else (layers, jnp.arange(n_local))
    )
    (x, new_cache), _ = jax.lax.scan(
        scan_body,
        (x, dict(kv_cache)),
        xs,
        unroll=max(cfg.scan_unroll, 1),
    )
    return x, new_cache


def forward(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,         # [B, T] int32
    positions: jnp.ndarray,      # [B, T] int32 absolute positions
    kv_cache: Optional[KVCache] = None,
    cache_offsets: Optional[jnp.ndarray] = None,  # [B] slot where this block starts
    attention_fn=None,  # optional (q, k, v, positions) -> o override for the
                        # cache-free path (e.g. parallel.ring_attention for sp)
    fresh_prefill: bool = False,  # static: this cached call writes a new
                        # request's prompt at offset 0 (positions arange(T)),
                        # so attention runs block-causal over the fresh
                        # q/k/v via ops.flash_attention.prefill_attention
                        # (Pallas kernel on TPU) instead of reading back the
                        # whole max_seq cache buffer
    logit_index: Optional[jnp.ndarray] = None,  # [B] int32: compute logits
                        # at this one position per sequence ([B, 1, V])
                        # instead of all T positions. Prefill only samples
                        # the prompt's last position — a full [B, T, V] f32
                        # logits tensor at 128k vocab is GBs of HBM (and T×
                        # the lm_head matmul) the sampler never reads
    block_table: Optional[jnp.ndarray] = None,  # [B, MAXB] int32: paged-KV
                        # mode — kv_cache is an init_paged_kv_cache pool and
                        # row b's positions live in blocks table[b, :]
    lora: Optional[Params] = None,  # multi-LoRA bank layer tree (the
                        # ops/lora.py bank's "layers" entry); serving
                        # (cached) path only — the cache-free training path
                        # ignores it
    lora_ids: Optional[jnp.ndarray] = None,  # [B] adapter index per row
    paged_kernel_ok: bool = True,  # False for GSPMD-sharded paged pools
                        # (run_cached_layers docstring)
) -> tuple[jnp.ndarray, Optional[KVCache]]:
    """Returns (logits [B, T, V] float32, updated cache).

    Without a cache this is a plain causal forward (training / compile
    checks). With a cache, keys/values of this block are written at
    ``cache_offsets`` and attention runs against the whole cache buffer with
    positional masking — or block-causal over the fresh projections when
    ``fresh_prefill`` (exact for offset-0 prefills; the engine's only
    prefill shape).
    """
    B, T = tokens.shape
    dt = cfg.jnp_dtype
    if attention_fn is not None and cfg.sliding_window is not None:
        raise ValueError(
            "attention_fn overrides (ring attention / sp) do not implement "
            "sliding-window masking; run windowed models with sp=1"
        )
    x = embed_tokens(params, cfg, tokens)  # [B, T, D]
    cos, sin = rope_frequencies(
        cfg.rotary_dim, cfg.max_seq_len, cfg.rope_theta, cfg.rope_scaling
    )

    use_cache = kv_cache is not None
    if use_cache and cache_offsets is None:
        cache_offsets = jnp.zeros((B,), dtype=jnp.int32)

    layers = params["layers"]
    if use_cache:
        x, new_cache_dict = run_cached_layers(
            layers, cfg, x, positions, cos, sin, kv_cache, cache_offsets,
            fresh_prefill=fresh_prefill, block_table=block_table,
            lora=lora, lora_ids=lora_ids, paged_kernel_ok=paged_kernel_ok,
        )
    else:
        def scan_body_nocache(carry, xs):
            p, lidx = xs
            return layer_forward(
                p, cfg, carry, positions, cos, sin, attention_fn,
                layer_idx=lidx,
            ), None

        x, _ = jax.lax.scan(
            scan_body_nocache, x, (layers, jnp.arange(cfg.n_layers)),
            unroll=max(cfg.scan_unroll, 1),
        )
        new_cache_dict = None

    if logit_index is not None:
        x = x[jnp.arange(B)[:, None], logit_index[:, None]]  # [B, 1, D]
    logits = final_logits(params, cfg, x)
    return logits, new_cache_dict
