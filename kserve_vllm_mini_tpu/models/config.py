"""Model architecture configs for the in-repo serving runtime.

The reference never touches model internals (models are opaque strings passed
to external engines, e.g. /root/reference/deploy.sh:25-39 --model-uri), but it
ships engine profiles for four model families
(/root/reference/profiles/tensorrt-llm/{llama-7b,codellama-7b,mistral-7b,
phi-2.7b}.yaml). The TPU build owns the runtime, so architecture configs are
first-class. The base family is the Llama-style decoder (RMSNorm, RoPE,
SwiGLU, GQA) covering BASELINE.json's Llama-3.x configs plus CodeLlama;
orthogonal architecture axes extend it to the other families:

- ``sliding_window`` — Mistral-style windowed attention;
- ``attn_bias`` — Qwen2-style q/k/v projection biases;
- ``n_experts`` / ``n_experts_per_tok`` — Mixtral-style sparse MoE MLP
  (models/moe.py), sharded over the mesh's ``ep`` axis;
- ``block="phi"`` — Phi-2-style parallel attention+MLP block: one
  LayerNorm (with bias) feeds both attention and a GELU MLP, partial
  rotary embedding, biases on every projection.
- ``block="gemma2"`` — Gemma-2-style block: sandwich RMSNorms (post-norms
  on both the attention and MLP branches before their residual adds),
  (1+w) norm weights, GeGLU MLP, sqrt(d_model)-scaled embeddings,
  attention/final logit soft-capping, explicit head_dim decoupled from
  d_model/n_heads, and sliding-window attention on alternating layers.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Optional

import jax.numpy as jnp


@dataclass(frozen=True)
class ModelConfig:
    name: str
    vocab_size: int
    d_model: int
    n_layers: int
    n_heads: int
    n_kv_heads: int
    d_ff: int
    max_seq_len: int = 4096
    rope_theta: float = 500_000.0
    # Llama-3.1-style RoPE frequency scaling:
    # (factor, low_freq_factor, high_freq_factor, original_max_position_embeddings)
    rope_scaling: Optional[tuple[float, float, float, int]] = None
    rms_eps: float = 1e-5
    dtype: str = "bfloat16"          # parameter/activation dtype
    tie_embeddings: bool = False
    # Mistral-style sliding-window attention: a query at absolute position p
    # attends keys j with p - window < j <= p. None = full causal.
    sliding_window: Optional[int] = None
    # Qwen2-style biases on the q/k/v projections (o/mlp stay bias-free).
    attn_bias: bool = False
    # Mixtral-style sparse MoE: n_experts > 0 replaces the dense SwiGLU MLP
    # with a top-k routed expert MLP (models/moe.py).
    n_experts: int = 0
    n_experts_per_tok: int = 2
    # Dispatch buffer head-room: each expert's token capacity per routed
    # block is ceil(tokens * top_k / n_experts * capacity_factor).
    expert_capacity_factor: float = 2.0
    # Block style: "llama" (pre-norm attn -> pre-norm SwiGLU, RMSNorm) or
    # "phi" (parallel attn+MLP off one LayerNorm, GELU MLP, all-bias).
    block: str = "llama"
    # lax.scan unroll over the layer stack: >1 lets XLA software-pipeline
    # weight streaming across layer boundaries at the cost of code size.
    # A schedule knob: numerically equivalent, but XLA may reassociate bf16
    # fusions so the last bits can differ (oracle-tested within tolerance).
    scan_unroll: int = 1
    # Fraction of head_dim that receives rotary embedding (phi-2: 0.4).
    partial_rotary_factor: float = 1.0
    # Gemma-2-family axes --------------------------------------------------
    # head_dim decoupled from d_model/n_heads (gemma-2: 256 while
    # d_model/n_heads derives 288 for 2b, 224 for 9b); None = derived.
    explicit_head_dim: Optional[int] = None
    # tanh soft-capping: attention logits (gemma-2: 50.0) and final lm
    # logits (gemma-2: 30.0). None disables.
    attn_softcap: Optional[float] = None
    final_softcap: Optional[float] = None
    # attention scale denominator override (gemma-2 query_pre_attn_scalar);
    # None = head_dim (the standard 1/sqrt(head_dim)).
    query_pre_attn_scalar: Optional[float] = None
    # sliding_window applies only to EVEN layers (gemma-2's local/global
    # alternation); odd layers attend the full causal context.
    alt_sliding_window: bool = False
    # How quantized matmul leaves contract (ops/qmatmul.py QUANT_MODES):
    # "dequant" casts the int weight to the activation dtype before the dot
    # (W8A16/W4A16); "w8a8" quantizes activations per token and runs the
    # contraction int8 x int8 on the MXU with scales folded
    # post-accumulation. A no-op on unquantized params. Static — it
    # selects the traced program, so it lives on the config every
    # execution path already threads.
    quant_mode: str = "dequant"

    @property
    def head_dim(self) -> int:
        if self.explicit_head_dim is not None:
            return self.explicit_head_dim
        return self.d_model // self.n_heads

    @property
    def is_moe(self) -> bool:
        return self.n_experts > 0

    @property
    def rotary_dim(self) -> int:
        """Even number of head dims receiving RoPE (phi uses a prefix)."""
        d = int(self.head_dim * self.partial_rotary_factor)
        return d - (d % 2)

    @property
    def jnp_dtype(self):
        return jnp.dtype(self.dtype)

    @property
    def param_count(self) -> int:
        """Approximate parameter count (embeddings + blocks + head)."""
        emb = self.vocab_size * self.d_model
        qo = self.d_model * self.n_heads * self.head_dim  # q and o projections
        attn = 2 * qo + 2 * self.d_model * (self.n_kv_heads * self.head_dim)
        mlp = (2 if self.block == "phi" else 3) * self.d_model * self.d_ff
        if self.is_moe:
            mlp = self.n_experts * mlp + self.d_model * self.n_experts
        if self.attn_bias:
            attn += self.n_heads * self.head_dim + 2 * self.n_kv_heads * self.head_dim
        norms = (4 if self.block == "gemma2" else 2) * self.d_model
        head = 0 if self.tie_embeddings else self.vocab_size * self.d_model
        return emb + self.n_layers * (attn + mlp + norms) + self.d_model + head

    def scaled(self, **kwargs) -> "ModelConfig":
        return replace(self, **kwargs)


# Presets. "llama-tiny" is the CI/test model (runs on CPU in <1s); the 8B and
# 70B configs match the published Llama-3.x architectures so real checkpoints
# load onto them; "smoke-125m" plays the role of the reference's
# facebook/opt-125m cpu-smoke config (BASELINE.json configs[0]).
PRESETS: dict[str, ModelConfig] = {
    "llama-tiny": ModelConfig(
        name="llama-tiny",
        vocab_size=512,
        d_model=128,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        d_ff=256,
        max_seq_len=256,
        rope_theta=10_000.0,
    ),
    "smoke-125m": ModelConfig(
        name="smoke-125m",
        vocab_size=32_000,
        d_model=768,
        n_layers=12,
        n_heads=12,
        n_kv_heads=12,
        d_ff=2048,
        max_seq_len=2048,
        rope_theta=10_000.0,
    ),
    "llama-1b": ModelConfig(
        name="llama-1b",
        vocab_size=128_256,
        d_model=2048,
        n_layers=16,
        n_heads=32,
        n_kv_heads=8,
        d_ff=8192,
        max_seq_len=8192,
        rope_scaling=(32.0, 1.0, 4.0, 8192),   # Llama-3.2-1B ships this
    ),
    "llama-3.1-8b": ModelConfig(
        name="llama-3.1-8b",
        vocab_size=128_256,
        d_model=4096,
        n_layers=32,
        n_heads=32,
        n_kv_heads=8,
        d_ff=14_336,
        max_seq_len=8192,
        rope_scaling=(8.0, 1.0, 4.0, 8192),    # Llama-3.1 config.json rope_scaling
    ),
    "llama-3-70b": ModelConfig(
        name="llama-3-70b",
        vocab_size=128_256,
        d_model=8192,
        n_layers=80,
        n_heads=64,
        n_kv_heads=8,
        d_ff=28_672,
        max_seq_len=8192,
    ),
    # -- the reference's other engine-profile families ----------------------
    # (/root/reference/profiles/tensorrt-llm/codellama-7b.yaml, mistral-7b.yaml)
    "codellama-7b": ModelConfig(
        name="codellama-7b",
        vocab_size=32_016,
        d_model=4096,
        n_layers=32,
        n_heads=32,
        n_kv_heads=32,               # Llama-2 7B is MHA
        d_ff=11_008,
        max_seq_len=8192,
        rope_theta=1_000_000.0,      # CodeLlama's long-context base
    ),
    "mistral-7b": ModelConfig(
        name="mistral-7b",
        vocab_size=32_000,
        d_model=4096,
        n_layers=32,
        n_heads=32,
        n_kv_heads=8,
        d_ff=14_336,
        max_seq_len=8192,
        rope_theta=10_000.0,
        sliding_window=4096,
    ),
    "qwen2-7b": ModelConfig(
        name="qwen2-7b",
        vocab_size=152_064,
        d_model=3584,
        n_layers=28,
        n_heads=28,
        n_kv_heads=4,
        d_ff=18_944,
        max_seq_len=8192,
        rope_theta=1_000_000.0,
        attn_bias=True,
    ),
    "mixtral-8x7b": ModelConfig(
        name="mixtral-8x7b",
        vocab_size=32_000,
        d_model=4096,
        n_layers=32,
        n_heads=32,
        n_kv_heads=8,
        d_ff=14_336,
        max_seq_len=8192,
        rope_theta=1_000_000.0,
        n_experts=8,
        n_experts_per_tok=2,
    ),
    "phi-2.7b": ModelConfig(
        name="phi-2.7b",
        vocab_size=51_200,
        d_model=2560,
        n_layers=32,
        n_heads=32,
        n_kv_heads=32,               # MHA
        d_ff=10_240,
        max_seq_len=2048,
        rope_theta=10_000.0,
        block="phi",
        partial_rotary_factor=0.4,
        attn_bias=True,
        rms_eps=1e-5,
    ),
    # Gemma-2 (published architecture): sandwich norms, GeGLU, soft-caps,
    # head_dim 256 decoupled from d_model/n_heads, alternating 4096-token
    # local / global attention, tied embeddings, 256k vocab.
    "gemma-2-9b": ModelConfig(
        name="gemma-2-9b",
        vocab_size=256_000,
        d_model=3584,
        n_layers=42,
        n_heads=16,
        n_kv_heads=8,
        d_ff=14_336,
        max_seq_len=8192,
        rope_theta=10_000.0,
        rms_eps=1e-6,
        block="gemma2",
        tie_embeddings=True,
        explicit_head_dim=256,
        query_pre_attn_scalar=256.0,
        attn_softcap=50.0,
        final_softcap=30.0,
        sliding_window=4096,
        alt_sliding_window=True,
    ),
    "gemma-2-2b": ModelConfig(
        name="gemma-2-2b",
        vocab_size=256_000,
        d_model=2304,
        n_layers=26,
        n_heads=8,
        n_kv_heads=4,
        d_ff=9216,
        max_seq_len=8192,
        rope_theta=10_000.0,
        rms_eps=1e-6,
        block="gemma2",
        tie_embeddings=True,
        explicit_head_dim=256,
        query_pre_attn_scalar=256.0,
        attn_softcap=50.0,
        final_softcap=30.0,
        sliding_window=4096,
        alt_sliding_window=True,
    ),
    # -- tiny CI variants (CPU in <1s) exercising each architecture axis ----
    "mistral-tiny": ModelConfig(
        name="mistral-tiny",
        vocab_size=512,
        d_model=128,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        d_ff=256,
        max_seq_len=256,
        rope_theta=10_000.0,
        sliding_window=16,           # small enough that tests hit the window
    ),
    "qwen-tiny": ModelConfig(
        name="qwen-tiny",
        vocab_size=512,
        d_model=128,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        d_ff=256,
        max_seq_len=256,
        rope_theta=10_000.0,
        attn_bias=True,
    ),
    "phi-tiny": ModelConfig(
        name="phi-tiny",
        vocab_size=512,
        d_model=128,
        n_layers=2,
        n_heads=4,
        n_kv_heads=4,
        d_ff=256,
        max_seq_len=256,
        rope_theta=10_000.0,
        block="phi",
        partial_rotary_factor=0.5,
        attn_bias=True,
    ),
    "gemma-tiny": ModelConfig(
        name="gemma-tiny",
        vocab_size=512,
        d_model=128,
        n_layers=4,                  # even+odd layers: both mask phases run
        n_heads=4,
        n_kv_heads=2,
        d_ff=256,
        max_seq_len=256,
        rope_theta=10_000.0,
        rms_eps=1e-6,
        block="gemma2",
        tie_embeddings=True,
        explicit_head_dim=48,        # != d_model/n_heads: exercises the override
        query_pre_attn_scalar=48.0,
        attn_softcap=50.0,
        final_softcap=30.0,
        sliding_window=16,
        alt_sliding_window=True,
    ),
    "mixtral-tiny": ModelConfig(
        name="mixtral-tiny",
        vocab_size=512,
        d_model=128,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        d_ff=256,
        max_seq_len=256,
        rope_theta=10_000.0,
        n_experts=4,
        n_experts_per_tok=2,
    ),
}


def get_config(name: str, **overrides) -> ModelConfig:
    if name not in PRESETS:
        raise ValueError(f"unknown model preset {name!r}; known: {sorted(PRESETS)}")
    cfg = PRESETS[name]
    return cfg.scaled(**overrides) if overrides else cfg
