"""HF-checkpoint -> param-pytree loader (offline, zero-copy-ish).

Maps HuggingFace Llama safetensors weights onto the stacked-layer pytree of
models/llama.py. Works entirely from a local directory (the deployment layer
mounts checkpoints from GCS the way the reference mounts s3:// model URIs,
/root/reference/deploy.sh:25-39); no network access is attempted.

HF stores projections as [out, in] matrices applied as x @ W.T; our forward
computes x @ W, so every projection is transposed once at load. Layer arrays
are stacked along a leading n_layers axis for lax.scan.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Callable, Optional

import jax.numpy as jnp
import numpy as np

from kserve_vllm_mini_tpu.models.config import ModelConfig, get_config

# our stacked-layer key -> (HF per-layer key, transpose?)
_LAYER_MAP = {
    "attn_norm": ("input_layernorm.weight", False),
    "wq": ("self_attn.q_proj.weight", True),
    "wk": ("self_attn.k_proj.weight", True),
    "wv": ("self_attn.v_proj.weight", True),
    "wo": ("self_attn.o_proj.weight", True),
    "mlp_norm": ("post_attention_layernorm.weight", False),
    "w_gate": ("mlp.gate_proj.weight", True),
    "w_up": ("mlp.up_proj.weight", True),
    "w_down": ("mlp.down_proj.weight", True),
}

# Qwen2-style q/k/v biases (vectors — no transpose)
_BIAS_MAP = {
    "bq": "self_attn.q_proj.bias",
    "bk": "self_attn.k_proj.bias",
    "bv": "self_attn.v_proj.bias",
}

# Mixtral MoE names: the router is ``block_sparse_moe.gate`` and experts use
# the w1/w3/w2 = gate/up/down convention. Expert weights stack to
# [E, in, out]; per-layer stacks add the leading L axis.
_MOE_EXPERT_MAP = {
    "w_gate": "block_sparse_moe.experts.{e}.w1.weight",
    "w_up": "block_sparse_moe.experts.{e}.w3.weight",
    "w_down": "block_sparse_moe.experts.{e}.w2.weight",
}

# Gemma-2 layer names: sandwich norms — input_layernorm (pre-attn),
# post_attention_layernorm (post-attn, pre-residual),
# pre/post_feedforward_layernorm around the GeGLU MLP. Norm weights are
# stored as offsets (model applies 1+w); matmuls follow Llama naming.
_GEMMA2_LAYER_MAP = {
    "attn_norm": ("input_layernorm.weight", False),
    "post_attn_norm": ("post_attention_layernorm.weight", False),
    "mlp_norm": ("pre_feedforward_layernorm.weight", False),
    "post_mlp_norm": ("post_feedforward_layernorm.weight", False),
    "wq": ("self_attn.q_proj.weight", True),
    "wk": ("self_attn.k_proj.weight", True),
    "wv": ("self_attn.v_proj.weight", True),
    "wo": ("self_attn.o_proj.weight", True),
    "w_gate": ("mlp.gate_proj.weight", True),
    "w_up": ("mlp.up_proj.weight", True),
    "w_down": ("mlp.down_proj.weight", True),
}

# Phi-2 layer names: one LayerNorm, ``dense`` o-projection, fc1/fc2 GELU MLP,
# biases everywhere. (matrix, transpose?) pairs plus a parallel bias table.
_PHI_LAYER_MAP = {
    "attn_norm": ("input_layernorm.weight", False),
    "attn_norm_b": ("input_layernorm.bias", False),
    "wq": ("self_attn.q_proj.weight", True),
    "bq": ("self_attn.q_proj.bias", False),
    "wk": ("self_attn.k_proj.weight", True),
    "bk": ("self_attn.k_proj.bias", False),
    "wv": ("self_attn.v_proj.weight", True),
    "bv": ("self_attn.v_proj.bias", False),
    "wo": ("self_attn.dense.weight", True),
    "bo": ("self_attn.dense.bias", False),
    "w_up": ("mlp.fc1.weight", True),
    "b_up": ("mlp.fc1.bias", False),
    "w_down": ("mlp.fc2.weight", True),
    "b_down": ("mlp.fc2.bias", False),
}


def config_from_hf(model_dir: str | Path) -> ModelConfig:
    """Derive a ModelConfig from an HF config.json."""
    with (Path(model_dir) / "config.json").open() as f:
        hf = json.load(f)
    rope_scaling = None
    rs = hf.get("rope_scaling")
    if isinstance(rs, dict) and rs.get("rope_type", rs.get("type")) == "llama3":
        rope_scaling = (
            float(rs.get("factor", 8.0)),
            float(rs.get("low_freq_factor", 1.0)),
            float(rs.get("high_freq_factor", 4.0)),
            int(rs.get("original_max_position_embeddings", 8192)),
        )
    model_type = hf.get("model_type", "llama")
    if model_type == "phi":
        block = "phi"
    elif model_type == "gemma2":
        block = "gemma2"
    else:
        block = "llama"
    sliding_window = hf.get("sliding_window")
    # gemma-2 windows apply to alternating layers; the window being smaller
    # than max_position_embeddings is by design, so skip the disable below
    alt_sliding = block == "gemma2" and sliding_window is not None
    # Qwen2 checkpoints ship sliding_window=131072 with
    # use_sliding_window=false — the window is disabled, not huge. A window
    # at/past max_position_embeddings is likewise never binding.
    if not hf.get("use_sliding_window", True):
        sliding_window = None
    if sliding_window and sliding_window >= hf.get("max_position_embeddings", 4096):
        sliding_window = None
    return ModelConfig(
        rope_scaling=rope_scaling,
        name=hf.get("_name_or_path", Path(model_dir).name) or Path(model_dir).name,
        vocab_size=hf["vocab_size"],
        d_model=hf["hidden_size"],
        n_layers=hf["num_hidden_layers"],
        n_heads=hf["num_attention_heads"],
        n_kv_heads=hf.get("num_key_value_heads", hf["num_attention_heads"]),
        d_ff=hf["intermediate_size"],
        max_seq_len=min(hf.get("max_position_embeddings", 4096), 16384),
        rope_theta=float(hf.get("rope_theta", 10_000.0)),
        rms_eps=float(hf.get("rms_norm_eps", hf.get("layer_norm_eps", 1e-5))),
        tie_embeddings=bool(hf.get("tie_word_embeddings", False)),
        sliding_window=int(sliding_window) if sliding_window else None,
        attn_bias=model_type in ("qwen2", "phi"),
        n_experts=int(hf.get("num_local_experts", 0)),
        n_experts_per_tok=int(hf.get("num_experts_per_tok", 2)),
        block=block,
        partial_rotary_factor=float(hf.get("partial_rotary_factor", 1.0)),
        explicit_head_dim=(
            int(hf["head_dim"]) if hf.get("head_dim") is not None else None
        ),
        attn_softcap=(
            float(hf["attn_logit_softcapping"])
            if hf.get("attn_logit_softcapping") is not None else None
        ),
        final_softcap=(
            float(hf["final_logit_softcapping"])
            if hf.get("final_logit_softcapping") is not None else None
        ),
        query_pre_attn_scalar=(
            float(hf["query_pre_attn_scalar"])
            if hf.get("query_pre_attn_scalar") is not None else None
        ),
        alt_sliding_window=alt_sliding,
    )


def _open_shards(model_dir: Path) -> Callable[[str], np.ndarray]:
    """Return tensor_name -> np.ndarray across single-file or sharded
    safetensors checkpoints."""
    from safetensors import safe_open

    index_path = model_dir / "model.safetensors.index.json"
    if index_path.exists():
        with index_path.open() as f:
            weight_map: dict[str, str] = json.load(f)["weight_map"]
        handles: dict[str, Any] = {}

        def get(name: str) -> np.ndarray:
            shard = weight_map[name]
            if shard not in handles:
                handles[shard] = safe_open(model_dir / shard, framework="numpy")
            return handles[shard].get_tensor(name)

        return get

    single = model_dir / "model.safetensors"
    if not single.exists():
        cands = sorted(model_dir.glob("*.safetensors"))
        if not cands:
            raise FileNotFoundError(f"no safetensors checkpoint under {model_dir}")
        single = cands[0]
    handle = safe_open(single, framework="numpy")

    def get_single(name: str) -> np.ndarray:
        return handle.get_tensor(name)

    return get_single


def load_hf_checkpoint(
    model_dir: str | Path,
    cfg: Optional[ModelConfig] = None,
    dtype: Optional[str] = None,
    quantize: bool | str = False,
) -> tuple[dict[str, Any], ModelConfig]:
    """Load an HF Llama-family checkpoint into (params, config).

    ``quantize=True`` (or ``"int8"``/``"int4"``) converts each matmul
    weight to that width **layer by layer during the load**, so the full-precision tree never exists on device —
    an 8B bf16 tree is ~16 GB, the entire HBM of the v5e this serves on
    (same rationale as models/llama.py init_params_quantized; quantizing
    after a full load re-creates the round-2 OOM for real checkpoints)."""
    model_dir = Path(model_dir)
    cfg = cfg or config_from_hf(model_dir)
    dt = jnp.dtype(dtype or cfg.dtype)
    get = _open_shards(model_dir)
    if quantize in ("none", "bf16", ""):
        quantize = False  # mode strings pass straight through from configs

    def conv(name: str, transpose: bool) -> jnp.ndarray:
        x = jnp.asarray(get(name))  # ml_dtypes handles bf16 numpy views
        if transpose:
            x = x.T
        return x.astype(dt)

    if quantize:
        from kserve_vllm_mini_tpu.ops.quant import QUANTIZABLE, quantize_weight
    q_bits = 4 if quantize == "int4" else 8

    def stack_quantized(per_layer_arrays) -> dict[str, Any]:
        qws = [quantize_weight(a, bits=q_bits) for a in per_layer_arrays]
        # stack EVERY key the leaf carries, not a hardcoded {"q", "s"}: a
        # leaf with a compensation term ("z"/"a") stacked key-by-name would
        # silently drop it and serve the offset-free weight (KVM062)
        return {k: jnp.stack([w[k] for w in qws]) for k in qws[0]}

    layers: dict[str, Any] = {}
    layer_map = {"phi": _PHI_LAYER_MAP, "gemma2": _GEMMA2_LAYER_MAP}.get(
        cfg.block, _LAYER_MAP
    )
    for ours, (hf_key, tr) in layer_map.items():
        if cfg.is_moe and ours in _MOE_EXPERT_MAP:
            # expert-stacked [L, E, in, out]: per layer, stack the E experts
            tmpl = _MOE_EXPERT_MAP[ours]
            per_layer = (
                jnp.stack([
                    conv(f"model.layers.{i}.{tmpl.format(e=e)}", True)
                    for e in range(cfg.n_experts)
                ])
                for i in range(cfg.n_layers)
            )
        else:
            per_layer = (
                conv(f"model.layers.{i}.{hf_key}", tr) for i in range(cfg.n_layers)
            )
        if quantize and ours in QUANTIZABLE:
            layers[ours] = stack_quantized(per_layer)
        else:
            layers[ours] = jnp.stack(list(per_layer))
    if cfg.is_moe:
        # router ("gate") is [E, d] applied as x @ W.T -> ours is [d, E]
        layers["router"] = jnp.stack([
            conv(f"model.layers.{i}.block_sparse_moe.gate.weight", True)
            for i in range(cfg.n_layers)
        ])
    if cfg.attn_bias and cfg.block != "phi":
        for ours, hf_key in _BIAS_MAP.items():
            layers[ours] = jnp.stack([
                conv(f"model.layers.{i}.{hf_key}", False) for i in range(cfg.n_layers)
            ])

    final_norm_key = (
        "model.final_layernorm.weight" if cfg.block == "phi" else "model.norm.weight"
    )
    params: dict[str, Any] = {
        "embed": conv("model.embed_tokens.weight", False),
        "layers": layers,
        "final_norm": conv(final_norm_key, False),
    }
    if cfg.block == "phi":
        params["final_norm_b"] = conv("model.final_layernorm.bias", False)
        params["lm_head_b"] = conv("lm_head.bias", False)
    if not cfg.tie_embeddings:
        params["lm_head"] = conv("lm_head.weight", False)
    return params, cfg


def save_checkpoint(params: dict[str, Any], cfg: ModelConfig, out_dir: str | Path) -> None:
    """Write our pytree back out as a (single-shard) HF-layout checkpoint, so
    quantization sweeps can materialize variants."""
    from safetensors.numpy import save_file

    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    tensors: dict[str, np.ndarray] = {}

    def put(name: str, x: jnp.ndarray, transpose: bool) -> None:
        arr = np.asarray(x.astype(jnp.float32))
        if transpose:
            arr = arr.T
        tensors[name] = np.ascontiguousarray(arr)

    put("model.embed_tokens.weight", params["embed"], False)
    if cfg.block == "phi":
        put("model.final_layernorm.weight", params["final_norm"], False)
        put("model.final_layernorm.bias", params["final_norm_b"], False)
        put("lm_head.bias", params["lm_head_b"], False)
    else:
        put("model.norm.weight", params["final_norm"], False)
    if "lm_head" in params:
        put("lm_head.weight", params["lm_head"], False)
    layer_map = {"phi": _PHI_LAYER_MAP, "gemma2": _GEMMA2_LAYER_MAP}.get(
        cfg.block, _LAYER_MAP
    )
    for ours, (hf_key, tr) in layer_map.items():
        for i in range(cfg.n_layers):
            if cfg.is_moe and ours in _MOE_EXPERT_MAP:
                tmpl = _MOE_EXPERT_MAP[ours]
                for e in range(cfg.n_experts):
                    put(
                        f"model.layers.{i}.{tmpl.format(e=e)}",
                        params["layers"][ours][i][e],
                        True,
                    )
            else:
                put(f"model.layers.{i}.{hf_key}", params["layers"][ours][i], tr)
    if cfg.is_moe:
        for i in range(cfg.n_layers):
            put(
                f"model.layers.{i}.block_sparse_moe.gate.weight",
                params["layers"]["router"][i],
                True,
            )
    if cfg.attn_bias and cfg.block != "phi":
        for ours, hf_key in _BIAS_MAP.items():
            for i in range(cfg.n_layers):
                put(f"model.layers.{i}.{hf_key}", params["layers"][ours][i], False)
    save_file(tensors, str(out_dir / "model.safetensors"))
    if cfg.block == "phi":
        model_type = "phi"
    elif cfg.block == "gemma2":
        model_type = "gemma2"
    elif cfg.is_moe:
        model_type = "mixtral"
    elif cfg.attn_bias:
        model_type = "qwen2"
    elif cfg.sliding_window is not None:
        model_type = "mistral"
    else:
        model_type = "llama"
    hf_cfg = {
        "vocab_size": cfg.vocab_size,
        "hidden_size": cfg.d_model,
        "num_hidden_layers": cfg.n_layers,
        "num_attention_heads": cfg.n_heads,
        "num_key_value_heads": cfg.n_kv_heads,
        "intermediate_size": cfg.d_ff,
        "max_position_embeddings": cfg.max_seq_len,
        "rope_theta": cfg.rope_theta,
        "rms_norm_eps": cfg.rms_eps,
        "tie_word_embeddings": cfg.tie_embeddings,
        "model_type": model_type,
    }
    if cfg.sliding_window is not None:
        hf_cfg["sliding_window"] = cfg.sliding_window
    if cfg.is_moe:
        hf_cfg["num_local_experts"] = cfg.n_experts
        hf_cfg["num_experts_per_tok"] = cfg.n_experts_per_tok
    if cfg.block == "phi":
        hf_cfg["partial_rotary_factor"] = cfg.partial_rotary_factor
        hf_cfg["layer_norm_eps"] = cfg.rms_eps
    if cfg.block == "gemma2":
        if cfg.explicit_head_dim is not None:
            hf_cfg["head_dim"] = cfg.explicit_head_dim
        hf_cfg["attn_logit_softcapping"] = cfg.attn_softcap
        hf_cfg["final_logit_softcapping"] = cfg.final_softcap
        hf_cfg["query_pre_attn_scalar"] = cfg.query_pre_attn_scalar
    if cfg.rope_scaling is not None:
        f_, lo, hi, omax = cfg.rope_scaling
        hf_cfg["rope_scaling"] = {
            "rope_type": "llama3",
            "factor": f_,
            "low_freq_factor": lo,
            "high_freq_factor": hi,
            "original_max_position_embeddings": omax,
        }
    with (out_dir / "config.json").open("w") as f:
        json.dump(hf_cfg, f, indent=2)
