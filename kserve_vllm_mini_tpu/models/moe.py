"""Sparse mixture-of-experts MLP (Mixtral-style top-k routing), TPU-first.

The reference has no MoE support at all (SURVEY.md §2.2: EP absent); this
module adds the family the TPU build owns end-to-end. Design:

- **Routing**: per-token softmax router, ``lax.top_k`` selection of
  ``n_experts_per_tok`` experts, gates renormalized over the chosen k
  (Mixtral's convention).
- **Dispatch**: capacity-bounded scatter into a per-expert token buffer
  ``[E, C, D]`` — O(tokens · k) memory, unlike the GShard one-hot einsum
  whose ``[S, E, C]`` dispatch tensor is quadratic in tokens. Position
  within each expert comes from a cumulative sum over a choice-major
  flattening, so every token's FIRST choice beats any token's second choice
  when an expert overflows (GShard priority). Overflowed assignments drop
  (their gate weight is simply not added — the residual passes through),
  which is the standard capacity-factor contract.
- **Expert compute**: one batched SwiGLU over ``[E, C, D]`` — three
  ``einsum('ecd,edf->ecf')`` matmuls the MXU tiles per expert. Expert
  weights are stacked ``[L, E, d, ff]`` so the layer scan treats MoE layers
  exactly like dense ones.
- **EP sharding**: the expert axis shards over the mesh's ``ep`` axis
  (parallel/sharding.py): expert weights are P(..., "ep", ...), and XLA
  lowers the dispatch/return movement to all-to-alls over ICI — the
  scaling-book recipe, not hand-written collectives.

Quantization: expert matmul weights quantize per-output-channel like dense
weights (ops/quant.py works on any [..., in, out] stack); the router stays
bf16 (it is d_model x E — noise-level bytes, accuracy-critical).
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp

from kserve_vllm_mini_tpu.models.config import ModelConfig
from kserve_vllm_mini_tpu.ops.quant import is_quantized, unpacked_q


def expert_capacity(cfg: ModelConfig, n_tokens: int) -> int:
    """Per-expert token capacity for a routed block of ``n_tokens``."""
    ideal = n_tokens * cfg.n_experts_per_tok / cfg.n_experts
    return max(int(math.ceil(ideal * cfg.expert_capacity_factor)), cfg.n_experts_per_tok)


def _expert_linear(x: jnp.ndarray, w: Any, mode: str = "dequant") -> jnp.ndarray:
    """Batched per-expert matmul ``[E, C, in] @ [E, in, out]``; ``w`` may be
    a plain array or an int8 dict (scale applied as a fused epilogue, same
    contract as ops.quant.linear). ``mode="w8a8"`` contracts in int8 with
    the expert axis as the batch dim (ops/qmatmul.py qdot)."""
    if is_quantized(w):
        if mode == "w8a8":
            from kserve_vllm_mini_tpu.ops.qmatmul import qdot

            return qdot(x, w, batch_dims=1)
        y = jnp.einsum("ecd,edf->ecf", x, unpacked_q(w).astype(x.dtype))
        return y * w["s"].astype(x.dtype)[:, None, :]
    return jnp.einsum("ecd,edf->ecf", x, w)


def moe_mlp(p: dict[str, Any], cfg: ModelConfig, h: jnp.ndarray) -> jnp.ndarray:
    """Routed SwiGLU MLP. ``h`` is the normed hidden [B, T, D]; returns the
    MLP delta [B, T, D] (caller adds the residual, mirroring the dense path).

    ``p`` holds this layer's ``router`` [D, E] plus expert-stacked
    ``w_gate``/``w_up`` [E, D, F] and ``w_down`` [E, F, D].
    """
    B, T, D = h.shape
    S = B * T
    E, K = cfg.n_experts, cfg.n_experts_per_tok
    C = expert_capacity(cfg, S)
    dt = h.dtype
    x = h.reshape(S, D)

    # -- route (f32 softmax; the router matmul is tiny) ---------------------
    router_logits = (x.astype(jnp.float32) @ p["router"].astype(jnp.float32))
    probs = jax.nn.softmax(router_logits, axis=-1)            # [S, E]
    gates, expert_idx = jax.lax.top_k(probs, K)               # [S, K]
    gates = gates / jnp.maximum(gates.sum(axis=-1, keepdims=True), 1e-9)

    # -- capacity positions: choice-major cumsum so first choices win -------
    flat_e = expert_idx.T.reshape(-1)                         # [K*S] choice-major
    onehot = jax.nn.one_hot(flat_e, E, dtype=jnp.int32)       # [K*S, E]
    pos = jnp.cumsum(onehot, axis=0) - onehot                 # entries start at 0
    pos_in_expert = jnp.take_along_axis(pos, flat_e[:, None], axis=1)[:, 0]
    keep = pos_in_expert < C                                  # [K*S]
    # dropped assignments scatter to a sentinel row past every expert buffer
    slot = jnp.where(keep, flat_e * C + jnp.minimum(pos_in_expert, C - 1), E * C)

    # -- dispatch: scatter tokens into [E*C(+1), D] expert buffers ----------
    x_rep = jnp.broadcast_to(x[None], (K, S, D)).reshape(K * S, D)
    buf = jnp.zeros((E * C + 1, D), dtype=dt).at[slot].add(x_rep)
    expert_in = buf[: E * C].reshape(E, C, D)

    # -- batched SwiGLU over experts ----------------------------------------
    qm = cfg.quant_mode
    gated = jax.nn.silu(
        _expert_linear(expert_in, p["w_gate"], mode=qm).astype(jnp.float32)
    ).astype(dt) * _expert_linear(expert_in, p["w_up"], mode=qm)
    expert_out = _expert_linear(gated, p["w_down"], mode=qm)  # [E, C, D]

    # -- return + combine: gather each kept assignment, weight by its gate --
    out_flat = expert_out.reshape(E * C, D)
    picked = jnp.where(
        keep[:, None], jnp.take(out_flat, jnp.minimum(slot, E * C - 1), axis=0), 0.0
    )                                                         # [K*S, D]
    gates_flat = gates.T.reshape(-1).astype(dt)               # choice-major [K*S]
    combined = (picked * gates_flat[:, None]).reshape(K, S, D).sum(axis=0)
    return combined.reshape(B, T, D)
