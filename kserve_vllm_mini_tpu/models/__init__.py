from kserve_vllm_mini_tpu.models.config import ModelConfig, PRESETS, get_config

__all__ = ["ModelConfig", "PRESETS", "get_config"]
