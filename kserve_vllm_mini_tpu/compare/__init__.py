"""Backend comparison, API parity, and fairness harnesses (framework L6/L7).

Analogs of the reference's runners/ab-compare.sh, scripts/compare_backends.py,
scripts/openai_parity_probe.py, and scripts/fairness_dual_tenant.py — as
typed, testable modules sharing the loadgen core instead of embedded shell
python.
"""
