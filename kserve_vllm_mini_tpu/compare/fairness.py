"""Dual-tenant fairness / backpressure harness.

Reference behavior (scripts/fairness_dual_tenant.py): two tenants load the
same endpoint concurrently — tenant A is latency-protected, tenant B is bulk
traffic. A guard watches tenant A's rolling p95 (:46-65) and throttles
tenant B while the budget is breached, releasing after a cooldown (:148-174).
The summary (:177-198) reports per-tenant p50/p95, throughput share, and
feeds the fairness budgets of the SLO gate (tools/gate.py:97-128).

The workers reuse the loadgen protocol adapters and RunDir contract, so a
fairness run produces a normal requests.csv (tenant column) that the
analyzer can process like any other run.
"""

from __future__ import annotations

import argparse
import asyncio
import bisect
import json
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Optional

import httpx

from kserve_vllm_mini_tpu.analysis.metrics import percentile
from kserve_vllm_mini_tpu.core.rundir import RequestRecord, RunDir
from kserve_vllm_mini_tpu.loadgen.adapters.base import CallResult, GenParams, get_adapter
from kserve_vllm_mini_tpu.loadgen.arrivals import generate_arrival_times
from kserve_vllm_mini_tpu.loadgen.prompts import make_prompt_fn


class RollingP95:
    """p95 over a sliding window of the most recent N latencies
    (fairness_dual_tenant.py:46-65). The window is kept sorted so p95 is a
    direct rank interpolation — no per-observation re-sort."""

    def __init__(self, window: int = 50):
        self.window = window
        self._recent: list[float] = []    # arrival order
        self._sorted: list[float] = []

    def add(self, value: float) -> None:
        self._recent.append(value)
        bisect.insort(self._sorted, value)
        if len(self._recent) > self.window:
            old = self._recent.pop(0)
            del self._sorted[bisect.bisect_left(self._sorted, old)]

    def p95(self) -> float:
        s = self._sorted
        if not s:
            return 0.0
        # same closest-rank interpolation as analysis.metrics.percentile,
        # applied to the already-sorted window
        rank = 0.95 * (len(s) - 1)
        lo = int(rank)
        hi = min(lo + 1, len(s) - 1)
        frac = rank - lo
        return s[lo] * (1.0 - frac) + s[hi] * frac

    def __len__(self) -> int:
        return len(self._recent)


@dataclass
class Guard:
    """Backpressure controller: while tenant A's rolling p95 breaches the
    budget, tenant B's workers are gated; the gate re-opens ``cooldown_s``
    after the breach clears (fairness_dual_tenant.py:148-174)."""

    p95_budget_ms: float
    cooldown_s: float = 2.0
    min_samples: int = 10
    rolling: RollingP95 = field(default_factory=RollingP95)
    throttle_events: int = 0
    throttled_s: float = 0.0
    _gate: asyncio.Event = field(default_factory=asyncio.Event)
    _release_at: float = 0.0
    _throttling: bool = False

    def __post_init__(self) -> None:
        self._gate.set()
        self._throttle_began = 0.0

    def total_throttled_s(self) -> float:
        """Accumulated gate-closed time, including a window still open now —
        a run that ends mid-throttle must not report ~0."""
        if self._throttling:
            return self.throttled_s + (time.time() - self._throttle_began)
        return self.throttled_s

    def observe(self, latency_ms: float) -> None:
        self.rolling.add(latency_ms)
        now = time.time()
        breaching = (
            len(self.rolling) >= self.min_samples
            and self.rolling.p95() > self.p95_budget_ms
        )
        if breaching:
            self._release_at = now + self.cooldown_s
            if not self._throttling:
                self._throttling = True
                self.throttle_events += 1
                self._throttle_began = now
                self._gate.clear()
        elif self._throttling and now >= self._release_at:
            self._throttling = False
            self.throttled_s += now - self._throttle_began
            self._gate.set()

    async def wait_clear(self) -> None:
        """Called by tenant-B workers before sending. Waits with a deadline,
        not just on the event: releases are normally driven by protected-
        tenant observations, but if tenant A finishes (or goes quiet) while
        the gate is closed, a parked worker must wake itself at
        ``_release_at`` rather than deadlock the run."""
        while self._throttling:
            remaining = self._release_at - time.time()
            if remaining <= 0:
                self._throttling = False
                self.throttled_s += time.time() - self._throttle_began
                self._gate.set()
                break
            try:
                await asyncio.wait_for(self._gate.wait(), timeout=remaining + 0.01)
            except asyncio.TimeoutError:
                continue  # deadline passed (or was extended) — re-check
        await self._gate.wait()


@dataclass
class TenantConfig:
    name: str
    requests: int = 100
    concurrency: int = 8
    pattern: str = "poisson"
    max_tokens: int = 32
    protected: bool = False     # guard watches this tenant's latency


async def _tenant_worker(
    idx: int,
    arrival_offset: float,
    t_start: float,
    tenant: TenantConfig,
    url: str,
    model: str,
    adapter,
    client: httpx.AsyncClient,
    sem: asyncio.Semaphore,
    prompt_fn,
    guard: Optional[Guard],
) -> RequestRecord:
    rec = RequestRecord(
        request_id=f"{tenant.name}-{idx:05d}",
        scheduled_ts=t_start + arrival_offset,
        tenant=tenant.name,
    )
    delay = rec.scheduled_ts - time.time()
    if delay > 0:
        await asyncio.sleep(delay)
    if guard is not None and not tenant.protected:
        await guard.wait_clear()
    async with sem:
        rec.start_ts = time.time()
        try:
            result = await adapter.generate(
                client, url, model, prompt_fn(idx),
                # the OpenAI `user` field names the tenant: against the
                # fleet router this is the session-affinity key, so each
                # tenant's traffic pins to (and thrashes) its own
                # replica's cache instead of smearing across the fleet
                GenParams(max_tokens=tenant.max_tokens,
                          extra={"user": tenant.name}),
                False, None,
            )
        except Exception as e:  # noqa: BLE001
            result = CallResult(error=f"adapter-{type(e).__name__}")
        rec.end_ts = time.time()
    rec.ok = result.ok
    rec.status_code = result.status_code
    rec.error = result.error
    # fleet-level backpressure (docs/FLEET.md): a 429 from the router
    # (or a single server's door) is ADMISSION CONTROL, not a broken
    # request — counted as a shed, excluded from the error rate, same
    # contract as the loadgen's accounting (docs/RESILIENCE.md)
    rec.shed = result.status_code == 429
    rec.tokens_in = result.tokens_in
    rec.tokens_out = result.tokens_out
    rec.latency_ms = (rec.end_ts - rec.start_ts) * 1000.0
    rec.ttft_ms = rec.latency_ms
    if guard is not None and tenant.protected and rec.ok:
        guard.observe(rec.latency_ms)
    return rec


async def run_fairness_async(
    url: str,
    tenants: list[TenantConfig],
    run_dir: RunDir,
    model: str = "default",
    backend: str = "openai",
    duration_s: float = 20.0,
    guard: Optional[Guard] = None,
    seed: int = 42,
    timeout_s: float = 60.0,
) -> list[RequestRecord]:
    adapter = get_adapter(backend)
    t_start = time.time()
    total_conc = sum(t.concurrency for t in tenants)
    limits = httpx.Limits(max_connections=total_conc + 4)
    tasks = []
    async with httpx.AsyncClient(timeout=timeout_s, limits=limits) as client:
        for ti, tenant in enumerate(tenants):
            arrivals = generate_arrival_times(
                tenant.pattern, tenant.requests, duration_s, seed=seed + ti
            )
            sem = asyncio.Semaphore(tenant.concurrency)
            prompt_fn = make_prompt_fn("default", seed=seed + ti)
            tasks.extend(
                _tenant_worker(
                    i, off, t_start, tenant, url, model, adapter, client, sem,
                    prompt_fn, guard,
                )
                for i, off in enumerate(arrivals)
            )
        records = await asyncio.gather(*tasks)
    records = sorted(records, key=lambda r: r.start_ts)
    run_dir.path.mkdir(parents=True, exist_ok=True)
    run_dir.write_requests(records)
    run_dir.write_meta(
        {
            "url": url,
            "model": model,
            "mode": "fairness_dual_tenant",
            "tenants": [t.name for t in tenants],
            "duration_s": duration_s,
            "started_at": t_start,
            "finished_at": time.time(),
        }
    )
    return list(records)


def summarize(
    records: list[RequestRecord], guard: Optional[Guard] = None
) -> dict[str, Any]:
    """Per-tenant latency/throughput + the cross-tenant fairness metrics the
    SLO gate budgets against (fairness_dual_tenant.py:177-198)."""
    by_tenant: dict[str, list[RequestRecord]] = {}
    for r in records:
        by_tenant.setdefault(r.tenant or "default", []).append(r)
    total_ok = sum(1 for r in records if r.ok)
    tenants: dict[str, Any] = {}
    p95s: dict[str, float] = {}
    shares: dict[str, float] = {}
    for name, recs in sorted(by_tenant.items()):
        lats = [r.latency_ms for r in recs if r.ok]
        ok = len(lats)
        sheds = sum(1 for r in recs if r.shed)
        t0 = min((r.start_ts for r in recs), default=0.0)
        t1 = max((r.end_ts for r in recs), default=0.0)
        span = max(t1 - t0, 1e-9)
        p95s[name] = percentile(lats, 95.0) if lats else float("nan")
        shares[name] = ok / total_ok if total_ok else 0.0
        tenants[name] = {
            "requests": len(recs),
            "ok": ok,
            # sheds are backpressure doing its job (door-level 429s, or
            # the fleet router's fleet-level admission) — reported in
            # their own column, EXCLUDED from the error rate, mirroring
            # the loadgen's shed/error split (docs/RESILIENCE.md)
            "sheds": sheds,
            "shed_rate": sheds / len(recs) if recs else 0.0,
            "error_rate": (
                (len(recs) - ok - sheds) / len(recs) if recs else 0.0
            ),
            "p50_ms": percentile(lats, 50.0) if lats else None,
            "p95_ms": p95s[name] if lats else None,
            "throughput_rps": ok / span,
            "throughput_share": shares[name],
        }
    valid_p95 = {k: v for k, v in p95s.items() if v == v}  # drop NaN
    summary: dict[str, Any] = {"tenants": tenants}
    if len(valid_p95) >= 2:
        summary["fairness_p95_ratio"] = max(valid_p95.values()) / max(
            min(valid_p95.values()), 1e-9
        )
    if shares:
        summary["fairness_throughput_share_min_tenant"] = min(shares.values())
    if guard is not None:
        summary["guard"] = {
            "p95_budget_ms": guard.p95_budget_ms,
            "throttle_events": guard.throttle_events,
            "throttled_s": round(guard.total_throttled_s(), 3),
        }
    return summary


def fairness_html(summary: dict[str, Any]) -> str:
    from html import escape

    rows = []
    for raw_name, t in summary["tenants"].items():
        name = escape(raw_name)
        rows.append(
            f"<tr><td>{name}</td><td>{t['requests']}</td>"
            f"<td>{t['p50_ms']:.1f}</td><td>{t['p95_ms']:.1f}</td>"
            f"<td>{t['throughput_rps']:.2f}</td>"
            f"<td>{100 * t['throughput_share']:.1f}%</td></tr>"
            if t["p50_ms"] is not None
            else f"<tr><td>{name}</td><td>{t['requests']}</td>"
                 f"<td>—</td><td>—</td><td>—</td><td>—</td></tr>"
        )
    guard_line = ""
    if "guard" in summary:
        g = summary["guard"]
        guard_line = (
            f"<p>guard: budget {g['p95_budget_ms']:.0f} ms, "
            f"{g['throttle_events']} throttle events, "
            f"{g['throttled_s']:.1f}s throttled</p>"
        )
    ratio = summary.get("fairness_p95_ratio")
    return f"""<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>Fairness report</title>
<style>body{{font-family:system-ui;margin:2rem}}table{{border-collapse:collapse}}
td,th{{border:1px solid #ccc;padding:.4rem .8rem;text-align:right}}
td:first-child,th:first-child{{text-align:left}}</style></head>
<body><h1>Dual-tenant fairness</h1>
<p>p95 ratio (worst/best tenant): {f"{ratio:.2f}" if ratio else "—"}</p>{guard_line}
<table><tr><th>tenant</th><th>requests</th><th>p50 ms</th><th>p95 ms</th>
<th>RPS</th><th>share</th></tr>
{''.join(rows)}
</table></body></html>
"""


# -- CLI ---------------------------------------------------------------------

def register(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("--url", required=True,
                        help="Endpoint under test: a single server, or "
                             "the fleet router (kvmini-tpu fleet) — "
                             "against the router the probe exercises "
                             "FLEET-level backpressure: per-replica "
                             "429s are absorbed by re-placement and "
                             "only fleet-wide overload sheds, landing "
                             "in the tenants' shed column "
                             "(docs/FLEET.md)")
    parser.add_argument("--model", default="default")
    parser.add_argument("--backend", default="openai")
    parser.add_argument("--duration", type=float, default=20.0)
    parser.add_argument("--requests-a", type=int, default=100)
    parser.add_argument("--requests-b", type=int, default=100)
    parser.add_argument("--concurrency-a", type=int, default=4)
    parser.add_argument("--concurrency-b", type=int, default=16)
    parser.add_argument("--max-tokens", type=int, default=32)
    parser.add_argument("--p95-budget-ms", type=float, default=0.0,
                        help="Enable the backpressure guard at this budget")
    parser.add_argument("--cooldown", type=float, default=2.0)
    parser.add_argument("--run-dir", default=None)
    parser.add_argument("--slo", default=None, help="Gate fairness metrics against slo.json")
    parser.add_argument("--html", default=None)


def run(args: argparse.Namespace) -> int:
    tenants = [
        TenantConfig("tenant-a", args.requests_a, args.concurrency_a,
                     max_tokens=args.max_tokens, protected=True),
        TenantConfig("tenant-b", args.requests_b, args.concurrency_b,
                     max_tokens=args.max_tokens),
    ]
    guard = Guard(args.p95_budget_ms, args.cooldown) if args.p95_budget_ms > 0 else None
    run_dir = RunDir(args.run_dir) if args.run_dir else RunDir.create()
    records = asyncio.run(
        run_fairness_async(
            args.url, tenants, run_dir, model=args.model, backend=args.backend,
            duration_s=args.duration, guard=guard,
        )
    )
    summary = summarize(records, guard)
    with (run_dir.path / "fairness_summary.json").open("w") as f:
        json.dump(summary, f, indent=2)
    run_dir.merge_into_results(
        {
            k: summary[k]
            for k in ("fairness_p95_ratio", "fairness_throughput_share_min_tenant")
            if k in summary
        }
    )
    if args.html:
        Path(args.html).write_text(fairness_html(summary))
    for name, t in summary["tenants"].items():
        p95 = f"{t['p95_ms']:.1f}" if t["p95_ms"] is not None else "—"
        print(
            f"{name}: {t['ok']}/{t['requests']} ok, p95 {p95} ms, "
            f"{t['throughput_rps']:.2f} rps, share {100 * t['throughput_share']:.0f}%"
        )
    if "fairness_p95_ratio" in summary:
        print(f"p95 ratio: {summary['fairness_p95_ratio']:.2f}")
    if args.slo:
        from kserve_vllm_mini_tpu.gates.slo import gate_results, load_slo, print_table

        budgets = {
            k: v for k, v in load_slo(args.slo).items() if k.startswith("fairness_")
        }
        if budgets:
            verdicts = gate_results(run_dir.read_results(), budgets)
            print_table(verdicts)
            if not all(v.ok for v in verdicts):
                return 3
    return 0
