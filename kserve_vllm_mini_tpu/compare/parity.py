"""OpenAI API conformance probe: which capabilities does an endpoint really
support?

Reference behavior (scripts/openai_parity_probe.py:32-318): probe five
capabilities — tool calling, parallel tool calling, JSON mode, logprobs, and
streaming shape/TTFT — against a /v1/chat/completions endpoint, emit a
capability matrix as JSON + HTML. Each probe is independent: a failure marks
the capability unsupported with detail, never aborts the matrix.

TPU relevance: JetStream, vLLM-TPU, and the in-repo runtime differ exactly
here (JetStream's HTTP server speaks a narrower dialect), so the matrix is
what tells an operator which profiles (tool-calling.yaml,
structured-output.yaml) a backend can run.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Optional

import httpx

CAPABILITIES = [
    "tools", "parallel_tools", "json_mode", "logprobs", "streaming",
    "sampling_penalties", "n_choices",
]

_WEATHER_TOOL = {
    "type": "function",
    "function": {
        "name": "get_weather",
        "description": "Get current weather for a city",
        "parameters": {
            "type": "object",
            "properties": {"city": {"type": "string"}},
            "required": ["city"],
        },
    },
}

_TIME_TOOL = {
    "type": "function",
    "function": {
        "name": "get_time",
        "description": "Get current local time for a city",
        "parameters": {
            "type": "object",
            "properties": {"city": {"type": "string"}},
            "required": ["city"],
        },
    },
}


@dataclass
class CapabilityResult:
    capability: str
    supported: bool
    latency_ms: float = 0.0
    detail: str = ""
    extra: dict[str, Any] = field(default_factory=dict)

    def as_dict(self) -> dict[str, Any]:
        return {
            "capability": self.capability,
            "supported": self.supported,
            "latency_ms": round(self.latency_ms, 1),
            "detail": self.detail,
            **self.extra,
        }


class ParityProber:
    """Async prober bound to one endpoint. One shared client; each probe is
    a single chat-completions call with capability-specific payload."""

    def __init__(self, base_url: str, model: str = "default", timeout_s: float = 30.0):
        self.base_url = base_url.rstrip("/")
        self.model = model
        self.timeout_s = timeout_s

    async def _chat(
        self, client: httpx.AsyncClient, payload: dict[str, Any]
    ) -> tuple[int, dict[str, Any], float]:
        body = {"model": self.model, **payload}
        t0 = time.time()
        resp = await client.post(f"{self.base_url}/v1/chat/completions", json=body)
        latency = (time.time() - t0) * 1000.0
        try:
            data = resp.json()
        except Exception:
            data = {}
        return resp.status_code, data, latency

    @staticmethod
    def _tool_calls(data: dict[str, Any]) -> list[dict[str, Any]]:
        try:
            return data["choices"][0]["message"].get("tool_calls") or []
        except (KeyError, IndexError, TypeError):
            return []

    async def probe_tools(self, client: httpx.AsyncClient) -> CapabilityResult:
        status, data, ms = await self._chat(
            client,
            {
                "messages": [{"role": "user", "content": "What is the weather in Paris?"}],
                "tools": [_WEATHER_TOOL],
                "tool_choice": "auto",
                "max_tokens": 64,
            },
        )
        if status != 200:
            return CapabilityResult("tools", False, ms, f"HTTP {status}")
        calls = self._tool_calls(data)
        if not calls:
            return CapabilityResult("tools", False, ms, "no tool_calls in response")
        fn = calls[0].get("function", {})
        try:
            args = json.loads(fn.get("arguments", "{}"))
            args_ok = isinstance(args, dict)
        except json.JSONDecodeError:
            args_ok = False
        if fn.get("name") != "get_weather" or not args_ok:
            return CapabilityResult(
                "tools", False, ms, f"malformed tool call: name={fn.get('name')!r}"
            )
        return CapabilityResult("tools", True, ms, "returned well-formed tool_calls")

    async def probe_parallel_tools(self, client: httpx.AsyncClient) -> CapabilityResult:
        status, data, ms = await self._chat(
            client,
            {
                "messages": [
                    {
                        "role": "user",
                        "content": "What are the weather and the local time in Paris? "
                                   "Use both tools.",
                    }
                ],
                "tools": [_WEATHER_TOOL, _TIME_TOOL],
                "tool_choice": "auto",
                "parallel_tool_calls": True,
                "max_tokens": 128,
            },
        )
        if status != 200:
            return CapabilityResult("parallel_tools", False, ms, f"HTTP {status}")
        calls = self._tool_calls(data)
        names = {c.get("function", {}).get("name") for c in calls}
        if len(calls) >= 2 and {"get_weather", "get_time"} <= names:
            return CapabilityResult(
                "parallel_tools", True, ms, f"{len(calls)} tool calls in one turn"
            )
        return CapabilityResult(
            "parallel_tools", False, ms, f"got {len(calls)} tool call(s): {sorted(filter(None, names))}"
        )

    async def probe_json_mode(self, client: httpx.AsyncClient) -> CapabilityResult:
        status, data, ms = await self._chat(
            client,
            {
                "messages": [
                    {
                        "role": "user",
                        "content": 'Return a JSON object with keys "city" and "country" for Paris.',
                    }
                ],
                "response_format": {"type": "json_object"},
                "max_tokens": 64,
            },
        )
        if status != 200:
            return CapabilityResult("json_mode", False, ms, f"HTTP {status}")
        try:
            content = data["choices"][0]["message"]["content"]
        except (KeyError, IndexError, TypeError):
            return CapabilityResult("json_mode", False, ms, "no message content")
        try:
            parsed = json.loads(content)
        except (json.JSONDecodeError, TypeError):
            return CapabilityResult("json_mode", False, ms, "content is not valid JSON")
        if not isinstance(parsed, dict):
            return CapabilityResult("json_mode", False, ms, "content is JSON but not an object")
        return CapabilityResult("json_mode", True, ms, "content parsed as a JSON object")

    async def probe_logprobs(self, client: httpx.AsyncClient) -> CapabilityResult:
        status, data, ms = await self._chat(
            client,
            {
                "messages": [{"role": "user", "content": "Say hello."}],
                "logprobs": True,
                "top_logprobs": 2,
                "max_tokens": 8,
            },
        )
        if status != 200:
            return CapabilityResult("logprobs", False, ms, f"HTTP {status}")
        try:
            lp = data["choices"][0].get("logprobs")
            content = (lp or {}).get("content") or []
        except (KeyError, IndexError, TypeError):
            return CapabilityResult("logprobs", False, ms, "malformed choices")
        if not content:
            return CapabilityResult("logprobs", False, ms, "no logprobs.content entries")
        entry = content[0]
        if "logprob" not in entry:
            return CapabilityResult("logprobs", False, ms, "entries missing 'logprob'")
        return CapabilityResult(
            "logprobs", True, ms, f"{len(content)} token logprob entries"
        )

    async def probe_streaming(self, client: httpx.AsyncClient) -> CapabilityResult:
        """SSE shape check + client TTFT (openai_parity_probe.py:214-248):
        chunks must be `data:` frames of chat.completion.chunk-shaped JSON
        ending with [DONE]."""
        body = {
            "model": self.model,
            "messages": [{"role": "user", "content": "Count to five."}],
            "stream": True,
            "max_tokens": 32,
        }
        t0 = time.time()
        chunks = 0
        ttft_ms = 0.0
        saw_done = False
        malformed = 0
        try:
            async with client.stream(
                "POST", f"{self.base_url}/v1/chat/completions", json=body
            ) as resp:
                if resp.status_code != 200:
                    return CapabilityResult(
                        "streaming", False, (time.time() - t0) * 1000.0,
                        f"HTTP {resp.status_code}",
                    )
                async for line in resp.aiter_lines():
                    line = line.strip()
                    if not line.startswith("data:"):
                        continue
                    payload = line[5:].strip()
                    if payload == "[DONE]":
                        saw_done = True
                        break
                    try:
                        evt = json.loads(payload)
                        if "choices" not in evt:
                            malformed += 1
                    except json.JSONDecodeError:
                        malformed += 1
                        continue
                    chunks += 1
                    if chunks == 1:
                        ttft_ms = (time.time() - t0) * 1000.0
        except httpx.HTTPError as e:
            return CapabilityResult(
                "streaming", False, (time.time() - t0) * 1000.0, f"{type(e).__name__}: {e}"
            )
        total_ms = (time.time() - t0) * 1000.0
        ok = chunks >= 1 and saw_done and malformed == 0
        detail = (
            f"{chunks} chunks, DONE={saw_done}, malformed={malformed}"
        )
        return CapabilityResult(
            "streaming", ok, total_ms, detail,
            extra={"ttft_ms": round(ttft_ms, 1), "chunks": chunks},
        )

    async def probe_sampling_penalties(
        self, client: httpx.AsyncClient
    ) -> CapabilityResult:
        """presence/frequency penalties must be accepted AND change the
        output (reference scripts/loadtest.py:260-342 sends them; vLLM
        honors them). Greedy + a large frequency penalty forbids token
        repetition, so a repeat-y baseline and a penalized run must differ
        unless the baseline already never repeats a token."""
        base_body = {
            "messages": [{"role": "user", "content": "ha ha ha ha ha"}],
            "max_tokens": 24,
            "temperature": 0,
        }
        status, data, ms = await self._chat(client, base_body)
        if status != 200:
            return CapabilityResult("sampling_penalties", False, ms, f"HTTP {status}")
        baseline = data["choices"][0]["message"].get("content") or ""
        status2, data2, ms2 = await self._chat(
            client, {**base_body, "frequency_penalty": 2.0, "presence_penalty": 1.5},
        )
        if status2 != 200:
            return CapabilityResult(
                "sampling_penalties", False, ms + ms2,
                f"penalized request HTTP {status2}",
            )
        penalized = data2["choices"][0]["message"].get("content") or ""
        # a server that silently drops the knobs returns the identical
        # greedy string; identical AND internally repetitive => dropped.
        # Penalties operate on TOKENS, so whitespace words alone miss
        # intra-word repetition ("hahahaha" is one word but heavily
        # token-repetitive) — also flag any 4-char substring occurring 3+
        # times (a character 4-gram repeated that often implies a repeated
        # token for every practical tokenizer).
        words = baseline.split()
        rep_gram = any(
            baseline.count(baseline[i:i + 4]) >= 3
            for i in range(max(len(baseline) - 3, 0))
        )
        repetitive = len(words) > len(set(words)) or rep_gram
        if penalized == baseline and repetitive:
            return CapabilityResult(
                "sampling_penalties", False, ms + ms2,
                "penalties accepted but output unchanged (likely ignored)",
            )
        return CapabilityResult(
            "sampling_penalties", True, ms + ms2,
            "accepted and output diverged" if penalized != baseline
            else "accepted (baseline had no repetition to penalize)",
        )

    async def probe_n_choices(self, client: httpx.AsyncClient) -> CapabilityResult:
        """n>1 must return n distinct-index choices in one response."""
        status, data, ms = await self._chat(
            client,
            {
                "messages": [{"role": "user", "content": "Pick a number."}],
                "max_tokens": 8,
                "temperature": 0.9,
                "n": 2,
            },
        )
        if status != 200:
            return CapabilityResult("n_choices", False, ms, f"HTTP {status}")
        choices = data.get("choices") or []
        if len(choices) != 2:
            return CapabilityResult(
                "n_choices", False, ms, f"asked n=2, got {len(choices)} choices"
            )
        idxs = sorted(c.get("index") for c in choices)
        if idxs != [0, 1]:
            return CapabilityResult(
                "n_choices", False, ms, f"choice indexes {idxs} != [0, 1]"
            )
        return CapabilityResult("n_choices", True, ms, "2 choices, indexes [0, 1]")

    async def probe_all(self) -> list[CapabilityResult]:
        async with httpx.AsyncClient(timeout=self.timeout_s) as client:
            results = []
            for probe in (
                self.probe_tools,
                self.probe_parallel_tools,
                self.probe_json_mode,
                self.probe_logprobs,
                self.probe_streaming,
                self.probe_sampling_penalties,
                self.probe_n_choices,
            ):
                try:
                    results.append(await probe(client))
                except Exception as e:  # noqa: BLE001 — one probe must not kill the matrix
                    name = probe.__name__.removeprefix("probe_")
                    results.append(
                        CapabilityResult(name, False, 0.0, f"{type(e).__name__}: {e}")
                    )
            return results


def matrix_dict(url: str, model: str, results: list[CapabilityResult]) -> dict[str, Any]:
    return {
        "endpoint": url,
        "model": model,
        "capabilities": {r.capability: r.as_dict() for r in results},
        "supported_count": sum(1 for r in results if r.supported),
        "total": len(results),
    }


def matrix_html(matrix: dict[str, Any]) -> str:
    from html import escape

    rows = []
    for name, r in matrix["capabilities"].items():
        badge = "✓" if r["supported"] else "✗"
        color = "#0a7a33" if r["supported"] else "#b3261e"
        rows.append(
            f"<tr><td>{escape(name)}</td>"
            f"<td style='color:{color};font-weight:bold'>{badge}</td>"
            f"<td>{r['latency_ms']:.0f} ms</td><td>{escape(r['detail'])}</td></tr>"
        )
    return f"""<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>OpenAI parity matrix</title>
<style>body{{font-family:system-ui;margin:2rem}}table{{border-collapse:collapse}}
td,th{{border:1px solid #ccc;padding:.4rem .8rem;text-align:left}}</style></head>
<body><h1>OpenAI API parity matrix</h1>
<p>endpoint: <code>{escape(matrix['endpoint'])}</code> · model: <code>{escape(matrix['model'])}</code>
· {matrix['supported_count']}/{matrix['total']} capabilities supported</p>
<table><tr><th>capability</th><th>supported</th><th>latency</th><th>detail</th></tr>
{''.join(rows)}
</table></body></html>
"""


# -- CLI ---------------------------------------------------------------------

def register(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("--url", required=True)
    parser.add_argument("--model", default="default")
    parser.add_argument("--timeout", type=float, default=30.0)
    parser.add_argument("--output", default=None, help="Write matrix JSON here")
    parser.add_argument("--html", default=None, help="Write HTML matrix here")


def run(args: argparse.Namespace) -> int:
    prober = ParityProber(args.url, args.model, args.timeout)
    results = asyncio.run(prober.probe_all())
    matrix = matrix_dict(args.url, args.model, results)
    for r in results:
        mark = "PASS" if r.supported else "FAIL"
        print(f"{r.capability:<16} {mark}  {r.latency_ms:7.0f} ms  {r.detail}")
    print(f"{matrix['supported_count']}/{matrix['total']} capabilities supported")
    if args.output:
        Path(args.output).parent.mkdir(parents=True, exist_ok=True)
        Path(args.output).write_text(json.dumps(matrix, indent=2))
    if args.html:
        Path(args.html).parent.mkdir(parents=True, exist_ok=True)
        Path(args.html).write_text(matrix_html(matrix))
    return 0
