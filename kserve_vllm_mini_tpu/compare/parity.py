"""OpenAI API conformance probe: which capabilities does an endpoint really
support?

Reference behavior (scripts/openai_parity_probe.py:32-318): probe five
capabilities — tool calling, parallel tool calling, JSON mode, logprobs, and
streaming shape/TTFT — against a /v1/chat/completions endpoint, emit a
capability matrix as JSON + HTML. Each probe is independent: a failure marks
the capability unsupported with detail, never aborts the matrix.

TPU relevance: JetStream, vLLM-TPU, and the in-repo runtime differ exactly
here (JetStream's HTTP server speaks a narrower dialect), so the matrix is
what tells an operator which profiles (tool-calling.yaml,
structured-output.yaml) a backend can run.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Optional

import httpx

CAPABILITIES = ["tools", "parallel_tools", "json_mode", "logprobs", "streaming"]

_WEATHER_TOOL = {
    "type": "function",
    "function": {
        "name": "get_weather",
        "description": "Get current weather for a city",
        "parameters": {
            "type": "object",
            "properties": {"city": {"type": "string"}},
            "required": ["city"],
        },
    },
}

_TIME_TOOL = {
    "type": "function",
    "function": {
        "name": "get_time",
        "description": "Get current local time for a city",
        "parameters": {
            "type": "object",
            "properties": {"city": {"type": "string"}},
            "required": ["city"],
        },
    },
}


@dataclass
class CapabilityResult:
    capability: str
    supported: bool
    latency_ms: float = 0.0
    detail: str = ""
    extra: dict[str, Any] = field(default_factory=dict)

    def as_dict(self) -> dict[str, Any]:
        return {
            "capability": self.capability,
            "supported": self.supported,
            "latency_ms": round(self.latency_ms, 1),
            "detail": self.detail,
            **self.extra,
        }


class ParityProber:
    """Async prober bound to one endpoint. One shared client; each probe is
    a single chat-completions call with capability-specific payload."""

    def __init__(self, base_url: str, model: str = "default", timeout_s: float = 30.0):
        self.base_url = base_url.rstrip("/")
        self.model = model
        self.timeout_s = timeout_s

    async def _chat(
        self, client: httpx.AsyncClient, payload: dict[str, Any]
    ) -> tuple[int, dict[str, Any], float]:
        body = {"model": self.model, **payload}
        t0 = time.time()
        resp = await client.post(f"{self.base_url}/v1/chat/completions", json=body)
        latency = (time.time() - t0) * 1000.0
        try:
            data = resp.json()
        except Exception:
            data = {}
        return resp.status_code, data, latency

    @staticmethod
    def _tool_calls(data: dict[str, Any]) -> list[dict[str, Any]]:
        try:
            return data["choices"][0]["message"].get("tool_calls") or []
        except (KeyError, IndexError, TypeError):
            return []

    async def probe_tools(self, client: httpx.AsyncClient) -> CapabilityResult:
        status, data, ms = await self._chat(
            client,
            {
                "messages": [{"role": "user", "content": "What is the weather in Paris?"}],
                "tools": [_WEATHER_TOOL],
                "tool_choice": "auto",
                "max_tokens": 64,
            },
        )
        if status != 200:
            return CapabilityResult("tools", False, ms, f"HTTP {status}")
        calls = self._tool_calls(data)
        if not calls:
            return CapabilityResult("tools", False, ms, "no tool_calls in response")
        fn = calls[0].get("function", {})
        try:
            args = json.loads(fn.get("arguments", "{}"))
            args_ok = isinstance(args, dict)
        except json.JSONDecodeError:
            args_ok = False
        if fn.get("name") != "get_weather" or not args_ok:
            return CapabilityResult(
                "tools", False, ms, f"malformed tool call: name={fn.get('name')!r}"
            )
        return CapabilityResult("tools", True, ms, "returned well-formed tool_calls")

    async def probe_parallel_tools(self, client: httpx.AsyncClient) -> CapabilityResult:
        status, data, ms = await self._chat(
            client,
            {
                "messages": [
                    {
                        "role": "user",
                        "content": "What are the weather and the local time in Paris? "
                                   "Use both tools.",
                    }
                ],
                "tools": [_WEATHER_TOOL, _TIME_TOOL],
                "tool_choice": "auto",
                "parallel_tool_calls": True,
                "max_tokens": 128,
            },
        )
        if status != 200:
            return CapabilityResult("parallel_tools", False, ms, f"HTTP {status}")
        calls = self._tool_calls(data)
        names = {c.get("function", {}).get("name") for c in calls}
        if len(calls) >= 2 and {"get_weather", "get_time"} <= names:
            return CapabilityResult(
                "parallel_tools", True, ms, f"{len(calls)} tool calls in one turn"
            )
        return CapabilityResult(
            "parallel_tools", False, ms, f"got {len(calls)} tool call(s): {sorted(filter(None, names))}"
        )

    async def probe_json_mode(self, client: httpx.AsyncClient) -> CapabilityResult:
        status, data, ms = await self._chat(
            client,
            {
                "messages": [
                    {
                        "role": "user",
                        "content": 'Return a JSON object with keys "city" and "country" for Paris.',
                    }
                ],
                "response_format": {"type": "json_object"},
                "max_tokens": 64,
            },
        )
        if status != 200:
            return CapabilityResult("json_mode", False, ms, f"HTTP {status}")
        try:
            content = data["choices"][0]["message"]["content"]
        except (KeyError, IndexError, TypeError):
            return CapabilityResult("json_mode", False, ms, "no message content")
        try:
            parsed = json.loads(content)
        except (json.JSONDecodeError, TypeError):
            return CapabilityResult("json_mode", False, ms, "content is not valid JSON")
        if not isinstance(parsed, dict):
            return CapabilityResult("json_mode", False, ms, "content is JSON but not an object")
        return CapabilityResult("json_mode", True, ms, "content parsed as a JSON object")

    async def probe_logprobs(self, client: httpx.AsyncClient) -> CapabilityResult:
        status, data, ms = await self._chat(
            client,
            {
                "messages": [{"role": "user", "content": "Say hello."}],
                "logprobs": True,
                "top_logprobs": 2,
                "max_tokens": 8,
            },
        )
        if status != 200:
            return CapabilityResult("logprobs", False, ms, f"HTTP {status}")
        try:
            lp = data["choices"][0].get("logprobs")
            content = (lp or {}).get("content") or []
        except (KeyError, IndexError, TypeError):
            return CapabilityResult("logprobs", False, ms, "malformed choices")
        if not content:
            return CapabilityResult("logprobs", False, ms, "no logprobs.content entries")
        entry = content[0]
        if "logprob" not in entry:
            return CapabilityResult("logprobs", False, ms, "entries missing 'logprob'")
        return CapabilityResult(
            "logprobs", True, ms, f"{len(content)} token logprob entries"
        )

    async def probe_streaming(self, client: httpx.AsyncClient) -> CapabilityResult:
        """SSE shape check + client TTFT (openai_parity_probe.py:214-248):
        chunks must be `data:` frames of chat.completion.chunk-shaped JSON
        ending with [DONE]."""
        body = {
            "model": self.model,
            "messages": [{"role": "user", "content": "Count to five."}],
            "stream": True,
            "max_tokens": 32,
        }
        t0 = time.time()
        chunks = 0
        ttft_ms = 0.0
        saw_done = False
        malformed = 0
        try:
            async with client.stream(
                "POST", f"{self.base_url}/v1/chat/completions", json=body
            ) as resp:
                if resp.status_code != 200:
                    return CapabilityResult(
                        "streaming", False, (time.time() - t0) * 1000.0,
                        f"HTTP {resp.status_code}",
                    )
                async for line in resp.aiter_lines():
                    line = line.strip()
                    if not line.startswith("data:"):
                        continue
                    payload = line[5:].strip()
                    if payload == "[DONE]":
                        saw_done = True
                        break
                    try:
                        evt = json.loads(payload)
                        if "choices" not in evt:
                            malformed += 1
                    except json.JSONDecodeError:
                        malformed += 1
                        continue
                    chunks += 1
                    if chunks == 1:
                        ttft_ms = (time.time() - t0) * 1000.0
        except httpx.HTTPError as e:
            return CapabilityResult(
                "streaming", False, (time.time() - t0) * 1000.0, f"{type(e).__name__}: {e}"
            )
        total_ms = (time.time() - t0) * 1000.0
        ok = chunks >= 1 and saw_done and malformed == 0
        detail = (
            f"{chunks} chunks, DONE={saw_done}, malformed={malformed}"
        )
        return CapabilityResult(
            "streaming", ok, total_ms, detail,
            extra={"ttft_ms": round(ttft_ms, 1), "chunks": chunks},
        )

    async def probe_all(self) -> list[CapabilityResult]:
        async with httpx.AsyncClient(timeout=self.timeout_s) as client:
            results = []
            for probe in (
                self.probe_tools,
                self.probe_parallel_tools,
                self.probe_json_mode,
                self.probe_logprobs,
                self.probe_streaming,
            ):
                try:
                    results.append(await probe(client))
                except Exception as e:  # noqa: BLE001 — one probe must not kill the matrix
                    name = probe.__name__.removeprefix("probe_")
                    results.append(
                        CapabilityResult(name, False, 0.0, f"{type(e).__name__}: {e}")
                    )
            return results


def matrix_dict(url: str, model: str, results: list[CapabilityResult]) -> dict[str, Any]:
    return {
        "endpoint": url,
        "model": model,
        "capabilities": {r.capability: r.as_dict() for r in results},
        "supported_count": sum(1 for r in results if r.supported),
        "total": len(results),
    }


def matrix_html(matrix: dict[str, Any]) -> str:
    from html import escape

    rows = []
    for name, r in matrix["capabilities"].items():
        badge = "✓" if r["supported"] else "✗"
        color = "#0a7a33" if r["supported"] else "#b3261e"
        rows.append(
            f"<tr><td>{escape(name)}</td>"
            f"<td style='color:{color};font-weight:bold'>{badge}</td>"
            f"<td>{r['latency_ms']:.0f} ms</td><td>{escape(r['detail'])}</td></tr>"
        )
    return f"""<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>OpenAI parity matrix</title>
<style>body{{font-family:system-ui;margin:2rem}}table{{border-collapse:collapse}}
td,th{{border:1px solid #ccc;padding:.4rem .8rem;text-align:left}}</style></head>
<body><h1>OpenAI API parity matrix</h1>
<p>endpoint: <code>{escape(matrix['endpoint'])}</code> · model: <code>{escape(matrix['model'])}</code>
· {matrix['supported_count']}/{matrix['total']} capabilities supported</p>
<table><tr><th>capability</th><th>supported</th><th>latency</th><th>detail</th></tr>
{''.join(rows)}
</table></body></html>
"""


# -- CLI ---------------------------------------------------------------------

def register(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("--url", required=True)
    parser.add_argument("--model", default="default")
    parser.add_argument("--timeout", type=float, default=30.0)
    parser.add_argument("--output", default=None, help="Write matrix JSON here")
    parser.add_argument("--html", default=None, help="Write HTML matrix here")


def run(args: argparse.Namespace) -> int:
    prober = ParityProber(args.url, args.model, args.timeout)
    results = asyncio.run(prober.probe_all())
    matrix = matrix_dict(args.url, args.model, results)
    for r in results:
        mark = "PASS" if r.supported else "FAIL"
        print(f"{r.capability:<16} {mark}  {r.latency_ms:7.0f} ms  {r.detail}")
    print(f"{matrix['supported_count']}/{matrix['total']} capabilities supported")
    if args.output:
        Path(args.output).parent.mkdir(parents=True, exist_ok=True)
        Path(args.output).write_text(json.dumps(matrix, indent=2))
    if args.html:
        Path(args.html).parent.mkdir(parents=True, exist_ok=True)
        Path(args.html).write_text(matrix_html(matrix))
    return 0
