"""A/B/C backend comparison under identical load.

Reference behavior: runners/ab-compare.sh:142-394 deploys each backend
serially, runs the same profile (optionally once streaming and once not),
extracts a fixed metric row per run into a unified CSV, then computes
per-metric winners into comparison_report.json;
scripts/compare_backends.py:69-90 defines direction-aware winner selection.

TPU-first differences: targets are either live endpoint URLs (any mix of
jetstream / vllm-tpu / external), or the in-repo JAX runtime booted
in-process (``self-serve``) — so a full comparison runs with no cluster at
all. One bench path (bench_pipeline.run_bench) replaces the reference's three
divergent invoke.sh clients, and the bench function is injectable for tests.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Callable, Optional

# CSV row layout, mirroring the reference's unified CSV (ab-compare.sh:140)
# with TPU additions (tokens_per_sec_per_chip, energy).
COMPARE_CSV_COLUMNS = [
    "backend",
    "streaming",
    "requests",
    "concurrency",
    "p50_ms",
    "p95_ms",
    "p99_ms",
    "ttft_p50_ms",
    "ttft_p95_ms",
    "mean_ttft_ms",
    "p95_tpot_ms",
    "throughput_rps",
    "tokens_per_sec",
    "tokens_per_sec_per_chip",
    "error_rate",
    "cost_per_1k_tokens",
    "energy_wh_per_1k_tokens",
    "status",
    "error",
    "elapsed_s",
]

# metric -> direction for winner selection (compare_backends.py:69-90).
# "min": lower is better.
WINNER_METRICS: dict[str, str] = {
    "p50_ms": "min",
    "p95_ms": "min",
    "p99_ms": "min",
    "ttft_p50_ms": "min",
    "ttft_p95_ms": "min",
    "mean_ttft_ms": "min",
    "p95_tpot_ms": "min",
    "throughput_rps": "max",
    "tokens_per_sec": "max",
    "tokens_per_sec_per_chip": "max",
    "error_rate": "min",
    "cost_per_1k_tokens": "min",
    "energy_wh_per_1k_tokens": "min",
}


@dataclass
class CompareTarget:
    """One contestant: a named backend and how to reach it."""

    backend: str                     # display/registry name: jetstream | vllm-tpu | jax-native | ...
    url: str = ""                    # live endpoint; "" => self-serve in-repo runtime
    protocol: str = "openai"         # loadgen adapter name


@dataclass
class BackendRunResult:
    """Typed result of one (backend, streaming) bench — the analog of the
    reference's BackendResult dataclass (compare_backends.py:22-58)."""

    backend: str
    streaming: bool
    results: dict[str, Any] = field(default_factory=dict)
    status: str = "ok"
    error: str = ""
    elapsed_s: float = 0.0

    def row(self) -> dict[str, Any]:
        r = self.results
        tpot = r.get("tpot_p95_ms", r.get("p95_tpot_ms"))
        return {
            "backend": self.backend,
            "streaming": int(self.streaming),
            "requests": r.get("requests"),
            "concurrency": r.get("concurrency"),
            "p50_ms": r.get("p50_ms"),
            "p95_ms": r.get("p95_ms"),
            "p99_ms": r.get("p99_ms"),
            "ttft_p50_ms": r.get("ttft_p50_ms"),
            "ttft_p95_ms": r.get("ttft_p95_ms"),
            "mean_ttft_ms": r.get("ttft_avg_ms", r.get("ttft_p50_ms")),
            "p95_tpot_ms": tpot,
            "throughput_rps": r.get("throughput_rps"),
            "tokens_per_sec": r.get("tokens_per_sec"),
            "tokens_per_sec_per_chip": r.get("tokens_per_sec_per_chip"),
            "error_rate": r.get("error_rate"),
            "cost_per_1k_tokens": r.get("cost_per_1k_tokens"),
            "energy_wh_per_1k_tokens": r.get("energy_wh_per_1k_tokens"),
            "status": self.status,
            "error": self.error,
            "elapsed_s": round(self.elapsed_s, 2),
        }


# bench function: (target, profile, streaming) -> flat results dict.
BenchTargetFn = Callable[[CompareTarget, dict[str, Any], bool], dict[str, Any]]


def default_bench_target_fn(
    cost_file: Optional[str] = None, prom_url: Optional[str] = None
) -> BenchTargetFn:
    def bench(target: CompareTarget, profile: dict[str, Any], streaming: bool) -> dict[str, Any]:
        from kserve_vllm_mini_tpu.bench_pipeline import run_bench

        merged = dict(profile)
        merged["streaming"] = streaming
        # the per-target protocol is explicit (--target NAME:PROTOCOL=URL);
        # it must beat any `backend` key a shared profile YAML carries
        merged["backend"] = target.protocol
        results, code = run_bench(
            url=target.url or None,
            profile=merged,
            self_serve=not target.url,
            cost_file=cost_file,
            prom_url=prom_url,
        )
        if not results:
            raise RuntimeError(f"bench exit code {code}")
        return results

    return bench


def pick_winners(rows: list[dict[str, Any]]) -> dict[str, Any]:
    """Per-metric winner across ok rows, split by streaming mode
    (the reference compares streaming and non-streaming separately,
    ab-compare.sh:290-394)."""
    winners: dict[str, Any] = {}
    for streaming in sorted({r.get("streaming") for r in rows}):
        mode_rows = [
            r for r in rows
            if r.get("streaming") == streaming and r.get("status") == "ok"
        ]
        mode: dict[str, Any] = {}
        for metric, direction in WINNER_METRICS.items():
            scored = [
                (float(r[metric]), r["backend"])
                for r in mode_rows
                if r.get(metric) not in (None, "")
            ]
            if not scored:
                continue
            best = min(scored) if direction == "min" else max(scored)
            mode[metric] = {"backend": best[1], "value": best[0], "direction": direction}
        if mode:
            counts: dict[str, int] = {}
            for w in mode.values():
                counts[w["backend"]] = counts.get(w["backend"], 0) + 1
            mode["overall"] = max(counts, key=counts.get)
        winners[f"streaming={streaming}"] = mode
    return winners


def compare_backends(
    targets: list[CompareTarget],
    profile: dict[str, Any],
    output_dir: Path,
    streaming_modes: tuple[bool, ...] = (True, False),
    bench_fn: Optional[BenchTargetFn] = None,
    quiesce_s: float = 0.0,
) -> dict[str, Any]:
    """Run every (target, streaming) cell serially under the identical
    profile; write comparison.csv + comparison_report.json; return the
    report dict. Failure cells record-and-continue
    (ab-compare.sh cleanup/continue behavior :237-248)."""
    from kserve_vllm_mini_tpu.sweeps.base import write_row

    bench_fn = bench_fn or default_bench_target_fn()
    output_dir = Path(output_dir)
    output_dir.mkdir(parents=True, exist_ok=True)
    csv_path = output_dir / "comparison.csv"
    # fresh comparison per invocation: stale rows from a previous run into
    # the same dir must not mix under one header
    csv_path.unlink(missing_ok=True)
    runs: list[BackendRunResult] = []
    for target in targets:
        for streaming in streaming_modes:
            label = f"{target.backend} streaming={streaming}"
            print(f"compare: {label}", file=sys.stderr)
            t0 = time.time()
            try:
                results = bench_fn(target, dict(profile), streaming)
                run = BackendRunResult(target.backend, streaming, results, elapsed_s=time.time() - t0)
            except Exception as e:  # noqa: BLE001 — record-and-continue is the contract
                run = BackendRunResult(
                    target.backend, streaming, {}, status="failed",
                    error=f"{type(e).__name__}: {e}", elapsed_s=time.time() - t0,
                )
                print(f"compare: {label} FAILED: {run.error}", file=sys.stderr)
            runs.append(run)
            write_row(csv_path, run.row(), COMPARE_CSV_COLUMNS)
            if quiesce_s > 0:
                time.sleep(quiesce_s)

    rows = [r.row() for r in runs]
    report = {
        "targets": [t.backend for t in targets],
        "profile": {
            k: profile.get(k)
            for k in ("model", "requests", "concurrency", "pattern", "max_tokens")
        },
        "rows": rows,
        "winners": pick_winners(rows),
        "failed": [r.backend for r in runs if r.status != "ok"],
    }
    with (output_dir / "comparison_report.json").open("w") as f:
        json.dump(report, f, indent=2)
    return report


def format_report(report: dict[str, Any]) -> str:
    lines = [f"backends compared: {', '.join(report['targets'])}"]
    for mode, winners in report.get("winners", {}).items():
        lines.append(f"\n[{mode}]")
        for metric, w in winners.items():
            if metric == "overall":
                continue
            lines.append(f"  {metric:<28} {w['backend']:<14} ({w['value']:.3f})")
        if "overall" in winners:
            lines.append(f"  {'OVERALL':<28} {winners['overall']}")
    if report.get("failed"):
        lines.append(f"\nfailed cells: {', '.join(report['failed'])}")
    return "\n".join(lines)


# -- CLI ---------------------------------------------------------------------

def register(parser: argparse.ArgumentParser) -> None:
    parser.add_argument(
        "--target", action="append", required=True, metavar="NAME[=URL]",
        help="Backend to compare; repeatable. NAME alone self-serves the "
             "in-repo runtime; NAME=URL hits a live endpoint. "
             "Optional protocol suffix NAME:PROTOCOL=URL.",
    )
    # None defaults so an explicit flag always beats the profile YAML
    # (same pattern as bench_pipeline.run)
    parser.add_argument("--profile", default=None, help="YAML load profile")
    parser.add_argument("--model", default=None)
    parser.add_argument("--requests", type=int, default=None)
    parser.add_argument("--concurrency", type=int, default=None)
    parser.add_argument("--max-tokens", type=int, default=None)
    parser.add_argument("--pattern", default=None)
    parser.add_argument("--streaming", choices=["both", "on", "off"], default="both")
    parser.add_argument("--output-dir", default="runs/compare")
    parser.add_argument("--cost-file", default=None)
    parser.add_argument("--quiesce", type=float, default=0.0,
                        help="Seconds to sleep between cells (cluster quiesce)")


def _parse_target(spec: str) -> CompareTarget:
    name, _, url = spec.partition("=")
    name, _, proto = name.partition(":")
    return CompareTarget(backend=name, url=url, protocol=proto or "openai")


def run(args: argparse.Namespace) -> int:
    profile: dict[str, Any] = {}
    if args.profile:
        import yaml

        with open(args.profile) as f:
            profile = yaml.safe_load(f) or {}
    overrides = {
        "model": args.model,
        "requests": args.requests,
        "concurrency": args.concurrency,
        "max_tokens": args.max_tokens,
        "pattern": args.pattern,
    }
    profile.update({k: v for k, v in overrides.items() if v is not None})
    defaults = {
        "model": "default", "requests": 100, "concurrency": 10,
        "max_tokens": 64, "pattern": "steady",
    }
    for k, v in defaults.items():
        profile.setdefault(k, v)
    modes = {"both": (True, False), "on": (True,), "off": (False,)}[args.streaming]
    targets = [_parse_target(s) for s in args.target]
    report = compare_backends(
        targets,
        profile,
        Path(args.output_dir),
        streaming_modes=modes,
        bench_fn=default_bench_target_fn(cost_file=args.cost_file),
        quiesce_s=args.quiesce,
    )
    print(format_report(report))
    return 0 if not report["failed"] else 1
