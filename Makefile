# Developer entry points (reference Makefile). Python-only build; no wheels
# of native code — the TPU compute path is JAX/XLA compiled at runtime.
PY ?= python

.PHONY: help test test-fast test-policy lint lint-invariants lint-changed fmt smoke bench bench-smoke bench-proxy-smoke chaos-smoke fleet-smoke fleet-trace-smoke kv-economy-smoke econ-smoke trajectory dashboards-validate helm-lint airgap clean

help:
	@grep -E '^[a-z-]+:' Makefile | sed 's/:.*//' | sort | uniq

test:
	# >=2 workers REQUIRED, not an optimization: a single process running
	# the whole suite segfaults around test ~335 (XLA:CPU state
	# accumulation; see docs/TROUBLESHOOTING.md). xdist keeps each worker
	# under the threshold; without it (minimal containers), two sequential
	# half-suite PROCESSES hold the same bound — slower, same signal.
	@if $(PY) -c "import xdist" 2>/dev/null; then \
	  $(PY) -m pytest tests/ -q -n 2; \
	else \
	  echo "NOTE: pytest-xdist not installed — running the suite as two sequential half-processes (single-process full suite segfaults ~test 335, docs/TROUBLESHOOTING.md)"; \
	  r=0; $(PY) -m pytest tests/test_[a-l]*.py -q || r=1; \
	  $(PY) -m pytest tests/test_[m-z]*.py -q || r=1; exit $$r; \
	fi

test-fast: lint-invariants  ## harness-only tests (skip JAX model/runtime suites)
	# -n 4: the harness lane is embarrassingly parallel; measured 11 min
	# -> <3 min on this box (the single-process segfault threshold only
	# bites the FULL suite, and xdist workers stay far under it)
	# (without xdist the fast tier runs single-process: it stays far
	# under the segfault threshold, so only wall time is lost)
	@if $(PY) -c "import xdist" 2>/dev/null; then XDIST="-n 4"; \
	else XDIST=""; echo "NOTE: pytest-xdist not installed — fast tier running single-process"; fi; \
	$(PY) -m pytest tests/ -q -m "not slow" $$XDIST --ignore=tests/test_model.py \
	  --ignore=tests/test_parallel.py --ignore=tests/test_flash_attention.py \
	  --ignore=tests/test_runtime.py --ignore=tests/test_loader.py \
	  --ignore=tests/test_quant.py

lint:
	# the ruff gate runs wherever ruff exists; a minimal container gets a
	# LOUD skip line, never a silent pass (tier-1 signal stays honest)
	@if $(PY) -m ruff --version >/dev/null 2>&1; then \
	  $(PY) -m ruff check kserve_vllm_mini_tpu tests; \
	else \
	  echo "SKIPPED: ruff not installed — the ruff gate DID NOT RUN in this container"; \
	fi
	$(PY) -c "import yaml,glob;[list(yaml.safe_load_all(open(f))) for f in glob.glob('profiles/**/*.yaml',recursive=True)+glob.glob('policies/**/*.yaml',recursive=True)]"
	$(PY) -c "import json,glob;[json.load(open(f)) for f in glob.glob('dashboards/*.json')]"

lint-invariants:  ## kvmini-lint: jit purity, lockstep, metrics drift, thread safety, dtype flow, buffer lifecycle, mesh/sharding, resource safety, protocol/contract, async discipline, config surface
	# gates on lint-baseline.json: new findings fail, fixed-but-still-
	# listed entries fail too (ratchet toward an empty baseline).
	# Rule table: docs/LINTING.md. JAX-free; runs in ~9s (families run
	# in a thread pool sized to the CPU count; --jobs 1 forces the
	# byte-identical serial path). --timing prints
	# per-checker wall time so a budget regression names its checker;
	# --timing-out writes the same report as the lint-timing.json
	# artifact CI uploads; --sarif writes the code-scanning doc CI
	# uploads as PR annotations — one run gates AND reports.
	$(PY) -m kserve_vllm_mini_tpu.lint kserve_vllm_mini_tpu/ --timing \
	  --timing-out lint-timing.json --sarif lint-results.sarif

# the fast pre-commit loop: lint only files changed vs REF (default HEAD)
# plus their cross-file importers. Directory-scan-only surfaces (KVM032
# docs drift, KVM131-133 config-surface joins) stay full-scan — run
# `make lint-invariants` before merging. FAMILY narrows to a comma list
# of rule families (e.g. `make lint-changed FAMILY=KVM05,KVM12`).
REF ?= HEAD
FAMILY ?=
lint-changed:  ## kvmini-lint over `git diff --name-only $(REF)` + importers; FAMILY=KVM05,KVM12 narrows
	$(PY) -m kserve_vllm_mini_tpu.lint --changed $(REF) $(if $(FAMILY),--family $(FAMILY))

fmt:
	$(PY) -m ruff format kserve_vllm_mini_tpu tests 2>/dev/null || true

smoke:  ## full pipeline on the CPU-faked mesh, no hardware
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	  $(PY) -m kserve_vllm_mini_tpu bench --self-serve --model llama-tiny \
	  --requests 20 --concurrency 4 --max-tokens 8

bench:  ## driver benchmark (one JSON line) on the attached accelerator
	$(PY) bench.py

# asserts the decode-pipeline counters (docs/DECODE_PIPELINE.md) land in
# results.json via the real stage chain — the same tier-1 gate CI runs.
# Also validates the exported traces.json against core/schema.py's
# TRACES_JSON_SCHEMA (docs/TRACING.md), and the live monitor's
# timeline.jsonl + results `monitor` block against TIMELINE_SAMPLE_SCHEMA /
# MONITOR_JSON_SCHEMA incl. the scripted-stall event (docs/MONITORING.md).
bench-smoke:  ## bench pipeline vs the mock server, tiny budget, no TPU
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_bench_smoke.py tests/test_monitor.py -q

# the resilience acceptance gate (docs/RESILIENCE.md): the local chaos
# scenario matrix end-to-end against the mock server — one fault per
# class through POST /faults, MTTR measured from fault-clear to first
# healthy completion, and a resilience_table.json that validates against
# core/schema.py RESILIENCE_JSON_SCHEMA — plus the loadgen retry/shed
# accounting and monitor event rules they feed.
chaos-smoke:  ## local-mode chaos matrix vs the mock server, no TPU, no cluster
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_chaos_local.py tests/test_resilience.py -q -m "not slow"

# the fleet acceptance gate (docs/FLEET.md): supervisor + cache-aware
# router + local actuator + replica chaos against JAX-free mock replica
# processes — placement scoring, 429 re-placement, per-replica metric
# aggregation, replica-kill with zero hung requests, and the
# resilience-table replica rows, all with no engine and no cluster.
fleet-smoke:  ## fleet router/supervisor/actuator vs mock replicas, no TPU
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_fleet.py -q -m "not slow"

# the fleet tracing acceptance gate (docs/TRACING.md "Fleet tracing"):
# the router's fleet.route/fleet.proxy span rail, honest shed /
# replica_lost terminal status, the bounded decision audit ring behind
# GET /fleet/decisions, per-replica clock-offset stitching of client +
# router + replica lanes into one schema-valid traces.json (one replica
# clock-skewed, one forced re-placement), and the report's fleet lane —
# all against JAX-free in-process mock replicas.
fleet-trace-smoke:  ## router spans + decision audit + 3-lane stitch, no TPU
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_fleet_tracing.py -q -m "not slow"

# the KV-block economy acceptance gate (docs/DISAGGREGATION.md v2,
# docs/FLEET.md warm-from-sibling, docs/TROUBLESHOOTING.md host tier):
# a mock-server fleet respawn warms the new replica from its
# deepest-owning sibling (/kv/export -> /kv/import) and the hit-depth
# gauge recovers in the first scrape window, with schema-valid Results
# blocks for the handoff/tier counters — no engine, no TPU.
kv-economy-smoke:  ## zero-copy handoff + prefix migration + host tier, no TPU
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_kv_economy.py -q -m "not slow"

# the live cost & energy rail acceptance gate (docs/ECONOMICS.md): the
# rolling-window $/1K-tok agrees with the post-hoc estimator within 10%
# on a steady run, scripted mock /metrics drive both economics events
# through the real scrape->sample->detector path, the scraped
# Results.economics block validates, and the cost-aware policy sheds the
# unprofitable marginal replica 2->1 while queue pressure and an SLO
# breach veto the shed — no engine, no TPU.
econ-smoke:  ## live $/1K-tok + Wh/1K-tok rail, events, cost-aware policy
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_economics.py -q -m "not slow"

# the never-dark acceptance gate (docs/PROFILING.md): with no TPU,
# `python bench.py` must exit 0 with a schema-valid `proxy` block
# (validate_proxy), a config over mocked HBM headroom must DOWNSHIFT
# (labeled) instead of RESOURCE_EXHAUSTing, and the trajectory must
# render the round into its report section. Runs the real bench.py
# children end-to-end on the forced 8-device host platform.
bench-proxy-smoke:  ## full CPU-mesh proxy tier end-to-end, no TPU
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	  $(PY) -m pytest tests/test_bench_proxy.py tests/test_profiling.py \
	  tests/test_trajectory.py -q

trajectory:  ## perf trend table over the committed BENCH_*.json rounds
	$(PY) -m kserve_vllm_mini_tpu trajectory --glob 'BENCH_*.json'

dashboards-validate:  ## dashboard JSON structure + panel/query checks
	$(PY) -m pytest tests/test_assets.py -q -k "dashboard"

test-policy:  ## policies vs a LIVE Gatekeeper (needs kubectl+cluster; skips without)
	bash tests/policy_admission_test.sh

helm-lint:
	@command -v helm >/dev/null && helm lint charts/kvmini-tpu || \
	  echo "helm not installed; skipping"

airgap:  ## wheel + charts + profiles tarball for disconnected installs
	$(PY) -m pip wheel . -w dist/ --no-deps
	tar czf dist/kvmini-tpu-airgap.tar.gz dist/*.whl charts profiles policies dashboards slo.json tpu-cost.yaml tpu-matrix.yaml

clean:
	rm -rf dist build *.egg-info runs artifacts
