#!/usr/bin/env python3
"""Driver benchmark: the in-repo engine's serving numbers on real TPU,
measured on the flagship 8B-class config against the north-star targets
(BASELINE.md: >=2000 output tok/s/chip and p50 TTFT < 30 ms on
Llama-3.1-8B-class @ v5e).

Prints ONE JSON line:
  {"metric": "...", "value": N, "unit": "...", "vs_baseline": N, "detail": {...}}

Architecture (reworked for VERDICT round-4 "Next round" #1): the relay to
the TPU can wedge such that every dispatch blocks FOREVER, with observed
wedge windows of ~40 minutes (docs/TROUBLESHOOTING.md). Three rounds of
driver benches died to this. The orchestrator therefore:

  1. probes the backend with a no-op dispatch in a SUBPROCESS under a hard
     timeout, on an ADAPTIVE retry schedule bounded by a total budget
     (KVMINI_BENCH_PROBE_BUDGET_S, default 1800 s) instead of a fixed
     3x75 s that gives up long before a transient wedge clears;
  2. runs each sub-benchmark (headline decode+TTFT+prefill buckets, paged
     KV, speculative decode, int4) as its OWN child process under its own
     timeout, in order of importance — a wedge mid-queue costs only the
     remaining sub-benches, never the ones already measured;
  3. persists every completed sub-measurement incrementally (children
     append to a progress file after each step; the parent folds partial
     progress into the artifact even when the child dies mid-run);
  4. ALWAYS prints exactly one JSON line and exits 0 — also on SIGTERM,
     so a driver-side timeout still lands whatever finished. A failed run
     reports the failure and the retry plan, nothing else (no re-asserted
     headline claims from previous sessions).

Sub-benchmark children are selected with KVMINI_BENCH_CHILD=<mode>:
  headline  decode tok/s/chip (int8, 80 slots), steady-state TTFT p50 with
            tunnel-RTT correction, prefill throughput+MFU for the
            128/512/2048 buckets, HBM/MFU accounting, $-and-Wh economics
  paged     the same decode workload through the block-pool cache + Pallas
            paged-decode kernel at identical geometry (kernel custom-call
            asserted in the lowered executable on TPU)
  spec      speculative decoding with a NAMED small drafter (llama-1b,
            distinct param trees — no relayout copy; VERDICT round-4 #3):
            accept ratio + measured speedup vs a served-style step
  int4      packed-nibble int4 weights at headline geometry (first TPU
            validation of the nibble workaround)
  hbm       bandwidth attribution: decode-step time fitted over a slot
            grid as t_fixed + S*t_per_slot, decomposed against the
            weight-stream and KV-stream rooflines (VERDICT round-4 #7)

Model size is overridable (KVMINI_BENCH_MODEL=llama-1b etc.) so the same
script smoke-tests on CPU; the driver runs the default 8B config.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import time

# v5e peak numbers (public spec): 819 GB/s HBM BW, 197 bf16 TFLOP/s
V5E_HBM_GBPS = 819.0
V5E_BF16_TFLOPS = 197.0

_DEFAULT_MODEL = "llama-3.1-8b"
_DEFAULT_QUANT = "int8"
# 80 slots measured 3,067 tok/s/chip vs 2,744 at 64 (r4 session) — the KV
# (80 x 512-token bf16 = 5.4 GB) + int8 weights still fit the v5e's HBM.
# If the child fails at 80 the orchestrator retries once at the proven 64
# (_FALLBACK_SLOTS) so a marginal-HBM compile can't cost the headline.
_DEFAULT_SLOTS = "80"
_FALLBACK_SLOTS = "64"
_BASELINE_TOKS = 2000.0  # north-star output tokens/sec/chip

_T_START = time.time()


def _log(msg: str) -> None:
    """Stage progress on stderr (stdout carries only the one JSON line)."""
    print(f"[bench +{time.time() - _T_START:.0f}s] {msg}", file=sys.stderr, flush=True)


def _env_model() -> str:
    return _knob("KVMINI_BENCH_MODEL")


def _env_quant() -> str:
    return _knob("KVMINI_BENCH_QUANT")


def _env_quant_mode() -> str:
    mode = _knob("KVMINI_BENCH_QUANT_MODE")
    if mode not in ("dequant", "w8a8"):
        # fail LOUD at the knob, not silently-dequant at the dispatch: a
        # typo'd mode would bench the wrong program under the requested
        # label (ops/quant.py linear dispatches on exact "w8a8")
        raise SystemExit(
            f"KVMINI_BENCH_QUANT_MODE={mode!r}: known modes are "
            "'dequant', 'w8a8'"
        )
    return mode


def _env_slots() -> int:
    return int(_knob("KVMINI_BENCH_SLOTS"))


def _env_disagg():
    """Whether to run the disaggregated-prefill sub-bench rows
    (runtime/disagg.py; docs/DISAGGREGATION.md). Loud validation at the
    knob: a garbled value must not silently bench the colocated path
    under a disagg label."""
    raw = _knob("KVMINI_BENCH_DISAGG")
    if not raw:
        return False
    if raw not in ("0", "1", "true", "false"):
        raise SystemExit(
            f"KVMINI_BENCH_DISAGG={raw!r}: must be '1'/'true' (bench the "
            "disaggregated prefill lane) or '0'/'false'/empty (colocated)"
        )
    return raw in ("1", "true")


def _env_fleet():
    """Replica count for the multi-replica fleet sub-bench row
    (fleet/; docs/FLEET.md), or 0 (off). Loud validation at the knob: a
    garbled value must not silently skip the row under a fleet label."""
    raw = _knob("KVMINI_BENCH_FLEET")
    if not raw or raw in ("0", "false"):
        return 0
    try:
        n = int(raw)
    except ValueError:
        raise SystemExit(
            f"KVMINI_BENCH_FLEET={raw!r}: must be a replica count >= 2 "
            "(empty/0 disables the fleet row)"
        ) from None
    if n < 2:
        raise SystemExit(
            f"KVMINI_BENCH_FLEET={n}: needs >= 2 replicas — a 1-replica "
            "fleet measures nothing the single-server rows don't"
        )
    return n


def _env_kv_tier():
    """Host-RAM KV tier byte cap for the fleet row's paged replicas
    (--kv-host-tier-bytes; docs/TROUBLESHOOTING.md "Host-RAM KV tier
    thrash"), or 0 (off). Loud validation at the knob: a garbled value
    must not silently bench the tierless path under a tier label."""
    raw = _knob("KVMINI_BENCH_KV_TIER")
    if not raw or raw in ("0", "false"):
        return 0
    try:
        n = int(raw)
    except ValueError:
        raise SystemExit(
            f"KVMINI_BENCH_KV_TIER={raw!r}: must be a host-RAM byte cap "
            "(empty/0 disables the tier)"
        ) from None
    if n < 0:
        raise SystemExit(
            f"KVMINI_BENCH_KV_TIER={n}: byte cap cannot be negative"
        )
    return n


def _env_migrate():
    """Whether the fleet row exercises warm-from-sibling prefix
    migration after a replica kill (docs/FLEET.md). Loud validation at
    the knob: a garbled value must not silently report a cold respawn
    under a migrate label."""
    raw = _knob("KVMINI_BENCH_MIGRATE")
    if not raw:
        return False
    if raw not in ("0", "1", "true", "false"):
        raise SystemExit(
            f"KVMINI_BENCH_MIGRATE={raw!r}: must be '1'/'true' (kill a "
            "replica and warm the respawn from its deepest-owning "
            "sibling) or '0'/'false'/empty (off); requires "
            "KVMINI_BENCH_FLEET >= 2"
        )
    return raw in ("1", "true")


def _env_cost_budget():
    """$/1K-token budget the serving rows judge their economics against
    (docs/ECONOMICS.md), or None (no verdict). Loud validation at the
    knob: a garbled budget must not silently report every row as
    in-budget."""
    raw = _knob("KVMINI_BENCH_COST_BUDGET")
    if not raw:
        return None
    try:
        budget = float(raw)
    except ValueError:
        raise SystemExit(
            f"KVMINI_BENCH_COST_BUDGET={raw!r}: must be a positive "
            "$/1K-token budget (empty disables the verdict)"
        ) from None
    if budget <= 0:
        raise SystemExit(
            f"KVMINI_BENCH_COST_BUDGET={budget}: budget must be > 0 "
            "(empty disables the verdict)"
        )
    return budget


def _env_prefill_chunk():
    """Tokens per interleaved prefill chunk, or None (monolithic). Loud
    validation at the knob: a garbled value must not silently bench the
    monolithic path under a chunked label."""
    raw = _knob("KVMINI_BENCH_PREFILL_CHUNK")
    if not raw:
        return None
    try:
        chunk = int(raw)
    except ValueError:
        raise SystemExit(
            f"KVMINI_BENCH_PREFILL_CHUNK={raw!r}: must be a positive "
            "integer token count (empty disables chunked prefill)"
        ) from None
    if chunk < 1:
        raise SystemExit(
            f"KVMINI_BENCH_PREFILL_CHUNK={chunk}: must be >= 1 (empty "
            "disables chunked prefill)"
        )
    return chunk


def _run_fleet_row(n_replicas: int, kv_tier_bytes: int = 0,
                   migrate: bool = False) -> dict:
    """The {mode}.fleet sub-measurement (docs/FLEET.md): spawn
    ``n_replicas`` CPU-forced llama-tiny serve replicas under the fleet
    supervisor, front them with the cache-aware router, and drive a
    small prefix-heavy multi-session burst through it. Reports fleet
    mechanics only — cold starts, routed p50, placement/reroute mix.
    ``kv_tier_bytes``/``migrate`` flip the replicas to the paged layout
    to exercise the host-RAM tier and warm-from-sibling prefix
    migration (a replica kill whose respawn imports the deepest-owning
    sibling's retained prefix blocks)."""
    import urllib.request

    from kserve_vllm_mini_tpu.fleet.router import (
        FleetRouter,
        RouterConfig,
        start_router,
    )
    from kserve_vllm_mini_tpu.fleet.supervisor import (
        FleetSupervisor,
        serve_replica_cmd,
    )
    from kserve_vllm_mini_tpu.loadgen.prompts import make_prompt_fn

    extra_args = ["--max-slots", "4", "--max-seq-len", "512",
                  "--prefix-cache"]
    if kv_tier_bytes or migrate:
        # tier and /kv/export|import are paged-pool surfaces
        extra_args += ["--kv-layout", "paged"]
    if kv_tier_bytes:
        extra_args += ["--kv-host-tier-bytes", str(kv_tier_bytes)]
    sup = FleetSupervisor(
        replica_cmd=serve_replica_cmd(
            model="llama-tiny",
            extra_args=extra_args,
            # the fleet row must NEVER claim the accelerator the serving
            # child is benching — replicas run on CPU by construction
            env_overrides={"JAX_PLATFORMS": "cpu"},
        ),
        ready_timeout_s=300.0,
        warm_from_siblings=migrate,
    )
    handle = None
    try:
        t0 = time.time()
        sup.start(n_replicas)
        boot_s = time.time() - t0
        router = FleetRouter(supervisor=sup,
                             cfg=RouterConfig(scrape_interval_s=0.25))
        handle = start_router(router)
        if migrate:
            # owners come straight off the in-process prefix index —
            # no router-URL round trip needed when the router is local
            sup._owners_fn = router._prefix.owners
        prompt_fn = make_prompt_fn("sessions", pool_size=4)
        lat_ms = []
        for i in range(16):
            body = json.dumps({
                "messages": [{"role": "user", "content": prompt_fn(i)}],
                "max_tokens": 4,
                "user": f"session-{i % 4}",
            }).encode()
            req = urllib.request.Request(
                handle.url + "/v1/chat/completions", data=body,
                headers={"Content-Type": "application/json"},
            )
            t1 = time.time()
            with urllib.request.urlopen(req, timeout=120) as r:
                r.read()
            lat_ms.append((time.time() - t1) * 1000.0)
        warm_row = None
        if migrate:
            victim = sup.replicas()[0]["rid"]
            sup.kill_replica(victim)
            deadline = time.time() + 300.0
            while time.time() < deadline:
                c = sup.counters()
                if c["warmed"] + c["warm_failures"] > 0:
                    break
                time.sleep(0.25)
            c = sup.counters()
            warm_row = {"warmed": c["warmed"],
                        "warm_failures": c["warm_failures"]}
        counters = sup.counters()
        colds = sorted(counters["cold_starts_s"])
        return {
            "replicas": n_replicas,
            "boot_s": round(boot_s, 2),
            "cold_start_p50_s": round(colds[len(colds) // 2], 2)
            if colds else None,
            "routed_request_p50_ms": round(
                sorted(lat_ms)[len(lat_ms) // 2], 2
            ),
            "placements": dict(router.placements),
            "reroutes": router.reroutes,
            "sheds": router.sheds,
            **({"kv_host_tier_bytes": kv_tier_bytes} if kv_tier_bytes
               else {}),
            **({"migration": warm_row} if warm_row is not None else {}),
            "series": "fleet-mechanics-cpu",  # never a TPU throughput claim
        }
    finally:
        # sup.stop() must run even when startup raised (half-spawned
        # replicas run in their own sessions and would orphan) or
        # handle.stop() itself fails
        try:
            if handle is not None:
                handle.stop()
        finally:
            sup.stop()


# ---------------------------------------------------------------------------
# Child-side: incremental progress + the sub-benchmark bodies.
# ---------------------------------------------------------------------------

def _progress(key: str, data: dict) -> None:
    """Append one completed sub-measurement to the progress file. The parent
    reads this when the child dies mid-run — whatever finished still lands
    in the artifact (VERDICT round-4 #1: the r4 mid-queue wedge cost the
    session every number after the first)."""
    path = os.environ.get("KVMINI_BENCH_PROGRESS")
    if not path:
        return
    with open(path, "a") as f:
        f.write(json.dumps({"key": key, "data": data}) + "\n")
        f.flush()
        os.fsync(f.fileno())


def _child_setup():
    """Shared child preamble: honor JAX_PLATFORMS despite the site hook
    having imported jax first (safe pre-device-touch), import the stack."""
    import jax

    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        jax.config.update("jax_platforms", plat)
    return jax


def _safe_backend(jax) -> str:
    """``jax.default_backend()`` RAISES (JaxRuntimeError) when the TPU
    plugin fails to initialize — the BENCH_r03 crash path, where the raw
    traceback escaped bench.py and the round produced no artifact. Turn
    it into the classification marker the parent reads (_classify ->
    tpu_unavailable) so the orchestrator hands the round to the proxy
    tier instead."""
    try:
        return jax.default_backend()
    except Exception as e:  # noqa: BLE001 — any backend-init failure
        raise SystemExit(
            f"Unable to initialize backend in child: {type(e).__name__}: {e}"
        )


def _headroom_capacity(jax, on_tpu: bool) -> "int | None":
    """Per-chip HBM budget for the admission/headroom guard
    (docs/PROFILING.md). Env override first (tests, what-if sizing);
    device introspection on TPU; None on CPU — a smoke run has no HBM to
    guard and must not downshift the config it was asked to smoke."""
    gb = _knob("KVMINI_BENCH_HBM_GB")
    if gb:
        return int(float(gb) * 1e9)
    if not on_tpu:
        return None
    from kserve_vllm_mini_tpu.profiling.headroom import device_hbm_bytes

    return device_hbm_bytes(jax.devices()[0])


def _timed_readback(fn, *args, n: int = 15):
    """p50 of n timed dispatch+readback runs of an already-compiled fn."""
    import numpy as np

    times = []
    for _ in range(n):
        t0 = time.time()
        _ = np.asarray(fn(*args))
        times.append((time.time() - t0) * 1000.0)
    return float(np.percentile(times, 50))


def _tunnel_rtt(jax, jnp, np) -> float:
    """Fixed per-readback tax under the remote relay: dispatch + 1-element
    readback of a compiled no-op, timed exactly like the TTFT loop. Sub-ms
    on a PCIe-attached host."""
    noop = jax.jit(lambda x: x + 1)
    xs = jnp.zeros((1,), jnp.int32)
    _ = np.asarray(noop(xs))
    return _timed_readback(noop, xs)


def _economics(jax, toks_per_sec: float, n_chips: int, on_tpu: bool) -> dict:
    """$/1K tokens and Wh/1K tokens from the chip-hour sheet + the modeled
    telemetry leg (decode keeps the chip busy => duty ~1 during the timed
    window), provenance-labeled like energy/collector.py's fallback chain."""
    from kserve_vllm_mini_tpu.analysis.telemetry import modeled_power
    from kserve_vllm_mini_tpu.costs.pricing import load_pricing

    try:
        if not on_tpu:
            # a CPU smoke run must not fabricate TPU economics
            return {"cost_per_1k_tokens_usd": 0.0, "energy_wh_per_1k_tokens": 0.0,
                    "cost_basis": "n/a (not on TPU)",
                    "energy_provenance": "n/a (not on TPU)"}
        kind = jax.devices()[0].device_kind.lower()
        if "v6" in kind:
            tpu_gen = "v6e"   # Trillium reports "TPU v6 lite" — check the
                              # generation before the "lite" tier
        elif "lite" in kind or "v5e" in kind:
            tpu_gen = "v5e"
        elif "v5" in kind:
            tpu_gen = "v5p"
        else:
            tpu_gen = "v4"
        pricing = load_pricing()
        chip_hourly, price_key = pricing.chip_price(tpu_gen)
        overhead = 1.0 + pricing.overhead_factor
        cost_per_1k = chip_hourly * overhead * n_chips / max(toks_per_sec, 1e-9) / 3.6
        watts = modeled_power(1.0, tpu_gen) * n_chips
        wh_per_1k = watts * (1000.0 / max(toks_per_sec, 1e-9)) / 3600.0
        out = {
            "cost_per_1k_tokens_usd": round(cost_per_1k, 6),
            "energy_wh_per_1k_tokens": round(wh_per_1k, 4),
            "cost_basis": f"{price_key} ${chip_hourly}/chip-hr x{overhead:.2f} overhead",
            "energy_provenance":
                f"modeled ({tpu_gen} duty 1.0 x TDP, analysis/telemetry.py)",
        }
        budget = _env_cost_budget()
        if budget is not None:
            # verdict only on REAL TPU economics — the not-on-TPU path
            # above must not report a fabricated in-budget pass
            out["cost_budget_usd_per_1k_tok"] = budget
            out["cost_over_budget"] = cost_per_1k > budget
        return out
    except Exception as e:  # noqa: BLE001 — the headline must survive a
        # pricing-sheet or device-introspection hiccup
        _log(f"economics skipped: {type(e).__name__}: {e}")
        return {"cost_per_1k_tokens_usd": 0.0, "energy_wh_per_1k_tokens": 0.0,
                "cost_basis": f"unavailable ({type(e).__name__})",
                "energy_provenance": f"unavailable ({type(e).__name__})"}


def _run_serving_child(mode: str) -> dict:
    """headline / paged / int4: decode throughput + TTFT (+ prefill buckets
    for headline) on the flagship config. `mode` picks cache layout/quant."""
    jax = _child_setup()
    import jax.numpy as jnp
    import numpy as np

    from functools import partial

    from kserve_vllm_mini_tpu.models.config import get_config
    from kserve_vllm_mini_tpu.models.llama import (
        forward,
        init_kv_cache,
        init_params,
        init_params_quantized,
    )
    from kserve_vllm_mini_tpu.ops.quant import quantized_bytes
    from kserve_vllm_mini_tpu.runtime.sampling import sample_tokens

    model = _env_model()
    quant = "int4" if mode == "int4" else _env_quant()
    quant_mode = _env_quant_mode() if quant != "none" else "dequant"
    paged = mode == "paged" or _knob("KVMINI_BENCH_PAGED") == "1"
    kv_quant = _knob("KVMINI_BENCH_KV") == "int8"
    # more slots amortize the 9 GB int8 weight stream over more tokens per
    # step (measured 1710 @ 32 -> 2744 @ 64 -> 3067 @ 80 tok/s/chip on the
    # v5e) until the KV stream and HBM capacity push back
    slots = _env_slots()
    prompt_len = 128
    max_seq = 512
    decode_steps = int(_knob("KVMINI_BENCH_STEPS"))
    warmup = 8

    backend = _safe_backend(jax)
    on_tpu = backend == "tpu"
    unroll = int(_knob("KVMINI_BENCH_UNROLL"))

    # admission/headroom guard (docs/PROFILING.md): BENCH_r02 died
    # RESOURCE_EXHAUSTED mid-run and produced nothing. Pre-flight the
    # config's analytic HBM footprint against device capacity and
    # DOWNSHIFT (slots first, then ctx) with a label — a smaller real
    # measurement beats a crashed round.
    headroom = None
    capacity = _headroom_capacity(jax, on_tpu)
    if capacity:
        from kserve_vllm_mini_tpu.profiling.headroom import serving_headroom_plan

        # ctx floor: the cache must hold every position the timed windows
        # write (prompt + warmup + both timed runs) — a ctx downshift
        # below that would clamp KV writes onto the last position and
        # corrupt the measurement instead of shrinking it
        ctx_need = prompt_len + warmup + decode_steps + decode_steps // 4 + 1
        # deliberately NOT passing prefill_chunk here: this child executes
        # MONOLITHIC batched/TTFT prefill probes regardless of the chunk
        # knob (the chunked row below is additional), so the guard must
        # price the monolithic activation set or it can admit a shape the
        # batch prefill then RESOURCE_EXHAUSTs on — the BENCH_r02 class.
        # Per-chunk pricing applies where chunked execution is real: the
        # Engine's own guard and the proxy tier's serving pre-flight.
        plan = serving_headroom_plan(
            model, slots, max_seq, quant, kv_quant, capacity,
            quant_mode=quant_mode,
            min_seq=min(max(256, ctx_need), max_seq),
        )
        headroom = plan.to_dict()
        if not plan.fits:
            # even maximally downshifted the config cannot fit: report the
            # OOM from the pre-flight (classified by the parent, which
            # then runs the proxy tier) instead of burning a compile on a
            # guaranteed RESOURCE_EXHAUSTED
            _progress(f"{mode}.headroom", headroom)
            raise SystemExit(
                "RESOURCE_EXHAUSTED (pre-flight): even downshifted to "
                f"slots={plan.slots} ctx={plan.max_seq} the config needs "
                f"{plan.estimate_bytes / 1e9:.1f} GB > "
                f"{plan.budget_bytes / 1e9:.1f} GB HBM budget"
            )
        if plan.downshifted:
            _log(plan.downshifted)
            slots, max_seq = plan.slots, plan.max_seq
            _progress(f"{mode}.headroom", headroom)

    cfg = get_config(model, max_seq_len=max_seq, scan_unroll=unroll,
                     quant_mode=quant_mode)
    _log(f"mode={mode} model={model} quant={quant} quant_mode={quant_mode} "
         f"slots={slots} paged={paged} unroll={unroll} backend={backend}")
    # int8/int4 weights are built layer-by-layer straight into quantized
    # leaves — the full-precision 8B tree (~16 GB bf16) must NEVER exist on
    # a 16 GB v5e (round-2 OOM)
    if quant in ("int8", "int4"):
        params = init_params_quantized(
            jax.random.PRNGKey(0), cfg, bits=4 if quant == "int4" else 8
        )
    else:
        params = init_params(jax.random.PRNGKey(0), cfg)
    jax.block_until_ready(params)
    param_bytes = quantized_bytes(params)
    _log(f"params ready ({param_bytes / 1e9:.2f} GB on device)")

    blk = 64  # paged block size, shared by the batch and TTFT caches
    block_table = None
    if paged:
        from kserve_vllm_mini_tpu.models.llama import init_paged_kv_cache

        maxb = max_seq // blk
        cache = init_paged_kv_cache(cfg, slots * maxb, blk, quantized=kv_quant)
        block_table = jnp.arange(slots * maxb, dtype=jnp.int32).reshape(slots, maxb)
    else:
        cache = init_kv_cache(cfg, slots, max_seq=max_seq, quantized=kv_quant)
    tkw = {"block_table": block_table} if paged else {}
    toks = jax.random.randint(jax.random.PRNGKey(1), (slots, prompt_len), 0,
                              cfg.vocab_size)
    pos = jnp.broadcast_to(jnp.arange(prompt_len, dtype=jnp.int32),
                           (slots, prompt_len))

    # -- batch prefill to fill all slots (fresh-prefill / flash path) -------
    @partial(jax.jit, donate_argnums=(1,))
    def prefill_batch(params, cache, toks, pos):
        # logit_index: full [slots, T, V] f32 logits for a 128k vocab is
        # ~2 GB of HBM the sampler never reads
        last = jnp.full((slots,), prompt_len - 1, dtype=jnp.int32)
        logits, cache = forward(params, cfg, toks, pos, cache,
                                jnp.zeros((slots,), jnp.int32), fresh_prefill=True,
                                logit_index=last, **tkw)
        return cache, jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)

    # -- single-request prefill: the per-request TTFT cost ------------------
    if paged:
        from kserve_vllm_mini_tpu.models.llama import init_paged_kv_cache

        cache1 = init_paged_kv_cache(cfg, max_seq // blk, blk, quantized=kv_quant)
        t1kw = {"block_table": jnp.arange(max_seq // blk, dtype=jnp.int32)[None]}
    else:
        cache1 = init_kv_cache(cfg, 1, max_seq=max_seq, quantized=kv_quant)
        t1kw = {}
    toks1, pos1 = toks[:1], pos[:1]

    @jax.jit
    def prefill_one(params, cache, toks, pos):
        logits, cache = forward(params, cfg, toks, pos, cache,
                                jnp.zeros((1,), jnp.int32), fresh_prefill=True,
                                **t1kw)
        return jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)

    _log("compiling single-request prefill")
    from kserve_vllm_mini_tpu.profiling.compile_stats import capture_compile_stats

    # explicit lower().compile() capture (docs/PROFILING.md): compile wall
    # time + the XLA cost model's FLOPs/bytes + the buffer-assignment peak
    # land in the artifact; the compiled executable is what gets timed, so
    # the stats describe exactly the program that produced the numbers
    prefill_one, pf_cs = capture_compile_stats(
        prefill_one, params, cache1, toks1, pos1,
        label=f"bench.prefill_one[{mode}]",
    )
    hlo = prefill_one.as_text()
    flash_lowered = "tpu_custom_call" in hlo
    # "tpu_custom_call" matches ANY TPU custom call; the Mosaic
    # backend_config embeds the kernel's function name, so also look for
    # the flash kernel specifically (reported, not asserted — the name
    # embedding is a lowering detail the assert must not couple to)
    flash_named = "_flash_kernel" in hlo
    _log(f"prefill compiled (flash_lowered={flash_lowered}, named={flash_named})")
    if on_tpu:
        assert flash_lowered, (
            "serving prefill must lower the Pallas flash kernel on TPU "
            "(ops/flash_attention.prefill_attention dispatch)"
        )

    # NOTE on timing: under the remote-TPU relay, block_until_ready() does
    # not guarantee device-side completion — only a host readback does, and
    # a readback pays the tunnel RTT. Latencies are timed WITH the readback
    # and reported next to the separately-measured RTT floor; throughput is
    # timed over two chained runs of different lengths, differenced, so the
    # RTT and dispatch overheads cancel.
    _log("batch prefill (first call: compile + run)")
    t0 = time.time()
    cache, tokens = prefill_batch(params, cache, toks, pos)
    _ = np.asarray(tokens)
    prefill_first_s = time.time() - t0
    _log(f"batch prefill done in {prefill_first_s:.1f}s")

    # steady-state single-request prefill p50 (TTFT)
    _ = np.asarray(prefill_one(params, cache1, toks1, pos1))  # warm
    ttft_p50 = _timed_readback(prefill_one, params, cache1, toks1, pos1)
    rtt_p50 = _tunnel_rtt(jax, jnp, np)
    ttft_adj = max(ttft_p50 - rtt_p50, 0.0)
    n_chips = jax.device_count()
    _progress(f"{mode}.ttft", {
        "ttft_p50_ms": round(ttft_p50, 2),
        "tunnel_rtt_p50_ms": round(rtt_p50, 2),
        "ttft_p50_adjusted_ms": round(ttft_adj, 2),
        "flash_prefill_lowered": bool(flash_lowered),
    })

    # -- chunked single-request prefill (KVMINI_BENCH_PREFILL_CHUNK): the
    # same prompt as the TTFT probe split into chunk-token pieces — piece
    # 0 on the flash fresh-prefill path, continuations on the positional-
    # masked cached path (int8-KV caches ride the cached-prefill kernel
    # on TPU). Timed whole-prompt so the row reads next to ttft_p50; the
    # per-piece wall is the interleaving window a decode sweep rides in.
    prefill_chunk = _env_prefill_chunk()
    if prefill_chunk and prefill_chunk < prompt_len:
        ch = prefill_chunk
        n_pieces = -(-prompt_len // ch)

        @jax.jit
        def prefill_c0(params, cache, piece, pos):
            logits, cache = forward(params, cfg, piece, pos, cache,
                                    jnp.zeros((1,), jnp.int32),
                                    fresh_prefill=True, **t1kw)
            return cache, jnp.argmax(logits[:, -1, :], axis=-1)

        @jax.jit
        def prefill_cont(params, cache, piece, offset):
            # offset: [1] absolute position of the piece's first token
            cpos = offset[:, None] + jnp.arange(piece.shape[1],
                                                dtype=jnp.int32)[None]
            logits, cache = forward(params, cfg, piece, cpos, cache,
                                    offset, **t1kw)
            return cache, jnp.argmax(logits[:, -1, :], axis=-1)

        def chunked_once():
            c, tok = cache1, None
            for i in range(n_pieces):
                piece = toks1[:, i * ch : (i + 1) * ch]
                if i == 0:
                    c, tok = prefill_c0(params, c, piece, pos1[:, :piece.shape[1]])
                else:
                    c, tok = prefill_cont(params, c, piece,
                                          jnp.full((1,), i * ch, jnp.int32))
            return tok

        _ = np.asarray(chunked_once())  # compile + warm (<= 2 variants)
        samples = []
        for _i in range(5):
            t0 = time.time()
            _ = np.asarray(chunked_once())
            samples.append((time.time() - t0) * 1000.0)
        chunked_ms = sorted(samples)[len(samples) // 2]
        row = {
            "chunk": ch,
            "chunks": n_pieces,
            "ms_p50": round(chunked_ms, 2),
            "ms_per_chunk_p50": round(chunked_ms / n_pieces, 2),
            "monolithic_ttft_p50_ms": round(ttft_p50, 2),
        }
        _progress(f"{mode}.prefill_chunked", row)
        _log(f"chunked prefill ({n_pieces} x {ch}): {row}")

    # -- disaggregated prefill lane (KVMINI_BENCH_DISAGG): the same prompt
    # as the TTFT probe prefilled into a 1-slot STAGING cache (the lane's
    # executable, runtime/disagg.py) and then handed off — the staged
    # stripe injected into the serving cache at slot 0 (update_cache_slots,
    # the engine's inject executable). Timed end-to-end so the row reads
    # next to ttft_p50: the delta vs monolithic is the handoff tax, and
    # what it buys is that NONE of the staging wall ran on the decode
    # lane (docs/DISAGGREGATION.md).
    if _env_disagg() and not paged:
        from kserve_vllm_mini_tpu.models.llama import (
            slice_cache_slots,
            update_cache_slots,
        )

        staging = init_kv_cache(cfg, 1, max_seq=max_seq, quantized=kv_quant)

        @jax.jit
        def lane_prefill(params, cache, toks, pos):
            logits, cache = forward(
                params, cfg, toks, pos, cache, jnp.zeros((1,), jnp.int32),
                fresh_prefill=True,
                logit_index=jnp.full((1,), prompt_len - 1, jnp.int32),
            )
            return cache, jnp.argmax(logits[:, -1, :], axis=-1)

        slice0 = jax.jit(lambda c: slice_cache_slots(c, 0))

        @partial(jax.jit, donate_argnums=(0,))
        def inject(cache, sub):
            return update_cache_slots(cache, sub, jnp.int32(0))

        def handoff_once():
            st, tok = lane_prefill(params, staging, toks1, pos1)
            sub = slice0(st)
            nonlocal cache
            cache = inject(cache, sub)
            # the row claims the WHOLE handoff (prefill + slice + inject):
            # tok only depends on the prefill, so block on the injected
            # cache too or the slice/inject device time leaks out of the
            # timed window and the handoff tax under-reports
            jax.block_until_ready(cache)
            return tok

        _ = np.asarray(handoff_once())  # compile + warm all three
        samples = []
        for _i in range(5):
            t0 = time.time()
            _ = np.asarray(handoff_once())
            samples.append((time.time() - t0) * 1000.0)
        row = {
            "ms_p50": round(sorted(samples)[len(samples) // 2], 2),
            "monolithic_ttft_p50_ms": round(ttft_p50, 2),
            "handoff_blocks": -(-prompt_len // blk),
        }
        _progress(f"{mode}.disagg_prefill", row)
        _log(f"disagg lane prefill + handoff: {row}")

    # -- multi-replica fleet (KVMINI_BENCH_FLEET): N CPU-forced replica
    # subprocesses behind the cache-aware router (fleet/, docs/FLEET.md).
    # Measures the fleet MECHANICS next to this mode's serving numbers —
    # scale-up cold start, routed-request p50 over a prefix-heavy
    # multi-session burst, placement mix — never TPU throughput (the
    # replicas deliberately pin JAX_PLATFORMS=cpu so the accelerator
    # under test stays exclusively the engine above).
    n_fleet = _env_fleet()
    kv_tier = _env_kv_tier()
    migrate = _env_migrate()
    if migrate and not n_fleet:
        raise SystemExit(
            "KVMINI_BENCH_MIGRATE=1 needs KVMINI_BENCH_FLEET >= 2 — "
            "warm-from-sibling migration is a fleet surface (a donor "
            "sibling must exist)"
        )
    if kv_tier and not n_fleet:
        raise SystemExit(
            "KVMINI_BENCH_KV_TIER is wired through the fleet row's "
            "paged replicas — set KVMINI_BENCH_FLEET >= 2 too, or unset "
            "it (a silently-ignored tier knob would mislabel the run)"
        )
    if n_fleet:
        row = _run_fleet_row(n_fleet, kv_tier_bytes=kv_tier,
                             migrate=migrate)
        _progress(f"{mode}.fleet", row)
        _log(f"fleet row ({n_fleet} replicas): {row}")

    # -- prefill throughput buckets (VERDICT round-4 #8: prefill is the
    # compute-bound side — tokens/s/chip + MFU, not just TTFT) ------------
    prefill_rows = {}
    if mode == "headline":
        for T in (128, 512, 2048):
            try:
                cfgT = cfg if T <= max_seq else get_config(
                    model, max_seq_len=T, scan_unroll=unroll,
                    quant_mode=quant_mode,
                )
                cT = init_kv_cache(cfgT, 1, max_seq=max(T, max_seq),
                                   quantized=kv_quant)
                tT = jax.random.randint(jax.random.PRNGKey(4), (1, T), 0,
                                        cfg.vocab_size)
                pT = jnp.arange(T, dtype=jnp.int32)[None]

                @jax.jit
                def prefill_T(params, cache, toks, pos, _cfg=cfgT, _T=T):
                    lg, cache = forward(
                        params, _cfg, toks, pos, cache,
                        jnp.zeros((1,), jnp.int32), fresh_prefill=True,
                        logit_index=jnp.full((1,), _T - 1, jnp.int32),
                    )
                    return jnp.argmax(lg[:, -1, :], axis=-1).astype(jnp.int32)

                _ = np.asarray(prefill_T(params, cT, tT, pT))  # compile+warm
                ms = _timed_readback(prefill_T, params, cT, tT, pT, n=9)
                dev_ms = max(ms - rtt_p50, 1e-6)
                tps = T / (dev_ms / 1000.0)
                # prefill FLOPs: 2*P*T matmul + 2*2*L*H*T^2*hd attention
                att = 4.0 * cfg.n_layers * cfg.n_heads * T * T * cfg.head_dim
                flops = 2.0 * cfg.param_count * T + att
                mfu = (flops / (dev_ms / 1000.0)) / (V5E_BF16_TFLOPS * 1e12) \
                    if on_tpu else 0.0
                prefill_rows[str(T)] = {
                    "ms_p50": round(ms, 2),
                    "ms_device": round(dev_ms, 2),
                    "tokens_per_sec_per_chip": round(tps / n_chips, 1),
                    "mfu": round(mfu, 4),
                }
                _log(f"prefill bucket {T}: {prefill_rows[str(T)]}")
                del cT
            except Exception as e:  # noqa: BLE001 — a failed long bucket
                # (e.g. 2048 OOM next to the serving caches) must not cost
                # the buckets already measured
                prefill_rows[str(T)] = {"error": f"{type(e).__name__}: {e}"}
                _log(f"prefill bucket {T} failed: {e}")
        _progress("headline.prefill_buckets", prefill_rows)

    @partial(jax.jit, donate_argnums=(1,))
    def decode(params, cache, tokens, lengths, rng):
        logits, cache = forward(params, cfg, tokens[:, None], lengths[:, None],
                                cache, lengths, **tkw)
        nxt = sample_tokens(
            logits[:, 0, :], rng,
            jnp.zeros((slots,), jnp.float32),
            jnp.zeros((slots,), jnp.int32),
            jnp.ones((slots,), jnp.float32),
        )
        return cache, nxt

    # explicit decode compile (docs/PROFILING.md): previously only the
    # paged mode lowered the decode up front; now every mode does, so the
    # artifact carries the decode executable's compile stats and warmup
    # dispatches the exact program the stats describe
    lengths0 = jnp.full((slots,), prompt_len, dtype=jnp.int32)
    decode, dec_cs = capture_compile_stats(
        decode, params, cache, tokens, lengths0, jax.random.PRNGKey(2),
        label=f"bench.decode[{mode}]",
    )
    # paged mode: assert the Pallas paged-decode kernel is in the decode
    # executable (same contract as flash_prefill_lowered; VERDICT r4 #2)
    paged_kernel_lowered = None
    if paged:
        dhlo = decode.as_text()
        paged_kernel_lowered = "tpu_custom_call" in dhlo
        _log(f"paged decode compiled (kernel_lowered={paged_kernel_lowered})")
        if on_tpu:
            assert paged_kernel_lowered, (
                "paged decode must lower the Pallas paged-attention kernel "
                "on TPU (ops/paged_attention.py dispatch)"
            )

    lengths = jnp.full((slots,), prompt_len, dtype=jnp.int32)
    rng = jax.random.PRNGKey(2)

    def run_steps(n: int, cache, tokens, lengths, rng):
        for _ in range(n):
            rng, sub = jax.random.split(rng)
            cache, tokens = decode(params, cache, tokens, lengths, sub)
            lengths = lengths + 1
        _ = np.asarray(tokens)  # true synchronization point
        return cache, tokens, lengths, rng

    _log("decode warmup (compile)")
    cache, tokens, lengths, rng = run_steps(warmup, cache, tokens, lengths, rng)
    _log("decode warmup done; timing")

    n_short = decode_steps // 4
    t0 = time.time()
    cache, tokens, lengths, rng = run_steps(n_short, cache, tokens, lengths, rng)
    t_short = time.time() - t0

    t0 = time.time()
    cache, tokens, lengths, rng = run_steps(decode_steps, cache, tokens, lengths, rng)
    t_long = time.time() - t0

    dt = max(t_long - t_short, 1e-9)
    n_timed = decode_steps - n_short
    step_ms = dt / n_timed * 1000.0
    toks_per_sec = slots * n_timed / dt
    per_chip = toks_per_sec / n_chips

    # achieved HBM streaming: every decode step reads all weights once plus
    # the live KV prefix per slot (2 tensors, kv-heads, ctx, head_dim)
    ctx_mid = prompt_len + warmup + n_short + n_timed // 2
    # int8-KV streams 1 byte/element + a 4-byte f32 scale per position
    kv_elem_bytes = (
        cfg.head_dim * 1 + 4 if kv_quant
        else cfg.head_dim * jnp.dtype(cfg.jnp_dtype).itemsize
    )
    kv_bytes_step = 2 * cfg.n_layers * slots * cfg.n_kv_heads * ctx_mid * kv_elem_bytes
    bytes_step = param_bytes + kv_bytes_step
    bw_gbps = bytes_step / (dt / n_timed) / 1e9
    bw_util = bw_gbps / V5E_HBM_GBPS if on_tpu else 0.0
    flops_step = 2.0 * cfg.param_count * slots
    mfu = (flops_step / (dt / n_timed)) / (V5E_BF16_TFLOPS * 1e12) if on_tpu else 0.0

    data = {
        "model": cfg.name,
        "quant": quant + ("+int8kv" if kv_quant else ""),
        "quant_mode": quant_mode,
        "paged": paged,
        "slots": slots,
        "tokens_per_sec_per_chip": round(per_chip, 1),
        "total_tokens_per_sec": round(toks_per_sec, 1),
        "decode_step_ms": round(step_ms, 3),
        "ttft_p50_ms": round(ttft_p50, 2),
        "tunnel_rtt_p50_ms": round(rtt_p50, 2),
        "ttft_p50_adjusted_ms": round(ttft_adj, 2),
        "ttft_target_ms": 30.0,
        "prefill_first_call_s": round(prefill_first_s, 2),
        "flash_prefill_lowered": bool(flash_lowered),
        "flash_kernel_named_in_hlo": bool(flash_named),
        "hbm_bw_gbps": round(bw_gbps, 1),
        "hbm_bw_util": round(bw_util, 3),
        "mfu": round(mfu, 4),
        "scan_unroll": unroll,
        "param_count": cfg.param_count,
        "param_bytes": int(param_bytes),
        "n_chips": n_chips,
        "device": str(jax.devices()[0]),
        **_economics(jax, toks_per_sec, n_chips, on_tpu),
    }
    # compile-stats + headroom observability (docs/PROFILING.md)
    data["compile_wall_s"] = round(
        pf_cs.compile_wall_s + dec_cs.compile_wall_s, 3
    )
    data["compile_stats"] = {
        "prefill_one": pf_cs.to_dict(), "decode": dec_cs.to_dict(),
    }
    if headroom:
        data["hbm_headroom"] = headroom
        if headroom.get("downshifted"):
            data["downshifted"] = headroom["downshifted"]
    if paged_kernel_lowered is not None:
        data["paged_kernel_lowered"] = bool(paged_kernel_lowered)
    if prefill_rows:
        data["prefill_buckets"] = prefill_rows
    _progress(f"{mode}.decode", data)
    return data


def _run_hbm_child() -> dict:
    """HBM-bandwidth attribution (VERDICT round-4 #7: ~47% of bandwidth
    unaccounted at the claimed headline). Decode-step time is modeled as

        t(S) = t_fixed + S * t_per_slot

    where t_fixed covers the weight stream (slot-independent) plus
    dispatch/launch overhead, and t_per_slot covers the per-slot KV
    stream + sampling. Measuring steady-state steps at several slot
    counts and fitting the line separates the two; comparing t_fixed
    against param_bytes / peak_BW then says how close the weight stream
    runs to the HBM roofline, and the residual IS the unaccounted part
    (dispatch, XLA prologue/epilogue, layout stalls). Also measures the
    per-step host-readback tax (chained vs per-step sync) — the serving
    engine pays one readback per chunk, the bench's chained loop none."""
    jax = _child_setup()
    import jax.numpy as jnp
    import numpy as np

    from functools import partial

    from kserve_vllm_mini_tpu.models.config import get_config
    from kserve_vllm_mini_tpu.models.llama import (
        forward,
        init_kv_cache,
        init_params,
        init_params_quantized,
    )
    from kserve_vllm_mini_tpu.ops.quant import quantized_bytes
    from kserve_vllm_mini_tpu.runtime.sampling import sample_tokens

    model = _env_model()
    quant = _env_quant()
    kv_quant = _knob("KVMINI_BENCH_KV") == "int8"
    prompt_len = 128
    max_seq = 512
    steps = int(_knob("KVMINI_BENCH_STEPS", "64"))
    slot_grid = [
        int(s) for s in _knob("KVMINI_BENCH_HBM_SLOTS").split(",")
    ]
    on_tpu = _safe_backend(jax) == "tpu"
    unroll = int(_knob("KVMINI_BENCH_UNROLL"))
    quant_mode = _env_quant_mode() if quant != "none" else "dequant"
    cfg = get_config(model, max_seq_len=max_seq, scan_unroll=unroll,
                     quant_mode=quant_mode)
    if quant in ("int8", "int4"):
        params = init_params_quantized(
            jax.random.PRNGKey(0), cfg, bits=4 if quant == "int4" else 8
        )
    else:
        params = init_params(jax.random.PRNGKey(0), cfg)
    jax.block_until_ready(params)
    param_bytes = quantized_bytes(params)
    n_chips = jax.device_count()
    _log(f"hbm: model={model} quant={quant} quant_mode={quant_mode} "
         f"slot grid={slot_grid}")

    rows = []
    for S in slot_grid:
        cache = init_kv_cache(cfg, S, max_seq=max_seq, quantized=kv_quant)
        toks = jax.random.randint(jax.random.PRNGKey(1), (S, prompt_len), 0,
                                  cfg.vocab_size)
        pos = jnp.broadcast_to(jnp.arange(prompt_len, dtype=jnp.int32),
                               (S, prompt_len))

        @partial(jax.jit, donate_argnums=(1,))
        def prefill(params, cache, toks, pos, _S=S):
            last = jnp.full((_S,), prompt_len - 1, dtype=jnp.int32)
            lg, cache = forward(params, cfg, toks, pos, cache,
                                jnp.zeros((_S,), jnp.int32),
                                fresh_prefill=True, logit_index=last)
            return cache, jnp.argmax(lg[:, -1, :], axis=-1).astype(jnp.int32)

        @partial(jax.jit, donate_argnums=(1,))
        def decode(params, cache, tokens, lengths, rng, _S=S):
            lg, cache = forward(params, cfg, tokens[:, None],
                                lengths[:, None], cache, lengths)
            nxt = sample_tokens(
                lg[:, 0, :], rng,
                jnp.zeros((_S,), jnp.float32), jnp.zeros((_S,), jnp.int32),
                jnp.ones((_S,), jnp.float32),
            )
            return cache, nxt

        cache, tokens = prefill(params, cache, toks, pos)
        _ = np.asarray(tokens)
        lengths = jnp.full((S,), prompt_len, dtype=jnp.int32)
        rng = jax.random.PRNGKey(2)

        def run(n, cache, tokens, lengths, rng, sync_each=False):
            for _ in range(n):
                rng, sub = jax.random.split(rng)
                cache, tokens = decode(params, cache, tokens, lengths, sub)
                lengths = lengths + 1
                if sync_each:
                    _ = np.asarray(tokens)
            _ = np.asarray(tokens)
            return cache, tokens, lengths, rng

        # warm/compile, then chained (device-limited) and per-step-sync
        # (serving-style) timings; chained uses two-length differencing so
        # the relay RTT cancels
        cache, tokens, lengths, rng = run(6, cache, tokens, lengths, rng)
        n_short = steps // 4
        t0 = time.time()
        cache, tokens, lengths, rng = run(n_short, cache, tokens, lengths, rng)
        t_a = time.time() - t0
        t0 = time.time()
        cache, tokens, lengths, rng = run(steps, cache, tokens, lengths, rng)
        t_b = time.time() - t0
        chained_ms = max(t_b - t_a, 1e-9) / (steps - n_short) * 1000.0
        t0 = time.time()
        cache, tokens, lengths, rng = run(12, cache, tokens, lengths, rng,
                                          sync_each=True)
        sync_ms = (time.time() - t0) / 12 * 1000.0
        # midpoint of the timed chained window (same accounting as the
        # headline child's ctx_mid — the KV floor must price the context
        # the timed steps actually streamed)
        n_timed = steps - n_short
        ctx = prompt_len + 6 + n_short + n_timed // 2
        kv_elem = (cfg.head_dim + 4 if kv_quant
                   else cfg.head_dim * jnp.dtype(cfg.jnp_dtype).itemsize)
        kv_bytes = 2 * cfg.n_layers * S * cfg.n_kv_heads * ctx * kv_elem
        rows.append({
            "slots": S,
            "chained_step_ms": round(chained_ms, 3),
            "per_step_sync_ms": round(sync_ms, 3),
            "readback_tax_ms": round(sync_ms - chained_ms, 3),
            "kv_bytes_per_step": int(kv_bytes),
            "tokens_per_sec_per_chip": round(S / (chained_ms / 1000) / n_chips, 1),
        })
        _progress("hbm.row", rows[-1])
        _log(f"hbm S={S}: {rows[-1]}")
        del cache

    # least-squares fit t(S) = t_fixed + S * t_per_slot over the chained
    # timings, then the roofline decomposition
    Ss = np.asarray([r["slots"] for r in rows], np.float64)
    ts = np.asarray([r["chained_step_ms"] for r in rows], np.float64)
    A = np.stack([np.ones_like(Ss), Ss], axis=1)
    (t_fixed, t_per_slot), *_ = np.linalg.lstsq(A, ts, rcond=None)
    weight_floor_ms = param_bytes / (V5E_HBM_GBPS * 1e9) * 1000.0
    kv_per_slot_floor_ms = (
        rows[0]["kv_bytes_per_step"] / rows[0]["slots"]
        / (V5E_HBM_GBPS * 1e9) * 1000.0
    )
    data = {
        "model": cfg.name,
        "quant": quant,
        "rows": rows,
        "fit_t_fixed_ms": round(float(t_fixed), 3),
        "fit_t_per_slot_ms": round(float(t_per_slot), 4),
        "weight_stream_floor_ms": round(weight_floor_ms, 3),
        "kv_stream_floor_ms_per_slot": round(kv_per_slot_floor_ms, 5),
        # how much of the slot-independent time the weight stream explains;
        # the rest is dispatch/prologue/layout — the "unaccounted" bucket
        "weight_stream_fraction_of_fixed": round(
            weight_floor_ms / max(float(t_fixed), 1e-9), 3
        ) if on_tpu else 0.0,
        "kv_stream_fraction_of_per_slot": round(
            kv_per_slot_floor_ms / max(float(t_per_slot), 1e-9), 3
        ) if on_tpu else 0.0,
        "param_bytes": int(param_bytes),
        "n_chips": n_chips,
    }
    _progress("hbm.fit", data)
    return data


def _run_spec_child() -> dict:
    """Speculative decoding with a NAMED drafter (default llama-1b): the
    deployment shape — two distinct param trees, no relayout copy (the 8B
    self-drafter pays +5.9 GB for a second int8 layout and OOMs a v5e).
    Reference claim to beat: 20-40% decode improvement (README.md:118).

    With random weights a small drafter accepts ~0 (its argmax agrees with
    the target's at chance), so alongside the measured accept ratio the
    child reports the speedup PROJECTION at the reference's own 0.7
    acceptance threshold plus the measured round/step cost ratio — the
    bracket real checkpoints land in. KVMINI_BENCH_DRAFTER=self measures
    the accept=1 upper bound instead."""
    jax = _child_setup()
    import jax.numpy as jnp
    import numpy as np

    from functools import partial

    from kserve_vllm_mini_tpu.models.config import get_config
    from kserve_vllm_mini_tpu.models.llama import (
        forward,
        init_kv_cache,
        init_params,
        init_params_quantized,
    )
    from kserve_vllm_mini_tpu.runtime.engine import build_spec_step
    from kserve_vllm_mini_tpu.runtime.sampling import sample_tokens

    model = _env_model()
    quant = _env_quant()
    kv_quant = _knob("KVMINI_BENCH_KV") == "int8"
    spec_k = int(_knob("KVMINI_BENCH_SPEC"))
    drafter = _knob("KVMINI_BENCH_DRAFTER")
    # spec needs TWO caches (target + drafter) resident at once; 32 slots
    # keeps both under the v5e ceiling next to the int8 8B weights
    s_slots = int(_knob("KVMINI_BENCH_SPEC_SLOTS"))
    prompt_len = 128
    max_seq = 512
    unroll = int(_knob("KVMINI_BENCH_UNROLL"))
    quant_mode = _env_quant_mode() if quant != "none" else "dequant"
    cfg = get_config(model, max_seq_len=max_seq, scan_unroll=unroll,
                     quant_mode=quant_mode)
    n_chips = jax.device_count()
    _log(f"spec: model={model} drafter={drafter} k={spec_k} slots={s_slots} "
         f"backend={_safe_backend(jax)}")

    if quant in ("int8", "int4"):
        params = init_params_quantized(
            jax.random.PRNGKey(0), cfg, bits=4 if quant == "int4" else 8
        )
    else:
        params = init_params(jax.random.PRNGKey(0), cfg)
    if drafter == "self":
        dcfg, dparams = cfg, params
    else:
        dcfg = get_config(drafter, max_seq_len=max_seq)
        if dcfg.vocab_size != cfg.vocab_size:
            dcfg = dcfg.scaled(vocab_size=cfg.vocab_size)
        # the drafter is small — bf16 keeps its quality; distinct tree, so
        # no cross-layout copy of the target's weights exists
        dparams = init_params(jax.random.PRNGKey(3), dcfg)
    jax.block_until_ready(params)
    _log("params ready (target + drafter)")

    toks_s = jax.random.randint(jax.random.PRNGKey(1), (s_slots, prompt_len), 0,
                                cfg.vocab_size)
    pos_s = jnp.broadcast_to(jnp.arange(prompt_len, dtype=jnp.int32),
                             (s_slots, prompt_len))

    @partial(jax.jit, donate_argnums=(1,), static_argnums=(4,))
    def sprefill(p, c, t, pp, which_cfg_is_target=True):
        cc = cfg if which_cfg_is_target else dcfg
        lg, c2 = forward(p, cc, t, pp, c, jnp.zeros((s_slots,), jnp.int32),
                         fresh_prefill=True,
                         logit_index=jnp.full((s_slots,), prompt_len - 1, jnp.int32))
        return c2, jnp.argmax(lg[:, -1, :], axis=-1).astype(jnp.int32)

    @partial(jax.jit, donate_argnums=(1,))
    def sdecode(p, c, tokens, lengths, rng):
        logits, c = forward(p, cfg, tokens[:, None], lengths[:, None], c, lengths)
        nxt = sample_tokens(
            logits[:, 0, :], rng,
            jnp.zeros((s_slots,), jnp.float32),
            jnp.zeros((s_slots,), jnp.int32),
            jnp.ones((s_slots,), jnp.float32),
        )
        return c, nxt

    # served-style plain step baseline — one readback per step, like the
    # engine's sweep — so the spec comparison is methodology-consistent (a
    # spec round inherently pays one host readback; the chained RTT-
    # cancelled headline step would bias spec low). Runs BEFORE the two
    # spec caches exist so at most two s_slots caches are ever resident.
    lengths_p = jnp.full((s_slots,), prompt_len, dtype=jnp.int32)
    cache_p = init_kv_cache(cfg, s_slots, max_seq=max_seq, quantized=kv_quant)
    cache_p, toks_p = sprefill(params, cache_p, toks_s, pos_s, True)
    rng_p = jax.random.PRNGKey(9)
    for _ in range(4):  # warm
        rng_p, sub_p = jax.random.split(rng_p)
        cache_p, toks_p = sdecode(params, cache_p, toks_p, lengths_p, sub_p)
        _ = np.asarray(toks_p)
        lengths_p = lengths_p + 1
    n_served = 16
    t0 = time.time()
    for _ in range(n_served):
        rng_p, sub_p = jax.random.split(rng_p)
        cache_p, toks_p = sdecode(params, cache_p, toks_p, lengths_p, sub_p)
        _ = np.asarray(toks_p)  # per-step readback, like a serving sweep
        lengths_p = lengths_p + 1
    t_step_served = max(time.time() - t0, 1e-9) / n_served
    _progress("spec.served_baseline", {
        "served_step_ms": round(t_step_served * 1000.0, 3),
        "slots": s_slots,
    })
    cache_p = None  # make room for the drafter cache

    t_cache, last = sprefill(
        params, init_kv_cache(cfg, s_slots, max_seq=max_seq, quantized=kv_quant),
        toks_s, pos_s, True,
    )
    d_cache, _ = sprefill(
        dparams, init_kv_cache(dcfg, s_slots, max_seq=max_seq, quantized=kv_quant),
        toks_s, pos_s, False,
    )
    spec = build_spec_step(cfg, dcfg, spec_k)
    lengths_h = np.full((s_slots,), prompt_len, dtype=np.int64)

    def spec_rounds(n, t_cache, d_cache, last, lengths_h):
        emitted = accepted = 0
        for _ in range(n):
            t_cache, d_cache, emit = spec(
                params, t_cache, dparams, d_cache,
                last, jnp.asarray(lengths_h, jnp.int32),
            )
            eh = np.asarray(jax.device_get(emit))   # sync point
            cnt = (eh >= 0).sum(axis=1)
            emitted += int(cnt.sum())
            accepted += int(np.maximum(cnt - 1, 0).sum())
            idx = np.clip(cnt - 1, 0, spec_k - 1)
            last = jnp.asarray(eh[np.arange(s_slots), idx].astype(np.int32))
            lengths_h = lengths_h + cnt
        return t_cache, d_cache, last, lengths_h, emitted, accepted

    max_rounds = max((max_seq - 1 - prompt_len - 8) // spec_k, 8)
    n_warm, n_meas = 3, min(24, max_rounds - 3)
    t_cache, d_cache, last, lengths_h, _, _ = spec_rounds(
        n_warm, t_cache, d_cache, last, lengths_h
    )
    _log("spec warmup done; timing")
    t0 = time.time()
    t_cache, d_cache, last, lengths_h, emitted, accepted = spec_rounds(
        n_meas, t_cache, d_cache, last, lengths_h
    )
    dt_spec = max(time.time() - t0, 1e-9)
    spec_tps = emitted / dt_spec
    proposed = n_meas * (spec_k - 1) * s_slots
    t_round = dt_spec / n_meas

    # speedup is a function of the acceptance rate α: a round costs t_round
    # and emits (k-1)α + 1 tokens/slot vs 1 per served step. α itself needs
    # real checkpoints (random-weight drafters accept at chance), so report
    # the measured α plus projections at α=0.7 (the reference's stated
    # threshold for its 20-40% claim) and α=1.
    def speedup_at(alpha: float) -> float:
        return ((spec_k - 1) * alpha + 1) * t_step_served / t_round

    data = {
        "drafter": drafter,
        "drafter_params": dcfg.param_count,
        "spec_tokens": spec_k,
        "slots": s_slots,
        "accept_ratio": round(accepted / proposed, 4) if proposed else 1.0,
        "tokens_per_sec_per_chip": round(spec_tps / n_chips, 1),
        "speedup_vs_served_measured": round(spec_tps / (s_slots / t_step_served), 3),
        "round_ms": round(t_round * 1000.0, 3),
        "served_step_ms": round(t_step_served * 1000.0, 3),
        "projected_speedup_at_accept_0.7": round(speedup_at(0.7), 3),
        "projected_speedup_at_accept_1.0": round(speedup_at(1.0), 3),
    }
    _progress("spec.result", data)
    return data


def _run_proxy_child() -> dict:
    """CPU-mesh proxy tier (docs/PROFILING.md): compile stats, cost-model
    FLOPs/bytes, peak-buffer estimates, and the sync-vs-chained
    step-count ratio on the forced 8-device host platform. The parent
    launches this child with JAX_PLATFORMS=cpu after the TPU probe fails,
    so a wedged relay degrades the round to tracked proxy metrics instead
    of darkness. Everything returned is labeled ``series: "proxy"`` and
    never claims device throughput."""
    jax = _child_setup()

    from kserve_vllm_mini_tpu.profiling.headroom import HBM_BYTES_BY_KIND
    from kserve_vllm_mini_tpu.profiling.proxy import run_proxy_tier

    model = _knob("KVMINI_BENCH_PROXY_MODEL") or _env_model()
    exec_model = _knob("KVMINI_BENCH_PROXY_EXEC_MODEL")
    gb = _knob("KVMINI_BENCH_HBM_GB")
    # no device to introspect in a proxy round: pre-flight the flagship
    # against the v5e capacity the BASELINE targets assume (overridable)
    hbm = int(float(gb) * 1e9) if gb else dict(HBM_BYTES_BY_KIND)["v5e"]
    _log(f"proxy tier: model={model} exec={exec_model} "
         f"backend={_safe_backend(jax)} devices={jax.device_count()}")
    data = run_proxy_tier(
        model,
        exec_model=exec_model,
        quant=_env_quant(),
        slots=_env_slots(),
        decode_steps=int(_knob("KVMINI_BENCH_PROXY_STEPS")),
        kv_quant=_knob("KVMINI_BENCH_KV") == "int8",
        quant_mode=(
            _env_quant_mode() if _env_quant() != "none" else "dequant"
        ),
        hbm_bytes=hbm,
        prefill_chunk=_env_prefill_chunk(),
    )
    _progress("proxy.block", data)
    return data


# ---------------------------------------------------------------------------
# Orchestration: probe -> sub-bench children -> one JSON line, rc 0 always.
# ---------------------------------------------------------------------------

def _bench_label() -> str:
    # raw env strings only: this runs on the must-never-raise failure path
    slots = os.environ.get("KVMINI_BENCH_SLOTS", _DEFAULT_SLOTS)
    return f"{_env_model()}, {_env_quant()}, slots={slots}"


def _classify(err_text: str) -> str:
    if "RESOURCE_EXHAUSTED" in err_text:
        return "oom"
    if "UNAVAILABLE" in err_text or "Unable to initialize backend" in err_text:
        return "tpu_unavailable"
    return "error"


def _probe(timeout_s: float) -> tuple[bool, str, str]:
    """No-op dispatch + readback in a subprocess under a hard timeout.

    A wedged relay blocks the dispatch forever — only a subprocess timeout
    can detect that. Returns (ok, status, detail)."""
    # The axon site hook imports jax at interpreter start, so the
    # JAX_PLATFORMS env var alone is too late — mirror tests/conftest.py and
    # update jax.config before any device is touched.
    code = (
        "import os, jax, numpy as np; "
        "p = os.environ.get('JAX_PLATFORMS'); "
        "p and jax.config.update('jax_platforms', p); "
        "print('backend', jax.default_backend(), "
        "float(np.asarray(jax.numpy.ones((4,)).sum())))"
    )
    try:
        p = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, errors="replace",
            timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return False, "tpu_unavailable", (
            f"probe timed out after {timeout_s:.0f}s — relay wedged "
            "(dispatch blocks forever; see docs/TROUBLESHOOTING.md)"
        )
    if p.returncode != 0:
        detail = f"probe rc={p.returncode}: {p.stderr.strip()[-1200:]}"
        return False, _classify(detail), detail
    # JAX can fall back to CPU with only a warning when the TPU plugin
    # fails to init — a "successful" CPU probe in a TPU-expected env would
    # run the 8B flagship on CPU and produce a misleading artifact.
    out = p.stdout.strip()
    parts = out.split()
    backend = parts[1] if len(parts) >= 2 else "?"
    plat = os.environ.get("JAX_PLATFORMS", "").split(",")[0].strip().lower()
    if plat in ("", "axon", "tpu") and backend != "tpu":
        return False, "tpu_unavailable", (
            f"probe fell back to backend {backend!r} (expected tpu; "
            f"JAX_PLATFORMS={plat or '<unset>'}): {out}"
        )
    return True, "ok", out


def _probe_until(budget_s: float, probe_timeout: float) -> tuple[bool, str, str]:
    """Adaptive probe loop under a TOTAL budget (VERDICT round-4 #1: the
    fixed 3x75 s schedule covered ~7 min while documented wedge windows run
    ~40 min). Waits escalate 30 -> 60 -> 120 -> 240 -> 300 s (then 300 s
    flat) so a fast recovery is caught fast and a long wedge is out-waited
    without hammering the relay."""
    deadline = time.time() + budget_s
    waits = [30.0, 60.0, 120.0, 240.0]
    attempt = 0
    while True:
        attempt += 1
        ok, status, detail = _probe(probe_timeout)
        if ok:
            _log(f"backend probe ok (attempt {attempt}): {detail}")
            return ok, status, detail
        remaining = deadline - time.time()
        wait = waits[min(attempt - 1, len(waits) - 1)] if attempt <= len(waits) \
            else 300.0
        if remaining <= wait + probe_timeout:
            _log(f"probe budget exhausted after {attempt} attempts "
                 f"({budget_s:.0f}s): {detail}")
            return False, status, (
                f"{detail} [probe gave up after {attempt} attempts over "
                f"{budget_s:.0f}s budget; set KVMINI_BENCH_PROBE_BUDGET_S "
                f"higher to out-wait longer wedges]"
            )
        _log(f"probe failed ({status}); retrying in {wait:.0f}s "
             f"(attempt {attempt}, {remaining:.0f}s of budget left)")
        time.sleep(wait)


def _run_child(mode: str, env_extra: dict, run_timeout: float,
               progress_path: str) -> tuple:
    """One sub-benchmark child under a hard timeout. Returns (rc, stdout,
    stderr_text); rc None means the timeout killed it."""
    env = dict(os.environ, KVMINI_BENCH_CHILD=mode,
               KVMINI_BENCH_PROGRESS=progress_path, **env_extra)
    with tempfile.NamedTemporaryFile("w+", suffix=".bench-stderr",
                                     errors="replace") as errf:
        try:
            p = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                env=env, stdout=subprocess.PIPE, stderr=errf, text=True,
                errors="replace", timeout=run_timeout,
            )
            rc, out = p.returncode, p.stdout
        except subprocess.TimeoutExpired as e:
            rc, out = None, (e.stdout or "")
            if isinstance(out, bytes):
                out = out.decode(errors="replace")
        errf.seek(0)
        err_text = errf.read()
    sys.stderr.write(err_text)  # keep the child's stage log visible
    sys.stderr.flush()
    return rc, out, err_text


def _extract_result(out: str):
    """The child's LAST parseable JSON line (teardown noise or a post-print
    crash must not cost us the measurement)."""
    result_line = None
    for line in out.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                parsed = json.loads(line)
                if isinstance(parsed, dict) and "data" in parsed:
                    result_line = parsed
            except ValueError:
                continue
    return result_line


def _read_progress(path: str) -> dict:
    """Fold the child's incremental progress lines into {key: data}."""
    out: dict = {}
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                    out[rec["key"]] = rec["data"]
                except (ValueError, KeyError):
                    continue
    except OSError:
        pass
    return out


class _Artifact:
    """The one-line artifact, assembled incrementally and emittable at any
    moment (SIGTERM from a driver-side timeout included)."""

    def __init__(self) -> None:
        self.sub: dict = {}          # mode -> {"status": ..., ...data}
        self.emitted = False

    def record(self, mode: str, status: str, data: dict | None,
               failure: str | None = None) -> None:
        entry: dict = {"status": status}
        if data:
            entry.update(data)
        if failure:
            entry["failure"] = failure[-1200:]
        self.sub[mode] = entry
        # persist next to the run so a SIGKILLed parent still leaves an
        # inspectable partial on disk
        try:
            with open("bench_partial.json", "w") as f:
                json.dump(self.sub, f, indent=2)
        except OSError:
            pass

    def emit(self, top_status: str, top_note: str = "") -> None:
        if self.emitted:
            return
        self.emitted = True
        head = self.sub.get("headline", {})
        # a child that measured decode and then died in teardown (the
        # documented post-print wedge) leaves the full decode record in its
        # progress file, folded here under head["decode"] — that IS the
        # measurement, so surface it instead of reporting NOT MEASURED
        dec = head.get("decode")
        if (
            "tokens_per_sec_per_chip" not in head
            and isinstance(dec, dict)
            and dec.get("tokens_per_sec_per_chip")
        ):
            head = {k: v for k, v in head.items() if k != "decode"}
            head.update(dec)
            head["note_headline"] = (
                "decode measured and persisted via the progress file; the "
                "child died after the measurement (status carries the "
                "failure mode)"
            )
        value = float(head.get("tokens_per_sec_per_chip", 0.0) or 0.0)
        ok = head.get("status") in ("ok", "timeout", "error") and value > 0
        label = _bench_label()
        metric = f"decode_tokens_per_sec_per_chip ({label})"
        if "headline" not in self.sub:
            metric += " [headline not selected by KVMINI_BENCH_MODES]"
        elif not ok:
            metric += f" [NOT MEASURED: {top_status}]"
        detail = dict(head)
        detail.pop("status", None)
        nested = {"paged": "paged_kv", "spec": "speculative", "int4": "int4",
                  "hbm": "hbm_attribution", "proxy": "proxy"}
        for mode, key in nested.items():
            if mode in self.sub:
                detail[key] = self.sub[mode]
        if top_note:
            detail["note"] = top_note
        record = {
            "metric": metric,
            "value": round(value, 1),
            "unit": "tokens/s/chip",
            "vs_baseline": round(value / _BASELINE_TOKS, 3),
            "status": top_status if not ok else "ok",
            "detail": detail,
        }
        print(json.dumps(record), flush=True)


def _orchestrate() -> int:
    art = _Artifact()

    def on_term(signum, frame):  # noqa: ARG001
        _log(f"signal {signum}: emitting partial artifact")
        art.emit("timeout", "parent received SIGTERM/SIGINT mid-run; "
                           "sub-benches recorded so far are included")
        sys.exit(0)

    # restore on exit: guard tests call main() in-process, and a leaked
    # handler would hijack the TEST runner's SIGINT/SIGTERM
    old_term = signal.signal(signal.SIGTERM, on_term)
    old_int = signal.signal(signal.SIGINT, on_term)
    try:
        return _orchestrate_body(art)
    finally:
        signal.signal(signal.SIGTERM, old_term)
        signal.signal(signal.SIGINT, old_int)


def _run_proxy_fallback(art: "_Artifact", run_timeout: float,
                        deadline: "float | None" = None) -> None:
    """Degrade the round to the CPU-mesh proxy tier (docs/PROFILING.md):
    one more child, on the forced 8-device host platform, so the round
    still lands tracked compile/cost-model metrics. Honors
    KVMINI_BENCH_PROXY=never."""
    if _knob("KVMINI_BENCH_PROXY") == "never" or "proxy" in art.sub:
        return
    budget = run_timeout
    if deadline is not None:
        # same refusal contract as the mode loop: never launch a child the
        # deadline can't accommodate — the parent must always have time to
        # print its one JSON line
        left = deadline - time.time()
        if left < 150.0:
            art.record("proxy", "skipped", None,
                       f"skipped: {left:.0f}s left before the deadline")
            return
        budget = min(run_timeout, left - 30.0)
    flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    flags.append("--xla_force_host_platform_device_count=8")
    env = {"JAX_PLATFORMS": "cpu", "XLA_FLAGS": " ".join(flags)}
    with tempfile.NamedTemporaryFile("w", suffix=".proxy.progress",
                                     delete=False) as pf:
        progress_path = pf.name
    _log(f"=== proxy tier (forced 8-device host platform, "
         f"timeout {budget:.0f}s) ===")
    rc, out, err_text = _run_child("proxy", env, budget, progress_path)
    parsed = _extract_result(out)
    if parsed is not None:
        art.record("proxy", "ok", parsed["data"])
        _log("proxy tier ok: compile stats + cost model captured")
    else:
        partial = _read_progress(progress_path)
        failure = (f"proxy child exceeded {budget:.0f}s" if rc is None
                   else f"proxy child rc={rc}: {err_text[-800:]}")
        art.record("proxy", "error",
                   partial.get("proxy.block"), failure)
        _log(f"proxy tier failed: {failure}")
    try:
        os.unlink(progress_path)
    except OSError:
        pass


def _orchestrate_body(art: "_Artifact") -> int:
    probe_timeout = float(_knob("KVMINI_BENCH_PROBE_TIMEOUT"))
    probe_budget = float(_knob("KVMINI_BENCH_PROBE_BUDGET_S"))
    run_timeout = float(_knob("KVMINI_BENCH_TIMEOUT"))
    # stop launching new children past the deadline so the parent always
    # has time to print (the driver's own patience is unknown)
    deadline = _T_START + float(_knob("KVMINI_BENCH_DEADLINE_S"))
    modes = [m.strip() for m in _knob("KVMINI_BENCH_MODES").split(",")
             if m.strip()]

    ok, probe_status, probe_detail = _probe_until(probe_budget, probe_timeout)
    if not ok:
        art.record("headline", probe_status, None,
                   f"probe never succeeded: {probe_detail}")
        # never-dark (ROADMAP item 5): the round degrades to the CPU-mesh
        # proxy tier instead of ending with an empty artifact
        _run_proxy_fallback(art, run_timeout, deadline)
        note = ("retry plan: driver re-runs bench.py next round; raise "
                "KVMINI_BENCH_PROBE_BUDGET_S past the wedge window")
        if art.sub.get("proxy", {}).get("status") == "ok":
            note = ("proxy tier carried the round (detail.proxy: compile "
                    "stats, cost-model FLOPs/bytes, step-count ratio); " + note)
        art.emit(probe_status, note)
        return 0

    wedged = False
    for mode in modes:
        if wedged:
            art.record(mode, "skipped", None,
                       "skipped: backend wedged earlier in the queue")
            continue
        left = deadline - time.time()
        if left < 180:
            art.record(mode, "skipped", None,
                       f"skipped: {left:.0f}s left before the deadline")
            continue
        budget = min(run_timeout, left - 120)
        with tempfile.NamedTemporaryFile("w", suffix=f".{mode}.progress",
                                         delete=False) as pf:
            progress_path = pf.name
        _log(f"=== sub-bench {mode} (timeout {budget:.0f}s) ===")
        rc, out, err_text = _run_child(mode, {}, budget, progress_path)
        parsed = _extract_result(out)
        status = ("timeout" if rc is None
                  else ("ok" if rc == 0 and parsed else _classify(err_text)))

        # headline OOM at the 80-slot default: retry once at the proven 64
        # (only OOM qualifies — a wedge fails the same way at any slot count)
        if (
            mode in ("headline", "paged", "int4")
            and parsed is None
            and status == "oom"
            and "KVMINI_BENCH_SLOTS" not in os.environ
        ):
            _log(f"{mode} OOM at slots={_DEFAULT_SLOTS}; retrying at "
                 f"slots={_FALLBACK_SLOTS}")
            rc, out, err_text = _run_child(
                mode, {"KVMINI_BENCH_SLOTS": _FALLBACK_SLOTS},
                min(run_timeout, deadline - time.time() - 120), progress_path,
            )
            parsed = _extract_result(out)
            if parsed is not None:
                parsed["data"]["slots_fallback"] = (
                    f"default slots={_DEFAULT_SLOTS} OOMed; measured at "
                    f"slots={_FALLBACK_SLOTS}"
                )
                status = "ok"

        if parsed is not None:
            art.record(mode, "ok", parsed["data"])
            _log(f"{mode} ok: "
                 f"{parsed['data'].get('tokens_per_sec_per_chip', '-')} tok/s/chip")
        else:
            partial = _read_progress(progress_path)
            failure = (f"child exceeded {budget:.0f}s (likely mid-run relay "
                       f"wedge)" if rc is None
                       else f"child rc={rc}: {err_text[-800:]}")
            data = {}
            for key, d in partial.items():
                data[key.split(".", 1)[-1]] = d
            art.record(mode, status, data or None, failure)
            _log(f"{mode} failed ({status}); "
                 f"{len(partial)} partial measurements retained")
            if status in ("timeout", "tpu_unavailable"):
                # re-probe quickly: if the relay is wedged, later children
                # would burn their timeouts for nothing
                ok2, _s, _d = _probe(probe_timeout)
                wedged = not ok2
                if wedged:
                    _log("relay wedged after child failure; skipping the "
                         "remaining sub-benches")
        try:
            os.unlink(progress_path)
        except OSError:
            pass

    if "headline" in art.sub:
        head_status = art.sub["headline"].get("status", "error")
    else:
        # operator-selected modes without the headline (e.g. a spec-only
        # re-run): the round's status is the selected sub-benches', not a
        # fabricated headline failure
        statuses = [e.get("status", "error") for e in art.sub.values()]
        head_status = next((s for s in statuses if s != "ok"), "ok")
    # never-dark: a round that lost its device mid-queue, OOMed past the
    # guard (or even at the guard's own pre-flight), or an operator asking
    # with KVMINI_BENCH_PROXY=always — all still land proxy metrics
    if (
        wedged
        or head_status in ("tpu_unavailable", "timeout", "oom")
        or _knob("KVMINI_BENCH_PROXY") == "always"
    ):
        _run_proxy_fallback(art, run_timeout, deadline)
    art.emit(head_status if head_status != "ok" else "ok")
    return 0


# env knob -> (CLI flag, default, help) — ONE table so --help, the flag
# parser, and the docs can never drift. Flags just set the env var (children
# inherit the environment, so both spellings reach every subprocess).
_ENV_KNOBS = {
    "KVMINI_BENCH_PROBE_BUDGET_S": (
        "--probe-budget-s", "1800",
        "total seconds to keep re-probing a wedged/unavailable TPU relay "
        "before giving up (observed wedge windows run ~40 min; raise past "
        "the wedge window when rounds die with status tpu_unavailable)",
    ),
    "KVMINI_BENCH_PROBE_TIMEOUT": (
        "--probe-timeout-s", "90",
        "hard timeout for ONE no-op probe dispatch (a wedged relay blocks "
        "forever; only a subprocess timeout detects it)",
    ),
    "KVMINI_BENCH_TIMEOUT": (
        "--run-timeout-s", "900",
        "hard timeout for one sub-benchmark child process",
    ),
    "KVMINI_BENCH_DEADLINE_S": (
        "--deadline-s", "7200",
        "stop launching new children this many seconds after start, so the "
        "parent always has time to print its one JSON line",
    ),
    "KVMINI_BENCH_MODES": (
        "--modes", "headline,paged,spec,int4,hbm",
        "comma-separated sub-benchmarks to run, in order",
    ),
    "KVMINI_BENCH_MODEL": (
        "--model", _DEFAULT_MODEL,
        "model config to serve (llama-tiny smoke-tests on CPU)",
    ),
    "KVMINI_BENCH_QUANT": (
        "--quant", _DEFAULT_QUANT,
        "weight quantization for the headline config",
    ),
    "KVMINI_BENCH_SLOTS": (
        "--slots", _DEFAULT_SLOTS,
        "decode batch slots (OOM at the default retries once at "
        f"{_FALLBACK_SLOTS})",
    ),
    "KVMINI_BENCH_STEPS": (
        "--steps", "128",
        "decode steps per timed measurement (the hbm sub-bench defaults "
        "to 64 when unset)",
    ),
    "KVMINI_BENCH_KV": (
        "--kv", "",
        "KV-cache quantization (kv_cache_dtype): 'int8' for scaled int8 "
        "KV (dense decode dequantizes in-kernel on TPU, paged already "
        "does), empty for the model dtype",
    ),
    "KVMINI_BENCH_QUANT_MODE": (
        "--quant-mode", "dequant",
        "how quantized matmuls contract (ops/qmatmul.py): 'dequant' casts "
        "the int weight to bf16 before the dot (W8A16/W4A16), 'w8a8' "
        "quantizes activations per token and contracts int8 x int8 on the "
        "MXU; also labels the proxy tier's compile drift",
    ),
    "KVMINI_BENCH_PAGED": (
        "--paged", "",
        "'1' routes the serving sub-benches through the paged KV pool "
        "even outside the paged mode",
    ),
    "KVMINI_BENCH_PREFILL_CHUNK": (
        "--prefill-chunk", "",
        "tokens per interleaved prefill chunk (runtime/engine.py "
        "prefill_chunk): the serving children time a chunked single-"
        "request prefill next to the monolithic one and the headroom "
        "guard prices the per-chunk workspace, and the proxy tier sizes "
        "its chunk-prefill cost entry to match — so sweeps can put "
        "chunk size on an axis; empty = monolithic prefill",
    ),
    "KVMINI_BENCH_DISAGG": (
        "--disagg", "",
        "'1' benches the disaggregated prefill lane (runtime/disagg.py, "
        "docs/DISAGGREGATION.md): the serving children time the lane's "
        "staging prefill + KV-block handoff injection next to the "
        "monolithic TTFT probe (the {mode}.disagg_prefill row), and the "
        "proxy tier's disagg_prefill compile-stats entry tracks the lane "
        "executable across dark rounds either way; empty = colocated",
    ),
    "KVMINI_BENCH_FLEET": (
        "--fleet", "",
        "N>=2 runs the multi-replica fleet sub-bench (fleet/, docs/"
        "FLEET.md): N CPU-forced llama-tiny serve replicas behind the "
        "cache-aware router — the {mode}.fleet row measures scale-up "
        "cold start (spawn -> healthy), routed request p50 over a "
        "prefix-heavy multi-session burst, and the placement/reroute "
        "mix. Fleet MECHANICS only (replicas pin JAX_PLATFORMS=cpu so "
        "they never contend for the TPU under test) — the row makes no "
        "accelerator throughput claims; empty/0 = off",
    ),
    "KVMINI_BENCH_KV_TIER": (
        "--kv-tier", "",
        "host-RAM KV tier byte cap for the fleet row's paged replicas "
        "(serve --kv-host-tier-bytes; docs/TROUBLESHOOTING.md 'Host-RAM "
        "KV tier thrash'): retained-LRU evictions demote to host RAM "
        "and promote back on prefix match instead of re-prefilling. "
        "Requires KVMINI_BENCH_FLEET >= 2; empty/0 = no tier",
    ),
    "KVMINI_BENCH_MIGRATE": (
        "--migrate", "",
        "'1' adds a warm-from-sibling migration leg to the fleet row "
        "(docs/FLEET.md): after the routed burst, one replica is killed "
        "and its respawn imports the deepest-owning sibling's retained "
        "prefix blocks (/kv/export -> /kv/import); the row reports the "
        "supervisor's warmed/warm_failures counters. Requires "
        "KVMINI_BENCH_FLEET >= 2; empty = cold respawn",
    ),
    "KVMINI_BENCH_UNROLL": (
        "--unroll", "1",
        "layer-scan unroll factor for the model config",
    ),
    "KVMINI_BENCH_SPEC": (
        "--spec-tokens", "4",
        "draft tokens per fused speculative round (spec sub-bench)",
    ),
    "KVMINI_BENCH_DRAFTER": (
        "--drafter", "llama-1b",
        "drafter model for the spec sub-bench ('self' = self-drafting "
        "upper bound)",
    ),
    "KVMINI_BENCH_SPEC_SLOTS": (
        "--spec-slots", "32",
        "decode batch slots for the spec sub-bench (two models resident)",
    ),
    "KVMINI_BENCH_HBM_SLOTS": (
        "--hbm-slots", "16,32,48,64,80",
        "slot grid the hbm sub-bench fits t_fixed + S*t_per_slot over",
    ),
    "KVMINI_BENCH_PROXY": (
        "--proxy", "auto",
        "CPU-mesh proxy tier (docs/PROFILING.md): 'auto' runs it whenever "
        "the TPU probe fails or the relay wedges mid-queue, 'always' also "
        "appends it to a successful round, 'never' disables it",
    ),
    "KVMINI_BENCH_PROXY_MODEL": (
        "--proxy-model", "",
        "model config the proxy tier compiles ABSTRACTLY for cost-model "
        "FLOPs/bytes — no weights materialized (empty = --model)",
    ),
    "KVMINI_BENCH_PROXY_EXEC_MODEL": (
        "--proxy-exec-model", "llama-tiny",
        "small config the proxy tier actually executes on the forced "
        "8-device host mesh for the sync-vs-chained step-count ratio",
    ),
    "KVMINI_BENCH_PROXY_STEPS": (
        "--proxy-steps", "24",
        "decode steps per proxy-tier timing window",
    ),
    "KVMINI_BENCH_HBM_GB": (
        "--hbm-gb", "",
        "per-chip HBM capacity (GB) for the admission/headroom guard; "
        "empty = detect from the device (guard disabled on CPU without "
        "an override); the proxy tier defaults to the v5e's 16",
    ),
    "KVMINI_BENCH_COST_BUDGET": (
        "--cost-budget", "",
        "$/1K-token budget the serving rows judge their economics "
        "against (docs/ECONOMICS.md): each TPU row's cost_per_1k_tokens_"
        "usd gains a cost_over_budget verdict; empty = no verdict "
        "(CPU smoke rows never get one — no fabricated passes)",
    ),
}
# parent<->child plumbing, not operator knobs (set by the orchestrator):
# KVMINI_BENCH_CHILD selects a sub-benchmark body, KVMINI_BENCH_PROGRESS
# points at the incremental progress file


def _knob(env: str, default: str | None = None) -> str:
    """Read an env knob with its _ENV_KNOBS default — the read sites MUST
    come through here or --help and behavior drift apart. ``default``
    overrides the table for the few mode-dependent cases (documented in
    the knob's help text), so even those stay greppable via this one
    function."""
    return os.environ.get(
        env, default if default is not None else _ENV_KNOBS[env][1]
    )


def _parse_args(argv: list) -> None:
    """CLI front over the env knobs. Every flag simply sets its env var,
    so the child processes and the documented env spellings stay the one
    source of truth; an env var set by the caller wins unless the flag is
    passed explicitly."""
    import argparse

    parser = argparse.ArgumentParser(
        prog="bench.py",
        description=(
            "Driver benchmark: one JSON line of serving numbers on the "
            "attached accelerator. Always exits 0 with a parseable "
            "artifact, even on TPU wedge/timeout."
        ),
        epilog=(
            "Every flag mirrors an environment variable (flag wins when "
            "both are set): "
            + "; ".join(
                f"{flag} = {env} (default {default!r})"
                for env, (flag, default, _h) in _ENV_KNOBS.items()
            )
        ),
    )
    for env, (flag, default, help_text) in _ENV_KNOBS.items():
        parser.add_argument(
            flag, default=None, metavar="V",
            help=f"{help_text} [env {env}, default {default}]",
        )
    args = parser.parse_args(argv)
    for env, (flag, _default, _h) in _ENV_KNOBS.items():
        val = getattr(args, flag.lstrip("-").replace("-", "_"))
        if val is not None:
            os.environ[env] = str(val)


def main(argv: list | None = None) -> int:
    # argv is only parsed when given (the __main__ path): the orchestration
    # guard tests call main() in-process under pytest, whose own argv must
    # not leak into the bench parser
    if argv is not None:
        _parse_args(argv)
    mode = os.environ.get("KVMINI_BENCH_CHILD")
    if mode:
        # Child: do the real work; the parent structures any failure.
        # flush — the pipe is block-buffered, and a post-print teardown
        # wedge must not strand the finished measurement in the buffer.
        if mode == "spec":
            data = _run_spec_child()
        elif mode == "hbm":
            data = _run_hbm_child()
        elif mode == "proxy":
            data = _run_proxy_child()
        else:
            data = _run_serving_child(mode)
        print(json.dumps({"mode": mode, "status": "ok", "data": data}),
              flush=True)
        return 0
    try:
        return _orchestrate()
    except Exception:  # noqa: BLE001 — the one-JSON-line contract is absolute
        import traceback

        art = _Artifact()
        art.record("headline", "error", None, traceback.format_exc())
        art.emit("error", "orchestrator crashed; traceback in detail")
        return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
