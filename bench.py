#!/usr/bin/env python3
"""Driver benchmark: decode throughput of the in-repo engine on real TPU.

Prints ONE JSON line:
  {"metric": "...", "value": N, "unit": "...", "vs_baseline": N}

Measures steady-state decode tokens/sec/chip on a Llama-architecture model
(llama-1b config, bf16, random weights — throughput is weight-value
independent) with all engine slots busy, jitted decode steps, donated cache.
Baseline: the north-star >=2000 output tokens/sec/chip
(/root/repo/BASELINE.json; BASELINE.md north-star table).
"""

from __future__ import annotations

import json
import sys
import time


def main() -> int:
    import jax
    import jax.numpy as jnp

    from kserve_vllm_mini_tpu.models.config import get_config
    from kserve_vllm_mini_tpu.models.llama import forward, init_kv_cache, init_params
    from kserve_vllm_mini_tpu.runtime.sampling import sample_tokens

    model = "llama-1b"
    slots = 32
    prompt_len = 128
    max_seq = 1024
    decode_steps = 256
    warmup = 16

    cfg = get_config(model, max_seq_len=max_seq)
    params = init_params(jax.random.PRNGKey(0), cfg)

    cache = init_kv_cache(cfg, slots, max_seq=max_seq)
    toks = jax.random.randint(jax.random.PRNGKey(1), (slots, prompt_len), 0, cfg.vocab_size)
    pos = jnp.broadcast_to(jnp.arange(prompt_len, dtype=jnp.int32), (slots, prompt_len))

    from functools import partial

    @partial(jax.jit, donate_argnums=(1,))
    def prefill(params, cache, toks, pos):
        logits, cache = forward(params, cfg, toks, pos, cache,
                                jnp.zeros((slots,), jnp.int32))
        return cache, jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)

    @partial(jax.jit, donate_argnums=(1,))
    def decode(params, cache, tokens, lengths, rng):
        logits, cache = forward(params, cfg, tokens[:, None], lengths[:, None],
                                cache, lengths)
        nxt = sample_tokens(
            logits[:, 0, :], rng,
            jnp.zeros((slots,), jnp.float32),
            jnp.zeros((slots,), jnp.int32),
            jnp.ones((slots,), jnp.float32),
        )
        return cache, nxt

    import numpy as np

    # NOTE on timing: under the remote-TPU relay, block_until_ready() does not
    # guarantee device-side completion — only a host readback does, and a
    # readback pays the tunnel RTT. We therefore time two chained runs of
    # different lengths, each ended by a readback, and difference them so the
    # RTT and dispatch overheads cancel.
    t_pre0 = time.time()
    cache, tokens = prefill(params, cache, toks, pos)
    _ = np.asarray(tokens)
    prefill_s = time.time() - t_pre0

    lengths = jnp.full((slots,), prompt_len, dtype=jnp.int32)
    rng = jax.random.PRNGKey(2)

    def run_steps(n: int, cache, tokens, lengths, rng):
        for _ in range(n):
            rng, sub = jax.random.split(rng)
            cache, tokens = decode(params, cache, tokens, lengths, sub)
            lengths = lengths + 1
        _ = np.asarray(tokens)  # true synchronization point
        return cache, tokens, lengths, rng

    cache, tokens, lengths, rng = run_steps(warmup, cache, tokens, lengths, rng)

    n_short = decode_steps // 4
    t0 = time.time()
    cache, tokens, lengths, rng = run_steps(n_short, cache, tokens, lengths, rng)
    t_short = time.time() - t0

    t0 = time.time()
    cache, tokens, lengths, rng = run_steps(decode_steps, cache, tokens, lengths, rng)
    t_long = time.time() - t0

    dt = max(t_long - t_short, 1e-9)
    decode_steps = decode_steps - n_short

    n_chips = jax.device_count()
    toks_per_sec = slots * decode_steps / dt
    per_chip = toks_per_sec / n_chips
    baseline = 2000.0  # north-star tokens/sec/chip

    result = {
        "metric": f"decode_tokens_per_sec_per_chip ({model}, bf16, slots={slots}, ctx~{prompt_len}+)",
        "value": round(per_chip, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(per_chip / baseline, 3),
        "detail": {
            "total_tokens_per_sec": round(toks_per_sec, 1),
            "decode_step_ms": round(dt / decode_steps * 1000.0, 3),
            "prefill_first_call_s": round(prefill_s, 2),
            "n_chips": n_chips,
            "device": str(jax.devices()[0]),
            "param_count": cfg.param_count,
        },
    }
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
