#!/usr/bin/env python3
"""Driver benchmark: the in-repo engine's serving numbers on real TPU,
measured on the flagship 8B-class config against the north-star targets
(BASELINE.md: >=2000 output tok/s/chip and p50 TTFT < 30 ms on
Llama-3.1-8B-class @ v5e).

Prints ONE JSON line:
  {"metric": "...", "value": N, "unit": "...", "vs_baseline": N, "detail": {...}}

What it measures (honest accounting per VERDICT.md round-1 #4):
- decode tokens/sec/chip: steady-state fused decode with all slots busy,
  int8 weights (8B bf16 does not fit one v5e's 16 GB HBM; int8 is the
  serving config the validator maps to v5e), donated caches.
- ttft_p50_ms: steady-state single-request prefill latency (128-token
  bucket, cache-write, flash-attention path) — the server-side TTFT a warm
  engine adds to a request. Under the remote-TPU relay every dispatch+
  readback pays a measured tunnel RTT (~70 ms) that a PCIe-attached serving
  host does not; the bench times an already-compiled 1-element no-op the
  same way to isolate it and reports both the raw number and
  ttft_p50_adjusted_ms = raw - rtt_p50 (the device-side TTFT).
- hbm_bw_util / mfu: achieved HBM weight+KV streaming as a fraction of v5e
  peak (819 GB/s) and MXU utilization vs bf16 peak (197 TFLOP/s).
- flash_prefill_lowered: asserts the prefill executable contains the Pallas
  kernel custom-call on TPU (the serving path provably executes the kernel,
  ops/flash_attention.py contract).

Model size is overridable (KVMINI_BENCH_MODEL=llama-1b etc.) so the same
script smoke-tests on CPU; the driver runs the default 8B config.

Wedge-proofing (VERDICT.md round-3 weak #1 — two straight rounds of rc=1):
the remote-TPU relay can wedge such that every dispatch blocks FOREVER (no
in-process call can time out of it), and backend init can raise UNAVAILABLE.
This script therefore runs as a small orchestrator:

  1. probe the backend with a no-op dispatch in a SUBPROCESS under a hard
     timeout (a wedged relay hangs the child; the parent survives);
  2. run the actual benchmark in a second subprocess (KVMINI_BENCH_CHILD=1)
     under its own timeout, so even a mid-run wedge or OOM cannot keep the
     parent from emitting its one line;
  3. ALWAYS print exactly one JSON line on stdout and exit 0 — with
     "status": "ok" and the measurements, or "status":
     "tpu_unavailable"/"oom"/"timeout"/"error" plus the error tail when the
     run could not complete.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time

# v5e peak numbers (public spec): 819 GB/s HBM BW, 197 bf16 TFLOP/s
V5E_HBM_GBPS = 819.0
V5E_BF16_TFLOPS = 197.0


_DEFAULT_MODEL = "llama-3.1-8b"
_DEFAULT_QUANT = "int8"
# 80 slots measured 3,067 tok/s/chip vs 2,744 at 64 (r4 session) — the KV
# (80 x 512-token bf16 = 5.4 GB) + int8 weights still fit the v5e's HBM.
# If the child fails at 80 the orchestrator retries once at the proven 64
# (_FALLBACK_SLOTS) so a marginal-HBM compile can't cost the headline.
_DEFAULT_SLOTS = "80"
_FALLBACK_SLOTS = "64"


def _env_model() -> str:
    return os.environ.get("KVMINI_BENCH_MODEL", _DEFAULT_MODEL)


def _env_quant() -> str:
    return os.environ.get("KVMINI_BENCH_QUANT", _DEFAULT_QUANT)


def _env_slots() -> int:
    return int(os.environ.get("KVMINI_BENCH_SLOTS", _DEFAULT_SLOTS))


def _log(msg: str) -> None:
    """Stage progress on stderr (stdout carries only the one JSON line)."""
    print(f"[bench +{time.time() - _T_START:.0f}s] {msg}", file=sys.stderr, flush=True)


_T_START = time.time()


def _run_bench() -> dict:
    import jax

    # Same site-hook workaround as _probe: honor JAX_PLATFORMS even though
    # the axon site imported jax before us (safe pre-device-touch).
    _plat = os.environ.get("JAX_PLATFORMS")
    if _plat:
        jax.config.update("jax_platforms", _plat)

    import jax.numpy as jnp
    import numpy as np

    from functools import partial

    from kserve_vllm_mini_tpu.models.config import get_config
    from kserve_vllm_mini_tpu.models.llama import (
        forward,
        init_kv_cache,
        init_params,
        init_params_quantized,
    )
    from kserve_vllm_mini_tpu.ops.quant import quantized_bytes
    from kserve_vllm_mini_tpu.runtime.sampling import sample_tokens

    model = _env_model()
    quant = _env_quant()
    kv_quant = os.environ.get("KVMINI_BENCH_KV", "") == "int8"
    # more slots amortize the 9 GB int8 weight stream over more tokens per
    # step (measured 1710 @ 32 -> 2744 @ 64 -> 3067 @ 80 tok/s/chip on the
    # v5e) until the KV stream and HBM capacity push back
    slots = _env_slots()
    prompt_len = 128
    max_seq = 512
    decode_steps = int(os.environ.get("KVMINI_BENCH_STEPS", "128"))
    warmup = 8

    on_tpu = jax.default_backend() == "tpu"
    unroll = int(os.environ.get("KVMINI_BENCH_UNROLL", "1"))
    cfg = get_config(model, max_seq_len=max_seq, scan_unroll=unroll)
    _log(f"model={model} quant={quant} slots={slots} unroll={unroll} "
         f"backend={jax.default_backend()}")
    # int8 weights are built layer-by-layer straight into int8 leaves — the
    # full-precision 8B tree (~16 GB bf16) must NEVER exist on a 16 GB v5e
    # (round-2 OOM, VERDICT.md Weak #1)
    if quant in ("int8", "int4"):
        params = init_params_quantized(
            jax.random.PRNGKey(0), cfg, bits=4 if quant == "int4" else 8
        )
    else:
        params = init_params(jax.random.PRNGKey(0), cfg)
    jax.block_until_ready(params)
    param_bytes = quantized_bytes(params)
    _log(f"params ready ({param_bytes / 1e9:.2f} GB on device)")

    # KVMINI_BENCH_PAGED=1: run the same workload through the block-pool
    # cache + the Pallas paged-decode kernel (ops/paged_attention.py) —
    # measures the kernel against the dense path at identical geometry.
    # Contiguous per-slot block ranges (the allocator's common case).
    paged = os.environ.get("KVMINI_BENCH_PAGED", "") == "1"
    blk = 64  # paged block size, shared by the batch and TTFT caches
    block_table = None
    if paged:
        from kserve_vllm_mini_tpu.models.llama import init_paged_kv_cache

        maxb = max_seq // blk
        cache = init_paged_kv_cache(cfg, slots * maxb, blk, quantized=kv_quant)
        block_table = jnp.arange(slots * maxb, dtype=jnp.int32).reshape(slots, maxb)
    else:
        cache = init_kv_cache(cfg, slots, max_seq=max_seq, quantized=kv_quant)
    tkw = {"block_table": block_table} if paged else {}
    toks = jax.random.randint(jax.random.PRNGKey(1), (slots, prompt_len), 0, cfg.vocab_size)
    pos = jnp.broadcast_to(jnp.arange(prompt_len, dtype=jnp.int32), (slots, prompt_len))

    # -- batch prefill to fill all slots (fresh-prefill / flash path) -------
    @partial(jax.jit, donate_argnums=(1,))
    def prefill_batch(params, cache, toks, pos):
        # logit_index: full [slots, T, V] f32 logits for a 128k vocab is
        # ~2 GB of HBM the sampler never reads
        last = jnp.full((slots,), prompt_len - 1, dtype=jnp.int32)
        logits, cache = forward(params, cfg, toks, pos, cache,
                                jnp.zeros((slots,), jnp.int32), fresh_prefill=True,
                                logit_index=last, **tkw)
        return cache, jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)

    # -- single-request prefill: the per-request TTFT cost ------------------
    if paged:
        cache1 = init_paged_kv_cache(cfg, max_seq // blk, blk, quantized=kv_quant)
        t1kw = {"block_table": jnp.arange(max_seq // blk, dtype=jnp.int32)[None]}
    else:
        cache1 = init_kv_cache(cfg, 1, max_seq=max_seq, quantized=kv_quant)
        t1kw = {}
    toks1, pos1 = toks[:1], pos[:1]

    @jax.jit
    def prefill_one(params, cache, toks, pos):
        logits, cache = forward(params, cfg, toks, pos, cache,
                                jnp.zeros((1,), jnp.int32), fresh_prefill=True,
                                **t1kw)
        return jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)

    _log("compiling single-request prefill")
    lowered = prefill_one.lower(params, cache1, toks1, pos1).compile()
    hlo = lowered.as_text()
    flash_lowered = "tpu_custom_call" in hlo
    # ADVICE.md round-2: "tpu_custom_call" matches ANY TPU custom call; the
    # Mosaic backend_config embeds the kernel's function name, so also look
    # for the flash kernel specifically (reported, not asserted — the name
    # embedding is a lowering detail the assert must not couple to)
    flash_named = "_flash_kernel" in hlo
    _log(f"prefill compiled (flash_lowered={flash_lowered}, named={flash_named})")
    if on_tpu:
        assert flash_lowered, (
            "serving prefill must lower the Pallas flash kernel on TPU "
            "(ops/flash_attention.prefill_attention dispatch)"
        )

    @partial(jax.jit, donate_argnums=(1,))
    def decode(params, cache, tokens, lengths, rng):
        logits, cache = forward(params, cfg, tokens[:, None], lengths[:, None],
                                cache, lengths, **tkw)
        nxt = sample_tokens(
            logits[:, 0, :], rng,
            jnp.zeros((slots,), jnp.float32),
            jnp.zeros((slots,), jnp.int32),
            jnp.ones((slots,), jnp.float32),
        )
        return cache, nxt

    # NOTE on timing: under the remote-TPU relay, block_until_ready() does not
    # guarantee device-side completion — only a host readback does, and a
    # readback pays the tunnel RTT. We therefore time two chained runs of
    # different lengths, each ended by a readback, and difference them so the
    # RTT and dispatch overheads cancel.
    _log("batch prefill (first call: compile + run)")
    t0 = time.time()
    cache, tokens = prefill_batch(params, cache, toks, pos)
    _ = np.asarray(tokens)
    prefill_first_s = time.time() - t0
    _log(f"batch prefill done in {prefill_first_s:.1f}s")

    # steady-state single-request prefill p50 (TTFT)
    ttfts = []
    _ = np.asarray(prefill_one(params, cache1, toks1, pos1))  # warm (compiled above)
    for _i in range(15):
        t0 = time.time()
        out = prefill_one(params, cache1, toks1, pos1)
        _ = np.asarray(out)
        ttfts.append((time.time() - t0) * 1000.0)
    ttft_p50 = float(np.percentile(ttfts, 50))

    # tunnel RTT floor: dispatch + 1-element readback of a compiled no-op,
    # timed exactly like the TTFT loop. On a PCIe-attached host this is
    # sub-ms; under the remote relay it is the fixed per-readback tax every
    # latency above includes.
    noop = jax.jit(lambda x: x + 1)
    xs = jnp.zeros((1,), jnp.int32)
    _ = np.asarray(noop(xs))
    rtts = []
    for _i in range(15):
        t0 = time.time()
        _ = np.asarray(noop(xs))
        rtts.append((time.time() - t0) * 1000.0)
    rtt_p50 = float(np.percentile(rtts, 50))
    ttft_adj = max(ttft_p50 - rtt_p50, 0.0)

    lengths = jnp.full((slots,), prompt_len, dtype=jnp.int32)
    rng = jax.random.PRNGKey(2)

    def run_steps(n: int, cache, tokens, lengths, rng):
        for _ in range(n):
            rng, sub = jax.random.split(rng)
            cache, tokens = decode(params, cache, tokens, lengths, sub)
            lengths = lengths + 1
        _ = np.asarray(tokens)  # true synchronization point
        return cache, tokens, lengths, rng

    _log("decode warmup (compile)")
    cache, tokens, lengths, rng = run_steps(warmup, cache, tokens, lengths, rng)
    _log("decode warmup done; timing")

    n_short = decode_steps // 4
    t0 = time.time()
    cache, tokens, lengths, rng = run_steps(n_short, cache, tokens, lengths, rng)
    t_short = time.time() - t0

    t0 = time.time()
    cache, tokens, lengths, rng = run_steps(decode_steps, cache, tokens, lengths, rng)
    t_long = time.time() - t0

    dt = max(t_long - t_short, 1e-9)
    n_timed = decode_steps - n_short
    step_ms = dt / n_timed * 1000.0

    n_chips = jax.device_count()
    toks_per_sec = slots * n_timed / dt
    per_chip = toks_per_sec / n_chips

    # achieved HBM streaming: every decode step reads all weights once plus
    # the live KV prefix per slot (2 tensors, kv-heads, ctx, head_dim)
    ctx_mid = prompt_len + warmup + n_short + n_timed // 2
    # int8-KV streams 1 byte/element + a 4-byte f32 scale per position
    kv_elem_bytes = (
        cfg.head_dim * 1 + 4 if kv_quant
        else cfg.head_dim * jnp.dtype(cfg.jnp_dtype).itemsize
    )
    kv_bytes_step = 2 * cfg.n_layers * slots * cfg.n_kv_heads * ctx_mid * kv_elem_bytes
    bytes_step = param_bytes + kv_bytes_step
    bw_gbps = bytes_step / (dt / n_timed) / 1e9
    bw_util = bw_gbps / V5E_HBM_GBPS if on_tpu else 0.0

    flops_step = 2.0 * cfg.param_count * slots
    mfu = (flops_step / (dt / n_timed)) / (V5E_BF16_TFLOPS * 1e12) if on_tpu else 0.0

    # -- north-star economics: $/1K tokens and Wh/1K tokens -----------------
    # (BASELINE.md asks for both populated on the 8B @ v5e config.) Cost
    # comes from the chip-hour sheet x the measured throughput; energy is
    # the telemetry chain's MODELED leg (decode keeps the chip busy, so
    # duty ~= 1 during the timed window) — provenance marked, same contract
    # as energy/collector.py's fallback chain.
    from kserve_vllm_mini_tpu.analysis.telemetry import modeled_power
    from kserve_vllm_mini_tpu.costs.pricing import load_pricing

    try:
        if on_tpu:
            # price/TDP keyed by the ACTUAL chip generation, not assumed v5e
            kind = jax.devices()[0].device_kind.lower()
            if "v6" in kind:
                tpu_gen = "v6e"      # Trillium reports "TPU v6 lite" — check
                                     # the generation before the "lite" tier
            elif "lite" in kind or "v5e" in kind:
                tpu_gen = "v5e"
            elif "v5" in kind:
                tpu_gen = "v5p"
            else:
                tpu_gen = "v4"
            pricing = load_pricing()
            chip_hourly, price_key = pricing.chip_price(tpu_gen)
            overhead = 1.0 + pricing.overhead_factor
            cost_per_1k = (
                chip_hourly * overhead * n_chips / max(toks_per_sec, 1e-9) / 3.6
            )
            watts = modeled_power(1.0, tpu_gen) * n_chips
            wh_per_1k = watts * (1000.0 / max(toks_per_sec, 1e-9)) / 3600.0
            cost_basis = f"{price_key} ${chip_hourly}/chip-hr x{overhead:.2f} overhead"
            energy_prov = f"modeled ({tpu_gen} duty 1.0 x TDP, analysis/telemetry.py)"
        else:
            # like mfu/bw_util: a CPU smoke run must not fabricate TPU economics
            cost_per_1k = wh_per_1k = 0.0
            cost_basis = energy_prov = "n/a (not on TPU)"
    except Exception as e:  # noqa: BLE001 — the headline number must survive
        # a pricing-sheet or device-introspection hiccup
        _log(f"economics skipped: {type(e).__name__}: {e}")
        cost_per_1k = wh_per_1k = 0.0
        cost_basis = energy_prov = f"unavailable ({type(e).__name__})"

    # -- speculative decoding measurement (KVMINI_BENCH_SPEC=k) -------------
    # Reference claim: 20-40% decode improvement at real acceptance rates
    # (README.md:118). With random weights a small drafter accepts ~0 (its
    # argmax and the target's agree at chance), so KVMINI_BENCH_DRAFTER=self
    # (default) measures the accept=1 UPPER BOUND of the fused spec path and
    # a named preset (e.g. llama-1b) measures the accept~0 overhead floor —
    # the two brackets real-checkpoint behavior, and accept_ratio is
    # reported so the bracket is explicit.
    spec_detail = None
    spec_k = int(os.environ.get("KVMINI_BENCH_SPEC", "0"))
    if spec_k > 0:
        from kserve_vllm_mini_tpu.runtime.engine import build_spec_step

        drafter = os.environ.get("KVMINI_BENCH_DRAFTER", "self")
        # spec runs at its own (smaller) batch: it needs TWO caches (target
        # + drafter) resident at once, which at the headline slot default
        # plus the int8 8B weights exceeds the v5e's 16 GB. The headline
        # caches are dropped first; speedup math is per-slot-normalized, so
        # the slot count only needs to match between the spec rounds and the
        # served-style comparison below.
        #
        # KVMINI_BENCH_SPEC_SLOTS: drafter=self at 8B needs headroom for a
        # second LAYOUT of the whole weight tree (XLA wants different int8
        # minor-to-major orders for the drafter's T=1 scan vs the target's
        # T=k verify when they share params — measured +5.9 GB over HBM at
        # 32 slots on the v5e), so a realistic big-target run uses a NAMED
        # small drafter (e.g. llama-1b, the deployment shape) where the two
        # param trees are distinct and no relayout copy exists.
        s_slots = int(os.environ.get("KVMINI_BENCH_SPEC_SLOTS", str(min(slots, 32))))
        if s_slots > slots:
            # toks/pos only have `slots` rows; a larger spec batch would
            # shape-mismatch deep in the model after the headline already ran
            _log(f"KVMINI_BENCH_SPEC_SLOTS={s_slots} > slots={slots}; clamping")
            s_slots = slots
        cache = cache1 = None  # free the headline caches (4.3 GB at 64 slots)
        toks_s, pos_s = toks[:s_slots], pos[:s_slots]
        _log(f"spec mode: drafter={drafter} k={spec_k} slots={s_slots}")
        if drafter == "self":
            dcfg, dparams = cfg, params
        else:
            dcfg = get_config(drafter, max_seq_len=max_seq)
            if dcfg.vocab_size != cfg.vocab_size:
                dcfg = dcfg.scaled(vocab_size=cfg.vocab_size)
            if quant in ("int8", "int4"):
                dparams = init_params_quantized(
                    jax.random.PRNGKey(3), dcfg, bits=4 if quant == "int4" else 8
                )
            else:
                dparams = init_params(jax.random.PRNGKey(3), dcfg)

        @partial(jax.jit, donate_argnums=(1,))
        def sprefill(p, c, t, pp):
            lg, c2 = forward(p, cfg, t, pp, c, jnp.zeros((s_slots,), jnp.int32),
                             fresh_prefill=True,
                             logit_index=jnp.full((s_slots,), prompt_len - 1, jnp.int32))
            return c2, jnp.argmax(lg[:, -1, :], axis=-1).astype(jnp.int32)

        @partial(jax.jit, donate_argnums=(1,))
        def sdecode(p, c, tokens, lengths, rng):
            logits, c = forward(p, cfg, tokens[:, None], lengths[:, None], c, lengths)
            nxt = sample_tokens(
                logits[:, 0, :], rng,
                jnp.zeros((s_slots,), jnp.float32),
                jnp.zeros((s_slots,), jnp.int32),
                jnp.ones((s_slots,), jnp.float32),
            )
            return c, nxt

        # comparability: the headline t_step is RTT-cancelled by chained-run
        # differencing, but a spec round inherently pays one host readback
        # (the next round's `last` depends on emit). Measure a served-style
        # plain step — one readback per step, like the engine's sweep — so
        # the spec comparison is methodology-consistent. Runs BEFORE the two
        # spec caches exist so at most two s_slots caches are ever resident.
        lengths_p = jnp.full((s_slots,), prompt_len, dtype=jnp.int32)
        cache_p = init_kv_cache(cfg, s_slots, max_seq=max_seq, quantized=kv_quant)
        cache_p, toks_p = sprefill(params, cache_p, toks_s, pos_s)
        rng_p = jax.random.PRNGKey(9)
        for _ in range(4):  # warm
            rng_p, sub_p = jax.random.split(rng_p)
            cache_p, toks_p = sdecode(params, cache_p, toks_p, lengths_p, sub_p)
            _ = np.asarray(toks_p)
            lengths_p = lengths_p + 1
        n_served = 16
        t0 = time.time()
        for _ in range(n_served):
            rng_p, sub_p = jax.random.split(rng_p)
            cache_p, toks_p = sdecode(params, cache_p, toks_p, lengths_p, sub_p)
            _ = np.asarray(toks_p)  # per-step readback, like a serving sweep
            lengths_p = lengths_p + 1
        t_step_served = max(time.time() - t0, 1e-9) / n_served
        cache_p = None  # make room for the drafter cache

        t_cache, last = sprefill(
            params, init_kv_cache(cfg, s_slots, max_seq=max_seq, quantized=kv_quant),
            toks_s, pos_s,
        )

        @partial(jax.jit, donate_argnums=(1,))
        def dprefill(p, c, t, pp):
            _, c2 = forward(p, dcfg, t, pp, c, jnp.zeros((s_slots,), jnp.int32),
                            fresh_prefill=True,
                            logit_index=jnp.full((s_slots,), prompt_len - 1, jnp.int32))
            return c2

        d_cache = dprefill(
            dparams, init_kv_cache(dcfg, s_slots, max_seq=max_seq, quantized=kv_quant),
            toks_s, pos_s,
        )
        spec = build_spec_step(cfg, dcfg, spec_k)
        lengths_h = np.full((s_slots,), prompt_len, dtype=np.int64)

        def spec_rounds(n, t_cache, d_cache, last, lengths_h):
            emitted = accepted = 0
            for _ in range(n):
                t_cache, d_cache, emit = spec(
                    params, t_cache, dparams, d_cache,
                    last, jnp.asarray(lengths_h, jnp.int32),
                )
                eh = np.asarray(jax.device_get(emit))   # sync point
                cnt = (eh >= 0).sum(axis=1)
                emitted += int(cnt.sum())
                accepted += int(np.maximum(cnt - 1, 0).sum())
                idx = np.clip(cnt - 1, 0, spec_k - 1)
                last = jnp.asarray(eh[np.arange(s_slots), idx].astype(np.int32))
                lengths_h = lengths_h + cnt
            return t_cache, d_cache, last, lengths_h, emitted, accepted

        max_rounds = max((max_seq - 1 - prompt_len - 8) // spec_k, 8)
        n_warm, n_meas = 3, min(24, max_rounds - 3)
        t_cache, d_cache, last, lengths_h, _, _ = spec_rounds(
            n_warm, t_cache, d_cache, last, lengths_h
        )
        _log("spec warmup done; timing")
        t0 = time.time()
        t_cache, d_cache, last, lengths_h, emitted, accepted = spec_rounds(
            n_meas, t_cache, d_cache, last, lengths_h
        )
        dt_spec = max(time.time() - t0, 1e-9)
        spec_tps = emitted / dt_spec
        proposed = n_meas * (spec_k - 1) * s_slots
        t_round = dt_spec / n_meas
        # speedup is a function of the acceptance rate α: a round costs
        # t_round and emits (k-1)α + 1 tokens/slot vs 1 per served step.
        # Both sides pay one host readback per dispatch (the chained,
        # RTT-cancelled headline t_step would bias spec low). α itself needs
        # real checkpoints (random-weight drafters accept at chance), so
        # report the measured α plus the projection at α=0.7 — the
        # reference's own stated threshold for its 20-40% claim.
        def speedup_at(alpha: float) -> float:
            return ((spec_k - 1) * alpha + 1) * t_step_served / t_round

        spec_detail = {
            "drafter": drafter,
            "spec_tokens": spec_k,
            "slots": s_slots,
            "accept_ratio": round(accepted / proposed, 4) if proposed else 1.0,
            "tokens_per_sec_per_chip": round(spec_tps / n_chips, 1),
            "speedup_vs_served_measured": round(
                spec_tps / (s_slots / t_step_served), 3
            ),
            "round_ms": round(t_round * 1000.0, 3),
            "served_step_ms": round(t_step_served * 1000.0, 3),
            "chained_step_ms": round(dt / n_timed * 1000.0, 3),
            "projected_speedup_at_accept_0.7": round(speedup_at(0.7), 3),
            "projected_speedup_at_accept_1.0": round(speedup_at(1.0), 3),
        }
        _log(f"spec: {spec_detail}")

    baseline = 2000.0  # north-star output tokens/sec/chip
    result = {
        "metric": (
            f"decode_tokens_per_sec_per_chip ({cfg.name}, {quant}"
            f"{'+int8kv' if kv_quant else ''}{', paged' if paged else ''}, "
            f"slots={slots}, ctx~{prompt_len}+)"
        ),
        "value": round(per_chip, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(per_chip / baseline, 3),
        "status": "ok",
        "detail": {
            "total_tokens_per_sec": round(toks_per_sec, 1),
            "decode_step_ms": round(step_ms, 3),
            "ttft_p50_ms": round(ttft_p50, 2),
            "tunnel_rtt_p50_ms": round(rtt_p50, 2),
            "ttft_p50_adjusted_ms": round(ttft_adj, 2),
            "ttft_target_ms": 30.0,
            "prefill_first_call_s": round(prefill_first_s, 2),
            "flash_prefill_lowered": bool(flash_lowered),
            "flash_kernel_named_in_hlo": bool(flash_named),
            "hbm_bw_gbps": round(bw_gbps, 1),
            "hbm_bw_util": round(bw_util, 3),
            "mfu": round(mfu, 4),
            "cost_per_1k_tokens_usd": round(cost_per_1k, 6),
            "cost_basis": cost_basis,
            "energy_wh_per_1k_tokens": round(wh_per_1k, 4),
            "energy_provenance": energy_prov,
            "scan_unroll": unroll,
            "param_count": cfg.param_count,
            "param_bytes": int(param_bytes),
            "n_chips": n_chips,
            "device": str(jax.devices()[0]),
        },
    }
    if spec_detail is not None:
        result["detail"]["speculative"] = spec_detail
    return result


# ---------------------------------------------------------------------------
# Orchestration: probe -> child run -> always one parseable JSON line, rc 0.
# ---------------------------------------------------------------------------

def _bench_label() -> str:
    # raw env strings only: this runs on the must-never-raise failure path
    # (a bogus KVMINI_BENCH_SLOTS must yield a labeled failure record, not
    # an int() crash inside _emit_failure)
    slots = os.environ.get("KVMINI_BENCH_SLOTS", _DEFAULT_SLOTS)
    return f"{_env_model()}, {_env_quant()}, slots={slots}"


def _classify(err_text: str) -> str:
    if "RESOURCE_EXHAUSTED" in err_text:
        return "oom"
    if "UNAVAILABLE" in err_text or "Unable to initialize backend" in err_text:
        return "tpu_unavailable"
    return "error"


def _emit_failure(status: str, stage: str, detail: str) -> None:
    """The one JSON line for a run that could not measure — still parseable,
    still carries the metric name, value 0, and the reason."""
    record = {
        "metric": f"decode_tokens_per_sec_per_chip ({_bench_label()}) "
                  f"[NOT MEASURED: {status}]",
        "value": 0.0,
        "unit": "tokens/s/chip",
        "vs_baseline": 0.0,
        "status": status,
        "detail": {
            "stage": stage,
            "error_tail": detail[-1500:],
            # Last hardware measurement, for context only — self-reported
            # (docs/PERFORMANCE.md), NOT a driver-verified value.
            "last_measured_reference": {
                "value": 3066.7,
                "unit": "tokens/s/chip",
                "config": "llama-3.1-8b int8, 80 slots, v5e",
                "provenance": "docs/PERFORMANCE.md (builder session 2026-07-31"
                              " ran this same script end-to-end, status ok;"
                              " not from a BENCH_r0X.json)",
            },
        },
    }
    print(json.dumps(record))


def _probe(timeout_s: float) -> tuple[bool, str, str]:
    """No-op dispatch + readback in a subprocess under a hard timeout.

    A wedged relay blocks the dispatch forever — only a subprocess timeout
    can detect that (memory: every in-process call blocks with it).
    Returns (ok, status, detail); status is authoritative ("ok" /
    "tpu_unavailable" / "oom" / "error"), not re-derived from the text.
    """
    # The axon site hook imports jax at interpreter start, so the
    # JAX_PLATFORMS env var alone is too late — mirror tests/conftest.py and
    # update jax.config before any device is touched.
    code = (
        "import os, jax, numpy as np; "
        "p = os.environ.get('JAX_PLATFORMS'); "
        "p and jax.config.update('jax_platforms', p); "
        "print('backend', jax.default_backend(), "
        "float(np.asarray(jax.numpy.ones((4,)).sum())))"
    )
    try:
        p = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, errors="replace",
            timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return False, "tpu_unavailable", (
            f"probe timed out after {timeout_s:.0f}s — relay wedged "
            "(dispatch blocks forever; see repo ops notes)"
        )
    if p.returncode != 0:
        detail = f"probe rc={p.returncode}: {p.stderr.strip()[-1200:]}"
        return False, _classify(detail), detail
    return True, "ok", p.stdout.strip()


def _orchestrate() -> int:
    probe_timeout = float(os.environ.get("KVMINI_BENCH_PROBE_TIMEOUT", "90"))
    # The relay's wedges are often transient (r4 session: wedged -> answered
    # -> wedged again within the hour), so a failed probe is retried a few
    # times before the run is declared unmeasurable — the driver invokes
    # this exactly once per round, and a 5-minute wait is cheap next to a
    # round with no number.
    probe_tries = max(int(os.environ.get("KVMINI_BENCH_PROBE_RETRIES", "3")), 1)
    probe_wait = float(os.environ.get("KVMINI_BENCH_PROBE_RETRY_WAIT", "75"))

    def _probe_once():
        ok, status, detail = _probe(probe_timeout)
        if ok:
            # JAX can fall back to CPU with only a warning when the TPU
            # plugin fails to init — a "successful" CPU probe in a
            # TPU-expected env would run the 8B flagship on CPU and produce
            # a misleading artifact. This is a relay failure mode (it gets
            # the same retries as a raising wedge), not a green light.
            parts = detail.split()
            backend = parts[1] if len(parts) >= 2 else "?"
            plat = os.environ.get("JAX_PLATFORMS", "").split(",")[0].strip().lower()
            if plat in ("", "axon", "tpu") and backend != "tpu":
                ok, status = False, "tpu_unavailable"
                detail = (
                    f"probe fell back to backend {backend!r} (expected tpu; "
                    f"JAX_PLATFORMS={plat or '<unset>'}): {detail}"
                )
        return ok, status, detail

    ok, probe_status, probe_detail = _probe_once()
    for _try in range(probe_tries - 1):
        if ok:
            break
        _log(f"probe failed ({probe_status}); retrying in {probe_wait:.0f}s "
             f"({_try + 2}/{probe_tries})")
        time.sleep(probe_wait)
        ok, probe_status, probe_detail = _probe_once()
    if not ok:
        _log(f"backend probe failed: {probe_detail}")
        _emit_failure(probe_status, "probe", probe_detail)
        return 0
    _log(f"backend probe ok: {probe_detail}")

    # The child gets a generous but finite budget: a warm full run is 3-5 min
    # on the relay; first-compile adds ~1 min. A mid-run wedge hangs the
    # child, not us.
    run_timeout = float(os.environ.get("KVMINI_BENCH_TIMEOUT", "900"))
    env = dict(os.environ, KVMINI_BENCH_CHILD="1")
    rc, out, err_text = _run_child(env, run_timeout)
    result_line = _extract_result(out)

    # The 80-slot default is the measured best but runs nearer the HBM
    # ceiling than 64; if it OOMs AND the operator did not pin the slot
    # count, retry once at the proven 64 so a marginal-HBM compile cannot
    # cost the round its headline number. Only OOM qualifies: a timeout or
    # unavailable relay fails the same way at any slot count, and a second
    # 900 s hang would double the damage for nothing.
    first_status = "timeout" if rc is None else _classify(err_text)
    if (
        result_line is None
        and first_status == "oom"
        and "KVMINI_BENCH_SLOTS" not in os.environ
    ):
        _log(
            f"child failed at default slots={_DEFAULT_SLOTS} "
            f"({first_status}); retrying at slots={_FALLBACK_SLOTS}"
        )
        rc2, out2, err2 = _run_child(
            dict(env, KVMINI_BENCH_SLOTS=_FALLBACK_SLOTS), run_timeout
        )
        line2 = _extract_result(out2)
        if line2 is not None:
            parsed = json.loads(line2)
            parsed.setdefault("detail", {})["slots_fallback"] = (
                f"default slots={_DEFAULT_SLOTS} failed ({first_status}: "
                f"{err_text[-300:]}); this run measured at "
                f"slots={_FALLBACK_SLOTS}"
            )
            print(json.dumps(parsed))
            return 0
        # report the ORIGINAL failure (the default config's) below

    if result_line is not None:
        print(result_line)
        return 0
    if rc is None:
        _emit_failure(
            "timeout", "run",
            f"benchmark child exceeded {run_timeout:.0f}s "
            f"(likely mid-run relay wedge); stderr tail: {err_text[-1200:]}",
        )
        return 0
    _emit_failure(_classify(err_text), "run",
                  f"child rc={rc}; stderr tail: {err_text[-1500:]}")
    return 0


def _run_child(env: dict, run_timeout: float) -> tuple:
    """One benchmark child under a hard timeout. Returns (rc, stdout,
    stderr_text); rc None means the timeout killed it (a signal-killed
    child's negative rc must fall through to _classify instead)."""
    with tempfile.NamedTemporaryFile("w+", suffix=".bench-stderr",
                                     errors="replace") as errf:
        try:
            p = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                env=env, stdout=subprocess.PIPE, stderr=errf, text=True,
                errors="replace", timeout=run_timeout,
            )
            rc, out = p.returncode, p.stdout
        except subprocess.TimeoutExpired as e:
            rc, out = None, (e.stdout or "")
            if isinstance(out, bytes):
                out = out.decode(errors="replace")
        errf.seek(0)
        err_text = errf.read()
    # Re-emit the child's stage log so interactive runs keep their trace.
    sys.stderr.write(err_text)
    sys.stderr.flush()
    return rc, out, err_text


def _extract_result(out: str):
    """The child's LAST parseable JSON line (teardown noise or a post-print
    crash must not cost us the measurement)."""
    result_line = None
    for line in out.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                parsed = json.loads(line)
                if isinstance(parsed, dict) and "metric" in parsed:
                    result_line = line
            except ValueError:
                continue
    return result_line


def main() -> int:
    if os.environ.get("KVMINI_BENCH_CHILD") == "1":
        # Child: do the real work; parent structures any failure. flush —
        # the pipe is block-buffered, and a post-print teardown wedge must
        # not strand the finished measurement in the buffer when the parent
        # SIGKILLs the child.
        print(json.dumps(_run_bench()), flush=True)
        return 0
    try:
        return _orchestrate()
    except Exception:  # noqa: BLE001 — the one-JSON-line contract is absolute
        import traceback

        _emit_failure("error", "orchestrator", traceback.format_exc())
        return 0


if __name__ == "__main__":
    sys.exit(main())
