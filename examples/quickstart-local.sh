#!/usr/bin/env bash
# 5-minute local quickstart: serve the in-repo JAX runtime, hit it with the
# OpenAI API, run a small load test, and render the report — no cluster.
# Works on CPU (tiny preset) or one TPU chip (swap in an 8B preset + int8).
#
# Usage: examples/quickstart-local.sh [model-preset]   (default: llama-tiny)
set -euo pipefail
cd "$(dirname "$0")/.."

MODEL="${1:-llama-tiny}"
PORT=8011

echo "== 1. serve $MODEL on :$PORT"
python -m kserve_vllm_mini_tpu serve --model "$MODEL" --port "$PORT" \
  --max-slots 4 --max-seq-len 256 &
SERVER_PID=$!
trap 'kill $SERVER_PID 2>/dev/null || true' EXIT

for _ in $(seq 1 60); do
  curl -sf "http://127.0.0.1:$PORT/v1/models" >/dev/null 2>&1 && break
  sleep 1
done

echo "== 2. one OpenAI chat call (streaming)"
# (head closes the stream early; the || true keeps pipefail happy)
curl -sN "http://127.0.0.1:$PORT/v1/chat/completions" \
  -H 'Content-Type: application/json' \
  -d '{"messages":[{"role":"user","content":"hello"}],"max_tokens":8,"stream":true}' \
  | head -5 || true

echo "== 3. JSON mode (grammar-constrained decoding)"
curl -s "http://127.0.0.1:$PORT/v1/chat/completions" \
  -H 'Content-Type: application/json' \
  -d '{"messages":[{"role":"user","content":"Give me JSON."}],"response_format":{"type":"json_object"},"max_tokens":40}' \
  | python -c 'import json,sys; d=json.load(sys.stdin); print(json.loads(d["choices"][0]["message"]["content"]))'

echo "== 4. load test (20 requests, open-loop)"
python -m kserve_vllm_mini_tpu loadtest --url "http://127.0.0.1:$PORT" \
  --model "$MODEL" --requests 20 --concurrency 4 --max-tokens 8 \
  --run-dir runs/quickstart

echo "== 5. analyze + report"
python -m kserve_vllm_mini_tpu analyze --run-dir runs/quickstart
python -m kserve_vllm_mini_tpu report --input runs/quickstart \
  --output runs/quickstart/report.html
echo "report: runs/quickstart/report.html"
