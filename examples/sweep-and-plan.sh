#!/usr/bin/env bash
# Quantization sweep -> Pareto CSV -> calibrated capacity plan.
# Self-serves the in-repo runtime per config, so it runs anywhere
# (CPU with the tiny preset; a real TPU chip with an 8B preset).
#
# Usage: examples/sweep-and-plan.sh [model-preset]   (default: llama-tiny)
set -euo pipefail
cd "$(dirname "$0")/.."

MODEL="${1:-llama-tiny}"
OUT=runs/example-sweep

echo "== 1. quantization sweep (bf16 vs int8 weights x kv dtypes)"
python -m kserve_vllm_mini_tpu sweep quantization \
  --model "$MODEL" --requests 10 --concurrency 2 \
  --quantizations none,int8 --kv-dtypes auto \
  --out-dir "$OUT"

echo "== 2. capacity plan for 20 RPS at p95<=2s on an 8B deployment"
python -m kserve_vllm_mini_tpu plan --target-rps 20 --model-size 8b \
  --p95-budget 2000 --accelerators v5e,v5p
echo "(rows are labeled measured/scaled/calibrated; feed a real sweep CSV"
echo " via --calibrate-csv to replace the built-in baselines)"
