"""Never-dark bench: the proxy-tier orchestration (stubbed children,
tier-1) and the full end-to-end proxy smoke (`make bench-proxy-smoke`,
marked slow): on a machine with no TPU, ``python bench.py`` must exit 0
with a schema-valid ``proxy`` block, a config over mocked HBM headroom
must downshift instead of crashing, and the trajectory renders the round
into its report section (docs/PROFILING.md)."""

import importlib.util
import json
import os
import subprocess
import sys

import pytest

from kserve_vllm_mini_tpu.core.schema import validate_proxy

_BENCH = os.path.join(os.path.dirname(__file__), "..", "bench.py")


def _load_bench():
    spec = importlib.util.spec_from_file_location("bench_proxy_mod", _BENCH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def bench():
    return _load_bench()


@pytest.fixture(autouse=True)
def _fast_orchestration(monkeypatch, tmp_path):
    monkeypatch.setenv("KVMINI_BENCH_PROBE_BUDGET_S", "0")
    monkeypatch.setenv("KVMINI_BENCH_MODES", "headline")
    monkeypatch.delenv("KVMINI_BENCH_PROXY", raising=False)
    monkeypatch.chdir(tmp_path)


_PROXY_DATA = {
    "series": "proxy", "platform": "cpu", "n_devices": 8,
    "model": "llama-3.1-8b", "exec_model": "llama-tiny",
    "flops": 1.39e11, "bytes_accessed": 9.46e10,
    "compile_wall_s": 2.5, "peak_bytes": 2.1e10, "step_count_ratio": 1.3,
}


def _proxy_child_stub(record_env):
    """subprocess.run stub: proxy children answer with a canned block,
    anything else wedges (TimeoutExpired)."""

    def fake_run(cmd, env=None, stdout=None, stderr=None, text=None,
                 errors=None, timeout=None, capture_output=None):
        record_env.append(dict(env or {}))
        if env and env.get("KVMINI_BENCH_CHILD") == "proxy":
            class P:
                returncode = 0
                stdout = json.dumps({"mode": "proxy", "status": "ok",
                                     "data": dict(_PROXY_DATA)}) + "\n"
            return P()
        raise subprocess.TimeoutExpired(cmd, timeout or 0)

    return fake_run


def test_probe_failure_hands_off_to_proxy_tier(bench, monkeypatch, capsys):
    """BENCH_r03's failure mode, after: probe never succeeds -> the round
    still exits 0 with detail.proxy carrying the fallback metrics, and
    the proxy child runs on the FORCED 8-device host platform."""
    envs = []
    monkeypatch.setattr(
        bench, "_probe", lambda t: (False, "tpu_unavailable", "wedged")
    )
    monkeypatch.setattr(subprocess, "run", _proxy_child_stub(envs))
    rc = bench.main()
    rec = json.loads(capsys.readouterr().out.strip())
    assert rc == 0
    assert rec["status"] == "tpu_unavailable"
    assert "NOT MEASURED" in rec["metric"]
    assert rec["detail"]["proxy"]["status"] == "ok"
    assert validate_proxy(rec["detail"]["proxy"] | {"series": "proxy"}) == []
    assert "proxy tier carried the round" in rec["detail"]["note"]
    # the child env: CPU platform + the virtual 8-device mesh flag
    (env,) = envs
    assert env["JAX_PLATFORMS"] == "cpu"
    assert "--xla_force_host_platform_device_count=8" in env["XLA_FLAGS"]
    assert env["KVMINI_BENCH_CHILD"] == "proxy"


def test_mid_queue_wedge_ends_with_proxy_round(bench, monkeypatch, capsys):
    """A relay that wedges after a good probe (headline child times out,
    re-probe fails) must still land the proxy block."""
    probes = {"n": 0}

    def probe(t):
        probes["n"] += 1
        return (probes["n"] == 1, "ok" if probes["n"] == 1 else
                "tpu_unavailable", "x")

    envs = []
    monkeypatch.setattr(bench, "_probe", probe)
    monkeypatch.setattr(subprocess, "run", _proxy_child_stub(envs))
    rc = bench.main()
    rec = json.loads(capsys.readouterr().out.strip())
    assert rc == 0
    assert rec["status"] == "timeout"
    assert rec["detail"]["proxy"]["flops"] == _PROXY_DATA["flops"]


def test_proxy_never_disables_fallback(bench, monkeypatch, capsys):
    monkeypatch.setenv("KVMINI_BENCH_PROXY", "never")
    calls = []
    monkeypatch.setattr(
        bench, "_probe", lambda t: (False, "tpu_unavailable", "wedged")
    )
    monkeypatch.setattr(subprocess, "run", _proxy_child_stub(calls))
    rc = bench.main()
    rec = json.loads(capsys.readouterr().out.strip())
    assert rc == 0
    assert not calls                      # no child launched at all
    assert "proxy" not in rec["detail"]


def test_proxy_always_appends_to_ok_round(bench, monkeypatch, capsys):
    monkeypatch.setenv("KVMINI_BENCH_PROXY", "always")
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    envs = []

    def fake_run(cmd, env=None, stdout=None, stderr=None, text=None,
                 errors=None, timeout=None, capture_output=None):
        envs.append(dict(env or {}))
        mode = env.get("KVMINI_BENCH_CHILD")

        class P:
            returncode = 0
            stdout = ""
        if mode == "headline":
            P.stdout = json.dumps({
                "mode": "headline", "status": "ok",
                "data": {"tokens_per_sec_per_chip": 2500.0},
            }) + "\n"
        elif mode == "proxy":
            P.stdout = json.dumps({"mode": "proxy", "status": "ok",
                                   "data": dict(_PROXY_DATA)}) + "\n"
        return P()

    monkeypatch.setattr(bench, "_probe", lambda t: (True, "ok", "backend cpu"))
    monkeypatch.setattr(subprocess, "run", fake_run)
    rc = bench.main()
    rec = json.loads(capsys.readouterr().out.strip())
    assert rc == 0
    assert rec["status"] == "ok" and rec["value"] == 2500.0
    assert rec["detail"]["proxy"]["step_count_ratio"] == 1.3


# -- end-to-end (make bench-proxy-smoke; slow tier in CI) ---------------------

def _run_bench_subprocess(extra_env, timeout=560):
    env = {k: v for k, v in os.environ.items()
           if not k.startswith("KVMINI_BENCH_")}
    env.update(extra_env)
    return subprocess.run(
        [sys.executable, os.path.abspath(_BENCH)],
        capture_output=True, text=True, errors="replace",
        timeout=timeout, env=env,
    )


@pytest.mark.slow
def test_bench_exits_zero_with_schema_valid_proxy_block(tmp_path):
    """THE acceptance path: no TPU -> python bench.py exits 0 and emits a
    schema-valid proxy block (FLOPs, bytes, compile wall-time, peak
    buffer, step-count ratio), end-to-end through the real child."""
    p = _run_bench_subprocess({
        # TPU expected, none present -> probe fails -> proxy tier
        "JAX_PLATFORMS": "",
        "KVMINI_BENCH_PROBE_BUDGET_S": "1",
        "KVMINI_BENCH_PROBE_TIMEOUT": "180",
        "KVMINI_BENCH_MODES": "",           # belt-and-braces: no TPU modes
        "KVMINI_BENCH_MODEL": "llama-tiny",
        "KVMINI_BENCH_PROXY_STEPS": "6",
    })
    assert p.returncode == 0, p.stderr[-2000:]
    rec = json.loads(p.stdout.strip().splitlines()[-1])
    proxy = rec["detail"]["proxy"]
    block = {k: v for k, v in proxy.items() if k != "status"}
    assert validate_proxy(block) == [], validate_proxy(block)
    for key in ("flops", "bytes_accessed", "compile_wall_s", "peak_bytes",
                "step_count_ratio"):
        assert block[key] > 0, key
    assert block["n_devices"] == 8      # the forced host mesh engaged
    assert block["platform"] == "cpu"
    assert "hbm_headroom" in block
    # nothing in a proxy round may claim device throughput
    assert rec["value"] == 0.0

    # ... and the trajectory ingests the round into its report section
    art = tmp_path / "BENCH_r99.json"
    art.write_text(json.dumps({"n": 99, "cmd": "bench", "rc": 0, "tail": "",
                               "parsed": rec}))
    from kserve_vllm_mini_tpu.analysis.trajectory import (
        build_trajectory,
        load_rounds,
    )
    from kserve_vllm_mini_tpu.report.html import generate_trajectory_html

    traj = build_trajectory(load_rounds([art]))
    assert traj["coverage"]["proxy"] == 1
    html = generate_trajectory_html(traj)
    assert "Perf trajectory" in html and "proxy" in html


@pytest.mark.slow
def test_headroom_preflight_reports_unfittable_as_oom():
    """A config that cannot fit even maximally downshifted must fail the
    PRE-FLIGHT with the RESOURCE_EXHAUSTED marker (parent classifies oom
    and runs the proxy tier) — no compile, no raw traceback."""
    p = _run_bench_subprocess({
        "JAX_PLATFORMS": "cpu",
        "KVMINI_BENCH_CHILD": "headline",
        "KVMINI_BENCH_MODEL": "llama-tiny",
        "KVMINI_BENCH_HBM_GB": "0.0001",   # nothing fits in 100 KB
    })
    assert p.returncode != 0
    assert "RESOURCE_EXHAUSTED (pre-flight)" in p.stderr
    assert "Traceback" not in p.stderr


def test_preflight_oom_triggers_proxy_fallback(bench, monkeypatch, capsys):
    """Orchestrator side of the same story: a headline child that dies
    with the pre-flight OOM marker still ends in a proxy round."""
    envs = []

    def fake_run(cmd, env=None, stdout=None, stderr=None, text=None,
                 errors=None, timeout=None, capture_output=None):
        envs.append(dict(env or {}))
        if env and env.get("KVMINI_BENCH_CHILD") == "proxy":
            class P:
                returncode = 0
                stdout = json.dumps({"mode": "proxy", "status": "ok",
                                     "data": dict(_PROXY_DATA)}) + "\n"
            return P()

        class P:
            returncode = 1
            stdout = ""
        if stderr is not None:
            stderr.write("RESOURCE_EXHAUSTED (pre-flight): even downshifted")
        return P()

    monkeypatch.setenv("KVMINI_BENCH_SLOTS", "96")  # pin: no 64-slot retry
    monkeypatch.setattr(bench, "_probe", lambda t: (True, "ok", "backend tpu"))
    monkeypatch.setattr(subprocess, "run", fake_run)
    rc = bench.main()
    rec = json.loads(capsys.readouterr().out.strip())
    assert rc == 0
    assert rec["status"] == "oom"
    assert rec["detail"]["proxy"]["status"] == "ok"


@pytest.mark.slow
def test_headroom_guard_downshifts_instead_of_crashing():
    """BENCH_r02's failure mode, after: a config sized to exceed (mocked)
    HBM headroom is downshifted and labeled, and the child completes with
    a real measurement at the admitted shape."""
    from kserve_vllm_mini_tpu.models.config import get_config
    from kserve_vllm_mini_tpu.profiling.headroom import estimate_serving_bytes

    # capacity that fits a small shape but NOT the 80-slot default
    cap_bytes = int(estimate_serving_bytes(
        get_config("llama-tiny", max_seq_len=512), 16, 512, quant="int8",
    )["total_bytes"] * 1.2)
    p = _run_bench_subprocess({
        "JAX_PLATFORMS": "cpu",
        "KVMINI_BENCH_CHILD": "headline",
        "KVMINI_BENCH_MODEL": "llama-tiny",
        "KVMINI_BENCH_STEPS": "8",
        "KVMINI_BENCH_HBM_GB": str(cap_bytes / 1e9),
    })
    assert p.returncode == 0, p.stderr[-2000:]
    child = json.loads(p.stdout.strip().splitlines()[-1])
    data = child["data"]
    assert data["downshifted"].startswith("downshifted: slots 80->")
    assert data["slots"] < 80
    assert data["tokens_per_sec_per_chip"] > 0
    assert data["hbm_headroom"]["fits"] is True
    # compile-stats capture rode along (the lower().compile() wrap)
    assert data["compile_wall_s"] > 0
    assert data["compile_stats"]["decode"]["flops"] > 0
